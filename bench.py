"""Benchmark matrix: the five BASELINE.md workloads through the full SQL
path on the TPU cop engine, plus cop-task p50 latency and the dispatch
overhead breakdown.

Prints ONE JSON line per metric (stdout); the LAST line is the headline
TPC-H Q1 figure:
  {"metric": "tpch_q1_rows_per_sec", "value": N, "unit": "rows/s",
   "vs_baseline": tpu_throughput / host_numpy_throughput}

The baseline is this framework's own host (numpy-vectorized) cop engine
on identical data and plans — the stand-in for the reference's Go
unistore closure executor (BASELINE.md: ">=10x unistore cop throughput"
is the north star; the Go engine isn't runnable in this image, so the
ratio is reported against the strongest CPU path available).

Workloads (BASELINE.md §Baseline procedure):
  q1     TPC-H Q1 multi-key GROUP BY pushdown          (BENCH_ROWS,   16M)
  q6     TPC-H Q6 scan+filter+SUM                      (BENCH_ROWS,   16M)
  topn   ORDER BY l_extendedprice DESC LIMIT 100       (BENCH_ROWS,   16M)
  q3     TPC-H Q3 joins through the mesh MPP path      (BENCH_Q3_ROWS, 4M)
  window SUM() OVER (PARTITION BY ... ORDER BY ...)    (BENCH_WIN_ROWS, 8M)
  p50    one-cop-task small scan latency, both engines (1M-row table)

  sched  64-way concurrent point-agg launch batching  (tools/bench_sched.py)

Env knobs: BENCH_ROWS / BENCH_Q3_ROWS / BENCH_WIN_ROWS, BENCH_REPS,
BENCH_QUERY (all|q1|q6|topn|q3|window|p50|sched — default all).
Per-dispatch tunnel round-trip is ~100ms fixed (measured; see
dispatch_overhead_ms), so throughput workloads run at row counts that
amortize it.
"""

import json
import os
import statistics
import sys
import time


def _run(s, sql, engine, n):
    # repeated identical reads must measure the ENGINE, not the cop
    # result cache (coprocessor_cache is benched separately by its tests)
    s.vars["tidb_enable_cop_result_cache"] = "OFF"
    s.vars["tidb_cop_engine"] = engine
    times, result = [], None
    for _ in range(n):
        t = time.time()
        result = s.execute(sql)
        times.append(time.time() - t)
    return result, min(times), statistics.median(times)


def _throughput(s, sql, rows, reps, host_reps, label, check=True, device_engine="tpu"):
    """Warm both engines, verify parity, measure medians; returns the
    metric dict (vs_baseline = tpu throughput / host throughput).
    device_engine="auto" for workloads whose plan mixes a device operator
    with a bare scan: forced 'tpu' would round-trip the scan through the
    device for nothing, which is not the product path."""
    host_res, _, _ = _run(s, sql, "host", 1)
    fb0 = s.cop.tpu.fallbacks
    tpu_res, _, _ = _run(s, sql, device_engine, 2)
    if check == "numeric":
        # order-insensitive numeric parity on the raw chunk lanes —
        # catches real divergence without rendering millions of rows
        # (float summation order may differ; exact lanes must match)
        import numpy as np

        assert len(host_res.chunk.columns) == len(tpu_res.chunk.columns), (
            f"{label}: column counts diverge"
        )
        for hc, tc in zip(host_res.chunk.columns, tpu_res.chunk.columns):
            assert int(hc.valid.sum()) == int(tc.valid.sum()), (
                f"{label}: NULL counts diverge"
            )
            hv = np.sort(np.asarray(hc.data[hc.valid], dtype=np.float64))
            tv = np.sort(np.asarray(tc.data[tc.valid], dtype=np.float64))
            assert hv.shape == tv.shape and np.allclose(hv, tv, rtol=1e-9, atol=1e-6), (
                f"{label}: engines diverge numerically"
            )
    elif check:
        assert sorted(host_res.rows()) == sorted(tpu_res.rows()), f"{label}: engines diverge"
    _, host_best, host_med = _run(s, sql, "host", host_reps)
    _, tpu_best, tpu_med = _run(s, sql, device_engine, reps)
    meta = {
        "workload": label, "rows": rows,
        "tpu_median_s": round(tpu_med, 4), "tpu_best_s": round(tpu_best, 4),
        "host_median_s": round(host_med, 4), "out_rows": tpu_res.chunk.num_rows,
    }
    fb = s.cop.tpu.fallbacks - fb0
    if fb:
        # a silent host fallback must never masquerade as a TPU number
        meta["tpu_fallbacks"] = fb
        print(f"WARNING: {label}: tpu engine fell back {fb}x", file=sys.stderr)
    print(json.dumps(meta), file=sys.stderr)
    return {
        "metric": f"{label}_rows_per_sec",
        "value": round(rows / tpu_med, 1),
        "unit": "rows/s",
        "vs_baseline": round(host_med / tpu_med, 3),
    }


def main():
    # honor an explicit CPU request even though the axon plugin pins
    # jax_platforms at interpreter start (env alone is too late here)
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    which = os.environ.get("BENCH_QUERY", "all")

    # -- smoke: compile + run every device program family on the REAL
    # platform at small sizes, asserting zero fallbacks (guards the
    # CPU-green/TPU-broken failure class; VERDICT r4 #3) -----------------
    if which == "smoke":
        import jax as _jax

        t_all = time.time()
        from tidb_tpu.session import Session
        from tidb_tpu.models import tpch

        s = Session()
        tpch.setup_tpch(s, 60_000)
        s.vars["tidb_enable_cop_result_cache"] = "OFF"
        s.vars["tidb_cop_engine"] = "tpu"
        fb0 = s.cop.tpu.fallbacks
        checks = []

        def run_both(tag, sql, order_insensitive=True):
            s.vars["tidb_cop_engine"] = "tpu"
            s.vars["tidb_allow_mpp"] = "ON"
            dev = s.must_query(sql)
            s.vars["tidb_cop_engine"] = "host"
            s.vars["tidb_allow_mpp"] = "OFF"
            host = s.must_query(sql)
            key = (lambda r: tuple((x is None, str(x)) for x in r))
            ok = (sorted(dev, key=key) == sorted(host, key=key)) if order_insensitive else dev == host
            checks.append((tag, ok))
            assert ok, f"smoke {tag}: device != host"

        run_both("fused_agg_q1", tpch.Q1)
        run_both("filter_sum_q6", tpch.Q6)
        run_both("multikey_topn",
                 "SELECT l_orderkey, l_extendedprice FROM lineitem"
                 " ORDER BY l_extendedprice DESC, l_orderkey, l_linenumber LIMIT 50",
                 order_insensitive=False)
        run_both("collated_group",
                 "SELECT l_returnflag, l_linestatus, COUNT(*), MIN(l_shipdate),"
                 " MAX(l_extendedprice) FROM lineitem GROUP BY l_returnflag, l_linestatus")
        run_both("window_rows_range",
                 "SELECT SUM(l_quantity) OVER (PARTITION BY l_returnflag"
                 " ORDER BY l_orderkey, l_linenumber ROWS BETWEEN 3 PRECEDING AND CURRENT ROW),"
                 " AVG(l_quantity) OVER (PARTITION BY l_linestatus"
                 " ORDER BY l_orderkey, l_linenumber),"
                 " COUNT(*) OVER (ORDER BY l_orderkey RANGE BETWEEN 100 PRECEDING AND 100 FOLLOWING)"
                 " FROM lineitem LIMIT 100000",
                 order_insensitive=False)
        run_both("mpp_q3_topk", tpch.Q3, order_insensitive=False)
        fb = s.cop.tpu.fallbacks - fb0
        mppfb = s.cop.mpp.fallbacks
        assert fb == 0, f"smoke: {fb} tpu engine fallbacks"
        assert mppfb == 0, f"smoke: {mppfb} mpp fallbacks ({s.cop.mpp.last_fallback_reason})"
        dt = time.time() - t_all
        print(json.dumps({"smoke": [t for t, _ in checks], "platform": _jax.devices()[0].platform,
                          "seconds": round(dt, 1)}), file=sys.stderr)
        print(json.dumps({"metric": "kernel_zoo_smoke", "value": round(dt, 1),
                          "unit": "s", "vs_baseline": 1.0}))
        return

    rows = int(os.environ.get("BENCH_ROWS", "16000000"))
    q3_rows = int(os.environ.get("BENCH_Q3_ROWS", "4000000"))
    win_rows = int(os.environ.get("BENCH_WIN_ROWS", "8000000"))
    reps = int(os.environ.get("BENCH_REPS", "11"))
    host_reps = max(2, reps // 5)

    from tidb_tpu.session import Session
    from tidb_tpu.models import tpch

    out = []

    # -- dispatch overhead: trivial jitted op round-trip (tunnel floor) ----
    if which in ("all", "p50"):
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: x + 1)
        x = jnp.zeros(1024)
        jax.block_until_ready(f(x))  # compile
        ts = []
        for _ in range(15):
            t = time.time()
            jax.block_until_ready(f(x))
            ts.append(time.time() - t)
        disp = statistics.median(ts)
        out.append({
            "metric": "dispatch_overhead_ms", "value": round(disp * 1e3, 2),
            "unit": "ms", "vs_baseline": 1.0,
        })

    # -- cop-task p50: one-region small scan in its OWN store -------------
    if which in ("all", "p50"):
        sp = Session()  # fresh storage: must not clobber the big table
        tpch.setup_lineitem(sp, 1_000_000)
        small = "SELECT COUNT(*), SUM(l_quantity) FROM lineitem WHERE l_discount <= 0.02"
        _run(sp, small, "host", 2)
        _run(sp, small, "tpu", 3)
        hts, tts = [], []
        sp.vars["tidb_cop_engine"] = "host"
        for _ in range(21):
            t = time.time(); sp.execute(small); hts.append(time.time() - t)
        sp.vars["tidb_cop_engine"] = "tpu"
        for _ in range(21):
            t = time.time(); sp.execute(small); tts.append(time.time() - t)
        host_p50 = statistics.median(hts)
        tpu_p50 = statistics.median(tts)
        print(json.dumps({"p50_host_ms": round(host_p50 * 1e3, 2),
                          "p50_tpu_ms": round(tpu_p50 * 1e3, 2)}), file=sys.stderr)
        out.append({
            "metric": "cop_task_p50_ms", "value": round(tpu_p50 * 1e3, 2),
            "unit": "ms", "vs_baseline": round(host_p50 / tpu_p50, 3),
        })
        del sp

    # -- q1 / q6 / topn / window on one big lineitem ----------------------
    q1_line = None
    if which in ("all", "q1", "q6", "topn", "window"):
        s = Session()
        t0 = time.time()
        tpch.setup_lineitem(s, rows)
        print(json.dumps({"load": "lineitem", "rows": rows, "s": round(time.time() - t0, 1)}),
              file=sys.stderr)
        if which in ("all", "q6"):
            out.append(_throughput(s, tpch.Q6, rows, reps, host_reps, "tpch_q6"))
        if which in ("all", "topn"):
            out.append(_throughput(s, tpch.TOPN, rows, reps, host_reps, "tpch_topn"))
        if which in ("all", "window"):
            win_sql = (
                "SELECT SUM(l_quantity) OVER (PARTITION BY l_returnflag, l_linestatus"
                " ORDER BY l_shipdate, l_orderkey, l_linenumber) FROM lineitem"
            )
            if win_rows != rows:
                sw = Session()
                tpch.setup_lineitem(sw, win_rows)
            else:
                sw = s
            out.append(_throughput(sw, win_sql, win_rows, max(3, reps // 2), host_reps,
                                   "window_sum_partition", check="numeric",
                                   device_engine="auto"))
            del sw
        if which in ("all", "q1"):
            q1_line = _throughput(s, tpch.Q1, rows, reps, host_reps, "tpch_q1")
            q1_line["metric"] = "tpch_q1_rows_per_sec"

    # -- cross-session launch batching (sched/batcher.py) -----------------
    if which in ("all", "sched"):
        sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"))
        from bench_sched import run_sched_bench

        out.append(run_sched_bench())

    # -- q3 through the mesh MPP path -------------------------------------
    if which in ("all", "q3"):
        s3 = Session()
        t0 = time.time()
        tpch.setup_tpch(s3, q3_rows)
        print(json.dumps({"load": "tpch", "rows": q3_rows, "s": round(time.time() - t0, 1)}),
              file=sys.stderr)
        s3.vars["tidb_allow_mpp"] = "ON"
        mpp0 = s3.cop.mpp.compile_count if hasattr(s3.cop, "mpp") else 0
        line = _throughput(s3, tpch.Q3, q3_rows, max(5, reps // 2), host_reps, "tpch_q3_mpp")
        mpp1 = s3.cop.mpp.compile_count if hasattr(s3.cop, "mpp") else 0
        print(json.dumps({
            "mpp_programs_compiled": mpp1 - mpp0,
            "mpp_fallbacks": getattr(s3.cop.mpp, "fallbacks", 0),
            "mpp_note": getattr(s3.cop.mpp, "last_fallback_reason", ""),
        }), file=sys.stderr)
        out.append(line)

    for line in out:
        print(json.dumps(line))
    if q1_line is not None:
        print(json.dumps(q1_line))


if __name__ == "__main__":
    main()

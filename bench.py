"""Benchmark: TPC-H Q1 through the full SQL path on the TPU cop engine.

Prints ONE JSON line:
  {"metric": "tpch_q1_rows_per_sec", "value": N, "unit": "rows/s",
   "vs_baseline": tpu_throughput / host_numpy_throughput}

The baseline is this framework's own host (numpy-vectorized) cop engine on
identical data and plans — the stand-in for the reference's Go unistore
closure executor (BASELINE.md: "≥10× unistore cop throughput" is the
north star; the Go engine isn't runnable in this image, so the ratio is
reported against the strongest CPU path available).

Env knobs: BENCH_ROWS (default 16,000,000 — ~TPC-H SF2.7 lineitem; large
enough that the per-dispatch tunnel round-trip (~100ms fixed, measured) is
amortized and the number reflects engine throughput), BENCH_QUERY (q1|q6|topn).
"""

import json
import os
import statistics
import sys
import time


def main():
    # honor an explicit CPU request even though the axon plugin pins
    # jax_platforms at interpreter start (env alone is too late here)
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    rows = int(os.environ.get("BENCH_ROWS", "16000000"))
    which = os.environ.get("BENCH_QUERY", "q1")
    reps = int(os.environ.get("BENCH_REPS", "11"))

    from tidb_tpu.session import Session
    from tidb_tpu.models import tpch

    s = Session()
    t0 = time.time()
    tpch.setup_lineitem(s, rows)
    load_s = time.time() - t0

    q = {"q1": tpch.Q1, "q6": tpch.Q6, "topn": tpch.TOPN}[which]

    def run(engine: str, n: int):
        s.vars["tidb_cop_engine"] = engine
        times = []
        result = None
        for _ in range(n):
            t = time.time()
            result = s.execute(q)
            times.append(time.time() - t)
        return result, min(times), statistics.median(times)

    # warm both paths (compile + tile/device cache build); two tpu warmups
    # absorb tunnel-side first-touch latency
    host_res, _, _ = run("host", 1)
    tpu_res, _, _ = run("tpu", 2)
    if s.cop.tpu.fallbacks:
        print(f"WARNING: tpu engine fell back {s.cop.tpu.fallbacks}x", file=sys.stderr)
    assert host_res.rows() == tpu_res.rows(), "engine results diverge"

    _, host_best, host_med = run("host", min(3, max(reps // 2, 2)))
    _, tpu_best, tpu_med = run("tpu", reps)

    value = rows / tpu_med
    vs = (rows / tpu_med) / (rows / host_med)
    meta = {
        "rows": rows,
        "query": which,
        "load_s": round(load_s, 2),
        "tpu_median_s": round(tpu_med, 4),
        "tpu_best_s": round(tpu_best, 4),
        "host_median_s": round(host_med, 4),
        "groups": len(tpu_res.rows()),
    }
    print(json.dumps(meta), file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": f"tpch_{which}_rows_per_sec",
                "value": round(value, 1),
                "unit": "rows/s",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()

"""Plan bindings — persisted hint sets matched by statement digest
(ref: bindinfo/handle.go:48 BindHandle, :124 Update; bindings live in
mysql.bind_info and attach their hints to any un-hinted statement whose
normalized digest matches)."""

from __future__ import annotations

import threading


class BindingCache:
    def __init__(self, storage):
        self.storage = storage
        self.notify_version = 0
        self._version = -1
        self._lock = threading.Lock()
        self._sys_session = None
        self._by_digest: dict[str, list] = {}  # digest → hints [(NAME, args)]

    def bump_version(self) -> None:
        with self._lock:
            self.notify_version += 1

    def _sys(self):
        if self._sys_session is None:
            from .session import Session

            self._sys_session = Session(self.storage)
        return self._sys_session

    def _ensure(self) -> None:
        with self._lock:
            v = self.notify_version
            if v == self._version:
                return
            from .parser import parse_one

            sess = self._sys()
            by_digest: dict[str, list] = {}
            for digest, bind_sql in sess._sql_internal(
                "SELECT original_digest, bind_sql FROM mysql.bind_info WHERE status = 'enabled'"
            ):
                try:
                    stmt = parse_one(bind_sql)
                except Exception:  # noqa: BLE001 — a broken binding must not break queries
                    continue
                hints = list(getattr(stmt, "hints", []) or [])
                if hints:
                    by_digest[digest] = hints
            self._by_digest = by_digest
            self._version = v

    def hints_for(self, digest: str) -> list:
        self._ensure()
        return self._by_digest.get(digest, [])

    def rows(self):
        self._ensure()
        return sorted(self._by_digest.items())

"""TPC-H workload module — the framework's flagship "model family"
(BASELINE.md configs: Q1/Q6/Q3/TopN on lineitem/orders/customer).

Provides schema DDL, a fast numpy data generator, a bulk loader through
the ingest path (the Lightning-analog, storage/mvcc.py ingest), and the
benchmark queries.
"""

from __future__ import annotations

import numpy as np

from ..codec.row import encode_row
from ..codec import tablecodec
from ..mysqltypes.coretime import pack_time
from ..mysqltypes.datum import (
    Datum,
    K_DEC,
    K_DUR,
    K_FLOAT,
    K_INT,
    K_STR,
    K_TIME,
    K_UINT,
)
from ..br.ingest import datum_for

LINEITEM_DDL = """CREATE TABLE lineitem (
  l_orderkey BIGINT NOT NULL,
  l_partkey BIGINT NOT NULL,
  l_suppkey BIGINT NOT NULL,
  l_linenumber BIGINT NOT NULL,
  l_quantity DECIMAL(15,2) NOT NULL,
  l_extendedprice DECIMAL(15,2) NOT NULL,
  l_discount DECIMAL(15,2) NOT NULL,
  l_tax DECIMAL(15,2) NOT NULL,
  l_returnflag CHAR(1) NOT NULL,
  l_linestatus CHAR(1) NOT NULL,
  l_shipdate DATE NOT NULL,
  l_commitdate DATE NOT NULL,
  l_receiptdate DATE NOT NULL,
  KEY idx_ship (l_shipdate)
)"""

ORDERS_DDL = """CREATE TABLE orders (
  o_orderkey BIGINT NOT NULL PRIMARY KEY,
  o_custkey BIGINT NOT NULL,
  o_orderstatus CHAR(1) NOT NULL,
  o_totalprice DECIMAL(15,2) NOT NULL,
  o_orderdate DATE NOT NULL,
  o_orderpriority CHAR(15) NOT NULL,
  o_shippriority BIGINT NOT NULL
)"""

CUSTOMER_DDL = """CREATE TABLE customer (
  c_custkey BIGINT NOT NULL PRIMARY KEY,
  c_name VARCHAR(25) NOT NULL,
  c_mktsegment CHAR(10) NOT NULL,
  c_acctbal DECIMAL(15,2) NOT NULL
)"""

Q1 = """SELECT l_returnflag, l_linestatus,
  SUM(l_quantity) AS sum_qty,
  SUM(l_extendedprice) AS sum_base_price,
  SUM(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
  SUM(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
  AVG(l_quantity) AS avg_qty,
  AVG(l_extendedprice) AS avg_price,
  AVG(l_discount) AS avg_disc,
  COUNT(*) AS count_order
FROM lineitem
WHERE l_shipdate <= '1998-09-02'
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus"""

Q6 = """SELECT SUM(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= '1994-01-01' AND l_shipdate < '1995-01-01'
  AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24"""

TOPN = "SELECT l_orderkey, l_extendedprice FROM lineitem ORDER BY l_extendedprice DESC LIMIT 100"

Q3 = """SELECT o.o_orderkey, SUM(l.l_extendedprice * (1 - l.l_discount)) AS revenue, o.o_orderdate
FROM customer c JOIN orders o ON c.c_custkey = o.o_custkey
JOIN lineitem l ON l.l_orderkey = o.o_orderkey
WHERE c.c_mktsegment = 'BUILDING' AND o.o_orderdate < '1995-03-15' AND l.l_shipdate > '1995-03-15'
GROUP BY o.o_orderkey, o.o_orderdate
ORDER BY revenue DESC LIMIT 10"""


def _rand_dates(rng, n, y0=1992, y1=1998):
    """Packed date int64s uniform over [y0, y1]."""
    years = rng.integers(y0, y1 + 1, n)
    months = rng.integers(1, 13, n)
    days = rng.integers(1, 29, n)
    return ((years * 13 + months) * 32 + days) * (24 * 60 * 60 * 1_000_000)


def gen_lineitem(n_rows: int, seed: int = 42) -> dict[str, np.ndarray]:
    """Generate lineitem columns, distribution-shaped like dbgen."""
    rng = np.random.default_rng(seed)
    orderkey = np.sort(rng.integers(1, max(n_rows // 4, 2), n_rows))
    qty = rng.integers(100, 5100, n_rows)  # 1.00..51.00 scale 2
    price = rng.integers(90000, 10500000, n_rows)  # 900.00..105000.00
    discount = rng.integers(0, 11, n_rows)  # 0.00..0.10
    tax = rng.integers(0, 9, n_rows)
    shipdate = _rand_dates(rng, n_rows)
    rf = rng.choice(np.array(["A", "N", "R"], dtype=object), n_rows, p=[0.25, 0.5, 0.25])
    ls = np.where(rng.random(n_rows) < 0.5, "O", "F").astype(object)
    return {
        "l_orderkey": orderkey,
        "l_partkey": rng.integers(1, 200000, n_rows),
        "l_suppkey": rng.integers(1, 10000, n_rows),
        "l_linenumber": rng.integers(1, 8, n_rows),
        "l_quantity": qty,
        "l_extendedprice": price,
        "l_discount": discount,
        "l_tax": tax,
        "l_returnflag": rf,
        "l_linestatus": ls,
        "l_shipdate": shipdate,
        "l_commitdate": shipdate + 32 * 24 * 3600 * 1_000_000,
        "l_receiptdate": shipdate + 33 * 24 * 3600 * 1_000_000,
    }




def gen_orders(n_orders: int, n_cust: int, seed: int = 43) -> dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    prios = np.array(["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"], dtype=object)
    return {
        "o_orderkey": np.arange(1, n_orders + 1, dtype=np.int64),
        "o_custkey": rng.integers(1, n_cust + 1, n_orders),
        "o_orderstatus": np.where(rng.random(n_orders) < 0.5, "O", "F").astype(object),
        "o_totalprice": rng.integers(90000, 50000000, n_orders),
        "o_orderdate": _rand_dates(rng, n_orders),
        "o_orderpriority": rng.choice(prios, n_orders),
        "o_shippriority": np.zeros(n_orders, dtype=np.int64),
    }


def gen_customer(n_cust: int, seed: int = 44) -> dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    segs = np.array(["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"], dtype=object)
    return {
        "c_custkey": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_name": np.array([f"Customer#{i:09d}" for i in range(1, n_cust + 1)], dtype=object),
        "c_mktsegment": rng.choice(segs, n_cust),
        "c_acctbal": rng.integers(-99999, 999999, n_cust),
    }


def generated_columns(n_lineitem: int, seed: int = 42):
    """The exact (lineitem, orders, customer) column dicts setup_tpch
    loads — single source of truth for test oracles."""
    n_orders = max(n_lineitem // 4, 2)
    n_cust = max(n_orders // 10, 2)
    return (
        gen_lineitem(n_lineitem, seed),
        gen_orders(n_orders, n_cust, seed + 1),
        gen_customer(n_cust, seed + 2),
    )


def setup_tpch(session, n_lineitem: int, seed: int = 42) -> None:
    """Load lineitem + orders + customer at a consistent mini scale:
    orderkeys correlate across lineitem/orders, custkeys across
    orders/customer (dbgen's referential shape)."""
    li, orders, cust = generated_columns(n_lineitem, seed)
    session.execute("DROP TABLE IF EXISTS lineitem")
    session.execute("DROP TABLE IF EXISTS orders")
    session.execute("DROP TABLE IF EXISTS customer")
    session.execute(LINEITEM_DDL)
    session.execute(ORDERS_DDL)
    session.execute(CUSTOMER_DDL)
    bulk_load(session, "lineitem", li)
    bulk_load(session, "orders", orders)
    bulk_load(session, "customer", cust)


Q4 = """SELECT o_orderpriority, COUNT(*) AS order_count
FROM orders
WHERE o_orderdate >= '1995-01-01' AND o_orderdate < '1996-01-01'
AND EXISTS (SELECT 1 FROM lineitem WHERE l_orderkey = o_orderkey AND l_commitdate < l_receiptdate)
GROUP BY o_orderpriority ORDER BY o_orderpriority"""

Q10 = """SELECT c.c_custkey, c.c_name, SUM(l.l_extendedprice * (1 - l.l_discount)) AS revenue
FROM customer c JOIN orders o ON c.c_custkey = o.o_custkey
JOIN lineitem l ON l.l_orderkey = o.o_orderkey
WHERE l.l_returnflag = 'R'
GROUP BY c.c_custkey, c.c_name ORDER BY revenue DESC, c.c_custkey LIMIT 20"""

Q18 = """SELECT o.o_orderkey, SUM(l.l_quantity) AS total_qty
FROM orders o JOIN lineitem l ON l.l_orderkey = o.o_orderkey
GROUP BY o.o_orderkey HAVING SUM(l.l_quantity) > 100
ORDER BY total_qty DESC, o.o_orderkey LIMIT 10"""


def _kind_of(ft) -> int:
    # ONE definition with the bulk engine (br/ingest.kind_of) — the PR 11
    # K_INT fallthrough that truncated DOUBLE columns to ints lived in a
    # private copy of this mapping
    from ..br.ingest import kind_of

    return kind_of(ft)


# kinds the columnar bulk path encodes; K_BYTES stays excluded (the
# trailing-NUL width heuristic would clip binary values ending in 0x00)
_BULK_KINDS = (K_INT, K_UINT, K_FLOAT, K_DEC, K_TIME, K_DUR, K_STR)


def bulk_load(session, table_name: str, columns: dict[str, np.ndarray], kinds: dict[str, int] | None = None, batch: int = 500_000):
    """Bulk-load columns into a table through the ingest path (2PC bypass,
    the Lightning local backend analog). Rows get sequential handles.
    Column kinds derive from the table schema unless overridden.

    Default route (tidb_bulk_ingest=ON): the shared bulk engine
    (br/ingest.BulkIngest) keeps the data COLUMNAR end to end — canonical
    numpy lanes become a ColumnarRun + IntIndexRun artifacts published
    atomically under one WAL ingest record; no row-major byte plane is
    materialized at load time. OFF (or ineligible kinds) recovers the
    legacy per-batch path: v2 row encode + per-batch segment ingest."""
    info = session.infoschema().table(session.current_db, table_name)
    names = list(columns)
    col_infos = [info.col_by_name(n) for n in names]
    if kinds is None:
        kinds = {n: _kind_of(c.ft) for n, c in zip(names, col_infos)}
    n = len(columns[names[0]])
    kind_list = [kinds[n_] for n_ in names]
    if (
        session.vars.get("tidb_bulk_ingest", "ON") == "ON"
        and info.partition is None
        and all(k in _BULK_KINDS for k in kind_list)
    ):
        from ..br.ingest import BulkIngest, IngestAborted

        try:
            job = BulkIngest(session, info)
        except IngestAborted:
            # DDL queued/running on the table: the legacy per-batch
            # segment path coexists with online DDL as it always did
            job = None
        if job is not None:
            try:
                job.add_columns(names, [columns[nm] for nm in names], kind_list)
                job.commit()
            except IngestAborted:
                job.abort()  # publish-time abort: recover via legacy below
            except BaseException:
                job.abort()
                raise
            else:
                return n
    return _bulk_load_segments(session, info, names, columns, kinds, col_infos, batch)


def _bulk_load_segments(session, info, names, columns, kinds, col_infos, batch):
    """Legacy bulk path (tidb_bulk_ingest=OFF): v2 row-major encode +
    one segment ingest per batch — kept bit-compatible as the live
    fallback and the paired-bench baseline."""
    from ..codec import rowfast

    col_ids = [c.id for c in col_infos]
    n = len(columns[names[0]])
    # clustered int pk: the pk VALUE is the row handle (ref: tables.go
    # AddRecord pkIsHandle) — sequential handles would mis-key PointGet
    # and index back-reads
    pk_handle_pos = None
    if info.pk_is_handle:
        hc = info.handle_col()
        pk_handle_pos = next(i for i, c in enumerate(col_infos) if c.offset == hc.offset)
        first_handle = None
    else:
        first_handle = session.alloc_auto_id(info, n)
    arrays = [columns[n_] for n_ in names]
    kind_list = [kinds[n_] for n_ in names]
    commit_ts = session.store.tso.next()
    scale_fix = [max(c.ft.decimal, 0) if k == K_DEC else 0 for c, k in zip(col_infos, kind_list)]
    indexes = [ix for ix in info.indexes if ix.state not in ("none", "delete_only") and not (info.pk_is_handle and ix.primary)]

    if rowfast.encodable_kinds(kind_list):
        name_pos = {c.offset: i for i, c in enumerate(col_infos)}
        int_kinds = (K_INT, K_TIME)
        mvcc = session.store.mvcc
        for lo in range(0, n, batch):
            hi = min(lo + batch, n)
            m = hi - lo
            arrs = [a[lo:hi] for a in arrays]
            if pk_handle_pos is not None:
                handles = np.asarray(arrs[pk_handle_pos]).astype(np.int64)
                presorted = bool(np.all(np.diff(handles) > 0)) if m > 1 else True
            else:
                handles = np.arange(first_handle + lo, first_handle + hi, dtype=np.int64)
                presorted = True
            buf, offs = rowfast.encode_rows_v2(col_ids, kind_list, scale_fix, arrs)
            key_mat = rowfast.record_key_matrix(info.id, handles)
            mvcc.ingest_run(key_mat, buf, offs[:-1], np.diff(offs), commit_ts, presorted=presorted)
            for ix in indexes:
                poss = [name_pos.get(off) for off in ix.col_offsets]
                if all(p is not None and kind_list[p] in int_kinds for p in poss):
                    kcols = [np.asarray(arrs[p]).astype(np.int64) for p in poss]
                    if ix.unique:
                        imat = rowfast.int_index_key_matrix(info.id, ix.id, kcols, None)
                        vbuf, vstarts, vlens = rowfast.handle_value_buffer(handles)
                        mvcc.ingest_run(imat, vbuf, vstarts, vlens, commit_ts)
                    else:
                        imat = rowfast.int_index_key_matrix(info.id, ix.id, kcols, handles)
                        z = np.zeros(m, dtype=np.int64)
                        mvcc.ingest_run(imat, b"", z, z, commit_ts)
                else:  # string/decimal/missing index cols — per-row fallback
                    kvs: list[tuple[bytes, bytes]] = []
                    _index_kvs_slow(info, ix, col_infos, arrs, kind_list, scale_fix, handles, kvs)
                    mvcc.ingest(kvs, commit_ts)
    else:
        _bulk_load_rows(session, info, col_infos, col_ids, arrays, kind_list, scale_fix, pk_handle_pos, first_handle, indexes, commit_ts, batch)
    # semi-sync parity with the bulk engine: each ingest_run fsynced
    # locally; one wal_sync extends the ack to durable-on-standby
    session.store.wal_sync()
    session.store.bump_version([tablecodec.record_prefix(info.id)])
    session.cop.tiles.invalidate_table(info.id)
    return n


def _index_kvs_slow(info, ix, col_infos, arrs, kind_list, scale_fix, handles, kvs):
    from ..table.table import Table

    tbl = Table(info)
    n_tbl_cols = len(info.columns)
    offsets = [c.offset for c in col_infos]
    for i in range(len(handles)):
        full = [Datum.null()] * n_tbl_cols
        for off, arr, k, sf in zip(offsets, arrs, kind_list, scale_fix):
            full[off] = datum_for(k, arr[i], sf)
        for c in info.columns:
            if c.hidden and c.name == "_tidb_rowid":
                full[c.offset] = Datum.i(int(handles[i]))
        ikey, ival, _ = tbl.index_value_key(ix, full, int(handles[i]))
        kvs.append((ikey, ival))


def _bulk_load_rows(session, info, col_infos, col_ids, arrays, kind_list, scale_fix, pk_handle_pos, first_handle, indexes, commit_ts, batch):
    """Per-row fallback for kinds the vectorized encoder doesn't cover."""
    from ..table.table import Table

    tbl = Table(info)
    offsets = [c.offset for c in col_infos]
    n_tbl_cols = len(info.columns)
    n = len(arrays[0])
    kvs = []
    for lo in range(0, n, batch):
        hi = min(lo + batch, n)
        for i in range(lo, hi):
            datums = [
                datum_for(k, arr[i], sf)
                for arr, k, sf in zip(arrays, kind_list, scale_fix)
            ]
            handle = datums[pk_handle_pos].to_int() if pk_handle_pos is not None else first_handle + i
            kvs.append((tablecodec.record_key(info.id, handle), encode_row(col_ids, datums)))
            if indexes:
                full = [Datum.null()] * n_tbl_cols
                for off, d in zip(offsets, datums):
                    full[off] = d
                for c in info.columns:
                    if c.hidden and c.name == "_tidb_rowid":
                        full[c.offset] = Datum.i(handle)
                for ix in indexes:
                    ikey, ival, _ = tbl.index_value_key(ix, full, handle)
                    kvs.append((ikey, ival))
        session.store.mvcc.ingest(kvs, commit_ts)
        kvs = []


def setup_lineitem(session, n_rows: int, seed: int = 42) -> int:
    session.execute("DROP TABLE IF EXISTS lineitem")
    session.execute(LINEITEM_DDL)
    cols = gen_lineitem(n_rows, seed)
    return bulk_load(session, "lineitem", cols)

from .schema import ColumnInfo, IndexInfo, TableInfo, DBInfo, InfoSchema
from .meta import Meta

"""Meta KV layout (ref: meta/meta.go + structure/ — fresh key design).

All schema metadata lives in the same transactional KV as table data, under
the b'm' prefix (sorts before all b't...' record keys):

  m:nextid           → global id allocator counter
  m:schema_version   → monotonically increasing schema version
  m:db:<name>        → DBInfo json
  m:tbl:<id>         → TableInfo json

Every DDL runs inside a normal 2PC txn over these keys, so concurrent DDL
conflicts surface as WriteConflict and retry — a deliberately simpler
model than the reference's async job queues (ddl/ddl_worker.go), kept
compatible in behavior for the single-coordinator case; the online
state-machine lives in ddl.py above this layer.
"""

from __future__ import annotations

import json

from .schema import DBInfo, TableInfo

K_NEXT_ID = b"m:nextid"
K_SCHEMA_VER = b"m:schema_version"
P_DB = b"m:db:"
P_TBL = b"m:tbl:"
P_JOB = b"m:job:"  # queued/running DDL jobs (ref: meta job queues, ddl_worker.go:67)
P_JOB_HIST = b"m:jobh:"  # finished jobs (ADMIN SHOW DDL JOBS)
P_SEQ = b"m:seq:"  # sequences (ref: ddl sequence objects, meta/autoid SequenceAllocator)
P_VIEW = b"m:view:"  # view definitions (stored SELECT text)
P_RG = b"m:rg:"  # resource groups (ref: meta.go ResourceGroup key space, DDL-managed)
P_RW = b"m:rw:"  # runaway watch list (sched/runaway.py): persisted KILL/
# COOLDOWN/DRYRUN digest watches so repeat offenders stay rejected across
# store restart (ref: mysql.tidb_runaway_watch, swept by TTL on load)


class Meta:
    """Meta accessor bound to one transaction."""

    def __init__(self, txn):
        self.txn = txn

    # --- id allocation -----------------------------------------------------

    def alloc_id(self, n: int = 1) -> int:
        cur = int(self.txn.get(K_NEXT_ID) or b"100")
        self.txn.put(K_NEXT_ID, str(cur + n).encode())
        return cur

    # --- schema version ----------------------------------------------------

    def schema_version(self) -> int:
        return int(self.txn.get(K_SCHEMA_VER) or b"0")

    def bump_schema_version(self) -> int:
        v = self.schema_version() + 1
        self.txn.put(K_SCHEMA_VER, str(v).encode())
        return v

    # --- databases ---------------------------------------------------------

    def db(self, name: str) -> DBInfo | None:
        raw = self.txn.get(P_DB + name.lower().encode())
        return DBInfo.from_json(json.loads(raw)) if raw else None

    def put_db(self, db: DBInfo) -> None:
        self.txn.put(P_DB + db.name.lower().encode(), json.dumps(db.to_json()).encode())

    def drop_db(self, name: str) -> None:
        self.txn.delete(P_DB + name.lower().encode())

    def list_dbs(self) -> list[DBInfo]:
        out = []
        for _, v in self.txn.scan(P_DB, P_DB + b"\xff"):
            out.append(DBInfo.from_json(json.loads(v)))
        return out

    # --- tables ------------------------------------------------------------

    def table(self, tid: int) -> TableInfo | None:
        raw = self.txn.get(P_TBL + str(tid).encode())
        return TableInfo.from_json(json.loads(raw)) if raw else None

    def put_table(self, t: TableInfo) -> None:
        self.txn.put(P_TBL + str(t.id).encode(), json.dumps(t.to_json()).encode())

    def drop_table(self, tid: int) -> None:
        self.txn.delete(P_TBL + str(tid).encode())

    def list_tables(self) -> list[TableInfo]:
        out = []
        for _, v in self.txn.scan(P_TBL, P_TBL + b"\xff"):
            out.append(TableInfo.from_json(json.loads(v)))
        return out

    # --- sequences (ref: 2020-04-17-sql-sequence.md; cached allocation) ----

    @staticmethod
    def _seq_key(db: str, name: str) -> bytes:
        return P_SEQ + f"{db.lower()}.{name.lower()}".encode()

    def sequence(self, db: str, name: str) -> dict | None:
        raw = self.txn.get(self._seq_key(db, name))
        return json.loads(raw) if raw else None

    def put_sequence(self, d: dict) -> None:
        self.txn.put(self._seq_key(d["db"], d["name"]), json.dumps(d).encode())

    def drop_sequence(self, db: str, name: str) -> None:
        self.txn.delete(self._seq_key(db, name))

    def list_sequences(self) -> list[dict]:
        return [json.loads(v) for _, v in self.txn.scan(P_SEQ, P_SEQ + b"\xff")]

    # --- views (ref: ddl_api.go CreateView; definition stored as text) -----

    @staticmethod
    def _view_key(db: str, name: str) -> bytes:
        return P_VIEW + f"{db.lower()}.{name.lower()}".encode()

    def view(self, db: str, name: str) -> dict | None:
        raw = self.txn.get(self._view_key(db, name))
        return json.loads(raw) if raw else None

    def put_view(self, d: dict) -> None:
        self.txn.put(self._view_key(d["db"], d["name"]), json.dumps(d).encode())

    def drop_view(self, db: str, name: str) -> None:
        self.txn.delete(self._view_key(db, name))

    def list_views(self) -> list[dict]:
        return [json.loads(v) for _, v in self.txn.scan(P_VIEW, P_VIEW + b"\xff")]

    # --- resource groups (ref: meta.go CreateResourceGroup; stored as the
    # group's keepalive-free spec dict, cached by sched.ResourceGroupManager) -

    @staticmethod
    def _rg_key(name: str) -> bytes:
        return P_RG + name.lower().encode()

    def resource_group(self, name: str) -> dict | None:
        raw = self.txn.get(self._rg_key(name))
        return json.loads(raw) if raw else None

    def put_resource_group(self, d: dict) -> None:
        self.txn.put(self._rg_key(d["name"]), json.dumps(d).encode())

    def drop_resource_group(self, name: str) -> None:
        self.txn.delete(self._rg_key(name))

    def list_resource_groups(self) -> list[dict]:
        return [json.loads(v) for _, v in self.txn.scan(P_RG, P_RG + b"\xff")]

    # --- runaway watch list (ref: mysql.tidb_runaway_watch; spec dicts
    # carry WALL-clock expiry so a restart can rebuild monotonic TTLs) ---

    @staticmethod
    def _rw_key(group: str, digest: str) -> bytes:
        return P_RW + f"{group}:{digest}".encode()

    def put_runaway_watch(self, d: dict) -> None:
        self.txn.put(self._rw_key(d["group"], d["digest"]), json.dumps(d).encode())

    def drop_runaway_watch(self, group: str, digest: str) -> None:
        self.txn.delete(self._rw_key(group, digest))

    def list_runaway_watches(self) -> list[dict]:
        return [json.loads(v) for _, v in self.txn.scan(P_RW, P_RW + b"\xff")]

    # --- DDL job queue (ref: ddl.go:535 doDDLJob, meta job lists) ----------

    @staticmethod
    def _job_key(jid: int) -> bytes:
        return P_JOB + f"{jid:012d}".encode()  # zero-pad: queue scans in id order

    def put_job(self, job) -> None:
        self.txn.put(self._job_key(job.id), json.dumps(job.to_json()).encode())

    def job(self, jid: int):
        from ..ddl.jobs import DDLJob

        raw = self.txn.get(self._job_key(jid))
        return DDLJob.from_json(json.loads(raw)) if raw else None

    def first_job(self):
        from ..ddl.jobs import DDLJob

        for _, v in self.txn.scan(P_JOB, P_JOB + b"\xff", limit=1):
            return DDLJob.from_json(json.loads(v))
        return None

    def jobs(self) -> list:
        from ..ddl.jobs import DDLJob

        return [DDLJob.from_json(json.loads(v)) for _, v in self.txn.scan(P_JOB, P_JOB + b"\xff")]

    def history_job(self, jid: int):
        from ..ddl.jobs import DDLJob

        raw = self.txn.get(P_JOB_HIST + f"{jid:012d}".encode())
        return DDLJob.from_json(json.loads(raw)) if raw else None

    def finish_job(self, job) -> None:
        """Move a job from the queue to history (ref: finishDDLJob)."""
        self.txn.delete(self._job_key(job.id))
        self.txn.put(P_JOB_HIST + f"{job.id:012d}".encode(), json.dumps(job.to_json()).encode())

    def job_history(self) -> list:
        from ..ddl.jobs import DDLJob

        out = []
        for _, v in self.txn.scan(P_JOB_HIST, P_JOB_HIST + b"\xff"):
            out.append(DDLJob.from_json(json.loads(v)))
        return out

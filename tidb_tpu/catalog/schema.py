"""Schema objects + InfoSchema cache (ref: infoschema/, parser/model).

TableInfo/ColumnInfo/IndexInfo serialize to JSON into the meta KV layout
(meta.py) and are cached per schema version in InfoSchema.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from ..errors import UnknownColumn, UnknownTable, UnknownDatabase
from ..mysqltypes.field_type import FieldType, TypeCode


@dataclass
class ColumnInfo:
    id: int
    name: str
    ft: FieldType
    offset: int
    default: object = None  # rendered default (python value) or None
    has_default: bool = False
    auto_increment: bool = False
    hidden: bool = False
    comment: str = ""

    def to_json(self):
        return {
            "id": self.id,
            "name": self.name,
            "tp": int(self.ft.tp),
            "flag": self.ft.flag,
            "flen": self.ft.flen,
            "decimal": self.ft.decimal,
            "elems": list(self.ft.elems),
            "collate": self.ft.collate,
            "offset": self.offset,
            "default": self.default,
            "has_default": self.has_default,
            "auto_increment": self.auto_increment,
            "hidden": self.hidden,
            "comment": self.comment,
        }

    @staticmethod
    def from_json(d):
        ft = FieldType(TypeCode(d["tp"]), d["flag"], d["flen"], d["decimal"], elems=tuple(d.get("elems", ())))
        ft.collate = d.get("collate", "utf8mb4_bin")
        return ColumnInfo(
            d["id"], d["name"], ft, d["offset"], d.get("default"), d.get("has_default", False),
            d.get("auto_increment", False), d.get("hidden", False), d.get("comment", ""),
        )


@dataclass
class IndexInfo:
    id: int
    name: str
    col_offsets: list[int]
    unique: bool = False
    primary: bool = False
    state: str = "public"  # online DDL states: delete_only/write_only/write_reorg/public

    def to_json(self):
        return {"id": self.id, "name": self.name, "cols": self.col_offsets, "unique": self.unique, "primary": self.primary, "state": self.state}

    @staticmethod
    def from_json(d):
        return IndexInfo(d["id"], d["name"], d["cols"], d["unique"], d["primary"], d.get("state", "public"))


@dataclass
class PartitionDef:
    """One partition: its own physical keyspace id (ref: model
    PartitionDefinition — each partition is a physical table)."""

    id: int
    name: str
    less_than: int | None = None  # RANGE bound; None = MAXVALUE / hash
    in_values: tuple | None = None  # LIST membership (may contain None=NULL)

    def to_json(self):
        return {"id": self.id, "name": self.name, "less_than": self.less_than,
                "in_values": list(self.in_values) if self.in_values is not None else None}

    @staticmethod
    def from_json(d):
        iv = d.get("in_values")
        return PartitionDef(d["id"], d["name"], d.get("less_than"),
                            tuple(iv) if iv is not None else None)


@dataclass
class PartitionInfo:
    """HASH / RANGE / LIST partitioning over one integer column (ref:
    model PartitionInfo + table/tables/partition.go locatePartition /
    locateListPartition)."""

    type: str  # 'hash' | 'range' | 'list'
    col: str  # partitioning column name
    defs: list[PartitionDef] = field(default_factory=list)

    def locate(self, v) -> PartitionDef:
        """Partition for one partition-column value. NULLs go to
        partition 0 for hash, the first range partition for range
        (MySQL: NULL sorts below every bound); LIST requires a partition
        that lists NULL explicitly."""
        from ..errors import TiDBError

        if self.type == "list":
            key = None if v is None else int(v)
            for pd in self.defs:
                if pd.in_values is not None and key in pd.in_values:
                    return pd
            raise TiDBError(
                "Table has no partition for value "
                + ("NULL" if v is None else str(int(v)))
            )
        if v is None:
            return self.defs[0]
        v = int(v)
        if self.type == "hash":
            # MySQL/TiDB use truncated modulo then abs (locateHashPartition,
            # ref table/tables/partition.go): -1 % 4 → p1, not Python's p3.
            # abs(v) % n IS truncated-mod-then-abs in exact int arithmetic.
            return self.defs[abs(v) % len(self.defs)]
        for pd in self.defs:
            if pd.less_than is None or v < pd.less_than:
                return pd
        raise TiDBError(f"Table has no partition for value {v}")

    def prune(self, eq_values=None, lo=None, hi=None) -> list[PartitionDef]:
        """Partitions that can contain rows matching the constraint:
        either an equality value set, or a [lo, hi] closed interval on the
        partition column (range partitioning only for intervals)."""
        if eq_values is not None:
            out, seen = [], set()
            for v in eq_values:
                try:
                    pd = self.locate(v)
                except Exception:  # value beyond the last range bound
                    continue
                if pd.id not in seen:
                    seen.add(pd.id)
                    out.append(pd)
            return out
        if self.type == "list" and (lo is not None or hi is not None):
            # a LIST partition can match iff some listed value intersects
            # the interval (rule_partition_processor.go list pruning)
            return [
                pd for pd in self.defs
                if pd.in_values and any(
                    x is not None
                    and (lo is None or x >= lo)
                    and (hi is None or x <= hi)
                    for x in pd.in_values
                )
            ]
        if self.type == "range" and (lo is not None or hi is not None):
            out = []
            prev_bound = None
            for pd in self.defs:
                # partition covers [prev_bound, less_than)
                if hi is not None and prev_bound is not None and hi < prev_bound:
                    break
                if lo is None or pd.less_than is None or lo < pd.less_than:
                    out.append(pd)
                prev_bound = pd.less_than
            return out
        return list(self.defs)

    def to_json(self):
        return {"type": self.type, "col": self.col, "defs": [d.to_json() for d in self.defs]}

    @staticmethod
    def from_json(d):
        return PartitionInfo(d["type"], d["col"], [PartitionDef.from_json(x) for x in d["defs"]])


@dataclass
class TableInfo:
    id: int
    name: str
    columns: list[ColumnInfo]
    indexes: list[IndexInfo] = field(default_factory=list)
    pk_is_handle: bool = False  # clustered single-int PK == row handle
    auto_inc_id: int = 1
    state: str = "public"
    db_name: str = ""
    partition: PartitionInfo | None = None

    def col_by_name(self, name: str) -> ColumnInfo:
        lname = name.lower()
        for c in self.columns:
            if c.name.lower() == lname:
                return c
        raise UnknownColumn(f"unknown column {name!r} in {self.name!r}")

    def visible_columns(self) -> list[ColumnInfo]:
        return [c for c in self.columns if not c.hidden]

    def handle_col(self) -> ColumnInfo | None:
        if self.pk_is_handle:
            pk = next((i for i in self.indexes if i.primary), None)
            if pk:
                return self.columns[pk.col_offsets[0]]
        return next((c for c in self.columns if c.name == "_tidb_rowid"), None)

    def index_by_name(self, name: str) -> IndexInfo | None:
        lname = name.lower()
        return next((i for i in self.indexes if i.name.lower() == lname), None)

    def physical_ids(self) -> list[int]:
        """Keyspace ids holding this table's rows (partition ids, or the
        table's own id when unpartitioned)."""
        if self.partition is not None:
            return [pd.id for pd in self.partition.defs]
        return [self.id]

    def partition_physical(self, pid: int) -> "TableInfo":
        """Physical TableInfo for one partition: identical schema, the
        partition's keyspace id (ref: tables/partition.go
        GetPartition)."""
        cache = self.__dict__.setdefault("_phys_cache", {})
        t = cache.get(pid)
        if t is None:
            t = TableInfo(
                pid, self.name, self.columns, self.indexes, self.pk_is_handle,
                self.auto_inc_id, self.state, self.db_name,
            )
            cache[pid] = t
        return t

    def to_json(self):
        return {
            "id": self.id,
            "name": self.name,
            "columns": [c.to_json() for c in self.columns],
            "indexes": [i.to_json() for i in self.indexes],
            "pk_is_handle": self.pk_is_handle,
            "auto_inc_id": self.auto_inc_id,
            "state": self.state,
            "db_name": self.db_name,
            "partition": self.partition.to_json() if self.partition else None,
        }

    @staticmethod
    def from_json(d):
        return TableInfo(
            d["id"], d["name"],
            [ColumnInfo.from_json(c) for c in d["columns"]],
            [IndexInfo.from_json(i) for i in d["indexes"]],
            d["pk_is_handle"], d.get("auto_inc_id", 1), d.get("state", "public"), d.get("db_name", ""),
            PartitionInfo.from_json(d["partition"]) if d.get("partition") else None,
        )


@dataclass
class DBInfo:
    name: str
    table_ids: list[int] = field(default_factory=list)

    def to_json(self):
        return {"name": self.name, "table_ids": self.table_ids}

    @staticmethod
    def from_json(d):
        return DBInfo(d["name"], d["table_ids"])


class InfoSchema:
    """Immutable snapshot of the full schema at one version
    (ref: infoschema/infoschema.go)."""

    def __init__(self, version: int, dbs: dict[str, DBInfo], tables: dict[int, TableInfo], views: dict | None = None):
        self.version = version
        self.dbs = {k.lower(): v for k, v in dbs.items()}
        self.tables = tables
        self.views = views or {}  # (db, name) → {"db","name","cols","sql"}
        self._by_name: dict[tuple[str, str], TableInfo] = {}
        for t in tables.values():
            self._by_name[(t.db_name.lower(), t.name.lower())] = t

    def db_names(self) -> list[str]:
        return sorted(self.dbs)

    def has_db(self, db: str) -> bool:
        return db.lower() in self.dbs

    def table_or_none(self, db: str, name: str) -> TableInfo | None:
        """Public lookup without raising (planner shadow checks)."""
        return self._by_name.get((db.lower(), name.lower()))

    def table(self, db: str, name: str) -> TableInfo:
        t = self._by_name.get((db.lower(), name.lower()))
        if t is None:
            if not self.has_db(db):
                raise UnknownDatabase(f"unknown database {db!r}")
            raise UnknownTable(f"table {db}.{name} doesn't exist")
        return t

    def table_by_id(self, tid: int) -> TableInfo | None:
        return self.tables.get(tid)

    def tables_in_db(self, db: str) -> list[TableInfo]:
        d = self.dbs.get(db.lower())
        if d is None:
            raise UnknownDatabase(f"unknown database {db!r}")
        return sorted((self.tables[t] for t in d.table_ids if t in self.tables), key=lambda t: t.name)

"""Schema objects + InfoSchema cache (ref: infoschema/, parser/model).

TableInfo/ColumnInfo/IndexInfo serialize to JSON into the meta KV layout
(meta.py) and are cached per schema version in InfoSchema.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from ..errors import UnknownColumn, UnknownTable, UnknownDatabase
from ..mysqltypes.field_type import FieldType, TypeCode


@dataclass
class ColumnInfo:
    id: int
    name: str
    ft: FieldType
    offset: int
    default: object = None  # rendered default (python value) or None
    has_default: bool = False
    auto_increment: bool = False
    hidden: bool = False
    comment: str = ""

    def to_json(self):
        return {
            "id": self.id,
            "name": self.name,
            "tp": int(self.ft.tp),
            "flag": self.ft.flag,
            "flen": self.ft.flen,
            "decimal": self.ft.decimal,
            "elems": list(self.ft.elems),
            "offset": self.offset,
            "default": self.default,
            "has_default": self.has_default,
            "auto_increment": self.auto_increment,
            "hidden": self.hidden,
            "comment": self.comment,
        }

    @staticmethod
    def from_json(d):
        ft = FieldType(TypeCode(d["tp"]), d["flag"], d["flen"], d["decimal"], elems=tuple(d.get("elems", ())))
        return ColumnInfo(
            d["id"], d["name"], ft, d["offset"], d.get("default"), d.get("has_default", False),
            d.get("auto_increment", False), d.get("hidden", False), d.get("comment", ""),
        )


@dataclass
class IndexInfo:
    id: int
    name: str
    col_offsets: list[int]
    unique: bool = False
    primary: bool = False
    state: str = "public"  # online DDL states: delete_only/write_only/write_reorg/public

    def to_json(self):
        return {"id": self.id, "name": self.name, "cols": self.col_offsets, "unique": self.unique, "primary": self.primary, "state": self.state}

    @staticmethod
    def from_json(d):
        return IndexInfo(d["id"], d["name"], d["cols"], d["unique"], d["primary"], d.get("state", "public"))


@dataclass
class TableInfo:
    id: int
    name: str
    columns: list[ColumnInfo]
    indexes: list[IndexInfo] = field(default_factory=list)
    pk_is_handle: bool = False  # clustered single-int PK == row handle
    auto_inc_id: int = 1
    state: str = "public"
    db_name: str = ""

    def col_by_name(self, name: str) -> ColumnInfo:
        lname = name.lower()
        for c in self.columns:
            if c.name.lower() == lname:
                return c
        raise UnknownColumn(f"unknown column {name!r} in {self.name!r}")

    def visible_columns(self) -> list[ColumnInfo]:
        return [c for c in self.columns if not c.hidden]

    def handle_col(self) -> ColumnInfo | None:
        if self.pk_is_handle:
            pk = next((i for i in self.indexes if i.primary), None)
            if pk:
                return self.columns[pk.col_offsets[0]]
        return next((c for c in self.columns if c.name == "_tidb_rowid"), None)

    def index_by_name(self, name: str) -> IndexInfo | None:
        lname = name.lower()
        return next((i for i in self.indexes if i.name.lower() == lname), None)

    def to_json(self):
        return {
            "id": self.id,
            "name": self.name,
            "columns": [c.to_json() for c in self.columns],
            "indexes": [i.to_json() for i in self.indexes],
            "pk_is_handle": self.pk_is_handle,
            "auto_inc_id": self.auto_inc_id,
            "state": self.state,
            "db_name": self.db_name,
        }

    @staticmethod
    def from_json(d):
        return TableInfo(
            d["id"], d["name"],
            [ColumnInfo.from_json(c) for c in d["columns"]],
            [IndexInfo.from_json(i) for i in d["indexes"]],
            d["pk_is_handle"], d.get("auto_inc_id", 1), d.get("state", "public"), d.get("db_name", ""),
        )


@dataclass
class DBInfo:
    name: str
    table_ids: list[int] = field(default_factory=list)

    def to_json(self):
        return {"name": self.name, "table_ids": self.table_ids}

    @staticmethod
    def from_json(d):
        return DBInfo(d["name"], d["table_ids"])


class InfoSchema:
    """Immutable snapshot of the full schema at one version
    (ref: infoschema/infoschema.go)."""

    def __init__(self, version: int, dbs: dict[str, DBInfo], tables: dict[int, TableInfo]):
        self.version = version
        self.dbs = {k.lower(): v for k, v in dbs.items()}
        self.tables = tables
        self._by_name: dict[tuple[str, str], TableInfo] = {}
        for t in tables.values():
            self._by_name[(t.db_name.lower(), t.name.lower())] = t

    def db_names(self) -> list[str]:
        return sorted(self.dbs)

    def has_db(self, db: str) -> bool:
        return db.lower() in self.dbs

    def table(self, db: str, name: str) -> TableInfo:
        t = self._by_name.get((db.lower(), name.lower()))
        if t is None:
            if not self.has_db(db):
                raise UnknownDatabase(f"unknown database {db!r}")
            raise UnknownTable(f"table {db}.{name} doesn't exist")
        return t

    def table_by_id(self, tid: int) -> TableInfo | None:
        return self.tables.get(tid)

    def tables_in_db(self, db: str) -> list[TableInfo]:
        d = self.dbs.get(db.lower())
        if d is None:
            raise UnknownDatabase(f"unknown database {db!r}")
        return sorted((self.tables[t] for t in d.table_ids if t in self.tables), key=lambda t: t.name)

"""INFORMATION_SCHEMA memtables (ref: infoschema/tables.go memtable
framework + executor/infoschema_reader.go, slow_query.go,
metrics_reader.go — virtual tables materialized from in-memory state at
read time)."""

from __future__ import annotations

import datetime

from ..mysqltypes.datum import Datum
from ..mysqltypes.field_type import ft_double, ft_longlong, ft_varchar

# table → (column names, field types)
SCHEMAS: dict[str, tuple[list[str], list]] = {
    "tables": (
        ["TABLE_SCHEMA", "TABLE_NAME", "TABLE_ID", "TABLE_ROWS", "PK_IS_HANDLE"],
        [ft_varchar(64), ft_varchar(64), ft_longlong(), ft_longlong(), ft_longlong()],
    ),
    "columns": (
        ["TABLE_SCHEMA", "TABLE_NAME", "COLUMN_NAME", "ORDINAL_POSITION", "DATA_TYPE"],
        [ft_varchar(64), ft_varchar(64), ft_varchar(64), ft_longlong(), ft_varchar(32)],
    ),
    "slow_query": (
        ["TIME", "USER", "DB", "QUERY_TIME", "DIGEST", "SUCC", "QUERY"],
        [ft_varchar(32), ft_varchar(32), ft_varchar(64), ft_double(), ft_varchar(32), ft_longlong(), ft_varchar(512)],
    ),
    "statements_summary": (
        ["DIGEST", "EXEC_COUNT", "SUM_LATENCY", "MAX_LATENCY", "AVG_LATENCY", "ERRORS", "DIGEST_TEXT"],
        [ft_varchar(32), ft_longlong(), ft_double(), ft_double(), ft_double(), ft_longlong(), ft_varchar(256)],
    ),
    "metrics": (
        ["NAME", "LABELS", "VALUE"],
        [ft_varchar(64), ft_varchar(128), ft_double()],
    ),
    "tidb_indexes": (
        ["TABLE_SCHEMA", "TABLE_NAME", "KEY_NAME", "COLUMN_NAMES", "NON_UNIQUE", "STATE"],
        [ft_varchar(64), ft_varchar(64), ft_varchar(64), ft_varchar(256), ft_longlong(), ft_varchar(16)],
    ),
}


def rows_for(session, name: str) -> list[list[Datum]]:
    name = name.lower()
    if name == "tables":
        is_ = session.infoschema()
        out = []
        for t in sorted(is_.tables.values(), key=lambda x: (x.db_name, x.name)):
            st = session.store.stats.get(t.id)
            rows = st.row_count if st is not None else 0
            out.append([
                Datum.s(t.db_name), Datum.s(t.name), Datum.i(t.id),
                Datum.i(int(rows)), Datum.i(1 if t.pk_is_handle else 0),
            ])
        return out
    if name == "columns":
        is_ = session.infoschema()
        out = []
        for t in sorted(is_.tables.values(), key=lambda x: (x.db_name, x.name)):
            for c in t.visible_columns():
                out.append([
                    Datum.s(t.db_name), Datum.s(t.name), Datum.s(c.name),
                    Datum.i(c.offset + 1), Datum.s(c.ft.tp.name.lower()),
                ])
        return out
    if name == "slow_query":
        out = []
        for e in session.store.stmt_stats.slow:
            ts = datetime.datetime.fromtimestamp(e["time"]).strftime("%Y-%m-%d %H:%M:%S")
            out.append([
                Datum.s(ts), Datum.s(e["user"]), Datum.s(e["db"]),
                Datum.f(e["query_time_s"]), Datum.s(e["digest"]),
                Datum.i(1 if e["succ"] else 0), Datum.s(e["query"]),
            ])
        return out
    if name == "statements_summary":
        out = []
        for st in session.store.stmt_stats.summary.values():
            avg = st["sum_latency_s"] / st["exec_count"] if st["exec_count"] else 0.0
            out.append([
                Datum.s(st["digest"]), Datum.i(st["exec_count"]),
                Datum.f(st["sum_latency_s"]), Datum.f(st["max_latency_s"]),
                Datum.f(avg), Datum.i(st["errors"]), Datum.s(st["sample_sql"]),
            ])
        return out
    if name == "metrics":
        from ..utils.metrics import REGISTRY

        return [[Datum.s(n), Datum.s(l), Datum.f(v)] for n, l, v in REGISTRY.rows()]
    if name == "tidb_indexes":
        is_ = session.infoschema()
        out = []
        for t in sorted(is_.tables.values(), key=lambda x: (x.db_name, x.name)):
            for ix in t.indexes:
                cols = ",".join(t.columns[o].name for o in ix.col_offsets)
                out.append([
                    Datum.s(t.db_name), Datum.s(t.name), Datum.s(ix.name),
                    Datum.s(cols), Datum.i(0 if ix.unique else 1), Datum.s(ix.state),
                ])
        return out
    raise KeyError(name)

"""INFORMATION_SCHEMA memtables (ref: infoschema/tables.go memtable
framework + executor/infoschema_reader.go, slow_query.go,
metrics_reader.go — virtual tables materialized from in-memory state at
read time)."""

from __future__ import annotations

import datetime

from ..mysqltypes.datum import Datum
from ..mysqltypes.field_type import ft_double, ft_longlong, ft_varchar

# table → (column names, field types)
SCHEMAS: dict[str, tuple[list[str], list]] = {
    "tables": (
        ["TABLE_SCHEMA", "TABLE_NAME", "TABLE_ID", "TABLE_ROWS", "PK_IS_HANDLE"],
        [ft_varchar(64), ft_varchar(64), ft_longlong(), ft_longlong(), ft_longlong()],
    ),
    "columns": (
        ["TABLE_SCHEMA", "TABLE_NAME", "COLUMN_NAME", "ORDINAL_POSITION", "DATA_TYPE"],
        [ft_varchar(64), ft_varchar(64), ft_varchar(64), ft_longlong(), ft_varchar(32)],
    ),
    "slow_query": (
        ["TIME", "USER", "DB", "QUERY_TIME", "DIGEST", "SUCC", "QUERY",
         # cop-path exec details (PR 3): admission wait, launch batching,
         # retries/backoff, device compile + host<->device transfer;
         # (PR 4): peak tracked statement memory; (PR 18): the serving
         # replica of a follower-routed read and the commit's
         # replication-wait share (wal.fsync vs quorum.wait split)
         "SCHED_WAIT", "BATCH_OCCUPANCY", "RETRIES", "BACKOFF_MS",
         "COMPILE_MS", "TRANSFER_BYTES", "MEM_MAX", "REPLICA",
         "QUORUM_WAIT_MS"],
        [ft_varchar(32), ft_varchar(32), ft_varchar(64), ft_double(), ft_varchar(32), ft_longlong(), ft_varchar(512),
         ft_double(), ft_longlong(), ft_longlong(), ft_double(),
         ft_double(), ft_longlong(), ft_longlong(), ft_varchar(64),
         ft_double()],
    ),
    "statements_summary": (
        ["DIGEST", "EXEC_COUNT", "SUM_LATENCY", "MAX_LATENCY", "AVG_LATENCY", "ERRORS", "DIGEST_TEXT",
         "SUM_SCHED_WAIT", "MAX_BATCH_OCCUPANCY", "SUM_RETRIES",
         "SUM_BACKOFF_MS", "SUM_COMPILE_MS", "SUM_TRANSFER_BYTES", "MAX_MEM",
         "SUM_QUORUM_WAIT_MS", "REPLICA_READS"],
        [ft_varchar(32), ft_longlong(), ft_double(), ft_double(), ft_double(), ft_longlong(), ft_varchar(256),
         ft_double(), ft_longlong(), ft_longlong(),
         ft_double(), ft_double(), ft_longlong(), ft_longlong(),
         ft_double(), ft_longlong()],
    ),
    # --- PR 4: runaway control + server memory arbitration ----------------
    "runaway_watches": (
        # live TTL watch list (sched/runaway.py): digests rejected (KILL)
        # or demoted (COOLDOWN) at admission until the watch expires
        ["RESOURCE_GROUP", "SQL_DIGEST", "ACTION", "REASON", "START_TIME", "REMAIN_S"],
        [ft_varchar(64), ft_varchar(32), ft_varchar(16), ft_varchar(32),
         ft_varchar(32), ft_double()],
    ),
    "runaway_events": (
        # every QUERY_LIMIT action fired (incl. watch-list hits)
        ["TIME", "RESOURCE_GROUP", "SQL_DIGEST", "RULE", "ACTION", "SAMPLE_SQL"],
        [ft_varchar(32), ft_varchar(64), ft_varchar(32), ft_varchar(32),
         ft_varchar(16), ft_varchar(256)],
    ),
    "memory_usage": (
        # live tracker tree (utils/memory): the server root + every
        # attached statement tracker
        ["SCOPE", "LABEL", "CONSUMED", "MAX_CONSUMED", "QUOTA", "SQL"],
        [ft_varchar(16), ft_varchar(64), ft_longlong(), ft_longlong(),
         ft_longlong(), ft_varchar(256)],
    ),
    "memory_usage_ops_history": (
        # arbiter actions: degrade / recover / kill with the victim
        ["TIME", "OP", "CONSUMED", "LIMIT", "VICTIM", "DETAILS"],
        [ft_varchar(32), ft_varchar(16), ft_longlong(), ft_longlong(),
         ft_varchar(64), ft_varchar(256)],
    ),
    "tidb_trace": (
        # flattened span rows of the last-N statement traces
        # (utils/tracing.TraceRing; one row per span, root included);
        # TXN_TRACE_ID links statements of one BEGIN…COMMIT (PR 5)
        ["TRACE_ID", "SESSION_ID", "SPAN_ID", "PARENT_SPAN_ID", "OPERATION",
         "START_MS", "DURATION_MS", "TAGS", "SQL", "TXN_TRACE_ID"],
        [ft_varchar(32), ft_longlong(), ft_longlong(), ft_longlong(), ft_varchar(128),
         ft_double(), ft_double(), ft_varchar(256), ft_varchar(512), ft_varchar(32)],
    ),
    "tidb_timeline": (
        # flattened device-timeline events (utils/timeline.TimelineRing):
        # real-timestamped engine-boundary + launch-lifecycle events,
        # TS_US/DUR_US in µs relative to the ring epoch (the same numbers
        # /debug/timeline exports for Perfetto)
        ["LANE", "TRACK", "NAME", "CATEGORY", "TS_US", "DUR_US", "ARGS"],
        [ft_varchar(16), ft_varchar(64), ft_varchar(64), ft_varchar(32),
         ft_double(), ft_double(), ft_varchar(512)],
    ),
    "metrics": (
        ["NAME", "LABELS", "VALUE"],
        [ft_varchar(64), ft_varchar(128), ft_double()],
    ),
    "tidb_indexes": (
        ["TABLE_SCHEMA", "TABLE_NAME", "KEY_NAME", "COLUMN_NAMES", "NON_UNIQUE", "STATE"],
        [ft_varchar(64), ft_varchar(64), ft_varchar(64), ft_varchar(256), ft_longlong(), ft_varchar(16)],
    ),
    "processlist": (
        ["ID", "USER", "HOST", "DB", "COMMAND", "TIME", "STATE", "INFO"],
        [ft_longlong(), ft_varchar(32), ft_varchar(64), ft_varchar(64),
         ft_varchar(16), ft_longlong(), ft_varchar(16), ft_varchar(512)],
    ),
    "tidb_regions": (
        ["REGION_ID", "START_KEY", "END_KEY", "TABLE_ID", "IS_INDEX"],
        [ft_longlong(), ft_varchar(64), ft_varchar(64), ft_longlong(), ft_longlong()],
    ),
    "metrics_summary": (
        ["METRICS_NAME", "INSTANCES", "SUM_VALUE", "AVG_VALUE", "MIN_VALUE", "MAX_VALUE", "RATE_PER_SEC"],
        [ft_varchar(64), ft_longlong(), ft_double(), ft_double(), ft_double(), ft_double(), ft_double()],
    ),
    "inspection_result": (
        ["RULE", "ITEM", "TYPE", "VALUE", "REFERENCE", "SEVERITY", "DETAILS"],
        [ft_varchar(32), ft_varchar(64), ft_varchar(16), ft_varchar(64),
         ft_varchar(64), ft_varchar(16), ft_varchar(256)],
    ),
    "cluster_info": (
        ["TYPE", "INSTANCE", "VERSION", "GIT_HASH", "START_TIME", "UPTIME"],
        [ft_varchar(16), ft_varchar(64), ft_varchar(32), ft_varchar(40),
         ft_varchar(32), ft_varchar(32)],
    ),
    # --- PR 18: fleet observability plane ---------------------------------
    "cluster_replication": (
        # one row for this store plus one per replication link
        # (ReplicaSet.link_states): transport, durable/applied horizons,
        # apply staleness (wall clock minus the applied watermark — the
        # router's follower-eligibility measure), reconnect count, and
        # the typed broken reason
        ["NODE", "ROLE", "TRANSPORT", "EPOCH", "DURABLE_FRAMES",
         "APPLIED_TS", "LAG_MS", "RECONNECTS", "STATE", "BROKEN_REASON"],
        [ft_varchar(64), ft_varchar(16), ft_varchar(16), ft_longlong(),
         ft_longlong(), ft_longlong(), ft_double(), ft_longlong(),
         ft_varchar(16), ft_varchar(256)],
    ),
    "cluster_metrics": (
        # the METRICS memtable federated over every fleet member via the
        # ship status RPC; a dead member contributes one ERROR row
        # (partial results inside the timeout bound, never a hang)
        ["NODE", "NAME", "LABELS", "VALUE", "ERROR"],
        [ft_varchar(64), ft_varchar(64), ft_varchar(128), ft_double(),
         ft_varchar(256)],
    ),
    "cluster_statements_summary": (
        # STATEMENTS_SUMMARY federated the same way (per-node digests:
        # follower-served statements execute — and are recorded — on the
        # replica, so fleet-wide analysis needs the fan-out)
        ["NODE", "DIGEST", "EXEC_COUNT", "SUM_LATENCY", "ERRORS",
         "SAMPLE_SQL", "ERROR"],
        [ft_varchar(64), ft_varchar(32), ft_longlong(), ft_double(),
         ft_longlong(), ft_varchar(256), ft_varchar(256)],
    ),
    "views": (
        ["TABLE_SCHEMA", "TABLE_NAME", "VIEW_DEFINITION"],
        [ft_varchar(64), ft_varchar(64), ft_varchar(1024)],
    ),
    "deadlocks": (
        ["DEADLOCK_ID", "OCCUR_TIME", "TRY_LOCK_TRX_ID", "TRX_HOLDING_LOCK"],
        [ft_longlong(), ft_varchar(32), ft_longlong(), ft_longlong()],
    ),
    "top_sql": (
        ["SQL_DIGEST", "EXEC_COUNT", "SUM_CPU_TIME", "AVG_CPU_TIME", "SAMPLE_SQL"],
        [ft_varchar(32), ft_longlong(), ft_double(), ft_double(), ft_varchar(256)],
    ),
    "compaction": (
        # delta-main compactor state per table (PR 16, storage/compact.py):
        # fold/merge round counts, fold output totals, the table's live
        # run count and current mutable-delta size (w-CF entries)
        ["TABLE_ID", "FOLDS", "MERGES", "ROWS_FOLDED", "VERSIONS_RECLAIMED",
         "RUNS", "DELTA_KEYS"],
        [ft_longlong(), ft_longlong(), ft_longlong(), ft_longlong(),
         ft_longlong(), ft_longlong(), ft_longlong()],
    ),
    "tidb_profile_cpu": (
        ["FUNCTION", "PERCENT_ABS", "PERCENT_PARENT", "SAMPLES", "DEPTH"],
        [ft_varchar(512), ft_double(), ft_double(), ft_longlong(), ft_longlong()],
    ),
    # --- PR 20: workload-history plane -------------------------------------
    "tidb_workload_profile": (
        # KIND=profile: one row per (statement digest, row-count bucket)
        # the workload profile observed (utils/workload.py) — the exact
        # evidence the auto-engine router cites: EWMA per-task walls for
        # both engines, compile/wire/wait costs, typed declines, and how
        # many routing decisions exploited this entry. KIND=resident: one
        # row per device-path cache pool (tile | build | batch) with its
        # live byte footprint (the same figures the
        # tidb_tpu_resident_bytes gauge exports); profile columns read 0.
        ["KIND", "DIGEST", "ROW_BUCKET", "EXECS", "DEVICE_RUNS", "HOST_RUNS",
         "DEVICE_TASK_MS", "HOST_TASK_MS", "COMPILE_MS", "WIRE_BYTES",
         "SCHED_WAIT_MS", "DECLINES", "DECISIONS", "BYTES", "TABLES"],
        [ft_varchar(16), ft_varchar(32), ft_longlong(), ft_longlong(),
         ft_longlong(), ft_longlong(), ft_double(), ft_double(), ft_double(),
         ft_longlong(), ft_double(), ft_longlong(), ft_longlong(),
         ft_longlong(), ft_varchar(64)],
    ),
}


def rows_for(session, name: str) -> list[list[Datum]]:
    name = name.lower()
    from ..utils import sem

    if not sem.check_table(name):
        from ..errors import TiDBError

        raise TiDBError(
            f"information_schema.{name} is not visible when security enhanced mode is enabled"
        )
    if name == "tables":
        is_ = session.infoschema()
        out = []
        for t in sorted(is_.tables.values(), key=lambda x: (x.db_name, x.name)):
            st = session.store.stats.get(t.id)
            rows = st.row_count if st is not None else 0
            out.append([
                Datum.s(t.db_name), Datum.s(t.name), Datum.i(t.id),
                Datum.i(int(rows)), Datum.i(1 if t.pk_is_handle else 0),
            ])
        for (d, n) in sorted(is_.views):
            out.append([Datum.s(d), Datum.s(n), Datum.i(-1), Datum.i(0), Datum.i(0)])
        return out
    if name == "columns":
        is_ = session.infoschema()
        out = []
        for t in sorted(is_.tables.values(), key=lambda x: (x.db_name, x.name)):
            for c in t.visible_columns():
                out.append([
                    Datum.s(t.db_name), Datum.s(t.name), Datum.s(c.name),
                    Datum.i(c.offset + 1), Datum.s(c.ft.tp.name.lower()),
                ])
        return out
    if name == "slow_query":
        out = []
        for e in session.store.stmt_stats.slow:
            ts = datetime.datetime.fromtimestamp(e["time"]).strftime("%Y-%m-%d %H:%M:%S")
            out.append([
                Datum.s(ts), Datum.s(e["user"]), Datum.s(e["db"]),
                Datum.f(e["query_time_s"]), Datum.s(e["digest"]),
                Datum.i(1 if e["succ"] else 0), Datum.s(e["query"]),
                Datum.f(e.get("sched_wait_ms", 0.0) / 1000.0),
                Datum.i(int(e.get("batch_occupancy", 0))),
                Datum.i(int(e.get("retries", 0))),
                Datum.f(e.get("backoff_ms", 0.0)),
                Datum.f(e.get("compile_ms", 0.0)),
                Datum.i(int(e.get("transfer_bytes", 0))),
                Datum.i(int(e.get("mem_bytes", 0))),
                Datum.s(e.get("replica", "")),
                Datum.f(e.get("quorum_wait_ms", 0.0)),
            ])
        return out
    if name == "statements_summary":
        out = []
        ss = session.store.stmt_stats
        with ss._lock:
            snap = [dict(st) for st in ss.summary.values()]
        for st in snap:
            avg = st["sum_latency_s"] / st["exec_count"] if st["exec_count"] else 0.0
            out.append([
                Datum.s(st["digest"]), Datum.i(st["exec_count"]),
                Datum.f(st["sum_latency_s"]), Datum.f(st["max_latency_s"]),
                Datum.f(avg), Datum.i(st["errors"]), Datum.s(st["sample_sql"]),
                Datum.f(st.get("sum_sched_wait_ms", 0.0) / 1000.0),
                Datum.i(int(st.get("max_batch_occupancy", 0))),
                Datum.i(int(st.get("sum_retries", 0))),
                Datum.f(st.get("sum_backoff_ms", 0.0)),
                Datum.f(st.get("sum_compile_ms", 0.0)),
                Datum.i(int(st.get("sum_transfer_bytes", 0))),
                Datum.i(int(st.get("max_mem_bytes", 0))),
                Datum.f(st.get("sum_quorum_wait_ms", 0.0)),
                Datum.i(int(st.get("replica_reads", 0))),
            ])
        return out
    if name == "tidb_trace":
        out = []
        for tr in session.store.trace_ring.snapshot():
            for sp in tr["spans"]:
                tags = " ".join(f"{k}={v}" for k, v in sp["tags"].items())
                out.append([
                    Datum.s(tr["trace_id"]), Datum.i(tr["session_id"]),
                    Datum.i(sp["span_id"]), Datum.i(sp["parent_id"]),
                    Datum.s(sp["operation"]),
                    Datum.f(sp["start_ms"]), Datum.f(sp["duration_ms"]),
                    Datum.s(tags[:256]), Datum.s(tr["sql"][:512]),
                    Datum.s(tr.get("txn_trace_id") or ""),
                ])
        return out
    if name == "tidb_timeline":
        from ..utils.timeline import _PID_NAMES

        tl = session.store.timeline
        out = []
        for ev in tl.snapshot():
            args = " ".join(f"{k}={v}" for k, v in ev.args.items())
            out.append([
                Datum.s(_PID_NAMES.get(ev.pid, str(ev.pid))), Datum.s(ev.lane),
                Datum.s(ev.name), Datum.s(ev.cat),
                Datum.f(round((ev.t_start_ns - tl.epoch_ns) / 1e3, 3)),
                Datum.f(round(max(ev.t_end_ns - ev.t_start_ns, 0) / 1e3, 3)),
                Datum.s(args[:512]),
            ])
        return out
    if name == "metrics":
        from ..utils.metrics import REGISTRY

        return [[Datum.s(n), Datum.s(l), Datum.f(v)] for n, l, v in REGISTRY.rows()]
    if name == "runaway_watches":
        rm = session.store.sched.runaway
        out = []
        for digest, w, remain in sorted(rm.watches_snapshot(), key=lambda x: x[0]):
            ts = datetime.datetime.fromtimestamp(w.start).strftime("%Y-%m-%d %H:%M:%S")
            out.append([
                Datum.s(w.group), Datum.s(digest), Datum.s(w.action),
                Datum.s(w.reason), Datum.s(ts), Datum.f(round(remain, 3)),
            ])
        return out
    if name == "runaway_events":
        rm = session.store.sched.runaway
        out = []
        for e in list(rm.events):
            ts = datetime.datetime.fromtimestamp(e["time"]).strftime("%Y-%m-%d %H:%M:%S")
            out.append([
                Datum.s(ts), Datum.s(e["group"]), Datum.s(e["digest"]),
                Datum.s(e["rule"]), Datum.s(e["action"]), Datum.s(e["sql"]),
            ])
        return out
    if name == "memory_usage":
        mem = session.store.mem
        out = [[
            Datum.s("server"), Datum.s(mem.label), Datum.i(mem.consumed),
            Datum.i(mem.max_consumed), Datum.i(mem.limit), Datum.s(""),
        ]]
        for t in sorted(mem.statements(), key=lambda x: -x.consumed):
            out.append([
                Datum.s("statement"), Datum.s(t.label), Datum.i(t.consumed),
                Datum.i(t.max_consumed), Datum.i(t.quota), Datum.s(t.sql),
            ])
        return out
    if name == "memory_usage_ops_history":
        mem = session.store.mem
        out = []
        for e in list(mem.events):
            ts = datetime.datetime.fromtimestamp(e["time"]).strftime("%Y-%m-%d %H:%M:%S")
            out.append([
                Datum.s(ts), Datum.s(e["op"]), Datum.i(int(e["consumed"])),
                Datum.i(int(e["limit"])), Datum.s(str(e.get("victim", ""))),
                Datum.s(str(e.get("victim_sql") or e.get("detail", ""))[:256]),
            ])
        return out
    if name == "processlist":
        import time as _time

        now = _time.time()
        out = []
        for cid, info in session.store.process_snapshot():
            out.append([
                Datum.i(cid), Datum.s(info["user"]), Datum.s("127.0.0.1"),
                Datum.s(info["db"]), Datum.s("Query" if info["sql"] else "Sleep"),
                Datum.i(int(now - info["start"])), Datum.s("autocommit"),
                Datum.s(info["sql"]) if info["sql"] else Datum.null(),
            ])
        return out
    if name == "tidb_regions":
        from ..codec import tablecodec

        out = []
        for r in session.store.regions.regions:
            tid = -1
            is_index = 0
            if len(r.start) >= 9 and r.start[:1] == b"t":
                try:
                    tid = tablecodec.decode_table_id(r.start)
                except Exception:  # noqa: BLE001 — raw boundary keys
                    tid = -1
                # auto-split keys can land inside the index keyspace
                is_index = 1 if r.start[9:11] == b"_i" else 0
            out.append([
                Datum.i(r.id), Datum.s(r.start.hex()), Datum.s(r.end.hex()),
                Datum.i(tid), Datum.i(is_index),
            ])
        return out
    if name == "metrics_summary":
        # per-base-metric aggregates over the label instances, plus the
        # windowed per-second RATE of the summed series — the PromQL
        # range-query analog (ref: infoschema/metric_table_def.go →
        # utils.metrics.MetricsHistory)
        from ..utils.metrics import HISTORY, REGISTRY

        agg: dict[str, list[float]] = {}
        for n, _l, v in REGISTRY.rows():
            agg.setdefault(n, []).append(float(v))
        rates = HISTORY.base_rates()
        out = []
        for n in sorted(agg):
            vs = agg[n]
            out.append([
                Datum.s(n), Datum.i(len(vs)), Datum.f(sum(vs)),
                Datum.f(sum(vs) / len(vs)), Datum.f(min(vs)), Datum.f(max(vs)),
                Datum.f(rates.get(n, 0.0)),
            ])
        return out
    if name == "views":
        return [
            [Datum.s(d), Datum.s(n), Datum.s(v["sql"])]
            for (d, n), v in sorted(session.infoschema().views.items())
        ]
    if name == "deadlocks":
        out = []
        det = session.store.detector
        with det._lock:
            hist = list(det.history)
        for d in hist:
            ts = datetime.datetime.fromtimestamp(d["time"]).strftime("%Y-%m-%d %H:%M:%S")
            out.append([
                Datum.i(d["id"]), Datum.s(ts),
                Datum.i(d["try_lock_trx"]), Datum.i(d["holding_trx"]),
            ])
        return out
    if name == "top_sql":
        ss = session.store.stmt_stats
        with ss._lock:  # concurrent record() must not mutate mid-sort
            snap = [dict(st) for st in ss.summary.values()]
        entries = sorted(
            snap, key=lambda st: st.get("sum_cpu_s", 0.0), reverse=True,
        )[:32]
        out = []
        for st in entries:
            cpu = st.get("sum_cpu_s", 0.0)
            avg = cpu / st["exec_count"] if st["exec_count"] else 0.0
            out.append([
                Datum.s(st["digest"]), Datum.i(st["exec_count"]),
                Datum.f(cpu), Datum.f(avg), Datum.s(st["sample_sql"]),
            ])
        return out
    if name == "compaction":
        from ..storage.compact import compaction_rows

        return [[Datum.i(int(v)) for v in row] for row in compaction_rows(session)]
    if name == "tidb_profile_cpu":
        return _cpu_profile_rows(session)
    if name == "tidb_workload_profile":
        return _workload_profile_rows(session)
    if name == "inspection_result":
        return _inspection_rows(session)
    if name == "cluster_replication":
        return _cluster_replication_rows(session)
    if name == "cluster_metrics":
        return _cluster_fanout_rows(session, "metrics")
    if name == "cluster_statements_summary":
        return _cluster_fanout_rows(session, "statements")
    if name == "cluster_info":
        import time as _time

        start = getattr(session.store, "start_time", None) or _time.time()
        up = int(_time.time() - start)
        started = datetime.datetime.fromtimestamp(start).strftime("%Y-%m-%d %H:%M:%S")
        return [[
            Datum.s("tidb"), Datum.s("127.0.0.1:4000"), Datum.s("8.0.11-tidb-tpu"),
            Datum.s("tpu-native"), Datum.s(started), Datum.s(f"{up}s"),
        ]]
    if name == "tidb_indexes":
        is_ = session.infoschema()
        out = []
        for t in sorted(is_.tables.values(), key=lambda x: (x.db_name, x.name)):
            for ix in t.indexes:
                cols = ",".join(t.columns[o].name for o in ix.col_offsets)
                out.append([
                    Datum.s(t.db_name), Datum.s(t.name), Datum.s(ix.name),
                    Datum.s(cols), Datum.i(0 if ix.unique else 1), Datum.s(ix.state),
                ])
        return out
    raise KeyError(name)


def _workload_profile_rows(session) -> list:
    """Profile rows (MRU first) from the store's workload-history plane,
    then one residency row per device-path cache pool. Reading the table
    is also the `tidb_tpu_resident_bytes` gauge's refresh point: byte
    ledgers live inside cache locks, so the gauge samples on pull (a
    metrics scrape after a memtable read sees the same figures the SQL
    row reported) rather than on every cache mutation."""
    from ..utils import metrics as M
    from ..copr.tilecache import batch_nbytes

    store = session.store
    out = []
    for e in store.workload.snapshot():
        out.append([
            Datum.s("profile"), Datum.s(e["digest"]), Datum.i(e["bucket"]),
            Datum.i(e["execs"]), Datum.i(e["device_runs"]),
            Datum.i(e["host_runs"]), Datum.f(e["device_task_ms"]),
            Datum.f(e["host_task_ms"]), Datum.f(e["compile_ms"]),
            Datum.i(int(e["wire_bytes"])), Datum.f(e["sched_wait_ms"]),
            Datum.i(e["declines"]), Datum.i(e["decisions"]), Datum.i(0),
            Datum.s(",".join(str(t) for t in sorted(e["tables"]))),
        ])
    # residency: tile = host-lane bytes of cached column batches, batch =
    # the real (compressed) wire bytes of their device mirrors, build =
    # the build-side cache's byte ledger (getattr — reading a memtable
    # must not instantiate a cache the workload never touched)
    tiles = session.cop.tiles
    tile_b = 0.0
    batch_b = 0.0
    with tiles._lock:
        for b in tiles._cache.values():
            tile_b += batch_nbytes(b)
            mirrors = getattr(b, "_mirrors", None)
            if mirrors is not None:
                batch_b += sum(
                    float(getattr(m, "wire_nbytes", 0)) for m in mirrors.values()
                )
    bc = getattr(store, "_build_cache", None)
    build_b = float(bc.nbytes) if bc is not None else 0.0
    for kind, nbytes in (("tile", tile_b), ("build", build_b), ("batch", batch_b)):
        M.TPU_RESIDENT_BYTES.set(nbytes, kind=kind)
        out.append([
            Datum.s("resident"), Datum.s(kind), Datum.i(0), Datum.i(0),
            Datum.i(0), Datum.i(0), Datum.f(0.0), Datum.f(0.0), Datum.f(0.0),
            Datum.i(0), Datum.f(0.0), Datum.i(0), Datum.i(0),
            Datum.i(int(nbytes)), Datum.s(""),
        ])
    return out


def _cluster_replication_rows(session) -> list:
    """One row for this store plus one per ship link — the fleet
    topology as SQL (ref: the reference's TIKV_STORE_STATUS /
    cluster-memtable shape over PD state; here the ReplicaSet IS the
    topology authority)."""
    store = session.store
    out = [[
        Datum.s("self"),
        Datum.s("standby" if store.standby else "primary"),
        Datum.s("-"),
        Datum.i(int(getattr(store, "_wal_epoch", 0) or 0)),
        Datum.i(int(getattr(store, "_applied_frames", 0))),
        Datum.i(int(store.applied_ts)),
        Datum.f(0.0), Datum.i(0), Datum.s("live"), Datum.s(""),
    ]]
    sh = getattr(store, "_shipper", None)
    if sh is not None:
        for s in sh.link_states():
            out.append([
                Datum.s(s["name"]), Datum.s("standby"),
                Datum.s(s.get("transport", "?")),
                Datum.i(-1),  # a link doesn't know the far side's epoch
                Datum.i(int(s["durable_gseq"] - s["base_gseq"])),
                Datum.i(int(s["applied_ts"])),
                Datum.f(float(s.get("lag_ms", 0.0))),
                Datum.i(int(s["reconnects"])),
                Datum.s("broken" if s["broken"] else "live"),
                Datum.s(s.get("reason", "")[:256]),
            ])
    return out


def _cluster_fanout_rows(session, kind: str) -> list:
    """CLUSTER_METRICS / CLUSTER_STATEMENTS_SUMMARY federation: the
    primary answers directly, in-process members are read directly,
    socket members over the ship status RPC — each bounded by the
    per-member timeout, so a dead node yields one row with ERROR set
    (partial results, never a hang)."""
    sh = getattr(session.store, "_shipper", None)
    if sh is None:
        from ..storage.ship import node_status

        statuses = [node_status(session.store, name="primary")]
    else:
        statuses = sh.fleet_statuses()
    rows: list = []
    for st in statuses:
        node = str(st.get("name", "?"))
        err = str(st.get("error", ""))
        if err:
            if kind == "metrics":
                rows.append([Datum.s(node), Datum.null(), Datum.null(),
                             Datum.null(), Datum.s(err[:256])])
            else:
                rows.append([Datum.s(node), Datum.null(), Datum.null(),
                             Datum.null(), Datum.null(), Datum.null(),
                             Datum.s(err[:256])])
            continue
        if kind == "metrics":
            for n, lbl, v in st.get("metrics", []):
                rows.append([Datum.s(node), Datum.s(n), Datum.s(lbl),
                             Datum.f(float(v)), Datum.s("")])
        else:
            for e in st.get("statements", []):
                rows.append([
                    Datum.s(node), Datum.s(str(e["digest"])),
                    Datum.i(int(e["exec_count"])),
                    Datum.f(float(e["sum_latency_s"])),
                    Datum.i(int(e["errors"])),
                    Datum.s(str(e["sample_sql"])), Datum.s(""),
                ])
    return rows


def _inspection_rows(session) -> list:
    """Self-diagnosis rules over internal counters (ref:
    executor/inspection_result.go — the reference fans out over cluster
    metrics; single process, so the rules read in-memory state)."""
    rows: list = []

    def add(rule, item, value, reference, severity, details):
        rows.append([
            Datum.s(rule), Datum.s(item), Datum.s("tidb"), Datum.s(str(value)),
            Datum.s(reference), Datum.s(severity), Datum.s(details),
        ])

    # every device path's declines count, not just cop lowering — read
    # the per-reason accounting (NOT the process-global registry: two
    # stores in one process must not see each other's fallbacks, the
    # same scoping rule the breaker series follows). The labeled
    # tidb_tpu_fallback_total{path,reason} series carries the
    # process-wide per-reason split for /metrics consumers.
    cop = session.cop
    fallbacks = getattr(cop._tpu, "fallbacks", 0) if cop._tpu else 0
    mpp_eng = getattr(cop, "_mpp", None)
    if mpp_eng is not None:
        fallbacks += mpp_eng.fallbacks
    fallbacks += int(cop.stats.get("window_fallbacks", 0))
    if fallbacks:
        add("engine", "tpu-fallback-count", fallbacks, "0", "warning",
            "statements fell back from a device path (cop/mpp/window) to "
            "the host engine — reason split: tidb_tpu_fallback_total{path,reason}")
    hits = getattr(session, "plan_cache_hits", 0)
    size = len(getattr(session, "_plan_cache", ()))
    add("plan-cache", "entries", size, "-", "info", f"hits this session: {hits}")
    slow = len(session.store.stmt_stats.slow)
    if slow:
        add("slow-query", "count", slow, "0", "warning",
            "statements over the slow-log threshold (information_schema.slow_query)")
    errs = sum(st["errors"] for st in session.store.stmt_stats.summary.values())
    if errs:
        add("statement", "error-count", errs, "0", "warning",
            "failed statements recorded in statements_summary")
    pending = [
        t.name for t in session.infoschema().tables.values()
        if session.store.stats.needs_analyze(t.id)
    ]
    if pending:
        add("stats", "auto-analyze-pending", len(pending), "0", "info",
            "tables past the modify ratio: " + ",".join(sorted(pending)[:8]))
    nregions = len(session.store.regions.regions)
    add("region", "count", nregions, "-", "info", "regions in the keyspace map")
    # --- fleet SLO rules (PR 18): read the lag monitor's inputs ------------
    sh = getattr(session.store, "_shipper", None)
    if sh is not None:
        states = sh.link_states()
        max_lag = float(
            session.store.global_vars.get("tidb_replica_read_max_lag_ms", 5000)
            or 0
        )
        live = 0
        for s in states:
            if s["broken"]:
                add("replication", f"broken-link:{s['name']}", "broken",
                    "live", "critical",
                    f"ship link is down ({s.get('reason', '')[:180]}); "
                    f"reconnects={s['reconnects']}")
                continue
            live += 1
            if s.get("lag_ms", 0.0) > max_lag:
                add("replication", f"lagging-replica:{s['name']}",
                    f"{s['lag_ms']:.0f}ms", f"<={max_lag:.0f}ms", "warning",
                    "apply lag exceeds tidb_replica_read_max_lag_ms — "
                    "follower reads fall back to the primary "
                    "(tidb_replica_lag_seconds)")
        n = len(states)
        need = (n + 1) // 2
        if n and live == need:
            # one more loss and QUORUM commits raise 8150: surface the
            # at-risk state BEFORE it becomes an outage
            add("replication", "quorum-at-risk", f"{live}/{n} live",
                f">{need} live", "warning",
                "live links equal the quorum minimum ceil(N/2) — a single "
                "further loss makes semi-sync QUORUM commits unreachable")
    return rows


def _cpu_profile_rows(session) -> list[list[Datum]]:
    """pprof-as-SQL (ref: util/profile/profile.go + infoschema
    TIDB_PROFILE_CPU): statistically sample every server thread's stack
    for a short window, aggregate into a call TREE, and render it as
    depth-indented rows with absolute and per-parent percentages — the
    reference's flamegraph table, over Python frames instead of Go pprof.
    """
    import sys
    import threading
    import time as _time

    me = threading.get_ident()
    duration_s = 0.2
    interval_s = 0.005
    counts: dict[tuple, int] = {}
    total = 0
    deadline = _time.time() + duration_s
    while _time.time() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # don't profile the profiler
            stack = []
            f = frame
            while f is not None and len(stack) < 48:
                co = f.f_code
                stack.append(f"{co.co_name} ({co.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
                f = f.f_back
            stack.reverse()
            prefix: tuple = ()
            for fn in stack:  # one incremental tuple per depth
                prefix = prefix + (fn,)
                counts[prefix] = counts.get(prefix, 0) + 1
            total += 1
        _time.sleep(interval_s)

    if total == 0:
        return [[Datum.s("root"), Datum.f(100.0), Datum.f(100.0), Datum.i(0), Datum.i(0)]]
    out = [[Datum.s("root"), Datum.f(100.0), Datum.f(100.0), Datum.i(total), Datum.i(0)]]
    # depth-first over prefixes, children by sample count (profile tree)
    tops = sorted({k for k in counts if len(k) == 1}, key=lambda k: -counts[k])

    def emit(prefix, parent_samples):
        n = counts[prefix]
        if n * 100.0 / total < 0.5 and len(prefix) > 1:
            return  # prune the noise floor like the reference's tree view
        name = "  " * len(prefix) + ("├─ " if len(prefix) > 1 else "") + prefix[-1]
        out.append([
            Datum.s(name[:512]), Datum.f(round(n * 100.0 / total, 2)),
            Datum.f(round(n * 100.0 / max(parent_samples, 1), 2)),
            Datum.i(n), Datum.i(len(prefix)),
        ])
        kids = sorted(
            (k for k in counts if len(k) == len(prefix) + 1 and k[:-1] == prefix),
            key=lambda k: -counts[k],
        )
        for k in kids:
            emit(k, n)

    for t in tops:
        emit(t, total)
    return out

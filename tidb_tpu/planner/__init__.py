from .plans import (
    LogicalPlan,
    DataSource,
    Selection,
    Projection,
    Aggregation,
    Join,
    Sort,
    Limit,
    Dual,
    SetOp,
    PlanCol,
)
from .builder import PlanBuilder
from .optimizer import optimize

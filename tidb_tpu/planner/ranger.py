"""Condition → key-range derivation (ref: util/ranger — detacher.go:736
DetachCondAndBuildRangeForIndex, ranger.go:328 BuildTableRange; fresh
compact implementation).

Given the pushed-down conjuncts of a DataSource and an index's column
offsets, detach the prefix of conditions that can be turned into
memcomparable key ranges:

  * an equality / IN chain on a prefix of the index columns, then
  * at most one range column with </<=/>/>= bounds.

Everything not consumed stays as a filter. Constants are converted to the
column's value domain only when the conversion is exact — lossy matches
(e.g. `int_col = 1.5`) are left as filters so semantics never change.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..codec.key import encode_datum_key
from ..codec import tablecodec
from ..expr.expression import Column as ECol, Constant, Expression, ScalarFunc
from ..mysqltypes.coretime import parse_datetime
from ..mysqltypes.datum import Datum, K_INT, K_UINT, K_FLOAT, K_DEC, K_STR, K_BYTES, K_TIME, K_DUR
from ..mysqltypes.field_type import FieldType

# cap on the cartesian product of IN-list point ranges (ref: ranger's
# range-building memory cap idea)
MAX_POINT_RANGES = 128

_REVERSE = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}


@dataclass
class ColAccess:
    """Simple conditions on one column, keyed for range building."""

    eq: list[Datum] = field(default_factory=list)  # values from = / IN
    eq_seen: bool = False  # an eq/IN cond was collected (empty eq ≠ unset)
    lo: tuple[Datum, bool] | None = None  # (bound, inclusive)
    hi: tuple[Datum, bool] | None = None
    conds: list[Expression] = field(default_factory=list)  # consumed conds

    def finalize(self) -> None:
        """Intersect eq points with any range bounds so the point ranges
        enforce EVERY consumed condition (mixed `a = 1 AND a > 5` must
        yield the empty set, not silently drop the bound)."""
        if not self.eq_seen:
            return
        pts = self.eq
        if self.lo is not None:
            v, incl = self.lo
            pts = [d for d in pts if (_cmp_datum(d, v) > 0 or (incl and _cmp_datum(d, v) == 0))]
        if self.hi is not None:
            v, incl = self.hi
            pts = [d for d in pts if (_cmp_datum(d, v) < 0 or (incl and _cmp_datum(d, v) == 0))]
        self.eq = pts
        self.lo = self.hi = None


def const_to_col_datum(d: Datum, ft: FieldType) -> Datum | None:
    """Convert a constant datum into the column's stored-key domain,
    returning None unless the conversion is exact (order-preserving and
    roundtrippable) — the gate that keeps range pruning semantics-safe."""
    if d.is_null:
        return None  # NULL never matches =/</> — handled by caller
    k = d.kind
    try:
        if ft.is_time():
            if k == K_TIME:
                return d
            if k in (K_STR, K_BYTES):
                s = d.val if isinstance(d.val, str) else d.val.decode("utf8", "replace")
                p = parse_datetime(s)
                return Datum.t(p) if p is not None else None
            return None
        if ft.is_int():
            # unsigned columns store 0x04 UINT-flag keys (encode_uint);
            # emitting a signed 0x03 datum here would build a key range
            # that can never match a stored entry
            def _fit(v: int) -> Datum | None:
                if ft.is_unsigned:
                    return Datum.u(v) if 0 <= v < (1 << 64) else None
                return Datum.i(v) if -(1 << 63) <= v < (1 << 63) else None

            if k in (K_INT, K_UINT):
                return _fit(int(d.val))
            if k == K_FLOAT:
                return _fit(int(d.val)) if float(d.val).is_integer() else None
            if k == K_DEC:
                dec = d.to_dec()
                if dec.scale == 0:
                    return _fit(dec.value)
                p = 10 ** dec.scale
                return _fit(dec.value // p) if dec.value % p == 0 else None
            return None
        if ft.is_decimal():
            if k in (K_INT, K_UINT, K_DEC):
                return Datum.d(d.to_dec())
            return None
        if ft.is_float():
            if k in (K_INT, K_UINT, K_FLOAT):
                return Datum.f(d.to_float())
            if k == K_DEC:
                return Datum.f(d.to_float())
            return None
        if ft.is_string():
            from ..mysqltypes import collate as _coll

            if _coll.is_ci(getattr(ft, "collate", None)):
                # index keys are stored in BINARY order; a ci predicate
                # must run through the weight-aware filter path, not a
                # binary key range (a range would drop case variants)
                return None
            if k in (K_STR, K_BYTES):
                return d
            return None
    except (ValueError, OverflowError):
        return None
    return None


def _simple_cond(c: Expression):
    """Recognize `col op const` / `const op col` / `col IN (consts)`.
    Returns (col_idx, op, [Datum...]) or None."""
    if not isinstance(c, ScalarFunc):
        return None
    name = c.sig.name
    if name in ("eq", "lt", "le", "gt", "ge"):
        a, b = c.args
        if isinstance(a, ECol) and isinstance(b, Constant):
            return a.idx, name, [b.value]
        if isinstance(a, Constant) and isinstance(b, ECol):
            return b.idx, _REVERSE[name], [a.value]
        return None
    if name == "in":
        a = c.args[0]
        if isinstance(a, ECol) and all(isinstance(x, Constant) for x in c.args[1:]):
            return a.idx, "in", [x.value for x in c.args[1:]]
    return None


def collect_col_access(conds: list[Expression], fts_by_off: dict[int, FieldType]) -> dict[int, ColAccess]:
    """Bucket usable simple conditions per column offset."""
    acc: dict[int, ColAccess] = {}
    for c in conds:
        s = _simple_cond(c)
        if s is None:
            continue
        off, op, vals = s
        ft = fts_by_off.get(off)
        if ft is None:
            continue
        conv = [const_to_col_datum(v, ft) for v in vals]
        if any(v is None for v in conv):
            continue  # not exactly representable — stays a filter
        a = acc.setdefault(off, ColAccess())
        if op in ("eq", "in"):
            if len(conv) > MAX_POINT_RANGES:
                continue
            if not a.eq_seen:
                a.eq = conv
                a.eq_seen = True
            else:
                keep = {_enc(d) for d in conv}
                a.eq = [d for d in a.eq if _enc(d) in keep]
            a.conds.append(c)
        elif op in ("gt", "ge"):
            b = (conv[0], op == "ge")
            if a.lo is None or _tighter_lo(b, a.lo):
                a.lo = b
            a.conds.append(c)
        elif op in ("lt", "le"):
            b = (conv[0], op == "le")
            if a.hi is None or _tighter_hi(b, a.hi):
                a.hi = b
            a.conds.append(c)
    for a in acc.values():
        a.finalize()
    return acc


def _cmp_datum(a: Datum, b: Datum) -> int:
    from ..mysqltypes.datum import compare_datum

    return compare_datum(a, b)


def _tighter_lo(new, old) -> bool:
    c = _cmp_datum(new[0], old[0])
    return c > 0 or (c == 0 and not new[1] and old[1])


def _tighter_hi(new, old) -> bool:
    c = _cmp_datum(new[0], old[0])
    return c < 0 or (c == 0 and not new[1] and old[1])


def prefix_next(b: bytes) -> bytes:
    """Smallest key greater than every key having prefix b (kv.Key.PrefixNext)."""
    ba = bytearray(b)
    for i in range(len(ba) - 1, -1, -1):
        if ba[i] != 0xFF:
            ba[i] += 1
            return bytes(ba[: i + 1])
        ba[i] = 0
    return b + b"\xff"


def _enc(d: Datum) -> bytes:
    buf = bytearray()
    encode_datum_key(buf, d)
    return bytes(buf)


@dataclass
class IndexAccess:
    """Result of detaching access conditions for one index."""

    ranges: list[tuple[bytes, bytes]]  # final key ranges (with index prefix)
    access_conds: list[Expression]  # consumed (enforced by the ranges)
    eq_count: int  # length of the equality prefix
    has_range: bool  # a range column bound was used


def detach_index_conditions(
    conds: list[Expression],
    table_id: int,
    index_id: int,
    col_offsets: list[int],
    col_fts: list[FieldType],
) -> IndexAccess | None:
    """Build key ranges for an index from pushed conjuncts. None if no
    usable access condition exists (ref: DetachCondAndBuildRangeForIndex)."""
    fts_by_off = {off: ft for off, ft in zip(col_offsets, col_fts)}
    acc = collect_col_access(conds, fts_by_off)

    idx_prefix = tablecodec.index_prefix(table_id, index_id)
    eq_values: list[list[Datum]] = []  # per eq column: candidate values
    i = 0
    for off in col_offsets:
        a = acc.get(off)
        if a is None or not a.eq:
            if a is not None and a.eq_seen and not a.eq:
                # eq/bound conds intersected to the empty set → impossible
                return IndexAccess([], a.conds, i + 1, False)
            break
        # dedup by encoded form (Datum is not hashable), keep key order
        uniq = {}
        for d in a.eq:
            uniq.setdefault(_enc(d), d)
        eq_values.append([uniq[k] for k in sorted(uniq)])
        i += 1
    eq_count = i

    range_bounds = None
    if i < len(col_offsets):
        a = acc.get(col_offsets[i])
        if a is not None and (a.lo or a.hi) and not a.eq:
            range_bounds = (a.lo, a.hi)

    if eq_count == 0 and range_bounds is None:
        return None

    # cartesian product of eq prefixes (capped; on overflow drop trailing
    # eq columns — their conds revert to filters, coarser range stays safe)
    prefixes = [b""]
    used_eq = 0
    consumed = []
    for k, vals in enumerate(eq_values):
        nxt = [p + _enc(v) for p in prefixes for v in vals]
        if len(nxt) > MAX_POINT_RANGES:
            range_bounds = None  # range col no longer adjacent to eq prefix
            break
        prefixes = nxt
        used_eq = k + 1
        a = acc.get(col_offsets[k])
        consumed.extend(a.conds)
    if used_eq == eq_count and range_bounds is not None:
        a = acc.get(col_offsets[eq_count])
        consumed.extend(a.conds)
    eq_count = used_eq
    if eq_count == 0 and range_bounds is None:
        return None

    ranges: list[tuple[bytes, bytes]] = []
    for p in prefixes:
        base = idx_prefix + p
        if range_bounds is None:
            ranges.append((base, prefix_next(base)))
            continue
        lo, hi = range_bounds
        if lo is not None:
            lo_key = base + _enc(lo[0])
            low = lo_key if lo[1] else prefix_next(lo_key)
        else:
            low = base + b"\x01"  # skip NULLs (NIL flag 0x00)
        if hi is not None:
            hi_key = base + _enc(hi[0])
            high = prefix_next(hi_key) if hi[1] else hi_key
        else:
            high = prefix_next(base)
        if low < high:
            ranges.append((low, high))
    return IndexAccess(ranges, consumed, eq_count, range_bounds is not None)


@dataclass
class HandleAccess:
    point_handles: list[int] | None  # exact handles (PointGet/BatchPointGet)
    ranges: list[tuple[bytes, bytes]] | None  # record-key ranges
    access_conds: list[Expression]


def detach_pk_handle_access(table, conds: list[Expression]) -> HandleAccess | None:
    """Clustered-int-pk access detection for a table whose expressions
    were built over its VISIBLE columns in order (the DataSource scope
    and the DML WHERE scope are both exactly that): map the handle
    column to its visible index and detach the handle conditions. The
    ONE definition both the SELECT point path (optimizer
    `_choose_for_ds`) and the DML point path (`_scan_matching_rows`)
    use — a change to handle detection lands in both or neither."""
    if not getattr(table, "pk_is_handle", False) or not conds:
        return None
    hc = table.handle_col()
    if hc is None:
        return None
    pk_vis = next(
        (i for i, c in enumerate(table.visible_columns()) if c.offset == hc.offset),
        None,
    )
    if pk_vis is None:
        return None
    return detach_handle_conditions(conds, table.id, pk_vis)


def _or_point_values(cond: Expression, pk_offset: int, ft) -> list[Datum] | None:
    """Flatten `pk=c1 OR pk IN (c2, c3) OR ...` into the point list the
    IN form would produce (ref: ranger's extractOrRanges). Every leaf of
    the OR chain must be an eq/IN on the SAME pk column with exactly-
    representable constants; anything else keeps the full-scan filter."""
    if not isinstance(cond, ScalarFunc):
        return None
    if cond.sig.name == "or":
        out: list[Datum] = []
        for arg in cond.args:
            sub = _or_point_values(arg, pk_offset, ft)
            if sub is None:
                return None
            out.extend(sub)
        return out if len(out) <= MAX_POINT_RANGES else None
    s = _simple_cond(cond)
    if s is None:
        return None
    off, op, vals = s
    if off != pk_offset or op not in ("eq", "in"):
        return None
    conv = [const_to_col_datum(v, ft) for v in vals]
    if any(v is None for v in conv):
        return None
    return conv


def detach_handle_conditions(
    conds: list[Expression], table_id: int, pk_offset: int
) -> HandleAccess | None:
    """Ranges over the integer handle (clustered pk) — ref: BuildTableRange."""
    from ..mysqltypes.field_type import ft_longlong

    acc = collect_col_access(conds, {pk_offset: ft_longlong()})
    a = acc.get(pk_offset)
    if a is None:
        # `pk=a OR pk=b [OR pk IN (...)]` — the disjunctive spelling of
        # an IN list (PR 15): one OR-chain condition over only the pk
        # detaches to the same multi-point access
        for c in conds:
            pts = _or_point_values(c, pk_offset, ft_longlong())
            if pts is not None and pts:
                handles = sorted({d.to_int() for d in pts})
                return HandleAccess(handles, None, [c])
        return None
    if a.eq_seen:
        handles = sorted({d.to_int() for d in a.eq})
        return HandleAccess(handles, None, a.conds)
    if a.lo is None and a.hi is None:
        return None
    lo_h = -(1 << 63)
    hi_h = (1 << 63) - 1
    if a.lo is not None:
        lo_h = a.lo[0].to_int() + (0 if a.lo[1] else 1)
    if a.hi is not None:
        hi_h = a.hi[0].to_int() - (0 if a.hi[1] else 1)
    if lo_h > hi_h:
        return HandleAccess(None, [], a.conds)  # empty range
    start = tablecodec.record_key(table_id, lo_h)
    end = prefix_next(tablecodec.record_key(table_id, hi_h))
    return HandleAccess(None, [(start, end)], a.conds)

"""AST → logical plan with name resolution (ref: planner/core/
logical_plan_builder.go + preprocess.go, compact redesign).

Aggregate extraction follows the reference's approach: walk select/having/
order expressions, lift aggregate calls into an Aggregation node, and
rewrite the outer expressions to reference aggregation output columns.
Non-aggregated bare columns under GROUP BY become first_row aggregates
(MySQL's permissive mode, like the reference defaults).
"""

from __future__ import annotations

from ..errors import AmbiguousColumn, TiDBError, UnknownColumn
from ..expr.aggregation import AGG_FUNCS, WINDOW_FUNCS, AggDesc, Frame, WinDesc, agg_ret_type
from ..expr.builtins import CAST_SIG
from ..expr.expression import Column as ECol, Constant, Expression, ScalarFunc, make_func
from ..mysqltypes.datum import Datum
from ..mysqltypes.field_type import FieldType, TypeCode, ft_double, ft_longlong, ft_varchar, parse_type_name
from ..mysqltypes.mydecimal import Dec
from ..parser import ast
from .plans import (
    Aggregation,
    CTERef,
    CTEStorage,
    DataSource,
    Dual,
    Join,
    Limit,
    LogicalPlan,
    PlanCol,
    Projection,
    RecursiveCTE,
    Selection,
    SetOp,
    Sort,
    Window,
)


def lit_to_constant(l: ast.Lit) -> Constant:
    v = l.value
    if l.kind == "null":
        return Constant(Datum.null(), FieldType(TypeCode.Null))
    if l.kind == "int":
        # literals above 2^63-1 are BIGINT UNSIGNED (MySQL literal typing);
        # a signed ft would silently wrap the int64 lane
        return Constant(Datum.i(v), ft_longlong(unsigned=v > 0x7FFFFFFFFFFFFFFF))
    if l.kind == "bool":
        return Constant(Datum.i(1 if v else 0), ft_longlong())
    if l.kind == "dec":
        return Constant(Datum.d(v), FieldType(TypeCode.NewDecimal, flen=30, decimal=v.scale))
    if l.kind == "float":
        return Constant(Datum.f(v), ft_double())
    if l.kind == "hex":
        return Constant(Datum.b(v), ft_varchar(len(v)))
    return Constant(Datum.s(v), ft_varchar(max(len(v), 1)))


_CMP_FUNCS = {"eq", "ne", "lt", "le", "gt", "ge", "nulleq", "in"}


def _refine_cmp_constants(fname: str, args: list[Expression]) -> list[Expression]:
    """Convert string constants compared against typed columns into the
    column's domain at plan time (ref: expression/builtin_compare.go
    RefineComparedConstant) — exact datetime/decimal compares, and the
    device engine sees only typed constants."""
    if fname not in _CMP_FUNCS or not args:
        return args
    col = next((a for a in args if isinstance(a, ECol)), None)
    if col is None:
        return args
    out = []
    for a in args:
        if isinstance(a, Constant) and a.value.kind == 5 and not a.value.is_null:  # K_STR
            ft = col.ret_type
            if ft.is_time():
                from ..mysqltypes.coretime import parse_datetime

                p = parse_datetime(a.value.val)
                if p is not None:
                    a = Constant(Datum.t(p), ft.clone())
            elif ft.is_decimal() or ft.is_int():
                d = a.value.to_dec()
                a = Constant(Datum.d(d), FieldType(TypeCode.NewDecimal, flen=30, decimal=d.scale))
            elif ft.is_float():
                a = Constant(Datum.f(a.value.to_float()), ft_double())
        out.append(a)
    return out


class NameScope:
    """Resolution scope over a plan's output columns."""

    def __init__(self, cols: list[PlanCol]):
        self.cols = cols

    def resolve(self, name: ast.Name) -> int:
        col = name.column.lower()
        tbl = (name.table or "").lower()
        hits = [
            i
            for i, c in enumerate(self.cols)
            if c.name.lower() == col and (not tbl or c.table_alias.lower() == tbl)
        ]
        if not hits:
            raise UnknownColumn(f"unknown column {'.'.join(name.parts)!r}")
        if len(hits) > 1:
            raise AmbiguousColumn(f"column {col!r} is ambiguous")
        return hits[0]


class PlanBuilder:
    """Builds logical plans; needs a catalog view + subquery executor hook."""

    def _now_epoch(self) -> float:
        from ..expr.sessioninfo import now_epoch

        return now_epoch(self.context_info.get("vars") or {})

    def _sysvar_constant(self, raw: str) -> Expression:
        """SELECT @@x / @@global.x / @@session.x → typed constant from the
        session registry (ref: expression/util.go GetSessionOrGlobalSystemVar;
        connectors issue these on connect, e.g. @@version_comment)."""
        from ..session.vars import SYSVARS

        name = raw
        want_global = False
        for pre in ("global.", "session.", "local."):
            if name.startswith(pre):
                name = name[len(pre):]
                want_global = pre == "global."
                break
        sv = SYSVARS.get(name)
        if sv is None:
            raise TiDBError(f"Unknown system variable '{name}'")
        if want_global:
            # @@global.x reads the STORE value, not this session's override
            reader = self.context_info.get("sysvar_read_global")
            val = reader(name) if reader is not None else sv.default
        else:
            reader = self.context_info.get("sysvar_read")
            if reader is not None:
                val = reader(name)
            else:
                val = self.context_info.get("vars", {}).get(name, sv.default)
        # live session state must not be baked into a cached plan
        self.used_eager_subquery = True
        if val is None:
            return Constant(Datum.null(), FieldType(TypeCode.Null))
        if sv.kind == "int":
            try:
                return Constant(Datum.i(int(val)), ft_longlong())
            except (TypeError, ValueError):
                pass
        if sv.kind == "float":
            try:
                return Constant(Datum.f(float(val)), ft_double())
            except (TypeError, ValueError):
                pass
        s = str(val)
        return Constant(Datum.s(s), ft_varchar(max(len(s), 1)))

    def _resolve_name(self, node: ast.Name, scope: NameScope) -> Expression:
        """Resolve a column name; names unknown in the local scope fall
        back to the enclosing query's scope as correlated references
        (ref: expression.CorrelatedColumn, rule_decorrelate.go)."""
        if len(node.parts) == 1 and node.parts[0].startswith("@@"):
            return self._sysvar_constant(node.parts[0][2:])
        try:
            idx = scope.resolve(node)
        except UnknownColumn:
            for outer in reversed(self._outer_scopes):
                try:
                    oidx = outer.resolve(node)
                except UnknownColumn:
                    continue
                c = outer.cols[oidx]
                return _CorrRef(oidx, c.ft, c.name)
            raise
        c = scope.cols[idx]
        return ECol(idx, c.ft, c.name)

    def __init__(self, infoschema, current_db: str, run_subquery=None, params=None, memtable_rows=None, context_info=None, hints=None, expose_rowid=None, seq_hook=None):
        self.is_ = infoschema
        self.db = current_db
        self.seq_hook = seq_hook  # session.sequence_op for NEXTVAL/LASTVAL/SETVAL
        # aliases whose hidden `_tidb_rowid` must be addressable (multi-
        # table DML projects per-target handles through the join)
        self.expose_rowid = expose_rowid or set()
        self.run_subquery = run_subquery  # callable(Select ast) -> list[Datum rows]
        self.params = params  # EXECUTE-bound Constants for '?' placeholders
        self.memtable_rows = memtable_rows  # callable(name) -> rows (info schema)
        self.context_info = context_info or {}  # user/conn info for info funcs
        self.hints = hints or []  # [(NAME, [args])] — statement-wide
        # set when a subquery was evaluated eagerly at plan time: such a
        # plan bakes in data and must not enter the plan cache
        self.used_eager_subquery = False
        # correlated-subquery build state (rule_decorrelate.go analog):
        # while building a subquery, unknown names resolve against the
        # enclosing scopes as _CorrRef placeholders
        self._outer_scopes: list[NameScope] = []
        # WITH-clause tables visible to the current (sub)query, innermost
        # last; entries: name → CTEDef | ("recursive", CTERef factory)
        self._cte_frames: list[dict] = []

    # ------------------------------------------------------------------ FROM

    # ------------------------------------------------------------------ CTE

    MAX_CTE_DEPTH = 32

    def _cte_frame(self, wf: ast.WithClause) -> dict:
        frame = {}
        for cte in wf.ctes:
            if cte.name.lower() in frame:
                raise TiDBError(f"Not unique table/alias: {cte.name!r}")
            kind = "recursive" if (wf.recursive and _refs_table(cte.select, cte.name)) else "plain"
            frame[cte.name.lower()] = (kind, cte)
        return frame

    def _lookup_cte(self, name: str):
        key = name.lower()
        # recursive-branch binding shadows everything
        bind = getattr(self, "_rec_bindings", {}).get(key)
        if bind is not None:
            return ("ref", bind)
        for frame in reversed(self._cte_frames):
            if key in frame:
                return frame[key]
        return None

    def _build_cte(self, tn: ast.TableName, entry) -> LogicalPlan:
        kind, payload = entry
        alias = tn.alias or tn.name
        if kind == "ref":
            storage, cols = payload
            return CTERef(tn.name, storage, [PlanCol(c.name, c.ft, alias) for c in cols])
        cte: ast.CTEDef = payload
        if kind == "building":
            raise TiDBError(f"CTE {cte.name!r} references itself but is not declared RECURSIVE")
        if kind == "plain":
            # inline the CTE body (materialization is an executor concern);
            # mark it 'building' so non-recursive self-reference errors
            for frame in reversed(self._cte_frames):
                if frame.get(cte.name.lower()) is entry:
                    frame[cte.name.lower()] = ("building", cte)
                    break
            try:
                sub = self.build_select(cte.select)
            finally:
                for frame in reversed(self._cte_frames):
                    if frame.get(cte.name.lower()) == ("building", cte):
                        frame[cte.name.lower()] = entry
                        break
            return self._alias_barrier(sub, cte.cols, alias)
        # recursive CTE: split seed vs recursive branches
        sel = cte.select
        if not isinstance(sel, ast.SetOpSelect) or len(sel.selects) != 2:
            raise TiDBError("recursive CTE must be 'seed UNION [ALL] recursive' with two branches")
        seed_ast, rec_ast = sel.selects
        if _refs_table(seed_ast, cte.name) or not _refs_table(rec_ast, cte.name):
            raise TiDBError("recursive CTE needs a non-recursive seed branch first")
        distinct = sel.ops[0] == "union"
        seed_plan = self.build_select(seed_ast)
        names = cte.cols or [c.name for c in seed_plan.out_cols]
        if len(names) != len(seed_plan.out_cols):
            raise TiDBError("CTE column list length mismatch")
        cols = [PlanCol(nm, c.ft, cte.name) for nm, c in zip(names, seed_plan.out_cols)]
        storage = CTEStorage()
        if not hasattr(self, "_rec_bindings"):
            self._rec_bindings = {}
        if cte.name.lower() in self._rec_bindings:
            raise TiDBError("nested recursion in recursive CTE is not supported")
        self._rec_bindings[cte.name.lower()] = (storage, cols)
        try:
            rec_plan = self.build_select(rec_ast)
        finally:
            del self._rec_bindings[cte.name.lower()]
        if len(rec_plan.out_cols) != len(cols):
            raise TiDBError(
                f"recursive branch of CTE {cte.name!r} returns {len(rec_plan.out_cols)} "
                f"columns, expected {len(cols)}"
            )
        node = RecursiveCTE(cte.name, seed_plan, rec_plan, storage, distinct,
                            [PlanCol(c.name, c.ft, alias) for c in cols])
        return node

    @staticmethod
    def _alias_barrier(sub: LogicalPlan, declared: list, alias: str, what: str = "CTE") -> LogicalPlan:
        """Re-alias a subplan through a Projection: explicit column list
        (CTE/view) or the subplan's own names (shared by CTEs, derived
        tables, and views)."""
        names = declared or [c.name for c in sub.out_cols]
        if len(names) != len(sub.out_cols):
            raise TiDBError(f"{what} column list length mismatch")
        cols = [PlanCol(nm, c.ft, alias) for nm, c in zip(names, sub.out_cols)]
        exprs = [ECol(i, c.ft, c.name) for i, c in enumerate(sub.out_cols)]
        return Projection(sub, exprs, cols)

    def build_table(self, tn: ast.TableName):
        if tn.db is None:
            ent = self._lookup_cte(tn.name)
            if ent is not None:
                return self._build_cte(tn, ent)
        db = (tn.db or self.db).lower()
        if db == "information_schema" and self.memtable_rows is not None:
            from ..catalog.memtables import SCHEMAS

            schema = SCHEMAS.get(tn.name.lower())
            if schema is not None:
                names, fts = schema
                alias = tn.alias or tn.name
                cols = [PlanCol(n, ft, alias) for n, ft in zip(names, fts)]
                provider = self.memtable_rows
                name = tn.name.lower()
                from .plans import Memtable

                return Memtable(name, lambda: provider(name), cols)
        db = tn.db or self.db
        key = ((tn.db or self.db).lower(), tn.name.lower())
        vdef = self.is_.views.get(key)
        shadow = self.is_.table_or_none(*key)
        # a session temp table shadows a same-named view (temp wins over
        # everything, matching the temp-shadows-permanent rule)
        if vdef is not None and not getattr(shadow, "temporary", False):
            return self._build_view(tn, vdef)
        info = self.is_.table(db, tn.name)
        cols = [
            PlanCol(c.name, c.ft, tn.alias or tn.name, c.offset)
            for c in info.columns
            if not c.hidden
        ]
        if (tn.alias or tn.name).lower() in self.expose_rowid:
            rid = next((c for c in info.columns if c.hidden and c.name == "_tidb_rowid"), None)
            if rid is not None:
                cols.append(PlanCol(rid.name, rid.ft, tn.alias or tn.name, rid.offset))
        ds = DataSource(info, tn.alias or tn.name, cols)
        # an aliased table is addressable ONLY by its alias (TiDB rule)
        name = (tn.alias or tn.name).lower()
        known = {ix.name.lower() for ix in info.indexes}
        for h, args in self.hints:
            if not args or args[0] != name:
                continue
            if h in ("USE_INDEX", "FORCE_INDEX", "IGNORE_INDEX"):
                wanted = {a.lower() for a in args[1:]}
                missing = wanted - known
                if missing:
                    raise TiDBError(
                        f"Key {sorted(missing)[0]!r} doesn't exist in table {name!r}"
                    )
                attr = "hint_ignore_index" if h == "IGNORE_INDEX" else "hint_use_index"
                cur = getattr(ds, attr, None) or set()
                setattr(ds, attr, cur | wanted)
        return ds

    MAX_VIEW_DEPTH = 16

    def _build_view(self, tn: ast.TableName, vdef: dict) -> LogicalPlan:
        """Expand a view reference: re-plan the stored SELECT against the
        current schema, then re-alias through a Projection barrier (ref:
        planner/core/logical_plan_builder.go BuildDataSourceFromView)."""
        self._view_depth = getattr(self, "_view_depth", 0) + 1
        # a view definition is an INDEPENDENT name scope planned in the
        # view's own database: the caller's db, CTE names, hints, and
        # outer scopes must not leak in
        saved = (self.db, self._cte_frames, self._outer_scopes, self.hints,
                 getattr(self, "_rec_bindings", {}))
        self.db = vdef["db"]
        self._cte_frames = []
        self._outer_scopes = []
        self.hints = []
        self._rec_bindings = {}
        try:
            if self._view_depth > self.MAX_VIEW_DEPTH:
                raise TiDBError(f"view {tn.name!r} nests too deeply (cycle?)")
            from ..parser import parse_one

            sub = self.build_select(parse_one(vdef["sql"]))
            return self._alias_barrier(sub, vdef.get("cols") or [], tn.alias or tn.name, what=f"view {tn.name!r}")
        finally:
            self._view_depth -= 1
            (self.db, self._cte_frames, self._outer_scopes, self.hints,
             self._rec_bindings) = saved

    def build_from(self, node) -> LogicalPlan:
        if node is None:
            return Dual()
        if isinstance(node, ast.TableName):
            return self.build_table(node)
        if isinstance(node, ast.SubqueryTable):
            sub = self.build_select(node.select)
            cols = [PlanCol(c.name, c.ft, node.alias) for c in sub.out_cols]
            # re-alias through a projection barrier
            exprs = [ECol(i, c.ft, c.name) for i, c in enumerate(sub.out_cols)]
            return Projection(sub, exprs, cols)
        if isinstance(node, ast.Join):
            return self.build_join(node)
        raise TiDBError(f"unsupported FROM clause {type(node).__name__}")

    def build_join(self, j: ast.Join) -> LogicalPlan:
        left = self.build_from(j.left)
        right = self.build_from(j.right)
        kind = j.kind
        straight = getattr(j, "straight", False)
        cols = list(left.out_cols) + list(right.out_cols)
        scope = NameScope(cols)
        conds = []
        if j.using:
            for name in j.using:
                li = NameScope(left.out_cols).resolve(ast.Name((name,)))
                ri = NameScope(right.out_cols).resolve(ast.Name((name,)))
                conds.append(
                    make_func(
                        "eq",
                        ECol(li, left.out_cols[li].ft, name),
                        ECol(len(left.out_cols) + ri, right.out_cols[ri].ft, name),
                    )
                )
        elif j.on is not None:
            conds = self.split_cnf(self.to_expr(j.on, scope))
        eq, other = [], []
        nl = len(left.out_cols)
        for c in conds:
            pair = self._as_eq_pair(c, nl)
            if pair is not None:
                eq.append(pair)
            else:
                other.append(c)
        if kind == "cross":
            kind = "inner"
        jn = Join(left, right, kind, eq, other, cols)
        jn.straight = straight
        return jn

    @staticmethod
    def _as_eq_pair(c: Expression, nl: int):
        """eq(col_left, col_right) across the join boundary → key pair."""
        if isinstance(c, ScalarFunc) and c.sig.name == "eq":
            a, b = c.args
            asides = set()
            a.collect_columns(asides)
            bsides = set()
            b.collect_columns(bsides)
            if asides and bsides:
                if max(asides) < nl and min(bsides) >= nl:
                    return (a, b)
                if max(bsides) < nl and min(asides) >= nl:
                    return (b, a)
        return None

    @staticmethod
    def split_cnf(e: Expression) -> list[Expression]:
        if isinstance(e, ScalarFunc) and e.sig.name == "and":
            return PlanBuilder.split_cnf(e.args[0]) + PlanBuilder.split_cnf(e.args[1])
        return [e]

    # ------------------------------------------------------------ expressions

    def to_expr(self, node, scope: NameScope, agg_ctx=None, allow_window=False) -> Expression:
        if isinstance(node, ast.Lit):
            return lit_to_constant(node)
        if isinstance(node, ast.Param):
            if self.params is None or node.index >= len(self.params):
                raise TiDBError("statement has placeholders but no parameters were bound")
            return self.params[node.index]
        if isinstance(node, ast.Name):
            return self._resolve_name(node, scope)
        if isinstance(node, ast.Call):
            lname = node.name.lower()
            if lname in ("charset", "collation", "coercibility") and len(node.args) == 1:
                return self._type_meta_func(lname, self.to_expr(node.args[0], scope, agg_ctx))
            info_c = self._info_func(lname, node)
            if info_c is not None:
                return info_c
            if getattr(node, "over", None) is not None or lname in WINDOW_FUNCS:
                if node.over is None:
                    raise TiDBError(f"window function {lname} requires an OVER clause")
                if agg_ctx is None or not allow_window:
                    raise TiDBError(f"window function {lname} is not allowed here")
                return self._window_expr(node, scope, agg_ctx)
            if lname in AGG_FUNCS:
                if agg_ctx is None:
                    raise TiDBError(f"aggregate {lname} not allowed here")
                return agg_ctx.add_agg(node, scope)
            if lname == "in_subquery":
                return self._in_subquery(node, scope, agg_ctx)
            if lname in ("nextval", "next_value", "lastval", "setval") and self.seq_hook is not None:
                return self._sequence_expr(lname, node, scope, agg_ctx)
            if lname in ("date_add", "date_sub", "adddate", "subdate") and len(node.args) == 2 \
                    and isinstance(node.args[1], ast.Interval):
                iv = node.args[1]
                return make_func(
                    lname,
                    self.to_expr(node.args[0], scope, agg_ctx),
                    self.to_expr(iv.expr, scope, agg_ctx),
                    Constant(Datum.s(iv.unit), ft_varchar(16)),
                )
            if lname in ("plus", "minus") and any(isinstance(a, ast.Interval) for a in node.args):
                # d + INTERVAL n unit  /  INTERVAL n unit + d  /  d - INTERVAL n unit
                iv = next(a for a in node.args if isinstance(a, ast.Interval))
                other = next(a for a in node.args if not isinstance(a, ast.Interval))
                fname = "date_add" if lname == "plus" else "date_sub"
                return make_func(
                    fname,
                    self.to_expr(other, scope, agg_ctx),
                    self.to_expr(iv.expr, scope, agg_ctx),
                    Constant(Datum.s(iv.unit), ft_varchar(16)),
                )
            args = [self.to_expr(a, scope, agg_ctx, allow_window) for a in node.args]
            args = _refine_cmp_constants(lname, args)
            return make_func(lname, *args)
        if isinstance(node, ast.CaseWhen):
            args = []
            for cond, res in node.whens:
                c = self.to_expr(cond, scope, agg_ctx, allow_window)
                if node.operand is not None:
                    c = make_func("eq", self.to_expr(node.operand, scope, agg_ctx, allow_window), c)
                args.append(c)
                args.append(self.to_expr(res, scope, agg_ctx, allow_window))
            if node.else_ is not None:
                args.append(self.to_expr(node.else_, scope, agg_ctx, allow_window))
            return make_func("case", *args)
        if isinstance(node, ast.Cast):
            e = self.to_expr(node.expr, scope, agg_ctx, allow_window)
            ft = parse_type_name(node.type_name, node.type_args, node.unsigned)
            return ScalarFunc(CAST_SIG, [e], ft)
        if isinstance(node, ast.SubqueryExpr):
            return self._scalar_subquery(node)
        if isinstance(node, ast.Star):
            raise TiDBError("* not allowed in this context")
        raise TiDBError(f"unsupported expression {type(node).__name__}")

    def _sequence_expr(self, lname: str, node, scope, agg_ctx):
        """NEXTVAL(seq)/LASTVAL(seq)/SETVAL(seq, n): the first argument is
        a sequence IDENTIFIER, not a column (parser sees a Name)."""
        if not node.args or not isinstance(node.args[0], ast.Name):
            raise TiDBError(f"{lname} requires a sequence name argument")
        sn = node.args[0]
        db = sn.parts[0] if len(sn.parts) >= 2 else self.db
        name = sn.parts[-1]
        op = "nextval" if lname == "next_value" else lname
        arg = None
        if op == "setval":
            if len(node.args) != 2:
                raise TiDBError("SETVAL requires (sequence, value)")
            arg = self.to_expr(node.args[1], scope, agg_ctx)
        elif len(node.args) != 1:
            raise TiDBError(f"{lname} takes exactly one argument")
        self.used_eager_subquery = True  # stateful: keep out of the plan cache
        return _SeqExpr(op, db, name, self.seq_hook, arg)

    def _type_meta_func(self, lname: str, arg: Expression) -> Constant:
        """CHARSET()/COLLATION()/COERCIBILITY() — metadata of the argument
        EXPRESSION, folded at plan time where the expression (not just its
        value) is visible (ref: expression/builtin_info.go)."""
        ft = arg.ret_type
        is_null = isinstance(arg, Constant) and arg.value.is_null
        is_str = ft.is_string() and not is_null
        if lname == "charset":
            v = (getattr(ft, "charset", None) or "utf8mb4") if is_str else "binary"
            return Constant(Datum.s(v), ft_varchar(32))
        if lname == "collation":
            v = (getattr(ft, "collate", None) or "utf8mb4_bin") if is_str else "binary"
            return Constant(Datum.s(v), ft_varchar(32))
        # coercibility (MySQL levels: 2=IMPLICIT column, 4=COERCIBLE
        # literal, 5=NUMERIC, 6=IGNORABLE NULL)
        if is_null:
            c = 6
        elif not ft.is_string():
            c = 5
        elif isinstance(arg, Constant):
            c = 4
        else:
            c = 2
        return Constant(Datum.i(c), ft_longlong())

    def _info_func(self, lname: str, node) -> Constant | None:
        """Session/time information functions evaluated at plan time
        (ref: builtin_info.go, builtin_time.go NOW/CURDATE). Plans that
        embed them are flagged uncacheable."""
        import time as _time

        from ..mysqltypes.coretime import pack_time
        from ..mysqltypes.datum import K_DUR
        from ..mysqltypes.field_type import TypeCode as TC

        if node.args:
            return None
        if lname in ("database", "schema"):
            self.used_eager_subquery = True
            return Constant(Datum.s(self.db), ft_varchar(64))
        if lname == "version":
            return Constant(Datum.s("8.0.11-tidb-tpu"), ft_varchar(64))
        if lname in ("user", "current_user", "session_user"):
            self.used_eager_subquery = True
            u = self.context_info.get("user", "root")
            return Constant(Datum.s(f"{u}@%"), ft_varchar(64))
        if lname == "connection_id":
            self.used_eager_subquery = True
            return Constant(Datum.i(int(self.context_info.get("conn_id", 0))), ft_longlong())
        if lname in ("now", "current_timestamp", "sysdate", "localtime", "localtimestamp"):
            self.used_eager_subquery = True
            t = _time.localtime(self._now_epoch())
            ft = FieldType(TC.Datetime)
            return Constant(Datum.t(pack_time(t.tm_year, t.tm_mon, t.tm_mday, t.tm_hour, t.tm_min, t.tm_sec)), ft)
        if lname in ("curdate", "current_date"):
            self.used_eager_subquery = True
            t = _time.localtime(self._now_epoch())
            return Constant(Datum.t(pack_time(t.tm_year, t.tm_mon, t.tm_mday)), FieldType(TC.Date))
        if lname in ("curtime", "current_time"):
            self.used_eager_subquery = True
            t = _time.localtime(self._now_epoch())
            us = (t.tm_hour * 3600 + t.tm_min * 60 + t.tm_sec) * 1_000_000
            return Constant(Datum(K_DUR, us), FieldType(TC.Duration))
        return None

    def _window_expr(self, node: ast.Call, scope, agg_ctx) -> "_WindowFuncExpr":
        """ast window call → placeholder expression lifted later by
        _build_windows (ref: logical_plan_builder.go buildWindowFunctions)."""
        lname = node.name.lower()
        svars = self.context_info.get("vars") or {}
        if svars.get("tidb_enable_window_function", "ON") != "ON":
            raise TiDBError(
                f"window function {lname} is disabled (tidb_enable_window_function=OFF)"
            )
        if node.distinct:
            raise TiDBError(f"DISTINCT is not supported in window function {lname}")
        args = []
        for a in node.args:
            if isinstance(a, ast.Star):
                continue  # COUNT(*) OVER (...)
            args.append(self.to_expr(a, scope, agg_ctx))
        part = [self.to_expr(p, scope, agg_ctx) for p in node.over.partition_by]
        order = [(self.to_expr(b.expr, scope, agg_ctx), b.desc) for b in node.over.order_by]

        def need(lo, hi):
            if not (lo <= len(args) <= hi):
                raise TiDBError(f"wrong argument count for window function {lname}")

        if lname in ("row_number", "rank", "dense_rank", "cume_dist", "percent_rank"):
            need(0, 0)
            ft = ft_double() if lname in ("cume_dist", "percent_rank") else ft_longlong()
        elif lname == "ntile":
            need(1, 1)
            if not (isinstance(args[0], Constant) and self._const_pos_int(args[0])):
                raise TiDBError("NTILE requires a positive integer constant")
            ft = ft_longlong()
        elif lname in ("lead", "lag"):
            need(1, 3)
            if len(args) >= 2:
                ok = isinstance(args[1], Constant) and not args[1].value.is_null
                try:
                    ok = ok and args[1].value.to_int() >= 0
                except Exception:
                    ok = False
                if not ok:
                    raise TiDBError(f"{lname} offset must be a non-negative integer constant")
            if len(args) == 3:
                a0, d2 = args[0].ret_type, args[2]
                if a0.is_string() != d2.ret_type.is_string():
                    raise TiDBError(f"{lname} default value type is incompatible with the value column")
                if a0.is_decimal() and isinstance(d2, Constant) and not d2.value.is_null:
                    # align the default to the value lane's scaled-int form
                    args[2] = Constant(
                        Datum.d(d2.value.to_dec().rescale(max(a0.decimal, 0))), a0.clone()
                    )
            ft = args[0].ret_type.clone()
        elif lname == "nth_value":
            need(2, 2)
            if not (isinstance(args[1], Constant) and self._const_pos_int(args[1])):
                raise TiDBError("NTH_VALUE position must be a positive integer constant")
            ft = args[0].ret_type.clone()
        elif lname in ("first_value", "last_value"):
            need(1, 1)
            ft = args[0].ret_type.clone()
        elif lname == "count":
            need(0, 1)
            ft = ft_longlong()
        elif lname in ("sum", "avg"):
            need(1, 1)
            ft = agg_ret_type(lname, args[0].ret_type)
        elif lname in ("min", "max"):
            need(1, 1)
            ft = args[0].ret_type.clone()
        else:
            raise TiDBError(f"{lname} cannot be used as a window function")
        frame = None
        if node.over.frame is not None and lname not in self._FRAME_IGNORING:
            frame = self._build_frame(node.over.frame, order, scope, agg_ctx)
        return _WindowFuncExpr(WinDesc(lname, args, part, order, ft, frame))

    _BOUND_RANK = {"up": 0, "pre": 1, "cur": 2, "fol": 3, "uf": 4}

    def _build_frame(self, fr, order, scope, agg_ctx) -> Frame:
        """ast.FrameSpec → validated normalized Frame (ref:
        planner/core/logical_plan_builder.go buildWindowFunctionFrame +
        checkFrameBound). RANGE offsets land pre-scaled for decimal keys."""
        if fr.start.kind == "uf":
            raise TiDBError("frame start cannot be UNBOUNDED FOLLOWING")
        if fr.end.kind == "up":
            raise TiDBError("frame end cannot be UNBOUNDED PRECEDING")
        if self._BOUND_RANK[fr.start.kind] > self._BOUND_RANK[fr.end.kind]:
            raise TiDBError("window frame start cannot be after frame end")

        def bound_off(b, what):
            if b.kind not in ("pre", "fol"):
                return 0
            e = self.to_expr(b.offset, scope, agg_ctx)
            if not isinstance(e, Constant) or e.value.is_null:
                raise TiDBError(f"window frame {what} offset must be a constant")
            if fr.unit == "rows":
                try:
                    off = e.value.to_int()
                except Exception:
                    off = -1
                if off < 0:
                    raise TiDBError("ROWS frame offset must be a non-negative integer")
                return off
            # RANGE: numeric offset, compared in the ORDER BY key's space
            if len(order) != 1:
                raise TiDBError("RANGE frame with offset requires exactly one ORDER BY expression")
            kft = order[0][0].ret_type
            if not (kft.is_int() or kft.is_decimal() or kft.is_float()):
                raise TiDBError("RANGE frame with offset requires a numeric ORDER BY expression")
            d = e.value
            if kft.is_decimal():
                # pre-scale exactly into the key lane's scaled-int form
                off = d.to_dec().rescale(max(kft.decimal, 0)).value
            elif kft.is_float():
                off = d.to_float()
            else:
                f = d.to_float()
                off = d.to_int() if float(int(f)) == f else f
            if (off if isinstance(off, (int, float)) else 0) < 0:
                raise TiDBError("RANGE frame offset must be non-negative")
            return off

        so, eo = bound_off(fr.start, "start"), bound_off(fr.end, "end")
        # same-kind offset ordering: (3 FOLLOWING .. 1 FOLLOWING) and
        # (2 PRECEDING .. 5 PRECEDING) are errors, not empty frames
        # (ref: MySQL ER_WINDOW_FRAME_START_ILLEGAL 3586)
        if (fr.start.kind == fr.end.kind == "fol" and so > eo) or (
            fr.start.kind == fr.end.kind == "pre" and so < eo
        ):
            raise TiDBError("window frame start cannot move after frame end")
        return Frame(fr.unit, fr.start.kind, so, fr.end.kind, eo)

    # frame clauses are accepted but ignored for these (SQL standard /
    # ref planner: needFrame==false funcs always use the whole partition)
    _FRAME_IGNORING = frozenset(
        ("row_number", "rank", "dense_rank", "cume_dist", "percent_rank", "ntile", "lead", "lag")
    )

    @staticmethod
    def _const_pos_int(c: Constant) -> bool:
        try:
            return not c.value.is_null and c.value.to_int() > 0
        except Exception:
            return False

    def _build_windows(self, plan, proj_exprs, order_items):
        """Lift _WindowFuncExpr placeholders into stacked Window nodes (one
        per distinct PARTITION/ORDER spec) and rewrite the outer exprs to
        reference the window output columns."""
        descs: list[WinDesc] = []
        seen: dict[str, WinDesc] = {}

        def collect(e):
            if isinstance(e, _WindowFuncExpr):
                k = repr(e.desc)
                if k not in seen:
                    seen[k] = e.desc
                    descs.append(e.desc)
                return
            if isinstance(e, ScalarFunc):
                for a in e.args:
                    collect(a)

        for e in proj_exprs:
            collect(e)
        for k, x, d, n in order_items:
            if k == "expr":
                collect(x)
        if not descs:
            return proj_exprs, order_items, plan

        # group by spec (first-seen order), stack one Window node per spec
        idx_of: dict[str, int] = {}
        by_spec: dict[str, list[WinDesc]] = {}
        for d in descs:
            by_spec.setdefault(d.spec_key(), []).append(d)
        for spec, ds in by_spec.items():
            base = len(plan.out_cols)
            cols = list(plan.out_cols) + [
                PlanCol(f"w{base + j}", d.ret_type) for j, d in enumerate(ds)
            ]
            plan = Window(plan, ds[0].part_by, ds[0].order_by, ds, cols)
            for j, d in enumerate(ds):
                idx_of[repr(d)] = base + j

        def replace(e):
            if isinstance(e, _WindowFuncExpr):
                i = idx_of[repr(e.desc)]
                return ECol(i, e.ret_type, f"w{i}")
            if isinstance(e, ScalarFunc):
                return ScalarFunc(e.sig, [replace(a) for a in e.args], e.ret_type)
            return e

        proj_exprs = [replace(e) for e in proj_exprs]
        order_items = [
            (k, replace(x) if k == "expr" else x, d, n) for k, x, d, n in order_items
        ]
        return proj_exprs, order_items, plan

    def _scalar_subquery(self, node: ast.SubqueryExpr) -> Expression:
        """Uncorrelated subqueries evaluate eagerly at plan time
        (correlated subqueries: decorrelation rule lands with the apply
        operator; ref rule_decorrelate.go)."""
        if self.run_subquery is None:
            raise TiDBError("subqueries not supported in this context")
        self.used_eager_subquery = True
        rows, fts = self.run_subquery(node.select)
        if node.modifier == "exists":
            return Constant(Datum.i(1 if rows else 0), ft_longlong())
        if node.modifier == "scalar":
            if len(rows) > 1:
                raise TiDBError("Subquery returns more than 1 row")
            if not rows:
                return Constant(Datum.null(), FieldType(TypeCode.Null))
            return Constant(rows[0][0], fts[0])
        raise TiDBError(f"unsupported subquery modifier {node.modifier}")

    def _in_subquery(self, node: ast.Call, scope, agg_ctx) -> Expression:
        lhs = self.to_expr(node.args[0], scope, agg_ctx)
        sub = node.args[1]
        self.used_eager_subquery = True
        rows, fts = self.run_subquery(sub.select)
        if not rows:
            return Constant(Datum.i(0), ft_longlong())
        consts = [Constant(r[0], fts[0]) for r in rows]
        return make_func("in", lhs, *consts)

    # ---------------------------------------------------------------- SELECT

    def build_select(self, sel) -> LogicalPlan:
        wf = getattr(sel, "with_", None)
        if wf is not None:
            self._cte_frames.append(self._cte_frame(wf))
            try:
                return self._build_select_body(sel)
            finally:
                self._cte_frames.pop()
        return self._build_select_body(sel)

    def _build_select_body(self, sel) -> LogicalPlan:
        if isinstance(sel, ast.SetOpSelect):
            return self.build_setop(sel)
        plan = self.build_from(sel.from_)
        scope = NameScope(plan.out_cols)

        if sel.where is not None:
            plan = self._build_where(plan, scope, sel.where)

        # expand stars into field list
        fields = []
        for f in sel.fields:
            if isinstance(f, ast.Star):
                for i, c in enumerate(plan.out_cols):
                    if f.table and c.table_alias.lower() != f.table.lower():
                        continue
                    fields.append(ast.SelectField(ast.Name((c.table_alias, c.name)), None))
                if not fields:
                    raise TiDBError("SELECT * with no tables")
            else:
                fields.append(f)

        agg_ctx = AggContext(self)
        group_exprs = []
        for g in sel.group_by:
            if isinstance(g, ast.Lit) and g.kind == "int":  # GROUP BY 2 (position)
                fe = fields[g.value - 1].expr
                group_exprs.append(self.to_expr(fe, scope))
            else:
                group_exprs.append(self.to_expr(g, scope))

        # convert select expressions, lifting aggregates
        proj_exprs = []
        proj_cols = []
        for f in fields:
            e = self.to_expr(f.expr, scope, agg_ctx, allow_window=True)
            name = f.alias or self._field_name(f.expr)
            proj_exprs.append(e)
            proj_cols.append(PlanCol(name, e.ret_type))

        having_expr = None
        if sel.having is not None:
            having_scope = ScopeWithAliases(scope, fields, proj_exprs)
            having_expr = self.to_expr_with_aliases(sel.having, having_scope, agg_ctx)

        # convert ORDER BY early: aliases → projected exprs, other exprs over
        # the child scope (may lift aggregates into agg_ctx)
        alias_scope = ScopeWithAliases(scope, fields, proj_exprs)
        order_items = []  # ('pos', i, desc) | ('expr', Expression, desc, ast)
        for b in sel.order_by:
            if isinstance(b.expr, ast.Lit) and b.expr.kind == "int":
                order_items.append(("pos", b.expr.value - 1, b.desc, None))
            else:
                e = self.to_expr_with_aliases(b.expr, alias_scope, agg_ctx, allow_window=True)
                order_items.append(("expr", e, b.desc, b.expr))

        need_agg = bool(group_exprs) or agg_ctx.aggs
        if need_agg:
            # rewrite first: it may append first_row aggs for bare columns
            proj_exprs = [agg_ctx.rewrite(e, group_exprs) for e in proj_exprs]
            if having_expr is not None:
                having_expr = agg_ctx.rewrite(having_expr, group_exprs)
            order_items = [
                (k, agg_ctx.rewrite(x, group_exprs) if k == "expr" else x, d, n)
                for k, x, d, n in order_items
            ]
            plan = self._build_agg(plan, scope, group_exprs, agg_ctx)

        if having_expr is not None:
            plan = Selection(plan, self.split_cnf(having_expr))

        # window functions sit above aggregation/HAVING, below the final
        # projection/DISTINCT/ORDER BY (ref: logical_plan_builder.go build order)
        proj_exprs, order_items, plan = self._build_windows(plan, proj_exprs, order_items)

        # sort columns: select-list matches by structure; others become
        # hidden projection columns trimmed after the sort
        n_visible = len(proj_exprs)
        hidden: list = []
        by: list = []
        for kind, x, desc, node in order_items:
            if kind == "pos":
                if not (0 <= x < n_visible):
                    raise TiDBError(f"ORDER BY position {x + 1} out of range")
                by.append((ECol(x, proj_exprs[x].ret_type, proj_cols[x].name), desc))
                continue
            idx = None
            for i, pe in enumerate(proj_exprs):
                if repr(pe) == repr(x):
                    idx = i
                    break
            if idx is None:
                hidden.append(x)
                idx = n_visible + len(hidden) - 1
            ft = (proj_exprs + hidden)[idx].ret_type
            by.append((ECol(idx, ft, f"s{idx}"), desc))

        if sel.distinct and hidden:
            raise TiDBError("ORDER BY expression must appear in SELECT DISTINCT list")

        all_exprs = proj_exprs + hidden
        all_cols = proj_cols + [PlanCol(f"h{i}", e.ret_type) for i, e in enumerate(hidden)]
        plan = Projection(plan, all_exprs, all_cols)

        if sel.distinct:
            gb = [ECol(i, c.ft, c.name) for i, c in enumerate(proj_cols)]
            plan = Aggregation(plan, gb, [], list(proj_cols))

        if by:
            plan = Sort(plan, by)

        if hidden:
            trims = [ECol(i, c.ft, c.name) for i, c in enumerate(proj_cols)]
            plan = Projection(plan, trims, proj_cols)

        if sel.limit is not None:
            cnt = self._const_int(sel.limit)
            off = self._const_int(sel.offset) if sel.offset is not None else 0
            plan = Limit(plan, cnt, off)
        return plan

    # ----------------------------------------------- WHERE / decorrelation

    @staticmethod
    def _ast_conjuncts(node) -> list:
        if isinstance(node, ast.Call) and node.name.lower() == "and":
            out = []
            for a in node.args:
                out.extend(PlanBuilder._ast_conjuncts(a))
            return out
        return [node]

    @staticmethod
    def _subquery_conjunct(cj):
        """Classify a WHERE conjunct that can decorrelate into a semi/anti
        join → (kind, lhs_ast, sub_select) or None."""
        if isinstance(cj, ast.SubqueryExpr) and cj.modifier == "exists":
            return ("semi", None, cj.select)
        if isinstance(cj, ast.Call) and cj.name.lower() == "in_subquery":
            return ("semi", cj.args[0], cj.args[1].select)
        if isinstance(cj, ast.Call) and cj.name.lower() == "not" and len(cj.args) == 1:
            inner = cj.args[0]
            if isinstance(inner, ast.SubqueryExpr) and inner.modifier == "exists":
                return ("anti", None, inner.select)
            if isinstance(inner, ast.Call) and inner.name.lower() == "in_subquery":
                return ("anti_in", inner.args[0], inner.args[1].select)
        return None

    @staticmethod
    def _simple_subquery(sel) -> bool:
        """Subqueries the decorrelated semi-join path handles: plain
        SELECT-FROM-WHERE (no agg/group/having/limit/distinct/set-ops)."""
        return (
            isinstance(sel, ast.Select)
            and not sel.group_by
            and sel.having is None
            and sel.limit is None
            and not sel.distinct
            and not sel_has_agg(sel)
        )

    def _build_where(self, plan, scope, where_ast):
        """WHERE with IN/EXISTS conjuncts rewritten to semi/anti hash joins
        (ref: planner/core/rule_decorrelate.go, expression_rewriter.go
        buildSemiJoin) so subqueries never re-execute per row. Subqueries
        beyond plain SPJ shape keep the eager-evaluation path (correct for
        uncorrelated; correlated ones error in name resolution)."""
        normal: list[Expression] = []
        subs = []
        for cj in self._ast_conjuncts(where_ast):
            hit = self._subquery_conjunct(cj)
            if hit is not None and self._simple_subquery(hit[2]):
                subs.append(hit)
                continue
            normal.extend(self.split_cnf(self.to_expr(cj, scope)))
        if normal:
            plan = Selection(plan, normal)
        for kind, lhs_ast, sub_sel in subs:
            plan = self._build_semi_join(plan, scope, kind, lhs_ast, sub_sel)
        return plan

    @staticmethod
    def _contains_corr(e: Expression) -> bool:
        if isinstance(e, _CorrRef):
            return True
        if isinstance(e, ScalarFunc):
            return any(PlanBuilder._contains_corr(a) for a in e.args)
        return False

    def _build_semi_join(self, plan, scope, kind, lhs_ast, sub_sel):
        """Build the subquery's FROM+WHERE manually (join right side keeps
        the subquery's FROM schema), extracting correlated conjuncts into
        join conditions."""
        nl = len(plan.out_cols)
        self._outer_scopes.append(scope)
        try:
            subplan = self.build_from(sub_sel.from_)
            sub_scope = NameScope(subplan.out_cols)
            corr: list[Expression] = []
            local: list[Expression] = []
            if sub_sel.where is not None:
                for cj in self._ast_conjuncts(sub_sel.where):
                    for e in self.split_cnf(self.to_expr(cj, sub_scope)):
                        (corr if self._contains_corr(e) else local).append(e)
            if local:
                subplan = Selection(subplan, local)
            field_e = None
            if lhs_ast is not None:  # IN (SELECT <one expr> ...)
                if len(sub_sel.fields) != 1 or isinstance(sub_sel.fields[0], ast.Star):
                    raise TiDBError("Operand should contain 1 column(s)")
                field_e = self.to_expr(sub_sel.fields[0].expr, sub_scope)
                if self._contains_corr(field_e):
                    raise TiDBError("correlated expression in IN subquery select list is not supported")
        finally:
            self._outer_scopes.pop()

        def rewrite(e):
            # subquery-schema expr → concatenated (outer + inner) schema
            if isinstance(e, _CorrRef):
                return ECol(e.idx, e.ret_type, e.name)
            if isinstance(e, ECol):
                return ECol(e.idx + nl, e.ret_type, e.name)
            if isinstance(e, ScalarFunc):
                return ScalarFunc(e.sig, [rewrite(a) for a in e.args], e.ret_type)
            return e

        def side(e) -> str:
            cols = set()
            e.collect_columns(cols)
            if cols and max(cols) < nl:
                return "outer"
            if cols and min(cols) >= nl:
                return "inner"
            return "mixed"

        eq, other = [], []
        for c in corr:
            rc = rewrite(c)
            if isinstance(rc, ScalarFunc) and rc.sig.name == "eq":
                a, b = rc.args
                sa, sb = side(a), side(b)
                if {sa, sb} == {"outer", "inner"}:
                    eq.append((a, b) if sa == "outer" else (b, a))
                    continue
            other.append(rc)

        na_key = None
        if field_e is not None:
            from .optimizer import _shift_expr

            lhs = self.to_expr(lhs_ast, scope)
            rhs = _shift_expr(field_e, nl)
            if kind == "anti_in":
                na_key = (lhs, rhs)  # null-aware NOT IN key
            else:
                eq.append((lhs, rhs))

        join = Join(plan, subplan, "anti" if kind == "anti_in" else kind, eq, other, list(plan.out_cols))
        join.na_key = na_key
        return join

    def _order_expr(self, node, out_scope: NameScope, fields, in_scope, agg_ctx):
        """ORDER BY resolves against output aliases first, then input."""
        if isinstance(node, ast.Name):
            try:
                idx = out_scope.resolve(node)
                c = out_scope.cols[idx]
                return ECol(idx, c.ft, c.name)
            except (UnknownColumn, AmbiguousColumn):
                pass
        # match structurally identical select expr
        for i, f in enumerate(fields):
            if f.expr == node:
                c = out_scope.cols[i]
                return ECol(i, c.ft, c.name)
        raise TiDBError("ORDER BY expression must appear in select list (hidden-column sort lands later)")

    @staticmethod
    def _has_agg_in_order(order_by) -> bool:
        def walk(n):
            if isinstance(n, ast.Call):
                if n.name.lower() in AGG_FUNCS:
                    return True
                return any(walk(a) for a in n.args)
            return False

        return any(walk(b.expr) for b in order_by)

    def _build_agg(self, plan, scope, group_exprs, agg_ctx):
        cols = [PlanCol(f"g{i}", e.ret_type) for i, e in enumerate(group_exprs)]
        for i, a in enumerate(agg_ctx.aggs):
            cols.append(PlanCol(f"a{i}", a.ret_type))
        return Aggregation(plan, group_exprs, agg_ctx.aggs, cols)

    def to_expr_with_aliases(self, node, scope_w, agg_ctx, allow_window=False):
        if isinstance(node, ast.Name) and len(node.parts) == 1:
            hit = scope_w.find_alias(node.column)
            if hit is not None:
                return hit
        if isinstance(node, ast.Call):
            lname = node.name.lower()
            if lname in ("charset", "collation", "coercibility") and len(node.args) == 1:
                return self._type_meta_func(lname, self.to_expr(node.args[0], scope_w.base, agg_ctx))
            info_c = self._info_func(lname, node)
            if info_c is not None:
                return info_c
            if getattr(node, "over", None) is not None or lname in WINDOW_FUNCS:
                return self.to_expr(node, scope_w.base, agg_ctx, allow_window=allow_window)
            if lname in AGG_FUNCS:
                return agg_ctx.add_agg(node, scope_w.base)
            args = [self.to_expr_with_aliases(a, scope_w, agg_ctx, allow_window) for a in node.args]
            return make_func(lname, *args)
        return self.to_expr(node, scope_w.base, agg_ctx, allow_window=allow_window)

    @staticmethod
    def _field_name(e) -> str:
        if isinstance(e, ast.Name):
            return e.column
        if isinstance(e, ast.Call):
            return f"{e.name}(...)" if e.args else f"{e.name}()"
        if isinstance(e, ast.Lit):
            return str(e.value)
        return "expr"

    def _const_int(self, node) -> int:
        if isinstance(node, ast.Lit) and node.kind == "int":
            return node.value
        raise TiDBError("LIMIT expects an integer literal")

    def build_setop(self, s: ast.SetOpSelect) -> LogicalPlan:
        children = [self.build_select(x) for x in s.selects]
        n = len(children[0].out_cols)
        for c in children[1:]:
            if len(c.out_cols) != n:
                raise TiDBError("The used SELECT statements have a different number of columns")
        from ..expr.builtins import merge_types

        cols = []
        for i in range(n):
            fts = [c.out_cols[i].ft for c in children]
            cols.append(PlanCol(children[0].out_cols[i].name, merge_types(fts)))
        plan = SetOp(children, s.ops, cols)
        if any(op == "union" for op in s.ops):
            gb = [ECol(i, c.ft, c.name) for i, c in enumerate(cols)]
            plan = Aggregation(plan, gb, [], list(cols))
        if s.order_by:
            scope = NameScope(plan.out_cols)
            by = []
            for b in s.order_by:
                if isinstance(b.expr, ast.Lit) and b.expr.kind == "int":
                    i = b.expr.value - 1
                    by.append((ECol(i, plan.out_cols[i].ft, plan.out_cols[i].name), b.desc))
                else:
                    by.append((self.to_expr(b.expr, scope), b.desc))
            plan = Sort(plan, by)
        if s.limit is not None:
            plan = Limit(plan, self._const_int(s.limit), self._const_int(s.offset) if s.offset else 0)
        return plan


class ScopeWithAliases:
    def __init__(self, base: NameScope, fields, proj_exprs):
        self.base = base
        self.fields = fields
        self.proj_exprs = proj_exprs

    def find_alias(self, name: str):
        lname = name.lower()
        for f, e in zip(self.fields, self.proj_exprs):
            if f.alias and f.alias.lower() == lname:
                return e
        return None


class AggContext:
    """Collects aggregates during expression conversion and rewrites outer
    expressions to reference the Aggregation node's output."""

    def __init__(self, builder: PlanBuilder):
        self.builder = builder
        self.aggs: list[AggDesc] = []
        self._agg_exprs: list[Expression] = []  # placeholder per agg

    def add_agg(self, node: ast.Call, scope: NameScope) -> Expression:
        name = node.name.lower()
        args = []
        for a in node.args:
            if isinstance(a, ast.Star):  # COUNT(*)
                args = []
                break
            args.append(self.builder.to_expr(a, scope))
        desc = AggDesc.make(name, args, distinct=node.distinct)
        if getattr(node, "sep", None) is not None:
            desc.sep = node.sep
        if desc.name == "group_concat":
            svars = self.builder.context_info.get("vars") or {}
            desc.max_len = int(svars.get("group_concat_max_len", desc.max_len))
        # dedup identical aggregates
        for i, existing in enumerate(self.aggs):
            if repr(existing) == repr(desc):
                return _AggRef(i, existing.ret_type)
        self.aggs.append(desc)
        return _AggRef(len(self.aggs) - 1, desc.ret_type)

    def rewrite(self, e: Expression, group_exprs) -> Expression:
        """Rewrite an expression over the child schema into one over the
        Aggregation output schema: [group cols..., agg cols...]."""
        ngroups = len(group_exprs)

        def rec(x):
            if isinstance(x, _AggRef):
                return ECol(ngroups + x.agg_idx, x.ret_type, f"a{x.agg_idx}")
            # an expression structurally equal to a group-by expr → its col
            for gi, g in enumerate(group_exprs):
                if repr(x) == repr(g):
                    return ECol(gi, g.ret_type, f"g{gi}")
            if isinstance(x, ECol):
                # bare column not in group by: first_row semantics
                for i, a in enumerate(self.aggs):
                    if a.name == "first_row" and repr(a.args[0]) == repr(x):
                        return ECol(ngroups + i, a.ret_type, f"a{i}")
                desc = AggDesc.make("first_row", [x])
                self.aggs.append(desc)
                return ECol(ngroups + len(self.aggs) - 1, desc.ret_type, "fr")
            if isinstance(x, _WindowFuncExpr):
                d = x.desc
                return _WindowFuncExpr(
                    WinDesc(
                        d.name,
                        [rec(a) for a in d.args],
                        [rec(p) for p in d.part_by],
                        [(rec(o), dsc) for o, dsc in d.order_by],
                        d.ret_type,
                    )
                )
            if isinstance(x, ScalarFunc):
                return ScalarFunc(x.sig, [rec(a) for a in x.args], x.ret_type)
            return x

        return rec(e)


def _refs_table(node, name: str) -> bool:
    """Does this (set-op) select reference `name` as a table — in FROM or
    inside an expression subquery (EXISTS/IN/scalar)?"""
    nm = name.lower()

    def from_tree(f):
        if isinstance(f, ast.TableName):
            return f.db is None and f.name.lower() == nm
        if isinstance(f, ast.Join):
            return from_tree(f.left) or from_tree(f.right)
        if isinstance(f, ast.SubqueryTable):
            return walk(f.select)
        return False

    def expr_walk(e):
        if isinstance(e, ast.SubqueryExpr):
            return walk(e.select)
        if isinstance(e, ast.Call):
            return any(expr_walk(a) for a in e.args)
        if isinstance(e, ast.CaseWhen):
            parts = [e.operand, e.else_] + [x for pair in e.whens for x in pair]
            return any(expr_walk(x) for x in parts if x is not None)
        if isinstance(e, ast.Cast):
            return expr_walk(e.expr)
        return False

    def walk(s):
        if isinstance(s, ast.SetOpSelect):
            return any(walk(x) for x in s.selects)
        if s.from_ is not None and from_tree(s.from_):
            return True
        exprs = [s.where, s.having] + [f.expr for f in s.fields if not isinstance(f, ast.Star)]
        return any(expr_walk(e) for e in exprs if e is not None)

    return walk(node)


def sel_has_agg(sel) -> bool:
    def walk(n):
        if isinstance(n, ast.Call):
            if n.name.lower() in AGG_FUNCS and getattr(n, "over", None) is None:
                return True
            return any(walk(a) for a in n.args)
        if isinstance(n, ast.CaseWhen):
            parts = [n.operand, n.else_] + [x for pair in n.whens for x in pair]
            return any(walk(x) for x in parts if x is not None)
        if isinstance(n, ast.Cast):
            return walk(n.expr)
        return False  # SubqueryExpr: nested aggs belong to the inner scope

    return any(walk(f.expr) for f in sel.fields if not isinstance(f, ast.Star))


class _SeqExpr(Expression):
    """NEXTVAL/LASTVAL/SETVAL over a sequence — evaluated per ROW at
    runtime through the session hook (ref: expression/builtin_other.go
    nextVal/lastVal/setVal; a cached batch makes per-row calls cheap)."""

    def __init__(self, op: str, db: str, name: str, hook, arg: Expression | None = None):
        self.op = op
        self.db = db
        self.name = name
        self.hook = hook
        self.arg = arg
        self.ret_type = ft_longlong()

    def collect_columns(self, out):
        if self.arg is not None:
            self.arg.collect_columns(out)

    def pushable(self) -> bool:
        return False  # stateful: never ships to the device engine

    def eval(self, chunk):
        import numpy as np

        n = max(chunk.num_rows, 1)
        if self.op == "lastval":
            v = self.hook("lastval", self.db, self.name)
            if v is None:
                return np.zeros(n, np.int64), np.zeros(n, bool)
            return np.full(n, v, np.int64), np.ones(n, bool)
        if self.op == "setval":
            d, valid = self.arg.eval(chunk)
            d = np.asarray(d).reshape(-1)
            valid = np.asarray(valid).reshape(-1)
            out = np.zeros(n, np.int64)
            ok = np.zeros(n, bool)
            for i in range(n):
                di, vi = d[i % len(d)], valid[i % len(valid)]
                if vi:  # SETVAL(s, NULL) → NULL for that row
                    out[i] = self.hook("setval", self.db, self.name, int(di))
                    ok[i] = True
            return out, ok
        out = np.fromiter(
            (self.hook("nextval", self.db, self.name) for _ in range(n)), np.int64, n
        )
        return out, np.ones(n, bool)

    def __repr__(self):
        return f"{self.op}({self.db}.{self.name})"


class _CorrRef(Expression):
    """A correlated reference to a column of the enclosing query
    (ref: expression.CorrelatedColumn). Only valid during subquery builds;
    _build_semi_join rewrites it to an outer-schema Column."""

    def __init__(self, idx: int, ret_type, name: str):
        self.idx = idx
        self.ret_type = ret_type
        self.name = name

    def collect_columns(self, out):
        pass  # not a local column

    def eval(self, chunk):
        raise TiDBError(f"correlated reference {self.name!r} is not supported in this position")

    def __repr__(self):
        return f"corr({self.name}#{self.idx})"


class _WindowFuncExpr(Expression):
    """Placeholder for a window function call, lifted into a Window plan
    node by PlanBuilder._build_windows."""

    def __init__(self, desc: WinDesc):
        self.desc = desc
        self.ret_type = desc.ret_type

    def collect_columns(self, out):
        for e in self.desc.args + self.desc.part_by:
            e.collect_columns(out)
        for e, _ in self.desc.order_by:
            e.collect_columns(out)

    def __repr__(self):
        return f"win[{self.desc!r}]"


class _AggRef(Expression):
    """Placeholder node for a lifted aggregate, resolved by AggContext.rewrite."""

    def __init__(self, agg_idx: int, ret_type):
        self.agg_idx = agg_idx
        self.ret_type = ret_type

    def collect_columns(self, out):
        pass

    def __repr__(self):
        return f"aggref#{self.agg_idx}"

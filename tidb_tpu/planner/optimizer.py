"""Logical optimization rules (ref: planner/core/optimizer.go:67 rule list;
this implements the subset that drives the pushdown story: predicate
pushdown (rule_predicate_push_down.go) and column pruning
(rule_column_pruning.go). Agg/TopN/Limit pushdown decisions happen at
executor build where cop DAGs are assembled, mirroring how the reference
decides cop vs root in the task model).
"""

from __future__ import annotations

from ..expr.expression import Column as ECol, Constant, Expression, ScalarFunc, make_func
from .plans import Aggregation, DataSource, Dual, Join, Limit, LogicalPlan, Projection, Selection, SetOp, Sort, Window


def optimize(plan: LogicalPlan, stats=None, variables=None) -> LogicalPlan:
    # Column pruning is implicit in this architecture: the tile cache holds
    # whole-table columnar batches decoded once per version, host chunks
    # reference those arrays zero-copy, and the device engine ships only
    # lanes referenced by DAG expressions. The usage analysis below serves
    # index-covering decisions.
    plan = push_down_predicates(plan)
    plan = reorder_joins(plan, stats, variables)
    choose_access_paths(plan, stats, variables)
    return plan


# ------------------------------------------------------------- join reorder


def _remap_expr(e: Expression, mapping: dict) -> Expression:
    if isinstance(e, ECol):
        return ECol(mapping[e.idx], e.ret_type, e.name)
    if isinstance(e, ScalarFunc):
        return ScalarFunc(e.sig, [_remap_expr(a, mapping) for a in e.args], e.ret_type)
    return e


def _reorderable(n) -> bool:
    return (
        isinstance(n, Join)
        and n.kind in ("inner", "cross")
        and n.na_key is None
        and not getattr(n, "straight", False)  # STRAIGHT_JOIN pins order
        and all(isinstance(c, (DataSource, Join)) for c in n.children)
    )


def reorder_joins(root: LogicalPlan, stats=None, variables=None) -> LogicalPlan:
    """Greedy join reorder for inner-join groups over base tables (ref:
    planner/core/rule_join_reorder.go joinReorderGreedySolver): start
    from the smallest estimated leaf, repeatedly join the connected leaf
    with the smallest estimate (cartesian members last). The rebuilt tree
    is wrapped in a Projection restoring the original column order, so
    parents are unaffected."""

    def walk(n: LogicalPlan) -> LogicalPlan:
        # top-down: the MAXIMAL inner-join group must be flattened as one
        # unit — a bottom-up walk would rewrite the inner trio first and
        # hide the outer tables behind the restoring Projection
        if _reorderable(n) and any(_reorderable(c) for c in n.children):
            out = _reorder_group(n, stats, variables)
            if out is not None:
                # the group's leaves were not visited yet; a second pass
                # over the rebuilt tree is a no-op for the group itself
                # (greedy is deterministic) and descends into the leaves
                out.children = [walk(c) for c in out.children]
                return out
        n.children = [walk(c) for c in n.children]
        return n

    return walk(root)


def _leaf_estimate(ds, stats) -> float:
    if not isinstance(ds, DataSource):
        return 1000.0
    tstats = stats.get(ds.table.id) if stats is not None else None
    if tstats is None or tstats.row_count <= 0:
        return 1000.0
    from ..statistics.selectivity import estimate_conds

    total = float(tstats.row_count)
    if not ds.pushed_conds:
        return total
    return max(estimate_conds(tstats, ds.pushed_conds, ds.table.visible_columns()) * total, 1.0)


REORDER_STATS = {"dp": 0, "greedy": 0}  # observable algorithm choice


def _dp_order(leaves, est, edges):
    """Left-deep exhaustive order via subset DP minimizing the summed
    intermediate cardinality (ref: rule_join_reorder_dp.go); eq-join
    connectivity earns a flat reduction factor — the same signal the
    greedy solver ranks by, applied optimally."""
    n = len(leaves)
    conn = [[False] * n for _ in range(n)]
    for a, b in edges:
        conn[a][b] = conn[b][a] = True
    best: dict = {}
    for i in range(n):
        best[1 << i] = (0.0, float(est[i]), (i,))
    for mask in range(1, 1 << n):
        cur = best.get(mask)
        if cur is None:
            continue
        cost, rows, order = cur
        for j in range(n):
            if mask & (1 << j):
                continue
            joined = rows * float(est[j])
            if any(conn[i][j] for i in order):
                joined *= 0.1  # eq-join selectivity proxy
            nm = mask | (1 << j)
            nc = cost + joined
            if nm not in best or nc < best[nm][0]:
                best[nm] = (nc, joined, order + (j,))
    return list(best[(1 << n) - 1][2])


def _reorder_group(root: Join, stats, variables=None):
    # 1. flatten the maximal inner-join subtree into leaves + global conds
    leaves: list = []  # (node, old_offset, width)
    eq_conds: list = []  # (l_expr, r_expr) in OLD global coordinates
    other_conds: list = []

    def flatten(n, offset) -> int:
        if _reorderable(n):
            wl = flatten(n.children[0], offset)
            wr = flatten(n.children[1], offset + wl)
            for l, r in n.eq_conds:
                # l is over the left child schema (== global already for a
                # left-edge subtree at `offset`), r over the concat schema
                eq_conds.append((_shift_expr(l, offset), _shift_expr(r, offset)))
            for c in n.other_conds:
                other_conds.append(_shift_expr(c, offset))
            return wl + wr
        leaves.append((n, offset, len(n.out_cols)))
        return len(n.out_cols)

    total = flatten(root, 0)
    if len(leaves) < 3:
        return None

    # 2. leaf connectivity via eq conds + estimates
    def owner(idx: int) -> int:
        for i, (_, off, w) in enumerate(leaves):
            if off <= idx < off + w:
                return i
        return -1

    est = [_leaf_estimate(n, stats) for n, _, _ in leaves]
    edges: list = []  # (leaf_a, leaf_b) per eq cond
    for l, r in eq_conds:
        ls = {owner(i) for i in _cols_of(l)}
        rs = {owner(i) for i in _cols_of(r)}
        if len(ls) == 1 and len(rs) == 1 and ls != rs:
            edges.append((next(iter(ls)), next(iter(rs))))

    # 3. join order: small groups run the exhaustive subset-DP solver,
    # larger ones the greedy solver (ref: rule_join_reorder.go — DP when
    # n <= tidb_opt_join_reorder_threshold, default 0 = always greedy)
    threshold = int((variables or {}).get("tidb_opt_join_reorder_threshold", "0") or 0)
    if 0 < len(leaves) <= min(threshold, 12):
        order = _dp_order(leaves, est, edges)
        REORDER_STATS["dp"] += 1
    else:
        order = [min(range(len(leaves)), key=lambda i: est[i])]
        chosen = set(order)
        while len(order) < len(leaves):
            connected = [
                i for i in range(len(leaves)) if i not in chosen
                and any((a in chosen) != (b in chosen) and i in (a, b) for a, b in edges)
            ]
            pool = connected or [i for i in range(len(leaves)) if i not in chosen]
            nxt = min(pool, key=lambda i: est[i])
            order.append(nxt)
            chosen.add(nxt)
        REORDER_STATS["greedy"] += 1
    if order == list(range(len(leaves))):
        return None  # already optimal order: keep the original tree

    # 4. old→new global index mapping
    new_off = {}
    pos = 0
    for i in order:
        new_off[i] = pos
        pos += leaves[i][2]
    mapping = {}
    for i, (n, old, w) in enumerate(leaves):
        for k in range(w):
            mapping[old + k] = new_off[i] + k

    # 5. rebuild left-deep in the new order, attaching conds at the first
    # node where all their columns are bound
    pending_eq = [(_remap_expr(l, mapping), _remap_expr(r, mapping)) for l, r in eq_conds]
    pending_other = [_remap_expr(c, mapping) for c in other_conds]
    acc = leaves[order[0]][0]
    width = leaves[order[0]][2]
    for i in order[1:]:
        leaf, _, w = leaves[i]
        width += w
        take_eq, take_other = [], []
        rest_eq = []
        for l, r in pending_eq:
            lc, rc = _cols_of(l), _cols_of(r)
            # column-less sides (ON 1=1) bind immediately
            if max(lc | rc, default=-1) < width:
                lw = width - w
                if lc and rc and max(lc) < lw and min(rc) >= lw:
                    take_eq.append((l, r))
                elif lc and rc and max(rc) < lw and min(lc) >= lw:
                    take_eq.append((r, l))
                else:  # both sides inside one child / constant → filter
                    take_other.append(make_func("eq", l, r))
            else:
                rest_eq.append((l, r))
        pending_eq = rest_eq
        rest_other = []
        for c in pending_other:
            if max(_cols_of(c), default=-1) < width:
                take_other.append(c)
            else:
                rest_other.append(c)
        pending_other = rest_other
        cols = list(acc.out_cols) + list(leaf.out_cols)
        acc = Join(acc, leaf, "inner" if take_eq or take_other else "cross", take_eq, take_other, cols)

    # 6. restore the original column order for the parent
    exprs = [
        ECol(mapping[i], root.out_cols[i].ft, root.out_cols[i].name) for i in range(total)
    ]
    return Projection(acc, exprs, list(root.out_cols))


# --------------------------------------------------------------- predicates


def _shift_expr(e: Expression, delta: int) -> Expression:
    if isinstance(e, ECol):
        return ECol(e.idx + delta, e.ret_type, e.name)
    if isinstance(e, ScalarFunc):
        return ScalarFunc(e.sig, [_shift_expr(a, delta) for a in e.args], e.ret_type)
    return e


def _cols_of(e: Expression) -> set:
    s: set = set()
    e.collect_columns(s)
    return s


def _subst_proj(e: Expression, proj_exprs) -> Expression | None:
    """Rewrite an expr over a Projection's output into one over its input
    (substitute projected expressions). None if not substitutable."""
    if isinstance(e, ECol):
        return proj_exprs[e.idx]
    if isinstance(e, ScalarFunc):
        args = [_subst_proj(a, proj_exprs) for a in e.args]
        if any(a is None for a in args):
            return None
        return ScalarFunc(e.sig, args, e.ret_type)
    if isinstance(e, Constant):
        return e
    return None


def push_down_predicates(plan: LogicalPlan, conds: list[Expression] | None = None) -> LogicalPlan:
    conds = conds or []
    if isinstance(plan, Selection):
        child = push_down_predicates(plan.children[0], conds + plan.conds)
        return child  # all conds either pushed or re-materialized below

    if isinstance(plan, DataSource):
        pushable = [c for c in conds if c.pushable()]
        rest = [c for c in conds if not c.pushable()]
        plan.pushed_conds.extend(pushable)
        if rest:
            return Selection(plan, rest)
        return plan

    if isinstance(plan, Projection):
        down, keep = [], []
        for c in conds:
            s = _subst_proj(c, plan.exprs)
            if s is not None:
                down.append(s)
            else:
                keep.append(c)
        plan.children[0] = push_down_predicates(plan.children[0], down)
        if keep:
            return Selection(plan, keep)
        return plan

    if isinstance(plan, Join):
        nl = len(plan.children[0].out_cols)
        left_conds, right_conds, keep = [], [], []
        for c in conds:
            cols = _cols_of(c)
            if cols and max(cols) < nl and plan.kind in ("inner", "left", "semi", "anti"):
                left_conds.append(c)
            elif cols and min(cols) >= nl and plan.kind in ("inner", "right"):
                right_conds.append(_shift_expr(c, -nl))
            else:
                keep.append(c)
        # inner joins: other_conds referencing one side sink too
        if plan.kind == "inner":
            still_other = []
            for c in plan.other_conds:
                cols = _cols_of(c)
                if cols and max(cols) < nl:
                    left_conds.append(c)
                elif cols and min(cols) >= nl:
                    right_conds.append(_shift_expr(c, -nl))
                else:
                    still_other.append(c)
            plan.other_conds = still_other
        plan.children[0] = push_down_predicates(plan.children[0], left_conds)
        plan.children[1] = push_down_predicates(plan.children[1], right_conds)
        if keep:
            return Selection(plan, keep)
        return plan

    if isinstance(plan, (Aggregation, Sort, Limit, SetOp, Dual)):
        # conditions do not push through these (agg: having semantics differ;
        # limit/sort: row-count changing) — recurse children without conds
        plan.children = [push_down_predicates(c) for c in plan.children]
        if conds:
            return Selection(plan, conds)
        return plan

    plan.children = [push_down_predicates(c) for c in plan.children]
    if conds:
        return Selection(plan, conds)
    return plan


# ------------------------------------------------------- access path choice


def _analyze_usage(node: LogicalPlan, uses: dict):
    """Map each node's output columns back to (DataSource, visible-pos) and
    record which DataSource columns any expression reads. Returns the
    colmap for `node`'s output schema (None for derived columns)."""
    from ..expr.expression import Column as EC

    if isinstance(node, DataSource):
        u = uses.setdefault(id(node), set())
        for c in node.pushed_conds:
            u |= _cols_of(c)
        return [(node, i) for i in range(len(node.out_cols))]
    if isinstance(node, Dual):
        return [None] * len(node.out_cols)

    maps = [_analyze_usage(c, uses) for c in node.children]

    def mark(e: Expression, colmap):
        for i in _cols_of(e):
            m = colmap[i] if 0 <= i < len(colmap) else None
            if m is not None:
                uses[id(m[0])].add(m[1])

    if isinstance(node, Selection):
        for c in node.conds:
            mark(c, maps[0])
        return maps[0]
    if isinstance(node, Projection):
        for e in node.exprs:
            mark(e, maps[0])
        return [
            maps[0][e.idx] if isinstance(e, EC) and 0 <= e.idx < len(maps[0]) else None
            for e in node.exprs
        ]
    if isinstance(node, Aggregation):
        for e in node.group_by:
            mark(e, maps[0])
        for a in node.aggs:
            for arg in a.args:
                mark(arg, maps[0])
        out = [
            maps[0][e.idx] if isinstance(e, EC) and 0 <= e.idx < len(maps[0]) else None
            for e in node.group_by
        ]
        out += [None] * (len(node.out_cols) - len(out))
        return out
    if isinstance(node, Join):
        # eq_conds exprs reference the CONCATENATED schema (the executor
        # shifts right keys child-local at build time) — mark against cm
        cm = maps[0] + maps[1]
        for le, re_ in node.eq_conds:
            mark(le, cm)
            mark(re_, cm)
        for c in node.other_conds:
            mark(c, cm)
        if getattr(node, "na_key", None) is not None:
            mark(node.na_key[0], maps[0])
            mark(node.na_key[1], cm)
        if node.kind in ("semi", "anti"):
            return maps[0]  # output schema is the left side only
        return cm
    if isinstance(node, Window):
        for e in node.part_by:
            mark(e, maps[0])
        for e, _ in node.order_by:
            mark(e, maps[0])
        for f in node.funcs:
            for a in f.args:
                mark(a, maps[0])
        return maps[0] + [None] * len(node.funcs)
    if isinstance(node, Sort):
        for e, _ in node.by:
            mark(e, maps[0])
        return maps[0]
    if isinstance(node, Limit):
        return maps[0]
    if isinstance(node, SetOp):
        # outputs are merged across children: conservatively mark all
        for m in maps:
            for entry in m:
                if entry is not None:
                    uses[id(entry[0])].add(entry[1])
        return [None] * len(node.out_cols)
    # unknown node: conservative — everything below counts as used
    for m in maps:
        for entry in m:
            if entry is not None:
                uses[id(entry[0])].add(entry[1])
    return [None] * len(node.out_cols)


def choose_access_paths(root: LogicalPlan, stats=None, variables=None) -> None:
    """Pick per-DataSource access paths: PointGet / table handle ranges /
    covering IndexReader / IndexLookUp double read (ref: planner/core
    find_best_task.go skyline+cost pruning; here a deterministic heuristic
    until the statistics CBO lands)."""
    uses: dict = {}
    root_map = _analyze_usage(root, uses)
    for entry in root_map:
        if entry is not None:
            uses[id(entry[0])].add(entry[1])

    def walk(n: LogicalPlan):
        if isinstance(n, DataSource):
            _choose_for_ds(n, uses.get(id(n), set()), stats, variables)
        for c in n.children:
            walk(c)

    walk(root)


def _prune_partitions(table, conds, vis_by_off):
    """Partitions that can match the pushed conds' constraint on the
    partition column, or None = all (ref: partition_prune.go, simplified
    to eq/IN + one interval)."""
    from . import ranger

    part = table.partition
    pcol = table.col_by_name(part.col)
    pvis = vis_by_off.get(pcol.offset)
    if pvis is None or not conds:
        return None
    acc = ranger.collect_col_access(conds, {pvis: pcol.ft}).get(pvis)
    if acc is None:
        return None
    if acc.eq_seen:
        return part.prune(eq_values=[None if d.is_null else d.to_int() for d in acc.eq])
    lo = hi = None
    if acc.lo is not None:
        lo = acc.lo[0].to_int() + (0 if acc.lo[1] else 1)
    if acc.hi is not None:
        hi = acc.hi[0].to_int() - (0 if acc.hi[1] else 1)
    if lo is None and hi is None:
        return None
    return part.prune(lo=lo, hi=hi)


def _choose_for_ds(ds: DataSource, used: set, stats=None, variables=None) -> None:
    from . import ranger

    table = ds.table
    visible = table.visible_columns()
    vis_by_off = {c.offset: i for i, c in enumerate(visible)}
    ds.path = "table"
    ds.index = None
    ds.key_ranges = None
    ds.point_handles = None
    conds = ds.pushed_conds
    # prepared-plan-cache rebind info (PR 14): the pre-drop conjunct list
    # (which references the parameter-slot Constants) and the conds the
    # chosen path consumed — rebind_cached_ranges re-derives the
    # value-dependent access info from these after a slot rebind
    ds._rebind_conds = list(conds)
    ds._rebind_consumed = []
    tstats = stats.get(table.id) if stats is not None else None

    if table.partition is not None:
        # Partitioned table: table-scan path over (pruned) partitions.
        # Index/point paths stay off in v1 — indexes are partition-local
        # and handles don't identify a partition. Conds are NOT dropped:
        # pruning bounds which partitions are read, the filter still runs.
        ds.pruned_parts = _prune_partitions(table, conds, vis_by_off)
        return

    # 1. clustered pk → point handles / record ranges
    pk_vis = None
    if table.pk_is_handle:
        hc = table.handle_col()
        if hc is not None and hc.offset in vis_by_off:
            pk_vis = vis_by_off[hc.offset]
    # detection shared with the DML point path (session._scan_matching_rows)
    ha = ranger.detach_pk_handle_access(table, conds)
    if ha is not None and ha.point_handles is not None:
        ds.path = "point"
        ds.point_handles = ha.point_handles
        ds._rebind_consumed = list(ha.access_conds)
        _drop_conds(ds, ha.access_conds)
        return

    # 2. secondary indexes — gather candidates (USE_INDEX restricts,
    # IGNORE_INDEX excludes — ref: planner/core hint handling)
    use_hint = getattr(ds, "hint_use_index", None)
    ignore_hint = getattr(ds, "hint_ignore_index", None) or ()
    candidates = []  # (idx, ia, col_vis, covering)
    for idx in table.indexes:
        if idx.state != "public" or (table.pk_is_handle and idx.primary):
            continue
        lname = idx.name.lower()
        if use_hint is not None and lname not in use_hint:
            continue
        if lname in ignore_hint:
            continue
        col_vis, col_fts = [], []
        ok = True
        for off in idx.col_offsets:
            if off not in vis_by_off:
                ok = False
                break
            col_vis.append(vis_by_off[off])
            col_fts.append(table.columns[off].ft)
        if not ok:
            continue
        ia = ranger.detach_index_conditions(conds, table.id, idx.id, col_vis, col_fts)
        if ia is None:
            continue
        covered = set(col_vis)
        if pk_vis is not None:
            covered.add(pk_vis)
        remaining = [c for c in conds if not any(c is a for a in ia.access_conds)]
        need = set(used)
        for c in remaining:
            need |= _cols_of(c)
        candidates.append((idx, ia, col_vis, need <= covered))

    chosen = None
    if tstats is not None and tstats.row_count > 0 and candidates:
        # cost-based: est rows through the access conds vs full scan;
        # a double read pays a per-row lookup penalty (ref: find_best_task
        # cost model, coefficients simplified)
        from ..statistics.selectivity import estimate_conds

        total = float(tstats.row_count)
        best_cost = total  # full table scan
        for idx, ia, col_vis, covering in candidates:
            est = estimate_conds(tstats, ia.access_conds, visible) * total
            if not ia.ranges:
                est = 0.0
            cost = est * (1.1 if covering else 3.0)
            if cost < best_cost:
                best_cost = cost
                chosen = (idx, ia, covering)
    elif candidates:
        # no stats: deterministic heuristic — eq-prefix beats range-only;
        # range-only allowed only when covering (presumed unselective)
        best_score = 0
        for idx, ia, col_vis, covering in candidates:
            score = ia.eq_count * 2 + (1 if ia.has_range else 0)
            if idx.unique and ia.eq_count == len(idx.col_offsets):
                score += 100
            if ia.eq_count == 0 and not covering:
                continue
            if score > best_score:
                best_score = score
                chosen = (idx, ia, covering)

    if chosen is not None:
        idx, ia, covering = chosen
        ds.index = idx
        ds.key_ranges = ia.ranges
        ds.path = "index" if covering else "index_lookup"
        ds._rebind_consumed = list(ia.access_conds)
        _drop_conds(ds, ia.access_conds)
        return

    # 3. pk record ranges
    if ha is not None and ha.ranges is not None:
        ds.path = "table"
        ds.key_ranges = ha.ranges
        ds._rebind_consumed = list(ha.access_conds)
        _drop_conds(ds, ha.access_conds)
        return

    # 4. index merge: a top-level OR whose every disjunct is sargable on
    # some index (or is a pk point set) becomes a union of index reads +
    # one double read; the OR stays as a filter so each branch may
    # over-approximate its disjunct (ref: planner/core
    # indexmerge_path.go generateIndexMergeOrPaths, union type only).
    if (variables or {}).get("tidb_enable_index_merge", "ON") == "ON":
        _try_index_merge(ds, conds, table, visible, vis_by_off, pk_vis, tstats)


def _split_dnf(e) -> list:
    from ..expr.expression import ScalarFunc

    if isinstance(e, ScalarFunc) and e.sig.name == "or":
        return _split_dnf(e.args[0]) + _split_dnf(e.args[1])
    return [e]


def _split_cnf(e) -> list:
    from ..expr.expression import ScalarFunc

    if isinstance(e, ScalarFunc) and e.sig.name == "and":
        return _split_cnf(e.args[0]) + _split_cnf(e.args[1])
    return [e]


def _try_index_merge(ds, conds, table, visible, vis_by_off, pk_vis, tstats) -> None:
    from . import ranger

    or_cond = None
    for c in conds:
        if _split_dnf(c) != [c]:
            or_cond = c
            break
    if or_cond is None:
        return
    disjuncts = _split_dnf(or_cond)
    use_hint = getattr(ds, "hint_use_index", None)
    ignore_hint = getattr(ds, "hint_ignore_index", None) or ()
    indexes = []
    for idx in table.indexes:
        if idx.state != "public" or (table.pk_is_handle and idx.primary):
            continue
        lname = idx.name.lower()
        if use_hint is not None and lname not in use_hint:
            continue
        if lname in ignore_hint:
            continue
        col_vis, col_fts, ok = [], [], True
        for off in idx.col_offsets:
            if off not in vis_by_off:
                ok = False
                break
            col_vis.append(vis_by_off[off])
            col_fts.append(table.columns[off].ft)
        if ok:
            indexes.append((idx, col_vis, col_fts))

    branches = []  # ("index", idx, ranges) | ("points", handles)
    est_rows = 0.0
    for d in disjuncts:
        cnf = _split_cnf(d)
        best = None
        if pk_vis is not None:
            ha = ranger.detach_handle_conditions(cnf, table.id, pk_vis)
            if ha is not None and ha.point_handles is not None:
                best = ("points", ha.point_handles)
        if best is None:
            best_eq = -1
            for idx, col_vis, col_fts in indexes:
                ia = ranger.detach_index_conditions(cnf, table.id, idx.id, col_vis, col_fts)
                if ia is None or ia.eq_count == 0 and not ia.has_range:
                    continue
                if ia.eq_count > best_eq:
                    best_eq = ia.eq_count
                    best = ("index", idx, ia.ranges)
        if best is None:
            return  # one unsargable disjunct sinks the whole union
        if tstats is not None and tstats.row_count > 0:
            from ..statistics.selectivity import estimate_conds

            est_rows += estimate_conds(tstats, cnf, visible) * float(tstats.row_count)
        branches.append(best)
    if tstats is not None and tstats.row_count > 0 and est_rows > 0.5 * tstats.row_count:
        return  # union would read most of the table: plain scan is cheaper
    ds.path = "index_merge"
    ds.merge_branches = branches


def _drop_conds(ds: DataSource, consumed: list) -> None:
    ds.pushed_conds = [c for c in ds.pushed_conds if not any(c is a for a in consumed)]


# --------------------------- prepared-plan cache rebind (PR 14) ------------
#
# The statement-id plan cache (session._prepared_plan_for) reuses a built
# physical plan across COM_STMT_EXECUTE repeats by mutating the parameter
# slot Constants in place. Everything the executors evaluate at RUN time
# (filters, projections, join keys) follows the new values automatically;
# what does NOT is the access info `_choose_for_ds` derived from the OLD
# values at optimize time — point handles, key ranges, partition pruning.
# `rebind_cached_ranges` re-derives exactly those from the saved pre-drop
# conjuncts (ref: planner/core/plan_cache.go RebuildPlan4CachedPlan /
# rebuildRange). A rebind that would change the plan SHAPE — a different
# set of conds became (or stopped being) sargable, e.g. `pk = 1.5` where
# the first execution bound an exact int — returns False: the baked
# access/filter split no longer matches and the caller must replan.


def plan_rebindable(root: LogicalPlan) -> bool:
    """Is every DataSource in this plan a shape rebind_cached_ranges can
    re-derive? Index-merge unions (per-branch detachments) and sources
    that never went through choose_access_paths are not."""
    ok = True

    def walk(n: LogicalPlan) -> None:
        nonlocal ok
        if not ok:
            return
        if isinstance(n, DataSource):
            if getattr(n, "_rebind_conds", None) is None:
                ok = False
            elif getattr(n, "path", "table") not in (
                    "point", "table", "index", "index_lookup"):
                ok = False
        for c in n.children:
            walk(c)

    walk(root)
    return ok


def rebind_cached_ranges(root: LogicalPlan) -> bool:
    """Recompute the value-derived access info of a cached prepared plan
    after its parameter slots were rebound. True = plan is ready to
    execute; False = the new values change the plan shape, replan."""
    ok = True

    def walk(n: LogicalPlan) -> None:
        nonlocal ok
        if not ok:
            return
        if isinstance(n, DataSource):
            ok = _rebind_ds(n)
        for c in n.children:
            walk(c)

    walk(root)
    return ok


def _same_conds(a: list, b: list) -> bool:
    """Identity-set equality: the rebind consumed exactly the conds the
    original optimization consumed (so the filters left in the plan
    still cover everything the ranges don't)."""
    return len(a) == len(b) and all(any(x is y for y in b) for x in a)


def _rebind_ds(ds: DataSource) -> bool:
    from . import ranger

    conds = getattr(ds, "_rebind_conds", None)
    if conds is None:
        return False
    table = ds.table
    if table.partition is not None:
        # partitioned sources bake only the pruning verdict; conds were
        # never dropped, so re-pruning is the whole rebind
        visible = table.visible_columns()
        vis_by_off = {c.offset: i for i, c in enumerate(visible)}
        ds.pruned_parts = _prune_partitions(table, conds, vis_by_off)
        return True
    saved = getattr(ds, "_rebind_consumed", [])
    path = getattr(ds, "path", "table")
    if path == "point":
        ha = ranger.detach_pk_handle_access(table, conds)
        if ha is None or ha.point_handles is None or not _same_conds(ha.access_conds, saved):
            return False
        ds.point_handles = ha.point_handles
        return True
    if path in ("index", "index_lookup"):
        visible = table.visible_columns()
        vis_by_off = {c.offset: i for i, c in enumerate(visible)}
        col_vis, col_fts = [], []
        for off in ds.index.col_offsets:
            if off not in vis_by_off:
                return False
            col_vis.append(vis_by_off[off])
            col_fts.append(table.columns[off].ft)
        ia = ranger.detach_index_conditions(conds, table.id, ds.index.id, col_vis, col_fts)
        if ia is None or not _same_conds(ia.access_conds, saved):
            return False
        ds.key_ranges = ia.ranges
        return True
    if path == "table":
        if ds.key_ranges is None:
            # full scan + filters: nothing value-derived was baked, as
            # long as the original consumed nothing either
            return not saved
        ha = ranger.detach_pk_handle_access(table, conds)
        if ha is None or ha.ranges is None or not _same_conds(ha.access_conds, saved):
            return False
        ds.key_ranges = ha.ranges
        return True
    return False  # index_merge & anything new: replan

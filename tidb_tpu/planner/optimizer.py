"""Logical optimization rules (ref: planner/core/optimizer.go:67 rule list;
this implements the subset that drives the pushdown story: predicate
pushdown (rule_predicate_push_down.go) and column pruning
(rule_column_pruning.go). Agg/TopN/Limit pushdown decisions happen at
executor build where cop DAGs are assembled, mirroring how the reference
decides cop vs root in the task model).
"""

from __future__ import annotations

from ..expr.expression import Column as ECol, Constant, Expression, ScalarFunc
from .plans import Aggregation, DataSource, Dual, Join, Limit, LogicalPlan, Projection, Selection, SetOp, Sort


def optimize(plan: LogicalPlan) -> LogicalPlan:
    # Column pruning is implicit in this architecture: the tile cache holds
    # whole-table columnar batches decoded once per version, host chunks
    # reference those arrays zero-copy, and the device engine ships only
    # lanes referenced by DAG expressions. An explicit pruning pass returns
    # when index-path selection needs per-path column sets.
    return push_down_predicates(plan)


# --------------------------------------------------------------- predicates


def _shift_expr(e: Expression, delta: int) -> Expression:
    if isinstance(e, ECol):
        return ECol(e.idx + delta, e.ret_type, e.name)
    if isinstance(e, ScalarFunc):
        return ScalarFunc(e.sig, [_shift_expr(a, delta) for a in e.args], e.ret_type)
    return e


def _cols_of(e: Expression) -> set:
    s: set = set()
    e.collect_columns(s)
    return s


def _subst_proj(e: Expression, proj_exprs) -> Expression | None:
    """Rewrite an expr over a Projection's output into one over its input
    (substitute projected expressions). None if not substitutable."""
    if isinstance(e, ECol):
        return proj_exprs[e.idx]
    if isinstance(e, ScalarFunc):
        args = [_subst_proj(a, proj_exprs) for a in e.args]
        if any(a is None for a in args):
            return None
        return ScalarFunc(e.sig, args, e.ret_type)
    if isinstance(e, Constant):
        return e
    return None


def push_down_predicates(plan: LogicalPlan, conds: list[Expression] | None = None) -> LogicalPlan:
    conds = conds or []
    if isinstance(plan, Selection):
        child = push_down_predicates(plan.children[0], conds + plan.conds)
        return child  # all conds either pushed or re-materialized below

    if isinstance(plan, DataSource):
        pushable = [c for c in conds if c.pushable()]
        rest = [c for c in conds if not c.pushable()]
        plan.pushed_conds.extend(pushable)
        if rest:
            return Selection(plan, rest)
        return plan

    if isinstance(plan, Projection):
        down, keep = [], []
        for c in conds:
            s = _subst_proj(c, plan.exprs)
            if s is not None:
                down.append(s)
            else:
                keep.append(c)
        plan.children[0] = push_down_predicates(plan.children[0], down)
        if keep:
            return Selection(plan, keep)
        return plan

    if isinstance(plan, Join):
        nl = len(plan.children[0].out_cols)
        left_conds, right_conds, keep = [], [], []
        for c in conds:
            cols = _cols_of(c)
            if cols and max(cols) < nl and plan.kind in ("inner", "left"):
                left_conds.append(c)
            elif cols and min(cols) >= nl and plan.kind in ("inner", "right"):
                right_conds.append(_shift_expr(c, -nl))
            else:
                keep.append(c)
        # inner joins: other_conds referencing one side sink too
        if plan.kind == "inner":
            still_other = []
            for c in plan.other_conds:
                cols = _cols_of(c)
                if cols and max(cols) < nl:
                    left_conds.append(c)
                elif cols and min(cols) >= nl:
                    right_conds.append(_shift_expr(c, -nl))
                else:
                    still_other.append(c)
            plan.other_conds = still_other
        plan.children[0] = push_down_predicates(plan.children[0], left_conds)
        plan.children[1] = push_down_predicates(plan.children[1], right_conds)
        if keep:
            return Selection(plan, keep)
        return plan

    if isinstance(plan, (Aggregation, Sort, Limit, SetOp, Dual)):
        # conditions do not push through these (agg: having semantics differ;
        # limit/sort: row-count changing) — recurse children without conds
        plan.children = [push_down_predicates(c) for c in plan.children]
        if conds:
            return Selection(plan, conds)
        return plan

    plan.children = [push_down_predicates(c) for c in plan.children]
    if conds:
        return Selection(plan, conds)
    return plan

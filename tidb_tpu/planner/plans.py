"""Logical plan nodes (ref: planner/core logical ops — compact redesign).

Every node carries an output schema: a list of PlanCol. Expressions inside
nodes reference child output by offset (expr.Column.idx), with join
children concatenated left-then-right.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..catalog.schema import TableInfo
from ..expr.expression import Expression
from ..expr.aggregation import AggDesc
from ..mysqltypes.field_type import FieldType


@dataclass
class PlanCol:
    name: str
    ft: FieldType
    table_alias: str = ""
    orig_offset: int = -1  # offset in the base table (DataSource only)


class LogicalPlan:
    children: list
    out_cols: list[PlanCol]

    def __init__(self, children, out_cols):
        self.children = children
        self.out_cols = out_cols

    def pretty(self, indent=0) -> str:
        pad = "  " * indent
        s = pad + self.describe()
        for c in self.children:
            s += "\n" + c.pretty(indent + 1)
        return s

    def describe(self) -> str:
        return type(self).__name__


class DataSource(LogicalPlan):
    def __init__(self, table: TableInfo, alias: str, cols: list[PlanCol]):
        super().__init__([], cols)
        self.table = table
        self.alias = alias
        self.pushed_conds: list[Expression] = []

    def describe(self):
        s = f"DataSource({self.alias or self.table.name})"
        path = getattr(self, "path", "table")
        if path == "point":
            s += f" point:{self.point_handles!r}"
        elif path in ("index", "index_lookup"):
            kind = "IndexReader" if path == "index" else "IndexLookUp"
            s += f" {kind}({self.index.name}, {len(self.key_ranges)} ranges)"
        elif path == "index_merge":
            names = [b[1].name if b[0] == "index" else "pk" for b in self.merge_branches]
            s += f" IndexMerge({', '.join(names)})"
        elif getattr(self, "key_ranges", None) is not None:
            s += f" handle_ranges:{len(self.key_ranges)}"
        if self.pushed_conds:
            s += f" pushed:{self.pushed_conds!r}"
        return s


class Selection(LogicalPlan):
    def __init__(self, child, conds: list[Expression]):
        super().__init__([child], child.out_cols)
        self.conds = conds

    def describe(self):
        return f"Selection{self.conds!r}"


class Projection(LogicalPlan):
    def __init__(self, child, exprs: list[Expression], cols: list[PlanCol]):
        super().__init__([child], cols)
        self.exprs = exprs

    def describe(self):
        return f"Projection{self.exprs!r}"


class Aggregation(LogicalPlan):
    def __init__(self, child, group_by: list[Expression], aggs: list[AggDesc], cols: list[PlanCol]):
        super().__init__([child], cols)
        self.group_by = group_by
        self.aggs = aggs

    def describe(self):
        return f"Aggregation(group={self.group_by!r}, aggs={self.aggs!r})"


class Join(LogicalPlan):
    def __init__(self, left, right, kind: str, eq_conds, other_conds, cols):
        super().__init__([left, right], cols)
        self.kind = kind  # inner | left | right | cross | semi | anti
        self.eq_conds = eq_conds  # [(left_expr, right_expr)] over the concatenated schema
        self.other_conds = other_conds  # over concatenated schema
        # null-aware NOT IN key pair (lhs over left schema, rhs over
        # concatenated schema); only set on anti joins built from NOT IN
        self.na_key = None

    def describe(self):
        return f"Join({self.kind}, eq={self.eq_conds!r}, other={self.other_conds!r})"


class Window(LogicalPlan):
    """Window functions over one PARTITION BY / ORDER BY spec (ref:
    planner/core PhysicalWindow; executor/window.go:31). Output = child
    columns followed by one column per window function; several specs in
    one query stack several Window nodes."""

    def __init__(self, child, part_by: list[Expression], order_by, funcs, cols):
        super().__init__([child], cols)
        self.part_by = part_by
        self.order_by = order_by  # [(Expression, desc)]
        self.funcs = funcs  # list[WinDesc]

    def describe(self):
        return (
            f"Window(partition={self.part_by!r}, order={[(repr(e), d) for e, d in self.order_by]!r}, "
            f"funcs={[f.name for f in self.funcs]!r})"
        )


class Sort(LogicalPlan):
    def __init__(self, child, by: list[tuple[Expression, bool]]):
        super().__init__([child], child.out_cols)
        self.by = by

    def describe(self):
        return f"Sort{[(repr(e), d) for e, d in self.by]!r}"


class Limit(LogicalPlan):
    def __init__(self, child, count: int, offset: int = 0):
        super().__init__([child], child.out_cols)
        self.count = count
        self.offset = offset

    def describe(self):
        return f"Limit({self.count}, offset={self.offset})"


class Dual(LogicalPlan):
    """One-row no-table source (SELECT 1)."""

    def __init__(self):
        super().__init__([], [])


class Memtable(LogicalPlan):
    """Virtual table materialized from in-memory state at read time
    (ref: infoschema memtable framework, tables.go)."""

    def __init__(self, name: str, provider, cols):
        super().__init__([], cols)
        self.name = name
        self.provider = provider  # callable() -> list[list[Datum]]

    def describe(self):
        return f"Memtable({self.name})"


class CTEStorage:
    """Shared buffer between a RecursiveCTE producer and its CTERef readers
    (ref: util/cteutil storage)."""

    def __init__(self):
        self.chunk = None  # current iteration's working chunk


class CTERef(LogicalPlan):
    """Reads the recursive CTE's working table inside the recursive branch
    (ref: executor/cte_table_reader.go CTETableReaderExec)."""

    def __init__(self, name: str, storage: CTEStorage, cols):
        super().__init__([], cols)
        self.name = name
        self.storage = storage

    def describe(self):
        return f"CTERef({self.name})"


class RecursiveCTE(LogicalPlan):
    """WITH RECURSIVE: seed plan UNION [ALL] recursive plan iterated to a
    fixpoint (ref: executor/cte.go:60 CTEExec)."""

    def __init__(self, name: str, seed, recursive, storage: CTEStorage, distinct: bool, cols):
        super().__init__([seed, recursive], cols)
        self.name = name
        self.storage = storage
        self.distinct = distinct  # UNION vs UNION ALL between iterations

    def describe(self):
        return f"RecursiveCTE({self.name}, {'union' if self.distinct else 'union_all'})"


class SetOp(LogicalPlan):
    def __init__(self, children, ops: list[str], cols):
        super().__init__(children, cols)
        self.ops = ops  # 'union' | 'union_all' | 'except' | 'intersect'

    def describe(self):
        return f"SetOp({self.ops})"

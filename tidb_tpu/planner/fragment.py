"""MPP fragment slicing (ref: planner/core/fragment.go:64
GenerateRootMPPTasks, :202 buildFragments; exchange types in
plan_to_pb.go:229).

The reference slices a physical plan into fragments at ExchangeSender/
ExchangeReceiver boundaries and dispatches each fragment to TiFlash
stores, with hash/broadcast chunk exchange over gRPC tunnels
(cophandler/mpp_exec.go:109). The TPU-native redesign keeps the same
*logical* slicing — this module produces the fragment tree — but the
fragments do not become separate processes: the whole tree compiles into
ONE SPMD program over a `jax.sharding.Mesh` (parallel/mpp.py), where an
ExchangeSender(hash) is an `all_to_all` collective over the mesh axis and
ExchangeSender(broadcast) is a replicated operand. XLA then fuses and
overlaps compute with ICI communication — the fusion boundary the
reference pays a serialization+network cost for disappears.

Eligibility here mirrors `CanExprsPushDown` + mppTask checks
(planner/core/task.go:2088): inner/left equi-joins on integer-typed keys,
scans without index paths, device-lowerable conditions.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field

from ..expr.expression import Column as ExprCol, Expression
from ..mysqltypes.field_type import FieldType
from .plans import Aggregation, DataSource, Join, LogicalPlan, Projection, Selection

# exchange modes (ref: tipb ExchangeType)
HASH = "hash"
BROADCAST = "broadcast"
PASSTHROUGH = "passthrough"
# PR 11 fused chains: a LUT-specialized join level needs NO exchange at
# all — the device-resident build structure (and the build lanes behind
# it) is replicated to every device, the sharded stream probes in place.
# Distinct from BROADCAST so EXPLAIN/tests can tell "replicated because
# small" from "replicated because the resident structure lives there".
LOCAL = "local"


@dataclass
class ScanFrag:
    """A leaf fragment: one table scan with pushed-down conditions."""

    ds: DataSource
    side_offset: int  # where this scan's columns start in the joined schema

    @property
    def n_cols(self) -> int:
        return len(self.ds.out_cols)


@dataclass
class JoinFrag:
    """A join fragment: probe child (sharded stream) ⋈ build child (scan).

    `exchange` is decided at compile time from build-side cardinality:
    BROADCAST replicates the build lanes to every device (all_gather
    analog); HASH repartitions both sides by join key (all_to_all)."""

    probe: "JoinFrag | ScanFrag"
    build: ScanFrag
    kind: str  # inner | left
    probe_keys: list[int]  # joined-schema column indices
    build_keys: list[int]
    post_conds: list[Expression] = field(default_factory=list)
    exchange: str = BROADCAST


@dataclass
class MPPPlan:
    root: JoinFrag
    scans: list[ScanFrag]
    agg: Aggregation | None  # fused partial aggregation, if any
    out_cols: list  # joined schema (probe cols then build cols, leftmost first)
    join_node: Join = None  # original plan node (host fallback path)
    # fused ORDER BY <agg output> LIMIT k (ref: pushed TopN over the MPP
    # gather, planner/core/task.go attach2Task TopN pushdown): set by the
    # Limit(Sort(...)) builder when the sort key is a single sum/count
    # aggregate. Enables the sorted (wide-key) device agg mode, whose
    # output is k groups per device instead of the joined rows.
    topn: tuple | None = None  # (agg_idx, desc: bool, k: int)

    def explain(self, indent: int = 0) -> str:
        """Fragment-tree rendering for EXPLAIN (sender/receiver parity)."""
        lines: list[str] = []
        if self.agg is not None:
            lines.append("PartialAggregation(psum)")
        def walk(f, depth):
            pad = "  " * depth
            if isinstance(f, ScanFrag):
                lines.append(f"{pad}ExchangeSender({PASSTHROUGH})")
                lines.append(f"{pad}  TableScan({f.ds.alias or f.ds.table.name})")
                return
            lines.append(f"{pad}HashJoin({f.kind})")
            walk(f.probe, depth + 1)
            lines.append(f"{pad}  ExchangeReceiver")
            lines.append(f"{pad}    ExchangeSender({f.exchange})")
            lines.append(f"{pad}      TableScan({f.build.ds.alias or f.build.ds.table.name})")
        walk(self.root, 1 if self.agg else 0)
        return "\n".join(lines)


def _int_key(ft: FieldType) -> bool:
    """Join keys must be integer-shaped on device: ints, dates/times
    (packed int64), decimals (scaled int64). Floats (inexact) and strings
    (per-table dict codes are not comparable across tables) fall back."""
    return not ft.is_float() and not ft.is_string()


def _plain_scan(ds: DataSource) -> bool:
    """Mesh gathers read whole-table lanes: a scan whose access path
    consumed conditions into key_ranges (PK handle ranges, index paths)
    must stay on the host readers or rows filtered by ranges would leak
    back in."""
    if ds.table.partition is not None:
        return False  # partitioned rows live in per-partition keyspaces
    return getattr(ds, "path", "table") == "table" and getattr(ds, "key_ranges", None) is None


def _fold_selection(node: LogicalPlan):
    """Selection(DataSource) → DataSource with conds folded into pushed.

    Works on a shallow COPY of the DataSource: slicing is an eligibility
    probe that may be declined (or run twice when try_build_mpp fires at
    nested nodes), so the shared plan tree must stay untouched."""
    if isinstance(node, Selection) and isinstance(node.children[0], DataSource):
        ds = copy.copy(node.children[0])
        ds.pushed_conds = list(ds.pushed_conds) + list(node.conds)
        return ds
    return node


def _peel_identity_projection(node: LogicalPlan) -> LogicalPlan:
    """The optimizer roots every SELECT with a Projection; when it is the
    identity over its child's schema it is a no-op for slicing, so peel it
    (mirrors eliminatePhysicalProjection, ref planner/core/optimizer.go:196)."""
    while isinstance(node, Projection):
        exprs = node.exprs
        child = node.children[0]
        if len(exprs) != len(child.out_cols):
            break
        if not all(isinstance(e, ExprCol) and e.idx == i for i, e in enumerate(exprs)):
            break
        node = child
    return node


def _note_reason(reason, key: str, detail: str, node=None) -> None:
    """Record the FIRST slice-decline reason (typed key + human detail +
    the Join node whose keys failed) for the enforce_mpp warning /
    fallback accounting — later, inner declines of the same slicing
    attempt don't overwrite it. The failing NODE lets the caller count
    one decline per statement even when an outer Join's slice fails on an
    inner Join's keys and the host build then retries that inner Join."""
    if reason is not None and not reason:
        reason.append((key, detail, node))


def _slice_join(node: Join, offset: int, scans: list[ScanFrag], reason=None):
    """Left-deep join tree → JoinFrag tree; None if ineligible."""
    if node.kind not in ("inner", "left"):
        return None, offset
    left, right = (_fold_selection(c) for c in node.children)
    # probe side: nested join or scan; build side: scan only (left-deep)
    if isinstance(left, Join):
        probe, offset = _slice_join(left, offset, scans, reason)
        if probe is None:
            return None, offset
    elif isinstance(left, DataSource):
        if not _plain_scan(left):
            return None, offset
        probe = ScanFrag(left, offset)
        scans.append(probe)
        offset += probe.n_cols
    else:
        return None, offset
    if not (isinstance(right, DataSource) and _plain_scan(right)):
        return None, offset
    build = ScanFrag(right, offset)
    scans.append(build)
    offset += build.n_cols

    if not node.eq_conds:
        return None, offset  # cross join: no MPP
    pk, bk = [], []
    for le, re in node.eq_conds:
        if not (isinstance(le, ExprCol) and isinstance(re, ExprCol)):
            _note_reason(reason, "non_column_join_key", "non-column join key", node)
            return None, offset
        if not (_int_key(le.ret_type) and _int_key(re.ret_type)):
            if le.ret_type.is_string() or re.ret_type.is_string():
                _note_reason(reason, "string_join_key", "string join key", node)
            elif le.ret_type.is_float() or re.ret_type.is_float():
                _note_reason(reason, "float_join_key", "float join key", node)
            else:
                _note_reason(reason, "non_int_join_key", "non-integer join key", node)
            return None, offset
        # eq_conds are over the concatenated schema; build side is the
        # right child, i.e. indices >= build.side_offset
        a, b = (le, re) if le.idx < build.side_offset else (re, le)
        if a.idx >= build.side_offset or b.idx < build.side_offset:
            return None, offset
        pk.append(a.idx)
        bk.append(b.idx)
    return JoinFrag(probe, build, node.kind, pk, bk, list(node.other_conds)), offset


def slice_plan(plan: LogicalPlan, reason: list | None = None) -> MPPPlan | None:
    """Try to slice an optimized plan (sub)tree into an MPP fragment plan.

    Accepted roots: Aggregation(JoinTree) — fully fused partial-agg
    program; JoinTree — joined-rows program (host operators continue on
    top). Returns None when the shape/types don't qualify; caller falls
    back to the root HashJoin path. `reason` (optional list) receives one
    `(typed_key, detail)` pair describing the FIRST decline — the
    enforce_mpp warning / tidb_tpu_fallback_total surface."""
    agg = None
    node = _peel_identity_projection(plan)
    if isinstance(node, Aggregation) and isinstance(node.children[0], (Join, Selection)):
        inner = _fold_selection(node.children[0])
        if isinstance(inner, Join):
            agg = node
            node = inner
    if not isinstance(node, Join):
        return None
    scans: list[ScanFrag] = []
    root, _ = _slice_join(node, 0, scans, reason)
    if root is None:
        return None
    if agg is not None:
        for a in agg.aggs:
            if a.name not in ("count", "sum", "avg", "min", "max") or a.distinct:
                return None
    return MPPPlan(root, scans, agg, list(node.out_cols), node)

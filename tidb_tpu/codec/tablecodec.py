"""Table/index key layout (ref: tablecodec/tablecodec.go:49-50,94).

  record: t{tableID}_r{handle}
  index : t{tableID}_i{indexID}{encoded values}[{encoded handle}]

IDs/handles use the sign-flipped big-endian int encoding so byte order is
numeric order, making region split points and range scans trivial.
"""

from __future__ import annotations

import struct

_SIGN = 0x8000000000000000


def _cint(v: int) -> bytes:
    return struct.pack(">Q", (v + _SIGN) & 0xFFFFFFFFFFFFFFFF)


def _dint(b: bytes) -> int:
    return struct.unpack(">Q", b)[0] - _SIGN


def table_prefix(table_id: int) -> bytes:
    return b"t" + _cint(table_id)


def record_prefix(table_id: int) -> bytes:
    return b"t" + _cint(table_id) + b"_r"


def record_key(table_id: int, handle: int) -> bytes:
    return b"t" + _cint(table_id) + b"_r" + _cint(handle)


def decode_record_handle(key: bytes) -> int:
    return _dint(key[11:19])


def index_prefix(table_id: int, index_id: int) -> bytes:
    return b"t" + _cint(table_id) + b"_i" + _cint(index_id)


def index_key(table_id: int, index_id: int, encoded_vals: bytes, handle: int | None = None) -> bytes:
    k = index_prefix(table_id, index_id) + encoded_vals
    if handle is not None:
        k += _cint(handle)
    return k


def decode_index_handle(key: bytes) -> int:
    """Handle is the trailing 8 bytes of a non-unique index key."""
    return _dint(key[-8:])


def is_record_key(key: bytes) -> bool:
    return len(key) >= 19 and key[:1] == b"t" and key[9:11] == b"_r"


def decode_table_id(key: bytes) -> int:
    return _dint(key[1:9])

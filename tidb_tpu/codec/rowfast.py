"""Vectorized batch row codec — "row format v2" (ref: util/rowcodec, whose
compact v2 format exists for exactly this reason: decoding straight into
columnar chunks without per-cell work; see also unistore's ChunkDecoder,
store/mockstore/unistore/cophandler/cop_handler.go:207).

The v1 codec (codec/row.py) is varint-tagged and inherently sequential.
This v2 layout is designed so a whole batch encodes/decodes with numpy
gathers — no per-cell Python:

  0x81                               (version flag; v1 rows start with an
                                      even zigzag-varint byte, so 0x81 is
                                      unambiguous)
  u8   ncols
  u8   nfix                          (fixed 8-byte cols; stored first)
  i32  col_id  x ncols               (little-endian)
  u8   kind    x ncols               (datum kinds; fixed kinds first)
  u8   scale   x ncols               (decimal scale, else 0)
  u16  vwidth  x (ncols - nfix)      (batch-padded byte width per varlen col)
  u8   nullbits x ceil(ncols/8)      (bit set = NULL)
  i64  payload x nfix                (scaled ints / raw float bits; zeros
                                      when NULL)
  per varlen col: u32 len + vwidth bytes (zero-padded; len 0 when NULL)

Varlen fields are padded to the batch max width, so EVERY row of a batch
has the same byte length: a batch encodes as one (n, row_len) uint8 matrix
with zero per-row work, and decodes as a reshape + fixed-offset slices.
(The padding trades bytes for bandwidth — the store is an in-memory
columnar replica, not a disk format, so decode throughput wins.)
"""

from __future__ import annotations

import numpy as np

from ..mysqltypes.datum import (
    Datum,
    K_BYTES,
    K_DEC,
    K_DUR,
    K_FLOAT,
    K_INT,
    K_STR,
    K_TIME,
    K_UINT,
)
from ..mysqltypes.mydecimal import Dec

V2_FLAG = 0x81

FIXED_KINDS = (K_INT, K_UINT, K_FLOAT, K_DEC, K_TIME, K_DUR)
VARLEN_KINDS = (K_STR, K_BYTES)

_SIGN = np.uint64(1 << 63)


# --- little vector helpers ---------------------------------------------------


def _ragged_scatter(dst: np.ndarray, starts: np.ndarray, lens: np.ndarray, src: np.ndarray) -> None:
    """dst[starts[i] + j] = src bytes of run i, for j < lens[i]."""
    total = int(lens.sum())
    if total == 0:
        return
    flat0 = np.zeros(len(lens), dtype=np.int64)
    np.cumsum(lens[:-1], out=flat0[1:])
    within = np.arange(total, dtype=np.int64) - np.repeat(flat0, lens)
    dst[np.repeat(starts, lens) + within] = src


def _ragged_gather(src: np.ndarray, starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Concatenate src[starts[i] : starts[i]+lens[i]] runs into one array."""
    total = int(lens.sum())
    if total == 0:
        return np.empty(0, dtype=src.dtype)
    flat0 = np.zeros(len(lens), dtype=np.int64)
    np.cumsum(lens[:-1], out=flat0[1:])
    within = np.arange(total, dtype=np.int64) - np.repeat(flat0, lens)
    return src[np.repeat(starts, lens) + within]


def _to_bytes_matrix(arr: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """String-ish column → (u8 matrix [n, w], lens [n]) of utf8 payloads."""
    if arr.dtype.kind == "S":
        s = arr
    elif arr.dtype.kind == "U":
        s = np.char.encode(arr, "utf8")
    else:  # object array of str/bytes
        try:
            s = arr.astype("S")  # ascii fast path
        except UnicodeEncodeError:
            enc = [v.encode("utf8") if isinstance(v, str) else (v or b"") for v in arr]
            s = np.array(enc, dtype="S")
    w = max(s.dtype.itemsize, 1)
    mat = s.view(np.uint8).reshape(len(s), w) if s.dtype.itemsize else np.zeros((len(s), 1), np.uint8)
    lens = (mat != 0).astype(np.int64)
    # length = position after last non-zero byte (SQL CHAR payloads have no
    # embedded NULs; padded tail is zeros)
    lens = w - np.argmax(lens[:, ::-1], axis=1)
    lens[~mat.any(axis=1)] = 0
    return mat, lens


def split_buffer(buf, offsets: np.ndarray) -> list[bytes]:
    """Slice one big buffer into per-row bytes. offsets has n+1 entries."""
    if isinstance(buf, np.ndarray):
        buf = buf.tobytes()
    offs = offsets.tolist()
    return [buf[a:b] for a, b in zip(offs[:-1], offs[1:])]


# --- encode ------------------------------------------------------------------


def encodable_kinds(kinds: list[int]) -> bool:
    # K_BYTES is excluded: the batch encoder's trailing-NUL length heuristic
    # (_to_bytes_matrix) would silently truncate binary values ending in
    # 0x00 — those rows take the per-row v1 path instead. (K_STR shares the
    # heuristic but SQL CHAR/VARCHAR text does not carry trailing NULs.)
    return all(k in FIXED_KINDS or k == K_STR for k in kinds)


def encode_rows_v2(
    col_ids: list[int],
    kinds: list[int],
    scales: list[int],
    arrays: list[np.ndarray],
    valids: list[np.ndarray | None] | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Encode n rows given per-column numpy arrays.

    Fixed-kind arrays must be integer/float numpy arrays (K_DEC arrays are
    the already-scaled int64 values at `scale`). Varlen arrays may be 'S',
    'U', or object dtype. Returns (u8 buffer array, offsets[n+1]); rows are
    uniform-length so offsets is simply arange * row_len.
    """
    n = len(arrays[0]) if arrays else 0
    order = sorted(range(len(kinds)), key=lambda i: (kinds[i] in VARLEN_KINDS, i))
    ids = [col_ids[i] for i in order]
    kds = [kinds[i] for i in order]
    scs = [scales[i] for i in order]
    arrs = [arrays[i] for i in order]
    vlds = [None if valids is None else valids[i] for i in order]
    ncols = len(ids)
    nfix = sum(1 for k in kds if k in FIXED_KINDS)
    nb = (ncols + 7) // 8

    # varlen block prep (need widths for the header)
    vmats: list[tuple[np.ndarray, np.ndarray]] = []
    for k, arr, v in zip(kds, arrs, vlds):
        if k not in VARLEN_KINDS:
            continue
        mat, lens = _to_bytes_matrix(arr)
        if v is not None and not v.all():
            lens = np.where(v, lens, 0)
            mat = np.where(v[:, None], mat, 0)
        vmats.append((mat, lens))

    header = bytearray([V2_FLAG, ncols, nfix])
    header += np.asarray(ids, dtype="<i4").tobytes()
    header += bytes(kds)
    header += bytes(scs)
    header += np.asarray([m.shape[1] for m, _ in vmats], dtype="<u2").tobytes()
    hlen = len(header)
    fixed_off = hlen + nb
    row_len = fixed_off + 8 * nfix + sum(4 + m.shape[1] for m, _ in vmats)

    out = np.zeros((n, row_len), dtype=np.uint8)
    out[:, :hlen] = np.frombuffer(bytes(header), dtype=np.uint8)
    # null bitmap
    for ci, v in enumerate(vlds):
        if v is not None and not v.all():
            out[:, hlen + ci // 8] |= (~v).astype(np.uint8) << (ci % 8)
    # fixed payload block
    if nfix:
        fix = np.zeros((n, nfix), dtype=np.int64)
        fi = 0
        for k, arr, v in zip(kds, arrs, vlds):
            if k not in FIXED_KINDS:
                continue
            if k == K_FLOAT:
                col = np.ascontiguousarray(arr, dtype=np.float64).view(np.int64)
            elif k == K_UINT:
                col = np.ascontiguousarray(arr, dtype=np.uint64).view(np.int64)
            else:
                col = np.asarray(arr).astype(np.int64, copy=False)
            if v is not None and not v.all():
                col = np.where(v, col, 0)
            fix[:, fi] = col
            fi += 1
        out[:, fixed_off : fixed_off + 8 * nfix] = fix.view(np.uint8).reshape(n, 8 * nfix)
    # varlen cols: u32 len + padded payload, all fixed offsets
    cur = fixed_off + 8 * nfix
    for mat, lens in vmats:
        w = mat.shape[1]
        out[:, cur : cur + 4] = lens.astype("<u4").view(np.uint8).reshape(n, 4)
        out[:, cur + 4 : cur + 4 + w] = mat
        cur += 4 + w
    offsets = np.arange(n + 1, dtype=np.int64) * row_len
    return out.reshape(-1), offsets


# --- single-row decode (point-get path) --------------------------------------


def decode_row_v2(data: bytes) -> dict[int, Datum]:
    u = np.frombuffer(data, dtype=np.uint8)
    ncols, nfix = int(u[1]), int(u[2])
    nvar = ncols - nfix
    p = 3
    ids = u[p : p + 4 * ncols].view("<i4").tolist()
    p += 4 * ncols
    kds = u[p : p + ncols].tolist()
    p += ncols
    scs = u[p : p + ncols].tolist()
    p += ncols
    widths = u[p : p + 2 * nvar].view("<u2").tolist()
    p += 2 * nvar
    nb = (ncols + 7) // 8
    nulls = u[p : p + nb]
    p += nb
    fix = u[p : p + 8 * nfix].view("<i8")
    p += 8 * nfix
    out: dict[int, Datum] = {}
    fi = 0
    vi = 0
    pos = p
    for ci in range(ncols):
        k, cid, sc = kds[ci], ids[ci], scs[ci]
        is_null = bool((nulls[ci // 8] >> (ci % 8)) & 1)
        if k in FIXED_KINDS:
            raw = int(fix[fi])
            fi += 1
            if is_null:
                out[cid] = Datum.null()
            elif k == K_FLOAT:
                out[cid] = Datum.f(float(np.int64(raw).view(np.float64)))
            elif k == K_UINT:
                out[cid] = Datum.u(int(np.int64(raw).view(np.uint64)))
            elif k == K_DEC:
                out[cid] = Datum.d(Dec(raw, sc))
            else:
                out[cid] = Datum(int(k), raw)
        else:
            w = widths[vi]
            vi += 1
            ln = int(u[pos : pos + 4].view("<u4")[0])
            payload = bytes(u[pos + 4 : pos + 4 + ln])
            pos += 4 + w
            if is_null:
                out[cid] = Datum.null()
            elif k == K_STR:
                out[cid] = Datum.s(payload.decode("utf8"))
            else:
                out[cid] = Datum.b(payload)
    return out


# --- batch decode ------------------------------------------------------------


def decode_v2_batch(
    big: np.ndarray,
    offs: np.ndarray,
    table,
    cols,
    rows_idx: np.ndarray,
) -> np.ndarray:
    """Decode v2 rows (at byte offsets `offs` inside u8 buffer `big`)
    directly into chunk columns `cols` at row positions `rows_idx`.

    Rows sharing row-0's header (the bulk loader emits identical headers
    per run) decode in one shot: fixed row length → the batch is a reshape
    (contiguous case) or one gather, then per-column fixed-offset slices.
    Rows with a different header (schema drifted mid-table) are skipped and
    their positions within `offs` are returned for a per-row fallback.
    Column values route by col_id into the table's column offsets; table
    columns absent from the row get their defaults.
    """
    from ..table.table import datum_from_default

    n = len(offs)
    if n == 0:
        return np.empty(0, np.int64)
    o0 = int(offs[0])
    ncols, nfix = int(big[o0 + 1]), int(big[o0 + 2])
    nvar = ncols - nfix
    nb = (ncols + 7) // 8
    hlen = 3 + 6 * ncols + 2 * nvar
    h0 = big[o0 + 3 : o0 + hlen]
    ids = h0[: 4 * ncols].view("<i4").tolist()
    kds = h0[4 * ncols : 5 * ncols].tolist()
    scs = h0[5 * ncols : 6 * ncols].tolist()
    widths = h0[6 * ncols :].view("<u2").tolist()
    fixed_off = hlen + nb
    row_len = fixed_off + 8 * nfix + sum(4 + w for w in widths)

    # one matrix for the whole batch: reshape when rows are contiguous
    if n == 1 or (np.diff(offs) == row_len).all():
        mat = big[o0 : o0 + n * row_len].reshape(n, row_len)
    else:
        idx = np.minimum(offs[:, None] + np.arange(row_len), len(big) - 1)
        mat = big[idx]
    mismatched = np.empty(0, np.int64)
    if n > 1:
        same = (mat[:, :hlen] == mat[0, :hlen]).all(axis=1)
        if not same.all():
            mismatched = np.nonzero(~same)[0]
            mat = mat[same]
            rows_idx = rows_idx[same]
            n = mat.shape[0]

    by_id = {c.id: c for c in table.columns}
    null_bytes = mat[:, hlen:fixed_off]
    fixmat = np.ascontiguousarray(mat[:, fixed_off : fixed_off + 8 * nfix]).view("<i8") if nfix else None

    present: set[int] = set()
    fi = 0
    vi = 0
    cur = fixed_off + 8 * nfix
    for ci in range(ncols):
        k, cid, sc = kds[ci], ids[ci], scs[ci]
        c = by_id.get(cid)
        valid = ((null_bytes[:, ci // 8] >> (ci % 8)) & 1) == 0
        if k in FIXED_KINDS:
            raw = fixmat[:, fi]
            fi += 1
            if c is None:
                continue
            present.add(cid)
            col = cols[c.offset]
            if k == K_FLOAT:
                vals = raw.view(np.float64)
            elif k == K_UINT:
                vals = raw.view(np.uint64)
            elif k == K_DEC:
                want = max(c.ft.decimal, 0)
                vals = raw if want == sc else (raw * 10 ** (want - sc) if want > sc else raw // 10 ** (sc - want))
            else:
                vals = raw
            col.data[rows_idx] = vals.astype(col.data.dtype, copy=False)
            col.valid[rows_idx] = valid
        else:
            w = widths[vi]
            vi += 1
            if c is not None:
                present.add(cid)
                col = cols[c.offset]
                payload = mat[:, cur + 4 : cur + 4 + w]
                if w == 0:
                    strs = np.full(n, "", dtype=object)
                else:
                    sarr = np.ascontiguousarray(payload).reshape(-1).view(f"S{w}")
                    if k == K_STR:
                        if (payload >= 0x80).any():  # non-ascii → utf8 per row
                            strs = np.array([bytes(x).decode("utf8") for x in sarr], dtype=object)
                        else:
                            strs = sarr.astype("U").astype(object)
                    else:
                        lens = np.ascontiguousarray(mat[:, cur : cur + 4]).view("<u4").reshape(n)
                        strs = np.array([bytes(x[:l]) for x, l in zip(payload, lens)], dtype=object)
                col.data[rows_idx] = strs
                col.valid[rows_idx] = valid
            cur += 4 + w

    for c in table.columns:
        if c.id in present:
            continue
        if c.hidden and c.name == "_tidb_rowid":
            continue  # caller fills from handles
        d = datum_from_default(c)
        col = cols[c.offset]
        if d.is_null:
            col.valid[rows_idx] = False
        else:
            for i in rows_idx:
                col.set_datum(int(i), d)
    return mismatched


# --- vectorized key builders -------------------------------------------------


def encode_handles(handles: np.ndarray) -> np.ndarray:
    """int64 handles → (n, 8) u8 sign-flipped big-endian (memcomparable)."""
    u = handles.astype(np.int64).view(np.uint64) ^ _SIGN
    return np.ascontiguousarray(u.astype(">u8")).view(np.uint8).reshape(len(handles), 8)


def record_key_matrix(table_id: int, handles: np.ndarray) -> np.ndarray:
    """Vectorized tablecodec.record_key batch → (n, 19) u8 matrix."""
    from . import tablecodec

    prefix = np.frombuffer(tablecodec.record_prefix(table_id), dtype=np.uint8)
    n = len(handles)
    mat = np.empty((n, 19), dtype=np.uint8)
    mat[:, :11] = prefix
    mat[:, 11:] = encode_handles(handles)
    return mat


def record_keys(table_id: int, handles: np.ndarray) -> list[bytes]:
    """Vectorized tablecodec.record_key for a handle batch."""
    mat = record_key_matrix(table_id, handles)
    buf = mat.tobytes()
    return [buf[i * 19 : (i + 1) * 19] for i in range(len(handles))]


def int_index_key_matrix(
    table_id: int,
    index_id: int,
    key_cols: list[np.ndarray],
    handles: np.ndarray | None,
) -> np.ndarray:
    """Vectorized index keys for all-int key columns (flag 0x03 + BE int
    each), with optional handle suffix (non-unique indexes) → (n, w) u8."""
    from . import tablecodec
    from .key import INT_FLAG

    prefix = np.frombuffer(tablecodec.index_prefix(table_id, index_id), dtype=np.uint8)
    n = len(key_cols[0])
    w = len(prefix) + 9 * len(key_cols) + (8 if handles is not None else 0)
    mat = np.empty((n, w), dtype=np.uint8)
    mat[:, : len(prefix)] = prefix
    p = len(prefix)
    for col in key_cols:
        mat[:, p] = INT_FLAG
        mat[:, p + 1 : p + 9] = encode_handles(np.asarray(col))
        p += 9
    if handles is not None:
        mat[:, p : p + 8] = encode_handles(handles)
    return mat


def int_index_keys(
    table_id: int,
    index_id: int,
    key_cols: list[np.ndarray],
    handles: np.ndarray | None,
) -> list[bytes]:
    mat = int_index_key_matrix(table_id, index_id, key_cols, handles)
    n, w = mat.shape
    buf = mat.tobytes()
    return [buf[i * w : (i + 1) * w] for i in range(n)]


def handle_value_buffer(handles: np.ndarray) -> tuple[bytes, np.ndarray, np.ndarray]:
    """Unique-index values (decimal-string handles) as one buffer +
    (starts, lens) — matches table.index_value_key's str(handle) value."""
    strs = np.char.mod("%d", handles).astype("S")
    w = strs.dtype.itemsize
    mat = strs.view(np.uint8).reshape(len(handles), w)
    lens = w - np.argmax((mat != 0)[:, ::-1], axis=1).astype(np.int64)
    lens[~(mat != 0).any(axis=1)] = 0
    total = int(lens.sum())
    out = np.zeros(total, dtype=np.uint8)
    starts = np.zeros(len(handles), dtype=np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    src = mat[np.arange(w)[None, :] < lens[:, None]]
    _ragged_scatter(out, starts, lens, src)
    return out.tobytes(), starts, lens

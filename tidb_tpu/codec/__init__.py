from .key import (
    encode_int,
    decode_int,
    encode_uint,
    encode_bytes,
    decode_bytes,
    encode_float,
    decode_float,
    encode_datum_key,
    decode_datum_key,
)
from .tablecodec import (
    record_key,
    record_prefix,
    index_key,
    index_prefix,
    table_prefix,
    decode_record_handle,
)
from .row import encode_row, decode_row

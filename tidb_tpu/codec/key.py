"""Memcomparable datum codec (ref: util/codec/codec.go, bytes.go, number.go).

Encoded keys compare bytewise in the same order as the source datums, which
is what makes range scans over the ordered KV store express SQL ranges.
Wire format flags follow the reference's codec:
  0x00 NULL, 0x01 bytes (group-of-8 + pad marker), 0x03 int (sign-flipped
  big-endian), 0x04 uint, 0x05 float (bit-flipped).
"""

from __future__ import annotations

import struct

from ..mysqltypes.datum import Datum, K_NULL, K_INT, K_UINT, K_FLOAT, K_DEC, K_STR, K_BYTES, K_TIME, K_DUR

NIL_FLAG = 0x00
BYTES_FLAG = 0x01
INT_FLAG = 0x03
UINT_FLAG = 0x04
FLOAT_FLAG = 0x05
DECIMAL_FLAG = 0x06
MAX_FLAG = 0xFA

_SIGN_MASK = 0x8000000000000000
_GROUP = 8
_PAD = 0x00
_MARKER = 0xFF


def encode_int(buf: bytearray, v: int) -> None:
    buf.append(INT_FLAG)
    buf += struct.pack(">Q", (v + _SIGN_MASK) & 0xFFFFFFFFFFFFFFFF)


def decode_int(data: memoryview, pos: int) -> tuple[int, int]:
    (u,) = struct.unpack_from(">Q", data, pos)
    return u - _SIGN_MASK, pos + 8


def encode_uint(buf: bytearray, v: int) -> None:
    buf.append(UINT_FLAG)
    buf += struct.pack(">Q", v & 0xFFFFFFFFFFFFFFFF)


def encode_bytes(buf: bytearray, data: bytes) -> None:
    """Group-of-8 escape encoding preserving order (ref: util/codec/bytes.go:33)."""
    buf.append(BYTES_FLAG)
    n = len(data)
    for i in range(0, n + 1, _GROUP):
        grp = data[i : i + _GROUP]
        pad = _GROUP - len(grp)
        buf += grp
        buf += bytes([_PAD]) * pad
        buf.append(_MARKER - pad)


def decode_bytes(data: memoryview, pos: int) -> tuple[bytes, int]:
    out = bytearray()
    while True:
        grp = bytes(data[pos : pos + _GROUP])
        marker = data[pos + _GROUP]
        pos += _GROUP + 1
        pad = _MARKER - marker
        out += grp[: _GROUP - pad]
        if pad > 0:
            break
    return bytes(out), pos


def encode_float(buf: bytearray, f: float) -> None:
    buf.append(FLOAT_FLAG)
    (u,) = struct.unpack(">Q", struct.pack(">d", f))
    if u & _SIGN_MASK:
        u = ~u & 0xFFFFFFFFFFFFFFFF
    else:
        u |= _SIGN_MASK
    buf += struct.pack(">Q", u)


def decode_float(data: memoryview, pos: int) -> tuple[float, int]:
    (u,) = struct.unpack_from(">Q", data, pos)
    if u & _SIGN_MASK:
        u &= ~_SIGN_MASK & 0xFFFFFFFFFFFFFFFF
    else:
        u = ~u & 0xFFFFFFFFFFFFFFFF
    return struct.unpack(">d", struct.pack(">Q", u))[0], pos + 8


def encode_decimal(buf: bytearray, value: int, scale: int) -> None:
    """Exact memcomparable decimal (ref: util/codec/decimal.go idea).

    Layout after the flag: sign byte (0 neg / 1 zero / 2 pos), then for
    non-zero values an exponent byte (count of integer digits + 128) and
    the significant digits (one byte each, digit+1) with a 0x00 terminator;
    negative values complement every post-sign byte so byte order flips.
    Trailing zeros are normalized away, so equal values encode identically
    regardless of scale.
    """
    buf.append(DECIMAL_FLAG)
    if value == 0:
        buf.append(1)
        return
    neg = value < 0
    digits = str(abs(value))
    # exponent: digits to the left of the decimal point
    exp = len(digits) - scale
    digits = digits.rstrip("0") or "0"
    body = bytearray()
    body.append((exp + 128) & 0xFF)
    body += bytes(int(c) + 1 for c in digits)
    body.append(0x00)
    if neg:
        buf.append(0)
        buf += bytes(255 - b for b in body)
    else:
        buf.append(2)
        buf += body


def decode_decimal(data: memoryview, pos: int) -> tuple["Dec", int]:
    from ..mysqltypes.mydecimal import Dec

    sign = data[pos]
    pos += 1
    if sign == 1:
        return Dec(0, 0), pos
    neg = sign == 0
    raw = bytearray()
    while True:
        b = data[pos]
        pos += 1
        if neg:
            b = 255 - b
        if len(raw) > 0 and b == 0x00:
            break
        raw.append(b)
    exp = raw[0] - 128
    digits = "".join(str(b - 1) for b in raw[1:])
    value = int(digits)
    scale = max(len(digits) - exp, 0)
    if exp > len(digits):
        value *= 10 ** (exp - len(digits))
    return Dec(-value if neg else value, scale), pos


def encode_datum_key(buf: bytearray, d: Datum) -> None:
    """Encode one datum in memcomparable form (for index keys / sort keys).

    Times/durations ride the int path (packed int64 order == chronological
    order); decimals use the exact sign/exponent/digits encoding.
    """
    k = d.kind
    if k == K_NULL:
        buf.append(NIL_FLAG)
    elif k in (K_INT, K_TIME, K_DUR):
        encode_int(buf, d.val)
    elif k == K_UINT:
        encode_uint(buf, d.val)
    elif k == K_FLOAT:
        encode_float(buf, d.val)
    elif k == K_DEC:
        encode_decimal(buf, d.val.value, d.val.scale)
    elif k == K_STR:
        encode_bytes(buf, d.val.encode("utf8"))
    elif k == K_BYTES:
        encode_bytes(buf, d.val)
    else:
        raise TypeError(f"cannot key-encode kind {k}")


def decode_datum_key(data: memoryview, pos: int) -> tuple[Datum, int]:
    flag = data[pos]
    pos += 1
    if flag == NIL_FLAG:
        return Datum.null(), pos
    if flag == INT_FLAG:
        v, pos = decode_int(data, pos)
        return Datum.i(v), pos
    if flag == UINT_FLAG:
        (u,) = struct.unpack_from(">Q", data, pos)
        return Datum.u(u), pos + 8
    if flag == FLOAT_FLAG:
        f, pos = decode_float(data, pos)
        return Datum.f(f), pos
    if flag == BYTES_FLAG:
        b, pos = decode_bytes(data, pos)
        return Datum.b(b), pos
    if flag == DECIMAL_FLAG:
        dec, pos = decode_decimal(data, pos)
        return Datum(K_DEC, dec), pos
    raise ValueError(f"bad key flag {flag:#x}")

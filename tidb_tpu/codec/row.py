"""Row value format (ref: util/rowcodec — compact row format v2).

Self-describing column-id tagged encoding. Layout:
  varint(ncols) then per column: varint(col_id), kind byte, payload.
Payloads use little-endian fixed ints / raw bytes with varint lengths.
Row decode into columnar chunks happens in copr/engine; this codec is only
on the txn write path and point-get path, not the scan hot loop (scans read
the columnar tile replica instead).
"""

from __future__ import annotations

import struct

from ..mysqltypes.datum import Datum, K_NULL, K_INT, K_UINT, K_FLOAT, K_DEC, K_STR, K_BYTES, K_TIME, K_DUR
from ..mysqltypes.mydecimal import Dec


def _wvarint(buf: bytearray, v: int) -> None:
    # zigzag for signed
    u = (v << 1) ^ (v >> 63) if v < 0 else v << 1
    while u >= 0x80:
        buf.append((u & 0x7F) | 0x80)
        u >>= 7
    buf.append(u)


def _rvarint(data, pos: int) -> tuple[int, int]:
    shift = 0
    u = 0
    while True:
        b = data[pos]
        pos += 1
        u |= (b & 0x7F) << shift
        if b < 0x80:
            break
        shift += 7
    v = (u >> 1) ^ -(u & 1)
    return v, pos


def encode_row(col_ids: list[int], datums: list[Datum]) -> bytes:
    buf = bytearray()
    _wvarint(buf, len(col_ids))
    for cid, d in zip(col_ids, datums):
        _wvarint(buf, cid)
        k = d.kind
        buf.append(k)
        if k == K_NULL:
            continue
        if k in (K_INT, K_TIME, K_DUR):
            _wvarint(buf, d.val)
        elif k == K_UINT:
            buf += struct.pack("<Q", d.val)
        elif k == K_FLOAT:
            buf += struct.pack("<d", d.val)
        elif k == K_DEC:
            _wvarint(buf, d.val.scale)
            b = str(d.val.value).encode()
            _wvarint(buf, len(b))
            buf += b
        elif k in (K_STR, K_BYTES):
            b = d.val.encode("utf8") if k == K_STR else d.val
            _wvarint(buf, len(b))
            buf += b
        else:
            raise TypeError(f"cannot row-encode kind {k}")
    return bytes(buf)


def decode_row(data: bytes) -> dict[int, Datum]:
    if data and data[0] == 0x81:  # row format v2 (vectorized batch codec)
        from .rowfast import decode_row_v2

        return decode_row_v2(data)
    pos = 0
    n, pos = _rvarint(data, pos)
    out: dict[int, Datum] = {}
    for _ in range(n):
        cid, pos = _rvarint(data, pos)
        k = data[pos]
        pos += 1
        if k == K_NULL:
            out[cid] = Datum.null()
            continue
        if k in (K_INT, K_TIME, K_DUR):
            v, pos = _rvarint(data, pos)
            out[cid] = Datum(k, v)
        elif k == K_UINT:
            (v,) = struct.unpack_from("<Q", data, pos)
            pos += 8
            out[cid] = Datum.u(v)
        elif k == K_FLOAT:
            (v,) = struct.unpack_from("<d", data, pos)
            pos += 8
            out[cid] = Datum.f(v)
        elif k == K_DEC:
            scale, pos = _rvarint(data, pos)
            ln, pos = _rvarint(data, pos)
            val = int(data[pos : pos + ln].decode())
            pos += ln
            out[cid] = Datum.d(Dec(val, scale))
        elif k in (K_STR, K_BYTES):
            ln, pos = _rvarint(data, pos)
            b = data[pos : pos + ln]
            pos += ln
            out[cid] = Datum.s(b.decode("utf8")) if k == K_STR else Datum.b(bytes(b))
        else:
            raise ValueError(f"bad row kind {k}")
    return out

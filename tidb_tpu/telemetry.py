"""Telemetry — anonymous usage snapshot (ref: telemetry/telemetry.go;
the reference periodically reports feature usage. Here the snapshot is
computed on demand and NEVER leaves the process — there is no egress)."""

from __future__ import annotations

import time

_START = time.time()


def snapshot(storage, session=None) -> dict:
    from .utils.metrics import REGISTRY

    is_tables = 0
    dbs = 0
    if session is not None:
        is_ = session.infoschema()
        is_tables = len(is_.tables)
        dbs = len(is_.db_names())
    counters = {}
    for name, labels, value in REGISTRY.rows():
        if name.startswith("tidb_query_total"):
            counters[labels or "total"] = counters.get(labels or "total", 0) + value
    return {
        "uptime_s": round(time.time() - _START, 1),
        "databases": dbs,
        "tables": is_tables,
        "queries": counters,
        "durable": storage.data_dir is not None,
        "regions": len(storage.regions.regions),
        "version": "8.0.11-tidb-tpu",
    }

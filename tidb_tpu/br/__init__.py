from .backup import run_backup, run_restore
from .importer import run_load_data

__all__ = ["run_backup", "run_restore", "run_load_data"]

"""LOAD DATA INFILE — Lightning-style bulk import with a resumable
checkpoint (ref: br/pkg/lightning: mydump CSV parsing, batched KV
encode, file checkpoints in lightning/checkpoints/ so an interrupted
import resumes at the last committed chunk; the wire-streaming variant
is executor/load_data.go)."""

from __future__ import annotations

import json
import os

from ..errors import TiDBError
from ..mysqltypes.datum import Datum
from ..table.table import Table

BATCH_ROWS = 2000


def _split_fields(line: str, sep: str, enclosed: str) -> list[str]:
    fields = line.split(sep)
    if enclosed:
        fields = [
            f[1:-1] if len(f) >= 2 and f.startswith(enclosed) and f.endswith(enclosed) else f
            for f in fields
        ]
    return fields


def run_load_data(session, stmt):
    """Chunked, checkpointed CSV import. Each batch commits in its own
    transaction and advances the checkpoint file; re-running the same
    LOAD DATA after an interruption skips completed batches."""
    from ..session.session import ResultSet

    path = stmt.path
    if not os.path.exists(path):
        raise TiDBError(f"file {path!r} not found")
    db = stmt.table.db or session.current_db
    info = session.infoschema().table(db, stmt.table.name)
    tbl = Table(info)
    visible = info.visible_columns()
    if stmt.columns:
        by_name = {c.name.lower(): c for c in visible}
        target = []
        for name in stmt.columns:
            c = by_name.get(name.lower())
            if c is None:
                raise TiDBError(f"unknown column {name!r} in LOAD DATA column list")
            target.append(c)
    else:
        target = visible

    with open(path, "r", encoding="utf8", errors="replace") as f:
        content = f.read()
    lines = content.split(stmt.lines_terminated)
    if lines and lines[-1] == "":
        lines.pop()
    lines = lines[stmt.ignore_lines :]

    ckpt_path = path + ".ckpt"
    start_row = 0
    if os.path.exists(ckpt_path):
        try:
            ck = json.loads(open(ckpt_path).read())
            if ck.get("table") == f"{db}.{info.name}".lower():
                start_row = int(ck.get("rows_done", 0))
        except (ValueError, OSError):
            start_row = 0

    affected = 0
    for lo in range(start_row, len(lines), BATCH_ROWS):
        batch = lines[lo : lo + BATCH_ROWS]
        txn = session.store.begin()
        try:
            for line in batch:
                if not line:
                    continue
                fields = _split_fields(line, stmt.fields_terminated, stmt.enclosed)
                datums = [session._default_datum(c) for c in visible]
                for col, raw in zip(target, fields):
                    if raw == "\\N":
                        datums[col.offset] = Datum.null()
                    else:
                        datums[col.offset] = session._cast_datum(Datum.s(raw), col.ft)
                if info.pk_is_handle:
                    pk = next(i for i in info.indexes if i.primary)
                    handle = datums[pk.col_offsets[0]].to_int()
                else:
                    handle = session.alloc_auto_id(info, 1)
                t = session._phys_table(info, datums) if info.partition else tbl
                t.add_record(txn, datums, handle)
                affected += 1
            txn.commit()
        except Exception:
            txn.rollback()
            raise
        # chunk-granularity resume point (Lightning checkpoint analog)
        with open(ckpt_path, "w") as f:
            f.write(json.dumps({"table": f"{db}.{info.name}".lower(), "rows_done": lo + len(batch)}))
    if os.path.exists(ckpt_path):
        os.unlink(ckpt_path)
    session._invalidate_tiles(info)
    session.store.stats.report_delta(info.id, affected, affected)
    return ResultSet([], None, affected=affected)

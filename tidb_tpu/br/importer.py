"""LOAD DATA INFILE — Lightning-style bulk import (ref: br/pkg/lightning:
mydump CSV parsing, batched KV encode, file checkpoints in
lightning/checkpoints/ so an interrupted import resumes at the last
committed chunk; the wire-streaming variant is executor/load_data.go).

Two routes (PR 15):

  bulk (default, `tidb_bulk_ingest=ON` or `WITH bulk_ingest=1`): parse
  the whole file into per-column raw-string lanes, cast each column
  VECTORIZED (numpy int/float/decimal/date parsing — no per-cell Datum
  work), and publish through the shared bulk engine
  (br/ingest.BulkIngest): sorted columnar KV artifacts, one atomic WAL
  ingest record, all-visible-or-absent under a crash. No checkpoints —
  a crashed bulk load left NOTHING visible, so a re-run starts clean.

  legacy (`tidb_bulk_ingest=OFF`, ineligible column types, partitioned
  targets, or resuming a partially-imported file): 2000-row transaction
  batches with a resumable checkpoint. The checkpoint sidecar lives in
  the store's DATA dir (not next to the input file — read-only input
  dirs must work), keyed by (path, table, mtime): a re-edited input file
  gets a fresh key and never silently resumes mid-file.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile

import numpy as np

from ..errors import TiDBError
from ..mysqltypes.coretime import pack_time
from ..mysqltypes.datum import Datum, K_DEC, K_FLOAT, K_STR, K_TIME
from ..table.table import Table

BATCH_ROWS = 2000
LOAD_OPTIONS = ("bulk_ingest", "batch_size")

# CoreTime packing strides DERIVED from pack_time so the vectorized date
# cast can never drift from the one layout definition (any affine change
# to pack_time propagates here at import)
_T0 = pack_time(0, 1, 1)
_US_SEC = pack_time(0, 1, 1, 0, 0, 1) - _T0
_US_MIN = pack_time(0, 1, 1, 0, 1, 0) - _T0
_US_HOUR = pack_time(0, 1, 1, 1, 0, 0) - _T0
_US_DAY = pack_time(0, 1, 2) - _T0
_MONTH_STRIDE = pack_time(0, 2, 1) - _T0
_YEAR_STRIDE = pack_time(1, 1, 1) - _T0


def _split_fields(line: str, sep: str, enclosed: str) -> list[str]:
    fields = line.split(sep)
    if enclosed:
        fields = [
            f[1:-1] if len(f) >= 2 and f.startswith(enclosed) and f.endswith(enclosed) else f
            for f in fields
        ]
    return fields


def ckpt_path(store, path: str, table_key: str, mtime_ns: int) -> str:
    """Checkpoint sidecar location: `<data_dir>/loadckpt/<key>.json`,
    keyed by (absolute input path, target table, input mtime). In-memory
    stores use a per-store temp dir (resume across restarts is moot when
    the data itself does not survive one)."""
    base = _ckpt_base(store)
    key = hashlib.sha1(
        f"{os.path.abspath(path)}|{table_key}|{mtime_ns}".encode()
    ).hexdigest()[:24]
    return os.path.join(base, key + ".json")


def _ckpt_base(store) -> str:
    if store.data_dir:
        return os.path.join(store.data_dir, "loadckpt")
    return os.path.join(tempfile.gettempdir(), f"tidb-tpu-loadckpt-{store.store_uid}")


def _sweep_ckpts(store, path: str, table_key: str) -> None:
    """A completed load retires EVERY checkpoint for this (path, table)
    — including stale-mtime keys from interrupted imports of earlier
    file versions, which would otherwise accumulate forever."""
    base = _ckpt_base(store)
    if not os.path.isdir(base):
        return
    want = os.path.abspath(path)
    for name in os.listdir(base):
        p = os.path.join(base, name)
        try:
            ck = json.loads(open(p).read())
            if ck.get("path") == want and ck.get("table") == table_key:
                os.unlink(p)
        except (ValueError, OSError):
            continue


def run_load_data(session, stmt):
    """LOAD DATA dispatch: bulk route when eligible, else the chunked,
    checkpointed legacy import."""
    from ..session.session import ResultSet

    path = stmt.path
    if not os.path.exists(path):
        raise TiDBError(f"file {path!r} not found")
    db = stmt.table.db or session.current_db
    info = session.infoschema().table(db, stmt.table.name)
    visible = info.visible_columns()
    if stmt.columns:
        by_name = {c.name.lower(): c for c in visible}
        target = []
        for name in stmt.columns:
            c = by_name.get(name.lower())
            if c is None:
                raise TiDBError(f"unknown column {name!r} in LOAD DATA column list")
            target.append(c)
    else:
        target = visible

    with open(path, "r", encoding="utf8", errors="replace") as f:
        content = f.read()
    lines = content.split(stmt.lines_terminated)
    if lines and lines[-1] == "":
        lines.pop()
    lines = lines[stmt.ignore_lines :]

    table_key = f"{db}.{info.name}".lower()
    mtime_ns = os.stat(path).st_mtime_ns
    cpath = ckpt_path(session.store, path, table_key, mtime_ns)
    start_row = 0
    if os.path.exists(cpath):
        try:
            ck = json.loads(open(cpath).read())
            if ck.get("table") == table_key:
                start_row = int(ck.get("rows_done", 0))
        except (ValueError, OSError):
            start_row = 0

    opts = getattr(stmt, "options", None) or {}
    for name in opts:
        if name not in LOAD_OPTIONS:
            raise TiDBError(
                f"unknown LOAD DATA option {name!r} (supported: "
                f"{', '.join(LOAD_OPTIONS)})"
            )
    # batch_size validates UP FRONT so a bad value fails deterministically,
    # not only on the statements that happen to take the legacy route
    try:
        batch_rows = int(opts.get("batch_size", BATCH_ROWS))
    except (TypeError, ValueError):
        raise TiDBError(f"invalid LOAD DATA batch_size {opts.get('batch_size')!r}")
    if batch_rows < 1:
        raise TiDBError(f"LOAD DATA batch_size must be >= 1, got {batch_rows}")
    flag = opts.get("bulk_ingest")
    if flag is None:
        bulk = session.vars.get("tidb_bulk_ingest", "ON") == "ON"
    else:
        bulk = str(flag).lower() in ("1", "on", "true")
    if start_row:
        # the file was partially imported under txn semantics: only the
        # legacy path can resume it without duplicating committed rows
        bulk = False
    if (
        bulk
        and info.partition is None
        and {c.offset for c in target} == {c.offset for c in visible}
    ):
        result = _load_bulk(session, info, db, target, lines, stmt, len(content))
        if result is not None:
            _sweep_ckpts(session.store, path, table_key)
            session.store.stats.report_delta(info.id, result, result)
            return ResultSet([], None, affected=result)

    return _load_legacy(session, info, visible, target, lines, stmt,
                        cpath, table_key, start_row, batch_rows)


# ------------------------------------------------------------------ bulk route


def _load_bulk(session, info, db, target, lines, stmt, content_bytes: int):
    """Columnar LOAD DATA: split → per-column raw lanes → vectorized
    casts → BulkIngest. Returns the row count, or None when the data
    doesn't fit the bulk route (caller falls back to legacy).

    Constraint parity with the legacy path: the bulk route requires an
    EMPTY target table (the Lightning physical-import restriction —
    conflicts against existing rows cannot be checked without the txn
    path), refuses NULL primary keys by falling back, and enforces
    in-file pk/unique duplicates via BulkIngest(enforce_unique=True)."""
    from ..codec import tablecodec
    from ..utils import metrics as M
    from .ingest import BulkIngest, IngestAborted, kind_of

    # Lightning physical-mode restriction: only empty tables — a row
    # colliding with EXISTING data must go through the txn path's
    # conflict checks, not silently shadow. prefix_next, not +b"\xff":
    # handles whose encoding starts 0xff must count as occupancy too
    from ..planner.ranger import prefix_next

    prefix = tablecodec.record_prefix(info.id)
    if session.store.snapshot().scan(prefix, prefix_next(prefix), 1):
        return None
    ncols = len(target)
    rows = []
    for line in lines:
        if not line:
            continue
        fields = _split_fields(line, stmt.fields_terminated, stmt.enclosed)
        if len(fields) < ncols:
            return None  # ragged rows keep the legacy default semantics
        rows.append(fields[:ncols])
    if not rows:
        return 0
    hc = info.handle_col() if info.pk_is_handle else None
    names, arrays, kinds, valids = [], [], [], []
    for ci, col in enumerate(target):
        raw = np.array([r[ci] for r in rows], dtype=object)
        kind = kind_of(col.ft)
        cast = _cast_column(raw, col.ft, kind)
        if cast is None:
            return None
        data, valid = cast
        if hc is not None and col.offset == hc.offset and valid is not None:
            return None  # NULL primary key: the legacy path errors properly
        names.append(col.name)
        arrays.append(data)
        kinds.append(kind)
        valids.append(valid)
    M.INGEST_BYTES.inc(content_bytes, stage="parse")
    try:
        # db explicitly: a db-qualified LOAD DATA must not resolve the
        # publish-time schema witness against session.current_db
        job = BulkIngest(session, info, db=db, enforce_unique=True,
                         require_empty=True)
    except IngestAborted:
        # a DDL job is queued/running on the table: the legacy txn path
        # coexists with online DDL exactly as it always did
        return None
    try:
        job.add_columns(names, arrays, kinds, valids)
        job.commit()
    except IngestAborted:
        # publish-time abort (a commit raced the ingest window): the
        # legacy route re-imports with full conflict checks
        job.abort()
        return None
    except BaseException:
        job.abort()
        raise
    return len(rows)


def _cast_column(raw: np.ndarray, ft, kind: int):
    """Vectorized cast of one raw-string column → (canonical array,
    valid mask | None), or None when the values don't fit the fast
    parsers (the caller falls back to the per-row legacy path)."""
    nulls = raw == "\\N"
    valid = None
    if nulls.any():
        valid = ~nulls
    if kind == K_STR:
        if ft.elems:
            # ENUM/SET: membership validation + case/order normalization
            # live in the per-row cast — a raw passthrough would store
            # 'blue' into ENUM('red','green') silently
            return None
        if valid is not None:
            raw = np.where(nulls, "", raw)
        return raw, valid
    if valid is not None:
        raw = np.where(nulls, "0", raw)
    try:
        if kind == K_FLOAT:
            return raw.astype(np.float64), valid
        if kind == K_DEC:
            # float64 parse + scaled round is EXACT only when the input
            # carries no more fractional digits than the column scale
            # (otherwise the half-way rounding direction depends on the
            # inexact float product — legacy Dec rounds half-away-from-
            # zero) and <= 15 total digits (DBL_DIG); anything wider, an
            # exponent form, or extra fractional digits takes the
            # per-row exact path
            if not (0 < ft.flen <= 15):
                return None
            scale = max(ft.decimal, 0)
            s = raw.astype("S")
            # strictly digits/sign/dot: 'inf'/'nan'/exponent forms would
            # astype(float) fine and then wrap int64 into garbage
            if (np.char.strip(s, b"0123456789.+-") != b"").any():
                return None
            dot = np.char.find(s, b".")
            slen = np.char.str_len(s)
            frac = np.where(dot >= 0, slen - dot - 1, 0)
            if (frac > scale).any():
                return None
            # the INPUT's digit count must fit float64 exactness too — a
            # 17-digit literal into DECIMAL(15,1) must not float-round
            # while legacy stores it exactly (sign/dot excluded; leading
            # zeros over-count toward the fallback, which is safe) — and
            # the SCALED integer must stay within float64's exact range:
            # int digits + scale <= 15 keeps value*10^scale < 10^15 <
            # 2^53 (at 10^18 one ulp is ~128 and np.rint lands on the
            # wrong integer)
            digits = slen - (dot >= 0) - np.char.startswith(s, b"-")
            if (digits > 15).any() or (((digits - frac) + scale) > 15).any():
                return None
            return np.rint(raw.astype(np.float64) * 10 ** scale).astype(np.int64), valid
        if kind == K_TIME:
            return _cast_dates(raw, valid)
        return raw.astype(np.int64), valid  # K_INT / K_UINT via int64 parse
    except (ValueError, TypeError, OverflowError):
        return None


def _cast_dates(raw: np.ndarray, valid):
    """Strict vectorized 'YYYY-MM-DD[ HH:MM:SS]' → packed CoreTime ints
    (mysqltypes/coretime.pack_time layout). Anything else — including
    fractional seconds and out-of-range fields — → None, so the exact
    per-row parser keeps the last word (a wide astype would otherwise
    silently TRUNCATE '…05.678901' to '…05')."""
    s = raw.astype("S27")  # wider than any datetime(6) literal: no clipping
    lens = np.char.str_len(s)
    n = len(s)
    if valid is not None:
        # the NULL sentinel matches the DOMINANT width so one NULL in a
        # DATETIME column doesn't disqualify the whole file (masked rows'
        # values are discarded anyway)
        vlens = lens[valid]
        if len(vlens) and (vlens == 19).all():
            sent, sw = b"0000-01-01 00:00:00", 19
        else:
            sent, sw = b"0000-01-01", 10
        lens = np.where(valid, lens, sw)
        s = np.where(valid, s, sent)
    if (lens == 10).all():
        w = 10
    elif (lens == 19).all():
        w = 19
    else:
        return None
    mat = np.zeros((n, w), dtype=np.uint8)
    flat = s.astype(f"S{w}").view(np.uint8).reshape(n, -1)
    mat[:, : flat.shape[1]] = flat[:, :w]
    d = mat - ord("0")

    def num(lo, hi):
        out = np.zeros(n, dtype=np.int64)
        for i in range(lo, hi):
            out = out * 10 + d[:, i]
        return out

    digits = np.ones(n, dtype=bool)
    for i in range(w):
        if i in (4, 7):
            digits &= mat[:, i] == ord("-")
        elif i == 10:
            digits &= mat[:, i] == ord(" ")
        elif i in (13, 16):
            digits &= mat[:, i] == ord(":")
        else:
            digits &= (d[:, i] >= 0) & (d[:, i] <= 9)
    if not digits.all():
        return None
    y, m, day = num(0, 4), num(5, 7), num(8, 10)
    if not (((m >= 1) & (m <= 12) & (day >= 1) & (day <= 31)).all()):
        return None  # out-of-range fields would pack into arithmetic garbage
    packed = _T0 + y * _YEAR_STRIDE + (m - 1) * _MONTH_STRIDE + (day - 1) * _US_DAY
    if w == 19:
        hh, mi, ss = num(11, 13), num(14, 16), num(17, 19)
        if not (((hh <= 23) & (mi <= 59) & (ss <= 59)).all()):
            return None
        packed = packed + hh * _US_HOUR + mi * _US_MIN + ss * _US_SEC
    return packed, valid


# ---------------------------------------------------------------- legacy route


def _load_legacy(session, info, visible, target, lines, stmt,
                 cpath: str, table_key: str, start_row: int, batch_rows: int):
    """Chunked, checkpointed CSV import. Each batch commits in its own
    transaction and advances the checkpoint file; re-running the same
    LOAD DATA after an interruption skips completed batches."""
    from ..session.session import ResultSet

    tbl = Table(info)
    affected = 0
    for lo in range(start_row, len(lines), batch_rows):
        batch = lines[lo : lo + batch_rows]
        txn = session.store.begin()
        try:
            for line in batch:
                if not line:
                    continue
                fields = _split_fields(line, stmt.fields_terminated, stmt.enclosed)
                datums = [session._default_datum(c) for c in visible]
                for col, raw in zip(target, fields):
                    if raw == "\\N":
                        datums[col.offset] = Datum.null()
                    else:
                        datums[col.offset] = session._cast_datum(Datum.s(raw), col.ft)
                if info.pk_is_handle:
                    pk = next(i for i in info.indexes if i.primary)
                    if datums[pk.col_offsets[0]].is_null:
                        raise TiDBError(
                            f"Column {visible[pk.col_offsets[0]].name!r} "
                            f"cannot be null (primary key)"
                        )
                    handle = datums[pk.col_offsets[0]].to_int()
                else:
                    handle = session.alloc_auto_id(info, 1)
                t = session._phys_table(info, datums) if info.partition else tbl
                t.add_record(txn, datums, handle)
                affected += 1
            txn.commit()
        except Exception:
            txn.rollback()
            raise
        # chunk-granularity resume point (Lightning checkpoint analog),
        # in the DATA dir so read-only input dirs work; `path` recorded
        # so completion can sweep stale-mtime keys of the same file
        os.makedirs(os.path.dirname(cpath), exist_ok=True)
        with open(cpath, "w") as f:
            f.write(json.dumps({
                "table": table_key,
                "rows_done": lo + len(batch),
                "path": os.path.abspath(stmt.path),
            }))
    _sweep_ckpts(session.store, stmt.path, table_key)
    session._invalidate_tiles(info)
    session.store.stats.report_delta(info.id, affected, affected)
    return ResultSet([], None, affected=affected)

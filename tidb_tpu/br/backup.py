"""BACKUP / RESTORE — snapshot backup to local storage
(ref: br/pkg/backup + restore driven from SQL via executor/brie.go;
BR's rewrite rules map backed-up table ids onto freshly allocated ids
at restore, which is what `_rewrite_key` does here).

Layout of a backup directory:
  manifest.bin   — CRC-framed JSON: backup_ts + per-table schema/file info
  t<id>.sst      — CRC-framed KV payload: all record+index keys of one
                   table at the backup snapshot (the SST analog)
"""

from __future__ import annotations

import json
import os
import struct

from ..catalog.meta import Meta
from ..catalog.schema import TableInfo
from ..codec import tablecodec
from ..errors import TableExists, TiDBError, UnknownDatabase
from ..storage import wal as w

SYSTEM_DBS = {"mysql", "information_schema", "performance_schema"}


def _pack_pairs(pairs) -> bytes:
    parts = [struct.pack("<Q", len(pairs))]
    for k, v in pairs:
        parts.append(struct.pack("<II", len(k), len(v)))
        parts.append(k)
        parts.append(v)
    return b"".join(parts)


def _unpack_pairs(payload: bytes):
    (n,) = struct.unpack_from("<Q", payload, 0)
    pos = 8
    out = []
    for _ in range(n):
        klen, vlen = struct.unpack_from("<II", payload, pos)
        pos += 8
        out.append((payload[pos : pos + klen], payload[pos + klen : pos + klen + vlen]))
        pos += klen + vlen
    return out


def run_backup(session, stmt):
    """BACKUP DATABASE *|db[,db] TO 'dir'."""
    from ..session.session import ResultSet

    path = stmt.storage
    os.makedirs(path, exist_ok=True)
    backup_ts = session.store.tso.next()
    is_ = session.infoschema()
    dbs = set(d.lower() for d in stmt.databases) or {
        d for d in is_.db_names() if d not in SYSTEM_DBS
    }
    snap = session.store.snapshot(backup_ts)
    manifest = {"backup_ts": backup_ts, "tables": []}
    total_kvs = total_bytes = 0
    for t in sorted(is_.tables.values(), key=lambda x: x.id):
        if t.db_name.lower() not in dbs:
            continue
        ent = {"db": t.db_name, "schema": t.to_json(), "kvs": 0}
        # one file per physical keyspace (partitions back up separately so
        # restore can remap each to a fresh partition id)
        files = []
        for pid in t.physical_ids():
            pairs = snap.scan(tablecodec.table_prefix(pid), tablecodec.table_prefix(pid + 1))
            payload = _pack_pairs(pairs)
            fname = f"t{pid}.sst"
            w.snap_write(os.path.join(path, fname), payload)
            files.append({"pid": pid, "file": fname, "kvs": len(pairs)})
            ent["kvs"] += len(pairs)
            total_kvs += len(pairs)
            total_bytes += len(payload)
        ent["file"] = files[0]["file"] if t.partition is None else None
        ent["parts"] = files
        manifest["tables"].append(ent)
    w.snap_write(os.path.join(path, "manifest.bin"), json.dumps(manifest).encode())
    return ResultSet.message_row(
        ["Destination", "Size", "BackupTS", "Queue Time", "Execution Time"],
        [path, str(total_bytes), str(backup_ts), "0", "0"],
    )


def _rewrite_key(key: bytes, new_id: int) -> bytes:
    # keys are 't' + 8-byte big-endian-comparable table id + suffix
    return tablecodec.table_prefix(new_id) + key[9:]


def run_restore(session, stmt):
    """RESTORE DATABASE *|db[,db] FROM 'dir' — schemas re-register under
    freshly allocated table ids; keys rewrite on ingest (BR rewrite-rule
    analog)."""
    from ..session.session import ResultSet

    path = stmt.storage
    raw = w.snap_read(os.path.join(path, "manifest.bin"))
    if raw is None:
        raise TiDBError(f"no backup manifest at {path!r}")
    manifest = json.loads(raw)
    want = set(d.lower() for d in stmt.databases)
    store = session.store
    total_kvs = 0
    for ent in manifest["tables"]:
        if want and ent["db"].lower() not in want:
            continue
        schema = TableInfo.from_json(ent["schema"])
        txn = store.begin()
        m = Meta(txn)
        dbi = m.db(ent["db"])
        if dbi is None:
            from ..catalog.schema import DBInfo

            dbi = DBInfo(ent["db"])
        for tid in dbi.table_ids:
            existing = m.table(tid)
            if existing and existing.name.lower() == schema.name.lower():
                txn.rollback()
                raise TableExists(f"table {ent['db']}.{schema.name} already exists")
        new_id = m.alloc_id()
        schema.id = new_id
        schema.db_name = ent["db"]
        # remap each old physical id (partition or the table itself) to a
        # freshly allocated keyspace
        id_map = {}
        parts = ent.get("parts") or [{"pid": ent["schema"]["id"], "file": ent["file"]}]
        if schema.partition is not None:
            for pd in schema.partition.defs:
                new_pid = m.alloc_id()
                id_map[pd.id] = new_pid
                pd.id = new_pid
        else:
            id_map[parts[0]["pid"]] = new_id
        m.put_table(schema)
        dbi.table_ids.append(new_id)
        m.put_db(dbi)
        m.bump_schema_version()
        txn.commit()

        for part in parts:
            payload = w.snap_read(os.path.join(path, part["file"]))
            if payload is None:
                raise TiDBError(f"backup file {part['file']} missing/corrupt")
            dst = id_map.get(part["pid"])
            if dst is None:
                raise TiDBError(f"backup partition {part['pid']} has no schema entry")
            pairs = [(_rewrite_key(k, dst), v) for k, v in _unpack_pairs(payload)]
            if not pairs:
                continue
            commit_ts = store.tso.next()
            store.mvcc.ingest(pairs, commit_ts)
            store.bump_version([pairs[0][0]])
            session.cop.tiles.invalidate_table(dst)
            total_kvs += len(pairs)
    session._is_cache = None
    return ResultSet.message_row(
        ["Destination", "Size", "BackupTS", "Queue Time", "Execution Time"],
        [path, str(total_kvs), str(manifest["backup_ts"]), "0", "0"],
    )

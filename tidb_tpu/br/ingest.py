"""Lightning-style shared bulk-ingest engine (PR 15) — the ONE path both
`LOAD DATA INFILE` (br/importer.py) and `models/tpch.bulk_load` drive
(ref: br/pkg/lightning local backend: encode rows into sorted KV
artifacts off the write path, then ingest them as a unit).

Pipeline: columnar input → vectorized canonicalization (int64/uint64/
float64 lanes, scaled-decimal int64, 'S<w>' string arrays — numpy, no
per-row Datum work) → sorted KV artifacts (storage/segment.ColumnarRun
for the record plane, IntIndexRun for all-int secondary indexes, a byte
Run for everything else) → ONE atomic publish: a single WAL ingest
record (`rec_ingest`) so recovery and shipped standbys see the whole
ingest or none of it, one data-version bump, one tile/build-cache
invalidation — never per batch.

Concurrency contract: the ingest window EXCLUDES online DDL on the
target table both ways — `BulkIngest` refuses to start while a DDL job
on the table is queued/running, and the DDL worker parks its job steps
while `Storage.table_ingesting` reports a live window. Session-level
schema changes that bypass the job queue are caught by the schema
fingerprint re-check at publish (the ingest aborts instead of publishing
rows encoded against a stale schema).

`SET tidb_bulk_ingest = OFF` routes both entry points back to their
legacy paths (per-batch segment ingest for bulk_load, 2000-row txn
batches for LOAD DATA) as a live fallback.
"""

from __future__ import annotations

import numpy as np

from ..codec import tablecodec
from ..errors import DuplicateEntry, TiDBError
from ..mysqltypes.datum import Datum, K_DEC, K_FLOAT, K_INT, K_STR, K_TIME, K_UINT
from ..mysqltypes.mydecimal import Dec
from ..storage.segment import ColSpec, ColumnarRun, IntIndexRun, Run
from ..utils import metrics as M
from ..utils.failpoint import inject as _fp

INT_KINDS = (K_INT, K_TIME)  # kinds whose index keys encode as 0x03+BE(int)


class IngestAborted(TiDBError):
    """The ingest window could not start or publish (concurrent DDL,
    schema changed under the window). Nothing became visible."""


def publish_barrier(store, table_id: int, tiles=None) -> None:
    """The shared publish tail every segment producer runs AFTER its WAL
    record is appended (bulk ingest here, the delta-main compactor in
    storage/compact.py): the semi-sync durability wait, then ONE
    data-version bump — which invalidates every session's version-checked
    tile/build-side cache entries for the table. Pass the local session's
    tile cache to ALSO drop its decoded tiles eagerly (remote sessions
    re-validate via the version bump alone)."""
    # full publish durability point: the record is already fsynced locally
    # (the producer syncs under the kv lock), but a semi-sync primary must
    # ALSO wait for the standby's ack before this publish may ack — the
    # kill-primary→promote crashpoint round caught exactly this gap.
    # Group-commit ON makes this a covered-seq fast path, never a second
    # fsync.
    store.wal_sync()
    # ONE schema-version barrier for the whole publish: data version bump
    # + tile/build-side invalidation, not per batch
    store.bump_version([tablecodec.record_prefix(table_id)])
    if tiles is not None:
        tiles.invalidate_table(table_id)


def kind_of(ft) -> int:
    """Column kind for the bulk codecs. The PR 11 K_INT fallthrough bug
    lived here: DOUBLE/FLOAT columns fell through to K_INT and were
    silently truncated to integers — floats now map to K_FLOAT, and
    UNSIGNED ints to K_UINT (a K_INT unsigned lane would emit 0x03
    INT_FLAG index keys where the txn path emits 0x04 UINT_FLAG — the
    two routes' index entries would never match)."""
    if ft.is_decimal():
        return K_DEC
    if ft.is_float():
        return K_FLOAT
    if ft.is_time():
        return K_TIME
    if ft.is_string():
        return K_STR
    if ft.is_unsigned:
        return K_UINT
    return K_INT


def datum_for(kind: int, value, scale: int = 0) -> Datum:
    """ONE kind→Datum routing switch for every per-row bulk fallback
    (this engine's slow index path AND models/tpch's legacy per-row
    paths) — the PR 11 K_INT fallthrough survived as long as it did
    because three hand-copied versions of this dispatch existed."""
    if kind == K_DEC:
        return Datum.d(Dec(int(value), scale))
    if kind == K_FLOAT:
        return Datum.f(float(value))
    if kind == K_STR:
        if isinstance(value, bytes):
            return Datum.s(value.decode("utf8"))
        return Datum.s(str(value))
    return Datum(int(kind), int(value))


def _schema_fingerprint(info) -> tuple:
    """What the encoded artifact depends on: column identities/kinds and
    the writable index set. Changes here between begin and publish mean
    the artifact no longer matches the table — the ingest must abort."""
    return (
        tuple((c.id, c.offset, c.name, kind_of(c.ft), max(c.ft.decimal, 0))
              for c in info.columns),
        # state-"none" indexes are invisible to the ingest (no plane is
        # built for them) AND legal to appear mid-window: an ALTER that
        # enqueued during the window parks at state none until the
        # window closes, then backfills over the published rows
        tuple((ix.id, ix.state, ix.unique, tuple(ix.col_offsets))
              for ix in info.indexes if ix.state != "none"),
        info.pk_is_handle,
    )


class BulkIngest:
    """One bulk-ingest window over one table: build sorted KV artifacts
    from columnar input, publish them atomically. Use as a context
    manager; an exception (or explicit abort) leaves NOTHING visible."""

    def __init__(self, session, info, db: str | None = None,
                 enforce_unique: bool = False, require_empty: bool = False):
        self.session = session
        self.store = session.store
        self.info = info
        self._db = db or session.current_db
        # in-batch pk/unique-key duplicate detection (LOAD DATA parity
        # with the txn path; bulk_load keeps the documented Lightning
        # ingest semantics — the caller owns dedup)
        self.enforce_unique = enforce_unique
        # Lightning physical-mode restriction, enforced ATOMICALLY: the
        # publish re-checks table emptiness under the kv lock, so a
        # commit racing in between an advance check and the publish
        # aborts the ingest instead of being silently shadowed
        self.require_empty = require_empty
        self._runs: list = []
        self._rows = 0
        self._bytes = 0
        self._open = False
        self._fingerprint = _schema_fingerprint(info)
        self.store.begin_table_ingest(info.id)
        self._open = True
        try:
            self._check_no_ddl()
        except BaseException:
            self.close()
            raise

    def _check_no_ddl(self) -> None:
        txn = self.store.begin()
        try:
            from ..catalog.meta import Meta

            jobs = Meta(txn).jobs()
        finally:
            txn.rollback()
        for job in jobs:
            if job.table_id == self.info.id:
                raise IngestAborted(
                    f"bulk ingest into {self.info.name!r} refused: DDL job "
                    f"{job.id} ({job.type}) is queued/running on the table — "
                    f"the ingest window excludes concurrent DDL"
                )

    # --- artifact build ----------------------------------------------------

    def add_columns(self, names: list[str], arrays: list[np.ndarray],
                    kinds: list[int] | None = None,
                    valids: list[np.ndarray | None] | None = None) -> int:
        """Vectorized encode of one columnar batch into pending runs.
        `arrays` follow the bulk_load contract: decimal lanes carry
        already-scaled int64 values at the column's schema scale. The
        ingest takes OWNERSHIP of the arrays (they become the store's
        segment payloads — callers must not mutate them afterwards)."""
        info = self.info
        col_infos = [info.col_by_name(n) for n in names]
        if kinds is None:
            kinds = [kind_of(c.ft) for c in col_infos]
        n = len(arrays[0]) if arrays else 0
        if n == 0:
            return 0

        specs: list[ColSpec] = []
        canon: list[np.ndarray] = []
        for c, k, arr in zip(col_infos, kinds, arrays):
            v = None
            if k == K_STR:
                # object str arrays pass through UNCONVERTED on in-memory
                # stores: they are already the scan-side chunk form. On a
                # DURABLE store they canonicalize NOW — the WAL 'C' record
                # stores 'S' lanes (which strip trailing NULs, the v2
                # heuristic accepted project-wide), and memory must serve
                # the SAME bytes recovery will (never diverge from the
                # durable state the ack promised)
                data = np.asarray(arr)
                if data.dtype.kind == "U" or (
                    data.dtype.kind == "O" and self.store.wal is not None
                ):
                    from ..storage.segment import canonical_str_array

                    data = canonical_str_array(data)
            elif k == K_FLOAT:
                data = np.ascontiguousarray(arr, dtype=np.float64)
            elif k == K_UINT:
                data = np.ascontiguousarray(arr, dtype=np.uint64)
            else:
                data = np.asarray(arr).astype(np.int64, copy=False)
            canon.append(data)
            scale = max(c.ft.decimal, 0) if k == K_DEC else 0
            specs.append(ColSpec(c.id, k, scale, data, v))
        if valids is not None:
            for spec, v in zip(specs, valids):
                if v is not None and not v.all():
                    spec.valid = np.ascontiguousarray(v, dtype=bool)

        # handles: clustered int pk IS the handle; else batch-alloc
        if info.pk_is_handle:
            hc = info.handle_col()
            pos = next(i for i, c in enumerate(col_infos) if c.offset == hc.offset)
            handles = canon[pos]
            if handles.dtype == np.uint64:
                # record keys order by the SIGNED bit pattern (sign-flip
                # BE), and uint64 np.diff wraps to always-positive —
                # out-of-order unsigned pks would pass as presorted
                handles = handles.view(np.int64)
            presorted = bool((np.diff(handles) > 0).all()) if n > 1 else True
        else:
            first = self.session.alloc_auto_id(info, n)
            handles = np.arange(first, first + n, dtype=np.int64)
            presorted = True

        rec = ColumnarRun.build(info.id, handles, specs, 0, presorted=presorted)
        if not presorted:
            # index planes follow the sorted order — data, handles AND
            # valid masks (rec.cols are the take()-reordered specs; the
            # unsorted originals would attribute NULLs to the wrong rows)
            handles = rec.handles_arr
            specs = rec.cols
            canon = [s.data for s in specs]
        if self.enforce_unique and rec.n > 1 and bool(
            (np.diff(rec.handles_arr) == 0).any()
        ):
            dup = int(rec.handles_arr[np.nonzero(np.diff(rec.handles_arr) == 0)[0][0]])
            raise DuplicateEntry(f"Duplicate entry '{dup}' for key 'PRIMARY'")
        self._runs.append(rec)
        self._bytes += int(handles.nbytes) + sum(int(d.nbytes) for d in canon)

        # secondary indexes (skip unwritable states and the clustered pk)
        pos_by_off = {c.offset: i for i, c in enumerate(col_infos)}
        for ix in info.indexes:
            if ix.state in ("none", "delete_only") or (info.pk_is_handle and ix.primary):
                continue
            poss = [pos_by_off.get(off) for off in ix.col_offsets]
            # NULL-bearing index columns must take the per-row path: the
            # int-key fast plane would index the 0 placeholder as a real
            # value (and trip a spurious unique-dup on multiple NULLs) —
            # index_value_key encodes NULL keys properly, handle-suffixed
            # so MySQL's many-NULLs-in-a-unique-index semantics hold
            has_null = any(
                p is not None and specs[p].valid is not None for p in poss
            )
            if not has_null and all(p is not None and kinds[p] in INT_KINDS for p in poss):
                kcols = [canon[p] for p in poss]
                run = IntIndexRun.build(info.id, ix.id, kcols, handles, ix.unique, 0)
                if self.enforce_unique and ix.unique and run.n > 1:
                    same = np.ones(run.n - 1, dtype=bool)
                    for c in run.key_cols:  # sorted: duplicates are adjacent
                        same &= np.diff(c) == 0
                    if bool(same.any()):
                        i = int(np.nonzero(same)[0][0])
                        vals = "-".join(str(int(c[i])) for c in run.key_cols)
                        raise DuplicateEntry(
                            f"Duplicate entry '{vals}' for key '{ix.name}'"
                        )
                self._runs.append(run)
                self._bytes += sum(int(c.nbytes) for c in run.key_cols)
            else:  # string/decimal/missing/NULL-bearing index cols — per-row fallback
                kvs: list[tuple[bytes, bytes]] = []
                self._slow_index_kvs(ix, col_infos, canon, kinds, handles, kvs,
                                     [s.valid for s in specs])
                if self.enforce_unique and ix.unique:
                    seen = set()
                    for k, _v in kvs:
                        if k in seen:
                            raise DuplicateEntry(
                                f"Duplicate entry for key '{ix.name}'"
                            )
                        seen.add(k)
                self._runs.extend(runs_from_kvs(kvs, 0))
                self._bytes += sum(len(k) + len(v) for k, v in kvs)
        self._rows += n
        M.INGEST_BYTES.inc(
            int(handles.nbytes) + sum(int(d.nbytes) for d in canon), stage="encode"
        )
        return n

    def _slow_index_kvs(self, ix, col_infos, canon, kinds, handles, kvs,
                        valids=None) -> None:
        from ..table.table import Table

        info = self.info
        tbl = Table(info)
        n_tbl_cols = len(info.columns)
        offsets = [c.offset for c in col_infos]
        scales = [max(c.ft.decimal, 0) if k == K_DEC else 0
                  for c, k in zip(col_infos, kinds)]
        if valids is None:
            valids = [None] * len(col_infos)
        for i in range(len(handles)):
            full = [Datum.null()] * n_tbl_cols
            for off, arr, k, sf, vm in zip(offsets, canon, kinds, scales, valids):
                if vm is not None and not vm[i]:
                    continue  # NULL stays Datum.null()
                full[off] = datum_for(k, arr[i], sf)
            for c in info.columns:
                if c.hidden and c.name == "_tidb_rowid":
                    full[c.offset] = Datum.i(int(handles[i]))
            ikey, ival, _ = tbl.index_value_key(ix, full, int(handles[i]))
            kvs.append((ikey, ival))

    # --- publish -----------------------------------------------------------

    def commit(self) -> int:
        """Publish every pending run atomically: one WAL ingest record,
        one version bump, one cache invalidation. A crash before the WAL
        append leaves the ingest fully absent; after it, fully visible."""
        if not self._open:
            raise IngestAborted("ingest window already closed")
        # crashpoint: artifacts built and sorted, NOTHING journaled or
        # published — recovery must see the ingest as absent
        _fp("ingest/after-artifact-before-publish")
        if _schema_fingerprint(self.info_now()) != self._fingerprint:
            self.close()
            raise IngestAborted(
                f"bulk ingest into {self.info.name!r} aborted: the table's "
                f"schema changed during the ingest window (nothing published)"
            )
        try:
            runs = self._runs
            commit_ts = self.store.tso.next()
            for r in runs:
                r.commit_ts = commit_ts
            self.store.mvcc.ingest_runs(runs, precondition=self._precondition())
            publish_barrier(self.store, self.info.id,
                            tiles=self.session.cop.tiles)
            M.INGEST_ROWS.inc(self._rows)
            if self.store.wal is not None:
                M.INGEST_BYTES.inc(self._bytes, stage="wal")
            M.INGEST_BYTES.inc(self._bytes, stage="publish")
            return self._rows
        finally:
            self.close()

    def _precondition(self):
        if not self.require_empty:
            return None
        from ..planner.ranger import prefix_next

        prefix = tablecodec.record_prefix(self.info.id)
        end = prefix_next(prefix)
        mvcc = self.store.mvcc

        def check():  # runs under the kv lock, before anything journals
            if mvcc.range_occupied(prefix, end):
                raise IngestAborted(
                    f"bulk ingest into {self.info.name!r} aborted: the table "
                    f"gained rows (or in-flight locks) during the ingest "
                    f"window — conflicts need the txn path (nothing published)"
                )

        return check

    def info_now(self):
        """Re-fetch the table info as the publish-time schema witness."""
        try:
            t = self.session.infoschema().table(self._db, self.info.name)
        except TiDBError:
            self.close()
            raise IngestAborted(
                f"bulk ingest aborted: table {self.info.name!r} vanished "
                f"during the ingest window"
            ) from None
        if t.id != self.info.id:
            self.close()
            raise IngestAborted(
                f"bulk ingest aborted: table {self.info.name!r} was dropped "
                f"and recreated during the ingest window"
            )
        return t

    def close(self) -> None:
        if self._open:
            self._open = False
            self.store.end_table_ingest(self.info.id)

    def abort(self) -> None:
        self._runs = []
        self.close()

    def __del__(self):  # leaked windows must not block DDL forever
        self.close()

    def __enter__(self) -> "BulkIngest":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None and self._open:
            self.commit()
        else:
            self.abort()


def runs_from_kvs(kvs: list[tuple[bytes, bytes]], commit_ts: int) -> list[Run]:
    """Arbitrary (key, value) pairs → fixed-width byte Runs (one per key
    width), sorted but NOT published — the BulkIngest building block the
    old mvcc.ingest published eagerly."""
    by_w: dict[int, list[tuple[bytes, bytes]]] = {}
    for k, v in kvs:
        by_w.setdefault(len(k), []).append((k, v))
    runs = []
    for w, group in by_w.items():
        n = len(group)
        key_mat = np.frombuffer(b"".join(k for k, _ in group), dtype=np.uint8).reshape(n, w)
        vbuf = b"".join(v for _, v in group)
        lens = np.fromiter((len(v) for _, v in group), np.int64, n)
        starts = np.zeros(n, dtype=np.int64)
        np.cumsum(lens[:-1], out=starts[1:])
        runs.append(Run.build(key_mat, vbuf, starts, lens, commit_ts))
    return runs

from .executors import build_executor, ExecContext, drain

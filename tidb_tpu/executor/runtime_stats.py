"""Per-operator runtime statistics for EXPLAIN ANALYZE
(ref: util/execdetails/execdetails.go:34 ExecDetails; the reference
collects per-executor rows/loops/time in the guarded Next wrapper,
executor/executor.go:268, and merges cop-task summaries at
distsql/select_result.go:341).

Stats attach by wrapping the built executor tree's bound `next` methods —
no class-identity changes, so plan-shape decisions (which use isinstance
on executors) are unaffected. Parent times are cumulative over children,
matching the reference's presentation.
"""

from __future__ import annotations

import time

from .executors import Executor


def child_execs(e: Executor) -> list[Executor]:
    out = []
    for attr in ("child", "left", "right", "outer"):
        c = getattr(e, attr, None)
        if isinstance(c, Executor):
            out.append(c)
    cs = getattr(e, "children", None)
    if isinstance(cs, (list, tuple)):
        out.extend(c for c in cs if isinstance(c, Executor))
    return out


def attach_runtime_stats(root: Executor) -> dict[int, dict]:
    """Instrument every node's next(); returns {id(executor): stats}."""
    stats: dict[int, dict] = {}

    def wrap(e: Executor) -> None:
        st = {"rows": 0, "loops": 0, "time_ns": 0}
        stats[id(e)] = st
        orig_next = e.next

        def timed_next():
            t0 = time.perf_counter_ns()
            c = orig_next()
            st["time_ns"] += time.perf_counter_ns() - t0
            st["loops"] += 1
            if c is not None:
                st["rows"] += c.num_rows
            return c

        e.next = timed_next
        for ch in child_execs(e):
            wrap(ch)

    wrap(root)
    return stats


def render_tree(root: Executor, stats: dict[int, dict]) -> list[str]:
    lines: list[str] = []

    def rec(e: Executor, depth: int) -> None:
        st = stats.get(id(e), {"rows": 0, "loops": 0, "time_ns": 0})
        extra = ""
        dag = getattr(e, "dag", None)
        if dag is not None:
            parts = []
            if dag.selection:
                parts.append("sel")
            if dag.agg:
                parts.append("agg")
            if dag.topn:
                parts.append("topn")
            if dag.limit:
                parts.append("limit")
            if parts:
                extra = f" cop:[{'+'.join(parts)}]"
        # which engine ran (tpu|host) and, on fallback, why — set by
        # executors with a device path (WindowExec, cop readers)
        eng = getattr(e, "last_engine", "")
        if eng:
            extra += f" engine:{eng}"
            reason = getattr(e, "fallback_reason", "")
            if reason:
                extra += f" fallback:[{reason}]"
        lines.append(
            f"{'  ' * depth}{type(e).__name__}{extra} "
            f"rows:{st['rows']} loops:{st['loops']} time:{st['time_ns'] / 1e6:.3f}ms"
        )
        for ch in child_execs(e):
            rec(ch, depth + 1)

    rec(root, 0)
    return lines

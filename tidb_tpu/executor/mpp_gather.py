"""MPPGather — dispatches a sliced fragment plan to the mesh MPP engine
(ref: executor/mpp_gather.go:42 MPPGather, :54 appendMPPDispatchReq;
store/copr/mpp.go:461 DispatchMPPTasks).

Where the reference serializes fragments to tipb, dials TiFlash stores
and streams exchanged chunks back, this gather step feeds tile-cache
column lanes into ONE compiled SPMD program (parallel/mpp.py) and reads
the psum'd partials / joined rows straight off the mesh."""

from __future__ import annotations

import numpy as np

from ..chunk.chunk import Chunk
from ..codec import tablecodec
from ..planner.fragment import MPPPlan, slice_plan
from ..planner.plans import Aggregation, Join, LogicalPlan
from .executors import ExecContext, Executor, FinalHashAggExec


def _has_join(plan: LogicalPlan) -> bool:
    if isinstance(plan, Join):
        return True
    return any(_has_join(c) for c in plan.children)


def try_build_mpp(plan: LogicalPlan, ctx: ExecContext) -> Executor | None:
    """Attempt the mesh MPP path for a plan subtree; None → caller builds
    the root (host) operator tree instead."""
    if ctx.engine == "host":
        return None
    if ctx.vars.get("tidb_allow_mpp", "ON") != "ON":
        return None
    if not _has_join(plan):
        return None
    mplan = slice_plan(plan)
    if mplan is None:
        return None
    # uncommitted writes on any scanned table → membuffer must be visible;
    # tile lanes come from the committed snapshot only (UnionScan later)
    if ctx.txn is not None:
        for sf in mplan.scans:
            prefix = tablecodec.record_prefix(sf.ds.table.id)
            if any(k.startswith(prefix) for k in ctx.txn.membuf):
                return None
    gather = MPPGatherExec(mplan, ctx)
    if mplan.agg is not None:
        agg = mplan.agg
        return FinalHashAggExec(gather, agg.group_by, agg.aggs, [c.ft for c in agg.out_cols])
    return gather


class MPPGatherExec(Executor):
    def __init__(self, mplan: MPPPlan, ctx: ExecContext):
        self.mplan = mplan
        self.ctx = ctx
        if mplan.agg is not None:
            fts = [g.ret_type for g in mplan.agg.group_by]
            for a in mplan.agg.aggs:
                fts.extend(ft for _, ft in a.partial_final_types())
        else:
            fts = [c.ft for c in mplan.out_cols]
        self.out_fts = fts
        self._pending: list[Chunk] | None = None

    def open(self):
        self._pending = None

    def next(self) -> Chunk | None:
        if self._pending is None:
            self._pending = self._produce()
        if not self._pending:
            return None
        return self._pending.pop(0)

    def _produce(self) -> list[Chunk]:
        chunk = self._dispatch()
        if chunk is not None:
            return [chunk]
        # engine declined at prepare time (non-unique build keys,
        # non-lowerable conds, ...): degrade to the host join path over
        # the original join subtree (slicing never mutated it)
        from .executors import LocalPartialAggExec, _ACTIVE_SESSION, build_executor, drain

        if self.ctx.vars.get("tidb_enforce_mpp", "OFF") == "ON":
            # the user demanded MPP; surface why it degraded (ref:
            # planner ErrInternal warnings under tidb_enforce_mpp)
            sess = _ACTIVE_SESSION.get()
            if sess is not None:
                reason = getattr(self.ctx.cop.mpp, "last_fallback_reason", "") or "not supported"
                sess.warnings.append(
                    f"MPP mode may be blocked because: {reason} (tidb_enforce_mpp=ON)"
                )

        host_ctx = ExecContext(
            self.ctx.cop, self.ctx.read_ts, engine="host",
            vars=dict(self.ctx.vars, tidb_allow_mpp="OFF"), txn=self.ctx.txn,
        )
        if self.mplan.agg is None:
            return [drain(build_executor(self.mplan.join_node, host_ctx))]
        # we sit under a FinalHashAggExec expecting PARTIAL layout
        p = LocalPartialAggExec(
            build_executor(self.mplan.join_node, host_ctx),
            self.mplan.agg.group_by,
            self.mplan.agg.aggs,
        )
        p.open()
        parts = []
        while True:
            c = p.next()
            if c is None:
                break
            parts.append(c)
        p.close()
        return parts

    def _dispatch(self) -> Chunk | None:
        from ..parallel.mesh import make_mesh
        from ..parallel.mpp import ScanData

        client = self.ctx.cop
        engine = client.mpp
        scan_datas = []
        for sf in self.mplan.scans:
            table = sf.ds.table
            prefix = tablecodec.record_prefix(table.id)
            ver, last_commit_ts = client.tiles.storage.data_version(prefix)
            # snapshot rule (tilecache.py get_batch): lanes built for a
            # read BELOW the last commit describe an older snapshot than
            # the version counter says — never cache or serve them under
            # (table, version) identity
            cacheable = self.ctx.read_ts >= last_commit_ts
            if not cacheable:
                ver = -1
            data, valid, orig_offs = [], [], []
            parts = None
            for pc in sf.ds.out_cols:
                off = pc.orig_offset
                orig_offs.append(off)
                ck = (table.id, ver, off)
                ent = engine._host_lane_cache.get(ck) if cacheable else None
                if ent is None:
                    # whole-table lane concatenation is O(table bytes) per
                    # column: do it once per (table, version), not per
                    # dispatch (the host twin of the device-lane cache)
                    if parts is None:
                        tasks = client.build_tasks(table.id, [(prefix, prefix + b"\xff")])
                        parts = [
                            client.tiles.get_batch(table, t.start, t.end, self.ctx.read_ts)
                            for t in tasks
                        ]
                        parts = [b for b in parts if b.n_rows]
                    if parts:
                        ent = (
                            np.concatenate([b.data[off] for b in parts]),
                            np.concatenate([b.valid[off] for b in parts]),
                        )
                    else:
                        from ..chunk.chunk import col_numpy_dtype, VARLEN

                        dt = col_numpy_dtype(pc.ft)
                        ent = (
                            np.empty(0, dtype=object if dt is VARLEN else dt),
                            np.zeros(0, dtype=bool),
                        )
                    if cacheable:
                        engine._host_lane_put(ck, ent)
                data.append(ent[0])
                valid.append(ent[1])
            scan_datas.append(
                ScanData(sf, data, valid, version=ver, shared=engine, orig_offs=orig_offs)
            )
        mesh = engine._mesh if getattr(engine, "_mesh", None) is not None else make_mesh()
        engine._mesh = mesh
        res = engine.execute(self.mplan, scan_datas, mesh, self.ctx.vars)
        if res is None:
            return None
        chunk, agg_done = res
        if chunk is not None and self.mplan.agg is not None and not agg_done:
            # the mesh joined the rows; partial aggregation finishes here
            # (group-key domains that direct addressing can't hold)
            from ..copr.dag import DAGRequest, ScanNode
            from ..copr.dag import AggNode as _DagAgg
            from ..copr.host_engine import _exec_agg

            pseudo = DAGRequest(
                ScanNode(0, list(range(chunk.num_cols)), chunk.field_types(), [])
            )
            pseudo.agg = _DagAgg(self.mplan.agg.group_by, self.mplan.agg.aggs)
            chunk = _exec_agg(pseudo, chunk, None)
        return chunk

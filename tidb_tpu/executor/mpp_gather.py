"""MPPGather — dispatches a sliced fragment plan to the mesh MPP engine
(ref: executor/mpp_gather.go:42 MPPGather, :54 appendMPPDispatchReq;
store/copr/mpp.go:461 DispatchMPPTasks).

Where the reference serializes fragments to tipb, dials TiFlash stores
and streams exchanged chunks back, this gather step feeds tile-cache
column lanes into ONE compiled SPMD program (parallel/mpp.py) and reads
the psum'd partials / joined rows straight off the mesh."""

from __future__ import annotations

import logging

import numpy as np

from ..chunk.chunk import Chunk
from ..codec import tablecodec
from ..planner.fragment import MPPPlan, slice_plan
from ..planner.ranger import prefix_next
from ..planner.plans import Join, LogicalPlan
from ..sched.scheduler import raise_if_interrupted
from ..utils import memory
from .executors import ExecContext, Executor, FinalHashAggExec

log = logging.getLogger("tidb_tpu.mpp")


def _has_join(plan: LogicalPlan) -> bool:
    if isinstance(plan, Join):
        return True
    return any(_has_join(c) for c in plan.children)


def try_build_mpp(plan: LogicalPlan, ctx: ExecContext) -> Executor | None:
    """Attempt the mesh MPP path for a plan subtree; None → caller builds
    the root (host) operator tree instead."""
    if ctx.engine == "host":
        return None
    if ctx.vars.get("tidb_allow_mpp", "ON") != "ON":
        return None
    if not _has_join(plan):
        return None
    reason: list = []
    mplan = slice_plan(plan, reason)
    if mplan is None:
        # a slice-time decline (string/float join keys, plan shape) is a
        # TYPED fallback too — counted ONCE per statement per failing
        # join node: try_build_mpp fires again for every nested Join the
        # host build recurses into (and an Aggregation pass precedes its
        # Join's), so the dedup keys on (statement ctx, failing node)
        if isinstance(plan, Join) and reason:
            key, detail, src = reason[0]
            seen = getattr(ctx, "_mpp_declines", None)
            if seen is None:
                seen = ctx._mpp_declines = set()
            if id(src) not in seen:
                seen.add(id(src))
                engine = ctx.cop.mpp
                engine._fallback(key, detail)
                if ctx.vars.get("tidb_enforce_mpp", "OFF") == "ON":
                    from .executors import _ACTIVE_SESSION

                    sess = _ACTIVE_SESSION.get(None)
                    if sess is not None:
                        sess.warnings.append(
                            f"MPP mode may be blocked because: {detail} "
                            f"(tidb_enforce_mpp=ON)"
                        )
        return None
    # uncommitted writes on any scanned table → membuffer must be visible;
    # tile lanes come from the committed snapshot only (UnionScan later)
    if ctx.txn is not None:
        for sf in mplan.scans:
            prefix = tablecodec.record_prefix(sf.ds.table.id)
            if any(k.startswith(prefix) for k in ctx.txn.membuf):
                return None
    gather = MPPGatherExec(mplan, ctx)
    if mplan.agg is not None:
        agg = mplan.agg
        return FinalHashAggExec(gather, agg.group_by, agg.aggs, [c.ft for c in agg.out_cols])
    return gather


class MPPGatherExec(Executor):
    def __init__(self, mplan: MPPPlan, ctx: ExecContext):
        self.mplan = mplan
        self.ctx = ctx
        if mplan.agg is not None:
            fts = [g.ret_type for g in mplan.agg.group_by]
            for a in mplan.agg.aggs:
                fts.extend(ft for _, ft in a.partial_final_types())
        else:
            fts = [c.ft for c in mplan.out_cols]
        self.out_fts = fts
        self._pending: list[Chunk] | None = None

    def open(self):
        self._pending = None

    def next(self) -> Chunk | None:
        if self._pending is None:
            self._pending = self._produce()
        if not self._pending:
            return None
        return self._pending.pop(0)

    def _produce(self) -> list[Chunk]:
        chunk = self._dispatch()
        if chunk is not None:
            return [chunk]
        # engine declined at prepare time (non-unique build keys,
        # non-lowerable conds, ...): degrade to the host join path over
        # the original join subtree (slicing never mutated it)
        from .executors import LocalPartialAggExec, _ACTIVE_SESSION, build_executor, drain

        if self.ctx.vars.get("tidb_enforce_mpp", "OFF") == "ON":
            # the user demanded MPP; surface why it degraded (ref:
            # planner ErrInternal warnings under tidb_enforce_mpp)
            sess = _ACTIVE_SESSION.get()
            if sess is not None:
                reason = getattr(self.ctx.cop.mpp, "last_fallback_reason", "") or "not supported"
                sess.warnings.append(
                    f"MPP mode may be blocked because: {reason} (tidb_enforce_mpp=ON)"
                )

        host_ctx = ExecContext(
            self.ctx.cop, self.ctx.read_ts, engine="host",
            vars=dict(self.ctx.vars, tidb_allow_mpp="OFF"), txn=self.ctx.txn,
        )
        if self.mplan.agg is None:
            return [drain(build_executor(self.mplan.join_node, host_ctx))]
        # we sit under a FinalHashAggExec expecting PARTIAL layout
        p = LocalPartialAggExec(
            build_executor(self.mplan.join_node, host_ctx),
            self.mplan.agg.group_by,
            self.mplan.agg.aggs,
        )
        p.open()
        parts = []
        while True:
            c = p.next()
            if c is None:
                break
            parts.append(c)
        p.close()
        return parts

    def _dispatch(self) -> Chunk | None:
        """Run the fragment plan on the mesh under the UNIFIED device
        fault domain (PR 8; arXiv:2203.01877 wants the accelerator path a
        drop-in peer of the host path, arXiv:2604.28079 wants its
        fallback graceful and observable):

          * the shared per-lane circuit breakers gate the dispatch
            upfront — when every lane refuses, MPP declines with typed
            reason `breaker_open` at zero exception cost (exactly the cop
            client's all-lanes-open → host rule), and a successful mesh
            run doubles as the half-open probe;
          * engine-boundary failures are classified into the typed
            taxonomy and transients retry through a Backoffer drawing the
            statement's per-task sleep budget, KILL/deadline-aware;
          * the O(table-bytes) host-lane concatenation and the per-scan
            mesh uploads poll the scheduler's shared interrupt gate and
            charge the statement's MemTracker, so KILL, runaway verdicts
            and memory arbitration reach MPP statements mid-flight.
        """
        from ..copr.retry import Backoffer, guarded_device_call
        from ..parallel.mesh import make_mesh

        client = self.ctx.cop
        engine = client.mpp
        # reset per dispatch — the reason surface must describe THIS
        # statement, never a stale decline from a previous one
        engine.last_fallback_reason = ""
        engine._decline_key = "not_supported"
        sctx = client._sched_ctx()
        st = client._stats_fn(sctx)
        trace = getattr(sctx, "trace", None)
        st("mpp_tasks")
        rc = getattr(sctx, "runaway", None)
        if rc is not None:
            # the runaway watch list gates MPP like it gates cop
            # admission: a quarantined digest is rejected (8254) before a
            # single lane is built, a COOLDOWN watch demotes the backoff
            # budget the retry loop below will draw from
            rc.on_admission()

        def gate():
            raise_if_interrupted(sctx.session, sctx.deadline)

        tpu = client.tpu
        # claim the mesh: every lane whose breaker admits work (an open
        # breaker past cooldown flips half-open here and this dispatch IS
        # its probe). The SPMD program spans the whole mesh, so a fatal
        # mesh fault feeds every admitted lane's breaker — and when no
        # lane admits, MPP declines before building a single lane.
        admitted = [l for l in tpu.lanes if l.breaker.allow()]
        if not admitted:
            engine._fallback(
                "breaker_open",
                f"device circuit breaker open ({tpu.breakers_describe()})",
            )
            st("mpp_fallbacks")
            st("breaker_skips")
            if trace is not None and trace.recording:
                trace.closed_span("mpp.degrade", 0.0, reason="breaker_open",
                                  state=tpu.breakers_describe())
            return None
        resolved = False  # admitted breakers heard success/failure/abort
        try:
            with memory.bind(getattr(sctx, "mem", None)):
                scan_datas = self._build_scan_datas(client, engine, gate)
                st("processed_rows", sum(sd.n_rows for sd in scan_datas))
                mesh = engine._mesh if getattr(engine, "_mesh", None) is not None else make_mesh()
                engine._mesh = mesh
                bo = Backoffer.for_ctx(sctx, stats=st)
                # fused-chain flag: the store-wide GLOBAL overrides the
                # session copy so `SET GLOBAL tidb_tpu_mpp_fused=OFF` is a
                # live incident fallback for EVERY session, not just ones
                # opened after it (the engine is per-client, so there is
                # no store-wide engine attribute to poke à la PR 7)
                gv = getattr(client.storage, "global_vars", None) or {}
                fused = gv.get(
                    "tidb_tpu_mpp_fused",
                    self.ctx.vars.get("tidb_tpu_mpp_fused", "ON"),
                ) == "ON"
                res, err = guarded_device_call(
                    # the OFF path (the live incident fallback) must not
                    # pay the per-dispatch meta read or lazily register
                    # the build cache with the memory arbiter — neither
                    # is consulted without fusion
                    lambda: engine.execute(self.mplan, scan_datas, mesh,
                                           self.ctx.vars, gate=gate,
                                           fused=fused,
                                           build_cache=(client.storage.build_cache
                                                        if fused else None),
                                           schema_ver=(self._schema_version(client)
                                                       if fused else -1)),
                    bo,
                    breakers=[l.breaker for l in admitted],
                    forced=False,  # enforce_mpp degrades with a warning,
                    # like the reference planner — it never hard-fails
                    failpoint="mpp/device-error",
                )
            # success/fault resolved every admitted breaker inside the
            # guard; a prepare-time DECLINE touched no device, so the
            # finally below releases any claimed probe slots instead
            resolved = err is not None or res is not None
            if err is not None:
                # terminal device fault: degrade to the host join with the
                # typed reason — never silently (a masked lowering bug
                # would hide behind the host answer)
                engine._fallback("device_error", f"{type(err).__name__}: {err}")
                st("mpp_fallbacks")
                st("fallback_errors")
                log.warning("MPP mesh fault (%s); falling back to host join", err)
                if trace is not None and trace.recording:
                    trace.closed_span("mpp.degrade", 0.0, reason="device_error",
                                      error=type(err).__name__)
                return None
            if res is None:
                # prepare declined or the run drop-guarded (typed reason
                # already counted by the engine)
                st("mpp_fallbacks")
                if trace is not None and trace.recording:
                    trace.closed_span("mpp.degrade", 0.0,
                                      reason=engine._decline_key,
                                      detail=engine.last_fallback_reason)
                return None
        finally:
            if not resolved:
                # an interrupt/quota verdict escaped mid-build: release
                # any claimed half-open probe slots without counting a
                # device fault either way
                for l in admitted:
                    l.breaker.record_aborted()
        chunk, agg_done = res
        if chunk is not None and self.mplan.agg is not None and not agg_done:
            return self._host_finish_agg(chunk)
        return chunk

    @staticmethod
    def _schema_version(client) -> int:
        """Current catalog schema version — the build-side cache key
        component that invalidates resident join structures on ANY DDL
        (ADD/DROP INDEX, ALTER TABLE bump it; a stale structure must
        never serve). One meta read per MPP dispatch, trivial next to
        the program itself."""
        from ..catalog.meta import Meta

        txn = client.storage.begin()
        try:
            return Meta(txn).schema_version()
        finally:
            txn.rollback()

    def _build_scan_datas(self, client, engine, gate) -> list:
        """Host-side lane sets per scan fragment, through the engine's
        (table, version)-keyed host-lane cache. The concatenation is
        O(table bytes) per column: `gate` polls the shared interrupt gate
        at every column so a KILL lands within one concat tick, and each
        freshly built lane charges the statement's MemTracker through the
        TLS seam `memory.bind` armed in _dispatch (cache hits are free —
        the builder paid; the PR 4 volume-proxy rule)."""
        from ..parallel.mpp import ScanData
        from ..utils.failpoint import inject as _fp

        scan_datas = []
        for sf in self.mplan.scans:
            table = sf.ds.table
            prefix = tablecodec.record_prefix(table.id)
            ver, last_commit_ts = client.tiles.storage.data_version(prefix)
            # snapshot rule (tilecache.py get_batch): lanes built for a
            # read BELOW the last commit describe an older snapshot than
            # the version counter says — never cache or serve them under
            # (table, version) identity
            cacheable = self.ctx.read_ts >= last_commit_ts
            if not cacheable:
                ver = -1
            data, valid, orig_offs = [], [], []
            parts = None
            for pc in sf.ds.out_cols:
                gate()  # one interrupt poll per lane-concat tick
                _fp("mpp/lane-concat")
                off = pc.orig_offset
                orig_offs.append(off)
                ck = (table.id, ver, off)
                # _host_lane_get, not a raw dict read: the hit must LRU-
                # touch or the byte-budget sweep evicts by first insertion
                ent = engine._host_lane_get(ck) if cacheable else None
                if ent is None:
                    # whole-table lane concatenation is O(table bytes) per
                    # column: do it once per (table, version), not per
                    # dispatch (the host twin of the device-lane cache)
                    if parts is None:
                        tasks = client.build_tasks(table.id, [(prefix, prefix_next(prefix))])
                        parts = [
                            client.tiles.get_batch(table, t.start, t.end, self.ctx.read_ts)
                            for t in tasks
                        ]
                        parts = [b for b in parts if b.n_rows]
                    if parts:
                        ent = (
                            np.concatenate([b.data[off] for b in parts]),
                            np.concatenate([b.valid[off] for b in parts]),
                        )
                    else:
                        from ..chunk.chunk import col_numpy_dtype, VARLEN

                        dt = col_numpy_dtype(pc.ft)
                        ent = (
                            np.empty(0, dtype=object if dt is VARLEN else dt),
                            np.zeros(0, dtype=bool),
                        )
                    # freshly concatenated lane: the statement that built
                    # it carries the bytes (quota breach raises 8175 here,
                    # reaching MPP statements like any cop task)
                    memory.consume_current(int(ent[0].nbytes) + int(ent[1].nbytes))
                    if cacheable:
                        engine._host_lane_put(ck, ent)
                data.append(ent[0])
                valid.append(ent[1])
            scan_datas.append(
                ScanData(sf, data, valid, version=ver, shared=engine, orig_offs=orig_offs)
            )
        return scan_datas

    def _host_finish_agg(self, chunk: Chunk) -> Chunk:
        """The mesh joined the rows; partial aggregation finishes here
        (group-key domains that direct addressing can't hold)."""
        from ..copr.dag import DAGRequest, ScanNode
        from ..copr.dag import AggNode as _DagAgg
        from ..copr.host_engine import _exec_agg

        pseudo = DAGRequest(
            ScanNode(0, list(range(chunk.num_cols)), chunk.field_types(), [])
        )
        pseudo.agg = _DagAgg(self.mplan.agg.group_by, self.mplan.agg.aggs)
        return _exec_agg(pseudo, chunk, None)

"""Chunk-volcano executors (ref: executor/executor.go Executor iface :259,
builder.go build :119 — compact redesign).

`build_executor` is also where cop-vs-root splitting happens (the task
model, planner/core/task.go): a pushable Aggregation/TopN/Limit over a
DataSource folds into the reader's DAG (cop side, TPU-executed partials)
with a root-side merge executor above it.
"""

from __future__ import annotations

import numpy as np

from ..chunk.chunk import Chunk, Column, col_numpy_dtype, VARLEN
from ..copr.dag import AggNode, DAGRequest, LimitNode, ScanNode, SelectionNode, TopNNode
from ..errors import TiDBError
from ..expr.aggregation import AggDesc
from ..expr.expression import Column as ECol, Constant, Expression
from ..mysqltypes.datum import Datum, compare_datum
from ..mysqltypes.field_type import FieldType, TypeCode, ft_longlong
from ..mysqltypes.mydecimal import Dec, pow10
from ..planner.plans import (
    Aggregation,
    CTERef as CTERefPlan,
    Memtable as MemtablePlan,
    DataSource,
    Dual,
    Join,
    Limit,
    LogicalPlan,
    Projection,
    RecursiveCTE as RecursiveCTEPlan,
    Selection,
    SetOp,
    Sort,
    Window as WindowPlan,
)


class ExecContext:
    def __init__(self, cop_client, read_ts: int, engine: str = "auto", vars=None, txn=None):
        self.cop = cop_client
        self.read_ts = read_ts
        self.engine = engine
        self.vars = vars or {}
        self.txn = txn  # for dirty-read merge (UnionScan) later

import contextvars

# statement-scoped memory tracker consumed by drain() at materialization
# points (ref: util/memory tracker attached session->executor)
_ACTIVE_TRACKER: contextvars.ContextVar = contextvars.ContextVar("mem_tracker", default=None)
# the executing session, for KILL checks at chunk boundaries
# (ref: sessVars.Killed checked in every guarded Next, executor.go:275)
_ACTIVE_SESSION: contextvars.ContextVar = contextvars.ContextVar("active_session", default=None)


class Executor:
    out_fts: list[FieldType]

    def open(self):
        pass

    def next(self) -> Chunk | None:
        raise NotImplementedError

    def close(self):
        pass


def drain(e: Executor) -> Chunk:
    from ..sched.scheduler import raise_if_interrupted

    tracker = _ACTIVE_TRACKER.get()
    sess = _ACTIVE_SESSION.get()
    e.open()
    chunks = []
    while True:
        # the scheduler's shared interrupt gate: KILL, max_execution_time,
        # server-memory OOM kills ("oom" reason) and the runaway
        # watchdog's QUERY_LIMIT tick all fire at this chunk boundary
        # exactly like they do in admission waits and backoff sleeps
        raise_if_interrupted(sess, getattr(sess, "_deadline", None) if sess is not None else None)
        c = e.next()
        if c is None:
            break
        if c.num_rows:
            if tracker is not None:
                from ..utils.memory import chunk_bytes

                tracker.consume(chunk_bytes(c))
            chunks.append(c)
    e.close()
    out = Chunk.empty(e.out_fts, 0) if not chunks else Chunk.concat_all(chunks)
    # LAST poll after materialization: a kill verdict (user KILL, memory
    # arbiter, runaway) landing while the final concat ran must not be
    # outrun by the statement finishing — the flag would be cancelled at
    # teardown and the over-limit result served as if nothing happened
    raise_if_interrupted(sess, getattr(sess, "_deadline", None) if sess is not None else None)
    return out


# ------------------------------------------------------------------- builder


def build_executor(plan: LogicalPlan, ctx: ExecContext) -> Executor:
    if isinstance(plan, Dual):
        return DualExec()
    if isinstance(plan, DataSource):
        return _build_reader(plan, ctx)
    if isinstance(plan, (Aggregation, Join)):
        # MPP seam: Aggregation(Join…)/Join subtrees may compile into one
        # mesh SPMD program (ref: planner mppTask, task.go:2088)
        from .mpp_gather import try_build_mpp

        mpp = try_build_mpp(plan, ctx)
        if mpp is not None:
            return mpp
    if isinstance(plan, Selection):
        return SelectionExec(build_executor(plan.children[0], ctx), plan.conds)
    if isinstance(plan, Projection):
        return ProjectionExec(build_executor(plan.children[0], ctx), plan.exprs, [c.ft for c in plan.out_cols])
    if isinstance(plan, Aggregation):
        return _build_agg(plan, ctx)
    if isinstance(plan, Join):
        out_fts = [c.ft for c in plan.out_cols]
        if plan.kind in ("inner", "left") and plan.eq_conds and plan.na_key is None:
            if ctx.vars.get("tidb_opt_prefer_index_join") == "ON":
                ex = _try_index_join(plan, ctx, out_fts)
                if ex is not None:
                    return ex
            merge_ok = all(
                l.ret_type.is_string() == r.ret_type.is_string() for l, r in plan.eq_conds
            )  # ordered merge can't compare string keys against numeric ones
            if merge_ok and ctx.vars.get("tidb_opt_prefer_merge_join") == "ON":
                return MergeJoinExec(
                    build_executor(plan.children[0], ctx),
                    build_executor(plan.children[1], ctx),
                    plan.kind, plan.eq_conds, plan.other_conds, out_fts,
                )
        quota = int(ctx.vars.get("tidb_mem_quota_query", "0") or 0)
        hj_quota = int(ctx.vars.get("tidb_mem_quota_hashjoin", "0") or 0)
        if hj_quota > 0:
            quota = min(quota, hj_quota) if quota > 0 else hj_quota
        return HashJoinExec(
            build_executor(plan.children[0], ctx),
            build_executor(plan.children[1], ctx),
            plan.kind,
            plan.eq_conds,
            plan.other_conds,
            out_fts,
            na_key=plan.na_key,
            spill_limit=quota,
        )
    if isinstance(plan, MemtablePlan):
        return MemtableExec(plan)
    if isinstance(plan, CTERefPlan):
        return CTERefExec(plan)
    if isinstance(plan, RecursiveCTEPlan):
        return RecursiveCTEExec(plan, ctx)
    if isinstance(plan, WindowPlan):
        return WindowExec(
            build_executor(plan.children[0], ctx),
            plan.part_by,
            plan.order_by,
            plan.funcs,
            [c.ft for c in plan.out_cols],
            ctx,
        )
    if isinstance(plan, Sort):
        quota = int(ctx.vars.get("tidb_mem_quota_query", "0") or 0)
        sort_quota = int(ctx.vars.get("tidb_mem_quota_sort", "0") or 0)
        if sort_quota > 0:
            quota = min(quota, sort_quota) if quota > 0 else sort_quota
        return SortExec(build_executor(plan.children[0], ctx), plan.by, spill_limit=quota)
    if isinstance(plan, Limit):
        return _build_limit(plan, ctx)
    if isinstance(plan, SetOp):
        return SetOpExec([build_executor(c, ctx) for c in plan.children], plan.ops, [c.ft for c in plan.out_cols])
    raise TiDBError(f"no executor for {type(plan).__name__}")


def _build_reader(ds: DataSource, ctx: ExecContext) -> "TableReaderExec":
    visible = list(ds.table.visible_columns())
    hidden_offs = {c.offset: c for c in ds.table.columns if c.hidden}
    for pc in ds.out_cols:
        if pc.orig_offset in hidden_offs:
            # multi-table DML exposed the hidden handle column: scan emits
            # it as a trailing lane (decode fills it from the record key)
            visible.append(hidden_offs[pc.orig_offset])
    scan = ScanNode(
        ds.table.id,
        [c.offset for c in visible],
        [c.ft for c in visible],
        [c.id for c in visible],
    )
    dag = DAGRequest(scan)
    if ds.pushed_conds:
        dag.selection = SelectionNode(ds.pushed_conds)
    if ds.table.partition is not None:
        parts = getattr(ds, "pruned_parts", None)
        if parts is None:
            parts = ds.table.partition.defs
        return PartitionReaderExec(ds.table, dag, ctx, parts)
    path = getattr(ds, "path", "table")
    if path == "point":
        return PointGetExec(ds.table, dag, ctx, ds.point_handles)
    if path == "index":
        return IndexReaderExec(ds.table, dag, ctx, ds.index, ds.key_ranges)
    if path == "index_lookup":
        return IndexLookUpExec(ds.table, dag, ctx, ds.index, ds.key_ranges)
    if path == "index_merge":
        return IndexMergeReaderExec(ds.table, dag, ctx, ds.merge_branches)
    return TableReaderExec(ds.table, dag, ctx, ranges=getattr(ds, "key_ranges", None))


def _try_index_join(plan: Join, ctx: ExecContext, out_fts) -> "IndexLookupJoinExec | None":
    """Pick an index-lookup join when the inner (right) side is a base
    table with an index led by the join key (ref: planner
    exhaust_physical_plans.go tryToGetIndexJoin, simplified to the
    sysvar-gated heuristic)."""
    right = plan.children[1]
    if not isinstance(right, DataSource) or len(plan.eq_conds) != 1:
        return None
    if getattr(right, "path", "table") != "table" or getattr(right, "key_ranges", None) is not None:
        return None  # access-path ranges already consumed pushed conds
    nl = len(plan.children[0].out_cols)
    rexpr = plan.eq_conds[0][1]
    if not isinstance(rexpr, ECol):
        return None
    ridx = rexpr.idx - nl
    if not (0 <= ridx < len(right.out_cols)):
        return None
    orig = right.out_cols[ridx].orig_offset
    index = next(
        (
            ix
            for ix in right.table.indexes
            if ix.state == "public" and ix.col_offsets and ix.col_offsets[0] == orig
        ),
        None,
    )
    if index is None:
        return None
    # probe keys are key-encoded with the outer expression's type flag;
    # anything but an exact int/int match would never equal the index
    # entries' encoding (silent empty result) — gate to same-class ints
    lft = plan.eq_conds[0][0].ret_type
    rft = right.table.columns[orig].ft
    if not (lft.is_int() and rft.is_int() and lft.is_unsigned == rft.is_unsigned):
        return None
    visible = right.table.visible_columns()
    scan = ScanNode(
        right.table.id,
        [c.offset for c in visible],
        [c.ft for c in visible],
        [c.id for c in visible],
    )
    dag = DAGRequest(scan)
    if right.pushed_conds:
        dag.selection = SelectionNode(right.pushed_conds)
    variant = ctx.vars.get("tidb_opt_index_join_variant", "hash")
    cls = IndexLookupMergeJoinExec if variant == "merge" else IndexLookupJoinExec
    return cls(
        build_executor(plan.children[0], ctx), ctx, right.table, index, dag,
        plan.kind, plan.eq_conds, plan.other_conds, out_fts,
    )


def _pushable_reader(e: Executor) -> "TableReaderExec | None":
    """The reader directly below, if its DAG can still absorb an op."""
    if isinstance(e, TableReaderExec) and e.dag.agg is None and e.dag.topn is None and e.dag.limit is None:
        return e
    return None


def _reader_under(e: Executor, depth: int = 6) -> "TableReaderExec | None":
    """Descend `.child` links to the reader (through projections etc.),
    returning it only if its DAG can still absorb an op."""
    for _ in range(depth):
        if e is None or isinstance(e, TableReaderExec):
            break
        e = getattr(e, "child", None)
    return _pushable_reader(e) if isinstance(e, TableReaderExec) else None


def _build_agg(plan: Aggregation, ctx: ExecContext) -> Executor:
    from ..expr.aggregation import PUSHABLE_AGGS

    child = build_executor(plan.children[0], ctx)
    if any(a.distinct or a.name not in PUSHABLE_AGGS and a.name != "group_concat" for a in plan.aggs):
        # DISTINCT and complete-only aggregates (percentile, json_*agg)
        # cannot split into partial/final across chunks — complete mode
        # over raw rows (ref: AggFuncMode Complete)
        return CompleteAggExec(child, plan.group_by, plan.aggs, [c.ft for c in plan.out_cols])
    reader = _pushable_reader(child)
    pushable = (
        reader is not None
        and all(g.pushable() for g in plan.group_by)
        and all(a.pushable() for a in plan.aggs)
    )
    if pushable:
        # cop side computes partials (psum pattern); root merges
        reader.dag.agg = AggNode(plan.group_by, plan.aggs)
        reader.out_fts = reader.dag.output_types()
        return FinalHashAggExec(reader, plan.group_by, plan.aggs, [c.ft for c in plan.out_cols])
    # root-side complete aggregation: local partials per chunk, then merge
    return FinalHashAggExec(
        LocalPartialAggExec(child, plan.group_by, plan.aggs),
        plan.group_by,
        plan.aggs,
        [c.ft for c in plan.out_cols],
    )


def _mpp_topn_spec(sort_plan: Sort, inner) -> tuple | None:
    """ORDER BY <single sum/count aggregate> over Projection?(Aggregation)
    → (agg_idx, desc) resolved into the Aggregation's agg list, else None.
    The device then returns only the top-k groups per device (exact: after
    the hash exchange every group is complete on one device)."""
    from ..expr.expression import Column as _EC

    if len(sort_plan.by) != 1:
        return None
    e, desc = sort_plan.by[0]
    if not isinstance(e, _EC):
        return None
    idx = e.idx
    while isinstance(inner, Projection):
        pe = inner.exprs[idx]
        if not isinstance(pe, _EC):
            return None
        idx = pe.idx
        inner = inner.children[0]
    if not isinstance(inner, Aggregation):
        return None
    ng = len(inner.group_by)
    if idx < ng:
        return None  # ordering by a group key: host TopN handles it
    a = inner.aggs[idx - ng]
    if a.name not in ("sum", "count") or a.distinct:
        return None
    # carry the Aggregation node so the attach step can verify the gather
    # it found actually fused THIS aggregation (nested aggs would
    # otherwise receive the outer agg's topn)
    return (idx - ng, bool(desc), inner)


def _find_mpp_gather(ex: Executor):
    from .mpp_gather import MPPGatherExec

    seen = 0
    while ex is not None and seen < 8:
        if isinstance(ex, MPPGatherExec):
            return ex
        ex = getattr(ex, "child", None)
        seen += 1
    return None


def _build_limit(plan: Limit, ctx: ExecContext) -> Executor:
    child = plan.children[0]
    n = plan.count + plan.offset
    if isinstance(child, Sort):
        spec = _mpp_topn_spec(child, child.children[0])
        sort_child = build_executor(child.children[0], ctx)
        reader = _pushable_reader(sort_child)
        push_by = child.by
        if reader is None:
            # TopN pushes below row-wise column projections once its sort
            # keys are rewritten into scan space (ref: planner/core
            # rule_topn_push_down.go pushing TopN through Projection)
            node, mapped = child.children[0], child.by
            ok = True
            while ok and isinstance(node, Projection):
                nb = []
                for e, desc in mapped:
                    if isinstance(e, ECol):
                        nb.append((node.exprs[e.idx], desc))
                    else:
                        ok = False
                        break
                if ok:
                    mapped, node = nb, node.children[0]
            if ok and isinstance(node, DataSource):
                r = _reader_under(sort_child)
                if r is not None:
                    reader, push_by = r, mapped
        if reader is not None and all(e.pushable() for e, _ in push_by):
            reader.dag.topn = TopNNode(push_by, n)  # per-task topn
        if spec is not None:
            gather = _find_mpp_gather(sort_child)
            if gather is not None and gather.mplan.agg is spec[2]:
                gather.mplan.topn = (spec[0], spec[1], n)
        return TopNExec(sort_child, child.by, plan.count, plan.offset)
    ex = build_executor(child, ctx)
    reader = _pushable_reader(ex)
    if reader is not None:
        reader.dag.limit = LimitNode(n)  # per-task limit; root applies exact
    return LimitExec(ex, plan.count, plan.offset)


# ----------------------------------------------------------------- executors


class DualExec(Executor):
    out_fts: list[FieldType] = []

    def __init__(self):
        self._done = False

    def next(self):
        if self._done:
            return None
        self._done = True
        # one phantom row so constant projections evaluate once
        return Chunk([Column(ft_longlong(), np.zeros(1, dtype=np.int64), np.ones(1, dtype=bool))])


class TableReaderExec(Executor):
    """Drives the cop client; returns per-task (partial) chunks
    (ref: executor/table_reader.go + distsql.Select)."""

    def __init__(self, table, dag: DAGRequest, ctx: ExecContext, ranges=None):
        self.table = table
        self.dag = dag
        self.ctx = ctx
        self.ranges = ranges
        self.out_fts = dag.output_types()
        self._results = None
        self._iter = None

    def open(self):
        conc = int(self.ctx.vars.get("tidb_distsql_scan_concurrency", "15"))
        rcache = self.ctx.vars.get("tidb_enable_cop_result_cache", "ON") in ("ON", "1", 1)
        self._results = self.ctx.cop.send(
            self.table, self.dag, self.ranges, self.ctx.read_ts, self.ctx.engine,
            txn=self.ctx.txn, concurrency=conc, result_cache=rcache,
        )
        self._iter = iter(self._results)

    def next(self):
        if self._iter is None:
            self.open()
        return next(self._iter, None)


class PartitionReaderExec(TableReaderExec):
    """Union of per-partition cop reads sharing ONE DAG shape (ref:
    PartitionUnion + tables/partition.go GetPartition): each partition is
    a physical keyspace; partial-agg/TopN chunks from every partition
    merge at the host final exactly like multi-region partials do."""

    def __init__(self, table, dag: DAGRequest, ctx: ExecContext, parts):
        super().__init__(table, dag, ctx, None)
        self.parts = parts

    def open(self):
        import itertools

        conc = int(self.ctx.vars.get("tidb_distsql_scan_concurrency", "15"))
        rcache = self.ctx.vars.get("tidb_enable_cop_result_cache", "ON") in ("ON", "1", 1)
        results = []
        for pd in self.parts:
            phys = self.table.partition_physical(pd.id)
            # One shared DAG for every partition: the cop client keys tasks
            # and decode off the `phys` table argument, and the DAG digest
            # feeds the XLA program cache — per-partition digests would
            # compile one identical program per partition.
            results.append(
                self.ctx.cop.send(
                    phys, self.dag, None, self.ctx.read_ts, self.ctx.engine,
                    txn=self.ctx.txn, concurrency=conc, result_cache=rcache,
                )
            )
        self._results = results
        self._iter = itertools.chain.from_iterable(results)


class IndexReaderExec(TableReaderExec):
    """Covering index read — index entries decoded straight into the
    visible-column layout, no second read (ref: executor/distsql.go
    IndexReaderExecutor)."""

    def __init__(self, table, dag: DAGRequest, ctx: ExecContext, index, ranges):
        super().__init__(table, dag, ctx, ranges)
        self.index = index

    def open(self):
        self._results = self.ctx.cop.send_index(
            self.table, self.index, self.dag, self.ranges or [], self.ctx.read_ts,
            self.ctx.engine, txn=self.ctx.txn,
        )
        self._iter = iter(self._results)


class IndexLookUpExec(TableReaderExec):
    """Double read: index scan → handles → table rows + DAG over them
    (ref: executor/distsql.go IndexLookUpExecutor's index/table workers)."""

    def __init__(self, table, dag: DAGRequest, ctx: ExecContext, index, ranges):
        super().__init__(table, dag, ctx, ranges)
        self.index = index

    def open(self):
        entries = self.ctx.cop.index_entries(
            self.table, self.index, self.ranges or [], self.ctx.read_ts, txn=self.ctx.txn
        )
        handles = [h for _, h in entries]
        self._results = self.ctx.cop.send_handles(
            self.table, self.dag, handles, self.ctx.read_ts, self.ctx.engine, txn=self.ctx.txn
        )
        self._iter = iter(self._results)


class IndexMergeReaderExec(TableReaderExec):
    """Union of index paths for an OR predicate: each branch scans one
    index (or is a pk point set), handles are unioned + deduped, then one
    double read fetches the rows with the full filter DAG re-applied, so
    per-branch over-approximation is safe (ref: executor/
    index_merge_reader.go:67 IndexMergeReaderExecutor, union mode)."""

    def __init__(self, table, dag: DAGRequest, ctx: ExecContext, branches):
        super().__init__(table, dag, ctx, None)
        self.branches = branches

    def open(self):
        handles: set[int] = set()
        for b in self.branches:
            if b[0] == "points":
                handles.update(b[1])
            else:
                _, index, ranges = b
                entries = self.ctx.cop.index_entries(
                    self.table, index, ranges or [], self.ctx.read_ts, txn=self.ctx.txn
                )
                handles.update(h for _, h in entries)
        self._results = self.ctx.cop.send_handles(
            self.table, self.dag, sorted(handles), self.ctx.read_ts,
            self.ctx.engine, txn=self.ctx.txn,
        )
        self._iter = iter(self._results)


class PointGetExec(TableReaderExec):
    """Handle-equality fast path bypassing the device engines
    (ref: executor/point_get.go, batch_point_get.go)."""

    def __init__(self, table, dag: DAGRequest, ctx: ExecContext, handles: list[int]):
        super().__init__(table, dag, ctx, None)
        self.handles = handles

    def open(self):
        self._results = self.ctx.cop.send_handles(
            self.table, self.dag, self.handles, self.ctx.read_ts, "host", txn=self.ctx.txn
        )
        self._iter = iter(self._results)


class SelectionExec(Executor):
    def __init__(self, child: Executor, conds: list[Expression]):
        self.child = child
        self.conds = conds
        self.out_fts = child.out_fts

    def open(self):
        self.child.open()

    def next(self):
        while True:
            c = self.child.next()
            if c is None:
                return None
            mask = np.ones(c.num_rows, dtype=bool)
            for cond in self.conds:
                d, v = cond.eval(c)
                mask &= v & (d != 0)
            out = c.filter(mask)
            if out.num_rows:
                return out

    def close(self):
        self.child.close()


class ProjectionExec(Executor):
    def __init__(self, child: Executor, exprs: list[Expression], out_fts):
        self.child = child
        self.exprs = exprs
        self.out_fts = out_fts

    def open(self):
        self.child.open()

    def next(self):
        c = self.child.next()
        if c is None:
            return None
        cols = []
        for e, ft in zip(self.exprs, self.out_fts):
            d, v = e.eval(c)
            d, v = _coerce_lane(d, v, e.ret_type, ft, c.num_rows)
            cols.append(Column(ft, d, v))
        return Chunk(cols)

    def close(self):
        self.child.close()


def _broadcast_lane(d, v, n: int):
    """Expand scalar/0-d eval results to n-row lanes."""
    if np.isscalar(d) or getattr(d, "ndim", 1) == 0:
        d = np.full(n, d)
        v = np.full(n, v)
    return d, v


def _coerce_lane(d, v, src_ft: FieldType, dst_ft: FieldType, n: int):
    """Align a lane to the projection's output type (scale fixes etc.)."""
    if dst_ft.is_decimal() and src_ft.is_decimal():
        ss, ds_ = max(src_ft.decimal, 0), max(dst_ft.decimal, 0)
        if ss != ds_:
            d = d * pow10(ds_ - ss) if ds_ > ss else d // pow10(ss - ds_)
    return _broadcast_lane(d, v, n)


class LimitExec(Executor):
    def __init__(self, child: Executor, count: int, offset: int = 0):
        self.child = child
        self.count = count
        self.offset = offset
        self.out_fts = child.out_fts

    def open(self):
        self.child.open()
        self._skipped = 0
        self._emitted = 0

    def next(self):
        while self._emitted < self.count:
            c = self.child.next()
            if c is None:
                return None
            if self._skipped < self.offset:
                drop = min(self.offset - self._skipped, c.num_rows)
                self._skipped += drop
                c = c.slice(drop, c.num_rows)
                if c.num_rows == 0:
                    continue
            take = min(self.count - self._emitted, c.num_rows)
            self._emitted += take
            return c.slice(0, take)
        return None

    def close(self):
        self.child.close()


class _NotOnDevice(Exception):
    """Window func/lane without a device form — reason for EXPLAIN ANALYZE."""


class WindowExec(Executor):
    """Window functions for one (PARTITION BY, ORDER BY) spec (ref:
    executor/window.go:31, pipelined_window.go:37, aggfuncs window funcs).

    One lexicographic sort by (partition, order) keys makes partitions and
    peer groups contiguous; every function is then computed vectorized on
    the sorted lanes (cumulative frames read at peer-group ends — MySQL's
    default RANGE UNBOUNDED PRECEDING..CURRENT ROW frame) and scattered
    back to input row order. Only min/max accumulation and decimal AVG
    walk partitions/peers in Python; everything else is numpy."""

    def __init__(self, child: Executor, part_by, order_by, funcs, out_fts, ctx=None):
        self.child = child
        self.part_by = part_by
        self.order_by = order_by
        self.funcs = funcs
        self.out_fts = out_fts
        self.ctx = ctx
        self._done = False
        self.last_engine = "host"  # surfaced by EXPLAIN ANALYZE
        self.fallback_reason = ""

    def open(self):
        self._done = False

    def close(self):
        self.child.close()

    @staticmethod
    def _lane(e, c, n):
        return _broadcast_lane(*e.eval(c), n)

    _AGG_FUNCS = ("count", "sum", "avg", "min", "max")

    def _whole_partition_fast_path(self, c: Chunk, n: int):
        """SUM()/COUNT()/... OVER (PARTITION BY k) with no ORDER BY — the
        pipelined-window shape (ref: executor/pipelined_window.go:37,
        BASELINE stretch config). Factorizes partition keys (np.unique)
        and segment-reduces, skipping the O(n log n) lexicographic sort
        and the inverse permutation entirely."""
        if self.order_by or not self.part_by:
            return None
        if any(f.name not in self._AGG_FUNCS or f.frame is not None for f in self.funcs):
            return None
        from ..expr.expression import collation_key_lane

        part_lanes = []
        for e in self.part_by:
            d, v = self._lane(e, c, n)
            part_lanes.append((collation_key_lane(d, e.ret_type), v))
        arg_lanes = []
        for f in self.funcs:
            if f.args:
                d, v = self._lane(f.args[0], c, n)
                if d.dtype == object and f.name in ("sum", "avg", "min", "max"):
                    return None  # string aggregates keep the generic path
                arg_lanes.append((d, v))
            else:
                arg_lanes.append((np.ones(n, dtype=np.int64), np.ones(n, dtype=bool)))
        from ..copr.host_engine import _group_codes_masked

        inv_sel, _, G = _group_codes_masked(part_lanes, np.ones(n, dtype=bool))
        pid = inv_sel  # mask is all-true: selected order == row order
        cols = list(c.columns)
        for i, (f, (d, v)) in enumerate(zip(self.funcs, arg_lanes)):
            ft = self.out_fts[len(c.columns) + i]
            cnt = np.bincount(pid, weights=v.astype(np.float64), minlength=G)
            if f.name == "count":
                data, valid = cnt[pid].astype(np.int64), np.ones(n, dtype=bool)
            elif f.name in ("sum", "avg"):
                if d.dtype == np.float64:
                    s = np.bincount(pid, weights=np.where(v, d, 0.0), minlength=G)
                else:
                    s = np.zeros(G, dtype=np.int64)
                    np.add.at(s, pid, np.where(v, d.astype(np.int64), 0))
                if f.name == "sum":
                    data = s[pid] if ft.is_float() else s[pid].astype(np.int64)
                    valid = cnt[pid] > 0
                else:
                    data, valid = self._avg_from_sums(f, ft, s, cnt, pid)
            else:  # min / max
                if d.dtype == np.float64:
                    init = np.inf if f.name == "min" else -np.inf
                    acc_dt = np.float64
                else:  # keep the lane's own int dtype (uint64 lanes wrap in int64)
                    acc_dt = d.dtype
                    init = np.iinfo(acc_dt).max if f.name == "min" else np.iinfo(acc_dt).min
                acc = np.full(G, init, dtype=acc_dt)
                fn = np.minimum if f.name == "min" else np.maximum
                fn.at(acc, pid, np.where(v, d, init))
                data, valid = acc[pid], cnt[pid] > 0
            cols.append(Column(ft, data, valid))
        return Chunk(cols)

    def _avg_from_sums(self, f, ft, s, cnt, pid):
        n = len(pid)
        if ft.is_float():
            with np.errstate(divide="ignore", invalid="ignore"):
                g = np.where(cnt > 0, s / np.maximum(cnt, 1), 0.0)
            return g[pid], cnt[pid] > 0
        arg_scale = max(f.args[0].ret_type.decimal, 0) if f.args[0].ret_type.is_decimal() else 0
        out_scale = max(ft.decimal, 0)
        G = len(s)
        qs = np.zeros(G, dtype=np.int64)
        qv = np.zeros(G, dtype=bool)
        for g in range(G):
            c_ = int(cnt[g])
            if c_ > 0:
                q = Dec(int(s[g]), arg_scale).div(Dec(c_, 0))
                if q is not None:
                    qs[g] = q.rescale(out_scale).value
                    qv[g] = True
        return qs[pid], qv[pid]

    def _device_guard_ctx(self):
        """(sctx, stats_fn, breaker) for the device window boundary: the
        window kernel runs a plain jit on the DEFAULT device, which is
        runner lane 0 — that lane's circuit breaker is the one this path
        feeds and is gated by."""
        if self.ctx is None or getattr(self.ctx, "cop", None) is None:
            return None, None, None
        client = self.ctx.cop
        sctx = client._sched_ctx()
        return sctx, client._stats_fn(sctx), client.tpu.breaker

    def _device_window_call(self, eng, sctx, st, breaker, fn):
        """One guarded device-window attempt under the unified fault
        domain (copr/retry.guarded_device_call): typed classification,
        transient retry on the statement's backoff budget, breaker feed.
        Returns results (None = cache miss), or None after setting
        `fallback_reason` when the device path lost and `auto` degrades;
        forced 'tpu' raises the typed error instead."""
        from ..copr.retry import Backoffer, guarded_device_call
        from ..utils import metrics as M

        bo = Backoffer.for_ctx(sctx, stats=st)
        res, err = guarded_device_call(
            fn, bo,
            breakers=(breaker,) if breaker is not None else (),
            forced=eng == "tpu",
            failpoint="window/device-error",
        )
        if err is not None:
            # a device-path failure must never be silent: typed reason in
            # EXPLAIN ANALYZE + the labeled fallback series, stack kept
            # (a fatal classification may be a masked lowering bug)
            self.fallback_reason = f"device window failed: {type(err).__name__}: {err}"
            M.TPU_FALLBACK.inc(path="window", reason="device_error")
            if st is not None:
                st("window_fallbacks")
                st("fallback_errors")
            trace = getattr(sctx, "trace", None) if sctx is not None else None
            if trace is not None and trace.recording:
                trace.closed_span("window.degrade", 0.0, reason="device_error",
                                  error=type(err).__name__)
            return None, err
        return res, None

    def _try_device(self, c: Chunk, n: int):
        """Route the window onto the device (sort + segmented scans in one
        XLA program — window_device.py) when the engine allows and every
        func/lane has a device form. Returns the output Chunk or None.

        Device faults here live in the SAME fault domain as the cop path
        (PR 8): typed taxonomy, Backoffer retry for transients, lane-0
        breaker feed/gating, `auto` degrading to the host twin with a
        typed reason and forced 'tpu' surfacing the real state."""
        from .window_device import MIN_DEVICE_ROWS

        eng = getattr(self.ctx, "engine", "auto") if self.ctx is not None else "auto"
        min_rows = MIN_DEVICE_ROWS
        if self.ctx is not None and getattr(self.ctx, "vars", None):
            min_rows = int(self.ctx.vars.get("tidb_window_device_min_rows", MIN_DEVICE_ROWS))
        if eng == "host" or (eng != "tpu" and n < min_rows):
            return None
        from ..utils import metrics as M
        from .window_device import encode_obj, run_cached_window, run_device_window

        sctx, st, breaker = self._device_guard_ctx()
        if breaker is not None and not breaker.allow():
            # upfront decline at zero exception cost: `auto` reaches the
            # host twin exactly like a breaker-skipped cop task; forced
            # 'tpu' fails fast with the breaker state
            if eng == "tpu":
                breaker.raise_open()
            self.fallback_reason = f"device breaker open ({breaker.describe()})"
            M.TPU_FALLBACK.inc(path="window", reason="breaker_open")
            if st is not None:
                st("window_fallbacks")
                st("breaker_skips")
            trace = getattr(sctx, "trace", None) if sctx is not None else None
            if trace is not None and trace.recording:
                trace.closed_span("window.degrade", 0.0, reason="breaker_open",
                                  state=breaker.describe())
            return None
        try:
            return self._try_device_admitted(
                c, n, eng, sctx, st, breaker, encode_obj,
                run_cached_window, run_device_window,
            )
        finally:
            if breaker is not None:
                # declines that never touched the device (unsupported
                # func, cache miss resolved by the fresh path, small
                # input) release a claimed half-open probe slot; after a
                # recorded success/failure this is a no-op
                breaker.record_aborted()

    def _try_device_admitted(self, c: Chunk, n: int, eng, sctx, st, breaker,
                             encode_obj, run_cached_window, run_device_window):
        from ..utils import metrics as M

        # stable provenance for the device-input cache: a plain unfiltered
        # scan of an unchanged table yields identical lanes every run —
        # repeated windows then skip ALL host prep (lane eval, encoding,
        # packing) AND the device-link upload
        prov = None
        ch = self.child
        if isinstance(ch, TableReaderExec) and self.ctx is not None:
            dag = ch.dag
            if (dag.agg is None and dag.topn is None and dag.limit is None
                    and ch.ranges is None):
                from ..codec import tablecodec

                tbl = ch.table
                storage = self.ctx.cop.tiles.storage
                ver, last_commit = storage.data_version(
                    tablecodec.table_prefix(tbl.id)
                )
                # uncommitted writes on this table make the lanes a dirty
                # merged view — cacheable under no committed version
                prefix = tablecodec.record_prefix(tbl.id)
                dirty = self.ctx.txn is not None and any(
                    k.startswith(prefix) for k in self.ctx.txn.membuf
                )
                if not dirty and self.ctx.read_ts >= last_commit:
                    import hashlib as _hl

                    spec = repr((self.part_by, self.order_by,
                                 [(f.name, f.args, f.frame) for f in self.funcs],
                                 dag.digest()))
                    prov = (getattr(storage, "store_uid", ""), tbl.id, ver,
                            _hl.sha256(spec.encode()).hexdigest()[:16])
        if prov is not None:
            results, err = self._device_window_call(
                eng, sctx, st, breaker, lambda: run_cached_window(prov, n)
            )
            if err is not None:
                return None
            if results is not None:
                self.last_engine = "tpu"
                if st is not None:
                    st("window_device_tasks")
                cols = list(c.columns)
                nbase = len(cols)
                for i, (data, valid) in enumerate(results):
                    cols.append(Column(self.out_fts[nbase + i], data, valid))
                return Chunk(cols)
        range_lane, range_stats = (None, None)
        if any(
            f.frame is not None and f.frame.unit == "range"
            and (f.frame.start_kind in ("pre", "fol") or f.frame.end_kind in ("pre", "fol"))
            for f in self.funcs
        ):
            range_lane, range_stats = self._range_lane_stats(c, n)
        try:
            fspecs = self._device_fspecs(c, n, range_stats)
        except _NotOnDevice as e:
            self.fallback_reason = str(e)
            M.TPU_FALLBACK.inc(path="window", reason="not_supported")
            return None

        def key_lane(e):
            from ..expr.expression import collation_key_lane

            d, v = self._lane(e, c, n)
            if d.dtype == object:
                # ci keys sort/group by WEIGHT; key codes never decode back
                d = encode_obj(collation_key_lane(d, e.ret_type), v)[0]
            return d, v

        part = [key_lane(e) for e in self.part_by]
        order = [(key_lane(e), desc) for e, desc in self.order_by]
        if not any(f.get("frame") is not None and len(f["frame"]) > 5 for f in fspecs):
            range_lane = None  # computed above only when a frame uses it
        rng_arg = (range_lane + range_stats) if range_lane is not None else None
        results, err = self._device_window_call(
            eng, sctx, st, breaker,
            lambda: run_device_window(part, order, fspecs, n, provenance=prov,
                                      range_lane=rng_arg),
        )
        if err is not None or results is None:
            return None
        self.last_engine = "tpu"
        if st is not None:
            st("window_device_tasks")
        cols = list(c.columns)
        nbase = len(cols)
        for i, (data, valid) in enumerate(results):
            cols.append(Column(self.out_fts[nbase + i], data, valid))
        return Chunk(cols)

    def _range_offset_ok(self, fr, range_stats, n: int):
        """Device-eligibility of a RANGE-offset frame: ONE integer-typed
        ORDER BY key (range_stats precomputed once per chunk), int
        offsets, and a composite band (n partitions worst case) that fits
        int64 — everything else stays on the host twin."""
        if range_stats is None:
            return False
        off_s = fr.start_off if fr.start_kind in ("pre", "fol") else 0
        off_e = fr.end_off if fr.end_kind in ("pre", "fol") else 0
        if not isinstance(off_s, int) or not isinstance(off_e, int):
            return False
        gmin, gmax = range_stats
        S = (gmax - gmin) + 2 * max(abs(off_s), abs(off_e)) + 4
        return n * S < 1 << 61

    def _range_lane_stats(self, c: Chunk, n: int):
        """((d, v), (gmin, gmax)) for the single ORDER BY key — computed
        ONCE per chunk and shared by eligibility gating, the kernel's
        runtime scalars, and the shipped search lane."""
        if len(self.order_by) != 1:
            return None, None
        d, v = self._lane(self.order_by[0][0], c, n)
        if getattr(d, "dtype", None) is None or d.dtype == object or d.dtype.kind != "i":
            return None, None
        pres = d[:n][v[:n]]
        if len(pres) == 0:
            return None, None  # all-NULL key: peer bounds; host is fine
        return (d, v), (int(pres.min()), int(pres.max()))

    def _device_fspecs(self, c: Chunk, n: int, range_stats=None):
        """Build window_device fspecs; raises _NotOnDevice when some func
        has no device form (the reason lands in EXPLAIN ANALYZE)."""
        from .window_device import SUPPORTED, encode_obj

        from .window_device import MAX_DEVICE_FRAME_W, frame_width

        fspecs = []
        for f in self.funcs:
            if f.name not in SUPPORTED:
                raise _NotOnDevice(f"window func {f.name} has no device kernel")
            frame = None
            if f.frame is not None and f.name in (
                "first_value", "last_value", "nth_value", "count", "sum", "avg", "min", "max",
            ):
                fr = f.frame
                frame = fr.key()
                if fr.unit == "range" and (
                    fr.start_kind in ("pre", "fol") or fr.end_kind in ("pre", "fol")
                ):
                    if not self._range_offset_ok(fr, range_stats, n):
                        raise _NotOnDevice(
                            "RANGE offset frame not device-eligible (non-int key/offset or composite overflow)"
                        )
                    # only `desc` is static; gmin/gmax ship as runtime
                    # scalars so data changes never recompile the kernel
                    frame = frame + (bool(self.order_by[0][1]),)
                if f.name in ("min", "max") and fr.start_kind != "up" and fr.end_kind != "uf":
                    # both-bounded: device needs a static sparse table
                    if fr.unit != "rows":
                        raise _NotOnDevice("peer-bounded MIN/MAX frame has no device kernel")
                    if frame_width(frame) > MAX_DEVICE_FRAME_W:
                        raise _NotOnDevice("ROWS frame too wide for the device sparse table")

            def const_int(e, what):
                if not isinstance(e, Constant):
                    raise _NotOnDevice(f"non-constant {what} for {f.name}")
                return e.value.to_int()

            name = f.name
            spec = {"name": name, "args": [], "post": None, "frame": frame}
            if name == "ntile":
                spec["static"] = ("ntile", const_int(f.args[0], "bucket count"))
            elif name in ("row_number", "rank", "dense_rank", "cume_dist", "percent_rank"):
                spec["static"] = (name,)
                if name in ("cume_dist", "percent_rank"):
                    # device returns int num/den; host does the f64 division
                    spec["post"] = (name,)
            elif name in ("lead", "lag"):
                off = const_int(f.args[1], "offset") if len(f.args) > 1 else 1
                has_default = len(f.args) > 2
                d, v = self._lane(f.args[0], c, n)
                if has_default:
                    dd, dv = self._lane(f.args[2], c, n)
                    if (d.dtype == object) != (dd.dtype == object):
                        raise _NotOnDevice("lead/lag default type mismatch")
                    if d.dtype == object:
                        # one vocab covers arg + default so codes compare
                        d, vocab, dd = encode_obj(d, v, extra=np.where(dv, dd, ""))
                        spec["post"] = ("decode", vocab)
                    elif d.dtype != dd.dtype:
                        d = d.astype(np.float64)
                        dd = dd.astype(np.float64)
                    spec["args"] = [(d, v), (dd, dv)]
                else:
                    if d.dtype == object:
                        codes, vocab, _ = encode_obj(d, v)
                        d = codes
                        spec["post"] = ("decode", vocab)
                    spec["args"] = [(d, v)]
                spec["static"] = (name, off, has_default)
            elif name in ("first_value", "last_value", "nth_value", "min", "max"):
                from ..mysqltypes import collate as _coll

                if name in ("min", "max") and _coll.is_ci(
                    getattr(f.args[0].ret_type, "collate", None)
                ):
                    # window encode_obj codes are binary-ordered; ci
                    # MIN/MAX needs weight order → host path
                    raise _NotOnDevice(f"window {name} over ci-collated strings")
                d, v = self._lane(f.args[0], c, n)
                if d.dtype == object:
                    codes, vocab, _ = encode_obj(d, v)
                    d = codes
                    spec["post"] = ("decode", vocab)
                spec["args"] = [(d, v)]
                if name == "nth_value":
                    spec["static"] = (name, const_int(f.args[1], "n"))
                else:
                    spec["static"] = (name,)
            elif name == "count":
                if f.args:
                    d, v = self._lane(f.args[0], c, n)
                    if d.dtype == object:
                        d = np.zeros(n, dtype=np.int64)  # only validity matters
                    spec["args"] = [(d, v)]
                    spec["static"] = ("count", True)
                else:
                    spec["static"] = ("count", False)
            elif name in ("sum", "avg"):
                d, v = self._lane(f.args[0], c, n)
                if d.dtype == object:
                    raise _NotOnDevice(f"window {name} over string operands")
                spec["args"] = [(d, v)]
                if name == "sum":
                    spec["static"] = ("sum", True)
                elif d.dtype == np.float64 or f.ret_type.is_float():
                    spec["static"] = ("avg", True, "f")
                    spec["post"] = ("avg_f",)
                else:
                    arg_scale = (
                        max(f.args[0].ret_type.decimal, 0)
                        if f.args[0].ret_type.is_decimal()
                        else 0
                    )
                    out_scale = max(f.ret_type.decimal, 0)
                    spec["static"] = ("avg", True, "dec")
                    spec["post"] = ("avg_dec", arg_scale, out_scale)
            fspecs.append(spec)
        return fspecs

    def next(self):
        if self._done:
            return None
        self._done = True
        c = drain(self.child)
        n = c.num_rows
        if n == 0:
            return Chunk.empty(self.out_fts, 0)
        eng = getattr(self.ctx, "engine", "auto") if self.ctx is not None else "auto"
        if eng == "tpu":
            # forced device: only fall to host when no device form exists
            dev = self._try_device(c, n)
            if dev is not None:
                return dev
        fast = self._whole_partition_fast_path(c, n)
        if fast is not None:
            # the O(n) bincount shape beats a device round-trip under 'auto'
            return fast
        if eng != "tpu":
            dev = self._try_device(c, n)
            if dev is not None:
                return dev
        from ..copr.host_engine import _lex_argsort
        from ..expr.expression import collation_key_lane

        def cmp_lane(e):
            d, v = self._lane(e, c, n)
            return collation_key_lane(d, e.ret_type), v

        part_lanes = [cmp_lane(e) for e in self.part_by]
        order_lanes = [(cmp_lane(e), desc) for e, desc in self.order_by]
        keys = [(d, v, False) for d, v in part_lanes]
        keys += [(d, v, desc) for (d, v), desc in order_lanes]
        order = _lex_argsort(keys, n) if keys else np.arange(n)

        def changed(lanes) -> np.ndarray:
            ch = np.zeros(n, dtype=bool)
            for d, v in lanes:
                sd, sv = d[order], v[order]
                if n > 1:
                    null_flip = sv[1:] != sv[:-1]
                    both = sv[1:] & sv[:-1]
                    ch[1:] |= null_flip | (both & (sd[1:] != sd[:-1]))
            return ch

        pstart = np.zeros(n, dtype=bool)
        pstart[0] = True
        pstart |= changed(part_lanes)
        pid = np.cumsum(pstart) - 1
        pidx = np.nonzero(pstart)[0]
        pend = np.append(pidx[1:], n) - 1
        pfirst_row = pidx[pid]
        plast_row = pend[pid]
        psize = (pend - pidx + 1)[pid]
        rn = np.arange(n) - pfirst_row

        ostart = pstart | (changed([l for l, _ in order_lanes]) if order_lanes else False)
        peer_id = np.cumsum(ostart) - 1
        oidx = np.nonzero(ostart)[0]
        oend_arr = np.append(oidx[1:], n) - 1
        peer_last = oend_arr[peer_id]
        frame_end = peer_last if self.order_by else plast_row

        env = dict(
            n=n, order=order, pid=pid, pidx=pidx, pend=pend,
            pfirst=pfirst_row, plast=plast_row, psize=psize, rn=rn,
            peer_id=peer_id, oidx=oidx, oend=oend_arr, peer_last=peer_last,
            frame_end=frame_end, order_lanes=order_lanes,
        )
        cols = list(c.columns)
        nbase = len(cols)
        for i, f in enumerate(self.funcs):
            ft = self.out_fts[nbase + i]
            sd, sv = self._compute(f, c, env)
            data = np.empty_like(sd)
            valid = np.empty(n, dtype=bool)
            data[order] = sd
            valid[order] = sv
            cols.append(Column(ft, data, valid))
        return Chunk(cols)

    # -- frame bounds over the sorted domain --------------------------------

    def _frame_bounds(self, f, env):
        """Per-row frame [fs, fe] (sorted-row indices, clipped to the
        partition) + non-empty mask for window func `f` (ref:
        executor/pipelined_window.go getStart/getEnd, planner WindowFrame).
        `None` frame keeps MySQL default semantics."""
        n = env["n"]
        ones = np.ones(n, dtype=bool)
        fr = f.frame
        if fr is None:
            return env["pfirst"], env["frame_end"], ones
        pfirst, plast = env["pfirst"], env["plast"]
        if fr.unit == "rows":
            iota = np.arange(n)

            def pos(kind, off, cur):
                if kind == "up":
                    return pfirst
                if kind == "uf":
                    return plast
                if kind == "cur":
                    return cur
                return iota - off if kind == "pre" else iota + off

            fs_raw = pos(fr.start_kind, fr.start_off, iota)
            fe_raw = pos(fr.end_kind, fr.end_off, iota)
        else:
            fs_raw, fe_raw = self._range_bounds(fr, env)
        ne = (fs_raw <= fe_raw) & (fs_raw <= plast) & (fe_raw >= pfirst)
        return np.clip(fs_raw, pfirst, plast), np.clip(fe_raw, pfirst, plast), ne

    def _range_bounds(self, fr, env):
        """RANGE frame edges: UNBOUNDED/CURRENT resolve to partition/peer
        ends; offset bounds binary-search the single numeric ORDER BY key
        per partition (keys ascend within a partition after the lex sort;
        DESC keys are negated into ascending space). NULL-key rows frame
        their peer (NULL) block on offset sides."""
        peer_first = env["oidx"][env["peer_id"]]
        peer_last = env["peer_last"]
        pfirst, plast = env["pfirst"], env["plast"]
        simple = {"up": pfirst, "uf": plast}
        need_search = fr.start_kind in ("pre", "fol") or fr.end_kind in ("pre", "fol")
        fs = simple.get(fr.start_kind, peer_first)
        fe = simple.get(fr.end_kind, peer_last)
        if not need_search:
            return fs, fe
        n = env["n"]
        (d, v), desc = env["order_lanes"][0]
        order = env["order"]
        sd, sv = d[order], v[order]
        kk = sd
        off_s, off_e = fr.start_off, fr.end_off
        if kk.dtype == np.uint64 or isinstance(off_s, float) or isinstance(off_e, float):
            kk = kk.astype(np.float64)
        if desc:
            kk = -kk  # descending keys → ascending space; offsets flip with it
        fs = np.array(np.broadcast_to(fs, n), dtype=np.int64)
        fe = np.array(np.broadcast_to(fe, n), dtype=np.int64)
        for p0, p1 in zip(env["pidx"], env["pend"]):
            sl = slice(p0, p1 + 1)
            kv, vv = kk[sl], sv[sl]
            vpos = np.nonzero(vv)[0]
            if len(vpos) == 0:
                continue  # all-NULL partition: peers already in place
            vlo, vhi = vpos[0], vpos[-1]
            vkeys = kv[vlo : vhi + 1]
            rows = vpos  # only valid-key rows get value-based bounds
            if fr.start_kind in ("pre", "fol"):
                tgt = kv[rows] - off_s if fr.start_kind == "pre" else kv[rows] + off_s
                fs[p0 + rows] = p0 + vlo + np.searchsorted(vkeys, tgt, side="left")
            if fr.end_kind in ("pre", "fol"):
                tgt = kv[rows] - off_e if fr.end_kind == "pre" else kv[rows] + off_e
                fe[p0 + rows] = p0 + vlo + np.searchsorted(vkeys, tgt, side="right") - 1
        return fs, fe

    # -- per-function kernels over the sorted domain ------------------------

    def _compute(self, f, c, env):
        n, order = env["n"], env["order"]
        name = f.name
        ones = np.ones(n, dtype=bool)
        if name == "row_number":
            return env["rn"] + 1, ones
        if name == "rank":
            return env["oidx"][env["peer_id"]] - env["pfirst"] + 1, ones
        if name == "dense_rank":
            return env["peer_id"] - env["peer_id"][env["pfirst"]] + 1, ones
        if name == "ntile":
            k = f.args[0].value.to_int()
            s, rn = env["psize"], env["rn"]
            big, rem = s // k, s % k
            cut = rem * (big + 1)
            tile = np.where(
                big > 0,
                np.where(rn < cut, rn // np.maximum(big + 1, 1), rem + (rn - cut) // np.maximum(big, 1)),
                rn,
            )
            return tile + 1, ones
        if name == "cume_dist":
            return (env["peer_last"] - env["pfirst"] + 1) / env["psize"], ones
        if name == "percent_rank":
            rank = env["oidx"][env["peer_id"]] - env["pfirst"] + 1
            return np.where(env["psize"] > 1, (rank - 1) / np.maximum(env["psize"] - 1, 1), 0.0), ones
        if name in ("lead", "lag"):
            d, v = self._lane(f.args[0], c, n)
            sd, sv = d[order], v[order]
            off = f.args[1].value.to_int() if len(f.args) > 1 else 1
            tgt = np.arange(n) + (off if name == "lead" else -off)
            ok = (tgt >= 0) & (tgt < n)
            tgt_c = np.clip(tgt, 0, n - 1)
            ok &= env["pid"][tgt_c] == env["pid"]
            if len(f.args) > 2:
                dd, dv = self._lane(f.args[2], c, n)
                dd, dv = dd[order], dv[order]
            else:
                dd, dv = np.zeros_like(sd), np.zeros(n, dtype=bool)
            data = np.where(ok, sd[tgt_c], dd)
            valid = np.where(ok, sv[tgt_c], dv)
            return data, valid
        if name in ("first_value", "last_value", "nth_value"):
            d, v = self._lane(f.args[0], c, n)
            sd, sv = d[order], v[order]
            fs_, fe_, ne_ = self._frame_bounds(f, env)
            if name == "first_value":
                pos, ok = fs_, ne_
            elif name == "last_value":
                pos, ok = fe_, ne_
            else:
                k = f.args[1].value.to_int()
                pos = fs_ + k - 1
                ok = ne_ & (pos <= fe_)
                pos = np.minimum(pos, n - 1)
            return sd[pos], sv[pos] & ok
        if name in ("count", "sum", "avg", "min", "max"):
            return self._compute_agg(f, c, env)
        raise TiDBError(f"unsupported window function {name}")

    def _compute_agg(self, f, c, env):
        n, order = env["n"], env["order"]
        name = f.name
        fs_, fe_, ne_ = self._frame_bounds(f, env)
        if f.args:
            d, v = self._lane(f.args[0], c, n)
            sd, sv = d[order], v[order]
        else:
            sd, sv = np.ones(n, dtype=np.int64), np.ones(n, dtype=bool)
        if sd.dtype == object and name in ("sum", "avg"):
            raise TiDBError(f"window {name} over string operands is not supported")
        cnt_cs = np.cumsum(sv.astype(np.int64))
        before = np.where(fs_ > 0, cnt_cs[np.maximum(fs_ - 1, 0)], 0)
        frame_cnt = np.where(ne_, cnt_cs[fe_] - before, 0)
        if name == "count":
            return frame_cnt, np.ones(n, dtype=bool)
        if name in ("sum", "avg"):
            is_f = sd.dtype == np.float64
            vals = np.where(sv, sd, 0.0 if is_f else 0)
            val_cs = np.cumsum(vals)
            vbefore = np.where(fs_ > 0, val_cs[np.maximum(fs_ - 1, 0)], 0)
            frame_sum = np.where(ne_, val_cs[fe_] - vbefore, 0)
            if name == "sum":
                return frame_sum, frame_cnt > 0
            if is_f or f.ret_type.is_float():
                with np.errstate(divide="ignore", invalid="ignore"):
                    return np.where(frame_cnt > 0, frame_sum / np.maximum(frame_cnt, 1), 0.0), frame_cnt > 0
            # decimal AVG: exact Dec division at peer granularity for the
            # default frame; explicit frames vary per row
            arg_scale = max(f.args[0].ret_type.decimal, 0) if f.args[0].ret_type.is_decimal() else 0
            out_scale = max(f.ret_type.decimal, 0)
            rows = env["oidx"] if f.frame is None else np.arange(n)
            qs = np.zeros(len(rows), dtype=np.int64)
            qv = np.zeros(len(rows), dtype=bool)
            for g, p in enumerate(rows):
                s_, c_ = int(frame_sum[p]), int(frame_cnt[p])
                if c_ > 0:
                    q = Dec(s_, arg_scale).div(Dec(c_, 0))
                    if q is not None:
                        qs[g] = q.rescale(out_scale).value
                        qv[g] = True
            if f.frame is None:
                return qs[env["peer_id"]], qv[env["peer_id"]]
            return qs, qv
        return self._compute_minmax(f, env, sd, sv, fs_, fe_, ne_, frame_cnt)

    def _compute_minmax(self, f, env, sd, sv, fs_, fe_, ne_, frame_cnt):
        n = env["n"]
        name = f.name
        valid = (frame_cnt > 0) & ne_
        is_obj = sd.dtype == object
        if is_obj:
            from ..expr.expression import collation_key_lane

            ks = collation_key_lane(sd, f.args[0].ret_type if f.args else None)

            def better(j, cur_k, cur_raw):
                # weight orders; equal weights keep the first value
                if ks[j] == cur_k:
                    return False
                return (ks[j] < cur_k) if name == "min" else (ks[j] > cur_k)

            if f.frame is None:
                return self._minmax_obj_default(env, sd, sv, fe_, ks, better)
            # explicit frame over a string lane: per-row scan (host-only path)
            out = np.empty(n, dtype=object)
            outv = np.zeros(n, dtype=bool)
            for i in range(n):
                if not ne_[i]:
                    continue
                cur, curk, curv = None, None, False
                for j in range(fs_[i], fe_[i] + 1):
                    if sv[j] and (not curv or better(j, curk, cur)):
                        cur, curk, curv = sd[j], ks[j], True
                out[i], outv[i] = cur, curv
            return out, outv
        ufunc = np.minimum if name == "min" else np.maximum
        fill = (np.inf if name == "min" else -np.inf) if sd.dtype == np.float64 else (
            np.iinfo(sd.dtype).max if name == "min" else np.iinfo(sd.dtype).min
        )
        masked = np.where(sv, sd, fill)
        fr = f.frame
        starts_at_pfirst = fr is None or (fr.start_kind == "up")
        if starts_at_pfirst:
            # growing frame: running accumulate per partition, read at fe
            acc = np.empty_like(masked)
            for p0, p1 in zip(env["pidx"], env["pend"]):
                acc[p0 : p1 + 1] = ufunc.accumulate(masked[p0 : p1 + 1])
            return acc[fe_], valid
        # sliding frame: sparse table (range-min-query) over the masked
        # lane — queries never cross a partition (fs/fe are clipped)
        w = np.maximum(fe_ - fs_ + 1, 1)
        L = max(1, int(np.max(w)).bit_length())
        levels = [masked]
        for k in range(1, L):
            h = 1 << (k - 1)
            prev = levels[-1]
            shifted = np.concatenate([prev[h:], np.full(h, fill, dtype=prev.dtype)])
            levels.append(ufunc(prev, shifted))
        stk = np.stack(levels)
        k = (np.frexp(w.astype(np.float64))[1] - 1).astype(np.int64)  # floor(log2 w), exact
        half = np.left_shift(np.int64(1), k)
        res = ufunc(stk[k, fs_], stk[k, np.maximum(fe_ - half + 1, 0)])
        return res, valid

    def _minmax_obj_default(self, env, sd, sv, fe_, ks, better):
        n = env["n"]
        acc = np.empty(n, dtype=object)
        accv = np.zeros(n, dtype=bool)
        for p0, p1 in zip(env["pidx"], env["pend"]):
            cur, curk, curv = None, None, False
            for i in range(p0, p1 + 1):
                if sv[i] and (not curv or better(i, curk, cur)):
                    cur, curk, curv = sd[i], ks[i], True
                acc[i], accv[i] = cur, curv
        return acc[fe_], accv[fe_]


SPILL_COUNT = 0  # process-wide spill events (observability + tests)


class _MergeVal:
    """Heap-comparable sort key element honoring NULL-first + desc;
    comparison goes through compare_datum so every datum kind (Dec,
    packed times, strings) orders correctly."""

    __slots__ = ("d", "desc")

    def __init__(self, d, desc):
        self.d = d
        self.desc = desc

    def __lt__(self, other):
        a, b = self.d, other.d
        if a.is_null != b.is_null:
            # asc: NULLs first; desc: NULLs last (MySQL)
            return a.is_null if not self.desc else b.is_null
        if a.is_null:
            return False
        c = compare_datum(a, b)
        return c > 0 if self.desc else c < 0

    def __eq__(self, other):
        a, b = self.d, other.d
        if a.is_null or b.is_null:
            return a.is_null and b.is_null
        return compare_datum(a, b) == 0


class SortExec(Executor):
    """External-merge sort (ref: executor/sort.go:35 + the spill action at
    :60 / util/chunk/row_container.go:235): input accumulates in memory
    until `spill_limit` bytes, each overflow sorts + spills one run file,
    and the tail is a k-way merge over the sorted runs."""

    def __init__(self, child: Executor, by, spill_limit: int = 0):
        self.child = child
        self.by = by
        self.spill_limit = spill_limit  # 0 = never spill
        self.out_fts = child.out_fts
        self._out = None

    def open(self):
        # the child is pulled inside _sorted_chunk — opening it here too
        # would run the whole subtree (incl. cop sends) twice
        self._out = None

    def _sort_in_mem(self, all_: Chunk) -> Chunk:
        from ..copr.host_engine import _lex_argsort
        from ..expr.expression import collation_key_lane

        keys = []
        for e, desc in self.by:
            d, v = _broadcast_lane(*e.eval(all_), all_.num_rows)
            keys.append((collation_key_lane(d, e.ret_type), v, desc))
        order = _lex_argsort(keys, all_.num_rows)
        return all_.take(order)

    def _produce(self):
        """Generator of output chunks. In-memory path yields once; the
        spill path streams merge batches (the SORT's working set is
        bounded by spill_limit + one input chunk; the final result is
        still charged to the statement tracker by the consuming drain, so
        quota bounds what the query ultimately materializes)."""
        from ..chunk.chunk_io import SpillFile
        from ..utils.memory import chunk_bytes

        sess = _ACTIVE_SESSION.get()
        runs: list[SpillFile] = []
        try:
            mem: list[Chunk] = []
            mem_bytes = 0
            self.child.open()
            from ..sched.scheduler import raise_if_interrupted

            try:
                while True:
                    # the shared interrupt gate: KILL, oom-arbiter kills
                    # and the runaway tick all land mid-spill too
                    raise_if_interrupted(sess)
                    c = self.child.next()
                    if c is None:
                        break
                    if not c.num_rows:
                        continue
                    mem.append(c)
                    mem_bytes += chunk_bytes(c)
                    if self.spill_limit and mem_bytes >= self.spill_limit:
                        global SPILL_COUNT
                        SPILL_COUNT += 1
                        run = SpillFile()
                        srt = self._sort_in_mem(Chunk.concat_all(mem))
                        for lo in range(0, srt.num_rows, 4096):
                            run.write(srt.slice(lo, min(lo + 4096, srt.num_rows)))
                        run.finish()
                        runs.append(run)
                        mem, mem_bytes = [], 0
            finally:
                self.child.close()
            tail = Chunk.concat_all(mem) if mem else Chunk.empty(self.out_fts, 0)
            if not runs:
                if tail.num_rows:
                    yield self._sort_in_mem(tail)
                return
            yield from self._merge_runs(runs, tail)
        finally:
            for r in runs:
                r.cleanup()

    def _merge_runs(self, runs, tail: Chunk):
        """K-way streaming merge of sorted run files + the in-memory tail."""
        import heapq

        def keyed(chunks_iter, sid):
            for c in chunks_iter:
                # one Column per (chunk, key): get_datum(i) per row after
                key_cols = []
                for e, desc in self.by:
                    d, v = _broadcast_lane(*e.eval(c), c.num_rows)
                    key_cols.append((Column(e.ret_type, d, v), desc))
                for i in range(c.num_rows):
                    key = tuple(_MergeVal(col.get_datum(i), desc) for col, desc in key_cols)
                    yield key, sid, c, i

        sources = [keyed(r.chunks(self.out_fts), k) for k, r in enumerate(runs)]
        if tail.num_rows:
            sources.append(keyed([self._sort_in_mem(tail)], len(runs)))
        batch_rows: list = []
        for key, sid, c, i in heapq.merge(*sources, key=lambda t: t[0]):
            batch_rows.append(c.get_row(i))
            if len(batch_rows) >= 4096:
                yield Chunk.from_datum_rows(self.out_fts, batch_rows)
                batch_rows = []
        if batch_rows:
            yield Chunk.from_datum_rows(self.out_fts, batch_rows)

    def next(self):
        if self._out is None:
            self._out = self._produce()
        return next(self._out, None)

    def close(self):
        # release the suspended generator promptly so spill files unlink
        # now, not at an eventual gc cycle collection
        if self._out is not None and hasattr(self._out, "close"):
            self._out.close()
        self._out = None


class TopNExec(SortExec):
    """ORDER BY ... LIMIT with a bounded working set: the buffer prunes
    to the top-k whenever it overflows a multiple of k, so memory is
    O(k + chunk) regardless of input size (ref: executor/sort.go:301
    TopNExec's heap)."""

    def __init__(self, child: Executor, by, count: int, offset: int = 0):
        super().__init__(child, by)
        self.count = count
        self.offset = offset

    def next(self):
        if self._out is None:
            k = self.offset + self.count
            sess = _ACTIVE_SESSION.get()
            tq = int(sess.vars.get("tidb_mem_quota_topn", "0") or 0) if sess is not None else 0
            buf: Chunk | None = None
            self.child.open()
            from ..sched.scheduler import raise_if_interrupted

            try:
                while True:
                    raise_if_interrupted(sess)
                    c = self.child.next()
                    if c is None:
                        break
                    if not c.num_rows:
                        continue
                    buf = c if buf is None else Chunk.concat_all([buf, c])
                    if buf.num_rows > max(4 * k, 4096):
                        buf = self._sort_in_mem(buf).slice(0, k)
                    if tq > 0:
                        # tidb_mem_quota_topn bounds the retained top-k
                        # working set (ref: TopNExec memTracker + the
                        # per-operator quota actions)
                        from ..utils.memory import chunk_bytes

                        if chunk_bytes(buf) > tq:
                            from ..errors import MemoryQuotaExceeded

                            raise MemoryQuotaExceeded(
                                f"Out Of Memory Quota! [topn] working set > {tq}"
                            )
            finally:
                self.child.close()
            if buf is None:
                buf = Chunk.empty(self.out_fts, 0)
            srt = self._sort_in_mem(buf) if buf.num_rows else buf
            self._out = srt.slice(min(self.offset, srt.num_rows), min(k, srt.num_rows))
            return self._out
        return None


class LocalPartialAggExec(Executor):
    """Root-side partial aggregation over arbitrary child chunks — produces
    the same partial layout a cop task would (so FinalHashAggExec is the
    single merge path for both)."""

    def __init__(self, child: Executor, group_by, aggs):
        self.child = child
        self.group_by = group_by
        self.aggs = aggs
        self._node = AggNode(group_by, aggs)
        fts = [g.ret_type for g in group_by]
        for a in aggs:
            fts.extend(ft for _, ft in a.partial_final_types())
        self.out_fts = fts

    def open(self):
        self.child.open()

    def next(self):
        from ..copr.dag import DAGRequest, ScanNode
        from ..copr.host_engine import _exec_agg

        c = self.child.next()
        if c is None:
            return None
        pseudo = DAGRequest(ScanNode(0, list(range(c.num_cols)), c.field_types(), []))
        pseudo.agg = self._node
        return _exec_agg(pseudo, c, None)

    def close(self):
        self.child.close()


class CompleteAggExec(Executor):
    """Complete-mode aggregation for DISTINCT (non-splittable) aggregates:
    groups raw rows, dedups per-group argument values, computes finals
    directly (ref: executor/aggregate.go unparallel path)."""

    def __init__(self, child: Executor, group_by, aggs: list[AggDesc], out_fts):
        self.child = child
        self.group_by = group_by
        self.aggs = aggs
        self.out_fts = out_fts
        self._done = False

    def open(self):
        self._done = False

    def close(self):
        self.child.close()

    def next(self):
        if self._done:
            return None
        self._done = True
        c = drain(self.child)
        n = c.num_rows
        from ..expr.aggregation import NULL_KEEPING_AGGS

        key_lanes = [_broadcast_lane(*g.eval(c), n) for g in self.group_by]
        arg_lanes = []
        for a in self.aggs:
            if a.args:
                # multi-lane aggs (JSON_OBJECTAGG) evaluate every non-const
                # argument; constant tail args (percentile) read at final
                lanes = []
                nlanes = 2 if a.name == "json_objectagg" else 1
                for x in a.args[:nlanes]:
                    d, v = _broadcast_lane(*x.eval(c), n)
                    lanes.append(Column(x.ret_type, d, v))
                arg_lanes.append(lanes)
            else:
                arg_lanes.append(None)
        key_cols = [Column(g.ret_type, d, v) for g, (d, v) in zip(self.group_by, key_lanes)]
        from ..expr.expression import collation_key_lane

        wkey_lanes = [
            collation_key_lane(col.data, g.ret_type)
            for g, col in zip(self.group_by, key_cols)
        ]
        groups: dict = {}
        order: list = []
        for i in range(n):
            key = tuple(
                (col.valid[i], wl[i] if col.valid[i] else None)
                for col, wl in zip(key_cols, wkey_lanes)
            )
            st = groups.get(key)
            if st is None:
                st = (i, [[] for _ in self.aggs])
                groups[key] = st
                order.append(key)
            for k, (a, cols) in enumerate(zip(self.aggs, arg_lanes)):
                if cols is None:
                    st[1][k].append(Datum.i(1))
                elif len(cols) > 1:
                    st[1][k].append(tuple(col.get_datum(i) for col in cols))
                elif cols[0].valid[i] or a.name in NULL_KEEPING_AGGS:
                    st[1][k].append(cols[0].get_datum(i))
        if not groups and not self.group_by:
            groups[()] = (0, [[] for _ in self.aggs])
            order.append(())
        out = Chunk.empty(self.out_fts, len(order))
        ng = len(self.group_by)
        for r, key in enumerate(order):
            first_i, states = groups[key]
            for gi, col in enumerate(key_cols):
                out.columns[gi].set_datum(r, col.get_datum(first_i))
            for k, a in enumerate(self.aggs):
                out.columns[ng + k].set_datum(r, self._final(a, states[k]))
        return out

    @staticmethod
    def _final(a: AggDesc, datums: list) -> Datum:
        from ..expr.expression import datum_sort_key
        from ..mysqltypes.datum import K_STR as _KS

        arg_ft = a.args[0].ret_type if a.args else None

        def dedup_key(d):
            if d.kind == _KS:
                return (d.kind, datum_sort_key(d, arg_ft)[0])
            return (d.kind, d.val)

        vals = datums
        if a.distinct:
            seen = set()
            vals = []
            for d in datums:
                key = dedup_key(d)
                if key not in seen:
                    seen.add(key)
                    vals.append(d)
        name = a.name
        if name == "count":
            return Datum.i(len(vals))
        if name == "approx_count_distinct":
            return Datum.i(len({dedup_key(d) for d in vals}))
        if name == "json_arrayagg":
            import json as _j

            if not vals:
                return Datum.null()
            return Datum.s(_j.dumps([_datum_to_json(d, a.args[0].ret_type) for d in vals]))
        if name == "json_objectagg":
            import json as _j

            if not vals:
                return Datum.null()
            obj = {}
            for kd, vd in vals:
                if kd.is_null:
                    raise TiDBError("JSON documents may not contain NULL member names")
                obj[kd.render(a.args[0].ret_type)] = _datum_to_json(vd, a.args[1].ret_type)
            return Datum.s(_j.dumps(obj))
        if not vals:
            return Datum.null() if name not in ("bit_and", "bit_or", "bit_xor") else (
                Datum.u(0xFFFFFFFFFFFFFFFF) if name == "bit_and" else Datum.u(0)
            )
        if name == "approx_percentile":
            p = a.args[1].value.to_int()
            svals = sorted(vals, key=_cmp_key)
            # nearest-rank percentile (ref: aggfuncs percentileOriginal*)
            idx = max((p * len(svals) + 99) // 100, 1) - 1
            return svals[min(idx, len(svals) - 1)]
        if name in ("sum", "avg"):
            from ..mysqltypes.datum import K_FLOAT

            if vals[0].kind == K_FLOAT or a.ret_type.is_float():
                s = sum(d.to_float() for d in vals)
                return Datum.f(s if name == "sum" else s / len(vals))
            acc = vals[0].to_dec()
            for d in vals[1:]:
                acc = acc + d.to_dec()
            if name == "sum":
                return Datum.d(acc)
            q = acc.div(Dec(len(vals), 0))
            return Datum.d(q.rescale(max(a.ret_type.decimal, 0))) if q is not None else Datum.null()
        if name in ("min", "max"):
            best = vals[0]
            for d in vals[1:]:
                if d.kind == _KS:
                    kd, kb = datum_sort_key(d, arg_ft), datum_sort_key(best, arg_ft)
                    if kd[0] == kb[0]:
                        cmp = 0  # equal-weight ties keep the first value
                    else:
                        cmp = -1 if kd[0] < kb[0] else 1
                else:
                    cmp = compare_datum(d, best)
                if (name == "min" and cmp < 0) or (name == "max" and cmp > 0):
                    best = d
            return best
        if name == "first_row":
            return vals[0]
        if name == "group_concat":
            return Datum.s(a.sep.join(d.render(a.args[0].ret_type) for d in vals)[: a.max_len])
        if name in ("stddev_pop", "stddev_samp", "var_pop", "var_samp"):
            import math as _math

            xs = [d.to_float() for d in vals]
            m = len(xs)
            if name.endswith("_samp") and m < 2:
                return Datum.null()
            mean = sum(xs) / m
            var = sum((x - mean) ** 2 for x in xs) / (m if name.endswith("_pop") else m - 1)
            return Datum.f(_math.sqrt(var) if name.startswith("stddev") else var)
        if name in ("bit_and", "bit_or", "bit_xor"):
            acc = -1 if name == "bit_and" else 0
            for d in vals:
                v = d.to_int()
                acc = acc & v if name == "bit_and" else (acc | v if name == "bit_or" else acc ^ v)
            return Datum.u(acc & 0xFFFFFFFFFFFFFFFF)
        raise TiDBError(f"unsupported complete aggregate {name}")


def _datum_to_json(d: Datum, ft) -> object:
    """Datum → python JSON value (ref: types/json CreateBinary paths)."""
    if d.is_null:
        return None
    if ft is not None and ft.is_decimal():
        return float(d.to_dec().to_float())
    from ..mysqltypes.datum import K_FLOAT, K_INT, K_UINT

    if d.kind == K_FLOAT:
        return float(d.val)
    if d.kind in (K_INT, K_UINT):
        return d.to_int()
    s = d.render(ft) if ft is not None else str(d.val)
    # JSON-typed operands embed as documents, not strings
    if ft is not None and ft.tp == TypeCode.JSON:
        import json as _j

        try:
            return _j.loads(s)
        except ValueError:
            return s
    return s


def _cmp_key(d: Datum):
    import functools

    return functools.cmp_to_key(compare_datum)(d)


class FinalHashAggExec(Executor):
    """Merges partial-agg chunks (from cop tasks or LocalPartialAggExec)
    into final values (ref: HashAggExec final workers, aggregate.go:104)."""

    def __init__(self, child: Executor, group_by, aggs: list[AggDesc], out_fts):
        self.child = child
        self.group_by = group_by
        self.aggs = aggs
        self.out_fts = out_fts
        self._done = False

    def open(self):
        self.child.open()
        self._done = False

    def next(self):
        if self._done:
            return None
        self._done = True
        from ..expr.expression import datum_sort_key
        from ..mysqltypes.datum import K_STR as _KS

        ngroup = len(self.group_by)

        def gkey(key):
            # partials from different tasks carry case-variant ci keys
            # that must merge into ONE group (weight identity)
            out = []
            for d, g in zip(key, self.group_by):
                if not d.is_null and d.kind == _KS:
                    out.append((False, datum_sort_key(d, g.ret_type)[0]))
                else:
                    out.append((d.is_null, None if d.is_null else d.val))
            return tuple(out)

        vector_ok = all(
            a.name in ("count", "sum", "avg", "min", "max") for a in self.aggs
        )
        chunks = []
        while True:
            c = self.child.next()
            if c is None:
                break
            if c.num_rows:
                chunks.append(c)
        all_ = Chunk.concat_all(chunks) if chunks else None
        fast = self._merge_vectorized(all_) if (vector_ok and all_ is not None) else None
        if fast is not None:
            return fast
        # the group hash table is the aggregate's real working set; charge
        # it to the statement tracker unless the session opted out
        # (ref: aggregate.go memTracker + tidb_track_aggregate_memory_usage)
        tracker = _ACTIVE_TRACKER.get()
        sess = _ACTIVE_SESSION.get()
        if tracker is not None and sess is not None:
            if sess.vars.get("tidb_track_aggregate_memory_usage", "ON") != "ON":
                tracker = None
        group_entry_bytes = 64 + 32 * len(self.aggs)

        groups: dict = {}
        firsts: dict = {}
        order: list = []
        for c in ([all_] if all_ is not None else []):
            for row in c.iter_rows():
                key = gkey(row[:ngroup])
                st = groups.get(key)
                if st is None:
                    st = [None] * len(self.aggs)
                    groups[key] = st
                    firsts[key] = tuple(row[:ngroup])
                    order.append(key)
                    if tracker is not None and len(order) % 4096 == 0:
                        tracker.consume(4096 * group_entry_bytes)
                self._merge_row(st, row[ngroup:])
        if not groups and not self.group_by:
            # global aggregate over empty input: one row of "empty" values
            groups[()] = [None] * len(self.aggs)
            firsts[()] = ()
            order.append(())
        out = Chunk.empty(self.out_fts, len(groups))
        for r, key in enumerate(order):
            st = groups[key]
            for i, d in enumerate(firsts[key]):
                out.columns[i].set_datum(r, d)
            for i, a in enumerate(self.aggs):
                out.columns[ngroup + i].set_datum(r, self._final_value(a, st[i], self.out_fts[ngroup + i]))
        return out

    def _merge_vectorized(self, all_: Chunk):
        """numpy merge of partial rows for the common aggregates — the
        reference's parallel final workers (aggregate.go:104) compressed
        into vector ops. None → the generic per-row merge runs (object/
        unsigned lanes, int64-overflow-risk sums, exotic aggs). This is
        the host final-merge cliff fix: high-NDV partials no longer grind
        a Python dict row by row."""
        if any(
            c.data.dtype == object or c.data.dtype.kind == "u"
            for c in all_.columns[len(self.group_by):]
        ):
            # string partials need datum semantics; uint64 values >= 2^63
            # would wrap under the int64 accumulators
            return None
        from ..copr.host_engine import _group_codes_masked
        from ..expr.expression import collation_key_lane

        ngroup = len(self.group_by)
        n = all_.num_rows
        for c in all_.columns[ngroup:]:
            if c.data.dtype.kind == "i" and len(c.data):
                mx = int(np.abs(np.where(c.valid, c.data, 0)).max())
                if mx and n > (1 << 62) // mx:
                    return None  # summing could overflow int64: Dec path
        if ngroup:
            keyvals = [
                (collation_key_lane(all_.columns[i].data, g.ret_type), all_.columns[i].valid)
                for i, g in enumerate(self.group_by)
            ]
            inv, first_row, G = _group_codes_masked(keyvals, np.ones(n, dtype=bool))
        else:
            inv = np.zeros(n, dtype=np.int64)
            first_row = np.zeros(1, dtype=np.int64)
            G = 1
        tracker = _ACTIVE_TRACKER.get()
        sess = _ACTIVE_SESSION.get()
        if tracker is not None and (
            sess is None or sess.vars.get("tidb_track_aggregate_memory_usage", "ON") == "ON"
        ):
            # same contract as the generic path: the group table is the
            # working set (may raise MemoryQuotaExceeded)
            tracker.consume(G * (64 + 32 * len(self.aggs)))
        out = Chunk.empty(self.out_fts, G)
        for i in range(ngroup):
            src = all_.columns[i]
            out.columns[i] = Column(self.out_fts[i], src.data[first_row], src.valid[first_row])
        pos = ngroup
        oi = ngroup
        for a in self.aggs:
            ft = self.out_fts[oi]
            if a.name == "count":
                cc = all_.columns[pos]
                cnt = np.zeros(G, dtype=np.int64)
                np.add.at(cnt, inv, np.where(cc.valid, cc.data.astype(np.int64), 0))
                out.columns[oi] = Column(ft, cnt, np.ones(G, bool))
                pos += 1
                oi += 1
                continue
            sd, sv = all_.columns[pos].data, all_.columns[pos].valid
            hasc = np.zeros(G, dtype=np.int64)
            np.add.at(hasc, inv, sv.astype(np.int64))
            has = hasc > 0
            if a.name in ("sum", "avg"):
                if sd.dtype.kind == "f":
                    acc = np.zeros(G, dtype=np.float64)
                    np.add.at(acc, inv, np.where(sv, sd, 0.0))
                else:
                    acc = np.zeros(G, dtype=np.int64)
                    np.add.at(acc, inv, np.where(sv, sd.astype(np.int64), 0))
                if a.name == "sum":
                    out.columns[oi] = Column(ft, acc, has)
                    oi += 1
                    pos += 1
                else:  # avg: (sum, count) lanes, vectorized finalize
                    cc = all_.columns[pos + 1]
                    cnt = np.zeros(G, dtype=np.int64)
                    np.add.at(cnt, inv, np.where(cc.valid, cc.data.astype(np.int64), 0))
                    ok = has & (cnt > 0)
                    if ft.is_float():
                        data = np.where(ok, acc / np.maximum(cnt, 1), 0.0)
                        out.columns[oi] = Column(ft, data, ok)
                    else:
                        # exact decimal AVG over scaled ints (the window
                        # kernel's _avg_dec_finish replicates Dec.div +
                        # rescale, incl. the double rounding)
                        from .window_device import _avg_dec_finish

                        sum_scale = max(a.partial_final_types()[0][1].decimal, 0)
                        qs, valid2 = _avg_dec_finish(
                            np.where(ok, acc, 0), np.maximum(cnt, 1),
                            sum_scale, max(ft.decimal, 0),
                        )
                        out.columns[oi] = Column(ft, qs, ok & valid2)
                    oi += 1
                    pos += 2
            else:  # min / max: single value lane
                if sd.dtype.kind == "f":
                    neutral = np.inf if a.name == "min" else -np.inf
                    acc = np.full(G, neutral, dtype=np.float64)
                    vals = np.where(sv, sd, neutral)
                else:
                    info = np.iinfo(np.int64)
                    neutral = info.max if a.name == "min" else info.min
                    acc = np.full(G, neutral, dtype=np.int64)
                    vals = np.where(sv, sd.astype(np.int64), neutral)
                (np.minimum if a.name == "min" else np.maximum).at(acc, inv, vals)
                data = np.where(has, acc, 0)
                out.columns[oi] = Column(ft, data.astype(np.float64) if ft.is_float() else data, has)
                oi += 1
                pos += 1
        return out

    def _merge_row(self, st, partials):
        pos = 0
        for i, a in enumerate(self.aggs):
            width = len(a.partial_final_types())
            vals = partials[pos : pos + width]
            pos += width
            st[i] = self._merge_state(a, st[i], vals)

    @staticmethod
    def _merge_state(a: AggDesc, state, vals):
        name = a.name
        vals_sep = a.sep
        if name == "count":
            v = vals[0].to_int() if not vals[0].is_null else 0
            return (state or 0) + v
        if name in ("sum", "avg"):
            s, cnt = (vals[0], vals[1]) if name == "avg" else (vals[0], None)
            if state is None:
                state = [None, 0]
            if not s.is_null:
                from ..mysqltypes.datum import K_FLOAT

                if s.kind == K_FLOAT:
                    state[0] = (state[0] or 0.0) + s.val
                else:
                    state[0] = (state[0] + s.to_dec()) if state[0] is not None else s.to_dec()
            if name == "avg" and cnt is not None and not cnt.is_null:
                state[1] += cnt.to_int()
            return state
        if name in ("min", "max"):
            v = vals[0]
            if v.is_null:
                return state
            if state is None:
                return v
            from ..mysqltypes.datum import K_STR as _KS

            if v.kind == _KS and state.kind == _KS:
                from ..expr.expression import datum_sort_key

                ft = a.args[0].ret_type if a.args else None
                kv, ks = datum_sort_key(v, ft), datum_sort_key(state, ft)
                if kv[0] == ks[0]:
                    return state  # equal-weight ties keep the first value
                better = kv[0] < ks[0] if name == "min" else kv[0] > ks[0]
                return v if better else state
            c = compare_datum(v, state)
            return v if (c < 0 if name == "min" else c > 0) else state
        if name == "first_row":
            return state if state is not None else vals[0]
        if name == "group_concat":
            v = vals[0]
            if v.is_null:
                return state
            return v.to_str() if state is None else state + vals_sep + v.to_str()
        if name in ("stddev_pop", "stddev_samp", "var_pop", "var_samp"):
            cnt = vals[0].to_int() if not vals[0].is_null else 0
            s_ = vals[1].to_float() if not vals[1].is_null else 0.0
            sq = vals[2].to_float() if not vals[2].is_null else 0.0
            if state is None:
                state = [0, 0.0, 0.0]
            state[0] += cnt
            state[1] += s_
            state[2] += sq
            return state
        if name in ("bit_and", "bit_or", "bit_xor"):
            ident = -1 if name == "bit_and" else 0
            v = vals[0].to_int() if not vals[0].is_null else ident
            if state is None:
                state = ident
            if name == "bit_and":
                return state & v
            if name == "bit_or":
                return state | v
            return state ^ v
        if name == "approx_count_distinct":
            from ..statistics.fmsketch import FMSketch

            if vals[0].is_null:
                return state
            b = vals[0].val
            sk = FMSketch.deserialize(b if isinstance(b, (bytes, bytearray)) else str(b).encode("latin-1"))
            if state is None:
                return sk
            state.merge(sk)
            return state
        raise NotImplementedError(name)

    @staticmethod
    def _final_value(a: AggDesc, state, ft: FieldType) -> Datum:
        name = a.name
        if name == "count":
            return Datum.i(state or 0)
        if name == "sum":
            if state is None or state[0] is None:
                return Datum.null()
            v = state[0]
            return Datum.f(v) if isinstance(v, float) else Datum.d(v)
        if name == "avg":
            if state is None or state[0] is None or state[1] == 0:
                return Datum.null()
            v, cnt = state
            if isinstance(v, float):
                return Datum.f(v / cnt)
            q = v.div(Dec(cnt, 0))
            return Datum.d(q.rescale(max(ft.decimal, 0))) if q is not None else Datum.null()
        if name in ("min", "max", "first_row"):
            return state if state is not None else Datum.null()
        if name == "group_concat":
            return Datum.s(state[: a.max_len]) if state is not None else Datum.null()
        if name in ("stddev_pop", "stddev_samp", "var_pop", "var_samp"):
            import math as _math

            if state is None or state[0] == 0:
                return Datum.null()
            n_, s_, sq = state
            if name.endswith("_samp"):
                if n_ < 2:
                    return Datum.null()
                var = (sq - s_ * s_ / n_) / (n_ - 1)
            else:
                var = sq / n_ - (s_ / n_) ** 2
            var = max(var, 0.0)  # numeric guard
            return Datum.f(_math.sqrt(var) if name.startswith("stddev") else var)
        if name in ("bit_and", "bit_or", "bit_xor"):
            ident = -1 if name == "bit_and" else 0
            v = state if state is not None else ident
            return Datum.u(v & 0xFFFFFFFFFFFFFFFF)
        if name == "approx_count_distinct":
            return Datum.i(state.ndv() if state is not None else 0)
        raise NotImplementedError(name)


def _split_sides(c: Expression):
    """Concatenated-schema condition → per-(left i, right j) predicate."""

    def check(lchunk, rchunk, i, j) -> bool:
        row = Chunk(
            [col.take(np.array([i])) for col in lchunk.columns]
            + [col.take(np.array([j])) for col in rchunk.columns]
        )
        d, v = _broadcast_lane(*c.eval(row), 1)
        return bool(v[0]) and bool(d[0] != 0)

    return check


class HashJoinExec(Executor):
    """Hash join building on the right child (ref: executor/join.go:50;
    semi/anti variants ref joiner.go semiJoiner/antiSemiJoiner, null-aware
    NOT IN per the reference's NAAJ semantics)."""

    SPILL_PARTITIONS = 16

    def __init__(self, left: Executor, right: Executor, kind: str, eq_conds, other_conds, out_fts, na_key=None, spill_limit: int = 0):
        self.left = left
        self.right = right
        self.kind = kind
        self.eq_conds = eq_conds
        self.other_conds = other_conds
        self.out_fts = out_fts
        self.na_key = na_key
        self.spill_limit = spill_limit
        self.spilled = False
        self._done = False
        self._part_iter = None

    def open(self):
        # children are opened by drain() in next() — see SortExec.open
        self._done = False
        self._part_iter = None
        self.spilled = False

    def next(self):
        if self._part_iter is not None:
            return next(self._part_iter, None)
        if self._done:
            return None
        self._done = True
        if (
            self.spill_limit
            and self.eq_conds
            and self.na_key is None
            and self.kind in ("inner", "left", "right")
        ):
            self._part_iter = self._bounded()
            return next(self._part_iter, None)
        lchunk = drain(self.left)
        rchunk = drain(self.right)
        if self.kind in ("semi", "anti"):
            return self._semi_anti(lchunk, rchunk)
        return self._join_pair(lchunk, rchunk)

    # --- grace hash join spill (ref: executor/hash_table.go spillable
    # hashRowContainer + join.go partition-wise rebuild) --------------------

    def _bounded(self):
        """Memory-bounded flow: read the build side up to the quota; on
        exceed, hash-partition both sides to disk and join partition
        pairs one at a time (grace hash join)."""
        from ..utils.memory import chunk_bytes

        self.right.open()
        rchunks, rbytes = [], 0
        exceeded = False
        while True:
            c = self.right.next()
            if c is None:
                break
            if c.num_rows:
                rchunks.append(c)
                rbytes += chunk_bytes(c)
            if rbytes > self.spill_limit:
                exceeded = True
                break
        if not exceeded:
            self.right.close()
            rchunk = Chunk.concat_all(rchunks) if rchunks else Chunk.empty(self.right.out_fts, 0)
            out = self._join_pair(drain(self.left), rchunk)
            if out is not None and out.num_rows:
                yield out
            return
        yield from self._grace(rchunks)

    @staticmethod
    def _check_kill():
        from ..sched.scheduler import raise_if_interrupted

        raise_if_interrupted(_ACTIVE_SESSION.get())

    def _spill_side(self, chunk_iter, keys, parts, salt: int = 0):
        P = len(parts)
        for c in chunk_iter:
            self._check_kill()
            if not c.num_rows:
                continue
            lanes = [k.eval(c) for k in keys]
            pid = np.zeros(c.num_rows, dtype=np.int64)
            for i in range(c.num_rows):
                kt = _key_tuple(lanes, i)
                # NULL keys never match: any partition works (0); the salt
                # redistributes on recursive re-partitioning
                pid[i] = (hash((salt, kt)) % P) if kt is not None else 0
            for p in range(P):
                mask = pid == p
                if mask.any():
                    parts[p].write(c.filter(mask))

    MAX_SPILL_DEPTH = 3

    def _grace(self, rchunks):
        from ..chunk.chunk_io import SpillFile
        from ..planner.optimizer import _shift_expr

        self.spilled = True
        P = self.SPILL_PARTITIONS
        nl = len(self.left.out_fts)
        rkeys = [_shift_expr(r, -nl) for _, r in self.eq_conds]
        lkeys = [l for l, _ in self.eq_conds]
        self._spill_files: list = []

        def new_parts():
            parts = [SpillFile() for _ in range(P)]
            self._spill_files.extend(parts)
            return parts

        try:
            rparts = new_parts()

            def right_rest():
                yield from rchunks
                while (c := self.right.next()) is not None:
                    yield c

            self._spill_side(right_rest(), rkeys, rparts)
            self.right.close()
            self.left.open()

            def left_all():
                while (c := self.left.next()) is not None:
                    yield c

            self._spill_side(left_all(), lkeys, lparts := new_parts())
            self.left.close()
            for sf in rparts + lparts:
                sf.finish()
            for p in range(P):
                # rows only ever match inside their own key partition, so
                # outer-side padding per partition pair stays correct
                yield from self._join_partition(lparts[p], rparts[p], new_parts, depth=1)
        finally:
            for sf in self._spill_files:
                sf.cleanup()

    def _join_partition(self, lsf, rsf, new_parts, depth: int):
        """Join one spilled partition pair. A build side still over the
        quota re-partitions with a fresh hash salt (recursive grace); at
        max depth — one hot key that cannot split — it joins materialized.
        The probe side always streams chunk-at-a-time from disk, so probe
        memory is one chunk regardless of partition size."""
        from ..planner.optimizer import _shift_expr
        from ..utils.memory import chunk_bytes

        lfts = self.left.out_fts
        rfts = self.right.out_fts
        # stream the build partition, keeping at most quota bytes in
        # memory before deciding to re-partition (never materialize a
        # whole oversized partition just to measure it)
        rit = rsf.chunks(rfts)
        rcs, rbytes, oversize = [], 0, False
        for c in rit:
            rcs.append(c)
            rbytes += chunk_bytes(c)
            if rbytes > self.spill_limit and depth < self.MAX_SPILL_DEPTH:
                oversize = True
                break
        if oversize:
            nl = len(lfts)
            rkeys = [_shift_expr(r, -nl) for _, r in self.eq_conds]
            lkeys = [l for l, _ in self.eq_conds]

            def build_rest():
                yield from rcs
                yield from rit

            sub_r = new_parts()
            self._spill_side(build_rest(), rkeys, sub_r, salt=depth)
            del rcs
            sub_l = new_parts()
            self._spill_side(lsf.chunks(lfts), lkeys, sub_l, salt=depth)
            for sf in sub_r + sub_l:
                sf.finish()
            for p in range(len(sub_r)):
                yield from self._join_partition(sub_l[p], sub_r[p], new_parts, depth + 1)
            return
        rchunk = Chunk.concat_all(rcs)
        if not rchunk.num_cols:
            rchunk = Chunk.empty(rfts, 0)
        del rcs
        matched_right = np.zeros(rchunk.num_rows, dtype=bool) if self.kind == "right" else None
        build = self._build_vec(rchunk, len(lfts))  # factorize build ONCE
        for lc in lsf.chunks(lfts):
            self._check_kill()
            out = self._probe_pair_vec(lc, rchunk, matched_right, build=build)
            if out is not None and out.num_rows:
                yield out
        if matched_right is not None:
            pad = self._right_pad(Chunk.empty(lfts, 0), rchunk, matched_right)
            if pad is not None and pad.num_rows:
                yield pad

    def _join_pair(self, lchunk: Chunk, rchunk: Chunk) -> Chunk:
        nl = lchunk.num_cols
        matched_right = np.zeros(rchunk.num_rows, dtype=bool) if self.kind == "right" else None
        if self.eq_conds:
            out = self._probe_pair_vec(lchunk, rchunk, matched_right)
        else:
            table = self._build_table(rchunk, nl)
            out = self._probe_emit(lchunk, rchunk, table, matched_right)
        if matched_right is not None:
            pad = self._right_pad(lchunk, rchunk, matched_right)
            if pad is not None:
                out = out.concat(pad)
        return out

    # --- vectorized equi-join core (replaces the per-row python build/
    # probe; the reference parallelizes the same loops with worker fleets,
    # join.go:413 — numpy lanes are the idiomatic host equivalent) --------

    def _encode_join_keys(self, lchunk: Chunk, rchunk: Chunk):
        """Joint factorization of the eq-key lanes of BOTH sides into one
        code space → (lcodes, lvalid, rcodes, rvalid); equal values get
        equal int64 codes, NULLs are invalid (never match)."""
        from ..copr.host_engine import _lane_codes
        from ..planner.optimizer import _shift_expr

        nl = lchunk.num_cols
        nL, nR = lchunk.num_rows, rchunk.num_rows
        lanes = []
        valid = np.ones(nL + nR, dtype=bool)
        from ..expr.expression import collation_key_lane
        from ..mysqltypes import collate as _coll

        for l_e, r_e in self.eq_conds:
            ld, lv = _broadcast_lane(*l_e.eval(lchunk), nL)
            rd, rv = _broadcast_lane(*_shift_expr(r_e, -nl).eval(rchunk), nR)
            if (ld.dtype == object) != (rd.dtype == object):
                ld, rd = ld.astype(object), rd.astype(object)
            if ld.dtype == object:
                cc = _coll.resolve([l_e.ret_type, r_e.ret_type])
                if _coll.is_ci(cc):
                    ld = _coll.weight_lane(ld, cc)
                    rd = _coll.weight_lane(rd, cc)
            both = np.concatenate([ld, rd])
            bv = np.concatenate([lv, rv])
            codes = _lane_codes(both, bv)
            lanes.append(codes)
            valid &= codes > 0
        packed = np.zeros(nL + nR, dtype=np.int64)
        total, ok = 1, True
        for lane in lanes:
            rng = int(lane.max()) + 1 if len(lane) else 1
            if total > (1 << 62) // max(rng, 1):
                ok = False
                break
            packed = packed * rng + lane
            total *= rng
        if not ok:  # range-product overflow: lexicographic unique instead
            _, inv = np.unique(np.stack(lanes), axis=1, return_inverse=True)
            packed = inv.astype(np.int64) + 1
        return packed[:nL], valid[:nL], packed[nL:], valid[nL:]

    def _build_vec(self, rchunk: Chunk, nl: int):
        """Hoistable build-side factorization for streamed probing (the
        grace path): per-lane sorted uniques + packed sorted build codes.
        Returns None for object lanes or radix overflow — the caller then
        falls back to per-chunk joint encoding."""
        from ..planner.optimizer import _shift_expr

        nR = rchunk.num_rows
        lanes = []
        packed = np.zeros(nR, dtype=np.int64)
        valid = np.ones(nR, dtype=bool)
        total = 1
        for _, r_e in self.eq_conds:
            rd, rv = _broadcast_lane(*_shift_expr(r_e, -nl).eval(rchunk), nR)
            if rd.dtype == object:
                return None
            uniq = np.unique(rd[rv])
            rng = len(uniq) + 1
            if total > (1 << 62) // max(rng, 1):
                return None
            code = np.where(rv, np.searchsorted(uniq, rd) + 1, 0)
            valid &= code > 0
            packed = packed * rng + code
            total *= rng
            lanes.append(uniq)
        rk_eff = np.where(valid, packed, -1)
        order = np.argsort(rk_eff, kind="stable")
        return lanes, rk_eff[order], order

    def _probe_codes(self, build, lchunk: Chunk):
        """Map one probe chunk into a hoisted build's code space; probe
        values absent from the build get the no-match sentinel."""
        lanes, _, _ = build
        nL = lchunk.num_rows
        lk = np.zeros(nL, dtype=np.int64)
        match = np.ones(nL, dtype=bool)
        for (l_e, _), uniq in zip(self.eq_conds, lanes):
            ld, lv = _broadcast_lane(*l_e.eval(lchunk), nL)
            if ld.dtype == object:
                return None
            nu = len(uniq)
            pos = np.searchsorted(uniq, ld)
            posc = np.minimum(pos, max(nu - 1, 0))
            hit = lv & (pos < nu) & ((uniq[posc] == ld) if nu else False)
            match &= hit
            lk = lk * (nu + 1) + np.where(hit, pos + 1, 0)
        return np.where(match, lk, -2)

    def _probe_pair_vec(self, lchunk: Chunk, rchunk: Chunk, matched_right, build=None) -> Chunk:
        """Sort-probe equi-join of one (probe chunk, build chunk) pair:
        argsort the build codes, searchsorted the probe codes, expand the
        hit ranges with repeat arithmetic. Emission order matches the
        per-row reference loop (probe order, build rows ascending,
        left-outer misses interleaved in place)."""
        nL, nR = lchunk.num_rows, rchunk.num_rows
        lk_eff = self._probe_codes(build, lchunk) if build is not None else None
        if lk_eff is not None:
            _, rs, order = build
        else:
            lk, lval, rk, rval = self._encode_join_keys(lchunk, rchunk)
            order = np.argsort(np.where(rval, rk, -1), kind="stable")
            rs = np.where(rval, rk, -1)[order]
            lk_eff = np.where(lval, lk, -2)  # NULL probes match nothing
        starts = np.searchsorted(rs, lk_eff, side="left")
        ends = np.searchsorted(rs, lk_eff, side="right")
        counts = ends - starts
        miss = counts == 0
        if self.kind == "left":
            counts_eff = np.where(miss, 1, counts)
        else:
            counts_eff = counts
            miss = np.zeros(nL, dtype=bool)
        total = int(counts_eff.sum())
        li_arr = np.repeat(np.arange(nL, dtype=np.int64), counts_eff)
        cum = np.zeros(nL, dtype=np.int64)
        if nL:
            np.cumsum(counts_eff[:-1], out=cum[1:])
        within = np.arange(total, dtype=np.int64) - np.repeat(cum, counts_eff)
        pos = np.repeat(starts, counts_eff) + within
        if nR:
            ri_arr = order[np.minimum(pos, nR - 1)]
        else:
            ri_arr = np.zeros(total, dtype=np.int64)
        ri_arr = np.where(np.repeat(miss, counts_eff), -1, ri_arr)
        li_out, ri_out = li_arr.tolist(), ri_arr.tolist()
        out = _assemble_join(lchunk, rchunk, li_out, ri_out, self.out_fts)
        if self.other_conds:
            out, li_out, ri_out = self._apply_other(out, lchunk, rchunk, li_out, ri_out)
            ri_arr = np.asarray(ri_out, dtype=np.int64)
        if matched_right is not None and len(ri_arr):
            matched_right[ri_arr[ri_arr >= 0]] = True
        return out

    def _build_table(self, rchunk: Chunk, nl: int) -> dict:
        # right-side key exprs are over the concatenated schema; shift down
        from ..planner.optimizer import _shift_expr

        rkeys = [_shift_expr(r, -nl) for _, r in self.eq_conds]
        table: dict = {}
        if rchunk.num_rows and rkeys:
            key_lanes = [k.eval(rchunk) for k in rkeys]
            for i in range(rchunk.num_rows):
                kt = _key_tuple(key_lanes, i)
                if kt is None:
                    continue
                table.setdefault(kt, []).append(i)
        return table

    def _probe_emit(self, lchunk, rchunk, table, matched_right) -> Chunk:
        """Probe one left chunk against a built table: assemble matched
        pairs, apply other-conditions, left-pad misses, and record right
        matches into the cross-chunk `matched_right` accumulator."""
        lkeys = [l for l, _ in self.eq_conds]
        li_out, ri_out = [], []
        if lchunk.num_rows:
            lkey_lanes = [k.eval(lchunk) for k in lkeys]
            for i in range(lchunk.num_rows):
                kt = _key_tuple(lkey_lanes, i)
                matches = table.get(kt, []) if kt is not None else []
                if not self.eq_conds:
                    matches = range(rchunk.num_rows)  # cartesian
                hit = False
                for j in matches:
                    li_out.append(i)
                    ri_out.append(j)
                    hit = True
                if not hit and self.kind == "left":
                    li_out.append(i)
                    ri_out.append(-1)
        out = _assemble_join(lchunk, rchunk, li_out, ri_out, self.out_fts)
        if self.other_conds:
            out, li_out, ri_out = self._apply_other(out, lchunk, rchunk, li_out, ri_out)
        if matched_right is not None:
            for j in ri_out:
                if j >= 0:
                    matched_right[j] = True
        return out

    def _right_pad(self, lchunk, rchunk, matched_right) -> Chunk | None:
        """Unmatched build rows null-padded for right-outer joins; lchunk
        only donates the left-side schema (may be empty)."""
        extra_r = [j for j in range(rchunk.num_rows) if not matched_right[j]]
        if not extra_r:
            return None
        return _assemble_join(lchunk, rchunk, [-1] * len(extra_r), extra_r, self.out_fts)

    def _emit(self, lchunk, rchunk, li_out, ri_out) -> Chunk:
        """Assemble a fully-materialized pair result (MergeJoin path)."""
        out = _assemble_join(lchunk, rchunk, li_out, ri_out, self.out_fts)
        if self.other_conds:
            out, li_out, ri_out = self._apply_other(out, lchunk, rchunk, li_out, ri_out)
        if self.kind == "right":
            matched_right = np.zeros(rchunk.num_rows, dtype=bool)
            for j in ri_out:
                if j >= 0:
                    matched_right[j] = True
            pad = self._right_pad(lchunk, rchunk, matched_right)
            if pad is not None:
                out = out.concat(pad)
        return out

    def _semi_anti(self, lchunk: Chunk, rchunk: Chunk) -> Chunk:
        """Semi: emit left rows with >=1 match. Anti: emit left rows with
        none. na_key (NOT IN) adds null-awareness: a NULL probe value or a
        NULL build value among candidates yields SQL NULL → row dropped."""
        from ..planner.optimizer import _shift_expr

        nl = lchunk.num_cols
        n = lchunk.num_rows
        if n == 0:
            return lchunk
        if self.eq_conds and self.na_key is None and not self.other_conds:
            # vectorized EXISTS/NOT EXISTS: hit = any equal build key
            lk, lval, rk, rval = self._encode_join_keys(lchunk, rchunk)
            rs = np.sort(np.where(rval, rk, -1))
            lk_eff = np.where(lval, lk, -2)
            hit = np.searchsorted(rs, lk_eff, "right") > np.searchsorted(rs, lk_eff, "left")
            return lchunk.filter(hit if self.kind == "semi" else ~hit)
        lkeys = [l for l, _ in self.eq_conds]
        rkeys = [_shift_expr(r, -nl) for _, r in self.eq_conds]
        table: dict = {}
        if rchunk.num_rows and rkeys:
            key_lanes = [k.eval(rchunk) for k in rkeys]
            for j in range(rchunk.num_rows):
                kt = _key_tuple(key_lanes, j)
                if kt is not None:
                    table.setdefault(kt, []).append(j)
        lkey_lanes = [k.eval(lchunk) for k in lkeys]
        na_l = na_r = None
        if self.na_key is not None:
            na_l = _broadcast_lane(*self.na_key[0].eval(lchunk), n)
            na_r = _broadcast_lane(*_shift_expr(self.na_key[1], -nl).eval(rchunk), rchunk.num_rows)
        other = [_split_sides(c) for c in self.other_conds]
        keep = np.zeros(n, dtype=bool)
        if self.na_key is not None and not lkeys and not other:
            # uncorrelated NOT IN fast path: one value-set + has-null scan
            if rchunk.num_rows == 0:
                keep[:] = True
            else:
                has_null = not bool(na_r[1].all())
                if not has_null:
                    vals = set(na_r[0][na_r[1]].tolist())
                    for i in range(n):
                        keep[i] = bool(na_l[1][i]) and na_l[0][i] not in vals
            return lchunk.filter(keep)
        for i in range(n):
            if lkeys:
                kt = _key_tuple(lkey_lanes, i)
                cands = table.get(kt, []) if kt is not None else []
            else:
                cands = range(rchunk.num_rows)
            if other:
                cands = [j for j in cands if self._other_pass(other, lchunk, rchunk, i, j)]
            if self.na_key is None:
                hit = bool(cands) if not isinstance(cands, range) else rchunk.num_rows > 0
                keep[i] = hit if self.kind == "semi" else not hit
                continue
            # null-aware NOT IN over the candidate set
            cands = list(cands)
            if not cands:
                keep[i] = True  # x NOT IN (empty) is TRUE even for NULL x
                continue
            if not na_l[1][i]:
                continue  # NULL probe vs non-empty set → NULL → dropped
            x = na_l[0][i]
            verdict = True
            for j in cands:
                if not na_r[1][j] or na_r[0][j] == x:
                    verdict = False  # NULL build value or a match → not TRUE
                    break
            keep[i] = verdict
        return lchunk.filter(keep)

    @staticmethod
    def _other_pass(other, lchunk, rchunk, i, j) -> bool:
        for fn in other:
            if not fn(lchunk, rchunk, i, j):
                return False
        return True

    def _apply_other(self, out: Chunk, lchunk, rchunk, li, ri):
        mask = np.ones(out.num_rows, dtype=bool)
        for c in self.other_conds:
            d, v = c.eval(out)
            mask &= v & (d != 0)
        if self.kind == "left":
            # keep left rows that lose all matches as null-padded
            li_arr = np.array(li, dtype=np.int64)
            ri_arr = np.array(ri, dtype=np.int64)
            keep = mask | (ri_arr < 0)
            surviving = set(li_arr[keep & (ri_arr >= 0)].tolist())
            lost = sorted(set(li_arr.tolist()) - surviving - set(li_arr[ri_arr < 0].tolist()))
            out = out.filter(keep)
            li2 = li_arr[keep].tolist()
            ri2 = ri_arr[keep].tolist()
            if lost:
                pad = _assemble_join(lchunk, rchunk, lost, [-1] * len(lost), self.out_fts)
                out = out.concat(pad)
                li2 += lost
                ri2 += [-1] * len(lost)
            return out, li2, ri2
        out2 = out.filter(mask)
        li2 = [x for x, m in zip(li, mask) if m]
        ri2 = [x for x, m in zip(ri, mask) if m]
        return out2, li2, ri2

    def close(self):
        if self._part_iter is not None and hasattr(self._part_iter, "close"):
            # unwinds _grace's finally so spill files delete deterministically
            # even when a Limit stops pulling early
            self._part_iter.close()
            self._part_iter = None
        self.left.close()
        self.right.close()


class MergeJoinExec(HashJoinExec):
    """Sort-merge join (ref: executor/merge_join.go MergeJoinExec): sorts
    both inputs on the join keys and zips equal-key groups. Inner and
    left-outer kinds; picked by `tidb_opt_prefer_merge_join`."""

    def next(self):
        if self._done:
            return None
        self._done = True
        lchunk = drain(self.left)
        rchunk = drain(self.right)
        nl = lchunk.num_cols
        from ..copr.host_engine import _lex_argsort
        from ..planner.optimizer import _shift_expr

        lkeys = [l for l, _ in self.eq_conds]
        rkeys = [_shift_expr(r, -nl) for _, r in self.eq_conds]
        if not lkeys:
            raise TiDBError("merge join requires equality join keys")
        from ..mysqltypes import collate as _coll

        # one collation per key PAIR, resolved across both sides (the
        # HashJoin rule): weighting only one side would never match
        pair_colls = [
            _coll.resolve([l.ret_type, r.ret_type]) for l, r in zip(lkeys, rkeys)
        ]

        def ci_lanes(keys, chunk):
            out = []
            for k, cc in zip(keys, pair_colls):
                d, v = _broadcast_lane(*k.eval(chunk), chunk.num_rows)
                if _coll.is_ci(cc) and getattr(d, "dtype", None) == object:
                    d = _coll.weight_lane(d, cc)
                out.append((d, v))
            return out

        ll = ci_lanes(lkeys, lchunk)
        rl = ci_lanes(rkeys, rchunk)
        lorder = _lex_argsort([(d, v, False) for d, v in ll], lchunk.num_rows)
        rorder = _lex_argsort([(d, v, False) for d, v in rl], rchunk.num_rows)
        # key tuples materialized once per row (None = NULL key, never matches)
        lk = [_key_tuple(ll, i) for i in lorder]
        rk = [_key_tuple(rl, j) for j in rorder]

        li_out, ri_out = [], []
        i = j = 0
        n, m = len(lorder), len(rorder)
        while i < n:
            kl = lk[i]
            if kl is None:
                if self.kind == "left":
                    li_out.append(lorder[i])
                    ri_out.append(-1)
                i += 1
                continue
            # advance right to the first key >= kl
            while j < m and (rk[j] is None or rk[j] < kl):
                j += 1
            # gather the right equal-key group
            j2 = j
            while j2 < m and rk[j2] == kl:
                j2 += 1
            # emit all left rows of this key against the group
            i2 = i
            while i2 < n and lk[i2] == kl:
                if j2 > j:
                    for jj in range(j, j2):
                        li_out.append(lorder[i2])
                        ri_out.append(rorder[jj])
                elif self.kind == "left":
                    li_out.append(lorder[i2])
                    ri_out.append(-1)
                i2 += 1
            i = i2
        return self._emit(lchunk, rchunk, li_out, ri_out)


class ChunkSourceExec(Executor):
    """Feeds a pre-materialized chunk into an executor tree."""

    def __init__(self, chunk: Chunk, out_fts):
        self.chunk = chunk
        self.out_fts = out_fts
        self._done = False

    def open(self):
        self._done = False

    def next(self):
        if self._done:
            return None
        self._done = True
        return self.chunk


class IndexLookupJoinExec(Executor):
    """Index-lookup join (ref: executor/index_lookup_join.go): batches the
    outer side's join keys into inner-index point lookups, fetches only
    matching inner rows, then probes them as a hash join. Wins when the
    outer side is small relative to the inner table."""

    def __init__(self, outer: Executor, ctx, table, index, dag, kind, eq_conds, other_conds, out_fts):
        self.outer = outer
        self.ctx = ctx
        self.table = table
        self.index = index
        self.dag = dag
        self.kind = kind
        self.eq_conds = eq_conds
        self.other_conds = other_conds
        self.out_fts = out_fts
        self._done = False

    def open(self):
        self._done = False

    def close(self):
        self.outer.close()

    def next(self):
        if self._done:
            return None
        self._done = True
        from ..codec import tablecodec
        from ..codec.key import encode_datum_key
        from ..planner.ranger import const_to_col_datum, prefix_next

        lchunk = drain(self.outer)
        lkey = self.eq_conds[0][0]
        d, v = _broadcast_lane(*lkey.eval(lchunk), lchunk.num_rows)
        # distinct non-null probe datums → index point ranges
        col = Column(lkey.ret_type, d, v)
        inner_ft = self.table.columns[self.index.col_offsets[0]].ft
        seen = set()
        ranges = []
        for i in range(lchunk.num_rows):
            if not v[i]:
                continue
            dat = col.get_datum(i)
            # probe keys must be encoded in the INNER column's key domain
            # (e.g. unsigned → 0x04 flag) or they never match stored entries
            conv = const_to_col_datum(dat, inner_ft)
            if conv is not None:
                dat = conv
            key = dat.val if not isinstance(dat.val, (bytearray,)) else bytes(dat.val)
            key = (dat.kind, key)
            if key in seen:
                continue
            seen.add(key)
            buf = bytearray(tablecodec.index_prefix(self.table.id, self.index.id))
            encode_datum_key(buf, dat)
            enc = bytes(buf)
            ranges.append((enc, prefix_next(enc)))
        # probe/fetch batching (ref: executor/index_lookup_join.go —
        # tidb_index_join_batch_size outer keys per probe round,
        # tidb_index_lookup_size handles per lookup task)
        join_batch = max(1, int(self.ctx.vars.get("tidb_index_join_batch_size", "25000")))
        lookup_size = max(1, int(self.ctx.vars.get("tidb_index_lookup_size", "20000")))
        handles = []
        for i in range(0, len(ranges), join_batch):
            entries = self.ctx.cop.index_entries(
                self.table, self.index, ranges[i : i + join_batch],
                self.ctx.read_ts, txn=self.ctx.txn,
            )
            handles.extend(h for _, h in entries)
        chunks = []
        for i in range(0, len(handles), lookup_size):
            chunks.extend(
                self.ctx.cop.send_handles(
                    self.table, self.dag, handles[i : i + lookup_size],
                    self.ctx.read_ts, self.ctx.engine, txn=self.ctx.txn,
                )
            )
        rchunk = Chunk.concat_all(chunks) if chunks else Chunk.empty(self.dag.output_types(), 0)
        return self._probe(lchunk, rchunk)

    def _probe(self, lchunk: Chunk, rchunk: Chunk) -> Chunk:
        """Final join over the fetched inner rows — hash probe here (this
        class IS the index_lookup_hash_join.go equivalent: the fetched
        inner rows become the hash build side)."""
        inner = HashJoinExec(
            ChunkSourceExec(lchunk, [c.ft for c in lchunk.columns]),
            ChunkSourceExec(rchunk, self.dag.output_types()),
            self.kind,
            self.eq_conds,
            self.other_conds,
            self.out_fts,
        )
        return drain(inner)


class IndexLookupMergeJoinExec(IndexLookupJoinExec):
    """Merge variant (ref: executor/index_lookup_merge_join.go): probes
    the fetched inner rows with a sort-merge join instead of a hash
    table, producing join-key-ordered output. MergeJoinExec re-sorts both
    sides (it does not yet exploit that the index fetch already returns
    key order); the variant's value here is the ordered output and the
    hash-table-free memory profile. Chosen by the INL_MERGE_JOIN hint."""

    def _probe(self, lchunk: Chunk, rchunk: Chunk) -> Chunk:
        inner = MergeJoinExec(
            ChunkSourceExec(lchunk, [c.ft for c in lchunk.columns]),
            ChunkSourceExec(rchunk, self.dag.output_types()),
            self.kind,
            self.eq_conds,
            self.other_conds,
            self.out_fts,
        )
        return drain(inner)


class MemtableExec(Executor):
    """Materializes an INFORMATION_SCHEMA virtual table
    (ref: executor/infoschema_reader.go memtableRetriever)."""

    def __init__(self, plan):
        self.plan = plan
        self.out_fts = [c.ft for c in plan.out_cols]
        self._done = False

    def open(self):
        self._done = False

    def next(self):
        if self._done:
            return None
        self._done = True
        return Chunk.from_datum_rows(self.out_fts, self.plan.provider())


class CTERefExec(Executor):
    """Reads the recursive CTE's current working table
    (ref: executor/cte_table_reader.go)."""

    def __init__(self, plan):
        self.plan = plan
        self.out_fts = [c.ft for c in plan.out_cols]
        self._done = False

    def open(self):
        self._done = False

    def next(self):
        if self._done:
            return None
        self._done = True
        c = self.plan.storage.chunk
        return c if c is not None else Chunk.empty(self.out_fts, 0)


class RecursiveCTEExec(Executor):
    """WITH RECURSIVE fixpoint iteration (ref: executor/cte.go:60 CTEExec):
    materialize the seed, then run the recursive branch against the
    previous iteration's rows until it produces nothing new."""

    MAX_ITER = 1000  # MySQL cte_max_recursion_depth default

    def __init__(self, plan, ctx):
        self.plan = plan
        self.ctx = ctx
        self.out_fts = [c.ft for c in plan.out_cols]
        self._done = False

    def open(self):
        self._done = False

    def next(self):
        if self._done:
            return None
        self._done = True
        seed = _coerce_chunk(drain(build_executor(self.plan.children[0], self.ctx)), self.out_fts)
        seen = None
        if self.plan.distinct:
            seen = set()
            keep = []
            for i, r in enumerate(seed.iter_rows()):
                t = tuple(r)
                if t not in seen:
                    seen.add(t)
                    keep.append(i)
            if len(keep) < seed.num_rows:
                seed = seed.take(np.asarray(keep, dtype=np.int64))
        result = [seed]
        work = seed
        max_iter = int(self.ctx.vars.get("cte_max_recursion_depth", self.MAX_ITER))
        for _ in range(max_iter):
            if work.num_rows == 0:
                break
            self.plan.storage.chunk = work
            rec = _coerce_chunk(drain(build_executor(self.plan.children[1], self.ctx)), self.out_fts)
            if self.plan.distinct:
                keep = []
                for i, r in enumerate(rec.iter_rows()):
                    t = tuple(r)
                    if t not in seen:
                        seen.add(t)
                        keep.append(i)
                rec = rec.take(np.asarray(keep, dtype=np.int64))
            if rec.num_rows == 0:
                break
            result.append(rec)
            work = rec
        else:
            raise TiDBError("recursive CTE exceeded max recursion depth")
        self.plan.storage.chunk = None
        return Chunk.concat_all(result)


def _key_tuple(key_lanes, i):
    """Join key for row i; None if any key part is NULL (never matches)."""
    kt = []
    for d, v in key_lanes:
        if not v[i]:
            return None
        x = d[i]
        if isinstance(x, (np.floating, float)):
            kt.append(float(x))
        elif isinstance(x, (np.integer, int)):
            kt.append(float(x))  # int/float cross-type joins hash alike
        else:
            kt.append(x)
    return tuple(kt)


def _assemble_join(lchunk: Chunk, rchunk: Chunk, li: list[int], ri: list[int], out_fts) -> Chunk:
    n = len(li)
    cols = []
    li_arr = np.asarray(li, dtype=np.int64)
    ri_arr = np.asarray(ri, dtype=np.int64)

    def gather(chunk: Chunk, idx_arr, col: int):
        c = chunk.columns[col]
        if c.data.shape[0] == 0:
            # all-padding side (e.g. right-outer pad with no probe rows)
            data = (np.full(n, None, dtype=object) if c.data.dtype == object
                    else np.zeros(n, dtype=c.data.dtype))
            return data, np.zeros(n, dtype=bool)
        safe = np.where(idx_arr >= 0, idx_arr, 0)
        data = c.data[safe]
        valid = c.valid[safe] & (idx_arr >= 0)
        return data, valid

    for k in range(lchunk.num_cols):
        d, v = gather(lchunk, li_arr, k)
        cols.append(Column(lchunk.columns[k].ft, d, v))
    for k in range(rchunk.num_cols):
        d, v = gather(rchunk, ri_arr, k)
        cols.append(Column(rchunk.columns[k].ft, d, v))
    return Chunk(cols)


class SetOpExec(Executor):
    def __init__(self, children, ops, out_fts):
        self.children = children
        self.ops = ops
        self.out_fts = out_fts

    def open(self):
        pass

    def next(self):
        if getattr(self, "_done", False):
            return None
        self._done = True
        chunks = [drain(c) for c in self.children]
        base = _coerce_chunk(chunks[0], self.out_fts)
        for op, nxt in zip(self.ops, chunks[1:]):
            nxt = _coerce_chunk(nxt, self.out_fts)
            if op in ("union", "union_all"):
                base = base.concat(nxt)  # distinct handled by planner's agg
            elif op == "except":
                rows = {tuple(r) for r in nxt.iter_rows_hashable()} if hasattr(nxt, "iter_rows_hashable") else {tuple(r) for r in nxt.iter_rows()}
                keep = [i for i, r in enumerate(base.iter_rows()) if tuple(r) not in rows]
                base = base.take(np.asarray(keep, dtype=np.int64))
            elif op == "intersect":
                rows = {tuple(r) for r in nxt.iter_rows()}
                keep = [i for i, r in enumerate(base.iter_rows()) if tuple(r) in rows]
                base = base.take(np.asarray(keep, dtype=np.int64))
        return base


def _coerce_chunk(c: Chunk, fts) -> Chunk:
    """Align a chunk's column types to target fts (set-op branch merge)."""
    cols = []
    for col, ft in zip(c.columns, fts):
        if col.ft.tp == ft.tp and max(col.ft.decimal, 0) == max(ft.decimal, 0):
            cols.append(Column(ft, col.data, col.valid))
            continue
        out = Column.empty(ft, len(col.data))
        for i in range(len(col.data)):
            out.set_datum(i, col.get_datum(i))
        cols.append(out)
    return Chunk(cols)

"""Device window kernels — one `lax.sort` + segmented scans per window spec.

The reference parallelizes windows by hash-sharding partitions across a
worker fleet (executor/shuffle.go:77) and pipelining within a partition
(executor/pipelined_window.go:37). On TPU the same work maps onto ONE
fused XLA program over the whole chunk:

    lexicographic `lax.sort` by (partition, order, row-id) keys
      -> partition/peer boundary flags (vectorized compares)
      -> cumulative / segmented scans (cumsum, cummax, associative_scan)
      -> gathers at frame ends
      -> scatter back to input row order via the carried row-id operand

Every function the host `WindowExec` supports for MySQL's default frame
(RANGE UNBOUNDED PRECEDING..CURRENT ROW) has a device form here; the sort
order, NULL placement (first asc / last desc) and tie-breaks reproduce
`host_engine._lex_argsort` exactly, so outputs are bit-identical to the
host oracle for integer/decimal/string lanes (floats match up to summation
order).

Strings never reach the device: lanes are dict-encoded to sorted-vocab
codes (binary-collation order preserved), computed in code space, decoded
on the way out — the tpu_engine string story applied to windows.
"""

from __future__ import annotations

from functools import lru_cache, reduce

import numpy as np

from ..copr.tpu_engine import lex_sort_perm
from ..jaxenv import jax, jnp, pack_flat, unpack_flat
from ..mysqltypes.mydecimal import DIV_FRAC_INCR, MAX_SCALE, Dec, pow10

# Below this many rows the ~100ms device dispatch dominates; 'auto' stays
# on host. 'tpu' forces the device path (tests, EXPLAIN).
MIN_DEVICE_ROWS = 1 << 15

# func names with a device kernel (everything WindowExec supports)
SUPPORTED = {
    "row_number", "rank", "dense_rank", "ntile", "cume_dist", "percent_rank",
    "lead", "lag", "first_value", "last_value", "nth_value",
    "count", "sum", "avg", "min", "max",
}

# funcs whose output is a value drawn from the argument lane (decode via
# the argument's vocab when the lane was dict-encoded)
_PASSTHROUGH = {"lead", "lag", "first_value", "last_value", "nth_value", "min", "max"}


def _bucket(n: int) -> int:
    """Pad to a power of two so recompiles are bounded (tpu_engine TILE rule)."""
    p = 1024
    while p < n:
        p <<= 1
    return p


def encode_obj(d: np.ndarray, v: np.ndarray, extra=None):
    """Dict-encode an object lane to sorted-vocab codes.

    Mirrors `_lex_argsort`'s np.unique trick, so code order == the host's
    binary sort order. `extra` values (lead/lag defaults) share the vocab."""
    strs = np.where(v, d, "").astype("U")
    pool = strs if extra is None else np.concatenate([strs, np.atleast_1d(extra).astype("U")])
    vocab, inv = np.unique(pool, return_inverse=True)
    codes = inv[: len(strs)].astype(np.int64)
    extra_codes = inv[len(strs):].astype(np.int64) if extra is not None else None
    return codes, vocab, extra_codes


# largest static ROWS window lowered via the on-device sparse table; wider
# sliding frames stay on host (memory: log2(w) extra lanes of length P)
MAX_DEVICE_FRAME_W = 1 << 16


def frame_width(frkey) -> int:
    """Static max width of a both-bounded ROWS frame key; <=0 == always
    empty."""
    shift = {"pre": -1, "cur": 0, "fol": 1}
    _, sk, so, ek, eo = frkey
    return (shift[ek] * eo if ek in shift else 0) - (shift[sk] * so if sk in shift else 0) + 1


def _canon_key_items(d: np.ndarray, v: np.ndarray, desc: bool):
    """One key lane → [(codes, rng)] of non-negative order codes with NULL
    placement (first asc / last desc, the host _lex_argsort contract) and
    direction folded in, ready for radix packing. Wide-span lanes that
    cannot shift return two items: a 2-range NULL word and a full-range
    canonical int64 word (rng None = standalone)."""
    if d.dtype == np.float64:
        # order-preserving bitcast (sign-flip trick); -0.0 folds into +0.0
        b = np.where(d == 0.0, 0.0, d).view(np.int64)
        key = np.where(b < 0, ~b, b ^ np.int64(-0x8000000000000000))
    elif d.dtype == np.uint64:
        key = (d ^ np.uint64(0x8000000000000000)).view(np.int64)
    else:
        key = d.astype(np.int64)
    vals = key[v]
    if len(vals) == 0:
        return [(np.where(v, 1, 0 if not desc else 2).astype(np.int64), 3)]
    mn, mx = int(vals.min()), int(vals.max())
    span = mx - mn
    if span < (1 << 61):
        if desc:
            shifted = (mx - key) + 1
        else:
            shifted = (key - mn) + 1
        codes = np.where(v, shifted, 0 if not desc else span + 2)
        return [(codes.astype(np.int64), span + 3)]
    # full-range lane: separate NULL word + canonical value word
    nullw = np.where(v, 1, 0 if not desc else 2).astype(np.int64)
    vw = np.where(v, ~key if desc else key, 0)  # ~ reverses int64 order
    return [(nullw, 3), (vw, None)]


def _pack_words(items, n: int, P: int):
    """Radix-pack [(codes, rng)] (most significant first) into as few
    device sort words as possible; pad rows [n:P] get a sentinel ABOVE
    every real code so they sort last and form their own partition.
    Words whose packed range fits int32 ship narrow (native TPU sorts)."""
    words: list[np.ndarray] = []
    cur, cur_rng = None, 1

    def flush():
        nonlocal cur, cur_rng
        if cur is None:
            return
        pad_val = cur_rng
        w = np.full(P, pad_val, dtype=np.int64)
        w[:n] = cur
        words.append(w.astype(np.int32) if cur_rng < (1 << 31) - 1 else w)
        cur, cur_rng = None, 1

    for codes, rng in items:
        if rng is None:  # standalone full-range word
            flush()
            w = np.full(P, np.iinfo(np.int64).max, dtype=np.int64)
            w[:n] = codes
            words.append(w)
            continue
        if cur is not None and cur_rng <= (1 << 61) // rng:
            cur = cur * rng + codes
            cur_rng *= rng
        else:
            flush()
            cur, cur_rng = codes.copy(), rng
    flush()
    return words


@lru_cache(maxsize=256)
def _build_kernel(spec):
    """spec = (n_part_words, n_order_words, funcspecs, framespecs) — all
    static, hashable. Key canonicalization/packing happened on HOST
    (_canon_key_items/_pack_words); the kernel only sorts the few packed
    words. framespecs[i] is None (default frame) or Frame.key()."""
    npw, now, funcspecs, framespecs = spec

    def kernel(words, fargs, range_key=None):
        P = words[0].shape[0]
        iota = jnp.arange(P, dtype=jnp.int64)
        vals = []
        for fa in fargs:
            for (d, v) in fa:
                vals += [d, v]
        # successive single-key stable sorts, NOT one multi-key sort: the
        # TPU comparator inlining explodes beyond 2 sort keys (294s
        # compile for one 7-key int32 sort vs 22s for the pass form —
        # measured on axon); the ascending initial perm IS the row-id
        # tie-break
        perm = lex_sort_perm(list(words), iota_dtype=jnp.int32)
        s_ops = [o[perm] for o in words]
        s_vals = [v[perm] for v in vals]

        def chg(idxs):
            if not idxs:
                return jnp.zeros(P, dtype=bool).at[0].set(True)
            c = reduce(
                jnp.logical_or, [s_ops[i][1:] != s_ops[i][:-1] for i in idxs]
            )
            return jnp.concatenate([jnp.ones(1, dtype=bool), c])

        pstart = chg(list(range(npw)))
        ostart = chg(list(range(npw + now)))
        pfirst = jax.lax.cummax(jnp.where(pstart, iota, 0))
        peer_first = jax.lax.cummax(jnp.where(ostart, iota, 0))

        def seg_last(starts):
            nxt = jnp.concatenate(
                [jnp.where(starts, iota, P)[1:], jnp.full(1, P, dtype=jnp.int64)]
            )
            return jnp.flip(jax.lax.cummin(jnp.flip(nxt))) - 1

        plast = seg_last(pstart)
        peer_last = seg_last(ostart)
        # default-frame end: current peer group (== partition end w/o ORDER BY)
        fe = peer_last
        pid = jnp.cumsum(pstart) - 1
        psize = plast - pfirst + 1
        rn = iota - pfirst
        ones = jnp.ones(P, dtype=bool)

        def scat(x):
            return jnp.zeros(P, dtype=x.dtype).at[perm].set(x)

        def range_offset_bounds(sk, so, ek, eo, meta):
            """RANGE N PRECEDING/FOLLOWING: binary search the single
            numeric ORDER BY key (host _range_bounds twin). Keys shift
            into a per-partition composite band (pid*S + shifted-key with
            NULL sentinels at the band edges), so ONE global sort-method
            searchsorted resolves every partition at once — S carries
            enough margin that offset targets never leave their band.
            gmin/gmax arrive as RUNTIME scalars (range_key[2:]) so data
            changes never recompile; only `desc` and the offsets are
            static."""
            desc = meta
            kd, kv, gmin, gmax = range_key
            S = (gmax - gmin) + 2 * max(abs(so), abs(eo), 1) + 4
            ks, kvs = kd[perm].astype(jnp.int64), kv[perm]
            kk = (gmax - ks) if desc else (ks - gmin)  # ascending, >= 0
            # NULLs sort first asc / last desc (canon-word contract):
            # sentinels keep the composite globally sorted
            sent = (S - 1) if desc else -1
            comp = pid * S + jnp.where(kvs, kk, sent)
            # valid-key run edges per partition (invalid block is
            # contiguous at the head asc / tail desc)
            inv = (~kvs).astype(jnp.int64)
            cinv = jnp.cumsum(inv)
            before = jnp.where(pfirst > 0, cinv[jnp.maximum(pfirst - 1, 0)], 0)
            ninv = cinv[plast] - before  # invalids in this partition
            vfirst = pfirst + (ninv if not desc else 0)
            vlast = plast - (ninv if desc else 0)

            def search(off, kind, side):
                tgt = comp + (off if kind == "fol" else -off)
                pos_ = jnp.searchsorted(comp, tgt, side=side, method="sort")
                return pos_.astype(jnp.int64)

            fs_r = jnp.clip(search(so, sk, "left"), vfirst, vlast + 1) \
                if sk in ("pre", "fol") else None
            fe_r = jnp.clip(search(eo, ek, "right") - 1, vfirst - 1, vlast) \
                if ek in ("pre", "fol") else None
            return fs_r, fe_r, kvs

        def frame_of(frkey):
            """frame key → (fs, fe, nonempty) over sorted rows (the host
            WindowExec._frame_bounds twin; RANGE offsets resolve through
            range_offset_bounds when the builder shipped the key lane)."""
            if frkey is None:
                return pfirst, fe, ones
            unit, sk, so, ek, eo = frkey[:5]
            cur_s = iota if unit == "rows" else peer_first
            cur_e = iota if unit == "rows" else peer_last

            def pos(kind, off, cur):
                if kind == "up":
                    return pfirst
                if kind == "uf":
                    return plast
                if kind == "cur":
                    return cur
                if unit == "range":
                    # offset kinds resolve by value search below; rows
                    # with NULL keys keep their peer block (host rule)
                    return cur
                return iota - off if kind == "pre" else iota + off

            fs_raw = pos(sk, so, cur_s)
            fe_raw = pos(ek, eo, cur_e)
            if unit == "range" and len(frkey) > 5 and (
                sk in ("pre", "fol") or ek in ("pre", "fol")
            ):
                fs_r, fe_r, kvs = range_offset_bounds(sk, so, ek, eo, frkey[5])  # frkey[5] = desc
                # NULL-key rows keep their peer-block bounds (host rule)
                if fs_r is not None:
                    fs_raw = jnp.where(kvs, fs_r, fs_raw)
                if fe_r is not None:
                    fe_raw = jnp.where(kvs, fe_r, fe_raw)
            ne = (fs_raw <= fe_raw) & (fs_raw <= plast) & (fe_raw >= pfirst)
            return jnp.clip(fs_raw, pfirst, plast), jnp.clip(fe_raw, pfirst, plast), ne

        def frame_cnt_of(sv, fb):
            fs_, fe_, ne_ = fb
            cs = jnp.cumsum(sv.astype(jnp.int64))
            before = jnp.where(fs_ > 0, cs[jnp.maximum(fs_ - 1, 0)], 0)
            return jnp.where(ne_, cs[fe_] - before, 0)

        def frame_sum_of(sd, sv, fb):
            fs_, fe_, ne_ = fb
            zero = jnp.zeros((), dtype=sd.dtype)
            cs = jnp.cumsum(jnp.where(sv, sd, zero))
            before = jnp.where(fs_ > 0, cs[jnp.maximum(fs_ - 1, 0)], zero)
            return jnp.where(ne_, cs[fe_] - before, zero)

        outs = []
        vi = 0

        def take_arg():
            nonlocal vi
            d, v = s_vals[vi], s_vals[vi + 1]
            vi += 2
            return d, v

        for fs, frkey in zip(funcspecs, framespecs):
            name = fs[0]
            fb = frame_of(frkey)
            if name == "row_number":
                sd, sv = rn + 1, ones
            elif name == "rank":
                sd, sv = peer_first - pfirst + 1, ones
            elif name == "dense_rank":
                dcs = jnp.cumsum(ostart.astype(jnp.int64))
                sd, sv = dcs - dcs[pfirst] + 1, ones
            elif name == "ntile":
                k = fs[1]
                big, rem = psize // k, psize % k
                cut = rem * (big + 1)
                sd = jnp.where(
                    big > 0,
                    jnp.where(
                        rn < cut,
                        rn // jnp.maximum(big + 1, 1),
                        rem + (rn - cut) // jnp.maximum(big, 1),
                    ),
                    rn,
                ) + 1
                sv = ones
            elif name == "cume_dist":
                # ratio of small ints: divide on HOST — TPU f64 division is
                # emulated and not correctly rounded (parity with the oracle)
                outs.append((scat(peer_last - pfirst + 1), scat(psize)))
                continue
            elif name == "percent_rank":
                rank = peer_first - pfirst + 1
                outs.append((scat(rank - 1), scat(psize - 1)))
                continue
            elif name in ("lead", "lag"):
                off, has_default = fs[1], fs[2]
                sd0, sv0 = take_arg()
                tgt = iota + (off if name == "lead" else -off)
                tgt_c = jnp.clip(tgt, 0, P - 1)
                ok = (tgt >= 0) & (tgt < P) & (pid[tgt_c] == pid)
                if has_default:
                    dd, dv = take_arg()
                else:
                    dd = jnp.zeros(P, dtype=sd0.dtype)
                    dv = jnp.zeros(P, dtype=bool)
                sd = jnp.where(ok, sd0[tgt_c], dd)
                sv = jnp.where(ok, sv0[tgt_c], dv)
            elif name in ("first_value", "last_value", "nth_value"):
                sd0, sv0 = take_arg()
                fs_, fe_, ne_ = fb
                if name == "first_value":
                    pos, ok = fs_, ne_
                elif name == "last_value":
                    pos, ok = fe_, ne_
                else:
                    pos = fs_ + fs[1] - 1
                    ok = ne_ & (pos <= fe_)
                    pos = jnp.clip(pos, 0, P - 1)
                sd, sv = sd0[pos], sv0[pos] & ok
            elif name == "count":
                if fs[1]:
                    _, sv0 = take_arg()
                else:
                    sv0 = ones
                sd, sv = frame_cnt_of(sv0, fb), ones
            elif name in ("sum", "avg"):
                sd0, sv0 = take_arg()
                fcnt = frame_cnt_of(sv0, fb)
                fsum = frame_sum_of(sd0, sv0, fb)
                if name == "sum":
                    sd, sv = fsum, fcnt > 0
                else:
                    # both avg kinds finish on host from (sum, cnt): 'dec'
                    # for exact Dec rounding, 'f' because TPU f64 division
                    # is not correctly rounded
                    outs.append((scat(fsum), scat(fcnt)))
                    continue
            elif name in ("min", "max"):
                sd0, sv0 = take_arg()
                is_f = jnp.issubdtype(sd0.dtype, jnp.floating)
                if name == "min":
                    fill = jnp.inf if is_f else np.iinfo(np.dtype(sd0.dtype)).max
                    op = jnp.minimum
                else:
                    fill = -jnp.inf if is_f else np.iinfo(np.dtype(sd0.dtype)).min
                    op = jnp.maximum
                masked = jnp.where(sv0, sd0, jnp.asarray(fill, dtype=sd0.dtype))
                fs_, fe_, ne_ = fb

                def comb(a, b, _op=op):
                    af, av = a
                    bf, bv = b
                    return af | bf, jnp.where(bf, bv, _op(av, bv))

                if frkey is None or frkey[1] == "up":
                    # growing frame: prefix scan per partition, read at fe
                    _, acc = jax.lax.associative_scan(comb, (pstart, masked))
                    sd = acc[fe_]
                elif frkey[3] == "uf":
                    # shrinking frame: suffix scan (reversed prefix), read at fs
                    plastflag = iota == plast
                    _, acc_r = jax.lax.associative_scan(
                        comb, (jnp.flip(plastflag), jnp.flip(masked))
                    )
                    sd = jnp.flip(acc_r)[fs_]
                else:
                    # both-bounded ROWS frame: static-depth sparse table
                    # (range-min-query); frame never crosses a partition
                    L = max(1, frame_width(frkey).bit_length())
                    levels = [masked]
                    for k in range(1, L):
                        h = 1 << (k - 1)
                        prev = levels[-1]
                        shifted = jnp.concatenate(
                            [prev[h:], jnp.full(h, fill, dtype=prev.dtype)]
                        )
                        levels.append(op(prev, shifted))
                    stk = jnp.stack(levels)
                    w = jnp.maximum(fe_ - fs_ + 1, 1)
                    # floor(log2 w) via a static comparison ladder — frexp
                    # lowers to an s64 bitcast the TPU X64 rewrite rejects
                    lk = jnp.zeros(P, dtype=jnp.int64)
                    for j in range(1, L):
                        lk = lk + (w >= (1 << j)).astype(jnp.int64)
                    half = jnp.left_shift(jnp.asarray(1, jnp.int64), lk)
                    sd = op(stk[lk, fs_], stk[lk, jnp.maximum(fe_ - half + 1, 0)])
                sv = frame_cnt_of(sv0, fb) > 0
            else:  # pragma: no cover — guarded by SUPPORTED
                raise AssertionError(name)
            outs.append((scat(sd), scat(sv.astype(jnp.bool_))))
        # pack every (value, valid) pair into ONE flat int64 vector with
        # in-band dtype tags and BIT-PACKED valid lanes: each device→host
        # array read over a remote link costs a full round-trip, and for
        # full-row window results the bool lanes would otherwise double
        # the transferred bytes.
        return pack_flat([o for pair in outs for o in pair])

    return jax.jit(kernel)


def _avg_dec_finish(s: np.ndarray, cnt: np.ndarray, arg_scale: int, out_scale: int):
    """Exact AVG(decimal) from int64 (sum, count): replicates
    Dec.div(Dec(cnt,0)).rescale(out_scale) — including the double rounding
    (round-half-away at scale+DIV_FRAC_INCR, then again at out_scale)."""
    sdiv = min(arg_scale + DIV_FRAC_INCR, MAX_SCALE)
    p1 = pow10(sdiv - arg_scale)
    valid = cnt > 0
    c = np.maximum(cnt, 1)
    amax = int(np.abs(s).max()) if s.size else 0
    if amax > (1 << 62) // max(p1, 1):
        # int64 headroom exhausted — exact big-int per row
        qs = np.zeros_like(s)
        for i in range(len(s)):
            if valid[i]:
                q = Dec(int(s[i]), arg_scale).div(Dec(int(cnt[i]), 0))
                qs[i] = q.rescale(out_scale).value if q is not None else 0
        return qs, valid
    num = np.abs(s) * p1
    q = num // c
    q += (num - q * c) * 2 >= c
    if sdiv > out_scale:
        p2 = pow10(sdiv - out_scale)
        q2 = q // p2
        q2 += (q - q2 * p2) * 2 >= p2
        q = q2
    elif out_scale > sdiv:
        q = q * pow10(out_scale - sdiv)
    return np.where(s < 0, -q, q).astype(np.int64), valid


# Prepared device inputs (packed sort words + padded arg lanes, all
# device-resident) keyed by (provenance, n, bucket), where provenance =
# (store uid, table id, data version, window-spec digest) from the
# caller. A repeated window over an unchanged table skips lane eval,
# dict-encoding, packing AND the device-link upload. Byte-budgeted LRU
# (hits re-insert; eviction pops the least recently used). Entries pin
# device (HBM) buffers — the budget bounds that too.
_INPUT_CACHE: dict = {}
_INPUT_CACHE_BYTES = [0]
INPUT_CACHE_BUDGET = 2 << 30


def _input_cache_put(key, value, nbytes: int):
    while _INPUT_CACHE and _INPUT_CACHE_BYTES[0] + nbytes > INPUT_CACHE_BUDGET:
        k = next(iter(_INPUT_CACHE))
        _, old_n = _INPUT_CACHE.pop(k)
        _INPUT_CACHE_BYTES[0] -= old_n
    _INPUT_CACHE[key] = (value, nbytes)
    _INPUT_CACHE_BYTES[0] += nbytes


def run_cached_window(provenance, n: int):
    """Replay a fully-prepared window (device inputs + post metadata) for
    a stable provenance, or None on miss. Lets the caller skip lane
    evaluation and dict-encoding entirely on repeat executions."""
    key = (provenance, n, _bucket(n))
    cached = _INPUT_CACHE.get(key)
    if cached is None:
        return None
    _INPUT_CACHE[key] = _INPUT_CACHE.pop(key)  # LRU: hits refresh recency
    words, fargs, pwords_n, owords_n, fspecs_meta, range_dev = cached[0]
    return _run_prepared(words, fargs, pwords_n, owords_n, fspecs_meta, n, range_dev)


def run_device_window(part_lanes, order_lanes, fspecs, n: int, provenance=None,
                      range_lane=None):
    """Execute a window spec on device; returns [(data, valid), ...] per func
    in input row order (numpy, length n).

    part_lanes: [(d, v)] int64/float64 (pre-encoded strings)
    order_lanes: [((d, v), desc)]
    fspecs: per func dict — {name, static, args: [(d, v), ...], post}
      post: ('decode', vocab) | ('avg_dec', arg_scale, out_scale) | None
    provenance: stable (table, version, spec-digest) identity from the
      caller, or None — enables the prepared-device-input cache.
    """
    P = _bucket(n)

    cache_key = (provenance, n, P) if provenance is not None else None
    cached = _INPUT_CACHE.get(cache_key) if cache_key is not None else None
    if cached is not None:
        _INPUT_CACHE[cache_key] = _INPUT_CACHE.pop(cache_key)  # LRU touch
        words, fargs, pwords_n, owords_n, fspecs_meta, range_dev = cached[0]
        return _run_prepared(words, fargs, pwords_n, owords_n, fspecs_meta, n, range_dev)

    def pad(d, v):
        dd = np.zeros(P, dtype=d.dtype)
        vv = np.zeros(P, dtype=bool)
        dd[:n], vv[:n] = d, v
        return jnp.asarray(dd), jnp.asarray(vv)

    part_items = []
    for d, v in part_lanes:
        part_items += _canon_key_items(np.asarray(d), np.asarray(v), False)
    if not part_items:
        # no PARTITION BY: one trivial word still separates the pad block
        part_items = [(np.zeros(n, dtype=np.int64), 1)]
    order_items = []
    for (d, v), desc in order_lanes:
        order_items += _canon_key_items(np.asarray(d), np.asarray(v), bool(desc))
    pwords = _pack_words(part_items, n, P)
    owords = _pack_words(order_items, n, P)
    words = tuple(jnp.asarray(w) for w in pwords + owords)
    fargs = tuple(tuple(pad(d, v) for d, v in f["args"]) for f in fspecs)
    if range_lane is not None:
        d0, v0, gmin, gmax = range_lane
        range_dev = pad(d0, v0) + (jnp.asarray(np.int64(gmin)), jnp.asarray(np.int64(gmax)))
    else:
        range_dev = None
    if cache_key is not None:
        nbytes = sum(w.nbytes for w in words) + sum(
            d.nbytes + v.nbytes for fa in fargs for d, v in fa
        ) + (sum(x.nbytes for x in range_dev) if range_dev is not None else 0)
        fspecs_meta = [{k: v for k, v in f.items() if k != "args"} for f in fspecs]
        _input_cache_put(
            cache_key,
            (words, fargs, len(pwords), len(owords), fspecs_meta, range_dev), nbytes,
        )
    return _run_prepared(words, fargs, len(pwords), len(owords), fspecs, n, range_dev)


def _run_prepared(words, fargs, n_pwords: int, n_owords: int, fspecs, n: int,
                  range_dev=None):
    funcspecs = tuple(f["static"] for f in fspecs)
    framespecs = tuple(f.get("frame") for f in fspecs)
    kernel = _build_kernel((n_pwords, n_owords, funcspecs, framespecs))
    flat = unpack_flat(np.asarray(kernel(words, fargs, range_dev)))
    outs = [(flat[2 * i], flat[2 * i + 1]) for i in range(len(fspecs))]

    results = []
    for f, (a, b) in zip(fspecs, outs):
        a = np.asarray(a)[:n]
        b = np.asarray(b)[:n]
        post = f.get("post")
        if post is None:
            results.append((a, b.astype(bool)))
        elif post[0] == "decode":
            vocab = post[1]
            v = b.astype(bool)
            code = np.clip(a, 0, max(len(vocab) - 1, 0))
            data = np.empty(n, dtype=object)
            data[:] = vocab[code] if len(vocab) else ""
            results.append((data, v))
        elif post[0] == "cume_dist":  # a=frame rows, b=psize (>=1)
            results.append((a / np.maximum(b, 1), np.ones(n, dtype=bool)))
        elif post[0] == "percent_rank":  # a=rank-1, b=psize-1
            data = np.where(b > 0, a / np.maximum(b, 1), 0.0)
            results.append((data, np.ones(n, dtype=bool)))
        elif post[0] == "avg_f":  # a=frame_sum(f64), b=frame_cnt
            cnt = b.astype(np.int64)
            data = np.where(cnt > 0, a / np.maximum(cnt, 1), 0.0)
            results.append((data, cnt > 0))
        else:  # avg_dec: a=frame_sum, b=frame_cnt (int64)
            _, arg_scale, out_scale = post
            qs, valid = _avg_dec_finish(a, b.astype(np.int64), arg_scale, out_scale)
            results.append((qs, valid))
    return results

"""MySQL field types (ref: types/field_type.go, parser/mysql type codes).

The TypeCode values follow the MySQL protocol type space so that a wire
layer can serialize them directly.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class TypeCode(enum.IntEnum):
    Decimal = 0x00  # legacy; we always use NewDecimal
    Tiny = 0x01
    Short = 0x02
    Long = 0x03
    Float = 0x04
    Double = 0x05
    Null = 0x06
    Timestamp = 0x07
    Longlong = 0x08
    Int24 = 0x09
    Date = 0x0A
    Duration = 0x0B
    Datetime = 0x0C
    Year = 0x0D
    NewDate = 0x0E
    Varchar = 0x0F
    Bit = 0x10
    JSON = 0xF5
    NewDecimal = 0xF6
    Enum = 0xF7
    Set = 0xF8
    TinyBlob = 0xF9
    MediumBlob = 0xFA
    LongBlob = 0xFB
    Blob = 0xFC
    VarString = 0xFD
    String = 0xFE


INT_TYPES = {TypeCode.Tiny, TypeCode.Short, TypeCode.Long, TypeCode.Int24, TypeCode.Longlong, TypeCode.Year, TypeCode.Bit}
FLOAT_TYPES = {TypeCode.Float, TypeCode.Double}
STRING_TYPES = {TypeCode.Varchar, TypeCode.VarString, TypeCode.String, TypeCode.TinyBlob, TypeCode.MediumBlob, TypeCode.LongBlob, TypeCode.Blob, TypeCode.Enum, TypeCode.Set}
TIME_TYPES = {TypeCode.Date, TypeCode.Datetime, TypeCode.Timestamp, TypeCode.NewDate}

# Column flags (ref: parser/mysql/type.go)
NOT_NULL_FLAG = 1
PRI_KEY_FLAG = 2
UNIQUE_KEY_FLAG = 4
MULTIPLE_KEY_FLAG = 8
UNSIGNED_FLAG = 32
BINARY_FLAG = 128
AUTO_INCREMENT_FLAG = 512

UNSPECIFIED_LENGTH = -1


@dataclass
class FieldType:
    """Type descriptor for a column or expression result.

    (ref: types/field_type.go FieldType: Tp/Flag/Flen/Decimal/Charset/Collate)
    """

    tp: TypeCode
    flag: int = 0
    flen: int = UNSPECIFIED_LENGTH
    decimal: int = UNSPECIFIED_LENGTH  # fractional digits for NewDecimal/time fsp
    charset: str = "utf8mb4"
    collate: str = "utf8mb4_bin"
    elems: tuple = field(default_factory=tuple)  # enum/set values

    @property
    def is_unsigned(self) -> bool:
        return bool(self.flag & UNSIGNED_FLAG)

    @property
    def not_null(self) -> bool:
        return bool(self.flag & NOT_NULL_FLAG)

    def is_int(self) -> bool:
        return self.tp in INT_TYPES

    def is_float(self) -> bool:
        return self.tp in FLOAT_TYPES

    def is_decimal(self) -> bool:
        return self.tp == TypeCode.NewDecimal

    def is_string(self) -> bool:
        return self.tp in STRING_TYPES

    def is_time(self) -> bool:
        return self.tp in TIME_TYPES

    def clone(self, **kw) -> "FieldType":
        d = dict(tp=self.tp, flag=self.flag, flen=self.flen, decimal=self.decimal, charset=self.charset, collate=self.collate, elems=self.elems)
        d.update(kw)
        return FieldType(**d)

    def type_name(self) -> str:
        n = _TYPE_NAMES.get(self.tp, "unknown")
        if self.tp == TypeCode.NewDecimal and self.flen > 0:
            n = f"{n}({self.flen},{max(self.decimal, 0)})"
        elif self.is_string() and self.flen > 0:
            n = f"{n}({self.flen})"
        if self.is_unsigned:
            n += " unsigned"
        return n


_TYPE_NAMES = {
    TypeCode.Tiny: "tinyint",
    TypeCode.Short: "smallint",
    TypeCode.Long: "int",
    TypeCode.Int24: "mediumint",
    TypeCode.Longlong: "bigint",
    TypeCode.Float: "float",
    TypeCode.Double: "double",
    TypeCode.NewDecimal: "decimal",
    TypeCode.Varchar: "varchar",
    TypeCode.String: "char",
    TypeCode.Blob: "text",
    TypeCode.Date: "date",
    TypeCode.Datetime: "datetime",
    TypeCode.Timestamp: "timestamp",
    TypeCode.Duration: "time",
    TypeCode.JSON: "json",
    TypeCode.Year: "year",
    TypeCode.Bit: "bit",
    TypeCode.Enum: "enum",
    TypeCode.Null: "null",
}


def ft_long(unsigned=False) -> FieldType:
    return FieldType(TypeCode.Long, flag=UNSIGNED_FLAG if unsigned else 0, flen=11)


def ft_longlong(unsigned=False) -> FieldType:
    return FieldType(TypeCode.Longlong, flag=UNSIGNED_FLAG if unsigned else 0, flen=20)


def ft_double() -> FieldType:
    return FieldType(TypeCode.Double, flen=22)


def ft_decimal(flen=11, frac=0) -> FieldType:
    return FieldType(TypeCode.NewDecimal, flen=flen, decimal=frac)


def ft_varchar(flen=255) -> FieldType:
    return FieldType(TypeCode.Varchar, flen=flen)


def ft_date() -> FieldType:
    return FieldType(TypeCode.Date, flen=10, decimal=0)


def ft_datetime(fsp=0) -> FieldType:
    return FieldType(TypeCode.Datetime, flen=19, decimal=fsp)


_NAME_TO_TYPE = {
    "tinyint": TypeCode.Tiny,
    "smallint": TypeCode.Short,
    "mediumint": TypeCode.Int24,
    "int": TypeCode.Long,
    "integer": TypeCode.Long,
    "bigint": TypeCode.Longlong,
    "float": TypeCode.Float,
    "double": TypeCode.Double,
    "real": TypeCode.Double,
    "decimal": TypeCode.NewDecimal,
    "numeric": TypeCode.NewDecimal,
    "varchar": TypeCode.Varchar,
    "char": TypeCode.String,
    "text": TypeCode.Blob,
    "tinytext": TypeCode.TinyBlob,
    "mediumtext": TypeCode.MediumBlob,
    "longtext": TypeCode.LongBlob,
    "blob": TypeCode.Blob,
    "varbinary": TypeCode.VarString,
    "binary": TypeCode.String,
    "date": TypeCode.Date,
    "datetime": TypeCode.Datetime,
    "timestamp": TypeCode.Timestamp,
    "time": TypeCode.Duration,
    "year": TypeCode.Year,
    "json": TypeCode.JSON,
    "bit": TypeCode.Bit,
    "enum": TypeCode.Enum,
    "set": TypeCode.Set,
    "bool": TypeCode.Tiny,
    "boolean": TypeCode.Tiny,
}


def parse_type_name(name: str, args=(), unsigned=False, elems=(), collate="") -> FieldType:
    """Map a SQL type name + length args to a FieldType (used by the DDL parser)."""
    tp = _NAME_TO_TYPE.get(name.lower())
    if tp is None:
        raise ValueError(f"unknown type {name!r}")
    ft = FieldType(tp)
    if collate:
        from .collate import is_supported

        if not is_supported(collate):
            raise ValueError(f"Unknown collation: '{collate}'")
        ft.collate = collate
    if unsigned:
        ft.flag |= UNSIGNED_FLAG
    if tp == TypeCode.NewDecimal:
        ft.flen = args[0] if args else 10
        ft.decimal = args[1] if len(args) > 1 else 0
    elif tp in (TypeCode.Datetime, TypeCode.Timestamp, TypeCode.Duration):
        ft.decimal = args[0] if args else 0
    elif args:
        ft.flen = args[0]
    if tp in (TypeCode.Enum, TypeCode.Set):
        ft.elems = tuple(elems)
    return ft

"""Fixed-point decimal (ref: types/mydecimal.go).

The reference stores decimals as 9-digit "words"; here a decimal is an
arbitrary-precision scaled integer `(value, scale)` meaning value * 10^-scale.
This representation is device-friendly: columns of decimals with a shared
column scale become plain int64 arrays on device, and SUM/COUNT/AVG partials
are exact integer reductions (`psum` over int64 lanes).

MySQL scale rules implemented here:
  add/sub : result scale = max(s1, s2)
  mul     : result scale = s1 + s2 (capped at 30)
  div     : result scale = s1 + 4 (DivFracIncr, capped at 30)
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

MAX_SCALE = 30
DIV_FRAC_INCR = 4


@lru_cache(maxsize=None)
def pow10(n: int) -> int:
    return 10**n


@dataclass(frozen=True)
class Dec:
    value: int  # scaled integer
    scale: int  # fractional digits

    def rescale(self, scale: int) -> "Dec":
        if scale == self.scale:
            return self
        if scale > self.scale:
            return Dec(self.value * pow10(scale - self.scale), scale)
        # shrink with round-half-away-from-zero (MySQL rounding)
        p = pow10(self.scale - scale)
        v, r = divmod(abs(self.value), p)
        if r * 2 >= p:
            v += 1
        return Dec(v if self.value >= 0 else -v, scale)

    def __add__(self, o: "Dec") -> "Dec":
        s = max(self.scale, o.scale)
        return Dec(self.rescale(s).value + o.rescale(s).value, s)

    def __sub__(self, o: "Dec") -> "Dec":
        s = max(self.scale, o.scale)
        return Dec(self.rescale(s).value - o.rescale(s).value, s)

    def __mul__(self, o: "Dec") -> "Dec":
        s = self.scale + o.scale
        d = Dec(self.value * o.value, s)
        return d.rescale(MAX_SCALE) if s > MAX_SCALE else d

    def div(self, o: "Dec") -> "Dec | None":
        """Returns None on division by zero (SQL NULL)."""
        if o.value == 0:
            return None
        s = min(self.scale + DIV_FRAC_INCR, MAX_SCALE)
        # numerator scaled to s + o.scale so the quotient has scale s
        num = self.value * pow10(s + o.scale - self.scale)
        q, r = divmod(abs(num), abs(o.value))
        if r * 2 >= abs(o.value):
            q += 1
        if (num < 0) != (o.value < 0):
            q = -q
        return Dec(q, s)

    def neg(self) -> "Dec":
        return Dec(-self.value, self.scale)

    def cmp(self, o: "Dec") -> int:
        s = max(self.scale, o.scale)
        a, b = self.rescale(s).value, o.rescale(s).value
        return (a > b) - (a < b)

    def to_float(self) -> float:
        return self.value / pow10(self.scale)

    def to_int(self) -> int:
        """Round to integer (half away from zero)."""
        return self.rescale(0).value

    def is_zero(self) -> bool:
        return self.value == 0

    def __str__(self) -> str:
        if self.scale == 0:
            return str(self.value)
        sign = "-" if self.value < 0 else ""
        v = abs(self.value)
        ip, fp = divmod(v, pow10(self.scale))
        return f"{sign}{ip}.{fp:0{self.scale}d}"

    __repr__ = __str__


def dec_from_string(s: str) -> Dec:
    s = s.strip()
    exp = 0
    for e in ("e", "E"):
        if e in s:
            s, es = s.split(e, 1)
            exp = int(es)
            break
    neg = s.startswith("-")
    s = s.lstrip("+-")
    if "." in s:
        ip, fp = s.split(".", 1)
    else:
        ip, fp = s, ""
    digits = (ip + fp) or "0"
    v = int(digits)
    scale = len(fp) - exp
    if scale < 0:
        v *= pow10(-scale)
        scale = 0
    if scale > MAX_SCALE:
        return Dec(-v if neg else v, scale).rescale(MAX_SCALE)
    return Dec(-v if neg else v, scale)


def dec_from_int(v: int) -> Dec:
    return Dec(v, 0)


def dec_from_float(f: float, scale: int | None = None) -> Dec:
    if scale is None:
        return dec_from_string(repr(f))
    return Dec(round(f * pow10(scale)), scale)


def dec_round(d: Dec, frac: int) -> Dec:
    """ROUND(d, frac) — keeps at most `frac` fractional digits."""
    if frac >= d.scale:
        return d
    if frac < 0:
        r = d.rescale(0)
        p = pow10(-frac)
        v, rem = divmod(abs(r.value), p)
        if rem * 2 >= p:
            v += 1
        v *= p
        return Dec(v if r.value >= 0 else -v, 0)
    return d.rescale(frac)

from .field_type import (
    FieldType,
    TypeCode,
    NOT_NULL_FLAG,
    PRI_KEY_FLAG,
    UNSIGNED_FLAG,
    AUTO_INCREMENT_FLAG,
    ft_long,
    ft_longlong,
    ft_double,
    ft_decimal,
    ft_varchar,
    ft_date,
    ft_datetime,
    parse_type_name,
)
from .datum import Datum, K_NULL, K_INT, K_UINT, K_FLOAT, K_DEC, K_STR, K_BYTES, K_TIME, K_DUR
from .mydecimal import Dec, dec_from_string, dec_round
from .coretime import (
    pack_time,
    unpack_time,
    parse_datetime,
    format_time,
    time_year,
    time_month,
    time_day,
)

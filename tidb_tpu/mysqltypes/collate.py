"""Collation weight strings (ref: util/collate/, expression/collation.go,
charset/collations generated tables — redesigned over Unicode
normalization instead of shipped weight tables).

A collation maps a string to a WEIGHT string such that binary comparison
of weights == collated comparison of the originals. Everything that
compares/sorts/groups strings (expression compare kernels, lexicographic
sorts, group-by factorization, join key encoding, the device
dict-encoder's sorted-vocab order) runs on weights when the column's
collation is case-insensitive, and on the raw bytes for binary
collations.

Weight sources:
 - *_unicode_ci: EXACT UCA 4.0.0 primary weights (uca400_weights.npz,
   derived from the public allkeys-4.0.0.txt — the table MySQL's
   utf8mb4_unicode_ci implements; ref: util/collate/unicode_ci.go
   semantics: ignorables drop, supplementary planes weigh 0xFFFD, PAD
   SPACE truncates trailing spaces).
 - *_general_ci: per-character NFD base letter, uppercased (accent- and
   case-insensitive for Latin; code-point order elsewhere). ß folds to S
   (matches MySQL general_ci's ß=s single-character behavior).
 - *_0900_ai_ci / *_unicode_520_ci: NFKD + casefold + combining-mark
   strip — UCA primary-strength approximation (those need UCA 9.0/5.2
   tables; documented gap).
"""

from __future__ import annotations

import os
import unicodedata
from functools import lru_cache

import numpy as np

_GENERAL_CI = {
    "utf8mb4_general_ci", "utf8_general_ci", "latin1_swedish_ci", "latin1_general_ci",
    "ascii_general_ci",
}
_UNICODE_CI = {
    "utf8mb4_unicode_ci", "utf8_unicode_ci", "utf8mb4_0900_ai_ci", "utf8mb4_unicode_520_ci",
}
_BIN = {"binary", "utf8mb4_bin", "utf8_bin", "latin1_bin", "ascii_bin", "utf8mb4_0900_bin"}

SUPPORTED = _GENERAL_CI | _UNICODE_CI | _BIN

DEFAULT = "utf8mb4_bin"


def is_ci(coll: str | None) -> bool:
    return bool(coll) and coll in (_GENERAL_CI | _UNICODE_CI)


def is_supported(coll: str) -> bool:
    return coll in SUPPORTED


@lru_cache(maxsize=65536)
def _general_ci_char(ch: str) -> str:
    d = unicodedata.normalize("NFD", ch)
    base = "".join(c for c in d if not unicodedata.combining(c)) or d
    u = base.upper()
    return u[0] if u else ch


_UCA400_EXACT = {"utf8mb4_unicode_ci", "utf8_unicode_ci"}
_uca400 = None


def _uca400_tables():
    global _uca400
    if _uca400 is None:
        path = os.path.join(os.path.dirname(__file__), "uca400_weights.npz")
        z = np.load(path)
        _uca400 = (z["offsets"], z["weights"])
    return _uca400


@lru_cache(maxsize=65536)
def _uca400_char(ch: str) -> str:
    cp = ord(ch)
    if cp > 0xFFFF:
        return "�"  # supplementary planes: single implicit weight
    offsets, weights = _uca400_tables()
    run = weights[offsets[cp]:offsets[cp + 1]]
    return "".join(chr(int(w)) for w in run)


def weight(s: str, coll: str) -> str:
    """Weight string for one value under `coll` (identity for binary)."""
    if coll in _GENERAL_CI:
        return "".join(_general_ci_char(ch) for ch in s)
    if coll in _UCA400_EXACT:
        # PAD SPACE: trailing spaces never distinguish values
        return "".join(_uca400_char(ch) for ch in s.rstrip(" "))
    if coll in _UNICODE_CI:
        d = unicodedata.normalize("NFKD", s.casefold())
        return "".join(c for c in d if not unicodedata.combining(c))
    return s


def weight_lane(d: np.ndarray, coll: str) -> np.ndarray:
    """Object lane → weight-string lane (same array when binary). Cached
    per distinct value; bytes entries decode latin-1 like the rest of the
    engine's mixed-lane handling."""
    if not is_ci(coll):
        return d
    out = np.empty(len(d), dtype=object)
    cache: dict = {}
    for i, s in enumerate(d):
        w = cache.get(s)
        if w is None:
            if isinstance(s, (bytes, bytearray)):
                w = weight(bytes(s).decode("latin-1"), coll)
            elif isinstance(s, str):
                w = weight(s, coll)
            else:
                w = s  # non-string residue (NULL fill values): pass through
            cache[s] = w
        out[i] = w
    return out


def resolve(fts) -> str:
    """Collation for a comparison across operand types — the first
    case-insensitive string collation wins (the coercibility ladder
    collapsed: columns beat literals, which carry the default bin)."""
    for ft in fts:
        if ft is not None and ft.is_string() and is_ci(getattr(ft, "collate", None)):
            return ft.collate
    return DEFAULT

"""Datum — the boxed SQL value (ref: types/datum.go).

Used only at slow boundaries (constants, point values, result rendering);
the hot paths operate on columnar Chunk/Tile data, never on Datums.
"""

from __future__ import annotations

import math
from fractions import Fraction

from .mydecimal import Dec, dec_from_string, dec_from_float, pow10
from .field_type import FieldType, TypeCode
from .coretime import format_time

K_NULL = 0
K_INT = 1
K_UINT = 2
K_FLOAT = 3
K_DEC = 4
K_STR = 5
K_BYTES = 6
K_TIME = 7  # packed int64 datetime
K_DUR = 8  # nanoseconds int


class Datum:
    __slots__ = ("kind", "val")

    def __init__(self, kind: int, val=None):
        self.kind = kind
        self.val = val

    # --- constructors -------------------------------------------------
    @staticmethod
    def null() -> "Datum":
        return Datum(K_NULL)

    @staticmethod
    def i(v: int) -> "Datum":
        return Datum(K_INT, int(v))

    @staticmethod
    def u(v: int) -> "Datum":
        return Datum(K_UINT, int(v))

    @staticmethod
    def f(v: float) -> "Datum":
        return Datum(K_FLOAT, float(v))

    @staticmethod
    def d(v: Dec) -> "Datum":
        return Datum(K_DEC, v)

    @staticmethod
    def s(v: str) -> "Datum":
        return Datum(K_STR, v)

    @staticmethod
    def b(v: bytes) -> "Datum":
        return Datum(K_BYTES, v)

    @staticmethod
    def t(packed: int) -> "Datum":
        return Datum(K_TIME, int(packed))

    # --- predicates ---------------------------------------------------
    @property
    def is_null(self) -> bool:
        return self.kind == K_NULL

    # --- conversions --------------------------------------------------
    def to_float(self) -> float:
        k = self.kind
        if k in (K_INT, K_UINT, K_TIME, K_DUR):
            return float(self.val)
        if k == K_FLOAT:
            return self.val
        if k == K_DEC:
            return self.val.to_float()
        if k in (K_STR, K_BYTES):
            s = self.val if isinstance(self.val, str) else self.val.decode("utf8", "replace")
            try:
                return float(s.strip() or 0)
            except ValueError:
                # MySQL parses the numeric prefix
                import re

                m = re.match(r"\s*[-+]?\d*\.?\d*(e[-+]?\d+)?", s, re.I)
                try:
                    return float(m.group(0)) if m and m.group(0).strip() else 0.0
                except ValueError:
                    return 0.0
        raise TypeError(f"cannot convert kind {k} to float")

    def to_dec(self) -> Dec:
        k = self.kind
        if k == K_DEC:
            return self.val
        if k in (K_INT, K_UINT):
            return Dec(self.val, 0)
        if k == K_FLOAT:
            return dec_from_float(self.val)
        if k in (K_STR, K_BYTES):
            s = self.val if isinstance(self.val, str) else self.val.decode("utf8", "replace")
            try:
                return dec_from_string(s)
            except ValueError:
                return Dec(0, 0)
        raise TypeError(f"cannot convert kind {k} to decimal")

    def to_int(self) -> int:
        k = self.kind
        if k in (K_INT, K_UINT, K_TIME, K_DUR):
            return self.val
        if k == K_FLOAT:
            # half away from zero, matching Dec.rescale (MySQL rounding)
            v = self.val
            return math.floor(v + 0.5) if v >= 0 else math.ceil(v - 0.5)
        if k == K_DEC:
            return self.val.to_int()
        if k in (K_STR, K_BYTES):
            return self.to_dec().to_int()
        raise TypeError(f"cannot convert kind {k} to int")

    def to_str(self) -> str:
        k = self.kind
        if k == K_STR:
            return self.val
        if k == K_BYTES:
            return self.val.decode("utf8", "replace")
        if k == K_FLOAT:
            v = self.val
            return str(int(v)) if v == int(v) and abs(v) < 1e15 else repr(v)
        return str(self.val)

    def render(self, ft: FieldType | None = None) -> str | None:
        """Result-set rendering (what a MySQL client would display)."""
        if self.is_null:
            return None
        if self.kind == K_TIME:
            is_date = ft is not None and ft.tp == TypeCode.Date
            fsp = ft.decimal if ft is not None and ft.decimal > 0 else 0
            return format_time(self.val, is_date=is_date, fsp=fsp)
        if self.kind == K_DUR:
            us = int(self.val)
            sign = "-" if us < 0 else ""
            us = abs(us)
            h, rem = divmod(us // 1_000_000, 3600)
            m, s = divmod(rem, 60)
            out = f"{sign}{h:02d}:{m:02d}:{s:02d}"
            fsp = ft.decimal if ft is not None and ft.decimal > 0 else 0
            if fsp > 0:
                out = (out + f".{us % 1_000_000:06d}")[: len(out) + 1 + fsp]
            return out
        return self.to_str()

    def __repr__(self):
        if self.is_null:
            return "NULL"
        return f"{self.to_str()}"

    def __eq__(self, other):
        if not isinstance(other, Datum):
            return NotImplemented
        return compare_datum(self, other) == 0 if not (self.is_null or other.is_null) else self.kind == other.kind

    def __hash__(self):
        """Consistent with __eq__: equal datums hash equal.

        Python guarantees hash(int) == hash(float) == hash(Fraction) for
        equal numeric values, so numeric kinds hash their exact value;
        strings and bytes hash their text (eq compares them as text).
        """
        k = self.kind
        if k == K_NULL:
            return hash(None)
        if k == K_DEC:
            return hash(Fraction(self.val.value, pow10(self.val.scale)))
        if k == K_BYTES:
            return hash(self.val.decode("utf8", "replace"))
        return hash(self.val)


_STRINGY = (K_STR, K_BYTES)


def compare_datum(a: Datum, b: Datum) -> int:
    """SQL comparison; NULL sorts first (ref: types/datum.go Compare)."""
    if a.is_null or b.is_null:
        return (not a.is_null) - (not b.is_null)
    ka, kb = a.kind, b.kind
    if ka == kb and ka not in _STRINGY:
        if ka == K_DEC:
            return a.val.cmp(b.val)
        va, vb = a.val, b.val
        return (va > vb) - (va < vb)
    if ka in _STRINGY and kb in _STRINGY:
        # varchar vs binary compares as text (binary collation)
        va, vb = a.to_str(), b.to_str()
        return (va > vb) - (va < vb)
    # mixed numeric comparison through float (string side parses numeric prefix)
    fa, fb = a.to_float(), b.to_float()
    return (fa > fb) - (fa < fb)

"""Datetime/date representation (ref: types/time.go, types/core_time.go).

A datetime is packed into a single int64 whose natural integer order equals
chronological order, so packed times compare/sort/min/max directly as int64
lanes on device:

    packed = ((((((year*13 + month)*32 + day)*24 + hour)*60 + minute)*60
               + second) * 1_000_000) + microsecond

(The *13 month radix matches the reference's core time layout idea; zero
month/day values used by MySQL "zero dates" survive the packing.)
"""

from __future__ import annotations

import re

_US = 1_000_000

# Extraction divisors/moduli derived from the packing radices — the single
# source of truth shared with expr/builtins date functions.
DIV_SECOND = _US
DIV_MINUTE = DIV_SECOND * 60
DIV_HOUR = DIV_MINUTE * 60
DIV_DAY = DIV_HOUR * 24
DIV_MONTH = DIV_DAY * 32
DIV_YEAR = DIV_MONTH * 13
MOD_MICRO = _US
MOD_SECOND = 60
MOD_MINUTE = 60
MOD_HOUR = 24
MOD_DAY = 32
MOD_MONTH = 13


def pack_time(year: int, month: int, day: int, hour: int = 0, minute: int = 0, second: int = 0, micro: int = 0) -> int:
    ymd = (year * 13 + month) * 32 + day
    return ((((ymd * 24 + hour) * 60 + minute) * 60 + second)) * _US + micro


def unpack_time(packed: int):
    micro = packed % _US
    t = packed // _US
    second = t % 60
    t //= 60
    minute = t % 60
    t //= 60
    hour = t % 24
    t //= 24
    day = t % 32
    t //= 32
    month = t % 13
    year = t // 13
    return year, month, day, hour, minute, second, micro


_DT_RE = re.compile(
    r"^\s*(\d{4})[-/](\d{1,2})[-/](\d{1,2})"
    r"(?:[T ](\d{1,2}):(\d{1,2})(?::(\d{1,2})(?:\.(\d{1,6}))?)?)?\s*$"
)


def parse_datetime(s: str) -> int | None:
    """Parse 'YYYY-MM-DD[ HH:MM:SS[.ffffff]]' → packed int64, None if invalid."""
    m = _DT_RE.match(s)
    if not m:
        return None
    year, month, day = int(m.group(1)), int(m.group(2)), int(m.group(3))
    hour = int(m.group(4) or 0)
    minute = int(m.group(5) or 0)
    second = int(m.group(6) or 0)
    frac = m.group(7) or ""
    micro = int(frac.ljust(6, "0")) if frac else 0
    if month > 12 or day > 31 or hour > 23 or minute > 59 or second > 59:
        return None
    return pack_time(year, month, day, hour, minute, second, micro)


def format_time(packed: int, is_date: bool = False, fsp: int = 0) -> str:
    y, mo, d, h, mi, s, us = unpack_time(packed)
    if is_date:
        return f"{y:04d}-{mo:02d}-{d:02d}"
    base = f"{y:04d}-{mo:02d}-{d:02d} {h:02d}:{mi:02d}:{s:02d}"
    if fsp > 0:
        base += "." + f"{us:06d}"[:fsp]
    return base


def number_to_datetime(v: int) -> int | None:
    """MySQL numeric datetime forms: YYYYMMDD or YYYYMMDDHHMMSS
    (ref: types/time.go ParseDatetimeFromNum)."""
    if v <= 0:
        return 0 if v == 0 else None
    s = str(v)
    if len(s) <= 8:
        s = s.zfill(8)
        return parse_datetime(f"{s[:4]}-{s[4:6]}-{s[6:8]}")
    if len(s) <= 14:
        s = s.zfill(14)
        return parse_datetime(f"{s[:4]}-{s[4:6]}-{s[6:8]} {s[8:10]}:{s[10:12]}:{s[12:14]}")
    return None


def time_year(packed: int) -> int:
    return packed // (_US * 60 * 60 * 24 * 32 * 13)


def time_month(packed: int) -> int:
    return (packed // (_US * 60 * 60 * 24 * 32)) % 13


def time_day(packed: int) -> int:
    return (packed // (_US * 60 * 60 * 24)) % 32


def time_hour(packed: int) -> int:
    return (packed // (_US * 60 * 60)) % 24


def time_minute(packed: int) -> int:
    return (packed // (_US * 60)) % 60


def time_second(packed: int) -> int:
    return (packed // _US) % 60


_DUR_RE = re.compile(r"^\s*(-)?(\d+):(\d{1,2})(?::(\d{1,2})(?:\.(\d{1,6}))?)?\s*$")


def parse_duration(s: str) -> int | None:
    """'[-]HH:MM[:SS[.f]]' → signed microseconds; MySQL parses the
    two-part form as hours:minutes (ref: types/duration.go)."""
    m = _DUR_RE.match(s)
    if m is None:
        return None
    neg, h, mi, sec, frac = m.groups()
    mi = int(mi)
    sec = int(sec) if sec is not None else 0
    if mi > 59 or sec > 59:
        return None
    us = ((int(h) * 3600 + mi * 60 + sec) * 1_000_000) + int((frac or "0").ljust(6, "0"))
    return -us if neg else us

"""System variables (ref: sessionctx/variable/sysvar.go — ~230 vars with
scope + validation; this registry carries the subset that drives behavior
here plus the high-traffic MySQL/TiDB knobs, each tagged with whether any
code actually consumes it — SET on an inert knob warns instead of lying).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class SysVar:
    name: str
    default: str
    scope: str = "both"  # both | session | global | none (read-only)
    kind: str = "str"  # bool | int | float | enum | str
    enum: tuple = ()
    lo: int | None = None
    hi: int | None = None
    consumed: bool = False  # True: some code path reads it

    def normalize(self, raw: str) -> str:
        """Validate + canonicalize a SET value (ref: sysvar.go Validation)."""
        s = str(raw).strip()
        if self.kind == "bool":
            up = s.upper()
            if up in ("ON", "1", "TRUE"):
                return "ON"
            if up in ("OFF", "0", "FALSE"):
                return "OFF"
            raise ValueError(f"Variable '{self.name}' can't be set to the value of '{raw}'")
        if self.kind == "int":
            try:
                # int(s) first: int(float(s)) corrupts 64-bit values >2^53
                v = int(s) if not any(c in s for c in ".eE") else int(float(s))
            except ValueError:
                raise ValueError(f"Incorrect argument type to variable '{self.name}'")
            if self.lo is not None:
                v = max(v, self.lo)
            if self.hi is not None:
                v = min(v, self.hi)
            return str(v)
        if self.kind == "float":
            try:
                v = float(s)
            except ValueError:
                raise ValueError(f"Incorrect argument type to variable '{self.name}'")
            # clamp like int vars — the stored/displayed value must match
            # what enforcement actually uses
            if self.lo is not None and v < self.lo:
                return str(float(self.lo))
            if self.hi is not None and v > self.hi:
                return str(float(self.hi))
            return s
        if self.kind == "enum":
            for e in self.enum:
                if s.lower() == e.lower():
                    return e
            raise ValueError(f"Variable '{self.name}' can't be set to the value of '{raw}'")
        return s


SYSVARS: dict[str, SysVar] = {}


def _sv(name, default, scope="both", kind="str", enum=(), lo=None, hi=None, consumed=False):
    SYSVARS[name] = SysVar(name, default, scope, kind, enum, lo, hi, consumed)


# --- engine / executor knobs (consumed) ------------------------------------
_sv("tidb_cop_engine", "auto", kind="enum", enum=("auto", "tpu", "host"), consumed=True)
_sv("tidb_executor_concurrency", "5", kind="int", lo=1, hi=256, consumed=True)
_sv("tidb_distsql_scan_concurrency", "15", kind="int", lo=1, hi=256, consumed=True)
_sv("tidb_enable_cop_result_cache", "ON", kind="bool", consumed=True)
_sv("tidb_mem_quota_query", str(1 << 30), kind="int", lo=0, consumed=True)
_sv("tidb_slow_log_threshold", "300", kind="int", lo=0, consumed=True)
_sv("tidb_allow_mpp", "ON", kind="bool", consumed=True)
_sv("tidb_broadcast_join_threshold_count", "10240", kind="int", lo=0, consumed=True)
_sv("tidb_txn_mode", "optimistic", kind="enum", enum=("optimistic", "pessimistic", ""), consumed=True)
_sv("tidb_retry_limit", "10", kind="int", lo=0, consumed=True)
_sv("autocommit", "ON", kind="bool", consumed=True)
_sv("tidb_opt_prefer_merge_join", "OFF", kind="bool", consumed=True)
_sv("tidb_opt_prefer_index_join", "OFF", kind="bool", consumed=True)
_sv("tidb_enable_auto_analyze", "ON", kind="bool", consumed=True)
_sv("tidb_snapshot", "", consumed=True)
_sv("group_concat_max_len", "1024", kind="int", lo=4, hi=1 << 20, consumed=True)
_sv("sql_select_limit", str(2**64 - 1), kind="int", lo=0, consumed=True)
_sv("max_execution_time", "0", kind="int", lo=0, consumed=True)
_sv("tidb_enable_window_function", "ON", kind="bool", consumed=True)
_sv("tidb_enable_noop_functions", "ON", kind="bool", consumed=True)
_sv("tidb_general_log", "OFF", kind="bool", consumed=True)
_sv("sql_mode", "ONLY_FULL_GROUP_BY,STRICT_TRANS_TABLES", consumed=True)
_sv("time_zone", "SYSTEM", consumed=True)
_sv("tidb_isolation_read_engines", "tpu,host", consumed=True)
_sv("tidb_enable_clustered_index", "ON", kind="bool", consumed=True)
_sv("tidb_window_device_min_rows", str(1 << 15), kind="int", lo=0, consumed=True)
_sv("cte_max_recursion_depth", "1000", kind="int", lo=0, hi=4294967295, consumed=True)
_sv("tidb_ddl_reorg_batch_size", "256", kind="int", lo=32, hi=10240, consumed=True)
_sv("sql_safe_updates", "OFF", kind="bool", consumed=True)
_sv("default_week_format", "0", kind="int", lo=0, hi=7, consumed=True)
_sv("div_precision_increment", "4", kind="int", lo=0, hi=30, consumed=True)
_sv("max_allowed_packet", "67108864", kind="int", lo=1024, hi=1 << 30, consumed=True)
_sv("auto_increment_increment", "1", kind="int", lo=1, hi=65535, consumed=True)
_sv("auto_increment_offset", "1", kind="int", lo=1, hi=65535, consumed=True)
_sv("timestamp", "", consumed=True)  # SET timestamp=N freezes NOW()
_sv("tidb_enable_index_merge", "ON", kind="bool", consumed=True)
_sv("tidb_enable_list_partition", "OFF", kind="bool", consumed=True)
# agg-below-join pushdown rule doesn't exist here (cop partial/final split
# is unconditional, like the reference's cop pushdown) — stays inert
_sv("tidb_opt_agg_push_down", "OFF", kind="bool")
_sv("tidb_opt_join_reorder_threshold", "0", kind="int", lo=0, hi=63, consumed=True)
_sv("tidb_enforce_mpp", "OFF", kind="bool", consumed=True)
_sv("tidb_broadcast_join_threshold_size", str(100 * 1024 * 1024), kind="int", lo=0, consumed=True)
_sv("tidb_redact_log", "OFF", kind="bool", consumed=True)
_sv("tidb_query_log_max_len", "4096", kind="int", lo=-1, consumed=True)
_sv("tidb_stmt_summary_max_sql_length", "4096", kind="int", lo=0, consumed=True)
_sv("tidb_enable_stmt_summary", "ON", kind="bool", consumed=True)
_sv("tidb_enable_slow_log", "ON", kind="bool", consumed=True)
_sv("tidb_stmt_summary_max_stmt_count", "3000", scope="global", kind="int", lo=1, consumed=True)
_sv("tidb_gc_enable", "ON", scope="global", kind="bool", consumed=True)
_sv("tidb_gc_life_time", "10m0s", scope="global", consumed=True)
_sv("tidb_gc_run_interval", "10m0s", scope="global", consumed=True)
_sv("tidb_index_lookup_size", "20000", kind="int", lo=1, consumed=True)
_sv("tidb_index_join_batch_size", "25000", kind="int", lo=1, consumed=True)
_sv("tidb_disable_txn_auto_retry", "ON", kind="bool", consumed=True)
_sv("tidb_multi_statement_mode", "OFF", kind="enum", enum=("OFF", "ON", "WARN"), consumed=True)
_sv("tidb_track_aggregate_memory_usage", "ON", kind="bool", consumed=True)
_sv("tidb_mem_quota_sort", str(32 << 30), scope="session", kind="int", lo=-1, consumed=True)
_sv("tidb_mem_quota_topn", str(32 << 30), scope="session", kind="int", lo=-1, consumed=True)
_sv("tidb_mem_quota_hashjoin", str(32 << 30), scope="session", kind="int", lo=-1, consumed=True)

# --- observability (PR 3: statement tracing + cop-path exec details) -------
# span recording for every statement (TRACE <sql> records regardless);
# traces land in the TIDB_TRACE ring / /debug/trace
_sv("tidb_enable_trace", "OFF", kind="bool", consumed=True)
# per-statement cop backoff sleep budget (session scope; statement scope
# via the SET_VAR optimizer hint) — replaces the fixed COP_BACKOFF_BUDGET_MS
_sv("tidb_backoff_budget_ms", "2000", kind="int", lo=0, hi=600000, consumed=True)
# capacity of the per-store TIDB_TRACE ring; SET GLOBAL resizes it live
# (PR 4 — replaces the fixed 64)
_sv("tidb_trace_ring_capacity", "64", scope="global", kind="int", lo=1, hi=4096,
    consumed=True)
# device timeline profiler (PR 5): real-timestamped engine-boundary and
# launch-lifecycle events into the per-store ring behind /debug/timeline
# and TIDB_TIMELINE. GLOBAL-only: one ring per store, one flag on it
_sv("tidb_enable_timeline", "ON", scope="global", kind="bool", consumed=True)
# capacity of the per-store device timeline ring; SET GLOBAL resizes it
# live keeping the newest events (PR 6 — replaces the fixed 8192, the
# tidb_trace_ring_capacity pattern one ring over)
_sv("tidb_timeline_ring_capacity", "8192", scope="global", kind="int", lo=64,
    hi=1 << 20, consumed=True)

# --- durability fault domain (PR 10) ---------------------------------------
# what recovery does with a damaged WAL (storage/txn.py Storage):
# tolerate-torn-tail (default) truncates a crash-torn tail but REFUSES
# mid-log corruption (valid frames after a bad one = bit rot inside
# committed history); absolute refuses any damage; drop-corrupt is the
# explicit opt-in to skip corrupt frames and salvage the records after
# them. GLOBAL-only and persisted in the data dir's RECOVERY_MODE sidecar
# so the setting survives the very crash it exists for. A corrupt
# SNAPSHOT is refused in every mode.
_sv("tidb_wal_recovery_mode", "tolerate-torn-tail", scope="global", kind="enum",
    enum=("tolerate-torn-tail", "absolute", "drop-corrupt"), consumed=True)

# --- group-commit WAL (PR 13) ----------------------------------------------
# ON (default): concurrent committers batch their WAL fsyncs into one —
# every committer appends, one leader fsyncs for the whole group, the
# followers wait on the flushed sequence (KILL/deadline release the wait
# through the shared interrupt gate; a failed group sync withholds EVERY
# ack in the group and poisons the log per the fsyncgate discipline).
# OFF recovers the exact PR 10 per-commit-fsync behavior live — the A/B
# baseline for tools/bench_serve.py and the incident fallback.
# GLOBAL-only: the durability protocol is a store-wide property.
_sv("tidb_wal_group_commit", "ON", scope="global", kind="bool", consumed=True)

# --- warm-standby shipping + online WAL media failover (PR 14) --------------
# semi-sync replication (MySQL rpl_semi_sync analog over WAL shipping):
# with a WalShipper attached, ON makes every commit ack additionally
# mean durable-on-STANDBY — after local group-commit durability the
# committer waits for the shipper's standby-fsync confirmation (released
# by KILL/deadline through the shared interrupt gate; the commit is then
# indeterminate, never falsely acked). QUORUM (PR 17) upgrades the ack
# to majority-of-N: the commit waits until the MEDIAN per-replica
# durable horizon covers it — ceil(N/2) of the N attached links — and
# raises the typed indeterminate shape (8150) when too many links are
# broken for the quorum to ever form. OFF (default) ships async —
# measured cost: nothing (the wait is never entered). GLOBAL-only like
# tidb_wal_group_commit: the durability protocol is store-wide.
_sv("tidb_wal_semi_sync", "OFF", scope="global", kind="enum",
    enum=("OFF", "ON", "QUORUM"), consumed=True)
# follower-read routing (PR 17; ref: client-go replica-read modes):
# "leader" (default) pins every statement to the primary; "follower" and
# "leader-and-follower" let top-level read-only statements route to an
# in-process replica whose applied-ts lag is within
# tidb_replica_read_max_lag_ms (choose-and-bump placement re-weighted by
# lag; automatic fallback to the primary when every replica is too
# stale). AS OF TIMESTAMP reads route to a replica only once its applied
# watermark REACHED the requested ts — the snapshot is then exactly the
# primary's.
_sv("tidb_replica_read", "leader", kind="enum",
    enum=("leader", "follower", "leader-and-follower"), consumed=True)
# bounded staleness for follower reads: a replica lagging more than this
# many wall-clock ms (primary now vs replica applied-ts physical time)
# is skipped
_sv("tidb_replica_read_max_lag_ms", "5000", kind="int", lo=0, hi=3600000,
    consumed=True)
# cross-node trace propagation (PR 18): ON (default) lets a
# follower-routed statement's replica-side spans (cop.task + its
# device-phase children) adopt into the PRIMARY statement trace tagged
# with the serving replica's name, and stamps the routing decision
# (outcome/reason) as a replica.route span. OFF reverts to untagged
# per-process spans — the A/B knob for the paired overhead gate
# (tools/bench_trace_propagation.py, standing ≤5% rule).
_sv("tidb_enable_trace_propagation", "ON", kind="bool", consumed=True)
# --- partition hardening (PR 19) --------------------------------------------
# link heartbeat cadence: an idle socket ship link pings the standby (a
# bare sync marker, acked like a batch) every this-many ms, so a
# black-holed link — a peer that accepts but never answers — is DETECTED
# instead of silently pinning the quorum until some later commit stalls
# on it. GLOBAL-only: link-health policy is fleet-wide.
_sv("tidb_replica_heartbeat_ms", "1000", scope="global", kind="int",
    lo=10, hi=3600000, consumed=True)
# per-IO deadline on ship-link sockets (replaces the old hard 30s): any
# frame/ack round trip exceeding it breaks the link TYPED
# (reason=timeout, no reconnect ladder — reconnecting to a black hole is
# futile), releasing quorum waiters to count the link against potential
_sv("tidb_replica_heartbeat_timeout_ms", "3000", scope="global", kind="int",
    lo=10, hi=3600000, consumed=True)
# bounded quorum wait: a semi-sync ON/QUORUM commit that cannot confirm
# within this many ms raises the typed indeterminate shape (8150) —
# durable locally, UNCONFIRMED on the fleet — instead of blocking until
# KILL/deadline. 0 disables the bound (the pre-PR-19 behavior).
_sv("tidb_replica_quorum_timeout_ms", "10000", scope="global", kind="int",
    lo=0, hi=3600000, consumed=True)
# comma-separated spare WAL directories: on a WAL IO failure the store
# checkpoints onto the first healthy spare (fresh log, writes resume,
# zero acks lost) instead of degrading read-only forever; failed media
# joins a background re-probe with hysteresis. Empty (default) keeps the
# exact PR 10 fsyncgate degrade. GLOBAL-only: media topology is
# store-wide.
_sv("tidb_wal_spare_dirs", "", scope="global", consumed=True)

# --- mesh-wide cop dispatch (PR 6) -----------------------------------------
# dispatch width over the device mesh: cop tasks place onto the first N
# runner lanes (0 = every device). Serving knob for hosts whose backend
# serializes executions across in-process devices (see BENCH_mesh_pr6's
# overlap_x): width 1 there recovers full cross-session coalescing
_sv("tidb_tpu_cop_lanes", "0", scope="global", kind="int", lo=0, hi=256,
    consumed=True)

# --- compressed, width-narrowed device tiles (PR 7) -------------------------
# ON (default): batches pad to power-of-two row buckets (min 256) and each
# column ships in the cheapest of dense/pack/dict/rle form with decode
# fused into the device program. OFF forces the legacy dense 64Ki-tile
# layout — the A/B baseline and the incident fallback. GLOBAL-only: the
# layout keys the store-wide compile cache and batcher groups
_sv("tidb_tpu_tile_compression", "ON", scope="global", kind="bool", consumed=True)

# --- fused MPP fragment chains (PR 11) --------------------------------------
# ON (default): all-inner fragment chains specialize eligible join levels
# to device-resident direct-address LUT structures (no in-program build
# sort, no exchange — the structure is cached across statements in the
# store's BuildSideCache) and group-on-build-key aggregations to
# build-row-position segments. OFF recovers the pre-fusion sort-join /
# sorted-agg programs exactly — the A/B baseline and the incident
# fallback, mirroring tidb_tpu_tile_compression. GLOBAL-only; the live
# value overrides every session's dispatch (incident semantics).
_sv("tidb_tpu_mpp_fused", "ON", scope="global", kind="bool", consumed=True)

# --- workload-history feedback routing (PR 20) -------------------------------
# ON (default): the `auto` engine routes per (statement digest, row
# bucket) from the store's observed WorkloadProfile (utils/workload.py)
# — first sight explores via the static heuristics, repeats exploit the
# measured per-task walls; the profile also arms at statement
# completion. OFF recovers the pre-feedback static heuristics exactly
# (no profile reads, no feeds, no route metrics) — the A/B baseline and
# the live incident fallback, mirroring tidb_tpu_tile_compression.
# GLOBAL-only: the history is store-wide and the routing contract must
# flip for every session at once.
_sv("tidb_tpu_feedback_route", "ON", scope="global", kind="bool", consumed=True)

# --- Lightning-style bulk ingest (PR 15: br/ingest.BulkIngest) --------------
# ON (default): LOAD DATA and models bulk_load build sorted columnar KV
# artifacts and publish them atomically under ONE WAL ingest record
# (all-visible-or-absent recovery), skipping per-row MVCC prewrite/
# commit. OFF recovers the legacy paths exactly — 2000-row txn batches
# for LOAD DATA, per-batch segment ingest for bulk_load — as the live
# incident fallback. Session-scoped so one load can opt out without
# flipping the store (a LOAD DATA ... WITH bulk_ingest=0 option
# overrides per statement).
_sv("tidb_bulk_ingest", "ON", kind="bool", consumed=True)

# --- delta-main compaction (PR 16: storage/compact.py) ---------------------
# The background worker that folds row-major txn writes + MVCC versions
# at/below the gc safepoint into sorted columnar segments, one per
# durable primary store. GLOBAL-only: compaction is a store property
# (the worker reads these from store.global_vars every tick — SET GLOBAL
# takes effect on the next round, no restart).
_sv("tidb_compact_enable", "ON", scope="global", kind="bool", consumed=True)
# minimum mutable w-CF entries under a table's prefix before a fold is
# worth the decode/build cost (MemKV.count_range per tick is two bisects)
_sv("tidb_compact_delta_threshold", "2048", scope="global", kind="int", lo=1, consumed=True)
# per-plane run-count bound: above it the oldest contiguous commit-ts
# prefix of structurally identical runs merges into one (size-tiered)
_sv("tidb_compact_max_runs", "8", scope="global", kind="int", lo=2, consumed=True)
# background tick cadence, tidb_gc_* go-duration format ('500ms', '5s')
_sv("tidb_compact_interval", "1s", scope="global", consumed=True)

# --- server memory arbitration (PR 4: utils/memory ServerMemTracker) -------
# store-wide hard limit on tracked statement memory; 0 = unlimited.
# GLOBAL-only like the reference: a per-session opt-out would defeat it
_sv("tidb_server_memory_limit", "0", scope="global", kind="int", lo=0, consumed=True)
# soft-limit ratio: above limit*ratio the store degrades (auto→host cop
# routing + tile/device cache eviction) before anything is killed
_sv("tidb_memory_usage_alarm_ratio", "0.8", scope="global", kind="float",
    lo=0, hi=1, consumed=True)

# --- resource control (sched/: admission + RU groups + launch batcher) ------
_sv("tidb_resource_group", "default", consumed=True)
# GLOBAL-only (as in the reference): a plain-SET session toggle would let
# any unprivileged session opt itself out of admission control
_sv("tidb_enable_resource_control", "ON", scope="global", kind="bool", consumed=True)

# --- read-only session state surfaced via SELECT @@x (SET is rejected;
# values are computed live by Session._sysvar_read) ------------------------
for _name in (
    "last_insert_id", "warning_count", "error_count", "tidb_current_ts",
    "tidb_last_txn_info", "tidb_last_query_info", "last_plan_from_cache",
    "last_plan_from_binding", "tidb_config",
):
    _sv(_name, "", scope="none", consumed=True)

# --- accepted, surfaced in SHOW, but nothing reads them here (warn) --------
for _name, _d, _k in (
    ("tidb_enable_chunk_rpc", "ON", "bool"),
    ("tidb_enable_vectorized_expression", "ON", "bool"),
    ("tidb_index_lookup_concurrency", "4", "int"),
    ("tidb_index_lookup_join_concurrency", "4", "int"),
    ("tidb_hash_join_concurrency", "5", "int"),
    ("tidb_window_concurrency", "4", "int"),
    ("tidb_projection_concurrency", "4", "int"),
    ("tidb_hashagg_partial_concurrency", "4", "int"),
    ("tidb_hashagg_final_concurrency", "4", "int"),
    ("tidb_merge_join_concurrency", "1", "int"),
    ("tidb_stream_agg_concurrency", "1", "int"),
    ("tidb_build_stats_concurrency", "4", "int"),
    ("tidb_opt_distinct_agg_push_down", "OFF", "bool"),
    ("tidb_enable_parallel_apply", "OFF", "bool"),
    ("tidb_enable_async_commit", "OFF", "bool"),
    ("tidb_enable_1pc", "OFF", "bool"),
    ("tidb_max_chunk_size", "1024", "int"),
    ("tidb_init_chunk_size", "32", "int"),
    ("tidb_enable_rate_limit_action", "ON", "bool"),
    ("tidb_enable_strict_double_type_check", "ON", "bool"),
    ("tidb_enable_table_partition", "ON", "bool"),
    ("tidb_scatter_region", "OFF", "bool"),
    ("tidb_enable_collect_execution_info", "ON", "bool"),
    ("tidb_enable_telemetry", "ON", "bool"),
    ("tidb_row_format_version", "2", "int"),
    ("tidb_analyze_version", "2", "int"),
    ("tidb_stats_load_sync_wait", "0", "int"),
    ("tidb_ddl_reorg_worker_cnt", "4", "int"),
    ("tidb_ddl_error_count_limit", "512", "int"),
    ("tidb_auto_analyze_ratio", "0.5", "float"),
    ("tidb_auto_analyze_start_time", "00:00 +0000", "str"),
    ("tidb_auto_analyze_end_time", "23:59 +0000", "str"),
    ("tidb_gc_concurrency", "-1", "int"),
    ("tidb_backoff_weight", "2", "int"),
    ("tidb_ddl_slow_threshold", "300", "int"),
    ("tidb_force_priority", "NO_PRIORITY", "str"),
    ("tidb_constraint_check_in_place", "OFF", "bool"),
    ("tidb_batch_insert", "OFF", "bool"),
    ("tidb_batch_delete", "OFF", "bool"),
    ("tidb_dml_batch_size", "0", "int"),
    ("tidb_opt_write_row_id", "OFF", "bool"),
    ("tidb_check_mb4_value_in_utf8", "ON", "bool"),
    ("tidb_opt_insubq_to_join_and_agg", "ON", "bool"),
    ("tidb_opt_correlation_threshold", "0.9", "float"),
    ("tidb_opt_correlation_exp_factor", "1", "int"),
    ("tidb_opt_network_factor", "1", "float"),
    ("tidb_opt_scan_factor", "1.5", "float"),
    ("tidb_opt_seek_factor", "20", "float"),
    ("tidb_opt_memory_factor", "0.001", "float"),
    ("tidb_opt_disk_factor", "1.5", "float"),
    ("tidb_opt_concurrency_factor", "3", "float"),
    ("tidb_enable_noop_variables", "ON", "bool"),
    ("tidb_low_resolution_tso", "OFF", "bool"),
    ("tidb_expensive_query_time_threshold", "60", "int"),
    ("tidb_skip_isolation_level_check", "OFF", "bool"),
    ("tidb_skip_ascii_check", "OFF", "bool"),
    ("tidb_skip_utf8_check", "OFF", "bool"),
    ("foreign_key_checks", "OFF", "bool"),
    ("unique_checks", "ON", "bool"),
    ("sql_auto_is_null", "OFF", "bool"),
    ("big_tables", "OFF", "bool"),
    ("sql_log_bin", "ON", "bool"),
    ("innodb_lock_wait_timeout", "50", "int"),
    ("lock_wait_timeout", "31536000", "int"),
    ("tx_read_only", "OFF", "bool"),
    ("transaction_read_only", "OFF", "bool"),
    ("lc_time_names", "en_US", "str"),
    ("max_sort_length", "1024", "int"),
    ("net_write_timeout", "60", "int"),
    ("net_read_timeout", "30", "int"),
    ("net_buffer_length", "16384", "int"),
    ("query_cache_size", "0", "int"),
    ("query_cache_type", "OFF", "str"),
    ("tmp_table_size", "16777216", "int"),
    ("max_heap_table_size", "16777216", "int"),
    ("thread_cache_size", "9", "int"),
    ("table_open_cache", "2000", "int"),
):
    _sv(_name, _d, kind=_k)

# --- remainder of the reference registry (sysvar.go) — registered with the
# reference's scope/kind/defaults so SET/SHOW behave, inert here (warn) -----
for _name, _d, _k in (
    ("allow_auto_random_explicit_insert", "OFF", "bool"),
    ("ddl_slow_threshold", "300", "int"),
    ("block_encryption_mode", "aes-128-ecb", "str"),
    ("tidb_allow_batch_cop", "1", "int"),
    ("tidb_allow_fallback_to_tikv", "", "str"),
    ("tidb_allow_remove_auto_inc", "OFF", "bool"),
    ("tidb_backoff_lock_fast", "100", "int"),
    ("tidb_batch_commit", "OFF", "bool"),
    ("tidb_capture_plan_baselines", "OFF", "bool"),
    ("tidb_checksum_table_concurrency", "4", "int"),
    ("tidb_ddl_reorg_priority", "PRIORITY_LOW", "str"),
    ("tidb_enable_alter_placement", "OFF", "bool"),
    ("tidb_enable_amend_pessimistic_txn", "OFF", "bool"),
    ("tidb_enable_auto_increment_in_generated", "OFF", "bool"),
    ("tidb_enable_cascades_planner", "OFF", "bool"),
    ("tidb_enable_change_multi_schema", "OFF", "bool"),
    ("tidb_enable_exchange_partition", "OFF", "bool"),
    ("tidb_enable_extended_stats", "OFF", "bool"),
    ("tidb_enable_fast_analyze", "OFF", "bool"),
    ("tidb_enable_global_temporary_table", "OFF", "bool"),
    ("tidb_enable_index_merge_join", "OFF", "bool"),
    ("tidb_enable_local_txn", "OFF", "bool"),
    ("tidb_enable_ordered_result_mode", "OFF", "bool"),
    ("tidb_enable_pipelined_window_function", "ON", "bool"),
    ("tidb_enable_point_get_cache", "OFF", "bool"),
    ("tidb_enable_streaming", "OFF", "bool"),
    ("tidb_enable_top_sql", "OFF", "bool"),
    ("tidb_evolve_plan_baselines", "OFF", "bool"),
    ("tidb_evolve_plan_task_end_time", "23:59 +0000", "str"),
    ("tidb_evolve_plan_task_max_time", "600", "int"),
    ("tidb_evolve_plan_task_start_time", "00:00 +0000", "str"),
    ("tidb_gc_scan_lock_mode", "LEGACY", "str"),
    ("tidb_guarantee_linearizability", "ON", "bool"),
    ("tidb_hash_exchange_with_new_collation", "ON", "bool"),
    ("tidb_index_serial_scan_concurrency", "1", "int"),
    ("tidb_max_delta_schema_count", "1024", "int"),
    ("tidb_mem_quota_apply_cache", str(32 << 20), "int"),
    ("tidb_mem_quota_indexlookupjoin", str(32 << 30), "int"),
    ("tidb_mem_quota_indexlookupreader", str(32 << 30), "int"),
    ("tidb_mem_quota_mergejoin", str(32 << 30), "int"),
    ("tidb_metric_query_range_duration", "60", "int"),
    ("tidb_metric_query_step", "60", "int"),
    ("tidb_mpp_store_fail_ttl", "60s", "str"),
    ("tidb_opt_broadcast_cartesian_join", "1", "int"),
    ("tidb_opt_broadcast_join", "OFF", "bool"),
    ("tidb_opt_copcpu_factor", "3.0", "float"),
    ("tidb_opt_cpu_factor", "3.0", "float"),
    ("tidb_opt_desc_factor", "3.0", "float"),
    ("tidb_opt_enable_correlation_adjustment", "ON", "bool"),
    ("tidb_opt_mpp_outer_join_fixed_build_side", "OFF", "bool"),
    ("tidb_opt_prefer_range_scan", "OFF", "bool"),
    ("tidb_opt_tiflash_concurrency_factor", "24.0", "float"),
    ("tidb_optimizer_selectivity_level", "0", "int"),
    ("tidb_partition_prune_mode", "static", "str"),
    ("tidb_pprof_sql_cpu", "0", "int"),
    ("tidb_record_plan_in_slow_log", "ON", "bool"),
    # tidb_replica_read lives in the consumed block above (PR 17)
    ("tidb_restricted_read_only", "OFF", "bool"),
    ("tidb_shard_allocate_step", str(2**63 - 1), "int"),
    ("tidb_slow_log_masking", "OFF", "bool"),
    ("tidb_slow_query_file", "", "str"),
    ("tidb_stmt_summary_history_size", "24", "int"),
    ("tidb_stmt_summary_internal_query", "OFF", "bool"),
    ("tidb_stmt_summary_refresh_interval", "1800", "int"),
    ("tidb_store_limit", "0", "int"),
    ("tidb_streamagg_concurrency", "1", "int"),
    ("tidb_top_sql_agent_address", "", "str"),
    ("tidb_top_sql_max_collect", "10000", "int"),
    ("tidb_top_sql_max_statement_count", "200", "int"),
    ("tidb_top_sql_precision_seconds", "1", "int"),
    ("tidb_top_sql_report_interval_seconds", "60", "int"),
    ("tidb_use_plan_baselines", "ON", "bool"),
    ("tidb_wait_split_region_finish", "ON", "bool"),
    ("tidb_wait_split_region_timeout", "300", "int"),
    ("tx_read_ts", "", "str"),
    ("txn_scope", "global", "str"),
    ("windowing_use_high_precision", "ON", "bool"),
    ("max_connections", "151", "int"),
    ("max_prepared_stmt_count", "-1", "int"),
    ("skip_name_resolve", "OFF", "bool"),
):
    _sv(_name, _d, kind=_k)

# --- connection/session plumbing clients legitimately SET ------------------
for _name, _d in (
    ("wait_timeout", "28800"), ("interactive_timeout", "28800"),
    ("character_set_server", "utf8mb4"), ("collation_server", "utf8mb4_bin"),
    ("character_set_client", "utf8mb4"), ("character_set_results", "utf8mb4"),
    ("character_set_connection", "utf8mb4"), ("collation_connection", "utf8mb4_bin"),
    ("character_set_database", "utf8mb4"), ("collation_database", "utf8mb4_bin"),
    ("tx_isolation", "REPEATABLE-READ"), ("transaction_isolation", "REPEATABLE-READ"),
    ("default_storage_engine", "InnoDB"), ("init_connect", ""),
):
    _sv(_name, _d)

# --- server identity (read-only: SET is rejected, ref ErrIncorrectScope) ---
for _name, _d in (
    ("ssl_ca", ""), ("ssl_cert", ""), ("ssl_key", ""), ("log_bin", "OFF"),
    ("plugin_dir", ""), ("plugin_load", ""),
    ("default_authentication_plugin", "mysql_native_password"),
    ("tidb_enable_enhanced_security", "OFF"),
    ("version_comment", "tidb-tpu"), ("port", "4000"), ("socket", ""),
    ("datadir", ""), ("version", "8.0.11-tidb-tpu"), ("hostname", "localhost"),
    ("license", "Apache License 2.0"), ("system_time_zone", "UTC"),
    ("lower_case_table_names", "2"), ("have_openssl", "DISABLED"),
    ("have_ssl", "DISABLED"), ("performance_schema", "OFF"),
):
    _sv(_name, _d, scope="none")

DEFAULT_VARS = {v.name: v.default for v in SYSVARS.values()}


def set_var(name: str, value: str, warnings: list | None = None,
            scope: str | None = None) -> str:
    """Validate one SET assignment → canonical stored value. Unknown
    variables raise (ref: ErrUnknownSystemVariable); known-but-inert ones
    append a warning so silent no-ops are visible. `scope` is the
    assignment's requested scope ("global" for SET GLOBAL) — global-only
    variables reject plain SET (MySQL ER_GLOBAL_VARIABLE), so store-wide
    state can never be mutated below the SET GLOBAL privilege check."""
    from ..utils import sem

    sem.check_variable(name)
    sv = SYSVARS.get(name)
    if sv is None:
        raise ValueError(f"Unknown system variable '{name}'")
    if sv.scope == "none":
        raise ValueError(f"Variable '{name}' is a read only variable")
    if sv.scope == "global" and scope != "global":
        raise ValueError(
            f"Variable '{name}' is a GLOBAL variable and should be set with SET GLOBAL"
        )
    if sv.scope == "session" and scope == "global":
        raise ValueError(f"Variable '{name}' is a SESSION variable")
    out = sv.normalize(value)
    if not sv.consumed and warnings is not None:
        warnings.append(
            f"variable '{name}' is accepted for compatibility but has no effect in this engine"
        )
    return out

"""System variables (ref: sessionctx/variable/sysvar.go — ~230 vars; the
subset that drives behavior here, with the rest present as inert knobs so
SHOW VARIABLES / SET round-trip like the reference)."""

DEFAULT_VARS = {
    # engine selection for pushed-down DAGs: tpu | host | auto
    "tidb_cop_engine": "auto",
    "tidb_executor_concurrency": "5",
    "tidb_distsql_scan_concurrency": "15",
    # per-task cop result cache (ref: coprocessor_cache.go; see CopResultCache)
    "tidb_enable_cop_result_cache": "ON",
    "tidb_mem_quota_query": str(1 << 30),
    "tidb_slow_log_threshold": "300",
    "tidb_enable_chunk_rpc": "ON",
    "tidb_allow_mpp": "ON",
    "tidb_broadcast_join_threshold_count": "10240",
    "tidb_isolation_read_engines": "tpu,host",
    "tidb_txn_mode": "optimistic",
    "tidb_retry_limit": "10",
    "autocommit": "ON",
    "sql_mode": "ONLY_FULL_GROUP_BY,STRICT_TRANS_TABLES",
    "max_execution_time": "0",
    "tidb_enable_vectorized_expression": "ON",
    "tidb_index_lookup_concurrency": "4",
    "tidb_hash_join_concurrency": "5",
    "tidb_build_stats_concurrency": "4",
    "tidb_opt_agg_push_down": "ON",
    "tidb_opt_prefer_merge_join": "OFF",
    "tidb_opt_prefer_index_join": "OFF",
    "tidb_enable_clustered_index": "ON",
    "tidb_snapshot": "",
    "time_zone": "SYSTEM",
    "wait_timeout": "28800",
    "interactive_timeout": "28800",
    "max_allowed_packet": "67108864",
    "version_comment": "tidb-tpu",
    "port": "4000",
    "socket": "",
    "datadir": "",
    "character_set_server": "utf8mb4",
    "collation_server": "utf8mb4_bin",
    "tx_isolation": "REPEATABLE-READ",
    "transaction_isolation": "REPEATABLE-READ",
}

"""Session — SQL execution driver (ref: session/session.go ExecuteStmt:1618,
LazyTxn txn.go:50; compact redesign).

Owns: current database, session vars, the lazy transaction, and the
catalog cache. Routes statements: DDL → meta transactions with schema
version bump; DML → executor over the txn membuffer; SELECT → plan,
optimize, execute via the cop client (TPU or host engine).
"""

from __future__ import annotations

import logging
import time

import numpy as np

log = logging.getLogger(__name__)

from ..catalog.meta import Meta
from ..catalog.schema import ColumnInfo, DBInfo, IndexInfo, InfoSchema, TableInfo
from ..chunk.chunk import Chunk, Column
from ..codec import tablecodec
from ..copr.client import CopClient
from ..errors import (
    DuplicateEntry,
    ResourceGroupNotExists,
    RetryableError,
    TableExists,
    TiDBError,
    UnknownColumn,
    UnknownDatabase,
    UnknownTable,
    WriteConflict,
)
from ..executor import ExecContext, build_executor, drain
from ..expr.expression import Column as ECol, Constant
from ..mysqltypes.datum import Datum
from ..mysqltypes.field_type import NOT_NULL_FLAG, PRI_KEY_FLAG, AUTO_INCREMENT_FLAG, FieldType, TypeCode, ft_longlong, ft_varchar, parse_type_name
from ..mysqltypes.coretime import parse_datetime
from ..parser import ast, parse_one
from ..planner.builder import NameScope, PlanBuilder, lit_to_constant
from ..planner.ranger import prefix_next
from ..planner.optimizer import optimize
from ..planner.plans import DataSource, Selection
from ..storage.txn import Storage, TOMBSTONE, Txn
from ..table.table import Table
from .vars import DEFAULT_VARS


class ResultSet:
    def __init__(self, names: list[str], chunk: Chunk, affected: int = 0, last_insert_id: int = 0):
        self.names = names
        self.chunk = chunk
        self.affected = affected
        self.last_insert_id = last_insert_id

    def rows(self) -> list[tuple]:
        return self.chunk.to_pylist() if self.chunk is not None else []

    def scalar(self):
        r = self.rows()
        return r[0][0] if r else None

    @classmethod
    def message_row(cls, names: list[str], values: list[str]) -> "ResultSet":
        from ..mysqltypes.field_type import ft_varchar

        chk = Chunk.empty([ft_varchar(64) for _ in names], 1)
        for c, v in enumerate(values):
            chk.columns[c].set_datum(0, Datum.s(v))
        return cls(names, chk)


class Session:
    def __init__(self, storage: Storage | None = None, cop_client: CopClient | None = None):
        self.store = storage or Storage()
        self.cop = cop_client or CopClient(self.store)
        self.current_db = "test"
        # session vars initialize from defaults overlaid with the store's
        # SET GLOBAL values (MySQL: session scope copies global at connect)
        self.vars = dict(DEFAULT_VARS)
        self.vars.update(getattr(self.store, "global_vars", None) or {})
        self.txn: Txn | None = None
        self.in_explicit_txn = False
        self._is_cache: InfoSchema | None = None
        self.warnings: list[str] = []
        self._prev_warnings: list[str] = []  # @@warning_count (prev stmt)
        self._prev_error = False  # @@error_count
        self._last_txn_info = ""  # @@tidb_last_txn_info (JSON)
        self._last_query_info = ""  # @@tidb_last_query_info (JSON)
        self._last_plan_from_cache = False
        self._last_plan_from_binding = False
        self._prev_plan_from_cache = False
        self._prev_plan_from_binding = False
        self.last_insert_id = 0
        # stats deltas buffered per-txn, flushed only on commit
        # (ref: statistics/handle SessionStatsCollector)
        self._pending_deltas: dict[int, list[int]] = {}
        # prepared statements + plan cache (ref: session.go:2042
        # ExecutePreparedStmt, planner/core/cache.go:128)
        # name → (source sql, parsed ast, param count)
        self.prepared: dict[str, tuple[str, object, int]] = {}
        self.user_vars: dict[str, Constant] = {}
        self._exec_params: list | None = None
        # prepared-plan cache identity (PR 14): the prepared statement's
        # stored AST object is stable across executes, so it anchors the
        # statement-id plan-cache key; `_active_prep` marks the AST the
        # CURRENT execute runs (nested/rewritten sub-selects never match)
        self._active_prep = None
        self._prep_seq = 0
        from collections import OrderedDict

        self._plan_cache: OrderedDict = OrderedDict()
        self.plan_cache_hits = 0
        # sql text → parsed AST (single-statement only; see execute())
        self._ast_cache: OrderedDict = OrderedDict()
        # sequence batch cache + LASTVAL memory (ref: meta/autoid
        # SequenceAllocator; entries [cur, end, inc, store generation])
        self._seq_cache: dict = {}
        # follower reads (PR 17): per-replica CopClient cache keyed by
        # id(replica store) — each replica carries its own tile/result
        # caches, exactly like the primary's shared client
        self._replica_cops: dict = {}
        self._seq_last: dict = {}
        # session-local temporary tables: (db, name) → TableInfo
        self._temp_tables: dict = {}
        self._temp_epoch = 0
        # authenticated identity (set by the wire handshake; in-process
        # sessions run as root, the bootstrap superuser)
        self.user = "root"
        self._session_bindings: dict[str, list] = {}  # digest → hints
        self._tracer = None  # per-statement StatementTrace (utils/tracing)
        self._stmt_digest = None  # per-statement digest (workload history key)
        # txn-level trace linkage: minted at BEGIN, stamped on every
        # statement trace until COMMIT/ROLLBACK (TIDB_TRACE TXN_TRACE_ID)
        self._txn_trace_id: str | None = None
        self._stmt_vars: dict[str, str] = {}  # SET_VAR hint statement scope
        import itertools as _it

        self.conn_id = next(Session._conn_counter)
        self._in_bootstrap = False
        # info published to builtin kernels (USER(), FOUND_ROWS(), ...)
        # via the expr.sessioninfo contextvar (ref: builtin_info.go)
        self._info = {
            "user": self.user, "conn_id": self.conn_id, "db": self.current_db,
            "found_rows": 0, "row_count": -1, "last_insert_id": 0,
            "vars": self.vars,  # live dict: builtins read session knobs
        }
        self._bootstrap()

    _conn_counter = __import__("itertools").count(1)

    PLAN_CACHE_SIZE = 128
    AST_CACHE_SIZE = 256
    AST_CACHE_MAX_SQL = 4096  # don't pin multi-MB INSERT batches

    @property
    def mem_tracker(self):
        """Session-level memory tracker: the middle layer of the
        statement → session → server tree (utils/memory). No quota of
        its own — it aggregates, the server root arbitrates."""
        if getattr(self, "_mem_sess_tracker", None) is None:
            from ..utils.memory import MemTracker as _MT

            self._mem_sess_tracker = _MT(
                0, f"session#{self.conn_id}", parent=self.store.mem
            )
        return self._mem_sess_tracker

    # ------------------------------------------------------------- bootstrap

    def _bootstrap(self):
        """Create system + default schemas and the privilege tables with a
        root superuser (ref: session/bootstrap.go — mysql.user et al)."""
        txn = self.store.begin()
        m = Meta(txn)
        if m.db("test") is None:
            for db in ("mysql", "information_schema", "performance_schema", "test"):
                m.put_db(DBInfo(db))
            m.bump_schema_version()
            txn.commit()
        else:
            txn.rollback()
        self._ensure_priv_tables()

    def _ensure_priv_tables(self):
        """Idempotent bootstrap upgrade (ref: bootstrap.go upgrade():643):
        stores created before the privilege subsystem gain mysql.user/db
        with the root superuser on first open."""
        try:
            self.infoschema().table("mysql", "user")
            return
        except UnknownTable:
            pass
        self._in_bootstrap = True
        try:
            self.execute(
                "CREATE TABLE mysql.user (host VARCHAR(64), user VARCHAR(32), "
                "auth_string VARCHAR(64), privs VARCHAR(512))"
            )
            self.execute(
                "CREATE TABLE mysql.db (host VARCHAR(64), user VARCHAR(32), "
                "db VARCHAR(64), privs VARCHAR(512))"
            )
            self.execute("INSERT INTO mysql.user VALUES ('%', 'root', '', 'ALL')")
        finally:
            self._in_bootstrap = False
        try:
            self.infoschema().table("mysql", "bind_info")
        except UnknownTable:
            self._in_bootstrap = True
            try:
                self.execute(
                    "CREATE TABLE mysql.bind_info (original_digest VARCHAR(32), "
                    "original_sql VARCHAR(1024), bind_sql VARCHAR(1024), status VARCHAR(16))"
                )
            finally:
                self._in_bootstrap = False
        try:
            self.infoschema().table("mysql", "tables_priv")
        except UnknownTable:
            self._in_bootstrap = True
            try:
                self.execute(
                    "CREATE TABLE mysql.tables_priv (host VARCHAR(64), user VARCHAR(32), "
                    "db VARCHAR(64), table_name VARCHAR(64), privs VARCHAR(512))"
                )
                self.execute(
                    "CREATE TABLE mysql.global_grants (user VARCHAR(32), priv VARCHAR(64))"
                )
            finally:
                self._in_bootstrap = False

    def _sql_internal(self, sql: str) -> list[tuple]:
        """Run SQL as the internal superuser (privilege checks suspended —
        the sysSessionPool analog, domain.go). System-table reads pin the
        host engine: compiling device programs for tiny mysql.* scans
        would cost seconds of jit for microseconds of work."""
        prev = self._in_bootstrap
        prev_engine = self.vars.get("tidb_cop_engine")
        self._in_bootstrap = True
        self.vars["tidb_cop_engine"] = "host"
        try:
            return self.execute(sql).rows()
        finally:
            self._in_bootstrap = prev
            self.vars["tidb_cop_engine"] = prev_engine

    # ------------------------------------------------------------- infoschema

    def infoschema(self) -> InfoSchema:
        txn = self.store.begin()
        m = Meta(txn)
        ver = m.schema_version()
        key = (ver, self._temp_epoch)
        if self._is_cache is not None and getattr(self._is_cache, "_cache_key", None) == key:
            txn.rollback()
            return self._is_cache
        dbs = {d.name: d for d in m.list_dbs()}
        tables = {t.id: t for t in m.list_tables()}
        views = {(v["db"], v["name"]): v for v in m.list_views()}
        txn.rollback()
        if self._temp_tables:
            # temp tables merge LAST so the constructor's insertion-order
            # _by_name loop shadows same-named permanent tables
            tables = {**tables, **{t.id: t for t in self._temp_tables.values()}}
        self._is_cache = InfoSchema(ver, dbs, tables, views)
        self._is_cache._cache_key = key
        return self._is_cache

    # ------------------------------------------------------------------- txn

    def _txn_mode_pessimistic(self, stmt_mode: str = "") -> bool:
        mode = stmt_mode or self.vars.get("tidb_txn_mode", "optimistic")
        return mode == "pessimistic"

    def _active_txn(self) -> Txn:
        if self.txn is None:
            self.txn = self.store.begin(pessimistic=self._txn_mode_pessimistic())
        return self.txn

    def _note_delta(self, table_id: int, changed: int, delta_rows: int) -> None:
        d = self._pending_deltas.setdefault(table_id, [0, 0])
        d[0] += changed
        d[1] += delta_rows

    def _flush_deltas(self) -> None:
        for tid, (m, d) in self._pending_deltas.items():
            self.store.stats.report_delta(tid, m, d)
        self._pending_deltas.clear()

    def _txn_committed(self, txn=None) -> None:
        """Post-commit hooks: flush stats deltas, auto-analyze trigger check
        (ref: domain autoAnalyzeWorker — ratio policy runs at commit
        boundaries, not a bg loop)."""
        if txn is not None:
            # @@tidb_last_txn_info (ref: sessionctx TxnInfo JSON shape)
            self._last_txn_info = '{"start_ts":%d,"commit_ts":%d}' % (
                txn.start_ts, getattr(txn, "commit_ts", 0)
            )
        self._flush_deltas()
        if self.vars.get("tidb_enable_auto_analyze", "ON") == "ON":
            self.store.stats.auto_analyze(self)

    def _finish_stmt(self):
        """Autocommit unless inside an explicit transaction."""
        if self.txn is not None and not self.in_explicit_txn:
            from ..utils import metrics as M

            t = self.txn
            t.commit()
            self.txn = None
            # session-level count: USER transaction outcomes only — the
            # storage layer also opens internal meta/infoschema txns,
            # which would swamp the series (analyzer registry pass
            # surfaced the dead metric; review placed it here)
            M.TXN_TOTAL.inc(result="commit")
            self._txn_committed(t)

    def _abort_stmt(self):
        if self.txn is not None and not self.in_explicit_txn:
            from ..utils import metrics as M

            self.txn.rollback()
            self.txn = None
            M.TXN_TOTAL.inc(result="rollback")
            self._pending_deltas.clear()

    def read_ts(self) -> int:
        if self.txn is not None:
            return self.txn.start_ts
        snap = self.vars.get("tidb_snapshot", "")
        if snap:
            # historic read at the snapshot's wall time (ref:
            # sessionctx/variable tidb_snapshot + MVCC read path)
            from ..mysqltypes.coretime import parse_datetime, unpack_time

            p = parse_datetime(str(snap))
            if p is None:
                raise TiDBError(f"invalid tidb_snapshot value {snap!r}")
            y, mo, d, h, mi, s, us = unpack_time(p)
            # local wall time → epoch; mktime with isdst=-1 resolves the
            # zone's actual DST state at that date (not just whether the
            # zone defines DST)
            ms = int(time.mktime((y, mo, d, h, mi, s, 0, 0, -1)) * 1000 + us // 1000)
            return ms << 18
        return self.store.tso.next()

    def _as_of_read_ts(self, node) -> int:
        """`AS OF TIMESTAMP expr` → read-ts (ref: planner staleread
        CalculateAsOfTsExpr): the column-free expr evaluates to a datetime
        (literal string or NOW() arithmetic); its wall time becomes the
        TSO physical component, same mapping as tidb_snapshot."""
        from ..mysqltypes.coretime import parse_datetime, unpack_time
        from ..mysqltypes.datum import K_TIME

        d = self._eval_const_expr(node).value
        if d.kind == K_TIME:
            packed = d.val
        else:
            packed = parse_datetime(str(d.val)) if d.val is not None else None
        if packed is None:
            raise TiDBError(f"invalid AS OF TIMESTAMP value {d.val!r}")
        y, mo, day, h, mi, s, us = unpack_time(packed)
        ms = int(time.mktime((y, mo, day, h, mi, s, 0, 0, -1)) * 1000 + us // 1000)
        return ms << 18

    def _replica_cop(self, store):
        """CopClient for a read replica, cached for the session (tile and
        result caches stay warm across statements)."""
        c = self._replica_cops.get(id(store))
        if c is None or c.storage is not store:
            c = CopClient(store)
            self._replica_cops[id(store)] = c
        return c

    def _note_route(self, decision: dict) -> bool:
        """Stamp one follower-routing decision onto the statement: the
        serving replica's name feeds the slow-log REPLICA column and the
        EXPLAIN ANALYZE `replica:` line, and (when span recording is on)
        the outcome/reason pair lands in the trace so every routing
        decision is explainable per statement. Returns whether replica
        span propagation is enabled (tidb_enable_trace_propagation)."""
        prop = self.vars.get("tidb_enable_trace_propagation", "ON") == "ON"
        self._route_replica = decision.get("replica") or None
        tracer = self._tracer
        if tracer is not None and prop:
            tracer.closed_span(
                "replica.route", 0.0,
                outcome=decision.get("outcome", ""),
                reason=decision.get("reason", ""),
                replica=decision.get("replica", "") or "-",
                lag_ms=decision.get("lag_ms", 0.0),
            )
        return prop

    # ---------------------------------------------------------------- execute

    def execute(self, sql: str) -> ResultSet:
        # parse cache: a warmed point workload re-sends identical text,
        # and nothing in the execution path mutates a parsed AST (the
        # prepared-statement path has always re-executed stored ASTs) —
        # so the second arrival of the same single-statement text skips
        # the parser entirely (ref: the non-prepared plan-cache direction
        # of the reference, applied at the parse layer)
        cached = self._ast_cache.get(sql)
        if cached is not None:
            self._ast_cache.move_to_end(sql)
            return self._execute_parsed(cached, sql)
        from ..parser.parser import parse

        stmts = parse(sql)
        if len(stmts) == 1 and len(sql) <= self.AST_CACHE_MAX_SQL:
            self._ast_cache[sql] = stmts[0]
            while len(self._ast_cache) > self.AST_CACHE_SIZE:
                self._ast_cache.popitem(last=False)
        if len(stmts) != 1:
            # multi-statement text: gated like the reference (session.go
            # ParseWithParams + tidb_multi_statement_mode; default OFF
            # rejects to keep the injection surface closed)
            mode = self.vars.get("tidb_multi_statement_mode", "OFF")
            if not stmts:
                raise TiDBError("empty statement")
            if mode == "OFF":
                raise TiDBError(
                    "client has multi-statement capability disabled; "
                    "set tidb_multi_statement_mode=ON to enable"
                )
            rs = ResultSet([], None)
            for one in stmts:
                # sql=None: sub-statements share one source string, which
                # must not collide in the plan cache / digest surfaces
                rs = self._execute_parsed(one, None)
            if mode == "WARN":
                self.warnings.append("multi-statement execution is deprecated")
            return rs
        return self._execute_parsed(stmts[0], sql)

    def _execute_parsed(self, stmt, sql: str | None) -> ResultSet:
        # sql=None (multi-statement sub-stmt): no per-statement source text,
        # so the plan cache / binding digests are bypassed; logs get a tag
        log_sql = sql if sql is not None else f"<multi-statement {type(stmt).__name__}>"
        # diagnostics area: each statement starts fresh; the previous
        # statement's warnings stay readable via @@warning_count and SHOW
        # WARNINGS (which skips the reset, like MySQL's diagnostics rules)
        is_diag = isinstance(stmt, ast.Show) and getattr(stmt, "kind", "") in ("warnings", "errors")
        if not is_diag:
            self._prev_warnings = self.warnings
            self.warnings = []
            # @@last_plan_from_cache/_binding describe the PREVIOUS statement;
            # snapshot before this statement's own planning overwrites them
            self._prev_plan_from_cache = self._last_plan_from_cache
            self._prev_plan_from_binding = self._last_plan_from_binding
            self._last_plan_from_cache = False
            self._last_plan_from_binding = False
        # statement-level savepoint: a failed statement inside an explicit
        # txn must not keep its partial writes (ref: session StmtRollback)
        saved = None
        if self.txn is not None:
            saved = (dict(self.txn.membuf), set(self.txn._locked_keys))
        from ..executor.executors import _ACTIVE_SESSION, _ACTIVE_TRACKER
        from ..utils.memory import MemTracker
        from ..utils import metrics as M

        if getattr(self, "_killed", False):
            self._killed = False
            self._kill_reason = None
            from ..errors import QueryInterrupted

            raise QueryInterrupted("Query execution was interrupted")
        quota = int(self.vars.get("tidb_mem_quota_query", "0") or 0)
        # statement tracker: leaf of the statement → session → server
        # tree (utils/memory) — always attached, even quota-less, so the
        # server arbiter can see (and kill) the top consumer
        tracker = MemTracker(quota, f"conn#{self.conn_id}", parent=self.mem_tracker,
                             session=self)
        tracker.sql = log_sql[:256]
        self.store.mem.attach_statement(tracker)
        token = _ACTIVE_TRACKER.set(tracker)
        stok = _ACTIVE_SESSION.set(self)
        if not self._in_bootstrap:
            import weakref

            self.store.register_process(self.conn_id, {
                "user": self.user,
                "db": self.current_db,
                "sql": log_sql[:256],
                "start": time.time(),
                "session": weakref.ref(self),
            })
        from ..expr import sessioninfo as _si

        self._info.update(user=self.user, conn_id=self.conn_id, db=self.current_db)
        itok = _si.CURRENT.set(self._info)
        met = int(self.vars.get("max_execution_time", "0") or 0)
        self._deadline = (time.monotonic() + met / 1000.0) if met > 0 else None
        # per-statement trace: counters (exec details for the slow log /
        # STATEMENTS_SUMMARY) always; spans only under tidb_enable_trace
        # or TRACE <sql> (near-zero cost otherwise)
        prev_tracer = self._tracer
        tracer = None
        prev_stmt_vars = self._stmt_vars
        self._stmt_vars = {}
        prev_runaway = getattr(self, "_runaway", None)
        self._runaway = None
        prev_route = getattr(self, "_route_replica", None)
        self._route_replica = None  # serving replica (slow-log REPLICA col)
        prev_digest = getattr(self, "_stmt_digest", None)
        self._stmt_digest = None  # cop client keys workload history by this
        if not self._in_bootstrap:
            from ..utils.stmtstats import sql_digest
            from ..utils.tracing import StatementTrace

            # statement digest (normalized-SQL hash, lru-cached): the
            # workload-history plane keys per-statement profiles by it,
            # and the cop client stamps it into SchedCtx for routing
            self._stmt_digest = sql_digest(log_sql)
            tracer = StatementTrace(
                sql=log_sql, session_id=self.conn_id,
                recording=self.vars.get("tidb_enable_trace", "OFF") == "ON",
            )
            # txn-level trace linking: the ast.Begin handler mints the id
            # once the txn actually starts (a failed BEGIN must not leave
            # a phantom id on later autocommit statements) and stamps it
            # onto this tracer; every statement inside the explicit txn
            # (COMMIT/ROLLBACK included — they are part of it) carries it
            # until the txn-control handler clears
            tracer.txn_trace_id = self._txn_trace_id
            self._tracer = tracer
            # runaway watchdog: a checker exists only when the bound
            # group carries a QUERY_LIMIT or the watch list is armed
            # (checker_for's fast exit IS the idle-watchdog overhead)
            ctl = self.store.sched
            self._runaway = ctl.runaway.checker_for(
                self, ctl.groups.get(self.vars.get("tidb_resource_group", "default")),
                log_sql, tracer,
            )
        if self.vars.get("tidb_general_log", "OFF") == "ON" and not self._in_bootstrap:
            gl = log_sql
            if self.vars.get("tidb_redact_log", "OFF") == "ON":
                from ..utils.stmtstats import normalize_sql

                gl = normalize_sql(gl)
            maxlen = int(self.vars.get("tidb_query_log_max_len", "4096"))
            if maxlen >= 0:
                gl = gl[:maxlen]
            log.info("GENERAL_LOG conn=%s user=%s db=%s sql=%s", self.conn_id, self.user, self.current_db, gl)
        t0 = time.perf_counter()
        t0_ns = time.perf_counter_ns()  # timeline clock (one monotonic source)
        c0 = time.thread_time()  # Top-SQL CPU attribution by digest
        ok = True
        try:
            retries = 0
            while True:
                try:
                    rs = self._execute_stmt(stmt, sql=sql)
                    if isinstance(stmt, (ast.Select, ast.SetOpSelect,
                                         ast.Insert, ast.Update, ast.Delete)):
                        # LAST verdict poll at the success boundary: a
                        # kill (user KILL / OOM arbiter / runaway)
                        # landing after drain()'s final gate — during
                        # result assembly — must fail THIS statement,
                        # before the autocommit below; tracker.detach()
                        # in the finally cancels unobserved oom flags
                        # (no next-statement spillover), so this is the
                        # verdict's last chance to be observed. Only the
                        # query/DML shapes poll: their work is still
                        # abortable here (autocommit happens below, an
                        # explicit txn restores the statement savepoint),
                        # while txn control and DDL/admin passed their
                        # durability point INSIDE _execute_stmt — a
                        # post-commit error would misreport a durable
                        # change (COMMIT, CREATE INDEX, ...) as failed.
                        from ..sched.scheduler import raise_if_interrupted

                        raise_if_interrupted(self, getattr(self, "_deadline", None))
                    start_ts = self.txn.start_ts if self.txn is not None else 0
                    self._finish_stmt()
                    break
                except WriteConflict:
                    # optimistic autocommit auto-retry (ref: session.go
                    # retryable commit under tidb_disable_txn_auto_retry=OFF
                    # bounded by tidb_retry_limit)
                    can_retry = (
                        not self.in_explicit_txn
                        and isinstance(stmt, (ast.Insert, ast.Update, ast.Delete))
                        and self.vars.get("tidb_disable_txn_auto_retry", "ON") == "OFF"
                        and retries < int(self.vars.get("tidb_retry_limit", "10"))
                    )
                    if not can_retry:
                        raise
                    retries += 1
                    if self.txn is not None:
                        try:
                            self.txn.rollback()
                        except Exception:  # noqa: BLE001
                            pass
                        self.txn = None
                    self._pending_deltas.clear()
            if not self._in_bootstrap:
                self._last_query_info = (
                    '{"start_ts":%d,"ru_consumption":0}' % start_ts
                )
            if rs.chunk is not None and rs.names:
                self._info["found_rows"] = rs.chunk.num_rows
                self._info["row_count"] = -1
            else:
                self._info["row_count"] = rs.affected
            self._info["last_insert_id"] = self.last_insert_id
            return rs
        except Exception:
            ok = False
            if saved is not None and self.txn is not None and self.in_explicit_txn:
                self.txn.membuf, self.txn._locked_keys = saved
            self._abort_stmt()
            raise
        finally:
            if not is_diag:
                self._prev_error = not ok
            # unwind the tracker tree: success, KILL and BackoffExhausted
            # all pass here — whatever the statement still holds returns
            # to the session + server trackers (never leaks upward)
            tracker.detach()
            _ACTIVE_TRACKER.reset(token)
            _ACTIVE_SESSION.reset(stok)
            _si.CURRENT.reset(itok)
            dur = time.perf_counter() - t0
            cpu = time.thread_time() - c0
            # restore, not clear: internal statements can nest (ANALYZE,
            # bootstrap upgrades) under an outer statement's hint scope
            self._tracer = prev_tracer
            self._stmt_vars = prev_stmt_vars
            self._runaway = prev_runaway
            route_replica = getattr(self, "_route_replica", None)
            self._route_replica = prev_route
            stmt_digest = getattr(self, "_stmt_digest", None)
            self._stmt_digest = prev_digest
            if not self._in_bootstrap:
                self.store.clear_process(self.conn_id)
                self.store.plugins.fire("on_query", self.user, self.current_db, sql, ok, dur)
                group = self.vars.get("tidb_resource_group", "default") or "default"
                M.QUERY_TOTAL.inc(type=type(stmt).__name__, result="OK" if ok else "Error")
                M.QUERY_DURATION.observe(dur, resource_group=group)
                tl = self.store.timeline
                if tl.enabled and tracer is not None:
                    from ..utils.timeline import PID_GROUPS, group_lane

                    # statement wall on the resource-group lane (one track
                    # per group+thread: concurrent sessions in one group
                    # must not emit partially-overlapping complete events
                    # on a single tid)
                    tl.record(
                        "statement", "statement", t0_ns, time.perf_counter_ns(),
                        pid=PID_GROUPS, lane=group_lane(group),
                        trace_id=tracer.trace_id,
                        txn_trace_id=tracer.txn_trace_id,
                        session_id=self.conn_id, ok=ok,
                    )
                threshold = float(self.vars.get("tidb_slow_log_threshold", "300")) / 1000.0
                if isinstance(stmt, (ast.CreateUser, ast.Grant, ast.SetStmt)):
                    # never record credential-bearing literals (MySQL
                    # redacts user-admin statements from logs)
                    log_sql = f"<redacted {type(stmt).__name__}>"
                details = None
                if tracer is not None:
                    if tracker.max_consumed:
                        tracer.set_max("mem_bytes", float(tracker.max_consumed))
                    tracer.finish(ok=ok)
                    details = tracer.details()
                    if route_replica:
                        details["replica"] = route_replica
                    if tracer.recording:
                        if isinstance(stmt, (ast.CreateUser, ast.Grant, ast.SetStmt)):
                            tracer.sql = log_sql
                        elif self.vars.get("tidb_redact_log", "OFF") == "ON":
                            from ..utils.stmtstats import normalize_sql

                            tracer.sql = normalize_sql(tracer.sql)
                        self.store.trace_ring.push(tracer)  # rendered lazily on read
                self.store.stmt_stats.record(
                    log_sql, dur, self.user, self.current_db, ok, threshold, cpu_s=cpu,
                    summary_on=self.vars.get("tidb_enable_stmt_summary", "ON") == "ON",
                    slow_log_on=self.vars.get("tidb_enable_slow_log", "ON") == "ON",
                    max_sql_len=int(self.vars.get("tidb_stmt_summary_max_sql_length", "4096")),
                    redact=self.vars.get("tidb_redact_log", "OFF") == "ON",
                    details=details,
                )
                # workload-history feed (PR 20): statements that ran cop
                # tasks deposit their observed profile — per-engine walls,
                # compile hits, wire bytes, declines — under (digest,
                # row-bucket); the cop client's auto-router reads it back.
                # Gated on the same switch the router consumes so OFF
                # leaves zero residue (and recovers static behavior live)
                if (
                    tracer is not None and stmt_digest
                    and tracer.counters.get("tasks")
                    and self.store.global_vars.get(
                        "tidb_tpu_feedback_route", "ON") == "ON"
                ):
                    self.store.workload.observe(
                        stmt_digest, tracer.counters, tables=tracer.tables,
                    )
                # AFTER the counters above so a snapshot sees this stmt
                # (statement completion drives metrics_summary windows even
                # under pure-SQL workloads; min-interval guard in tick())
                M.HISTORY.tick()  # metrics_summary window sampling

    def must_query(self, sql: str) -> list[tuple]:
        return self.execute(sql).rows()

    # --------------------------------------------------------- privileges

    @property
    def tlocks(self):
        if getattr(self.store, "_table_locks", None) is None:
            from ..storage.tablelock import TableLocks

            self.store._table_locks = TableLocks()
        return self.store._table_locks

    def _run_lock_tables(self, stmt: ast.LockTables) -> ResultSet:
        """LOCK TABLES implicitly commits and replaces any held locks
        (ref: lock/lock.go + MySQL LOCK TABLES semantics)."""
        self._implicit_commit()
        items = []
        for tn, mode in stmt.tables:
            info = self.infoschema().table(tn.db or self.current_db, tn.name)
            self.priv.require(self, self.user, (tn.db or self.current_db).lower(),
                              "LOCK TABLES", tn.name.lower())
            items.append((info.id, info.name, mode))
        self.tlocks.release_all(self.conn_id)
        self._locked_ids = {}
        self.tlocks.acquire(self.conn_id, items)
        self._locked_ids = {tid: mode for tid, _, mode in items}
        return ResultSet([], None)

    def _run_unlock_tables(self) -> ResultSet:
        self._implicit_commit()
        self.tlocks.release_all(self.conn_id)
        self._locked_ids = {}
        return ResultSet([], None)

    def release_table_locks(self) -> None:
        """Connection teardown hook (server deregister)."""
        if getattr(self, "_locked_ids", None):
            self.tlocks.release_all(self.conn_id)
            self._locked_ids = {}

    def _tlock_read(self, info) -> None:
        if getattr(self, "_locked_ids", None) and info.db_name.lower() != "mysql":
            if info.id not in self._locked_ids:
                from ..storage.tablelock import TableLockError

                raise TableLockError(
                    f"Table '{info.name}' was not locked with LOCK TABLES"
                )
        self.tlocks.check_read(info.id, info.name, self.conn_id)

    def _tlock_write(self, info) -> None:
        if getattr(self, "_locked_ids", None) and info.db_name.lower() != "mysql":
            if info.id not in self._locked_ids:
                from ..storage.tablelock import TableLockError

                raise TableLockError(
                    f"Table '{info.name}' was not locked with LOCK TABLES"
                )
        self.tlocks.check_write(info.id, info.name, self.conn_id)

    def _check_plan_locks(self, plan) -> None:
        """Reads under LOCK TABLES: every base-table DataSource in the
        plan must be readable by this connection."""
        if isinstance(plan, DataSource):
            self._tlock_read(plan.table)
        for c in plan.children:
            self._check_plan_locks(c)

    @property
    def priv(self):
        if getattr(self.store, "_priv_cache", None) is None:
            from ..privilege import PrivilegeCache

            self.store._priv_cache = PrivilegeCache(self.store)
        return self.store._priv_cache

    def _stmt_privileges(self, stmt) -> list[tuple]:
        """→ [(priv, db[, table])] required by this statement (ref: the
        reference's visitInfo collection in planbuilder.go); the table
        element enables tables_priv-level grants."""

        def from_dbs(node, out, ctes=frozenset()):
            if isinstance(node, ast.TableName):
                if node.db is None and node.name.lower() in ctes:
                    return  # CTE reference in this scope, not a base table
                out.add(((node.db or self.current_db).lower(), node.name.lower()))
            elif isinstance(node, ast.Join):
                from_dbs(node.left, out, ctes)
                from_dbs(node.right, out, ctes)
            elif isinstance(node, ast.SubqueryTable):
                sel_dbs(node.select, out, ctes)

        def expr_dbs(e, out, ctes=frozenset()):
            if isinstance(e, ast.SubqueryExpr):
                sel_dbs(e.select, out, ctes)
            elif isinstance(e, ast.Call):
                for a in e.args:
                    expr_dbs(a, out, ctes)
            elif isinstance(e, ast.CaseWhen):
                for pair in e.whens:
                    expr_dbs(pair[0], out, ctes)
                    expr_dbs(pair[1], out, ctes)
                if e.operand is not None:
                    expr_dbs(e.operand, out, ctes)
                if e.else_ is not None:
                    expr_dbs(e.else_, out, ctes)
            elif isinstance(e, ast.Cast):
                expr_dbs(e.expr, out, ctes)

        def sel_dbs(sel, out, ctes=frozenset()):
            # `ctes` is scoped: names bind in THIS select and below, never
            # in sibling or enclosing scopes (a leaked name would suppress
            # privilege checks on a same-named real table)
            if isinstance(sel, ast.SetOpSelect):
                for s in sel.selects:
                    sel_dbs(s, out, ctes)
                return
            wf = getattr(sel, "with_", None)
            if wf is not None:
                inner = set(ctes)
                for cte in wf.ctes:
                    # WITH RECURSIVE: the name binds inside its own body
                    body = inner | {cte.name.lower()} if wf.recursive else inner
                    sel_dbs(cte.select, out, frozenset(body))
                    inner.add(cte.name.lower())
                ctes = frozenset(inner)
            if sel.from_ is not None:
                from_dbs(sel.from_, out, ctes)
            for e in [sel.where, sel.having] + [f.expr for f in sel.fields if not isinstance(f, ast.Star)]:
                if e is not None:
                    expr_dbs(e, out, ctes)

        def order_group_dbs(sel, out):
            if isinstance(sel, ast.SetOpSelect):
                for b in sel.order_by:
                    expr_dbs(b.expr, out)
                return
            for b in sel.order_by:
                expr_dbs(b.expr, out)
            for g in sel.group_by:
                expr_dbs(g, out)

        if isinstance(stmt, (ast.Select, ast.SetOpSelect)):
            dbs: set = set()
            sel_dbs(stmt, dbs)
            order_group_dbs(stmt, dbs)
            out = [("SELECT", d, t) for d, t in dbs]
            if getattr(stmt, "into_outfile", None) is not None:
                out.append(("FILE", "*"))  # writes server-side files
            return out
        if isinstance(stmt, ast.Insert):
            out = [("INSERT", (stmt.table.db or self.current_db).lower(), stmt.table.name.lower())]
            dbs: set = set()
            if stmt.select is not None:  # INSERT ... SELECT reads too
                sel_dbs(stmt.select, dbs)
            for row in stmt.values:
                for v in row:
                    if v is not None and not isinstance(v, ast.Default):
                        expr_dbs(v, dbs)
            for _, e in stmt.on_dup:
                expr_dbs(e, dbs)
            out += [("SELECT", d, t) for d, t in dbs]
            return out
        if isinstance(stmt, ast.LoadData):
            return [("INSERT", (stmt.table.db or self.current_db).lower(), stmt.table.name.lower())]
        if isinstance(stmt, ast.Update):
            dbs: set = set()
            if stmt.where is not None:
                expr_dbs(stmt.where, dbs)
            for _, e in stmt.sets:
                expr_dbs(e, dbs)
            reads = [("SELECT", d, t) for d, t in dbs]
            if isinstance(stmt.table, ast.TableName):
                db = (stmt.table.db or self.current_db).lower()
                return [("UPDATE", db, stmt.table.name.lower())] + reads
            # multi-table: UPDATE only on assigned tables, SELECT on the
            # rest (MySQL resolution; an unqualified SET column can't be
            # attributed without the schema → UPDATE everywhere, safe side)
            alias_map = self._dml_alias_map(stmt.table)
            set_aliases = {name.table.lower() for name, _ in stmt.sets if name.table}
            bare = any(name.table is None for name, _ in stmt.sets)
            out = []
            for alias, (d, t) in alias_map.items():
                writes = bare or alias in set_aliases
                out.append(("UPDATE" if writes else "SELECT", d, t))
            return out + reads
        if isinstance(stmt, ast.Delete):
            dbs: set = set()
            if stmt.where is not None:
                expr_dbs(stmt.where, dbs)
            reads = [("SELECT", d, t) for d, t in dbs]
            if isinstance(stmt.table, ast.TableName) and stmt.targets is None:
                db = (stmt.table.db or self.current_db).lower()
                return [("DELETE", db, stmt.table.name.lower())] + reads
            # multi-table: targets name ALIASES, so resolve through the
            # alias map (comparing base names would let `DELETE a FROM t
            # AS a` slip through with SELECT only)
            alias_map = self._dml_alias_map(stmt.table)
            targets = {t.lower() for t in (stmt.targets or ())}
            out = []
            for alias, (d, t) in alias_map.items():
                out.append(("DELETE" if alias in targets else "SELECT", d, t))
            return out + reads
        if isinstance(stmt, ast.TraceStmt):
            return self._stmt_privileges(stmt.stmt)
        if isinstance(stmt, ast.CreateView):
            db = (stmt.table.db or self.current_db).lower()
            # OR REPLACE can destroy an existing definition: DROP too
            return [("CREATE", db)] + ([("DROP", db)] if stmt.or_replace else [])
        if isinstance(stmt, ast.DropView):
            return [("DROP", (tn.db or self.current_db).lower()) for tn in stmt.names]
        if isinstance(stmt, (ast.CreateTable, ast.CreateDatabase)):
            db = getattr(getattr(stmt, "table", None), "db", None) or getattr(stmt, "name", None) or self.current_db
            return [("CREATE", db.lower())]
        if isinstance(stmt, ast.CreateIndex):
            return [("INDEX", (stmt.table.db or self.current_db).lower())]
        if isinstance(stmt, ast.DropIndex):
            return [("INDEX", (stmt.table.db or self.current_db).lower())]
        if isinstance(stmt, ast.DropTable):
            return [("DROP", (tn.db or self.current_db).lower()) for tn in stmt.tables]
        if isinstance(stmt, ast.DropDatabase):
            return [("DROP", stmt.name.lower())]
        if isinstance(stmt, ast.TruncateTable):
            return [("DROP", (stmt.table.db or self.current_db).lower())]
        if isinstance(stmt, ast.AlterTable):
            return [("ALTER", (stmt.table.db or self.current_db).lower())]
        if isinstance(stmt, ast.BRIEStmt):
            # BACKUP/RESTORE gate on their dynamic privileges (ref:
            # planbuilder.go visitInfo for BRIE + SUPER fallback)
            kind = getattr(stmt, "kind", "backup").lower()
            return [("RESTORE_ADMIN" if kind == "restore" else "BACKUP_ADMIN", "*")]
        if isinstance(stmt, ast.KillStmt):
            return [("CONNECTION_ADMIN", "*")]
        if isinstance(stmt, (ast.CreateUser, ast.DropUser, ast.Grant, ast.Revoke,
                             ast.AdminStmt, ast.LoadStats)):
            # LoadStats reads server-side files and rewrites shared
            # statistics that steer every session's plans
            return [("SUPER", "*")]
        if isinstance(stmt, (ast.CreateBinding, ast.DropBinding)):
            # global bindings steer every session's plans; session-scoped
            # ones only affect the caller
            return [("SUPER", "*")] if stmt.global_ else []
        return []  # SET/SHOW/USE/txn control etc. need no table privilege

    def _dml_alias_map(self, from_ast) -> dict[str, tuple[str, str]]:
        """alias(lower) → (db, table) for privilege attribution — one
        walk shared with the executor's _dml_leaves."""
        return {
            a: ((tn.db or self.current_db).lower(), tn.name.lower())
            for a, tn in self._dml_leaves(from_ast).items()
        }

    def _check_privileges(self, stmt) -> None:
        if self._in_bootstrap:
            return
        for entry in self._stmt_privileges(stmt):
            priv, db = entry[0], entry[1]
            table = entry[2] if len(entry) > 2 else None
            if db in ("information_schema", "performance_schema"):
                continue
            from ..privilege.cache import DYNAMIC_PRIVS

            if priv in DYNAMIC_PRIVS:
                self.priv.require_dynamic(self, self.user, priv)
                continue
            self.priv.require(self, self.user, db, priv, table)

    def _execute_stmt(self, stmt, sql: str | None = None) -> ResultSet:
        from ..utils import metrics as M

        self._check_privileges(stmt)
        if isinstance(stmt, (ast.Select, ast.SetOpSelect)):
            return self.run_select(stmt, sql=sql, top_level=True)
        if isinstance(stmt, (ast.Insert, ast.Update, ast.Delete)) and self.vars.get("tidb_snapshot"):
            # a session pinned to a historic snapshot must not mutate
            # state it cannot observe (ref: session tidb_snapshot guard)
            raise TiDBError("can not execute write statement when 'tidb_snapshot' is set")
        if isinstance(stmt, ast.Insert):
            return self._run_insert(stmt)
        if isinstance(stmt, ast.Update):
            return self._run_update(stmt)
        if isinstance(stmt, ast.Delete):
            return self._run_delete(stmt)
        if isinstance(stmt, ast.CreateTable):
            return self._ddl_create_table(stmt)
        if isinstance(stmt, ast.DropTable):
            return self._ddl_drop_table(stmt)
        if isinstance(stmt, ast.TruncateTable):
            return self._ddl_truncate(stmt)
        if isinstance(stmt, ast.CreateIndex):
            return self._ddl_create_index(stmt)
        if isinstance(stmt, ast.DropIndex):
            return self._ddl_drop_index(stmt)
        if isinstance(stmt, ast.AlterTable):
            return self._ddl_alter(stmt)
        if isinstance(stmt, ast.CreateDatabase):
            return self._ddl_create_db(stmt)
        if isinstance(stmt, ast.DropDatabase):
            return self._ddl_drop_db(stmt)
        if isinstance(stmt, ast.UseDB):
            if not self.infoschema().has_db(stmt.name):
                raise UnknownDatabase(f"unknown database {stmt.name!r}")
            self.current_db = stmt.name
            return ResultSet([], None)
        if isinstance(stmt, ast.Begin):
            if self.txn is not None:
                self.txn.commit()
                M.TXN_TOTAL.inc(result="commit")
                self._flush_deltas()
            self.txn = self.store.begin(pessimistic=self._txn_mode_pessimistic(stmt.mode))
            self.in_explicit_txn = True
            from ..utils import tracing as _tracing

            self._txn_trace_id = _tracing.new_txn_trace_id()
            if self._tracer is not None:  # stamp the BEGIN itself
                self._tracer.txn_trace_id = self._txn_trace_id
            return ResultSet([], None)
        if isinstance(stmt, ast.Commit):
            t = self.txn
            if t is not None:
                t.commit()
                M.TXN_TOTAL.inc(result="commit")
            self.txn = None
            self.in_explicit_txn = False
            self._txn_trace_id = None  # COMMIT itself was stamped already
            self._txn_committed(t)
            return ResultSet([], None)
        if isinstance(stmt, ast.Rollback):
            if self.txn is not None:
                self.txn.rollback()
                M.TXN_TOTAL.inc(result="rollback")
            self.txn = None
            self.in_explicit_txn = False
            self._txn_trace_id = None
            self._pending_deltas.clear()
            return ResultSet([], None)
        if isinstance(stmt, ast.SetStmt):
            for scope, name, val in stmt.assignments:
                if (
                    isinstance(val, ast.Name)
                    and len(val.parts) == 1
                    and not val.parts[0].startswith("@")
                ):
                    # SET var = bare_word — MySQL reads the identifier as a
                    # string value (e.g. SET tidb_multi_statement_mode = WARN)
                    c = Constant(Datum.s(val.parts[0]), ft_varchar(max(len(val.parts[0]), 1)))
                else:
                    c = self._eval_const_expr(val)
                if name.startswith("@") and not name.startswith("@@"):
                    self.user_vars[name.lower()] = c  # typed, for EXECUTE USING
                else:
                    if scope == "global" and not self._in_bootstrap:
                        self.priv.require_dynamic(self, self.user, "SYSTEM_VARIABLES_ADMIN")
                    from .vars import SYSVARS, set_var

                    try:
                        out = set_var(
                            name, c.value.render(c.ret_type), self.warnings,
                            scope=scope,
                        )
                    except ValueError as e:
                        raise TiDBError(str(e))
                    if name == "tidb_resource_group" and not self._in_bootstrap:
                        out = out.lower()
                        if not self.store.sched.groups.exists(out):
                            raise ResourceGroupNotExists(
                                f"resource group '{out}' does not exist"
                            )
                    if scope == "global":
                        # SET GLOBAL: store-wide value, visible to NEW
                        # sessions and @@global reads; the current
                        # session's value is unchanged unless the var is
                        # global-only (MySQL scope rules)
                        gv = self.store.global_vars
                        prev_g = gv.get(name)
                        prev_s = self.vars.get(name)
                        gv[name] = out
                        if SYSVARS[name].scope == "global":
                            self.vars[name] = out
                        try:
                            self._apply_global_sysvar(name, out)
                        except TiDBError:
                            # component rejected the value: restore both
                            if prev_g is None:
                                gv.pop(name, None)
                            else:
                                gv[name] = prev_g
                            if prev_s is not None:
                                self.vars[name] = prev_s
                            raise
                    else:
                        self.vars[name] = out
                    # plan-time knobs (group_concat_max_len, sql_mode, ...)
                    # bake into cached plans — never serve a stale one
                    self._plan_cache.clear()
            return ResultSet([], None)
        if isinstance(stmt, ast.CreateSequence):
            return self._ddl_create_sequence(stmt)
        if isinstance(stmt, ast.DropSequence):
            return self._ddl_drop_sequence(stmt)
        if isinstance(stmt, ast.ResourceGroupDDL):
            return self._run_resource_group_ddl(stmt)
        if isinstance(stmt, ast.SetResourceGroup):
            return self._run_set_resource_group(stmt)
        if isinstance(stmt, ast.TraceStmt):
            return self._run_trace(stmt)
        if isinstance(stmt, ast.CreateView):
            return self._ddl_create_view(stmt)
        if isinstance(stmt, ast.DropView):
            return self._ddl_drop_view(stmt)
        if isinstance(stmt, ast.LoadStats):
            import json as _json

            try:
                with open(stmt.path, "r", encoding="utf8") as f:
                    self.store.stats.load_dump(self, _json.load(f))
            except OSError as e:
                raise TiDBError(f"Load Stats: open file {stmt.path!r} failed: {e.strerror}")
            except (_json.JSONDecodeError, KeyError, TypeError) as e:
                raise TiDBError(f"Load Stats: invalid stats dump: {e}")
            self._plan_cache.clear()
            return ResultSet([], None)
        if isinstance(stmt, ast.LockTables):
            return self._run_lock_tables(stmt)
        if isinstance(stmt, ast.UnlockTables):
            return self._run_unlock_tables()
        if isinstance(stmt, ast.Prepare):
            return self._run_prepare(stmt)
        if isinstance(stmt, ast.Execute):
            return self._run_execute(stmt)
        if isinstance(stmt, ast.Deallocate):
            if stmt.name not in self.prepared:
                raise TiDBError(f"Unknown prepared statement handler ({stmt.name})")
            del self.prepared[stmt.name]
            return ResultSet([], None)
        if isinstance(stmt, ast.Show):
            return self._run_show(stmt)
        if isinstance(stmt, ast.Explain):
            return self._run_explain(stmt)
        if isinstance(stmt, ast.AnalyzeTable):
            return self._run_analyze(stmt)
        if isinstance(stmt, ast.FlushStmt):
            return ResultSet([], None)
        if isinstance(stmt, ast.SplitRegion):
            return self._run_split_region(stmt)
        if isinstance(stmt, ast.KillStmt):
            return self._run_kill(stmt)
        if isinstance(stmt, ast.AdminStmt):
            if stmt.kind == "show_ddl_jobs":
                return self._admin_show_ddl_jobs()
            if stmt.kind == "check_table":
                return self._admin_check_table(stmt.target)
            if stmt.kind == "checksum_table":
                return self._admin_checksum_table(stmt.target)
            if stmt.kind == "recover_index":
                return self._admin_recover_cleanup_index(*stmt.target, recover=True)
            if stmt.kind == "cleanup_index":
                return self._admin_recover_cleanup_index(*stmt.target, recover=False)
            if stmt.kind == "promote":
                # warm-standby failover promotion (PR 14): flips the
                # store read-write; rejected on a store that is not (or
                # no longer) a standby
                self.store.promote()
                return ResultSet([], None)
            if stmt.kind == "rejoin":
                # rebuild this fenced old primary as a standby of the
                # promoted new primary (PR 17); rejected while healthy
                self.store.rejoin()
                return ResultSet([], None)
        if isinstance(stmt, ast.CreateBinding):
            return self._run_create_binding(stmt)
        if isinstance(stmt, ast.DropBinding):
            return self._run_drop_binding(stmt)
        if isinstance(stmt, ast.CreateUser):
            return self._run_create_user(stmt)
        if isinstance(stmt, ast.DropUser):
            return self._run_drop_user(stmt)
        if isinstance(stmt, (ast.Grant, ast.Revoke)):
            return self._run_grant_revoke(stmt)
        if isinstance(stmt, ast.BRIEStmt):
            from .. import br

            return br.run_backup(self, stmt) if stmt.kind == "backup" else br.run_restore(self, stmt)
        if isinstance(stmt, ast.LoadData):
            from .. import br

            return br.run_load_data(self, stmt)
        raise TiDBError(f"unsupported statement {type(stmt).__name__}")

    # ------------------------------------------------------- user admin

    @staticmethod
    def _q(s: str) -> str:
        """Escape a value for single-quoted interpolation into internal
        SQL (privilege checks are suspended there — injection-proof)."""
        return (s or "").replace("\\", "\\\\").replace("'", "''")

    def _implicit_commit(self) -> None:
        """User-admin/DDL statements implicitly commit any open txn
        (MySQL implicit-commit statement list)."""
        if self.txn is not None:
            t = self.txn
            t.commit()
            self.txn = None
            self.in_explicit_txn = False
            self._txn_trace_id = None
            self._txn_committed(t)

    def _run_create_user(self, stmt: ast.CreateUser) -> ResultSet:
        from ..privilege import mysql_native_hash
        from ..privilege.cache import PrivilegeError

        self._implicit_commit()
        for spec in stmt.users:
            if self.priv.user_exists(self, spec.user):
                if stmt.if_not_exists:
                    continue
                raise PrivilegeError(f"CREATE USER failed: '{spec.user}' already exists")
            h = mysql_native_hash(spec.password or "")
            self._sql_internal(
                f"INSERT INTO mysql.user VALUES ('{self._q(spec.host)}', '{self._q(spec.user)}', '{h}', '')"
            )
        self.priv.bump_version()
        return ResultSet([], None)

    def _run_drop_user(self, stmt: ast.DropUser) -> ResultSet:
        from ..privilege.cache import PrivilegeError

        self._implicit_commit()
        for spec in stmt.users:
            if not self.priv.user_exists(self, spec.user):
                if stmt.if_exists:
                    continue
                raise PrivilegeError(f"DROP USER failed: '{spec.user}' does not exist")
            self._sql_internal(f"DELETE FROM mysql.user WHERE user = '{self._q(spec.user)}'")
            self._sql_internal(f"DELETE FROM mysql.db WHERE user = '{self._q(spec.user)}'")
        self.priv.bump_version()
        return ResultSet([], None)

    def _run_grant_revoke(self, stmt) -> ResultSet:
        from ..privilege.cache import DYNAMIC_PRIVS, PRIVS, PrivilegeError

        self._implicit_commit()
        grant = isinstance(stmt, ast.Grant)
        privs = set(p.upper() for p in stmt.privs)
        dynamic = privs & DYNAMIC_PRIVS
        privs -= dynamic
        unknown = privs - PRIVS - {"ALL"}
        if unknown:
            raise TiDBError(f"unknown privilege(s): {', '.join(sorted(unknown))}")
        if dynamic and (stmt.db != "*" or stmt.table != "*"):
            raise TiDBError("Illegal privilege level specified for dynamic privilege (use *.*)")
        if stmt.db == "*" and stmt.table != "*":
            raise TiDBError("Incorrect use of DB GRANT and table-level privileges (*.<table>)")
        for spec in stmt.users:
            if not self.priv.user_exists(self, spec.user):
                raise PrivilegeError(f"there is no such user '{spec.user}'")
            u = self._q(spec.user)
            for dp in sorted(dynamic):
                self._sql_internal(
                    f"DELETE FROM mysql.global_grants WHERE user = '{u}' AND priv = '{dp}'"
                )
                if grant:
                    self._sql_internal(
                        f"INSERT INTO mysql.global_grants VALUES ('{u}', '{dp}')"
                    )
            if not privs:
                continue
            if stmt.db != "*" and stmt.table != "*":
                self._grant_revoke_table(stmt, spec, privs, grant)
                continue
            if stmt.db == "*":
                rows = self._sql_internal(f"SELECT privs FROM mysql.user WHERE user = '{u}'")
                cur = set((rows[0][0] or "").split(",")) - {""}
                new = self._apply_priv_change(cur, privs, grant)
                self._sql_internal(
                    f"UPDATE mysql.user SET privs = '{','.join(sorted(new))}' WHERE user = '{u}'"
                )
            else:
                d = self._q(stmt.db)
                rows = self._sql_internal(
                    f"SELECT privs FROM mysql.db WHERE user = '{u}' AND db = '{d}'"
                )
                if not rows and not grant:
                    raise PrivilegeError(
                        f"there is no such grant defined for user '{spec.user}' on '{stmt.db}'"
                    )
                cur = set((rows[0][0] or "").split(",")) - {""} if rows else set()
                new = self._apply_priv_change(cur, privs, grant)
                if rows:
                    self._sql_internal(
                        f"UPDATE mysql.db SET privs = '{','.join(sorted(new))}' "
                        f"WHERE user = '{u}' AND db = '{d}'"
                    )
                else:
                    self._sql_internal(
                        f"INSERT INTO mysql.db VALUES ('{self._q(spec.host)}', '{u}', "
                        f"'{d}', '{','.join(sorted(new))}')"
                    )
        self.priv.bump_version()
        return ResultSet([], None)

    def _grant_revoke_table(self, stmt, spec, privs: set, grant: bool) -> None:
        """Table-level grant bookkeeping in mysql.tables_priv (ref:
        privilege cache tablesPriv + executor/grant.go table scope)."""
        from ..privilege.cache import PrivilegeError

        if grant:
            # the object must exist on GRANT (table OR view); REVOKE must
            # still work for grants whose object was since dropped
            is_ = self.infoschema()
            if (stmt.db.lower(), stmt.table.lower()) not in is_.views:
                is_.table(stmt.db, stmt.table)
        u = self._q(spec.user)
        d = self._q(stmt.db)
        t = self._q(stmt.table)
        rows = self._sql_internal(
            f"SELECT privs FROM mysql.tables_priv WHERE user = '{u}' "
            f"AND db = '{d}' AND table_name = '{t}'"
        )
        if not rows and not grant:
            raise PrivilegeError(
                f"there is no such grant defined for user '{spec.user}' on "
                f"'{stmt.db}.{stmt.table}'"
            )
        cur = set((rows[0][0] or "").split(",")) - {""} if rows else set()
        new = self._apply_priv_change(cur, privs, grant)
        if rows:
            self._sql_internal(
                f"UPDATE mysql.tables_priv SET privs = '{','.join(sorted(new))}' "
                f"WHERE user = '{u}' AND db = '{d}' AND table_name = '{t}'"
            )
        else:
            self._sql_internal(
                f"INSERT INTO mysql.tables_priv VALUES ('{self._q(spec.host)}', "
                f"'{u}', '{d}', '{t}', '{','.join(sorted(new))}')"
            )

    @staticmethod
    def _apply_priv_change(cur: set, privs: set, grant: bool) -> set:
        from ..privilege.cache import PrivilegeError

        if grant:
            return cur | privs
        if "ALL" in privs:
            return set()
        if "ALL" in cur:
            # MySQL: revoking a specific priv from an ALL holder errors
            raise PrivilegeError("cannot partially revoke from an ALL PRIVILEGES grant")
        return cur - privs

    def _run_create_binding(self, stmt: ast.CreateBinding) -> ResultSet:
        from ..utils.stmtstats import sql_digest

        using = parse_one(stmt.using_sql)
        if not getattr(using, "hints", None):
            raise TiDBError("the USING statement carries no optimizer hints")
        digest = sql_digest(stmt.for_sql)
        if not stmt.global_:
            self._session_bindings[digest] = list(using.hints)
            self._plan_cache.clear()
            return ResultSet([], None)
        self._sql_internal(f"DELETE FROM mysql.bind_info WHERE original_digest = '{digest}'")
        self._sql_internal(
            "INSERT INTO mysql.bind_info VALUES "
            f"('{digest}', '{self._q(stmt.for_sql)}', '{self._q(stmt.using_sql)}', 'enabled')"
        )
        self.bindings.bump_version()
        self._plan_cache.clear()
        return ResultSet([], None)

    def _run_drop_binding(self, stmt: ast.DropBinding) -> ResultSet:
        from ..utils.stmtstats import sql_digest

        digest = sql_digest(stmt.for_sql)
        if not stmt.global_:
            self._session_bindings.pop(digest, None)
            self._plan_cache.clear()
            return ResultSet([], None)
        self._sql_internal(f"DELETE FROM mysql.bind_info WHERE original_digest = '{digest}'")
        self.bindings.bump_version()
        self._plan_cache.clear()
        return ResultSet([], None)

    def _run_split_region(self, stmt: ast.SplitRegion) -> ResultSet:
        """SPLIT TABLE t BETWEEN (lo) AND (hi) REGIONS n | BY (v),(v)...
        (ref: executor/split.go SplitTableRegionExec — here splits land in
        the region map directly; the scatter step is a no-op in-process)."""
        info = self.infoschema().table(stmt.table.db or self.current_db, stmt.table.name)
        keys: list[bytes] = []
        if stmt.between is not None:
            lo_e, hi_e, n = stmt.between
            lo = self._eval_const_expr(lo_e[0]).value.to_int()
            hi = self._eval_const_expr(hi_e[0]).value.to_int()
            if n <= 0 or hi <= lo:
                raise TiDBError("Split table region lower value should be less than the upper value")
            step = max((hi - lo) // n, 1)
            keys = [tablecodec.record_key(info.id, lo + i * step) for i in range(1, n)]
        else:
            for vals in stmt.by:
                h = self._eval_const_expr(vals[0]).value.to_int()
                keys.append(tablecodec.record_key(info.id, h))
        created = self.store.regions.split_many(keys)
        return ResultSet.message_row(["TOTAL_SPLIT_REGION", "SCATTER_FINISH_RATIO"], [str(created), "1.0"])

    def _run_kill(self, stmt: ast.KillStmt) -> ResultSet:
        """KILL [QUERY] <id> (ref: server.go:609 Kill + sessVars.Killed):
        flags the target session; its executor loop raises
        QueryInterrupted at the next chunk boundary."""
        info = self.store.get_process(stmt.conn_id)
        if info is None:
            raise TiDBError(f"Unknown thread id: {stmt.conn_id}")
        target = info["session"]()
        if target is not None:
            target._killed = True
        return ResultSet([], None)

    def _admin_check_table(self, tn) -> ResultSet:
        """ADMIN CHECK TABLE: verify row↔index consistency for every
        public index (ref: executor/admin.go CheckTableExec + executor.go
        CheckTableExec). Raises on any dangling or missing entry."""
        info = self.infoschema().table(tn.db or self.current_db, tn.name)
        snap = self.store.snapshot()
        for pid in info.physical_ids():
            tbl = Table(info.partition_physical(pid)) if info.partition else Table(info)
            self._check_physical(snap, info, tbl, pid)
        return ResultSet([], None)

    def _check_physical(self, snap, info, tbl, pid: int) -> None:
        prefix = tablecodec.record_prefix(pid)
        decoded = [
            (tablecodec.decode_record_handle(k), tbl.decode_record(v))
            for k, v in snap.scan(prefix, prefix_next(prefix))
        ]
        for idx in info.indexes:
            if idx.state != "public" or (info.pk_is_handle and idx.primary):
                continue
            expected = {}
            for handle, datums in decoded:
                key, val, _ = tbl.index_value_key(idx, tbl.row_datums_with_hidden(datums, handle), handle)
                expected[key] = val
            ipfx = tablecodec.index_prefix(pid, idx.id)
            actual = dict(snap.scan(ipfx, prefix_next(ipfx)))
            missing = set(expected) - set(actual)
            dangling = set(actual) - set(expected)
            # values must match too: a unique entry pointing at the wrong
            # handle has the right KEY but the wrong stored value
            corrupt = sum(1 for k in expected if k in actual and actual[k] != expected[k])
            if missing or dangling or corrupt:
                raise TiDBError(
                    f"admin check table {info.name!r} index {idx.name!r} inconsistent: "
                    f"{len(missing)} missing, {len(dangling)} dangling, "
                    f"{corrupt} mismatched entries"
                )

    def _admin_recover_cleanup_index(self, tn, idx_name: str, recover: bool) -> ResultSet:
        """ADMIN RECOVER INDEX (write missing entries back) / ADMIN
        CLEANUP INDEX (delete dangling entries) — ref: executor/admin.go
        RecoverIndexExec:180, CleanupIndexExec:524."""
        info = self.infoschema().table(tn.db or self.current_db, tn.name)
        idx = info.index_by_name(idx_name)
        if idx is None or idx.state != "public":
            raise TiDBError(f"index {idx_name!r} does not exist in table {tn.name!r}")
        if info.pk_is_handle and idx.primary:
            raise TiDBError("the clustered PRIMARY key has no separate index keyspace")
        txn = self._active_txn()
        snap = self.store.snapshot(self.read_ts())
        fixed = scanned = 0
        for pid in info.physical_ids():
            tbl = Table(info.partition_physical(pid)) if info.partition else Table(info)
            prefix = tablecodec.record_prefix(pid)
            expected = {}
            for k, v in snap.scan(prefix, prefix_next(prefix)):
                handle = tablecodec.decode_record_handle(k)
                datums = tbl.decode_record(v)
                key, val, _ = tbl.index_value_key(
                    idx, tbl.row_datums_with_hidden(datums, handle), handle
                )
                expected[key] = val
                scanned += 1
            ipfx = tablecodec.index_prefix(pid, idx.id)
            actual = dict(snap.scan(ipfx, prefix_next(ipfx)))
            if recover:
                for k in set(expected) - set(actual):
                    txn.put(k, expected[k])
                    fixed += 1
            else:
                for k in set(actual) - set(expected):
                    txn.delete(k)
                    fixed += 1
        name = "ADDED_COUNT" if recover else "REMOVED_COUNT"
        chk = Chunk.from_datum_rows(
            [ft_longlong(), ft_longlong()], [[Datum.i(fixed), Datum.i(scanned)]]
        )
        return ResultSet([name, "SCAN_COUNT"], chk)

    def _admin_checksum_table(self, tn) -> ResultSet:
        """ADMIN CHECKSUM TABLE (ref: executor/checksum.go — a 64-bit
        XOR-of-per-kv-digests over the table's kv pairs at a consistent
        snapshot; order-independent like the reference's crc64 xor)."""
        import hashlib

        info = self.infoschema().table(tn.db or self.current_db, tn.name)
        snap = self.store.snapshot()
        crc = 0
        total_kvs = 0
        total_bytes = 0
        for pid in info.physical_ids():
            for k, v in snap.scan(tablecodec.table_prefix(pid), tablecodec.table_prefix(pid + 1)):
                h = hashlib.blake2b(k + b"\x00" + v, digest_size=8).digest()
                crc ^= int.from_bytes(h, "big")
                total_kvs += 1
                total_bytes += len(k) + len(v)
        return ResultSet.message_row(
            ["Db_name", "Table_name", "Checksum_crc64_xor", "Total_kvs", "Total_bytes"],
            [info.db_name, info.name, str(crc), str(total_kvs), str(total_bytes)],
        )

    def _admin_show_ddl_jobs(self) -> ResultSet:
        """ADMIN SHOW DDL JOBS (ref: executor ShowDDLJobsExec)."""
        from ..mysqltypes.field_type import ft_varchar

        txn = self.store.begin()
        m = Meta(txn)
        jobs = m.job_history()
        pending = m.jobs()
        txn.rollback()
        names = ["JOB_ID", "JOB_TYPE", "TABLE_ID", "SCHEMA_STATE", "STATE", "ERROR"]
        rows = [
            (str(j.id), j.type, str(j.table_id), j.schema_state, j.state, j.error or "")
            for j in pending + sorted(jobs, key=lambda x: -x.id)
        ]
        chk = Chunk.empty([ft_varchar(64) for _ in names], len(rows))
        for r, row in enumerate(rows):
            for c, v in enumerate(row):
                chk.columns[c].set_datum(r, Datum.s(v))
        return ResultSet(names, chk)

    def _const_of(self, node) -> Constant:
        if isinstance(node, ast.Lit):
            return lit_to_constant(node)
        if isinstance(node, ast.Name):
            return Constant(Datum.s(".".join(node.parts)), ft_varchar())
        raise TiDBError("expected literal")

    def _eval_const_expr(self, node) -> Constant:
        """Evaluate a column-free expression to a typed Constant (for
        SET @var = <expr> and INSERT value expressions). Bare identifiers
        are NOT treated as strings here — they must resolve (and cannot,
        in an empty scope), matching MySQL's unknown-column error."""
        if isinstance(node, ast.Lit):
            return lit_to_constant(node)
        builder = self._builder()
        e = builder.to_expr(node, NameScope([]))
        one = Chunk([Column(ft_longlong(), np.zeros(1, dtype=np.int64), np.ones(1, dtype=bool))])
        d, v = e.eval(one)
        d = np.asarray(d).reshape(-1)
        v = np.asarray(v).reshape(-1)
        if not v[0]:
            return Constant(Datum.null(), e.ret_type)
        return Constant(Column(e.ret_type, d[:1], v[:1]).get_datum(0), e.ret_type)

    # ---------------------------------------------------------------- SELECT

    def _apply_global_sysvar(self, name: str, val: str) -> None:
        """Push store-level knobs into their owning component (ref:
        gc_worker.go loading tidb_gc_* from mysql.tidb each round)."""
        if name in ("tidb_gc_life_time", "tidb_gc_run_interval"):
            from ..storage.gcworker import parse_go_duration_ms

            ms = parse_go_duration_ms(val)
            if ms is None:
                raise TiDBError(f"invalid duration value for '{name}': '{val}'")
            gw = self.store.gc_worker
            if name == "tidb_gc_life_time":
                gw.life_ms = ms
            else:
                gw.interval_ms = ms
        elif name == "tidb_gc_enable":
            self.store.gc_worker.enabled = val == "ON"
        elif name == "tidb_stmt_summary_max_stmt_count":
            # store-wide telemetry capacity: global-only, applied once
            # here instead of last-writer-wins through per-record calls
            self.store.stmt_stats.summary_capacity = int(val)
        elif name == "tidb_trace_ring_capacity":
            # live resize, keeping the newest traces (PR 3 debt)
            self.store.trace_ring.resize(int(val))
        elif name == "tidb_timeline_ring_capacity":
            # live resize of the device timeline ring, keeping the newest
            # events (PR 5 debt: capacity was hard-coded at 8192)
            self.store.timeline.resize(int(val))
        elif name == "tidb_tpu_cop_lanes":
            # mesh dispatch width: takes effect for the next placement
            self.store.sched.tpu_engine.set_active_lanes(int(val))
        elif name == "tidb_tpu_tile_compression":
            # tile layout flag on the store-wide engine: mirrors built
            # under the other layout rebuild lazily on next touch (the
            # compile cache keys carry the codec signature, so old and
            # new programs coexist without collisions)
            self.store.sched.tpu_engine.tile_compression = val == "ON"
        elif name == "tidb_enable_timeline":
            # store-wide flag on the ring itself: takes effect for every
            # session's next engine call, no per-session re-read needed
            self.store.timeline.enabled = val == "ON"
        elif name == "tidb_wal_recovery_mode":
            # applies to the NEXT recovery; persisted in the data dir's
            # RECOVERY_MODE sidecar so it survives the crash it's for
            self.store.set_wal_recovery_mode(val)
        elif name == "tidb_wal_spare_dirs":
            # spare WAL media for online failover (PR 14): applies to
            # the next IO-failure rotation attempt
            self.store.set_wal_spare_dirs(val)
        elif name == "tidb_server_memory_limit":
            self.store.mem.set_limit(int(val))
        elif name == "tidb_memory_usage_alarm_ratio":
            self.store.mem.set_alarm_ratio(float(val))
        elif name == "tidb_compact_interval":
            # the compactor re-reads global_vars each tick — validate the
            # duration here (so a bad SET fails loudly, not silently at
            # the next tick) and wake the worker to adopt the new cadence
            from ..storage.gcworker import parse_go_duration_ms

            if parse_go_duration_ms(val) is None:
                raise TiDBError(f"invalid duration value for '{name}': '{val}'")
            comp = self.store.compactor
            if comp is not None:
                comp.wake()
        elif name in ("tidb_compact_enable", "tidb_compact_delta_threshold",
                      "tidb_compact_max_runs"):
            comp = self.store.compactor
            if comp is not None:
                comp.wake()  # pull-model knobs: next round sees them

    def _sysvar_read_global(self, name: str):
        """@@global.x: the store-wide value (SET GLOBAL overrides over
        registry defaults), never this session's override."""
        from .vars import SYSVARS

        sv = SYSVARS.get(name)
        return self.store.global_vars.get(name, sv.default if sv else "")

    def _sysvar_read(self, name: str):
        """Live value for SELECT @@name — dynamic session state for the
        read-only status vars, stored value otherwise (ref: sessionctx
        variable GetSessionOrGlobalSystemVar)."""
        if name == "warning_count":
            return len(self._prev_warnings)
        if name == "error_count":
            return 1 if getattr(self, "_prev_error", False) else 0
        if name == "last_insert_id":
            return int(self.last_insert_id or 0)
        if name == "tidb_current_ts":
            return int(self.txn.start_ts) if self.txn is not None else 0
        if name == "tidb_last_txn_info":
            return self._last_txn_info or ""
        if name == "tidb_last_query_info":
            return self._last_query_info or ""
        if name == "last_plan_from_cache":
            return "1" if getattr(self, "_prev_plan_from_cache", False) else "0"
        if name == "last_plan_from_binding":
            return "1" if getattr(self, "_prev_plan_from_binding", False) else "0"
        if name == "tidb_config":
            import json as _json

            return _json.dumps({"store": "tidb-tpu", "host": "0.0.0.0"})
        from .vars import SYSVARS

        sv = SYSVARS.get(name)
        return self.vars.get(name, sv.default if sv else "")

    def _builder(self, expose_rowid=None) -> PlanBuilder:
        return PlanBuilder(
            self.infoschema(), self.current_db,
            run_subquery=self._run_subquery, params=self._exec_params,
            memtable_rows=self._memtable_rows,
            context_info={"user": self.user, "conn_id": self.conn_id, "vars": self.vars,
                          "sysvar_read": self._sysvar_read,
                          "sysvar_read_global": self._sysvar_read_global},
            hints=getattr(self, "_cur_hints", None),
            expose_rowid=expose_rowid,
            seq_hook=self.sequence_op,
        )

    @property
    def bindings(self):
        if getattr(self.store, "_binding_cache", None) is None:
            from ..bindinfo import BindingCache

            self.store._binding_cache = BindingCache(self.store)
        return self.store._binding_cache

    def _effective_hints(self, stmt, sql: str | None) -> list:
        hints = list(getattr(stmt, "hints", []) or [])
        if hints or sql is None or self._in_bootstrap:
            return hints
        b = self.bindings
        # fast path: no bindings anywhere → skip digesting entirely
        if not self._session_bindings and b.notify_version == b._version and not b._by_digest:
            return hints
        from ..utils.stmtstats import sql_digest

        digest = sql_digest(sql)
        local = self._session_bindings.get(digest)
        if local:
            self._last_plan_from_binding = True
            return local
        out = b.hints_for(digest)
        self._last_plan_from_binding = bool(out)
        return out

    def _memtable_rows(self, name: str):
        from ..catalog.memtables import rows_for

        return rows_for(self, name)

    def _plan_env_key(self) -> tuple:
        """The non-SQL half of every plan-cache key: everything baked
        into a built plan that can drift between executions."""
        return (
            self.current_db,
            self.infoschema().version,
            self._temp_epoch,  # temp tables shadow names per-session
            self.store.stats.generation,
            self.vars.get("tidb_cop_engine", ""),
            # type-inference / planning knobs baked into built plans
            self.vars.get("div_precision_increment", "4"),
            self.vars.get("default_week_format", "0"),
            self.vars.get("tidb_enable_index_merge", "ON"),
            self.vars.get("tidb_opt_join_reorder_threshold", "0"),
            repr(getattr(self, "_cur_hints", None) or []),
        )

    def _prepared_plan_for(self, stmt):
        """Statement-id prepared-plan cache (ref: planner/core
        plan_cache.go GetPlanFromSessionPlanCache + RebuildPlan4CachedPlan):
        repeats of COM_STMT_EXECUTE / EXECUTE skip the parser AND the
        optimizer. The first execution's parameter Constants stay
        embedded in the cached plan as live slots; a repeat mutates them
        in place with the new values and re-derives only the
        value-dependent access info (point handles / key ranges /
        partition pruning) from the saved access conditions. A repeat
        whose values change the plan SHAPE (a cond stopped being
        sargable) drops the entry and replans — correctness never rides
        on the cache."""
        from ..planner import optimizer as _opt

        params = self._exec_params
        anchor = self._active_prep
        if anchor is None or anchor is not stmt or self.txn is not None:
            # a nested sub-select of a prepared DML, or inside an explicit
            # txn (the text plan cache bypasses there too): plan fresh
            return self.plan_select(stmt)
        seq = getattr(anchor, "_prep_plan_seq", None)
        if seq is None:
            self._prep_seq += 1
            seq = self._prep_seq
            try:
                anchor._prep_plan_seq = seq
            except (AttributeError, TypeError):
                return self.plan_select(stmt)
        # param TYPE signature: a re-prepare-free client may flip a
        # parameter from int to string between executes — those need
        # (and get) distinct plans, since inference baked the old type
        sig = tuple(
            (p.value.kind, getattr(p.ret_type, "tp", None)) for p in params
        )
        key = ("~prep~", seq, sig, self._plan_env_key())
        ent = self._plan_cache.get(key)
        if ent is not None:
            plan, slots = ent
            for slot, p in zip(slots, params):
                # slot IS p on the first (caching) execution's aliases —
                # self-assignment is a no-op; fresh wire params mutate
                # the embedded slots, which every expression in the
                # cached plan references
                slot.value = p.value
                slot.ret_type = p.ret_type
            if _opt.rebind_cached_ranges(plan):
                self._plan_cache.move_to_end(key)
                self.plan_cache_hits += 1
                self._last_plan_from_cache = True
                return plan
            del self._plan_cache[key]  # shape changed under the new values
        plan = self.plan_select(stmt)
        if not getattr(plan, "_uncacheable", False) and _opt.plan_rebindable(plan):
            self._plan_cache[key] = (plan, list(params))
            while len(self._plan_cache) > self.PLAN_CACHE_SIZE:
                self._plan_cache.popitem(last=False)
        return plan

    def _plan_for(self, stmt, sql: str | None):
        """Plan with an LRU plan cache for parameter-free statements
        (ref: planner/core/cache.go:128 plan-cache key = stmt digest +
        schema version; stats generation added so ANALYZE invalidates).
        Parameterized executions route to the statement-id prepared-plan
        cache instead (PR 14 — prepared repeats skip the optimizer)."""
        if self._exec_params is not None:
            return self._prepared_plan_for(stmt)
        if sql is None or self.txn is not None:
            return self.plan_select(stmt)
        key = (sql, self._plan_env_key())
        plan = self._plan_cache.get(key)
        self._last_plan_from_cache = plan is not None
        if plan is not None:
            self._plan_cache.move_to_end(key)
            self.plan_cache_hits += 1
            return plan
        plan = self.plan_select(stmt)
        if not getattr(plan, "_uncacheable", False):
            self._plan_cache[key] = plan
            while len(self._plan_cache) > self.PLAN_CACHE_SIZE:
                self._plan_cache.popitem(last=False)
        return plan

    def plan_select(self, stmt):
        builder = self._builder()
        plan = builder.build_select(stmt)
        plan = optimize(plan, self.store.stats, self.vars)
        plan._uncacheable = builder.used_eager_subquery
        return plan

    def run_select(self, stmt, sql: str | None = None, top_level: bool = False) -> ResultSet:
        prev_hints = getattr(self, "_cur_hints", None)
        hints = self._effective_hints(stmt, sql)
        self._cur_hints = hints
        try:
            plan = self._plan_for(stmt, sql)
        finally:
            # restore, not clear: subquery planning nests run_select
            self._cur_hints = prev_hints
        engine = self.vars.get("tidb_cop_engine", "auto")
        exec_vars = self.vars
        for h, args in hints:
            if h == "MERGE_JOIN":
                exec_vars = dict(exec_vars, tidb_opt_prefer_merge_join="ON")
            elif h in ("INL_JOIN", "INDEX_JOIN"):
                exec_vars = dict(exec_vars, tidb_opt_prefer_index_join="ON")
            elif h == "INL_HASH_JOIN":
                exec_vars = dict(exec_vars, tidb_opt_prefer_index_join="ON",
                                 tidb_opt_index_join_variant="hash")
            elif h == "INL_MERGE_JOIN":
                exec_vars = dict(exec_vars, tidb_opt_prefer_index_join="ON",
                                 tidb_opt_index_join_variant="merge")
            elif h == "HASH_JOIN":
                exec_vars = dict(
                    exec_vars, tidb_opt_prefer_merge_join="OFF", tidb_opt_prefer_index_join="OFF"
                )
            elif h == "READ_FROM_STORAGE" and args:
                store_kind = args[0].split("[")[0]
                if store_kind in ("tpu", "tiflash"):
                    engine = "tpu"
                elif store_kind in ("host", "tikv"):
                    engine = "host"
            elif h == "SET_VAR" and args:
                # statement-scope sysvar override (ref: MySQL SET_VAR
                # optimizer hint); consumed by the cop path via
                # _stmt_vars (e.g. tidb_backoff_budget_ms), cleared at
                # statement end
                from .vars import SYSVARS

                for a in args:
                    if "=" not in a:
                        continue
                    k, v = (p.strip() for p in a.split("=", 1))
                    sv = SYSVARS.get(k)
                    if sv is None:
                        self.warnings.append(f"Unresolved name '{k}' in SET_VAR hint")
                        continue
                    try:
                        self._stmt_vars[k] = sv.normalize(v)
                    except ValueError as e:
                        self.warnings.append(str(e))
        # --- stale reads + follower routing (PR 17) ------------------------
        # AS OF TIMESTAMP pins the statement's read-ts; `tidb_replica_read`
        # lets top-level autocommit reads run against an attached in-process
        # replica whose applied watermark is close enough (AS OF: watermark
        # must have REACHED the requested ts; plain follower read: lag
        # within tidb_replica_read_max_lag_ms, served at the watermark).
        # Fallback is always the primary — routing never changes results
        # beyond the documented staleness bound.
        as_of = getattr(stmt, "as_of", None)
        read_ts = None
        if as_of is not None:
            if self.txn is not None:
                raise TiDBError("as of timestamp can't be set in transaction")
            read_ts = self._as_of_read_ts(as_of)
        cop = self.cop
        route_store = None
        router = None
        if top_level and not self.store.standby:
            sh = getattr(self.store, "_shipper", None)
            rr = str(exec_vars.get("tidb_replica_read", "leader")).lower()
            wants_follower = sh is not None and (
                as_of is not None or rr in ("follower", "leader-and-follower")
            )
            if wants_follower and self.txn is not None:
                # follower read requested inside an open txn: routing
                # would miss the txn's own uncommitted writes, so the
                # primary serves — counted with its reason like every
                # other fallback (the PR 8 taxonomy)
                from ..utils import metrics as M

                M.REPLICA_READS.inc(outcome="fallback_stale", reason="in_txn")
                self._note_route({"outcome": "fallback_stale",
                                  "reason": "in_txn", "replica": "",
                                  "lag_ms": 0.0})
            elif wants_follower:
                max_lag = int(exec_vars.get("tidb_replica_read_max_lag_ms", 5000) or 0)
                router = sh.router
                decision: dict = {}
                route_store = router.route(as_of_ts=read_ts, max_lag_ms=max_lag,
                                           decision=decision)
                prop = self._note_route(decision)
                if route_store is not None:
                    cop = self._replica_cop(route_store)
                    # cross-node trace propagation: the replica-side cop
                    # tags its spans with the serving replica so they
                    # adopt into THIS statement's trace attributed
                    cop.replica_name = decision.get("replica") if prop else None
                    if read_ts is None:
                        # bounded-staleness read at the replica's applied
                        # watermark: everything the replica has is visible,
                        # nothing torn (frames apply in commit order)
                        read_ts = route_store.applied_ts
        try:
            ctx = ExecContext(
                cop,
                self.read_ts() if read_ts is None else read_ts,
                engine=engine,
                vars=exec_vars,
                txn=self.txn,
            )
            tl = getattr(self.store, "_table_locks", None)
            if (tl is not None and tl._locks) or getattr(self, "_locked_ids", None):
                self._check_plan_locks(plan)
            sel_limit = int(self.vars.get("sql_select_limit", 2**64 - 1) or 2**64 - 1)
            if top_level and sel_limit < 2**64 - 1 and getattr(stmt, "limit", None) is None:
                # plant a real Limit node so execution stops early instead of
                # materializing the full result and slicing (ref: planbuilder
                # sql_select_limit handling)
                from ..planner.plans import Limit as _LimitPlan

                plan = _LimitPlan(plan, sel_limit)
            ex = build_executor(plan, ctx)
            if getattr(self, "_trace_collect", False):
                # TRACE hook: instrument THIS (fully gated) execution rather
                # than re-running the select outside the normal path
                from ..executor.runtime_stats import attach_runtime_stats

                self._trace_result = (ex, attach_runtime_stats(ex))
            chunk = drain(ex)
        finally:
            if route_store is not None:
                router.release(route_store)
        names = [c.name for c in plan.out_cols]
        rs = ResultSet(names, chunk)
        outfile = getattr(stmt, "into_outfile", None)
        if outfile is not None:
            return self._write_outfile(rs, stmt)
        return rs

    def _write_outfile(self, rs: ResultSet, stmt) -> ResultSet:
        """SELECT INTO OUTFILE (ref: executor/select_into.go): tab/newline
        separated, NULL as \\N, file must not already exist."""
        import os

        from ..utils import sem

        sem.check_file_access()

        path = stmt.into_outfile
        if os.path.exists(path):
            raise TiDBError(f"File {path!r} already exists")
        fsep, lsep = stmt.outfile_fsep, stmt.outfile_lsep

        def esc(v: str) -> str:
            # ESCAPED BY '\\' defaults: backslash first, then separators,
            # so a literal "\N" can never collide with the NULL marker
            v = v.replace("\\", "\\\\")
            if fsep:
                v = v.replace(fsep, "\\" + fsep)
            if lsep:
                v = v.replace(lsep, "\\" + lsep)
            return v

        n = 0
        with open(path, "w", encoding="utf8") as f:
            for row in rs.rows():
                f.write(fsep.join("\\N" if v is None else esc(v) for v in row))
                f.write(lsep)
                n += 1
        return ResultSet([], None, affected=n)

    # --------------------------------------------------- prepared statements

    @staticmethod
    def _count_params(node) -> int:
        """Max '?' ordinal in a statement AST (+1)."""
        import dataclasses

        best = 0

        def walk(x):
            nonlocal best
            if isinstance(x, ast.Param):
                best = max(best, x.index + 1)
            elif dataclasses.is_dataclass(x) and not isinstance(x, type):
                for f in dataclasses.fields(x):
                    walk(getattr(x, f.name))
            elif isinstance(x, (list, tuple)):
                for i in x:
                    walk(i)

        walk(node)
        return best

    def _run_prepare(self, stmt: ast.Prepare) -> ResultSet:
        sql = stmt.sql
        if stmt.from_var is not None:  # PREPARE name FROM @var
            c = self.user_vars.get(stmt.from_var)
            if c is None or c.value.is_null:
                raise TiDBError(f"user variable {stmt.from_var} holds no statement")
            sql = c.value.to_str()
        parsed = parse_one(sql)
        self.prepared[stmt.name] = (sql, parsed, self._count_params(parsed))
        return ResultSet([], None)

    def _run_execute(self, stmt: ast.Execute) -> ResultSet:
        """EXECUTE name [USING @a, ...] (ref: session.go:2042
        ExecutePreparedStmt): binds typed user-var Constants onto the
        stored AST's '?' placeholders and runs it. The planner re-runs
        per execution (it is microseconds); the expensive device programs
        are reused through the DAG-digest jit cache."""
        ent = self.prepared.get(stmt.name)
        if ent is None:
            raise TiDBError(f"Unknown prepared statement handler ({stmt.name})")
        sql, parsed, n_params = ent
        params = []
        for ref in stmt.using:
            c = self.user_vars.get(ref.lower())
            if c is None:
                params.append(Constant(Datum.null(), ft_varchar()))
            else:
                params.append(c)
        if len(params) != n_params:
            raise TiDBError(
                f"Incorrect arguments to EXECUTE: statement needs {n_params}, got {len(params)}"
            )
        self._exec_params = params
        prev_prep = self._active_prep
        self._active_prep = parsed
        try:
            return self._execute_stmt(parsed)
        finally:
            self._exec_params = None
            self._active_prep = prev_prep

    def execute_prepared_ast(self, parsed, params: list, sql: str | None = None) -> ResultSet:
        """Wire-protocol COM_STMT_EXECUTE entry: run a pre-parsed
        statement with bound Constant parameters (ref: conn_stmt.go
        handleStmtExecute → session ExecutePreparedStmt).

        Routed through `_execute_parsed` so binary-protocol statements
        get the SAME lifecycle as COM_QUERY text: statement savepoint,
        mem tracker, KILL/deadline gate, metrics/trace, and — critically
        — AUTOCOMMIT. The old direct `_execute_stmt` call never ran
        `_finish_stmt`, so a wire prepared INSERT left its autocommit
        txn open (unsynced — no durability point) until some later text
        statement happened to close it. `sql` is the prepare-time text,
        used for logs/digests; parameterized SELECTs hit the
        statement-id prepared-plan cache (`_prepared_plan_for`)."""
        self._exec_params = params
        prev_prep = self._active_prep
        self._active_prep = parsed
        try:
            return self._execute_parsed(parsed, sql)
        finally:
            self._exec_params = None
            self._active_prep = prev_prep

    def _run_subquery(self, select_ast):
        rs = self.run_select(select_ast)
        rows = [rs.chunk.get_row(i) for i in range(rs.chunk.num_rows)]
        return rows, rs.chunk.field_types()

    # ------------------------------------------------------------------- DML

    # ------------------------------------------------------------ sequences

    # ------------------------------------------------- resource control

    def _run_resource_group_ddl(self, stmt: ast.ResourceGroupDDL) -> ResultSet:
        """CREATE/ALTER/DROP RESOURCE GROUP → the store-wide group table
        (ref: ddl_api.go CreateResourceGroup; persisted like bindinfo,
        effective for every session over the store on next admission)."""
        mgr = self.store.sched.groups
        if stmt.kind == "create":
            mgr.create(stmt.name, stmt.spec, if_not_exists=stmt.if_not_exists)
        elif stmt.kind == "alter":
            mgr.alter(stmt.name, stmt.spec)
        else:
            # sessions still bound to the dropped name degrade to the
            # default group at their next admission (manager.get fallback)
            mgr.drop(stmt.name, if_exists=stmt.if_exists)
        return ResultSet([], None)

    def _run_set_resource_group(self, stmt: ast.SetResourceGroup) -> ResultSet:
        name = stmt.name.lower()
        if not self.store.sched.groups.exists(name):
            raise ResourceGroupNotExists(f"resource group '{name}' does not exist")
        self.vars["tidb_resource_group"] = name
        return ResultSet([], None)

    def _ddl_create_sequence(self, stmt: ast.CreateSequence) -> ResultSet:
        """CREATE SEQUENCE (ref: docs/design/2020-04-17-sql-sequence.md;
        cached-batch allocation is the design's headline throughput
        lever)."""
        db = stmt.table.db or self.current_db
        if stmt.increment == 0:
            raise TiDBError("INCREMENT must not be 0")
        if stmt.cycle:
            raise TiDBError("CYCLE sequences are not supported")
        txn = self._ddl_txn()
        m = Meta(txn)
        if m.db(db) is None:
            txn.rollback()
            raise UnknownDatabase(f"unknown database {db!r}")
        if m.sequence(db, stmt.table.name) is not None:
            txn.rollback()
            if stmt.if_not_exists:
                return ResultSet([], None)
            raise TiDBError(f"sequence {stmt.table.name!r} already exists")
        # sequences share the table namespace (ErrTableExists behavior)
        if m.view(db, stmt.table.name) is not None:
            txn.rollback()
            raise TableExists(f"a view named {stmt.table.name!r} already exists (shared namespace)")
        try:
            self.infoschema().table(db, stmt.table.name)
            txn.rollback()
            raise TableExists(f"table {stmt.table.name!r} already exists")
        except (UnknownTable, UnknownDatabase):
            pass
        m.put_sequence({
            "db": db.lower(), "name": stmt.table.name.lower(),
            "start": stmt.start, "increment": stmt.increment,
            "cache": max(stmt.cache, 1), "maxvalue": stmt.maxvalue,
            "minvalue": stmt.minvalue, "next": stmt.start,
        })
        txn.commit()
        return ResultSet([], None)

    def _ddl_drop_sequence(self, stmt: ast.DropSequence) -> ResultSet:
        for tn in stmt.names:
            db = tn.db or self.current_db
            txn = self._ddl_txn()
            m = Meta(txn)
            if m.sequence(db, tn.name) is None:
                txn.rollback()
                if stmt.if_exists:
                    continue
                raise TiDBError(f"Unknown SEQUENCE: '{db}.{tn.name}'")
            m.drop_sequence(db, tn.name)
            txn.commit()
            self._seq_cache.pop((db.lower(), tn.name.lower()), None)
            self._bump_seq_gen()
        return ResultSet([], None)

    def _retry_meta_txn(self, fn, what: str):
        """Run fn(txn, meta) in its own small txn, retrying on write
        conflicts (the shared idiom under auto-id and sequence
        allocation; ref: meta/autoid)."""
        for _ in range(8):
            txn = self.store.begin()
            try:
                out = fn(txn, Meta(txn))
                txn.commit()
                return out
            except (WriteConflict, RetryableError):
                continue
            except Exception:
                txn.rollback()
                raise
        raise RetryableError(f"{what} kept conflicting")

    # --------------------------------------------------------------- views

    def _ddl_create_view(self, stmt: ast.CreateView) -> ResultSet:
        """CREATE [OR REPLACE] VIEW: the definition is stored as SQL text
        and re-planned at reference time against the CURRENT schema (ref:
        ddl/ddl_api.go CreateView; TiDB stores the select as ViewInfo)."""
        db = stmt.table.db or self.current_db
        # the definition must plan NOW so broken views fail at CREATE —
        # in the VIEW's own database — and an explicit column list must
        # match its arity
        vbuilder = self._builder()
        vbuilder.db = db
        plan = optimize(vbuilder.build_select(parse_one(stmt.select_sql)), self.store.stats, self.vars)
        if stmt.cols and len(stmt.cols) != len(plan.out_cols):
            raise TiDBError(
                f"view {stmt.table.name!r} column list does not match its definition")
        txn = self._ddl_txn()
        m = Meta(txn)
        dbi = m.db(db)
        if dbi is None:
            txn.rollback()
            raise UnknownDatabase(f"unknown database {db!r}")
        if m.view(db, stmt.table.name) is not None and not stmt.or_replace:
            txn.rollback()
            raise TableExists(f"view {stmt.table.name!r} already exists")
        # table/sequence clash checks run INSIDE the DDL txn so a racing
        # CREATE TABLE conflicts instead of slipping past a stale snapshot
        for tid in dbi.table_ids:
            t = m.table(tid)
            if t and t.name.lower() == stmt.table.name.lower():
                txn.rollback()
                raise TableExists(f"table {stmt.table.name!r} already exists")
        if m.sequence(db, stmt.table.name) is not None:
            txn.rollback()
            raise TableExists(
                f"a sequence named {stmt.table.name!r} already exists (shared namespace)")
        m.put_view({
            "db": db.lower(), "name": stmt.table.name.lower(),
            "cols": list(stmt.cols), "sql": stmt.select_sql,
        })
        m.bump_schema_version()
        txn.commit()
        return ResultSet([], None)

    def _ddl_drop_view(self, stmt: ast.DropView) -> ResultSet:
        for tn in stmt.names:
            db = tn.db or self.current_db
            txn = self._ddl_txn()
            m = Meta(txn)
            if m.view(db, tn.name) is None:
                txn.rollback()
                if stmt.if_exists:
                    continue
                raise UnknownTable(f"view {db}.{tn.name} doesn't exist")
            m.drop_view(db, tn.name)
            m.bump_schema_version()
            txn.commit()
        return ResultSet([], None)

    @property
    def _seq_gen(self) -> int:
        return getattr(self.store, "seq_generation", 0)

    def _bump_seq_gen(self) -> None:
        """Invalidate EVERY session's cached sequence batches (drops and
        drop-database must not let other sessions keep serving values
        from a dropped or recreated sequence)."""
        self.store.seq_generation = self._seq_gen + 1

    def sequence_op(self, op: str, db: str, name: str, arg: int | None = None):
        """NEXTVAL/LASTVAL/SETVAL runtime hook. NEXTVAL serves from a
        session-cached batch; one meta txn claims `cache` values at a
        time (the design doc's 1000-value default is what makes the
        published ~3000 TPS number reachable)."""
        key = (db.lower(), name.lower())
        if op == "lastval":
            return self._seq_last.get(key)
        if op == "setval":
            def do(txn, m):
                d = m.sequence(db, name)
                if d is None:
                    raise TiDBError(f"Unknown SEQUENCE: '{db}.{name}'")
                d["next"] = int(arg) + d["increment"]
                m.put_sequence(d)
                return int(arg)

            out = self._retry_meta_txn(do, "SETVAL")
            self._seq_cache.pop(key, None)
            return out
        cache = self._seq_cache.get(key)
        # exhaustion must be >= / <= — a MAXVALUE-clamped batch end need
        # not land exactly on the increment stride; a stale generation
        # means some session dropped/recreated a sequence
        if (
            cache is None
            or cache[3] != self._seq_gen
            or (cache[0] >= cache[1] if cache[2] > 0 else cache[0] <= cache[1])
        ):
            cache = self._seq_claim_batch(db, name, key)
        v = cache[0]
        cache[0] += cache[2]
        self._seq_last[key] = v
        return v

    def _seq_claim_batch(self, db: str, name: str, key) -> list:
        gen = self._seq_gen

        def do(txn, m):
            d = m.sequence(db, name)
            if d is None:
                raise TiDBError(f"Unknown SEQUENCE: '{db}.{name}'")
            inc = d["increment"]
            first = d["next"]
            bound = d.get("maxvalue") if inc > 0 else d.get("minvalue")
            if bound is not None and (first > bound if inc > 0 else first < bound):
                raise TiDBError(f"Sequence '{db}.{name}' has run out")
            n_vals = d["cache"]
            if bound is not None:
                # stride-aligned clamp: only whole steps up to the bound
                n_vals = min(n_vals, abs(bound - first) // abs(inc) + 1)
            end = first + inc * n_vals
            d["next"] = end
            m.put_sequence(d)
            return [first, end, inc, gen]

        cache = self._retry_meta_txn(do, "sequence allocation")
        self._seq_cache[key] = cache
        return cache

    def alloc_auto_id(self, tinfo: TableInfo, n: int) -> int:
        """Batched auto-id allocation in its own small txn (ref: meta/autoid)."""
        if getattr(tinfo, "temporary", False):
            # session-private object: no cross-session contention to guard
            first = tinfo.auto_inc_id
            tinfo.auto_inc_id += n
            return first

        def do(txn, m):
            t = m.table(tinfo.id)
            first = t.auto_inc_id
            t.auto_inc_id += n
            m.put_table(t)
            tinfo.auto_inc_id = t.auto_inc_id
            return first

        return self._retry_meta_txn(do, "auto-id allocation")

    def _rebase_auto_id(self, tinfo: TableInfo, v: int) -> None:
        """Bump the allocator past an explicitly-inserted auto value
        (ref: meta/autoid alloc.go Rebase)."""
        if getattr(tinfo, "temporary", False):
            tinfo.auto_inc_id = max(tinfo.auto_inc_id, v + 1)
            return
        if tinfo.auto_inc_id > v:
            return  # cheap pre-check on the cached counter

        def do(txn, m):
            t = m.table(tinfo.id)
            if t.auto_inc_id <= v:
                t.auto_inc_id = v + 1
                m.put_table(t)
            tinfo.auto_inc_id = t.auto_inc_id
            return None

        self._retry_meta_txn(do, "auto-id rebase")

    @staticmethod
    def _next_in_series(base: int, inc: int, off: int) -> int:
        """Smallest v >= base with v ≡ offset (mod increment) — MySQL's
        AUTO_INCREMENT series under auto_increment_increment/offset."""
        if base <= off:
            return off
        return off + -((off - base) // inc) * inc

    def _alloc_auto_series(self, tinfo: TableInfo, inc: int, off: int) -> int:
        """Allocate the next id in the (offset, increment) series (ref:
        meta/autoid + MySQL multi-master interleave semantics)."""
        if getattr(tinfo, "temporary", False):
            nxt = self._next_in_series(tinfo.auto_inc_id, inc, off)
            tinfo.auto_inc_id = nxt + 1
            return nxt

        def do(txn, m):
            t = m.table(tinfo.id)
            nxt = self._next_in_series(t.auto_inc_id, inc, off)
            t.auto_inc_id = nxt + 1
            m.put_table(t)
            tinfo.auto_inc_id = t.auto_inc_id
            return nxt

        return self._retry_meta_txn(do, "auto-id allocation")

    def _eval_insert_value(self, node, col: ColumnInfo) -> Datum:
        if isinstance(node, ast.Default) or node is None:
            return self._default_datum(col)
        if isinstance(node, ast.Lit):
            c = lit_to_constant(node)
            return self._cast_datum(c.value, col.ft)
        # general expression with no column refs
        c = self._eval_const_expr(node)
        return self._cast_datum(c.value, col.ft)

    def _default_datum(self, col: ColumnInfo) -> Datum:
        if col.auto_increment:
            return Datum.null()  # filled by allocator
        if col.has_default and col.default is not None:
            return self._cast_datum(Datum.s(str(col.default)), col.ft)
        return Datum.null()

    def _cast_datum(self, d: Datum, ft: FieldType) -> Datum:
        """Insert-time coercion to the column type (ref: table/column.go CastValue)."""
        if d.is_null:
            return d
        if ft.is_time():
            from ..mysqltypes.datum import K_INT, K_TIME, K_UINT
            from ..mysqltypes.coretime import number_to_datetime

            if d.kind == K_TIME:
                return Datum.t(d.val)
            if d.kind in (K_INT, K_UINT):
                p = number_to_datetime(d.val)
                if p is None:
                    raise TiDBError(f"incorrect datetime value {d.val!r}")
                return Datum.t(p)
            p = parse_datetime(d.to_str())
            if p is None:
                raise TiDBError(f"incorrect datetime value {d.to_str()!r}")
            return Datum.t(p)
        if ft.is_decimal():
            return Datum.d(d.to_dec().rescale(max(ft.decimal, 0)))
        if ft.is_float():
            return Datum.f(d.to_float())
        if ft.is_int():
            return Datum.u(d.to_int()) if ft.is_unsigned else Datum.i(d.to_int())
        if ft.tp == TypeCode.Duration:
            from ..mysqltypes.datum import Datum as _D, K_DUR, K_INT, K_UINT
            from ..mysqltypes.coretime import parse_duration

            if d.kind == K_DUR:
                return d
            if d.kind in (K_INT, K_UINT):  # HHMMSS number form
                v = abs(d.val)
                us = ((v // 10000) * 3600 + ((v // 100) % 100) * 60 + v % 100) * 1_000_000
                return _D(K_DUR, -us if d.val < 0 else us)
            us = parse_duration(d.to_str())
            if us is None:
                raise TiDBError(f"incorrect time value {d.to_str()!r}")
            return _D(K_DUR, us)
        if ft.tp == TypeCode.Enum:
            s = d.to_str()
            low = [e.lower() for e in ft.elems]
            if s.lower() in low:
                return Datum.s(ft.elems[low.index(s.lower())])
            if d.kind in (1, 2):  # numeric index, 1-based
                i = d.to_int()
                if 1 <= i <= len(ft.elems):
                    return Datum.s(ft.elems[i - 1])
            raise TiDBError(f"data truncated: {s!r} not in ENUM{ft.elems}")
        if ft.tp == TypeCode.Set:
            s = d.to_str()
            low = [e.lower() for e in ft.elems]
            members = []
            for part in (p for p in s.split(",") if p != ""):
                if part.lower() not in low:
                    raise TiDBError(f"data truncated: {part!r} not in SET{ft.elems}")
                canon = ft.elems[low.index(part.lower())]
                if canon not in members:
                    members.append(canon)
            members.sort(key=lambda x: ft.elems.index(x))  # SET normalizes order
            return Datum.s(",".join(members))
        if ft.tp == TypeCode.JSON:
            import json as _json

            try:
                obj = _json.loads(d.to_str())
            except ValueError:
                raise TiDBError(f"invalid JSON text: {d.to_str()[:64]!r}")
            return Datum.s(_json.dumps(obj))
        if ft.is_string():
            return Datum.s(d.to_str())
        return d

    def _run_insert(self, stmt: ast.Insert) -> ResultSet:
        info = self.infoschema().table(stmt.table.db or self.current_db, stmt.table.name)
        self._tlock_write(info)
        tbl = Table(info)
        txn = self._active_txn()
        visible = info.visible_columns()
        if stmt.columns:
            name_to_col = {c.name.lower(): c for c in visible}
            target = [name_to_col.get(c.lower()) or info.col_by_name(c) for c in stmt.columns]
        else:
            target = visible

        rows_sources: list[list] = []
        if stmt.select is not None:
            rs = self.run_select(stmt.select)
            for i in range(rs.chunk.num_rows):
                rows_sources.append(rs.chunk.get_row(i))
        else:
            rows_sources = stmt.values

        all_datums = []
        for vals in rows_sources:
            if len(vals) != len(target):
                raise TiDBError("Column count doesn't match value count")
            datums = [self._default_datum(c) for c in visible]
            for col, v in zip(target, vals):
                if isinstance(v, Datum):
                    datums[col.offset] = self._cast_datum(v, col.ft)
                else:
                    datums[col.offset] = self._eval_insert_value(v, col)
            all_datums.append(datums)
        if txn.pessimistic and all_datums:
            self._lock_insert_keys(tbl, txn, all_datums)
        affected = 0
        delta = 0  # net row-count change (upserts affect 2 but add 0)
        on_dup_cache: dict = {}  # per-statement compiled ON DUP assignments
        # ONE batched id allocation for the whole statement — per-row
        # allocation runs a meta txn (prewrite+commit) PER ROW, which is
        # the difference between 1k and 100k+ rows/s on bulk VALUES
        # (ref: meta/autoid batched allocator, alloc.go Alloc n>1)
        auto_col = next((c for c in info.columns if c.auto_increment), None)
        inc = int(self.vars.get("auto_increment_increment", "1"))
        aoff = int(self.vars.get("auto_increment_offset", "1"))
        n_auto = 0
        if auto_col is not None:
            # explicit auto-column values rebase the allocator first so a
            # later NULL row in this (or any) statement can't collide
            # (ref: meta/autoid alloc.go Rebase)
            explicit = [
                d[auto_col.offset].to_int() for d in all_datums
                if not d[auto_col.offset].is_null
            ]
            if explicit:
                self._rebase_auto_id(info, max(explicit))
            if inc == 1 and aoff == 1:
                n_auto = sum(1 for d in all_datums if d[auto_col.offset].is_null)
        n_handle = 0 if info.pk_is_handle else len(all_datums)
        alloc = None
        if n_auto + n_handle > 1:
            base = self.alloc_auto_id(info, n_auto + n_handle)
            alloc = iter(range(base, base + n_auto + n_handle))
        # MySQL: multi-row INSERT reports the FIRST generated id
        self._liid_locked = False
        for datums in all_datums:
            a, d = self._insert_row(tbl, txn, datums, stmt, on_dup_cache,
                                    alloc=alloc, inc=inc, aoff=aoff, auto_col=auto_col)
            affected += a
            delta += d
        self._invalidate_tiles(info)
        self._note_delta(info.id, affected, delta)
        return ResultSet([], None, affected=affected, last_insert_id=self.last_insert_id)

    def _note_liid(self, gen_id) -> None:
        """Record the statement's FIRST landed auto id (MySQL rule)."""
        if gen_id is not None and not getattr(self, "_liid_locked", False):
            self.last_insert_id = gen_id
            self._liid_locked = True

    def _insert_row(self, tbl: Table, txn, datums: list[Datum], stmt, on_dup_cache: dict,
                    alloc=None, inc: int = 1, aoff: int = 1, auto_col=None) -> tuple[int, int]:
        """Insert one row; returns (affected_rows, net_row_delta). `alloc`
        is a statement-level pre-allocated id iterator (one meta txn per
        STATEMENT, not per row); inc/aoff/auto_col come from the statement."""
        info = tbl.info
        # handle: clustered int pk or auto rowid
        handle = None
        gen_id = None  # generated auto id — reported only if the row lands
        if auto_col is None:
            auto_col = next((c for c in info.columns if c.auto_increment), None)
        if auto_col is not None and datums[auto_col.offset].is_null:
            if inc > 1 or aoff > 1:
                v = self._alloc_auto_series(info, inc, aoff)
            elif alloc is not None:
                v = next(alloc)
            else:
                v = self.alloc_auto_id(info, 1)
            datums[auto_col.offset] = Datum.i(v)
            gen_id = v
        if info.pk_is_handle:
            pk = next(i for i in info.indexes if i.primary)
            handle = datums[pk.col_offsets[0]].to_int()
        elif alloc is not None:
            handle = next(alloc)
        else:
            handle = self.alloc_auto_id(info, 1)
        for c in info.visible_columns():
            if c.ft.not_null and datums[c.offset].is_null:
                raise TiDBError(f"Column '{c.name}' cannot be null")
        if info.partition is not None:
            tbl = self._phys_table(info, datums)  # partition keyspace
        conflicts = self._conflicting_handles(tbl, txn, datums, handle)
        if conflicts:
            if getattr(stmt, "on_dup", None):
                return self._on_dup_update(tbl, txn, stmt, datums, conflicts[0], handle, on_dup_cache, info)
            if getattr(stmt, "replace", False):
                # REPLACE deletes EVERY row that conflicts on pk or any
                # unique index, then inserts (MySQL semantics)
                removed = 0
                for h in conflicts:
                    old = self._row_by_handle(tbl, txn, h)
                    if old is not None:
                        tbl.remove_record(txn, h, old)
                        removed += 1
                tbl.add_record(txn, datums, handle, check_dup=False)
                self._note_liid(gen_id)  # REPLACE inserted the row
                return 1 + len(conflicts), 1 - removed
            if getattr(stmt, "ignore", False):
                return 0, 0
            raise DuplicateEntry(f"Duplicate entry in '{info.name}'")
        tbl.add_record(txn, datums, handle)
        # MySQL: LAST_INSERT_ID() is the FIRST id generated for a row
        # that was actually INSERTED (IGNOREd rows don't count)
        self._note_liid(gen_id)
        return 1, 1

    def _lock_insert_keys(self, tbl: Table, txn, rows: list[list[Datum]]) -> None:
        """Pessimistic INSERT locks, batched per statement: explicit-pk
        record keys (racing same-pk inserts serialize) and public unique
        index keys (racing same-unique-value inserts serialize) — one TSO
        fetch + one acquisition round for the whole statement."""
        info = tbl.info
        pk = next((i for i in info.indexes if i.primary), None) if info.pk_is_handle else None
        keys: list[bytes] = []
        for datums in rows:
            t = self._phys_table(info, datums) if info.partition is not None else tbl
            if pk is not None and not datums[pk.col_offsets[0]].is_null:
                keys.append(t.record_key(datums[pk.col_offsets[0]].to_int()))
            full = t.row_datums_with_hidden(datums, 0)
            for idx in info.indexes:
                if not idx.unique or (info.pk_is_handle and idx.primary) or idx.state != "public":
                    continue
                key, _, distinct = t.index_value_key(idx, full, None)
                if distinct:
                    keys.append(key)
        txn.lock_keys_for_update(keys)

    def _phys_table(self, info: TableInfo, datums) -> Table:
        """Physical Table for one row: the located partition's keyspace,
        or the table itself (ref: tables/partition.go locatePartition)."""
        if info.partition is None:
            return Table(info)
        pcol = info.col_by_name(info.partition.col)
        d = datums[pcol.offset]
        pd = info.partition.locate(None if d.is_null else d.to_int())
        return Table(info.partition_physical(pd.id))

    def _rewrite_row(self, info: TableInfo, txn, ptbl: Table, handle: int, old, new) -> None:
        """Apply an UPDATE to one row, re-keying the record when the
        clustered pk (== handle) or the target partition changed — an
        in-place overwrite would leave the row under a key encoding the
        OLD pk (ref: executor/update.go updateRecord's handle-changed
        remove+add path)."""
        new_handle = handle
        if info.pk_is_handle:
            pk = next(i for i in info.indexes if i.primary)
            new_handle = new[pk.col_offsets[0]].to_int()
        dst = self._phys_table(info, new) if info.partition is not None else ptbl
        if new_handle == handle and dst.info.id == ptbl.info.id:
            ptbl.update_record(txn, handle, old, new)
            return
        ptbl.remove_record(txn, handle, old)
        dst.add_record(txn, new, new_handle)  # check_dup guards the new key

    def _invalidate_tiles(self, info: TableInfo) -> None:
        for pid in info.physical_ids():
            self.cop.tiles.invalidate_table(pid)

    def _read_for_write(self, txn, key: bytes):
        """Existence read for write-conflict checks: pessimistic txns must
        see the LATEST committed value (current read at for_update_ts),
        not their start_ts snapshot; the membuffer always wins."""
        if key in txn.membuf:
            v = txn.membuf[key]
            return None if v == TOMBSTONE else v
        if txn.pessimistic:
            return self.store.snapshot(txn.for_update_ts).get(key)
        return txn.snapshot.get(key)

    def _on_dup_update(
        self, tbl: Table, txn, stmt, new_datums, handle: int, new_handle: int, cache: dict,
        linfo: TableInfo | None = None,
    ) -> tuple[int, int]:
        """INSERT ... ON DUPLICATE KEY UPDATE (ref: executor/insert.go
        onDuplicateUpdate): assignments evaluate over the EXISTING row,
        with VALUES(col) resolving to the would-be inserted value.
        Affected rows: 2 if changed, 0 if set to current values.

        Assignment expressions compile ONCE per statement (`cache`):
        VALUES(col) rewrites to a pseudo-column appended after the table's
        columns, so the same compiled expr evaluates every duplicate row;
        user '?' placeholders resolve normally from _exec_params."""
        from ..planner.plans import PlanCol

        info = tbl.info
        old = self._row_by_handle(tbl, txn, handle)
        if old is None and txn.pessimistic:
            # the conflict was found by a current read; fetch the row there
            raw = self._read_for_write(txn, tbl.record_key(handle))
            if raw is not None:
                old = tbl.decode_record(raw)
        if old is None:
            # conflicting row vanished underneath us: plain insert, under
            # the NEW row's own handle (the stale conflicting handle may
            # come from a dangling unique entry and must not be reused);
            # check_dup=False lets the write reclaim that dangling entry
            tbl.add_record(txn, new_datums, new_handle, check_dup=False)
            return 1, 1
        visible = info.visible_columns()
        if "exprs" not in cache:
            vpfx = "__values__"
            scope = NameScope(
                [PlanCol(c.name, c.ft, info.name) for c in visible]
                + [PlanCol(vpfx + c.name, c.ft, info.name) for c in visible]
            )

            def subst(node):
                if isinstance(node, ast.Call):
                    if (
                        node.name.lower() == "values"
                        and len(node.args) == 1
                        and isinstance(node.args[0], ast.Name)
                    ):
                        col = info.col_by_name(node.args[0].column)
                        return ast.Name((vpfx + col.name,))
                    return ast.Call(node.name, [subst(a) for a in node.args], node.distinct)
                if isinstance(node, ast.CaseWhen):
                    return ast.CaseWhen(
                        subst(node.operand) if node.operand is not None else None,
                        [(subst(c), subst(r)) for c, r in node.whens],
                        subst(node.else_) if node.else_ is not None else None,
                    )
                if isinstance(node, ast.Cast):
                    import copy as _copy

                    n2 = _copy.copy(node)
                    n2.expr = subst(node.expr)
                    return n2
                if isinstance(node, ast.Interval):
                    return ast.Interval(subst(node.expr), node.unit)
                return node

            cache["exprs"] = [
                (info.col_by_name(cname), self._builder().to_expr(subst(e_ast), scope))
                for cname, e_ast in stmt.on_dup
            ]
        fts = [c.ft for c in visible] * 2
        updated = list(old)
        changed = False
        for col, e in cache["exprs"]:
            # MySQL evaluates assignments left-to-right: later ones see
            # earlier updated values
            row = [updated[c.offset] for c in visible] + [new_datums[c.offset] for c in visible]
            chunk = Chunk.from_datum_rows(fts, [row])
            d, v = e.eval(chunk)
            d = np.atleast_1d(np.asarray(d))
            v = np.atleast_1d(np.asarray(v))
            nv = self._cast_datum(Column(e.ret_type, d[:1], v[:1]).get_datum(0), col.ft) if v[0] else Datum.null()
            if repr(nv) != repr(updated[col.offset]):
                changed = True
            updated[col.offset] = nv
        if changed:
            self._rewrite_row(linfo or tbl.info, txn, tbl, handle, old, updated)
            return 2, 0
        return 0, 0

    def _conflicting_handles(self, tbl: Table, txn, datums, handle: int) -> list[int]:
        """Handles of existing rows this insert collides with (pk + every
        public unique index)."""
        info = tbl.info
        out = []
        if info.pk_is_handle and self._read_for_write(txn, tbl.record_key(handle)) is not None:
            out.append(handle)
        full = tbl.row_datums_with_hidden(datums, handle)
        for idx in info.indexes:
            if not idx.unique or (info.pk_is_handle and idx.primary) or idx.state != "public":
                continue
            key, _, distinct = tbl.index_value_key(idx, full, None)
            if not distinct:
                continue  # NULL-bearing unique keys never conflict
            existing = self._read_for_write(txn, key)
            if existing:
                h = int(existing)
                if h not in out:
                    out.append(h)
        return out

    def _row_by_handle(self, tbl: Table, txn, handle: int):
        raw = txn.get(tbl.record_key(handle))
        if raw is None:
            return None
        return tbl.decode_record(raw)

    def _scan_matching_rows(self, stmt_table, where):
        """Shared UPDATE/DELETE row collection, returning
        (table, [(handle, datums)]). Point-handle fast path: when the
        WHERE clause pins the clustered int pk to literal value(s) (the
        OLTP `UPDATE ... WHERE id = ?` shape), only those handles are
        fetched — the same ranger detachment the SELECT point path uses
        (tools/bench_serve.py exposed the full scan: a point UPDATE on
        an 8K-row table decoded and filtered all 8192 rows in Python,
        ~500ms/stmt). Everything else takes the full scan + filter as
        before; the FULL condition is always re-evaluated on fetched
        rows, so residual predicates keep their semantics."""
        info = self.infoschema().table(stmt_table.db or self.current_db, stmt_table.name)
        self._tlock_write(info)
        tbl = Table(info)
        txn = self._active_txn()
        builder = self._builder()
        cond = None
        if where is not None:
            from ..planner.plans import PlanCol

            scope = NameScope([PlanCol(c.name, c.ft, stmt_table.alias or info.name) for c in info.visible_columns()])
            cond = builder.to_expr(where, scope)

        def matches(datums) -> bool:
            if cond is None:
                return True
            visible = [datums[c.offset] for c in info.visible_columns()]
            chunk = Chunk.from_datum_rows([c.ft for c in info.visible_columns()], [visible])
            d, valid = cond.eval(chunk)
            return bool(valid[0] and d[0] != 0)

        point_handles = None
        if cond is not None and info.partition is None:
            from ..planner import ranger

            ha = ranger.detach_pk_handle_access(info, builder.split_cnf(cond))
            if ha is not None and ha.point_handles is not None:
                point_handles = ha.point_handles

        rows = []
        if point_handles is not None:
            # point fetch, membuffer-merged; pessimistic DML reads
            # CURRENT (fresh for_update_ts), mirroring scan_current
            keys = [tbl.record_key(h) for h in point_handles]
            if txn.pessimistic:
                txn.for_update_ts = self.store.tso.next()
                snap = self.store.snapshot(txn.for_update_ts)
            else:
                snap = txn.snapshot
            fetch = [k for k in keys if k not in txn.membuf]
            fetched = snap.batch_get(fetch) if fetch else {}
            for h, k in zip(point_handles, keys):
                v = txn.membuf.get(k, None)
                if v == TOMBSTONE:
                    continue
                if v is None:
                    v = fetched.get(k)
                if v is None:
                    continue
                datums = tbl.decode_record(v)
                if matches(datums):
                    rows.append((tbl, h, datums))
        else:
            kvs = []  # (phys_tbl, key, value) across every partition keyspace
            for pid in info.physical_ids():
                ptbl = Table(info.partition_physical(pid)) if info.partition else tbl
                prefix = tablecodec.record_prefix(pid)
                if txn.pessimistic:
                    # pessimistic DML scans with a CURRENT read (fresh
                    # for_update_ts) so rows that started matching after
                    # start_ts are found and locked, not just re-filtered
                    part = txn.scan_current(prefix, prefix_next(prefix))
                else:
                    part = txn.scan(prefix, prefix_next(prefix))
                kvs.extend((ptbl, k, v) for k, v in part)
            for ptbl, k, v in kvs:
                handle = tablecodec.decode_record_handle(k)
                datums = ptbl.decode_record(v)
                if matches(datums):
                    rows.append((ptbl, handle, datums))

        if txn.pessimistic and rows:
            # pessimistic "current read" (ref: executor/adapter.go:588
            # handlePessimisticDML + client-go for_update_ts): lock the
            # matched rows, then recompute from the LATEST committed values
            # so concurrent committed updates are not lost
            keys = [t.record_key(h) for t, h, _ in rows]
            txn.lock_keys_for_update(keys)
            snap = self.store.snapshot(txn.for_update_ts)
            fresh = snap.batch_get([k for k in keys if k not in txn.membuf])
            cur_rows = []
            for (t, h, _), k in zip(rows, keys):
                if k in txn.membuf:
                    v = txn.membuf[k]
                    if v == TOMBSTONE:
                        continue
                else:
                    v = fresh.get(k)
                    if v is None:
                        continue  # deleted underneath us
                datums = t.decode_record(v)
                if matches(datums):  # re-filter on current values
                    cur_rows.append((t, h, datums))
            rows = cur_rows
        return info, tbl, txn, rows

    # ------------------------------------------------- multi-table DML

    @staticmethod
    def _dml_leaves(node) -> dict:
        """alias(lower) → ast.TableName for every base-table leaf of a
        FROM tree (subquery sources are joinable but not DML targets)."""
        leaves: dict = {}

        def walk(n):
            if isinstance(n, ast.Join):
                walk(n.left)
                walk(n.right)
            elif isinstance(n, ast.TableName):
                leaves[(n.alias or n.name).lower()] = n

        walk(node)
        return leaves

    def _dml_join_select(self, from_ast, where, fields, expose: set, read_ts: int):
        """Run the DML row-collection join: SELECT <fields> FROM <refs>
        WHERE <cond> with hidden handles exposed; returns the Chunk (ref:
        the reference plans multi-table DML as a select whose schema is
        extended with per-table handle columns — planbuilder.go
        buildUpdate/buildDelete)."""
        sel = ast.Select(fields=fields, from_=from_ast, where=where)
        builder = self._builder(expose_rowid=expose)
        plan = builder.build_select(sel)
        plan = optimize(plan, self.store.stats, self.vars)
        ctx = ExecContext(
            self.cop, read_ts, engine="host", vars=self.vars, txn=self.txn
        )
        return drain(build_executor(plan, ctx))

    def _dml_collect(self, stmt, fields, expose: set, txn, keys_of):
        """Collection pass for multi-table DML. Optimistic: one snapshot
        read at start_ts. Pessimistic: current read at a fresh
        for_update_ts, lock the identified row keys, and re-collect until
        no new keys appear — so WHERE/join and SET values are evaluated
        on the locked, current versions (the multi-table analog of the
        single-table scan_current + lock + re-filter loop; ref:
        executor/adapter.go handlePessimisticDML retry on lock error)."""
        if txn is None or not txn.pessimistic:
            return self._dml_join_select(stmt.table, stmt.where, fields, expose, self.read_ts())
        locked: set[bytes] = set()
        chunk = None
        for _ in range(4):
            txn.for_update_ts = self.store.tso.next()
            chunk = self._dml_join_select(
                stmt.table, stmt.where, fields, expose, txn.for_update_ts
            )
            keys = set(keys_of(chunk))
            if not (keys - locked):
                break
            txn.lock_keys_for_update(keys)
            locked |= keys
        return chunk

    def _dml_fetch_current(self, txn, tbl: Table, keys: list[bytes]) -> dict:
        """key → raw row value for DML writes. Pessimistic txns lock the
        keys (no-op for already-locked) and read at for_update_ts;
        optimistic reads through the txn view."""
        if txn.pessimistic and keys:
            txn.lock_keys_for_update(keys)
            snap = self.store.snapshot(txn.for_update_ts)
            fresh = snap.batch_get([k for k in keys if k not in txn.membuf])
            out = {}
            for k in keys:
                if k in txn.membuf:
                    v = txn.membuf[k]
                    if v != TOMBSTONE:
                        out[k] = v
                elif fresh.get(k) is not None:
                    out[k] = fresh[k]
            return out
        return {k: v for k in keys if (v := txn.get(k)) is not None}

    def _run_update_multi(self, stmt: ast.Update) -> ResultSet:
        leaves = self._dml_leaves(stmt.table)
        if not leaves:
            raise TiDBError("UPDATE requires at least one base table")
        infos = {
            a: self.infoschema().table(tn.db or self.current_db, tn.name)
            for a, tn in leaves.items()
        }
        # SET targets: qualified names pick their table; bare names must
        # be unambiguous across the joined tables (MySQL resolution rule)
        sets: dict[str, list] = {}
        for name, expr in stmt.sets:
            if name.table is not None:
                alias = name.table.lower()
                if alias not in infos:
                    raise UnknownTable(f"unknown table {name.table!r} in UPDATE")
            else:
                hits = [
                    a for a, info in infos.items()
                    if any(c.name.lower() == name.column.lower() for c in info.visible_columns())
                ]
                if not hits:
                    raise UnknownColumn(f"unknown column {name.column!r}")
                if len(hits) > 1:
                    raise TiDBError(f"column {name.column!r} in SET is ambiguous")
                alias = hits[0]
            col = infos[alias].col_by_name(name.column)
            sets.setdefault(alias, []).append((col, expr))
        if stmt.order_by or stmt.limit is not None:
            # MySQL rejects these on the multi-table form (syntax error);
            # silently dropping them would unbound a bounded statement
            raise TiDBError("multi-table UPDATE does not allow ORDER BY or LIMIT")
        order = sorted(sets)
        for a in order:
            self._tlock_write(infos[a])
            if infos[a].partition is not None:
                raise TiDBError("multi-table UPDATE on a partitioned table is not supported")
        expose = {a for a in order if infos[a].handle_col().hidden}
        fields = []
        for a in order:
            fields.append(ast.SelectField(ast.Name((a, infos[a].handle_col().name))))
            fields.extend(ast.SelectField(e) for _, e in sets[a])
        txn = self._active_txn()
        tbls = {a: Table(infos[a]) for a in order}

        def keys_of(chunk):
            out = []
            p = 0
            for a in order:
                hcol = chunk.columns[p]
                p += 1 + len(sets[a])
                for i in range(chunk.num_rows):
                    hd = hcol.get_datum(i)
                    if not hd.is_null:
                        out.append(tbls[a].record_key(hd.to_int()))
            return out

        chunk = self._dml_collect(stmt, fields, expose, txn, keys_of)
        affected = 0
        pos = 0
        n = chunk.num_rows if chunk is not None else 0
        for a in order:
            info = infos[a]
            tbl = tbls[a]
            hcol = chunk.columns[pos]
            vcols = chunk.columns[pos + 1 : pos + 1 + len(sets[a])]
            pos += 1 + len(sets[a])
            new_vals: dict[int, list] = {}
            for i in range(n):
                hd = hcol.get_datum(i)
                if hd.is_null:
                    continue  # outer-join miss: nothing to update
                h = hd.to_int()
                if h not in new_vals:  # first joined match wins
                    new_vals[h] = [c.get_datum(i) for c in vcols]
            keys = [tbl.record_key(h) for h in new_vals]
            cur = self._dml_fetch_current(txn, tbl, keys)
            changed_rows = 0
            for h, vals in new_vals.items():
                raw = cur.get(tbl.record_key(h))
                if raw is None:
                    continue  # deleted underneath us
                datums = tbl.decode_record(raw)
                new = list(datums)
                changed = False
                for (col, _), vd in zip(sets[a], vals):
                    nv = self._cast_datum(vd, col.ft) if not vd.is_null else Datum.null()
                    if repr(nv) != repr(datums[col.offset]):
                        changed = True
                    new[col.offset] = nv
                if changed:
                    self._rewrite_row(info, txn, tbl, h, datums, new)
                    changed_rows += 1
            if changed_rows:
                self._invalidate_tiles(info)
                self._note_delta(info.id, changed_rows, 0)
            affected += changed_rows
        return ResultSet([], None, affected=affected)

    def _run_delete_multi(self, stmt: ast.Delete) -> ResultSet:
        leaves = self._dml_leaves(stmt.table)
        targets = [t.lower() for t in (stmt.targets or [])]
        if not targets:
            raise TiDBError("multi-table DELETE requires explicit target tables")
        for t in targets:
            if t not in leaves:
                raise UnknownTable(f"unknown table {t!r} in MULTI DELETE")
        infos = {
            a: self.infoschema().table(leaves[a].db or self.current_db, leaves[a].name)
            for a in targets
        }
        if stmt.order_by or stmt.limit is not None:
            raise TiDBError("multi-table DELETE does not allow ORDER BY or LIMIT")
        for a in targets:
            self._tlock_write(infos[a])
            if infos[a].partition is not None:
                raise TiDBError("multi-table DELETE on a partitioned table is not supported")
        expose = {a for a in targets if infos[a].handle_col().hidden}
        fields = [
            ast.SelectField(ast.Name((a, infos[a].handle_col().name))) for a in targets
        ]
        txn = self._active_txn()
        tbls = {a: Table(infos[a]) for a in targets}

        def keys_of(chunk):
            out = []
            for j, a in enumerate(targets):
                hcol = chunk.columns[j]
                for i in range(chunk.num_rows):
                    hd = hcol.get_datum(i)
                    if not hd.is_null:
                        out.append(tbls[a].record_key(hd.to_int()))
            return out

        chunk = self._dml_collect(stmt, fields, expose, txn, keys_of)
        n = chunk.num_rows if chunk is not None else 0
        affected = 0
        for j, a in enumerate(targets):
            info = infos[a]
            tbl = tbls[a]
            hcol = chunk.columns[j]
            handles = []
            seen = set()
            for i in range(n):
                hd = hcol.get_datum(i)
                if hd.is_null:
                    continue
                h = hd.to_int()
                if h not in seen:
                    seen.add(h)
                    handles.append(h)
            keys = [tbl.record_key(h) for h in handles]
            cur = self._dml_fetch_current(txn, tbl, keys)
            removed = 0
            for h in handles:
                raw = cur.get(tbl.record_key(h))
                if raw is None:
                    continue
                tbl.remove_record(txn, h, tbl.decode_record(raw))
                removed += 1
            if removed:
                self._invalidate_tiles(info)
                self._note_delta(info.id, removed, -removed)
            affected += removed
        return ResultSet([], None, affected=affected)

    def _check_safe_updates(self, stmt) -> None:
        """sql_safe_updates=ON rejects UPDATE/DELETE with neither a WHERE
        clause nor a LIMIT (MySQL ER_UPDATE_WITHOUT_KEY_IN_SAFE_MODE)."""
        if self.vars.get("sql_safe_updates", "OFF") != "ON":
            return
        if stmt.where is None and getattr(stmt, "limit", None) is None:
            raise TiDBError(
                "You are using safe update mode and you tried to update a "
                "table without a WHERE that uses a KEY column"
            )

    def _run_update(self, stmt: ast.Update) -> ResultSet:
        self._check_safe_updates(stmt)
        if not isinstance(stmt.table, ast.TableName):
            return self._run_update_multi(stmt)
        info, tbl, txn, rows = self._scan_matching_rows(stmt.table, stmt.where)
        sets = []
        from ..planner.plans import PlanCol

        scope = NameScope([PlanCol(c.name, c.ft, stmt.table.alias or info.name) for c in info.visible_columns()])
        builder = self._builder()
        for name, expr in stmt.sets:
            col = info.col_by_name(name.column)
            sets.append((col, builder.to_expr(expr, scope)))
        affected = 0
        vis = info.visible_columns()
        for ptbl, handle, datums in rows:
            visible_vals = [datums[c.offset] for c in vis]
            chunk = Chunk.from_datum_rows([c.ft for c in vis], [visible_vals])
            new = list(datums)
            changed = False
            for col, e in sets:
                d, v = e.eval(chunk)
                lane = Column(e.ret_type, d[:1], v[:1])
                nv = self._cast_datum(lane.get_datum(0), col.ft) if v[0] else Datum.null()
                if repr(nv) != repr(datums[col.offset]):
                    changed = True
                new[col.offset] = nv
            if changed:
                self._rewrite_row(info, txn, ptbl, handle, datums, new)
                affected += 1
        self._invalidate_tiles(info)
        self._note_delta(info.id, affected, 0)
        return ResultSet([], None, affected=affected)

    def _run_delete(self, stmt: ast.Delete) -> ResultSet:
        self._check_safe_updates(stmt)
        if not isinstance(stmt.table, ast.TableName) or stmt.targets is not None:
            return self._run_delete_multi(stmt)
        info, tbl, txn, rows = self._scan_matching_rows(stmt.table, stmt.where)
        for ptbl, handle, datums in rows:
            ptbl.remove_record(txn, handle, datums)
        self._invalidate_tiles(info)
        self._note_delta(info.id, len(rows), -len(rows))
        return ResultSet([], None, affected=len(rows))

    # ------------------------------------------------------------------- DDL

    def _ddl_txn(self):
        return self.store.begin()

    def _ddl_create_db(self, stmt: ast.CreateDatabase) -> ResultSet:
        txn = self._ddl_txn()
        m = Meta(txn)
        if m.db(stmt.name) is not None:
            txn.rollback()
            if stmt.if_not_exists:
                return ResultSet([], None)
            raise TiDBError(f"database {stmt.name!r} exists")
        m.put_db(DBInfo(stmt.name))
        m.bump_schema_version()
        txn.commit()
        return ResultSet([], None)

    def _ddl_drop_db(self, stmt: ast.DropDatabase) -> ResultSet:
        txn = self._ddl_txn()
        m = Meta(txn)
        db = m.db(stmt.name)
        if db is None:
            txn.rollback()
            if stmt.if_exists:
                return ResultSet([], None)
            raise UnknownDatabase(f"unknown database {stmt.name!r}")
        phys: list[int] = []
        for tid in db.table_ids:
            t = m.table(tid)
            phys.extend(t.physical_ids() if t else [tid])
            m.drop_table(tid)
        for vw in m.list_views():
            if vw["db"] == stmt.name.lower():
                m.drop_view(vw["db"], vw["name"])
        dropped_seq = False
        for sq in m.list_sequences():
            if sq["db"] == stmt.name.lower():
                m.drop_sequence(sq["db"], sq["name"])
                self._seq_cache.pop((sq["db"], sq["name"]), None)
                dropped_seq = True
        if dropped_seq:
            self._bump_seq_gen()
        m.drop_db(stmt.name)
        m.bump_schema_version()
        txn.commit()
        for pid in phys:
            self.store.mvcc.unsafe_destroy_range(tablecodec.table_prefix(pid), tablecodec.table_prefix(pid + 1))
            self.cop.tiles.invalidate_table(pid)
        return ResultSet([], None)

    def _ddl_create_table(self, stmt: ast.CreateTable) -> ResultSet:
        if stmt.temporary:
            return self._ddl_create_temp_table(stmt)
        db = stmt.table.db or self.current_db
        txn = self._ddl_txn()
        m = Meta(txn)
        dbi = m.db(db)
        if dbi is None:
            txn.rollback()
            raise UnknownDatabase(f"unknown database {db!r}")
        for tid in dbi.table_ids:
            t = m.table(tid)
            if t and t.name.lower() == stmt.table.name.lower():
                txn.rollback()
                if stmt.if_not_exists:
                    return ResultSet([], None)
                raise TableExists(f"table {stmt.table.name!r} already exists")
        if m.sequence(db, stmt.table.name) is not None:
            txn.rollback()
            raise TableExists(
                f"a sequence named {stmt.table.name!r} already exists (shared namespace)"
            )
        if m.view(db, stmt.table.name) is not None:
            txn.rollback()
            raise TableExists(
                f"a view named {stmt.table.name!r} already exists (shared namespace)"
            )

        try:
            info = self._build_table_info(stmt, m, db)
        except TiDBError:
            txn.rollback()
            raise
        m.put_table(info)
        dbi.table_ids.append(info.id)
        m.put_db(dbi)
        m.bump_schema_version()
        txn.commit()
        return ResultSet([], None)

    def _build_table_info(self, stmt: ast.CreateTable, m: Meta, db: str) -> TableInfo:
        """Columns/indexes/partition construction shared by permanent and
        temporary CREATE TABLE (ids come from the meta allocator either
        way, so temp keyspaces never collide with real tables)."""
        tid = m.alloc_id()
        cols: list[ColumnInfo] = []
        indexes: list[IndexInfo] = []
        for i, cd in enumerate(stmt.columns):
            if cd.name.lower().startswith("_tidb_"):
                raise TiDBError(f"column name {cd.name!r} is reserved")
            ft = parse_type_name(cd.type_name, cd.type_args, cd.unsigned, cd.elems, getattr(cd, "collate", ""))
            if cd.not_null or cd.primary_key:
                ft.flag |= NOT_NULL_FLAG
            if cd.auto_increment:
                ft.flag |= AUTO_INCREMENT_FLAG
            default = None
            has_default = False
            if cd.default is not None and isinstance(cd.default, ast.Lit):
                default = cd.default.value if cd.default.kind != "dec" else str(cd.default.value)
                has_default = default is not None
                if isinstance(default, bytes):
                    default = default.decode("utf8", "replace")
            cols.append(ColumnInfo(m.alloc_id(), cd.name, ft, i, default, has_default, cd.auto_increment, comment=cd.comment))
            if cd.primary_key:
                indexes.append(IndexInfo(0, "PRIMARY", [i], unique=True, primary=True))
            elif cd.unique:
                indexes.append(IndexInfo(0, f"uk_{cd.name}", [i], unique=True))
        for idef in stmt.indexes:
            offs = []
            for cn in idef.columns:
                offs.append(next(c.offset for c in cols if c.name.lower() == cn.lower()))
            indexes.append(IndexInfo(0, idef.name, offs, idef.unique, idef.primary))
        # primary dedup + id assignment
        seen_primary = False
        final_idx = []
        for idx in indexes:
            if idx.primary:
                if seen_primary:
                    raise TiDBError("Multiple primary key defined")
                seen_primary = True
            idx.id = m.alloc_id()
            final_idx.append(idx)
        pk = next((i for i in final_idx if i.primary), None)
        pk_is_handle = bool(pk and len(pk.col_offsets) == 1 and cols[pk.col_offsets[0]].ft.is_int())
        if not pk_is_handle:
            # hidden rowid column
            rid = ColumnInfo(m.alloc_id(), "_tidb_rowid", ft_longlong(), len(cols), hidden=True)
            cols.append(rid)
        info = TableInfo(tid, stmt.table.name, cols, final_idx, pk_is_handle, db_name=db)
        if stmt.partition is not None:
            info.partition = self._build_partition_info(m, stmt.partition, cols, final_idx)
        return info

    def _ddl_create_temp_table(self, stmt: ast.CreateTable) -> ResultSet:
        """CREATE TEMPORARY TABLE: session-local, shadows a same-named
        permanent table, vanishes on disconnect (ref: the local temporary
        tables the session layer merges at commit — session.go:575; here
        rows live in a private keyspace under normal MVCC)."""
        db = stmt.table.db or self.current_db
        key = (db.lower(), stmt.table.name.lower())
        if key in self._temp_tables:
            if stmt.if_not_exists:
                return ResultSet([], None)
            raise TableExists(f"table {stmt.table.name!r} already exists")
        if stmt.partition is not None:
            raise TiDBError("temporary tables cannot be partitioned")
        if not self.infoschema().has_db(db):
            raise UnknownDatabase(f"unknown database {db!r}")

        info = self._retry_meta_txn(
            lambda txn, m: self._build_table_info(stmt, m, db), "temp-table id allocation"
        )
        info.temporary = True
        self._temp_tables[key] = info
        self._temp_epoch += 1
        self._is_cache = None
        return ResultSet([], None)

    def _destroy_temp_keyspace(self, info) -> None:
        self.store.mvcc.unsafe_destroy_range(
            tablecodec.table_prefix(info.id), tablecodec.table_prefix(info.id + 1)
        )
        self.cop.tiles.invalidate_table(info.id)

    def drop_temp_tables(self) -> None:
        """Connection teardown: destroy every temp table's keyspace."""
        for info in self._temp_tables.values():
            self._destroy_temp_keyspace(info)
        self._temp_tables.clear()
        self._temp_epoch += 1
        self._is_cache = None

    def _build_partition_info(self, m, spec, cols, indexes):
        """Validate + materialize a PARTITION BY clause (ref: ddl/ddl_api.go
        buildTablePartitionInfo + checkPartitionKeysConstraint): integer
        partition column, present in every unique key, ascending range
        bounds; each partition gets its own physical keyspace id."""
        from ..catalog.schema import PartitionDef, PartitionInfo

        pcol = next((c for c in cols if c.name.lower() == spec.col.lower()), None)
        if pcol is None:
            raise UnknownColumn(f"unknown partitioning column {spec.col!r}")
        if not pcol.ft.is_int():
            raise TiDBError("partitioning column must be an integer type")
        for idx in indexes:
            if idx.unique and pcol.offset not in idx.col_offsets:
                raise TiDBError(
                    "A PRIMARY KEY/UNIQUE INDEX must include all columns in the "
                    "table's partitioning function"
                )
        if spec.type == "hash":
            if spec.count < 1:
                raise TiDBError("at least one partition required")
            defs = [PartitionDef(m.alloc_id(), f"p{i}") for i in range(spec.count)]
        elif spec.type == "list":
            # gated like the reference (ddl/ddl_api.go checks
            # tidb_enable_list_partition before building the info)
            if self.vars.get("tidb_enable_list_partition", "OFF") != "ON":
                raise TiDBError(
                    "LIST partitioning requires tidb_enable_list_partition = ON"
                )
            if not spec.defs:
                raise TiDBError("at least one partition required")
            seen_vals: set = set()
            defs = []
            for name, vals in spec.defs:
                for v in vals:
                    if v in seen_vals:
                        raise TiDBError(
                            f"Multiple definition of same constant in list partitioning: {v}"
                        )
                    seen_vals.add(v)
                defs.append(PartitionDef(m.alloc_id(), name, in_values=tuple(vals)))
        else:
            if not spec.defs:
                raise TiDBError("at least one partition required")
            defs = []
            prev = None
            for i, (name, bound) in enumerate(spec.defs):
                if bound is None and i != len(spec.defs) - 1:
                    raise TiDBError("MAXVALUE can only be used in the last partition")
                if bound is not None and prev is not None and bound <= prev:
                    raise TiDBError("VALUES LESS THAN values must be strictly increasing")
                prev = bound if bound is not None else prev
                defs.append(PartitionDef(m.alloc_id(), name, bound))
        return PartitionInfo(spec.type, pcol.name, defs)

    def _ddl_drop_table(self, stmt: ast.DropTable) -> ResultSet:
        for tn in stmt.tables:
            db = tn.db or self.current_db
            tkey = (db.lower(), tn.name.lower())
            if tkey in self._temp_tables:
                # MySQL: DROP TABLE removes the temp table first
                self._destroy_temp_keyspace(self._temp_tables.pop(tkey))
                self._temp_epoch += 1
                self._is_cache = None
                continue
            txn = self._ddl_txn()
            m = Meta(txn)
            dbi = m.db(db)
            target = None
            if dbi:
                for tid in dbi.table_ids:
                    t = m.table(tid)
                    if t and t.name.lower() == tn.name.lower():
                        target = t
                        break
            if target is None:
                txn.rollback()
                if stmt.if_exists:
                    continue
                raise UnknownTable(f"table {tn.name!r} doesn't exist")
            dbi.table_ids.remove(target.id)
            m.put_db(dbi)
            m.drop_table(target.id)
            m.bump_schema_version()
            txn.commit()
            for pid in target.physical_ids():
                self.store.mvcc.unsafe_destroy_range(tablecodec.table_prefix(pid), tablecodec.table_prefix(pid + 1))
                self.cop.tiles.invalidate_table(pid)
        return ResultSet([], None)

    def _temp_info(self, tn: ast.TableName):
        return self._temp_tables.get(((tn.db or self.current_db).lower(), tn.name.lower()))

    def _reject_temp_ddl(self, tn: ast.TableName, what: str) -> None:
        if self._temp_info(tn) is not None:
            raise TiDBError(f"{what} is not supported on temporary tables")

    def _ddl_truncate(self, stmt: ast.TruncateTable) -> ResultSet:
        tinfo = self._temp_info(stmt.table)
        if tinfo is not None:
            self._destroy_temp_keyspace(tinfo)
            tinfo.auto_inc_id = 1
            return ResultSet([], None)
        info = self.infoschema().table(stmt.table.db or self.current_db, stmt.table.name)
        for pid in info.physical_ids():
            self.store.mvcc.unsafe_destroy_range(tablecodec.table_prefix(pid), tablecodec.table_prefix(pid + 1))
        txn = self._ddl_txn()
        m = Meta(txn)
        t = m.table(info.id)
        t.auto_inc_id = 1
        m.put_table(t)
        m.bump_schema_version()
        txn.commit()
        self.store.bump_version([tablecodec.record_prefix(pid) for pid in info.physical_ids()])
        self._invalidate_tiles(info)
        return ResultSet([], None)

    def _ddl_create_index(self, stmt: ast.CreateIndex) -> ResultSet:
        return self._add_index(stmt.table, stmt.index)

    def _add_index(self, tn: ast.TableName, idef: ast.IndexDef) -> ResultSet:
        """Online ADD INDEX through the F1 state machine (ref:
        ddl/index.go onCreateIndex): the index is registered in state
        'none', a DDL job is enqueued, and the worker drives
        delete_only→write_only→write_reorg→public with a resumable
        backfill. This session waits for completion (doDDLJob loop)."""
        self._reject_temp_ddl(tn, "ADD INDEX")
        db = tn.db or self.current_db
        if self.infoschema().table(db, tn.name).partition is not None:
            raise TiDBError("online ADD INDEX on a partitioned table is not supported yet")
        txn = self._ddl_txn()
        m = Meta(txn)
        info = self.infoschema().table(db, tn.name)
        t = m.table(info.id)
        if t.index_by_name(idef.name):
            txn.rollback()
            raise TiDBError(f"duplicate key name {idef.name!r}")
        offs = [t.col_by_name(c).offset for c in idef.columns]
        idx = IndexInfo(m.alloc_id(), idef.name, offs, idef.unique, idef.primary, state="none")
        t.indexes.append(idx)
        m.put_table(t)
        m.bump_schema_version()
        txn.commit()
        jid = self.store.ddl.enqueue(
            "add_index", info.id,
            {"index_id": idx.id, "index_name": idx.name,
             # reorg batch per txn (ref: tidb_ddl_reorg_batch_size)
             "reorg_batch_size": int(self.vars.get("tidb_ddl_reorg_batch_size", "256"))},
        )
        self.store.ddl.run_until_done(jid)
        return ResultSet([], None)

    def _ddl_drop_index(self, stmt: ast.DropIndex) -> ResultSet:
        self._reject_temp_ddl(stmt.table, "DROP INDEX")
        db = stmt.table.db or self.current_db
        info = self.infoschema().table(db, stmt.table.name)
        txn = self._ddl_txn()
        m = Meta(txn)
        t = m.table(info.id)
        idx = t.index_by_name(stmt.name)
        txn.rollback()
        if idx is None:
            raise TiDBError(f"index {stmt.name!r} doesn't exist")
        jid = self.store.ddl.enqueue(
            "drop_index", info.id, {"index_id": idx.id, "index_name": idx.name}
        )
        self.store.ddl.run_until_done(jid)
        return ResultSet([], None)

    def _ddl_alter(self, stmt: ast.AlterTable) -> ResultSet:
        self._reject_temp_ddl(stmt.table, "ALTER TABLE")
        for action, payload in stmt.actions:
            if action == "add_index":
                self._add_index(stmt.table, payload)
            elif action == "drop_index":
                self._ddl_drop_index(ast.DropIndex(payload, stmt.table))
            elif action == "add_column":
                self._alter_add_column(stmt.table, payload)
            elif action == "drop_column":
                self._alter_drop_column(stmt.table, payload)
            elif action == "rename":
                self._alter_rename(stmt.table, payload)
            elif action == "add_partition":
                self._alter_add_partition(stmt.table, payload)
            elif action == "drop_partition":
                self._alter_drop_partition(stmt.table, payload, truncate=False)
            elif action == "truncate_partition":
                self._alter_drop_partition(stmt.table, payload, truncate=True)
            else:
                raise TiDBError(f"unsupported ALTER action {action}")
        return ResultSet([], None)

    def _alter_add_partition(self, tn: ast.TableName, defs: list) -> None:
        """ALTER TABLE ... ADD PARTITION for RANGE/LIST tables (ref:
        ddl/partition.go onAddTablePartition): range bounds must ascend
        strictly above the current maximum; list values must be disjoint
        from every existing partition's value set."""
        from ..catalog.schema import PartitionDef

        db = tn.db or self.current_db
        info = self.infoschema().table(db, tn.name)
        if info.partition is None or info.partition.type not in ("range", "list"):
            raise TiDBError("ADD PARTITION requires a RANGE or LIST partitioned table")
        txn = self._ddl_txn()
        m = Meta(txn)
        t = m.table(info.id)
        cur = t.partition.defs
        if info.partition.type == "list":
            names = {d.name.lower() for d in cur}
            existing = {v for d in cur for v in (d.in_values or ())}
            for name, payload in defs:
                if not (isinstance(payload, tuple) and payload and payload[0] == "in"):
                    txn.rollback()
                    raise TiDBError("LIST partition requires VALUES IN (...)")
                if name.lower() in names:
                    txn.rollback()
                    raise TiDBError(f"Duplicate partition name {name}")
                vals = payload[1]
                dup = existing.intersection(vals)
                if dup:
                    txn.rollback()
                    raise TiDBError(
                        f"Multiple definition of same constant in list partitioning: {next(iter(dup))}"
                    )
                t.partition.defs.append(PartitionDef(m.alloc_id(), name, in_values=tuple(vals)))
                names.add(name.lower())
                existing.update(vals)
            m.put_table(t)
            m.bump_schema_version()
            txn.commit()
            return
        if cur and cur[-1].less_than is None:
            txn.rollback()
            raise TiDBError("MAXVALUE can only be used in last partition definition")
        prev = cur[-1].less_than if cur else None
        names = {d.name.lower() for d in cur}
        for name, bound in defs:
            if isinstance(bound, tuple):
                txn.rollback()
                raise TiDBError("VALUES IN is only valid for LIST partitioned tables")
            if name.lower() in names:
                txn.rollback()
                raise TiDBError(f"Duplicate partition name {name}")
            if bound is not None and prev is not None and bound <= prev:
                txn.rollback()
                raise TiDBError("VALUES LESS THAN value must be strictly increasing for each partition")
            if prev is None and cur:
                txn.rollback()
                raise TiDBError("MAXVALUE can only be used in last partition definition")
            t.partition.defs.append(PartitionDef(m.alloc_id(), name, bound))
            names.add(name.lower())
            prev = bound
        m.put_table(t)
        m.bump_schema_version()
        txn.commit()

    def _alter_drop_partition(self, tn: ast.TableName, names: list, truncate: bool) -> None:
        """DROP PARTITION (range only, removes defs + rows) / TRUNCATE
        PARTITION (any type, keeps defs) — ref: ddl/partition.go
        onDropTablePartition/onTruncateTablePartition + delete_range."""
        db = tn.db or self.current_db
        info = self.infoschema().table(db, tn.name)
        if info.partition is None:
            raise TiDBError(f"table {tn.name!r} is not partitioned")
        if not truncate and info.partition.type not in ("range", "list"):
            raise TiDBError("DROP PARTITION can only be used on RANGE/LIST partitions")
        txn = self._ddl_txn()
        m = Meta(txn)
        t = m.table(info.id)
        by_name = {d.name.lower(): d for d in t.partition.defs}
        wanted = []
        for n in names:
            pd = by_name.get(n.lower())
            if pd is None:
                txn.rollback()
                raise TiDBError(f"Unknown partition {n!r} in table {tn.name!r}")
            wanted.append(pd)
        if not truncate and len(wanted) == len(t.partition.defs):
            txn.rollback()
            raise TiDBError("Cannot remove all partitions, use DROP TABLE instead")
        if not truncate:
            drop_ids = {pd.id for pd in wanted}
            t.partition.defs = [d for d in t.partition.defs if d.id not in drop_ids]
        m.put_table(t)
        m.bump_schema_version()
        txn.commit()
        for pd in wanted:
            self.store.mvcc.unsafe_destroy_range(
                tablecodec.table_prefix(pd.id), tablecodec.table_prefix(pd.id + 1)
            )
            self.cop.tiles.invalidate_table(pd.id)

    def _alter_add_column(self, tn: ast.TableName, cd: ast.ColumnDef):
        if cd.name.lower().startswith("_tidb_"):
            raise TiDBError(f"column name {cd.name!r} is reserved")
        db = tn.db or self.current_db
        info = self.infoschema().table(db, tn.name)
        txn = self._ddl_txn()
        m = Meta(txn)
        t = m.table(info.id)
        ft = parse_type_name(cd.type_name, cd.type_args, cd.unsigned, cd.elems, getattr(cd, "collate", ""))
        if cd.not_null:
            ft.flag |= NOT_NULL_FLAG
        default = None
        has_default = False
        if cd.default is not None and isinstance(cd.default, ast.Lit):
            default = cd.default.value if cd.default.kind != "dec" else str(cd.default.value)
            has_default = default is not None
        # new column goes before any hidden rowid
        hidden = [c for c in t.columns if c.hidden]
        vis = [c for c in t.columns if not c.hidden]
        col = ColumnInfo(m.alloc_id(), cd.name, ft, len(vis), default, has_default)
        vis.append(col)
        for i, h in enumerate(hidden):
            h.offset = len(vis) + i
        t.columns = vis + hidden
        m.put_table(t)
        m.bump_schema_version()
        txn.commit()
        self._invalidate_tiles(info)

    def _alter_drop_column(self, tn: ast.TableName, name: str):
        db = tn.db or self.current_db
        info = self.infoschema().table(db, tn.name)
        txn = self._ddl_txn()
        m = Meta(txn)
        t = m.table(info.id)
        col = t.col_by_name(name)
        if t.partition is not None and col.name.lower() == t.partition.col.lower():
            txn.rollback()
            raise TiDBError(f"cannot drop partitioning column {name!r}")
        for idx in t.indexes:
            if col.offset in idx.col_offsets:
                txn.rollback()
                raise TiDBError(f"cannot drop indexed column {name!r}")
        t.columns.remove(col)
        for c in t.columns:
            if c.offset > col.offset:
                c.offset -= 1
        for idx in t.indexes:
            idx.col_offsets = [o - 1 if o > col.offset else o for o in idx.col_offsets]
        m.put_table(t)
        m.bump_schema_version()
        txn.commit()
        self._invalidate_tiles(info)

    def _alter_rename(self, tn: ast.TableName, new: ast.TableName):
        db = tn.db or self.current_db
        info = self.infoschema().table(db, tn.name)
        txn = self._ddl_txn()
        m = Meta(txn)
        t = m.table(info.id)
        t.name = new.name
        m.put_table(t)
        m.bump_schema_version()
        txn.commit()

    # ------------------------------------------------------------------ SHOW

    def _run_show(self, stmt: ast.Show) -> ResultSet:
        is_ = self.infoschema()
        if stmt.kind == "processlist":
            rows = []
            now = time.time()
            for cid, info in self.store.process_snapshot():
                rows.append([
                    Datum.i(cid), Datum.s(info["user"]), Datum.s(info["db"]),
                    Datum.i(int(now - info["start"])), Datum.s(info["sql"]),
                ])
            chk = Chunk.from_datum_rows(
                [ft_longlong(), ft_varchar(), ft_varchar(), ft_longlong(), ft_varchar()], rows
            )
            return ResultSet(["Id", "User", "db", "Time", "Info"], chk)
        if stmt.kind == "table_status":
            pat = None
            if stmt.like is not None and isinstance(stmt.like, ast.Lit):
                from ..expr.builtins import like_to_regex

                pat = like_to_regex(stmt.like.value)
            rows = []
            for t in is_.tables_in_db(self.current_db):
                if pat is not None and not pat.match(t.name):
                    continue
                st = self.store.stats.get(t.id)
                nrows = st.row_count if st is not None else 0
                rows.append([
                    Datum.s(t.name), Datum.s("tpu"), Datum.i(int(nrows)),
                    Datum.s("Fixed"), Datum.s(""),
                ])
            chk = Chunk.from_datum_rows(
                [ft_varchar(), ft_varchar(), ft_longlong(), ft_varchar(), ft_varchar()], rows
            )
            return ResultSet(["Name", "Engine", "Rows", "Row_format", "Comment"], chk)
        if stmt.kind == "resource_groups":
            rows = [
                [
                    Datum.s(g.name.upper()),
                    Datum.s("UNLIMITED" if g.ru_per_sec <= 0 else str(g.ru_per_sec)),
                    Datum.s(g.priority),
                    Datum.s("YES" if g.burstable else "NO"),
                    Datum.s(ql.render() if (ql := g.parsed_limit()) is not None else "NULL"),
                ]
                for g in self.store.sched.groups.list()
            ]
            chk = Chunk.from_datum_rows([ft_varchar()] * 5, rows)
            return ResultSet(["Name", "RU_PER_SEC", "Priority", "Burstable", "QUERY_LIMIT"], chk)
        if stmt.kind == "bindings":
            rows = self._sql_internal(
                "SELECT original_sql, bind_sql, status FROM mysql.bind_info"
            )
            chk = Chunk.from_datum_rows(
                [ft_varchar(), ft_varchar(), ft_varchar()],
                [[Datum.s(a), Datum.s(b), Datum.s(c)] for a, b, c in rows],
            )
            return ResultSet(["Original_sql", "Bind_sql", "Status"], chk)
        if stmt.kind == "grants":
            user = stmt.target.user if stmt.target is not None else self.user
            grants = self.priv.grants_for(self, user)
            chk = Chunk.from_datum_rows([ft_varchar()], [[Datum.s(g)] for g in grants])
            return ResultSet([f"Grants for {user}@%"], chk)
        if stmt.kind == "databases":
            names = is_.db_names()
            chk = Chunk.from_datum_rows([ft_varchar()], [[Datum.s(n)] for n in names])
            return ResultSet(["Database"], chk)
        if stmt.kind == "tables":
            db = stmt.target or self.current_db
            tbls = sorted(
                [t.name for t in is_.tables_in_db(db)]
                + [n for d, n in is_.views if d == db.lower()]
            )
            chk = Chunk.from_datum_rows([ft_varchar()], [[Datum.s(n)] for n in tbls])
            return ResultSet([f"Tables_in_{db}"], chk)
        if stmt.kind == "columns":
            vkey = ((stmt.target.db or self.current_db).lower(), stmt.target.name.lower())
            vdef = is_.views.get(vkey)
            # a session temp table shadows a same-named view (same rule as
            # the planner's name resolution)
            shadow = is_.table_or_none(*vkey)
            if vdef is not None and not getattr(shadow, "temporary", False):
                # DESC on a view: plan the definition in the VIEW's OWN
                # database (no caller db/temp leakage — mirror _build_view)
                vbuilder = self._builder()
                vbuilder.db = vdef["db"]
                plan = optimize(vbuilder.build_select(parse_one(vdef["sql"])), self.store.stats, self.vars)
                names = vdef.get("cols") or [c.name for c in plan.out_cols]
                rows = [
                    [Datum.s(n), Datum.s(c.ft.type_name()),
                     Datum.s("NO" if c.ft.not_null else "YES"),
                     Datum.s(""), Datum.null(), Datum.s("")]
                    for n, c in zip(names, plan.out_cols)
                ]
                chk = Chunk.from_datum_rows([ft_varchar()] * 6, rows)
                return ResultSet(["Field", "Type", "Null", "Key", "Default", "Extra"], chk)
            info = is_.table(stmt.target.db or self.current_db, stmt.target.name)
            rows = []
            for c in info.visible_columns():
                rows.append(
                    [
                        Datum.s(c.name),
                        Datum.s(c.ft.type_name()),
                        Datum.s("NO" if c.ft.not_null else "YES"),
                        Datum.s(self._key_flag(info, c)),
                        Datum.s(str(c.default)) if c.has_default else Datum.null(),
                        Datum.s("auto_increment" if c.auto_increment else ""),
                    ]
                )
            chk = Chunk.from_datum_rows([ft_varchar()] * 6, rows)
            return ResultSet(["Field", "Type", "Null", "Key", "Default", "Extra"], chk)
        if stmt.kind == "variables":
            import re

            pat = None
            if stmt.like is not None and isinstance(stmt.like, ast.Lit):
                from ..expr.builtins import like_to_regex

                pat = like_to_regex(stmt.like.value)
            rows = [
                [Datum.s(k), Datum.s(str(v))]
                for k, v in sorted(self.vars.items())
                if pat is None or pat.match(k)
            ]
            chk = Chunk.from_datum_rows([ft_varchar(), ft_varchar()], rows)
            return ResultSet(["Variable_name", "Value"], chk)
        if stmt.kind == "stats_meta":
            rows = []
            for db in is_.db_names():
                for t in is_.tables_in_db(db):
                    ts = self.store.stats.get(t.id)
                    if ts is None:
                        continue
                    rows.append([Datum.s(db), Datum.s(t.name), Datum.s(str(ts.modify_count)),
                                 Datum.s(str(ts.row_count)), Datum.s(str(ts.version))])
            chk = Chunk.from_datum_rows([ft_varchar()] * 5, rows)
            return ResultSet(["Db_name", "Table_name", "Modify_count", "Row_count", "Version"], chk)
        if stmt.kind == "stats_histograms":
            rows = []
            for db in is_.db_names():
                for t in is_.tables_in_db(db):
                    ts = self.store.stats.get(t.id)
                    if ts is None:
                        continue
                    for c in t.visible_columns():
                        cs = ts.col(c.id)
                        if cs is None:
                            continue
                        nb = len(cs.hist.uppers) if cs.hist is not None else 0
                        rows.append([Datum.s(db), Datum.s(t.name), Datum.s(c.name),
                                     Datum.s(str(cs.ndv)), Datum.s(str(cs.null_count)), Datum.s(str(nb))])
            chk = Chunk.from_datum_rows([ft_varchar()] * 6, rows)
            return ResultSet(["Db_name", "Table_name", "Column_name", "Distinct_count", "Null_count", "Buckets"], chk)
        if stmt.kind == "create_table":
            vdef = is_.views.get(
                ((stmt.target.db or self.current_db).lower(), stmt.target.name.lower()))
            if vdef is not None:
                cols = f"({', '.join(vdef['cols'])}) " if vdef.get("cols") else ""
                ddl = f"CREATE VIEW `{vdef['name']}` {cols}AS {vdef['sql']}"
                chk = Chunk.from_datum_rows(
                    [ft_varchar(), ft_varchar()], [[Datum.s(vdef["name"]), Datum.s(ddl)]])
                return ResultSet(["View", "Create View"], chk)
            info = is_.table(stmt.target.db or self.current_db, stmt.target.name)
            chk = Chunk.from_datum_rows(
                [ft_varchar(), ft_varchar()],
                [[Datum.s(info.name), Datum.s(self._show_create(info))]],
            )
            return ResultSet(["Table", "Create Table"], chk)
        if stmt.kind == "warnings":
            rows = [[Datum.s("Warning"), Datum.i(1105), Datum.s(w)] for w in self.warnings]
            chk = Chunk.from_datum_rows([ft_varchar(), ft_longlong(), ft_varchar()], rows)
            return ResultSet(["Level", "Code", "Message"], chk)
        if stmt.kind == "index":
            info = is_.table(stmt.target.db or self.current_db, stmt.target.name)
            rows = []
            for idx in info.indexes:
                for seq, off in enumerate(idx.col_offsets):
                    rows.append([Datum.s(info.name), Datum.i(0 if idx.unique else 1), Datum.s(idx.name), Datum.i(seq + 1), Datum.s(info.columns[off].name)])
            chk = Chunk.from_datum_rows([ft_varchar(), ft_longlong(), ft_varchar(), ft_longlong(), ft_varchar()], rows)
            return ResultSet(["Table", "Non_unique", "Key_name", "Seq_in_index", "Column_name"], chk)
        # engines/collation/charset/status/processlist: minimal static forms
        chk = Chunk.from_datum_rows([ft_varchar()], [])
        return ResultSet([stmt.kind], chk)

    @staticmethod
    def _key_flag(info: TableInfo, c: ColumnInfo) -> str:
        for idx in info.indexes:
            if idx.col_offsets and idx.col_offsets[0] == c.offset:
                if idx.primary:
                    return "PRI"
                return "UNI" if idx.unique else "MUL"
        return ""

    @staticmethod
    def _show_create(info: TableInfo) -> str:
        lines = []
        for c in info.visible_columns():
            s = f"  `{c.name}` {c.ft.type_name()}"
            if c.ft.not_null:
                s += " NOT NULL"
            if c.auto_increment:
                s += " AUTO_INCREMENT"
            if c.has_default:
                s += f" DEFAULT '{c.default}'"
            lines.append(s)
        for idx in info.indexes:
            cols = ", ".join(f"`{info.columns[o].name}`" for o in idx.col_offsets)
            if idx.primary:
                lines.append(f"  PRIMARY KEY ({cols})")
            elif idx.unique:
                lines.append(f"  UNIQUE KEY `{idx.name}` ({cols})")
            else:
                lines.append(f"  KEY `{idx.name}` ({cols})")
        body = ",\n".join(lines)
        out = f"CREATE TABLE `{info.name}` (\n{body}\n) ENGINE=tpu"
        part = info.partition
        if part is not None:
            if part.type == "hash":
                out += f"\nPARTITION BY HASH (`{part.col}`) PARTITIONS {len(part.defs)}"
            else:
                defs = ", ".join(
                    f"PARTITION `{d.name}` VALUES LESS THAN "
                    + ("MAXVALUE" if d.less_than is None else f"({d.less_than})")
                    for d in part.defs
                )
                out += f"\nPARTITION BY RANGE (`{part.col}`) ({defs})"
        return out

    # --------------------------------------------------------------- EXPLAIN

    def _run_analyze(self, stmt: ast.AnalyzeTable) -> ResultSet:
        """ANALYZE TABLE — full stats build over columnar batches
        (ref: executor/analyze.go:68)."""
        for tn in stmt.tables:
            info = self.infoschema().table(tn.db or self.current_db, tn.name)
            self.store.stats.analyze_table(self, info)
        return ResultSet([], None)

    def _run_explain(self, stmt: ast.Explain) -> ResultSet:
        if not isinstance(stmt.stmt, (ast.Select, ast.SetOpSelect)):
            raise TiDBError("EXPLAIN supports SELECT only for now")
        prev_hints = getattr(self, "_cur_hints", None)
        self._cur_hints = self._effective_hints(stmt.stmt, getattr(stmt, "inner_sql", None))
        try:
            plan = self.plan_select(stmt.stmt)
        finally:
            self._cur_hints = prev_hints
        if getattr(stmt, "analyze", False):
            return self._run_explain_analyze(plan)
        lines = plan.pretty().split("\n")
        chk = Chunk.from_datum_rows([ft_varchar()], [[Datum.s(l)] for l in lines])
        return ResultSet(["plan"], chk)

    def _run_trace(self, stmt: ast.TraceStmt) -> ResultSet:
        """TRACE <sql>: hierarchical span rows (operation, startTS,
        duration) from the statement tracer (ref: executor/trace.go +
        util/tracing). The tree covers the full cop path — admission
        waits, co-batched launch spans (fan-out attributed, with
        occupancy and launch id), backoff sleeps labeled by error class,
        breaker events, device compile/transfer/execute phases — plus the
        per-operator executor spans EXPLAIN ANALYZE uses, and the legacy
        resource-control summary span."""
        from ..executor.runtime_stats import child_execs
        from ..utils.tracing import Span

        inner = stmt.stmt
        tracer = self._tracer
        if tracer is not None:
            # the statement trace already exists (created per statement in
            # _execute_parsed); TRACE flips span recording on for the
            # gated inner run
            tracer.enable_recording()
        # the inner statement runs through _execute_stmt so EVERY gate
        # (privileges, table locks, hints, outfile, ...) applies exactly
        # as it would un-traced; run_select stores the instrumented tree
        self._trace_collect = True
        self._trace_result = None
        try:
            self._execute_stmt(inner)
        finally:
            self._trace_collect = False
        if tracer is None:  # bootstrap-internal edge: nothing to render
            return ResultSet(
                ["operation", "startTS", "duration"],
                Chunk.from_datum_rows([ft_varchar()] * 3, []),
            )
        extra: list[Span] = []
        c = dict(tracer.counters)
        if c.get("tasks"):
            # resource-control summary span: wait is the measured queue
            # time, RU/batch counters ride in the operation label
            extra.append(Span(
                f"cop.sched[group={self.vars.get('tidb_resource_group', 'default') or 'default'}"
                f" ru={c.get('ru', 0.0):.2f} batched={int(c.get('batched_tasks', 0))}"
                f" dedup={int(c.get('dedup_tasks', 0))}]",
                0, int(c.get("sched_wait_ms", 0.0) * 1e6), parent_id=tracer.root_id,
            ))
        if self._trace_result is not None:
            ex, stats = self._trace_result
            self._trace_result = None

            def rec(e, parent_id):
                est = stats.get(id(e), {"time_ns": 0, "rows": 0})
                sp = Span(f"executor.{type(e).__name__}", 0, est["time_ns"],
                          parent_id=parent_id)
                extra.append(sp)
                for ch in child_execs(e):
                    rec(ch, sp.span_id)

            rec(ex, tracer.root_id)

        def span_rows(tree_rows, base_depth=0):
            out = []
            for depth, sp in tree_rows:
                tags = " ".join(f"{k}={v}" for k, v in sp.tags.items())
                op = ("." * max(depth + base_depth - 1, 0)) + sp.name + (
                    f"[{tags}]" if tags else "")
                out.append([
                    Datum.s(op),
                    Datum.s(f"{sp.start_ns / 1e6:.3f}ms"),
                    Datum.s(f"{sp.dur_ns / 1e6:.3f}ms"),
                ])
            return out

        rows = []
        txn_id = tracer.txn_trace_id
        if txn_id is not None:
            # multi-statement txn tree: every already-finished statement
            # of this txn (from the ring) renders under one txn root,
            # the traced statement last — `BEGIN; ...; TRACE <stmt>`
            # shows the whole transaction so far
            from ..utils.tracing import StatementTrace as _ST

            siblings = [
                t for t in self.store.trace_ring.items()
                if isinstance(t, _ST) and t.txn_trace_id == txn_id and t is not tracer
            ]
            rows.append([Datum.s(f"txn[txn_trace_id={txn_id} statements={len(siblings) + 1}]"),
                         Datum.s("0.000ms"), Datum.s("-")])
            for t in siblings:
                rows.extend(span_rows(t.tree(), base_depth=1))
            rows.extend(span_rows(tracer.tree(extra=extra), base_depth=1))
        else:
            rows = span_rows(tracer.tree(extra=extra))
        chk = Chunk.from_datum_rows([ft_varchar()] * 3, rows)
        return ResultSet(["operation", "startTS", "duration"], chk)

    def _run_explain_analyze(self, plan) -> ResultSet:
        """Execute with per-operator runtime stats + cop-layer counters
        (ref: executor/explain.go EXPLAIN ANALYZE; util/execdetails)."""
        from ..executor.runtime_stats import attach_runtime_stats, render_tree

        # follower routing applies exactly as the bare statement's gate
        # would route it, so the `replica:` line reports the serving
        # node the real execution would use
        cop = self.cop
        route_store = router = None
        decision: dict | None = None
        read_ts = self.read_ts()
        sh = getattr(self.store, "_shipper", None)
        rr = str(self.vars.get("tidb_replica_read", "leader")).lower()
        if (self.txn is None and not self.store.standby and sh is not None
                and rr in ("follower", "leader-and-follower")):
            decision = {}
            router = sh.router
            max_lag = int(self.vars.get("tidb_replica_read_max_lag_ms", 5000) or 0)
            route_store = router.route(as_of_ts=None, max_lag_ms=max_lag,
                                       decision=decision)
            prop = self._note_route(decision)
            if route_store is not None:
                cop = self._replica_cop(route_store)
                cop.replica_name = decision.get("replica") if prop else None
                read_ts = route_store.applied_ts
        ctx = ExecContext(
            cop,
            read_ts,
            engine=self.vars.get("tidb_cop_engine", "auto"),
            vars=self.vars,
            txn=self.txn,
        )
        before = dict(cop.stats)
        tpu0 = (cop.tpu.compile_count, cop.tpu.fallbacks) if cop._tpu else (0, 0)
        ex = build_executor(plan, ctx)
        stats = attach_runtime_stats(ex)
        t0 = time.perf_counter_ns()
        try:
            drain(ex)
        finally:
            if route_store is not None:
                router.release(route_store)
        wall_ms = (time.perf_counter_ns() - t0) / 1e6
        lines = render_tree(ex, stats)
        d = {k: cop.stats[k] - before.get(k, 0) for k in cop.stats}
        lines.append(
            f"cop: tasks:{d['tasks']} tpu:{d['tpu_tasks']} host:{d['host_tasks']} "
            f"region_errors:{d['region_errors']} fallback_errors:{d['fallback_errors']}"
        )
        if d["tasks"]:
            lines.append(
                f"sched: group:{self.vars.get('tidb_resource_group', 'default') or 'default'} "
                f"wait:{d['sched_wait_ms']:.3f}ms ru:{d['ru']:.2f} "
                f"batched:{d['batched_tasks']} dedup:{d['dedup_tasks']}"
            )
        if d["retries"] or d["breaker_skips"]:
            # fault-tolerance line: typed backoff retries this statement
            # paid, and device launches skipped by an open breaker
            lines.append(
                f"retry: backoffs:{d['retries']} backoff_ms:{d['backoff_ms']:.3f} "
                f"breaker_skips:{d['breaker_skips']}"
            )
        if d.get("mem_degraded_tasks"):
            # memory-arbitration line: auto tasks rerouted to host while
            # the store sat over its soft memory limit
            lines.append(f"mem: degraded_tasks:{d['mem_degraded_tasks']}")
        if d.get("mpp_tasks"):
            # unified fault domain (PR 8): mesh MPP dispatches this
            # statement attempted, how many degraded to the host join,
            # and the TYPED reason behind the last degrade
            mline = (
                f"mpp: dispatches:{d['mpp_tasks']} fallbacks:{d['mpp_fallbacks']}"
            )
            reason = getattr(cop.mpp, "last_fallback_reason", "") \
                if getattr(cop, "_mpp", None) is not None else ""
            if d.get("mpp_fallbacks") and reason:
                mline += f" reason:[{reason}]"
            lines.append(mline)
        if d.get("window_device_tasks") or d.get("window_fallbacks"):
            # device-window runs vs typed declines (the per-operator
            # fallback:[...] tag carries the reason text)
            lines.append(
                f"window: device:{d['window_device_tasks']} "
                f"fallbacks:{d['window_fallbacks']}"
            )
        if (d["compile_ms"] or d["transfer_bytes"] or d["device_ms"]
                or d.get("cache_ref_bytes") or d.get("shared_h2d_bytes")):
            # device-path line: XLA compile wall, host<->device bytes and
            # execute+fetch time attributed to this statement's cop tasks,
            # plus bytes served from cached device lanes (cache_ref),
            # grouped-launch shared uploads (shared_h2d, PR 5), and the
            # tile-codec split: dense bytes the uploads represent
            # (logical) vs narrowed/compressed bytes that moved (wire)
            lines.append(
                f"device: compile_ms:{d['compile_ms']:.3f} "
                f"transfer_bytes:{int(d['transfer_bytes'])} "
                f"device_ms:{d['device_ms']:.3f} "
                f"logical_bytes:{int(d.get('logical_bytes', 0))} "
                f"wire_bytes:{int(d.get('wire_bytes', 0))} "
                f"cache_ref:{int(d.get('cache_ref_bytes', 0))} "
                f"shared_h2d:{int(d.get('shared_h2d_bytes', 0))} "
                f"lanes:{len(cop.tpu.lanes) if cop._tpu else 1} "
                f"reroutes:{int(d.get('lane_reroutes', 0))} "
                f"spills:{int(d.get('lane_spills', 0))}"
            )
        if cop._tpu:
            # per-device breakers (PR 6): one state per runner lane; the
            # aggregate reads `open` when every lane is open (= cop path
            # fully drained to host), `open(k/n)` for a partial outage
            lanes = cop.tpu.lanes
            n_open = sum(1 for l in lanes if l.breaker.state == "open")
            n_half = sum(1 for l in lanes if l.breaker.state == "half-open")
            if n_open == len(lanes):
                agg = "open"
            elif n_open:
                agg = f"open({n_open}/{len(lanes)})"
            elif n_half:
                agg = f"half-open({n_half}/{len(lanes)})"
            else:
                agg = "closed"
            lines.append(
                f"tpu: compiles:{cop.tpu.compile_count - tpu0[0]} "
                f"fallbacks:{cop.tpu.fallbacks - tpu0[1]} "
                f"breaker:{agg} trips:{sum(l.breaker.trips for l in lanes)}"
            )
        if d.get("route_decisions"):
            # feedback-routing line (PR 20): how many auto-engine
            # decisions this statement took, how many exploited learned
            # history vs explored the static heuristic, and the LAST
            # decision's verdict with the evidence the router cited
            rline = (
                f"route: decisions:{int(d['route_decisions'])} "
                f"history:{int(d.get('route_history', 0))} "
                f"explore:{int(d.get('route_explore', 0))}"
            )
            last = cop.last_route
            if last is not None:
                rline += (
                    f" last:{last.get('decision')}"
                    f" reason:{last.get('reason')}"
                    f" evidence:[{last.get('evidence', '')}]"
                )
            lines.append(rline)
        if decision is not None:
            # routing line: the node a follower-read statement was (or
            # would be) served by, or the typed fallback reason
            if decision.get("outcome") == "follower":
                lines.append(
                    f"replica: name:{decision.get('replica')} "
                    f"lag_ms:{decision.get('lag_ms', 0.0):.1f}"
                )
            else:
                lines.append(
                    f"replica: fallback reason:{decision.get('reason', '')}"
                )
        lines.append(f"total: {wall_ms:.3f}ms")
        chk = Chunk.from_datum_rows([ft_varchar()], [[Datum.s(l)] for l in lines])
        return ResultSet(["plan"], chk)

from .session import Session, ResultSet

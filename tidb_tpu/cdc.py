"""Change data capture (ref: br/pkg/cdclog/ + store/driver/txn/binlog.go
— the commit-time hook TiCDC/binlog drain from, re-expressed as an
in-process change feed over the percolator commit path).

The reference emits row-change events at transaction commit: cdclog
writes (commit_ts, table, row) entries sinks replay in commit order;
binlog attaches prewrite values to the 2PC. Here `ChangeFeed` registers
on the Storage and receives every committed mutation batch exactly once,
AFTER the commit point (phase 2 succeeded on the primary — the txn is
durable), with decoded table/row identity for record keys.

Sinks: any callable(list[ChangeEvent]); `FileSink` appends the cdclog-
style JSON lines. Events within one txn share commit_ts and arrive in
key order; delivery holds the feed lock, so sinks see whole-txn batches
serially. Across CONCURRENT committers the delivery order may trail the
commit_ts order (commit_ts acquisition and publication are not one
atomic step) — every event carries its commit_ts, so strict replay
sorts by it, exactly like cdclog consumers resolve file interleaving.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass


@dataclass(frozen=True)
class ChangeEvent:
    commit_ts: int
    start_ts: int
    table_id: int | None  # None: non-record key (index/meta)
    handle: int | None
    op: str  # 'put' | 'delete'
    key: bytes
    value: bytes | None  # encoded row (None for deletes)


class ChangeFeed:
    """Commit-time event bus; attach via Storage.cdc.subscribe()."""

    def __init__(self):
        self._sinks: list = []
        self._lock = threading.Lock()

    def subscribe(self, sink) -> None:
        with self._lock:
            self._sinks.append(sink)

    def unsubscribe(self, sink) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    @property
    def active(self) -> bool:
        return bool(self._sinks)

    def publish(self, start_ts: int, commit_ts: int, muts) -> None:
        """Called by Txn.commit after phase 2 on the primary. `muts` is
        the sorted mutation list (key order within the txn)."""
        if not self._sinks:
            return
        from .codec import tablecodec
        from .storage.mvcc import OP_DEL, OP_LOCK, OP_PUT

        events = []
        for m in muts:
            if m.op == OP_LOCK:
                continue
            tid = handle = None
            if tablecodec.is_record_key(m.key):
                tid = tablecodec.decode_table_id(m.key)
                handle = tablecodec.decode_record_handle(m.key)
            events.append(ChangeEvent(
                commit_ts, start_ts, tid, handle,
                "delete" if m.op == OP_DEL else "put",
                m.key, m.value if m.op == OP_PUT else None,
            ))
        if not events:
            return
        # deliver under the lock: sinks see txn batches one at a time
        with self._lock:
            for sink in list(self._sinks):
                sink(events)


class FileSink:
    """cdclog-style JSON-lines sink (ref: br/pkg/cdclog file layout —
    one ts-ordered log of row changes)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def __call__(self, events: list[ChangeEvent]) -> None:
        with self._lock, open(self.path, "a") as f:
            for e in events:
                f.write(json.dumps({
                    "commit_ts": e.commit_ts,
                    "start_ts": e.start_ts,
                    "table_id": e.table_id,
                    "handle": e.handle,
                    "op": e.op,
                    "key": e.key.hex(),
                    "value": e.value.hex() if e.value is not None else None,
                }) + "\n")

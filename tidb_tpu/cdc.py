"""Change data capture (ref: br/pkg/cdclog/ + store/driver/txn/binlog.go
— the commit-time hook TiCDC/binlog drain from, re-expressed as an
in-process change feed over the percolator commit path).

The reference emits row-change events at transaction commit: cdclog
writes (commit_ts, table, row) entries sinks replay in commit order;
binlog attaches prewrite values to the 2PC. Here `ChangeFeed` registers
on the Storage and receives every committed mutation batch exactly once,
AFTER the commit point (phase 2 succeeded on the primary — the txn is
durable), with decoded table/row identity for record keys.

Sinks: any callable(list[ChangeEvent]); `FileSink` appends the cdclog-
style JSON lines. Events within one txn share commit_ts and arrive in
key order; delivery holds the feed lock, so sinks see whole-txn batches
serially. Across CONCURRENT committers the delivery order may trail the
commit_ts order (commit_ts acquisition and publication are not one
atomic step) — every event carries its commit_ts, so strict replay
sorts by it, exactly like cdclog consumers resolve file interleaving.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from dataclasses import dataclass


@dataclass(frozen=True)
class ChangeEvent:
    commit_ts: int
    start_ts: int
    table_id: int | None  # None: non-record key (index/meta)
    handle: int | None
    op: str  # 'put' | 'delete'
    key: bytes
    value: bytes | None  # encoded row (None for deletes)


class ChangeFeed:
    """Commit-time event bus; attach via Storage.cdc.subscribe()."""

    def __init__(self):
        self._sinks: list = []
        self._lock = threading.Lock()

    def subscribe(self, sink) -> None:
        with self._lock:
            self._sinks.append(sink)

    def unsubscribe(self, sink) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    @property
    def active(self) -> bool:
        return bool(self._sinks)

    def publish(self, start_ts: int, commit_ts: int, muts) -> None:
        """Called by Txn.commit after phase 2 on the primary. `muts` is
        the sorted mutation list (key order within the txn)."""
        if not self._sinks:
            return
        from .codec import tablecodec
        from .storage.mvcc import OP_DEL, OP_LOCK, OP_PUT

        events = []
        for m in muts:
            if m.op == OP_LOCK:
                continue
            tid = handle = None
            if tablecodec.is_record_key(m.key):
                tid = tablecodec.decode_table_id(m.key)
                handle = tablecodec.decode_record_handle(m.key)
            events.append(ChangeEvent(
                commit_ts, start_ts, tid, handle,
                "delete" if m.op == OP_DEL else "put",
                m.key, m.value if m.op == OP_PUT else None,
            ))
        if not events:
            return
        # deliver under the lock: sinks see txn batches one at a time
        with self._lock:
            for sink in list(self._sinks):
                sink(events)


class FileSink:
    """cdclog-style JSON-lines sink (ref: br/pkg/cdclog file layout —
    one ts-ordered log of row changes).

    Durable mode (PR 14): `durable=True` fsyncs the file on a cadence
    (`fsync_interval_s`; 0 = every batch) so the sink honestly survives
    SIGKILL — the crashpoint CDC-not-ahead invariant is then checked
    against bytes that were really on disk, not page cache the crash may
    or may not have flushed. `rotate_bytes` caps segment size: a full
    segment renames to `<path>.NNNNNN` (dir-fsynced in durable mode) and
    a fresh live file opens; `segments(path)` lists rotated + live parts
    in write order for consumers/checkers."""

    def __init__(self, path: str, durable: bool = False,
                 fsync_interval_s: float = 0.0, rotate_bytes: int | None = None):
        self.path = path
        self.durable = durable
        self.fsync_interval_s = fsync_interval_s
        self.rotate_bytes = rotate_bytes
        self._lock = threading.Lock()
        self._f = None
        self._rotations = 0
        self._last_fsync = 0.0

    def __call__(self, events: list[ChangeEvent]) -> None:
        with self._lock:
            f = self._open_locked()
            for e in events:
                f.write(json.dumps({
                    "commit_ts": e.commit_ts,
                    "start_ts": e.start_ts,
                    "table_id": e.table_id,
                    "handle": e.handle,
                    "op": e.op,
                    "key": e.key.hex(),
                    "value": e.value.hex() if e.value is not None else None,
                }) + "\n")
            f.flush()
            if self.durable:
                now = time.time()
                if now - self._last_fsync >= self.fsync_interval_s:
                    os.fsync(f.fileno())
                    self._last_fsync = now
            if self.rotate_bytes is not None and f.tell() >= self.rotate_bytes:
                self._rotate_locked()

    def _open_locked(self):
        if self._f is None:
            self._f = open(self.path, "a", encoding="utf8")
            # resuming over earlier rotations: continue the numbering
            existing = glob.glob(self.path + ".*")
            if existing and self._rotations == 0:
                self._rotations = len(existing)
        return self._f

    def _rotate_locked(self) -> None:
        f = self._f
        if self.durable:
            os.fsync(f.fileno())
        f.close()
        self._f = None
        os.replace(self.path, f"{self.path}.{self._rotations:06d}")
        self._rotations += 1
        if self.durable:
            d = os.path.dirname(os.path.abspath(self.path))
            fd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                if self.durable:
                    self._f.flush()
                    os.fsync(self._f.fileno())
                self._f.close()
                self._f = None

    @staticmethod
    def segments(path: str) -> list[str]:
        """Rotated segments (write order) + the live file, existing only."""
        out = sorted(glob.glob(path + ".*"))
        if os.path.exists(path):
            out.append(path)
        return out

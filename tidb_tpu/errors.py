"""MySQL-compatible error space (ref: errno/errno.go, util/dbterror)."""


class TiDBError(Exception):
    code = 1105  # ER_UNKNOWN_ERROR

    def __init__(self, msg: str = ""):
        super().__init__(msg)
        self.msg = msg


class ParseError(TiDBError):
    code = 1064


class UnknownDatabase(TiDBError):
    code = 1049


class UnknownTable(TiDBError):
    code = 1146


class TableExists(TiDBError):
    code = 1050


class UnknownColumn(TiDBError):
    code = 1054


class AmbiguousColumn(TiDBError):
    code = 1052


class DuplicateEntry(TiDBError):
    code = 1062


class WriteConflict(TiDBError):
    """Optimistic transaction write-write conflict (ref: kv/error.go ErrWriteConflict)."""

    code = 9007


class LockedError(TiDBError):
    """Key is locked by another in-flight transaction (percolator lock)."""

    code = 9008

    def __init__(self, msg="", key=None, lock=None):
        super().__init__(msg)
        self.key = key
        self.lock = lock


class DeadlockError(TiDBError):
    """Pessimistic lock wait closed a cycle (MySQL ER_LOCK_DEADLOCK)."""


class RetryableError(TiDBError):
    code = 9009


class TxnAborted(TiDBError):
    code = 9010


class DivisionByZero(TiDBError):
    code = 1365


class DataOutOfRange(TiDBError):
    code = 1690


class TruncatedWrongValue(TiDBError):
    code = 1292


class QueryInterrupted(TiDBError):
    code = 1317


class MemoryQuotaExceeded(TiDBError):
    code = 8175


class ServerMemoryExceeded(MemoryQuotaExceeded):
    """The store-wide tidb_server_memory_limit was breached and THIS
    statement was the top consumer: the arbiter (utils/memory
    ServerMemTracker) fails the allocator in place instead of flagging
    its session (ref: util/servermemorylimit killSessIfNeeded)."""


class RunawayKilled(QueryInterrupted):
    """A statement crossed its resource group's QUERY_LIMIT with
    ACTION=KILL (ref: ErrResourceGroupQueryRunawayInterrupted, 8253).
    Subclasses QueryInterrupted so every interrupt-aware wait (admission,
    backoff, chunk boundaries) treats it like the kill it is."""

    code = 8253

    def __init__(self, msg: str = ""):
        super().__init__(msg)
        self.reason = "runaway"


class RunawayQuarantined(RunawayKilled):
    """A statement whose digest sits in the runaway watch list was
    rejected at admission, before consuming a ticket (ref:
    ErrResourceGroupQueryRunawayQuarantine, 8254)."""

    code = 8254


class ResourceGroupExists(TiDBError):
    """CREATE RESOURCE GROUP on an existing name (ref: ErrResourceGroupExists)."""

    code = 8248


class ResourceGroupNotExists(TiDBError):
    """ALTER/DROP/SET on an unknown resource group (ref: ErrResourceGroupNotExists)."""

    code = 8249


# --- cop-path retriable taxonomy (ref: store/tikv/retry + kv/error.go) ----
#
# The Backoffer (copr/retry.py) classifies every fault on the cop path into
# one of these before deciding whether/how long to back off; the blanket
# `except Exception` the device fallback used to hide behind is gone.


class RegionError(TiDBError):
    """A cop task's view of the region map went stale mid-flight — always
    retriable after re-locating (ref: errorpb region errors, 9005)."""

    code = 9005

    def __init__(self, msg: str = "", region_id: int | None = None):
        super().__init__(msg)
        self.region_id = region_id


class EpochNotMatch(RegionError):
    """Region split/merged since the task was built: the (id, epoch, span)
    no longer matches — re-split the remaining range (ref: EpochNotMatch)."""


class NotLeader(RegionError):
    """Region leadership moved stores; same data, new leader — retry the
    SAME task against the new leader, no re-split (ref: NotLeader)."""


class ServerBusy(RegionError):
    """Store rejected the task under load — retriable with a longer,
    decorrelated backoff (ref: ServerIsBusy, 9003)."""

    code = 9003


class ResourceGroupQueueFull(ServerBusy):
    """Admission queue overflow under sustained overload — the in-process
    ServerBusy: the cop client retries it through the Backoffer's
    serverBusy class before surfacing (ref: ErrResourceGroupThrottled
    8252; TiKV's ServerIsBusy→BoTiKVServerBusy loop)."""

    code = 8252


class DeviceError(TiDBError):
    """Base for TPU-engine faults classified at the engine boundary."""

    code = 9013


class DeviceTransientError(DeviceError):
    """Retriable device fault (preempted/ busy/ tunnel hiccup): worth a
    backoff-retry on the device path before conceding to the host."""


class DeviceFatalError(DeviceError):
    """Non-retriable device fault (miscompile, crashed runtime): feeds the
    circuit breaker; `auto` traffic falls back to host immediately."""

    code = 9014


class CircuitBreakerOpen(TiDBError):
    """TPU engine breaker is open: `engine='tpu'` requests fail fast with
    the breaker state instead of paying the fault cost per query."""

    code = 9015


class BackoffExhausted(TiDBError):
    """A cop task spent its whole backoff sleep budget and still failed;
    the message names the region, per-class attempt counts and last error."""

    code = 9004


# --- durability fault domain (storage/wal.py + storage/txn.py) --------------
#
# The disk joins the typed taxonomy: an IO failure on the WAL poisons the
# log (fsyncgate discipline: after one failed fsync the page cache is in
# an unknowable state, so NOTHING may ever ack again), and recovery
# refuses to guess when the log is corrupt rather than merely torn.


class StorageIOError(TiDBError):
    """A WAL append/fsync failed: the store is read-only degraded.
    Commits fail loud with this error (no false acks — the fsyncgate
    failure mode), reads keep serving the recovered state."""

    code = 9016


class WalCorruptionError(TiDBError):
    """Recovery found corruption it will not silently drop: a mid-log
    frame with valid CRC frames after it (bit rot inside committed
    history, NOT a torn tail), or a corrupt/short snapshot payload.
    Governed by `tidb_wal_recovery_mode` — the default tolerates only a
    torn tail; `drop-corrupt` is the explicit opt-in to salvage past
    corrupt log frames (never past a corrupt snapshot)."""

    code = 9017


class CommitIndeterminateError(StorageIOError):
    """The commit IN FLIGHT at the moment of a WAL failure: the error
    landed AT the durability point (after phase 2, during the fsync), so
    the outcome is UNKNOWN — the group leader's fsync may still have
    covered it, a spare-dir rotation may have snapshotted it, or it may
    be gone with the page cache. The ack is withheld (never falsified),
    but unlike a plain `StorageIOError` — which means the commit
    determinately did NOT happen — the client must treat this one as
    undetermined (ref: ErrResultUndetermined, 8150). Subclasses
    StorageIOError so every existing degrade handler keeps working."""

    code = 8150


class StandbyReadOnly(TiDBError):
    """The store is a warm standby replaying a primary's shipped WAL:
    writes are rejected until `ADMIN PROMOTE` flips it read-write
    (MySQL --super-read-only analog, ER_OPTION_PREVENTS_STATEMENT)."""

    code = 1290

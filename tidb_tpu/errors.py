"""MySQL-compatible error space (ref: errno/errno.go, util/dbterror)."""


class TiDBError(Exception):
    code = 1105  # ER_UNKNOWN_ERROR

    def __init__(self, msg: str = ""):
        super().__init__(msg)
        self.msg = msg


class ParseError(TiDBError):
    code = 1064


class UnknownDatabase(TiDBError):
    code = 1049


class UnknownTable(TiDBError):
    code = 1146


class TableExists(TiDBError):
    code = 1050


class UnknownColumn(TiDBError):
    code = 1054


class AmbiguousColumn(TiDBError):
    code = 1052


class DuplicateEntry(TiDBError):
    code = 1062


class WriteConflict(TiDBError):
    """Optimistic transaction write-write conflict (ref: kv/error.go ErrWriteConflict)."""

    code = 9007


class LockedError(TiDBError):
    """Key is locked by another in-flight transaction (percolator lock)."""

    code = 9008

    def __init__(self, msg="", key=None, lock=None):
        super().__init__(msg)
        self.key = key
        self.lock = lock


class DeadlockError(TiDBError):
    """Pessimistic lock wait closed a cycle (MySQL ER_LOCK_DEADLOCK)."""


class RetryableError(TiDBError):
    code = 9009


class TxnAborted(TiDBError):
    code = 9010


class DivisionByZero(TiDBError):
    code = 1365


class DataOutOfRange(TiDBError):
    code = 1690


class TruncatedWrongValue(TiDBError):
    code = 1292


class QueryInterrupted(TiDBError):
    code = 1317


class MemoryQuotaExceeded(TiDBError):
    code = 8175


class ResourceGroupExists(TiDBError):
    """CREATE RESOURCE GROUP on an existing name (ref: ErrResourceGroupExists)."""

    code = 8248


class ResourceGroupNotExists(TiDBError):
    """ALTER/DROP/SET on an unknown resource group (ref: ErrResourceGroupNotExists)."""

    code = 8249


class ResourceGroupQueueFull(TiDBError):
    """Admission queue overflow under sustained overload — the backpressure
    hard edge (ref: ErrResourceGroupThrottled 8252)."""

    code = 8252

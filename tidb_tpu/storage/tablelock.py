"""Table-lock bookkeeping — LOCK TABLES ... READ|WRITE
(ref: lock/lock.go Checker + table lock state on TableInfo; single
process, so the registry lives in memory on the Storage and conflicts
answer immediately with the MySQL error instead of queueing).
"""

from __future__ import annotations

import threading

from ..errors import TiDBError


class TableLockError(TiDBError):
    pass


class TableLocks:
    """table_id → (mode, {conn_id}); WRITE holds exactly one owner."""

    def __init__(self):
        self._locks: dict[int, tuple[str, set[int]]] = {}
        self._names: dict[int, str] = {}
        self._lock = threading.Lock()

    def acquire(self, conn: int, items: list[tuple[int, str, str]]) -> None:
        """Atomically take [(table_id, name, READ|WRITE)]; all-or-nothing
        (MySQL acquires the whole LOCK TABLES list or fails)."""
        with self._lock:
            for tid, name, mode in items:
                cur = self._locks.get(tid)
                if cur is None:
                    continue
                cmode, owners = cur
                others = owners - {conn}
                if others and (mode == "WRITE" or cmode == "WRITE"):
                    raise TableLockError(
                        f"Table '{name}' was locked in {cmode} by session {min(others)}"
                    )
            for tid, name, mode in items:
                cmode, owners = self._locks.get(tid, (mode, set()))
                if owners == {conn} or not owners:
                    self._locks[tid] = (mode, {conn})
                else:
                    self._locks[tid] = (cmode, owners | {conn})
                self._names[tid] = name

    def release_all(self, conn: int) -> None:
        with self._lock:
            for tid in list(self._locks):
                mode, owners = self._locks[tid]
                owners.discard(conn)
                if not owners:
                    del self._locks[tid]
                    self._names.pop(tid, None)

    def held_by(self, conn: int) -> dict[int, str]:
        with self._lock:
            return {tid: m for tid, (m, owners) in self._locks.items() if conn in owners}

    def check_read(self, tid: int, name: str, conn: int) -> None:
        """Reads fail only against another session's WRITE lock."""
        with self._lock:
            cur = self._locks.get(tid)
            if cur is None:
                return
            mode, owners = cur
            if mode == "WRITE" and conn not in owners:
                raise TableLockError(
                    f"Table '{name}' was locked in WRITE by session {min(owners)}"
                )

    def check_write(self, tid: int, name: str, conn: int) -> None:
        """Writes fail against any READ lock (even the caller's own) and
        against another session's WRITE lock."""
        with self._lock:
            cur = self._locks.get(tid)
            if cur is None:
                return
            mode, owners = cur
            if mode == "READ":
                if conn in owners:
                    raise TableLockError(
                        f"Table '{name}' was locked with a READ lock and can't be updated"
                    )
                raise TableLockError(
                    f"Table '{name}' was locked in READ by session {min(owners)}"
                )
            if conn not in owners:
                raise TableLockError(
                    f"Table '{name}' was locked in WRITE by session {min(owners)}"
                )

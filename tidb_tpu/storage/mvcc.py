"""Percolator MVCC over the ordered KV (ref: unistore/tikv/mvcc — behavior
spec; the column-family encoding here is a fresh design).

Key layout inside one MemKV:
  lock   CF: b'l' + user_key                     → Lock record
  write  CF: b'w' + user_key + rev_ts(commit_ts) → WriteRecord
  default CF: b'd' + user_key + rev_ts(start_ts) → row value

rev_ts inverts the timestamp so ascending key order visits newest commits
first — a snapshot read is "seek to (key, read_ts), take first".

Transactional verbs (the tikv/server.go:149-466 surface): prewrite,
commit, rollback, check_txn_status, resolve, get/batch_get/scan.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from ..errors import LockedError, WriteConflict, TxnAborted
from .memkv import MemKV

OP_PUT = 0
OP_DEL = 1
OP_ROLLBACK = 2
OP_LOCK = 3  # lock-only record (SELECT FOR UPDATE)

_MAX = 0xFFFFFFFFFFFFFFFF


def rev_ts(ts: int) -> bytes:
    return struct.pack(">Q", _MAX - ts)


def unrev_ts(b: bytes) -> int:
    return _MAX - struct.unpack(">Q", b)[0]


@dataclass
class Lock:
    op: int
    primary: bytes
    start_ts: int
    ttl_ms: int
    for_update_ts: int = 0
    min_commit_ts: int = 0

    def encode(self) -> bytes:
        return struct.pack(">BQQQQH", self.op, self.start_ts, self.ttl_ms, self.for_update_ts, self.min_commit_ts, len(self.primary)) + self.primary

    @staticmethod
    def decode(b: bytes) -> "Lock":
        op, start_ts, ttl, fut, mct, plen = struct.unpack_from(">BQQQQH", b)
        off = struct.calcsize(">BQQQQH")
        return Lock(op, b[off : off + plen], start_ts, ttl, fut, mct)


@dataclass
class WriteRecord:
    op: int
    start_ts: int

    def encode(self) -> bytes:
        return struct.pack(">BQ", self.op, self.start_ts)

    @staticmethod
    def decode(b: bytes) -> "WriteRecord":
        op, start_ts = struct.unpack(">BQ", b[:9])
        return WriteRecord(op, start_ts)


@dataclass
class Mutation:
    op: int  # OP_PUT / OP_DEL / OP_LOCK
    key: bytes
    value: bytes = b""


def _lk(key: bytes) -> bytes:
    return b"l" + key


def _wk(key: bytes, ts: int) -> bytes:
    return b"w" + key + rev_ts(ts)


def _dk(key: bytes, ts: int) -> bytes:
    return b"d" + key + rev_ts(ts)


class MVCCStore:
    """One region-server's transactional KV (single process, many regions)."""

    def __init__(self, kv: MemKV | None = None):
        self.kv = kv or MemKV()
        # data-version counters per table-prefix space are maintained above
        # (storage.Storage) — the MVCC layer stays schema-agnostic.

    # --- reads ------------------------------------------------------------

    def _check_lock(self, key: bytes, read_ts: int):
        raw = self.kv.get(_lk(key))
        if raw is None:
            return
        lock = Lock.decode(raw)
        if lock.op == OP_LOCK:
            return  # lock-only records don't block reads
        if lock.start_ts <= read_ts:
            raise LockedError(f"key is locked by txn {lock.start_ts}", key=key, lock=lock)

    def _visible_write(self, key: bytes, read_ts: int) -> WriteRecord | None:
        for k, v in self.kv.iter_from(_wk(key, read_ts)):
            if not k.startswith(b"w" + key) or len(k) != 1 + len(key) + 8:
                return None
            rec = WriteRecord.decode(v)
            if rec.op in (OP_PUT, OP_DEL):
                return rec
            # rollbacks / lock-records: keep looking at older versions
        return None

    def get(self, key: bytes, read_ts: int) -> bytes | None:
        self._check_lock(key, read_ts)
        rec = self._visible_write(key, read_ts)
        if rec is None or rec.op == OP_DEL:
            return None
        return self.kv.get(_dk(key, rec.start_ts))

    def batch_get(self, keys: list[bytes], read_ts: int) -> dict[bytes, bytes]:
        out = {}
        for k in keys:
            v = self.get(k, read_ts)
            if v is not None:
                out[k] = v
        return out

    def scan(self, start: bytes, end: bytes, read_ts: int, limit: int | None = None):
        """Snapshot range scan → list of (user_key, value)."""
        out = []
        # collect blocking locks in range first (reader must resolve)
        for k, raw in self.kv.scan(_lk(start), _lk(end)):
            lock = Lock.decode(raw)
            if lock.op != OP_LOCK and lock.start_ts <= read_ts:
                raise LockedError("range contains locked key", key=k[1:], lock=lock)
        cur = start
        it = self.kv.iter_from(b"w" + cur)
        last_key = None
        for k, v in it:
            if not k.startswith(b"w") or (end is not None and k[1:-8] >= end):
                break
            ukey = k[1:-8]
            if ukey == last_key:
                continue  # older version of an already-decided key
            ts = unrev_ts(k[-8:])
            if ts > read_ts:
                continue  # newer than snapshot; keep scanning same key
            last_key = ukey
            rec = WriteRecord.decode(v)
            if rec.op == OP_PUT:
                val = self.kv.get(_dk(ukey, rec.start_ts))
                out.append((ukey, val))
                if limit is not None and len(out) >= limit:
                    break
            elif rec.op == OP_DEL:
                continue
            else:
                # rollback/lock record newest-visible: older versions may
                # still be visible — rare path, do a point get
                val_rec = self._visible_write(ukey, read_ts)
                if val_rec and val_rec.op == OP_PUT:
                    out.append((ukey, self.kv.get(_dk(ukey, val_rec.start_ts))))
                    if limit is not None and len(out) >= limit:
                        break
        return out

    # --- writes (percolator) ---------------------------------------------

    def prewrite(self, muts: list[Mutation], primary: bytes, start_ts: int, ttl_ms: int = 3000, for_update_ts: int = 0):
        """First phase: lock every key and stage values."""
        with self.kv.lock:
            for m in muts:
                raw = self.kv.get(_lk(m.key))
                if raw is not None:
                    lock = Lock.decode(raw)
                    if lock.start_ts != start_ts:
                        raise LockedError(f"key locked by {lock.start_ts}", key=m.key, lock=lock)
                    continue  # idempotent re-prewrite
                # write-conflict check: any commit newer than our snapshot?
                for k, v in self.kv.iter_from(b"w" + m.key):
                    if not k.startswith(b"w" + m.key) or len(k) != 1 + len(m.key) + 8:
                        break
                    committed = unrev_ts(k[-8:])
                    rec = WriteRecord.decode(v)
                    if rec.op == OP_ROLLBACK and rec.start_ts == start_ts:
                        raise TxnAborted(f"txn {start_ts} already rolled back")
                    if committed > start_ts and rec.op in (OP_PUT, OP_DEL) and for_update_ts == 0:
                        raise WriteConflict(f"conflict at {committed} > start {start_ts}")
                    break
                self.kv.put(_lk(m.key), Lock(m.op, primary, start_ts, ttl_ms, for_update_ts).encode())
                if m.op == OP_PUT:
                    self.kv.put(_dk(m.key, start_ts), m.value)

    def commit(self, keys: list[bytes], start_ts: int, commit_ts: int):
        with self.kv.lock:
            for key in keys:
                raw = self.kv.get(_lk(key))
                if raw is None:
                    # already committed (retry) or rolled back?
                    st = self._find_txn_write(key, start_ts)
                    if st is not None and st.op != OP_ROLLBACK:
                        continue  # idempotent
                    raise TxnAborted(f"commit of missing lock, txn {start_ts}")
                lock = Lock.decode(raw)
                if lock.start_ts != start_ts:
                    raise TxnAborted(f"lock owned by {lock.start_ts}, not {start_ts}")
                op = OP_PUT if lock.op == OP_PUT else (OP_DEL if lock.op == OP_DEL else OP_LOCK)
                self.kv.put(_wk(key, commit_ts), WriteRecord(op, start_ts).encode())
                self.kv.delete(_lk(key))

    def rollback(self, keys: list[bytes], start_ts: int):
        with self.kv.lock:
            for key in keys:
                raw = self.kv.get(_lk(key))
                if raw is not None:
                    lock = Lock.decode(raw)
                    if lock.start_ts == start_ts:
                        self.kv.delete(_lk(key))
                        self.kv.delete(_dk(key, start_ts))
                # tombstone so late prewrites of this txn fail
                self.kv.put(_wk(key, start_ts), WriteRecord(OP_ROLLBACK, start_ts).encode())

    def _find_txn_write(self, key: bytes, start_ts: int) -> WriteRecord | None:
        for k, v in self.kv.iter_from(b"w" + key):
            if not k.startswith(b"w" + key) or len(k) != 1 + len(key) + 8:
                return None
            rec = WriteRecord.decode(v)
            if rec.start_ts == start_ts:
                return rec
        return None

    def check_txn_status(self, primary: bytes, start_ts: int, now_ms: int) -> tuple[str, int]:
        """→ ('committed', commit_ts) | ('rolled_back', 0) | ('locked', ttl) —
        and rolls back expired primary locks (ref: tikv/server.go:285)."""
        raw = self.kv.get(_lk(primary))
        if raw is not None:
            lock = Lock.decode(raw)
            if lock.start_ts == start_ts:
                from .tso import TSO

                if TSO.physical_ms(start_ts) + lock.ttl_ms < now_ms:
                    self.rollback([primary], start_ts)
                    return "rolled_back", 0
                return "locked", lock.ttl_ms
        rec_ts = self._find_commit(primary, start_ts)
        if rec_ts is not None:
            return "committed", rec_ts
        # no lock, no commit: treat as rolled back (and tombstone it)
        self.rollback([primary], start_ts)
        return "rolled_back", 0

    def _find_commit(self, key: bytes, start_ts: int) -> int | None:
        for k, v in self.kv.iter_from(b"w" + key):
            if not k.startswith(b"w" + key) or len(k) != 1 + len(key) + 8:
                return None
            rec = WriteRecord.decode(v)
            if rec.start_ts == start_ts and rec.op in (OP_PUT, OP_DEL, OP_LOCK):
                return unrev_ts(k[-8:])
        return None

    def resolve_lock(self, key: bytes, lock: Lock, now_ms: int) -> bool:
        """Resolve one blocking lock via its primary. True if cleared."""
        status, commit_ts = self.check_txn_status(lock.primary, lock.start_ts, now_ms)
        if status == "committed":
            self.commit([key], lock.start_ts, commit_ts)
            return True
        if status == "rolled_back":
            self.rollback([key], lock.start_ts)
            return True
        return False

    def ingest(self, kvs: list[tuple[bytes, bytes]], commit_ts: int) -> None:
        """Bulk ingest pre-committed data, bypassing 2PC (ref:
        br/pkg/lightning local backend — builds SSTs and ingests)."""
        pairs = []
        for k, v in kvs:
            pairs.append((_wk(k, commit_ts), WriteRecord(OP_PUT, commit_ts).encode()))
            pairs.append((_dk(k, commit_ts), v))
        self.kv.bulk_load(pairs)

    def unsafe_destroy_range(self, start: bytes, end: bytes) -> int:
        """Physically remove ALL versions/locks in a user-key range —
        the delete-range verb used when tables are dropped/truncated
        (ref: gc_worker delete-ranges; tikv UnsafeDestroyRange)."""
        n = 0
        for cf in (b"d", b"w", b"l"):
            n += self.kv.delete_range(cf + start, cf + end)
        return n

    # --- GC (ref: store/gcworker) -----------------------------------------

    def gc(self, safe_point: int) -> int:
        """Drop versions no snapshot at/after safe_point can see."""
        removed = 0
        with self.kv.lock:
            doomed_w: list[bytes] = []
            doomed_d: list[bytes] = []
            last_key = None
            kept_newest = False
            for k, v in list(self.kv.iter_from(b"w")):
                if not k.startswith(b"w"):
                    break
                ukey, ts = k[1:-8], unrev_ts(k[-8:])
                if ukey != last_key:
                    last_key, kept_newest = ukey, False
                rec = WriteRecord.decode(v)
                if ts > safe_point:
                    continue
                if rec.op not in (OP_PUT, OP_DEL):
                    # rollback/lock markers are not data versions: safe to
                    # drop once no pre-safepoint txn can prewrite again —
                    # and they must NOT count as the kept newest version
                    doomed_w.append(k)
                    continue
                if not kept_newest:
                    kept_newest = True
                    if rec.op == OP_DEL:  # newest visible is a delete: drop it too
                        doomed_w.append(k)
                        doomed_d.append(_dk(ukey, rec.start_ts))
                    continue
                doomed_w.append(k)
                doomed_d.append(_dk(ukey, rec.start_ts))
            for k in doomed_w + doomed_d:
                self.kv.delete(k)
                removed += 1
        return removed

"""Percolator MVCC over the ordered KV (ref: unistore/tikv/mvcc — behavior
spec; the column-family encoding here is a fresh design).

Key layout inside one MemKV:
  lock   CF: b'l' + user_key                     → Lock record
  write  CF: b'w' + user_key + rev_ts(commit_ts) → WriteRecord
  default CF: b'd' + user_key + rev_ts(start_ts) → row value

rev_ts inverts the timestamp so ascending key order visits newest commits
first — a snapshot read is "seek to (key, read_ts), take first".

Transactional verbs (the tikv/server.go:149-466 surface): prewrite,
commit, rollback, check_txn_status, resolve, get/batch_get/scan.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from ..errors import LockedError, WriteConflict, TxnAborted
from .memkv import MemKV

OP_PUT = 0
OP_DEL = 1
OP_ROLLBACK = 2
OP_LOCK = 3  # lock-only record (SELECT FOR UPDATE)
OP_PESSIMISTIC = 4  # pessimistic lock, no staged data (ref: tikv LockType::Pessimistic)

_MAX = 0xFFFFFFFFFFFFFFFF


def rev_ts(ts: int) -> bytes:
    return struct.pack(">Q", _MAX - ts)


def unrev_ts(b: bytes) -> int:
    return _MAX - struct.unpack(">Q", b)[0]


@dataclass
class Lock:
    op: int
    primary: bytes
    start_ts: int
    ttl_ms: int
    for_update_ts: int = 0
    min_commit_ts: int = 0

    def encode(self) -> bytes:
        return struct.pack(">BQQQQH", self.op, self.start_ts, self.ttl_ms, self.for_update_ts, self.min_commit_ts, len(self.primary)) + self.primary

    @staticmethod
    def decode(b: bytes) -> "Lock":
        op, start_ts, ttl, fut, mct, plen = struct.unpack_from(">BQQQQH", b)
        off = struct.calcsize(">BQQQQH")
        return Lock(op, b[off : off + plen], start_ts, ttl, fut, mct)


@dataclass
class WriteRecord:
    op: int
    start_ts: int

    def encode(self) -> bytes:
        return struct.pack(">BQ", self.op, self.start_ts)

    @staticmethod
    def decode(b: bytes) -> "WriteRecord":
        op, start_ts = struct.unpack(">BQ", b[:9])
        return WriteRecord(op, start_ts)


@dataclass
class Mutation:
    op: int  # OP_PUT / OP_DEL / OP_LOCK
    key: bytes
    value: bytes = b""


def _lk(key: bytes) -> bytes:
    return b"l" + key


def _wk(key: bytes, ts: int) -> bytes:
    return b"w" + key + rev_ts(ts)


class CompactionRaced(Exception):
    """A write slipped under the fold timestamp between artifact build
    and publish — the compactor aborts the round (nothing journaled,
    nothing visible) and retries on a later tick."""


def _retire_match(run, table_id: int, tprefix: bytes,
                  kind: int, aux: int, cts: int) -> bool:
    """Does `run` match a Z-record retire identity? Identities are stable
    across alive-mask compaction (checkpoint snapshots rewrite runs with
    dead rows squeezed out, so positions/first-keys may drift between the
    live publish and a snapshot+tail replay — commit_ts, table ids and
    key width do not)."""
    from .segment import ColumnarRun, IntIndexRun, Run

    if run.commit_ts != cts:
        return False
    if kind == 0:
        return isinstance(run, ColumnarRun) and run.table_id == table_id
    if kind == 1:
        return isinstance(run, IntIndexRun) and run.table_id == table_id and run.index_id == aux
    # byte run: width + table scope via the first key's prefix (runs
    # never span tables — every producer builds them per table)
    return (type(run) is Run and run.w == aux and run.n > 0
            and run.key_at(0).startswith(tprefix))


def _dk(key: bytes, ts: int) -> bytes:
    return b"d" + key + rev_ts(ts)


class MVCCStore:
    """One region-server's transactional KV (single process, many regions).

    Two planes:
      - mutable plane: lock/write/default CFs in the ordered MemKV — the
        percolator write path (prewrite/commit), versioned per key;
      - ingest plane: immutable sorted `Run` segments (storage/segment.py),
        one commit_ts per run — the Lightning-SST / TiFlash-replica analog.
    Reads merge both; newer commit_ts wins per key.
    """

    def __init__(self, kv: MemKV | None = None):
        # NOT `kv or MemKV()`: an empty MemKV is falsy (__len__ == 0) and
        # would silently orphan the caller's store
        self.kv = kv if kv is not None else MemKV()
        self.runs: list = []  # Run segments, ascending commit_ts
        # data-version counters per table-prefix space are maintained above
        # (storage.Storage) — the MVCC layer stays schema-agnostic.
        # liveness hook (start_ts -> bool), installed by the owning
        # Storage: the in-process analog of the reference's txn TTL
        # heartbeat. check_txn_status consults it before TTL-expiring a
        # primary lock — a CPU-starved but LIVE transaction must not have
        # its locks stolen by an impatient waiter (the bank-transfer
        # flake: a >TTL scheduler stall between lock acquisition and
        # commit let a sibling roll back a live txn, which then died with
        # TxnAborted instead of the retryable contract errors). Orphans
        # stay resolvable: a crashed process's recovered locks, and
        # simulated dead txns using raw TSO values, are not registered.
        self.txn_live = None

    # --- reads ------------------------------------------------------------

    def _check_lock(self, key: bytes, read_ts: int):
        raw = self.kv.get(_lk(key))
        if raw is None:
            return
        lock = Lock.decode(raw)
        if lock.op in (OP_LOCK, OP_PESSIMISTIC):
            return  # lock-only / pessimistic locks stage no data: reads pass
        if lock.start_ts <= read_ts:
            raise LockedError(f"key is locked by txn {lock.start_ts}", key=key, lock=lock)

    def _visible_write(self, key: bytes, read_ts: int) -> tuple[WriteRecord, int] | None:
        """Newest visible PUT/DEL record → (record, commit_ts)."""
        for k, v in self.kv.iter_from(_wk(key, read_ts)):
            if not k.startswith(b"w" + key) or len(k) != 1 + len(key) + 8:
                return None
            rec = WriteRecord.decode(v)
            if rec.op in (OP_PUT, OP_DEL):
                return rec, unrev_ts(k[-8:])
            # rollbacks / lock-records: keep looking at older versions
        return None

    def _run_get(self, key: bytes, read_ts: int) -> tuple[bytes | None, int]:
        """Newest run entry visible at read_ts → (value, commit_ts)."""
        for run in reversed(self.runs):
            if run.commit_ts > read_ts:
                continue
            i = run.find(key)
            if i >= 0:
                return run.value(i), run.commit_ts
        return None, 0

    def _run_newest_commit(self, key: bytes) -> int:
        for run in reversed(self.runs):
            if run.find(key) >= 0:
                return run.commit_ts
        return 0

    def get(self, key: bytes, read_ts: int) -> bytes | None:
        self._check_lock(key, read_ts)
        found = self._visible_write(key, read_ts)
        rval, rts = self._run_get(key, read_ts) if self.runs else (None, 0)
        if found is not None:
            rec, cts = found
            if cts >= rts:  # mutable write newer than any run entry
                if rec.op == OP_DEL:
                    return None
                return self.kv.get(_dk(key, rec.start_ts))
        return rval

    def batch_get(self, keys: list[bytes], read_ts: int) -> dict[bytes, bytes]:
        out = {}
        for k in keys:
            v = self.get(k, read_ts)
            if v is not None:
                out[k] = v
        return out

    def _scan_mut(self, start: bytes, end: bytes | None, read_ts: int):
        """Mutable-plane scan → [(user_key, value | None-for-delete, commit_ts)]."""
        out = []
        it = self.kv.iter_from(b"w" + start)
        last_key = None
        for k, v in it:
            if not k.startswith(b"w") or (end is not None and k[1:-8] >= end):
                break
            ukey = k[1:-8]
            if ukey < start:
                # iter_from(b"w"+start) can land mid-version-space of the
                # PRECEDING user key when `start` falls strictly inside a
                # stored key's (ukey || rev_ts) span — e.g. a region split
                # at a non-record-key boundary (chaos found this): the
                # rev_ts bytes of ukey's versions sort above start's
                # suffix. Half-open [start, end) means ukey >= start.
                continue
            if ukey == last_key:
                continue  # older version of an already-decided key
            ts = unrev_ts(k[-8:])
            if ts > read_ts:
                continue  # newer than snapshot; keep scanning same key
            last_key = ukey
            rec = WriteRecord.decode(v)
            if rec.op == OP_PUT:
                out.append((ukey, self.kv.get(_dk(ukey, rec.start_ts)), ts))
            elif rec.op == OP_DEL:
                out.append((ukey, None, ts))
            else:
                # rollback/lock record newest-visible: older versions may
                # still be visible — rare path, do a point get
                found = self._visible_write(ukey, read_ts)
                if found and found[0].op == OP_PUT:
                    out.append((ukey, self.kv.get(_dk(ukey, found[0].start_ts)), found[1]))
                elif found:
                    out.append((ukey, None, found[1]))
        return out

    def _check_range_locks(self, start: bytes, end: bytes | None, read_ts: int) -> None:
        # cap at b"m": the l-CF's end — an open-ended scan must not run
        # into the next CF's keys
        hi = _lk(end) if end is not None else b"m"
        for k, raw in self.kv.scan(_lk(start), hi):
            lock = Lock.decode(raw)
            if lock.op not in (OP_LOCK, OP_PESSIMISTIC) and lock.start_ts <= read_ts:
                raise LockedError("range contains locked key", key=k[1:], lock=lock)

    def scan_segments(self, start: bytes, end: bytes | None, read_ts: int):
        """Snapshot range scan without materializing per-row objects:
        → (segments: list[SegmentView], loose: list[(user_key, value)]).

        Segments are slices of ingest runs visible at read_ts; `loose` is
        the (usually small) mutable plane. Shadowing is resolved here:
        newer runs drop duplicate keys from older ones, and mutable writes
        newer than a run entry drop it (a mutable DELETE suppresses it)."""
        from .segment import SegmentView

        self._check_range_locks(start, end, read_ts)
        mut = self._scan_mut(start, end, read_ts)
        segs: list[SegmentView] = []
        for run in self.runs:  # ascending commit_ts
            if run.commit_ts > read_ts:
                continue
            i, j = run.range(start, end)
            if i < j:
                segs.append(SegmentView(run, i, j))
        # run-vs-run: a newer run shadows duplicate keys in older runs.
        # Pairs can only collide when key widths match (different widths
        # can't encode equal keys) and commit_ts differs (one bulk_load's
        # runs share a ts and are disjoint by construction) — so the
        # per-key set walk below runs only on genuine re-ingest overlap.
        for bi in range(1, len(segs)):
            b = segs[bi]
            for ai in range(bi):
                a = segs[ai]
                if (
                    a.run.w == b.run.w
                    and a.run.commit_ts != b.run.commit_ts
                    and a.min_key() <= b.max_key()
                    and b.min_key() <= a.max_key()
                ):
                    bkeys = {b.run.key_at(i) for i in range(b.i, b.j)}
                    drop = {idx for idx in range(a.i, a.j) if a.run.key_at(idx) in bkeys}
                    if drop:
                        a.drop = (a.drop or set()) | drop
        loose: list[tuple[bytes, bytes]] = []
        for k, v, ts in mut:
            shadowed = False
            for s in segs:
                idx = s.run.find(k)
                if s.i <= idx < s.j:
                    if s.run.commit_ts > ts:
                        shadowed = True  # run entry is newer — run wins
                    else:
                        s.drop = (s.drop or set()) | {idx}
            if not shadowed and v is not None:
                loose.append((k, v))
        return segs, loose

    def scan(self, start: bytes, end: bytes, read_ts: int, limit: int | None = None):
        """Snapshot range scan → list of (user_key, value), key-ordered."""
        segs, loose = self.scan_segments(start, end, read_ts)
        if not segs:
            out = loose
        else:
            segs.sort(key=lambda s: s.min_key())
            disjoint = all(
                segs[i].max_key() < segs[i + 1].min_key() for i in range(len(segs) - 1)
            )
            out = []
            for s in segs:
                out.extend(s.pairs())
            if loose or not disjoint:
                out.extend(loose)
                out.sort(key=lambda kv: kv[0])
        return out[:limit] if limit is not None else out

    # --- writes (percolator) ---------------------------------------------

    def prewrite(self, muts: list[Mutation], primary: bytes, start_ts: int, ttl_ms: int = 3000, for_update_ts: int = 0, pess_keys=frozenset()):
        """First phase: lock every key and stage values. Keys in
        `pess_keys` were pessimistically locked by this txn: finding them
        unlocked means a waiter resolved them away (TTL expiry) — the txn
        must abort (TiKV's PessimisticLockNotFound)."""
        with self.kv.lock:
            for m in muts:
                raw = self.kv.get(_lk(m.key))
                if raw is None and m.key in pess_keys:
                    raise TxnAborted(
                        f"pessimistic lock on {m.key!r} was resolved away (txn {start_ts})"
                    )
                if raw is not None:
                    lock = Lock.decode(raw)
                    if lock.start_ts != start_ts:
                        raise LockedError(f"key locked by {lock.start_ts}", key=m.key, lock=lock)
                    # our own lock: pessimistic→prewrite conversion (or an
                    # idempotent re-prewrite) replaces it and stages data
                    self.kv.put(_lk(m.key), Lock(m.op, primary, start_ts, ttl_ms, for_update_ts).encode())
                    if m.op == OP_PUT:
                        self.kv.put(_dk(m.key, start_ts), m.value)
                    continue
                # write-conflict check: any commit newer than our snapshot?
                for k, v in self.kv.iter_from(b"w" + m.key):
                    if not k.startswith(b"w" + m.key) or len(k) != 1 + len(m.key) + 8:
                        break
                    committed = unrev_ts(k[-8:])
                    rec = WriteRecord.decode(v)
                    if rec.op == OP_ROLLBACK and rec.start_ts == start_ts:
                        raise TxnAborted(f"txn {start_ts} already rolled back")
                    # keys the txn pessimistically locked never reach here
                    # (the own-lock branch above handles them). Unlocked
                    # keys ARE conflict-checked even in pessimistic txns —
                    # against the current-read horizon for_update_ts (TiKV
                    # constraint-check semantics), start_ts for optimistic.
                    if committed > max(start_ts, for_update_ts) and rec.op in (OP_PUT, OP_DEL):
                        raise WriteConflict(f"conflict at {committed} > start {start_ts}")
                    break
                if self.runs and self._run_newest_commit(m.key) > max(start_ts, for_update_ts):
                    raise WriteConflict(f"ingest-run conflict newer than start {start_ts}")
                self.kv.put(_lk(m.key), Lock(m.op, primary, start_ts, ttl_ms, for_update_ts).encode())
                if m.op == OP_PUT:
                    self.kv.put(_dk(m.key, start_ts), m.value)

    def _newest_commit_ts(self, key: bytes) -> int:
        """Newest PUT/DEL commit ts for a key across both planes."""
        newest = 0
        for k, v in self.kv.iter_from(b"w" + key):
            if not k.startswith(b"w" + key) or len(k) != 1 + len(key) + 8:
                break
            rec = WriteRecord.decode(v)
            if rec.op in (OP_PUT, OP_DEL):
                newest = unrev_ts(k[-8:])
                break
        if self.runs:
            newest = max(newest, self._run_newest_commit(key))
        return newest

    def high_water_ts(self) -> int:
        """Largest timestamp embedded anywhere in the store's durable
        state: commit timestamps in the write CF and segment runs, start
        timestamps staged in the data CF, and the timestamps carried by
        unresolved locks. Recovery and standby promotion seed the TSO
        with this (TSO.advance_to) so a reborn store never allocates a
        read or start timestamp at or below an already-durable commit."""
        hw = 0
        with self.kv.lock:
            for cf in (b"d", b"w"):
                for k, _ in self.kv.iter_from(cf):
                    if not k.startswith(cf):
                        break
                    if len(k) >= 9:
                        hw = max(hw, unrev_ts(k[-8:]))
            for k, raw in self.kv.iter_from(b"l"):
                if not k.startswith(b"l"):
                    break
                try:
                    lock = Lock.decode(raw)
                except (struct.error, IndexError):
                    continue
                hw = max(hw, lock.start_ts, lock.for_update_ts, lock.min_commit_ts)
        for r in self.runs:
            hw = max(hw, r.commit_ts)
        return hw

    def acquire_pessimistic_lock(
        self, keys: list[bytes], primary: bytes, start_ts: int, for_update_ts: int, ttl_ms: int = 3000
    ) -> None:
        """Lock keys at DML time without staging data (ref: unistore
        tikv/server.go:192 KvPessimisticLock). Raises LockedError when a
        key is held by another txn and WriteConflict when a commit newer
        than for_update_ts exists (caller retries with a fresh ts)."""
        with self.kv.lock:
            for key in keys:
                raw = self.kv.get(_lk(key))
                if raw is not None:
                    lock = Lock.decode(raw)
                    if lock.start_ts != start_ts:
                        raise LockedError(f"key locked by {lock.start_ts}", key=key, lock=lock)
                if self._newest_commit_ts(key) > for_update_ts:
                    raise WriteConflict(f"pessimistic lock sees commit newer than {for_update_ts}")
            for key in keys:
                self.kv.put(_lk(key), Lock(OP_PESSIMISTIC, primary, start_ts, ttl_ms, for_update_ts).encode())

    def pessimistic_rollback(self, keys: list[bytes], start_ts: int) -> None:
        """Release pessimistic locks without aborting the txn (no rollback
        tombstone — the txn may still prewrite later)."""
        with self.kv.lock:
            for key in keys:
                raw = self.kv.get(_lk(key))
                if raw is not None:
                    lock = Lock.decode(raw)
                    if lock.start_ts == start_ts and lock.op == OP_PESSIMISTIC:
                        self.kv.delete(_lk(key))

    def commit(self, keys: list[bytes], start_ts: int, commit_ts: int):
        with self.kv.lock:
            for key in keys:
                raw = self.kv.get(_lk(key))
                if raw is None:
                    # already committed (retry) or rolled back?
                    st = self._find_txn_write(key, start_ts)
                    if st is not None and st.op != OP_ROLLBACK:
                        continue  # idempotent
                    raise TxnAborted(f"commit of missing lock, txn {start_ts}")
                lock = Lock.decode(raw)
                if lock.start_ts != start_ts:
                    # a resolver may have rolled this key FORWARD already
                    # (our primary was committed, a blocked reader/writer
                    # resolved the secondary via check_txn_status) and a
                    # NEWER txn locked it since — commit is idempotent on
                    # an already-committed key (TiKV semantics); only a
                    # foreign lock with NO write record of ours is abort
                    st = self._find_txn_write(key, start_ts)
                    if st is not None and st.op != OP_ROLLBACK:
                        continue
                    raise TxnAborted(f"lock owned by {lock.start_ts}, not {start_ts}")
                op = OP_PUT if lock.op == OP_PUT else (OP_DEL if lock.op == OP_DEL else OP_LOCK)
                self.kv.put(_wk(key, commit_ts), WriteRecord(op, start_ts).encode())
                self.kv.delete(_lk(key))

    def rollback(self, keys: list[bytes], start_ts: int):
        with self.kv.lock:
            for key in keys:
                raw = self.kv.get(_lk(key))
                if raw is not None:
                    lock = Lock.decode(raw)
                    if lock.start_ts == start_ts:
                        self.kv.delete(_lk(key))
                        self.kv.delete(_dk(key, start_ts))
                # tombstone so late prewrites of this txn fail
                self.kv.put(_wk(key, start_ts), WriteRecord(OP_ROLLBACK, start_ts).encode())

    def _find_txn_write(self, key: bytes, start_ts: int) -> WriteRecord | None:
        for k, v in self.kv.iter_from(b"w" + key):
            if not k.startswith(b"w" + key) or len(k) != 1 + len(key) + 8:
                return None
            rec = WriteRecord.decode(v)
            if rec.start_ts == start_ts:
                return rec
        return None

    def check_txn_status(self, primary: bytes, start_ts: int, now_ms: int) -> tuple[str, int]:
        """→ ('committed', commit_ts) | ('rolled_back', 0) | ('locked', ttl) —
        and rolls back expired primary locks (ref: tikv/server.go:285)."""
        raw = self.kv.get(_lk(primary))
        if raw is not None:
            lock = Lock.decode(raw)
            if lock.start_ts == start_ts:
                from .tso import TSO

                # TTL counts from the LAST acquisition (for_update_ts is
                # refreshed per pessimistic lock round), so long-lived but
                # active txns aren't rolled back by impatient waiters
                base = max(start_ts, lock.for_update_ts)
                if TSO.physical_ms(base) + lock.ttl_ms < now_ms:
                    live = self.txn_live
                    if live is not None and live(start_ts):
                        # owner is a LIVE registered txn: an expired TTL
                        # means a slow owner, not an abandoned one — keep
                        # the lock; the waiter's own deadline bounds it
                        return "locked", lock.ttl_ms
                    self.rollback([primary], start_ts)
                    return "rolled_back", 0
                return "locked", lock.ttl_ms
        rec_ts = self._find_commit(primary, start_ts)
        if rec_ts is not None:
            return "committed", rec_ts
        # no lock, no commit: treat as rolled back (and tombstone it)
        self.rollback([primary], start_ts)
        return "rolled_back", 0

    def _find_commit(self, key: bytes, start_ts: int) -> int | None:
        for k, v in self.kv.iter_from(b"w" + key):
            if not k.startswith(b"w" + key) or len(k) != 1 + len(key) + 8:
                return None
            rec = WriteRecord.decode(v)
            if rec.start_ts == start_ts and rec.op in (OP_PUT, OP_DEL, OP_LOCK):
                return unrev_ts(k[-8:])
        return None

    def resolve_lock(self, key: bytes, lock: Lock, now_ms: int) -> bool:
        """Resolve one blocking lock via its primary. True if cleared."""
        status, commit_ts = self.check_txn_status(lock.primary, lock.start_ts, now_ms)
        if status == "committed":
            self.commit([key], lock.start_ts, commit_ts)
            return True
        if status == "rolled_back":
            self.rollback([key], lock.start_ts)
            return True
        return False

    def ingest_run(
        self,
        key_mat,
        vbuf: bytes,
        starts,
        lens,
        commit_ts: int,
        presorted: bool = False,
    ) -> None:
        """Bulk ingest one fixed-width-key segment, bypassing 2PC (ref:
        br/pkg/lightning local backend — builds SSTs and ingests). All
        entries become visible atomically at commit_ts."""
        from .segment import Run

        run = Run.build(key_mat, vbuf, starts, lens, commit_ts, presorted=presorted)
        self.ingest_runs([run])

    def ingest_runs(self, runs: list, precondition=None) -> None:
        """Atomic multi-run ingest (PR 15): EVERY run — record plane plus
        index planes — lands under ONE journal record and one lock hold,
        so recovery sees the whole ingest or none of it (all-visible-or-
        absent; the crashpoint `ingest/after-artifact-before-publish`
        invariant). Runs must already be sorted (the Run/ColumnarRun/
        IntIndexRun builders guarantee it).

        `precondition`, when given, runs UNDER the kv lock before the
        journal append — the seam that closes the bulk route's
        check-then-publish race (a commit landing between an advance
        occupancy check and the publish must abort the ingest, never be
        silently shadowed). It must raise to refuse; nothing has been
        journaled or made visible at that point."""
        runs = [r for r in runs if r.n]
        if not runs:
            return
        # kv.lock serializes against checkpoint() snapshotting runs and
        # rotating the journal under the same lock. Journal FIRST: a
        # poisoned WAL (IO-failure degrade) raises out of the append,
        # and journal-first keeps the in-memory runs exactly at the
        # state the durable log describes
        with self.kv.lock:
            if precondition is not None:
                precondition()
            j = getattr(self, "journal", None)
            if j is not None:
                from .wal import iter_ingest_chunks

                # streamed as ONE frame group: the logical record is
                # never materialized whole, so a 16M-row ingest journals
                # at per-run memory instead of holding its entire WAL
                # image resident (recovery re-joins the group and
                # replays it as atomically as the single-frame form)
                j.append_group(iter_ingest_chunks(runs))
                j.sync()  # bulk ingests are their own durability point
            self.runs.extend(runs)
        hook = getattr(self, "split_hook", None)
        if hook is not None:
            for run in runs:
                hook(run)

    def ingest(self, kvs: list[tuple[bytes, bytes]], commit_ts: int) -> None:
        """Bulk ingest arbitrary (key, value) pairs: groups by key width
        into fixed-width runs (one run per width)."""
        import numpy as np

        by_w: dict[int, list[tuple[bytes, bytes]]] = {}
        for k, v in kvs:
            by_w.setdefault(len(k), []).append((k, v))
        for w, group in by_w.items():
            n = len(group)
            key_mat = np.frombuffer(b"".join(k for k, _ in group), dtype=np.uint8).reshape(n, w)
            vbuf = b"".join(v for _, v in group)
            lens = np.fromiter((len(v) for _, v in group), np.int64, n)
            starts = np.zeros(n, dtype=np.int64)
            np.cumsum(lens[:-1], out=starts[1:])
            self.ingest_run(key_mat, vbuf, starts, lens, commit_ts)

    def range_occupied(self, start: bytes, end: bytes) -> bool:
        """Any committed version, ingest-run entry or in-flight LOCK in
        the user-key range? The bulk route's require-empty witness —
        locks count because a prewritten txn's commit would land AFTER
        the ingest and be silently shadowed."""
        for cf in (b"w", b"l"):
            for k, _v in self.kv.iter_from(cf + start):
                if k.startswith(cf) and k[1:] < end:
                    return True
                break
        for run in self.runs:
            i, j = run.range(start, end)
            if i < j and (run.alive is None or run.alive[i:j].any()):
                return True
        return False

    def kill_runs_range(self, start: bytes, end: bytes) -> int:
        n = 0
        for run in self.runs:
            n += run.kill_range(start, end)
        self.runs = [r for r in self.runs if r.alive is None or r.alive.any()]
        return n

    def unsafe_destroy_range(self, start: bytes, end: bytes) -> int:
        """Physically remove ALL versions/locks in a user-key range —
        the delete-range verb used when tables are dropped/truncated
        (ref: gc_worker delete-ranges; tikv UnsafeDestroyRange)."""
        n = 0
        for cf in (b"d", b"w", b"l"):
            n += self.kv.delete_range(cf + start, cf + end)
        # journal the run-kill BEFORE mutating the runs (a K record over a
        # range no run intersects replays as a no-op, so over-journaling
        # when self.runs is non-empty is safe; killing first and then
        # failing the append would leave memory ahead of the durable log)
        j = getattr(self, "journal", None)
        if j is not None and self.runs:
            from .wal import rec_kill_runs

            j.append(rec_kill_runs(start, end))
        n += self.kill_runs_range(start, end)
        return n

    # --- GC (ref: store/gcworker) -----------------------------------------

    def gc(self, safe_point: int) -> int:
        """Drop versions no snapshot at/after safe_point can see."""
        removed = 0
        with self.kv.lock:
            doomed_w: list[bytes] = []
            doomed_d: list[bytes] = []
            last_key = None
            kept_newest = False
            for k, v in list(self.kv.iter_from(b"w")):
                if not k.startswith(b"w"):
                    break
                ukey, ts = k[1:-8], unrev_ts(k[-8:])
                if ukey != last_key:
                    last_key, kept_newest = ukey, False
                rec = WriteRecord.decode(v)
                if ts > safe_point:
                    continue
                if rec.op not in (OP_PUT, OP_DEL):
                    # rollback/lock markers are not data versions: safe to
                    # drop once no pre-safepoint txn can prewrite again —
                    # and they must NOT count as the kept newest version
                    doomed_w.append(k)
                    continue
                if not kept_newest:
                    kept_newest = True
                    if rec.op == OP_DEL:  # newest visible is a delete: drop it too
                        doomed_w.append(k)
                        doomed_d.append(_dk(ukey, rec.start_ts))
                    continue
                doomed_w.append(k)
                doomed_d.append(_dk(ukey, rec.start_ts))
            for k in doomed_w + doomed_d:
                self.kv.delete(k)
                removed += 1
        return removed

    # --- delta-main compaction (PR 16, storage/compact.py) ----------------

    def fold_plan(self, start: bytes, end: bytes, fold_ts: int):
        """Deterministic fold decision for the mutable span [start, end)
        at fold_ts — a pure function of (kv state, runs state, span,
        fold_ts), so WAL replay of a Z record (which carries NO per-key
        deletions) recomputes exactly what the live publish decided.
        Caller must hold kv.lock. Returns (doom, kills, puts):

          doom:  w/d-CF kv keys to delete (every version <= fold_ts of a
                 key that has a visible version there, plus stray
                 rollback/lock markers — mvcc.gc's rules, except the
                 newest visible PUT moves into a segment instead of
                 staying row-major)
          kills: user keys whose entries in runs with commit_ts <
                 fold_ts must die — REQUIRED for deletes: dropping a
                 newest-visible DEL without killing the older run entry
                 would resurrect the run's value (the crashpoint
                 checker's "no resurrected GC'd versions" invariant)
          puts:  (ukey, start_ts, commit_ts) of newest-visible PUTs to
                 fold; their values live at _dk(ukey, start_ts), which
                 is immutable once the w record exists
        """
        doom: list[bytes] = []
        kills: list[bytes] = []
        puts: list[tuple[bytes, int, int]] = []

        def flush(ukey, entries):
            newest = None
            for _wk, ts, rec in entries:
                if rec.op in (OP_PUT, OP_DEL):
                    newest = (ts, rec)
                    break
            if newest is None:
                # only rollback/lock markers at/below fold_ts: drop them,
                # nothing folds and no run entry is disturbed
                doom.extend(wk for wk, _ts, _r in entries)
                return
            for wk, _ts, rec in entries:
                doom.append(wk)
                if rec.op in (OP_PUT, OP_DEL):
                    doom.append(_dk(ukey, rec.start_ts))
            nts, nrec = newest
            # a run entry NEWER than the newest mutable version (a bulk
            # ingest published over txn-written rows) stays authoritative:
            # the mutable tail is shadowed garbage, the run survives
            run_ts = 0
            for r in self.runs:
                if nts < r.commit_ts <= fold_ts and r.find(ukey) >= 0:
                    run_ts = max(run_ts, r.commit_ts)
            if run_ts > nts:
                return
            kills.append(ukey)
            if nrec.op == OP_PUT:
                puts.append((ukey, nrec.start_ts, nts))

        cur = None
        entries: list = []
        for k, v in self.kv.iter_from(b"w" + start):
            if not k.startswith(b"w") or k[1:] >= end:
                break
            ukey, ts = k[1:-8], unrev_ts(k[-8:])
            if ukey != cur:
                if entries:
                    flush(cur, entries)
                cur, entries = ukey, []
            if ts <= fold_ts:  # iteration order is newest-first per key
                entries.append((k, ts, WriteRecord.decode(v)))
        if entries:
            flush(cur, entries)
        return doom, kills, puts

    def apply_compaction(self, table_id: int, fold_ts: int, spans, retire,
                         new_runs, record=None, expect_plans=None,
                         record_chunks=None) -> int:
        """Fold-and-swap one table's delta (PR 16): delete every mutable
        version <= fold_ts in `spans` (recomputed via fold_plan — see
        there for why replay converges), kill run entries the fold
        superseded, retire merged source runs, and publish `new_runs` —
        all under ONE kv-lock hold and ONE journal record, the same
        atomicity discipline as ingest_runs.

        `record` is the pre-built Z payload on the live path (journal
        FIRST, then mutate); `record_chunks` is its streamed form — an
        iterable of chunks journaled as ONE frame group, so the Z image
        is never materialized whole (satellite of PR 17). Replay and
        standby apply pass neither — their journals are detached or the
        frame was already appended upstream.
        `expect_plans`, when given, must equal the recomputed plans or
        CompactionRaced raises with nothing journaled — the live
        publisher's witness that no write slipped under fold_ts between
        artifact build and publish. Returns mutable versions removed."""
        from ..codec import tablecodec

        tprefix = tablecodec.table_prefix(table_id)
        removed = 0
        with self.kv.lock:
            plans = [self.fold_plan(s, e, fold_ts) for s, e in spans]
            if expect_plans is not None and plans != expect_plans:
                raise CompactionRaced(
                    f"table {table_id}: span state changed between fold "
                    f"and publish (will retry)"
                )
            if record is not None or record_chunks is not None:
                j = getattr(self, "journal", None)
                if j is not None:
                    if record_chunks is not None:
                        j.append_group(record_chunks)
                    else:
                        j.append(record)
                    j.sync()  # compactions are their own durability point
            kj = self.kv.journal
            self.kv.journal = None  # the Z record IS these deletions
            try:
                for doom, kills, _puts in plans:
                    for k in doom:
                        self.kv.delete(k)
                    removed += len(doom)
                    # <= fold_ts: equal-ts runs share no keys with the new
                    # fold run ONLY because this kill covers them (scans
                    # never dedup equal-commit_ts runs); entries genuinely
                    # newer than the folded version never reach `kills` —
                    # fold_plan's run-wins guard keeps them
                    for uk in kills:
                        ke = uk + b"\x00"
                        for r in self.runs:
                            if r.commit_ts <= fold_ts:
                                r.kill_range(uk, ke)
            finally:
                self.kv.journal = kj
            if retire:
                self.runs = [
                    r for r in self.runs
                    if not any(_retire_match(r, table_id, tprefix, *t)
                               for t in retire)
                ]
            live = [r for r in new_runs if r.n]
            self.runs.extend(live)
            # scan recency is POSITION in this list (ascending commit_ts
            # invariant); folded runs carry commit_ts = fold_ts, below
            # any later ingest — the stable re-sort keeps position order
            # equal to timestamp order
            self.runs.sort(key=lambda r: r.commit_ts)
            self.runs = [r for r in self.runs if r.alive is None or r.alive.any()]
        hook = getattr(self, "split_hook", None)
        if hook is not None:
            for r in live:
                hook(r)
        return removed

from .memkv import MemKV
from .tso import TSO
from .mvcc import MVCCStore, Lock, WriteRecord
from .txn import Txn, Storage, Snapshot
from .regions import RegionMap, Region

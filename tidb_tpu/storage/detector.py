"""First-waiter deadlock detector for pessimistic locks
(ref: store/mockstore/unistore/tikv/detector.go).

Each transaction waits on at most one holder at a time (the first lock it
blocks on), so the wait-for graph is a function txn → txn and cycle
detection is a pointer chase. The LATER waiter — the one whose edge
closes the cycle — gets the DeadlockError, matching the reference's
first-waiter victim policy.
"""

from __future__ import annotations

import time
from collections import deque
from threading import Lock

from ..errors import DeadlockError


class DeadlockDetector:
    def __init__(self, history_capacity: int = 64):
        self._lock = Lock()
        self._wait_for: dict[int, int] = {}  # waiter start_ts → holder start_ts
        # recent deadlocks for information_schema.deadlocks
        # (ref: util/deadlockhistory)
        self.history: deque = deque(maxlen=history_capacity)
        self._next_id = 1

    def register(self, waiter: int, holder: int) -> None:
        """Record waiter→holder; raises DeadlockError if it closes a cycle."""
        with self._lock:
            cur = holder
            for _ in range(len(self._wait_for) + 1):
                if cur == waiter:
                    self.history.append({
                        "id": self._next_id,
                        "time": time.time(),
                        "try_lock_trx": waiter,
                        "holding_trx": holder,
                    })
                    self._next_id += 1
                    raise DeadlockError(
                        f"Deadlock found when trying to get lock: txn {waiter} waits for {holder}"
                    )
                cur = self._wait_for.get(cur)
                if cur is None:
                    break
            self._wait_for[waiter] = holder

    def done(self, waiter: int) -> None:
        with self._lock:
            self._wait_for.pop(waiter, None)

"""Replica-fleet WAL shipping (PR 14 single standby → PR 17 fan-out) —
the log IS the database, so durability-by-replication is just streaming
it (ref: "Near Data Processing in Taurus Database", arXiv:2506.20010 —
Log Stores replicate the log, Page Stores replay it; MySQL semi-sync
replication is the commit-protocol analog).

`ReplicaSet` taps the primary's `Wal` ONCE (every accepted append
enqueues; see Wal.tap) and fans the stream out to N standbys over
per-link threads — a dead or slow standby never blocks the others. Only
frames the primary has fsynced (`Wal.durable_seq`) ever ship: a standby
must never be ahead of the primary's durable state, or a primary
crash+recovery would leave it holding history the primary lost. Each
standby journals shipped frames into its OWN wal (fresh CRC chain — a
reopened standby replay-verifies the shipped bytes for free), fsyncs
once per batch, applies, and advances its applied watermark.

Frame accounting: every tapped frame gets a global ship sequence
(`gseq`). A standby's bootstrap snapshot is cut under the primary's kv
lock, so the cut gseq cleanly partitions history: frames at/below the
cut are IN the snapshot, frames above it ship. A link's durable horizon
is then `base_gseq + frames-acked-by-the-standby` — counting, not
content inspection, which also gives socket reconnect an exact resync
point. The shared queue prunes at the minimum horizon over live links
(plus not-yet-attached bootstrap cuts), so one slow replica bounds
memory, not correctness.

Transports: in-process (`attach`) and socket (`StandbyServer` /
`attach_socket`) whose wire format reuses the WAL frame shape (u32 len,
u32 crc32, payload) with a sync marker per batch and a cumulative
(count, applied_ts) ack back. The socket link survives transient damage:
on a dropped connection (including a standby-side CRC refusal of a
wire-corrupted frame) it reconnects with bounded backoff, re-handshakes
(`HELLO` → standby instance token + acked count), verifies it is talking
to the SAME standby instance, and resyncs from the last acked frame —
counted in `tidb_ship_reconnects_total{reason}`. A changed token means
the far side restarted (its count restarts too): the link breaks
permanently, re-bootstrap required.

Semi-sync (`tidb_wal_semi_sync`): Storage.wal_sync calls `wait_durable`
after local durability. `ON` keeps the PR 14 contract — the ack means
durable on AT LEAST ONE standby. `QUORUM` waits until the MEDIAN
per-standby durable horizon covers the commit, i.e. a majority
ceil(N/2) of the N registered links acked it. Both waits poll the
shared interrupt gate (KILL / max_execution_time release them; the
commit is then indeterminate, never falsely acked), and an unreachable
quorum (too many broken links) raises the typed indeterminate shape
(8150) instead of blocking forever.

Failover coupling: when the primary degrades and cannot rotate onto a
spare (storage/txn.py online WAL failover), a ReplicaSet constructed
with `auto_promote=True` drains the remaining DURABLE frames and
promotes the in-process standby with the HIGHEST durable horizon (the
N>1 tie-break: it loses the least acked history); the degraded primary
is then permanently fenced (`_failover_disabled`) so a later media heal
cannot create split brain. `rejoin()` heals the fleet afterwards: it
rebuilds the fenced old primary as a standby of the new one — its
divergent unacked tail is discarded wholesale (old logs unlinked) under
a fresh snapshot cut from the new primary, then shipping resumes.

`ReplicaRouter` is the read side: lag-bounded follower reads pick among
in-process replicas by the PR 6 placement shape (atomic choose-and-bump
under one lock, mirroring TPUEngine.place) re-weighted by applied-ts
lag instead of lane occupancy, falling back to the primary when every
replica is too stale.
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import threading
import time
import zlib
from collections import deque

from ..errors import CommitIndeterminateError, TiDBError

log = logging.getLogger(__name__)


def frame_table_prefix(payload: bytes) -> bytes | None:
    """9-byte table prefix (b't' + table_id) a WAL record touches, for
    the standby's data-version bump: replayed frames must invalidate the
    same tile/cop-result caches a primary commit would (Storage.
    bump_version), or standby reads keep serving pre-apply results."""
    if not payload:
        return None
    tag = payload[:1]
    if tag in (b"G", b"g", b"F"):
        return None  # group framing: prefixes come from the joined record
    if tag in (b"P", b"D") and len(payload) >= 5:
        (klen,) = struct.unpack_from("<I", payload, 1)
        key = payload[5 : 5 + klen]
        # kv-layer keys carry a CF prefix byte (d/w/l) before the user key
        if len(key) >= 10 and key[:1] in (b"d", b"w", b"l"):
            return key[1:10]
        return key[:9] if len(key) >= 9 else None
    if tag in (b"X", b"K") and len(payload) >= 5:
        (slen,) = struct.unpack_from("<I", payload, 1)
        start = payload[5 : 5 + slen]
        if len(start) >= 10 and start[:1] in (b"d", b"w", b"l"):
            return start[1:10]
        return start[:9] if len(start) >= 9 else None
    if tag == b"R" and len(payload) >= 21:
        w, n, _cts = struct.unpack_from("<IQQ", payload, 1)
        if n and w >= 9:
            return payload[21 : 21 + 9]  # first row of the key matrix
    if tag == b"C" and len(payload) >= 29:
        from ..codec import tablecodec

        table_id = struct.unpack_from("<QQq", payload, 1)[2]
        return tablecodec.record_prefix(table_id)[:9]
    if tag == b"N" and len(payload) >= 35:
        from ..codec import tablecodec

        table_id = struct.unpack_from("<QQq", payload, 1)[2]
        return tablecodec.record_prefix(table_id)[:9]
    if tag == b"I" and len(payload) >= 13:
        # one logical bulk ingest: every nested run targets one table —
        # the first sub-record's prefix stands for the frame
        (slen,) = struct.unpack_from("<Q", payload, 5)
        return frame_table_prefix(payload[13 : 13 + slen])
    if tag == b"Z" and len(payload) >= 17:
        from ..codec import tablecodec

        (table_id,) = struct.unpack_from("<q", payload, 1)
        return tablecodec.record_prefix(table_id)[:9]
    return None


def frame_commit_ts(payload: bytes) -> int:
    """Best-effort commit_ts carried by one WAL record: R (ingest run)
    records name it outright; P records landing in the write CF encode
    it in the key suffix. Everything else (locks, defaults, deletes,
    group-framing chunks) reports 0 — the applied watermark only ever
    advances on commits."""
    if not payload:
        return 0
    tag = payload[:1]
    if tag in (b"G", b"g", b"F"):
        return 0
    if tag == b"R" and len(payload) >= 21:
        return struct.unpack_from("<IQQ", payload, 1)[2]
    if tag in (b"C", b"N") and len(payload) >= 17:
        return struct.unpack_from("<QQ", payload, 1)[1]
    if tag == b"I" and len(payload) >= 13:
        (slen,) = struct.unpack_from("<Q", payload, 5)
        return frame_commit_ts(payload[13 : 13 + slen])
    if tag == b"Z" and len(payload) >= 17:
        # a compaction frame's fold timestamp: every version it folds is
        # at/below it, so the applied watermark never regresses
        return struct.unpack_from("<Q", payload, 9)[0]
    if tag == b"P" and len(payload) >= 5:
        (klen,) = struct.unpack_from("<I", payload, 1)
        if len(payload) >= 5 + klen and klen >= 9:
            key = payload[5 : 5 + klen]
            if key[:1] == b"w":
                from .mvcc import unrev_ts

                return unrev_ts(key[-8:])
    return 0


class _Link:
    """One primary→standby replication link: transport + horizons.
    All mutable fields are guarded by the owning ReplicaSet's `_cond`
    except the transport objects themselves (only the link's own ship
    thread touches those)."""

    __slots__ = (
        "name", "standby", "sender", "base_gseq", "sent_gseq",
        "durable_gseq", "applied_ts", "error", "thread", "reconnects",
        "route_standby", "ack_wall", "reason", "hb_wall",
    )

    def __init__(self, name: str, base_gseq: int, standby=None, sender=None):
        self.name = name
        self.standby = standby  # in-process standby Storage (or None)
        self.sender = sender  # _SocketSender (or None)
        self.base_gseq = base_gseq  # gseq of the bootstrap snapshot cut
        self.sent_gseq = base_gseq  # highest gseq handed to the transport
        self.durable_gseq = base_gseq  # base + frames acked durable far-side
        self.applied_ts = 0
        self.error: Exception | None = None
        self.thread: threading.Thread | None = None
        self.reconnects = 0  # consecutive failures (resets on a good ack)
        # a socket link whose standby ALSO lives in this process (the
        # embedded-fleet topology: WAL frames over real TCP, follower
        # reads served directly) — routing-only, never a promote target
        self.route_standby = None
        self.ack_wall = 0.0  # wall time of the link's newest durable ack
        # typed break taxonomy (PR 19): peer_closed | io_error | timeout
        # | partitioned | refused — "" while the link is live
        self.reason = ""
        self.hb_wall = 0.0  # wall time of the newest successful wire round trip


class ReplicaSet:
    """Primary-side half of fleet replication: observes appends via the
    Wal tap, fans durable frames out to every attached standby in order,
    and releases semi-sync/quorum waiters as per-link durable horizons
    advance."""

    POLL_S = 0.05  # cond-wait slice (interrupt-gate cadence, like sync_group)
    DRAIN_DEADLINE_S = 5.0  # auto-promote: max wait for durable frames to drain
    RECONNECT_MAX = 5  # consecutive socket failures before the link breaks
    RECONNECT_BACKOFF_S = 0.05  # doubles per consecutive failure, capped
    MONITOR_INTERVAL_S = 0.5  # lag-monitor sampling tick
    STATUS_TIMEOUT_S = 1.0  # per-member bound on the status-RPC fan-out
    HEARTBEAT_MS = 1000  # default tidb_replica_heartbeat_ms (idle-link ping)
    HEARTBEAT_TIMEOUT_MS = 3000  # default tidb_replica_heartbeat_timeout_ms
    QUORUM_TIMEOUT_MS = 10000  # default tidb_replica_quorum_timeout_ms

    def __init__(self, store, auto_promote: bool = False):
        self.store = store
        self.auto_promote = auto_promote
        self._cond = threading.Condition()
        # stop() sets this so reconnect-backoff / drain sleeps wake
        # immediately instead of waiting out the ladder (PR 19)
        self._stop_event = threading.Event()
        # lag monitor (PR 18): samples per-replica staleness into
        # tidb_replica_lag_seconds on a fixed tick; _mon_lock guards the
        # thread handle + last-tick snapshot only (sampling itself walks
        # link_states() with no monitor state held)
        self._mon_lock = threading.Lock()
        self._mon_thread: threading.Thread | None = None
        self._mon_wake = threading.Event()
        self._mon_last = 0.0
        # status-RPC fan-out result slots (one writer thread per member)
        self._status_lock = threading.Lock()
        # FIFO of (wal, local_seq, payload, gseq, enqueue_wall): append
        # order IS ship order; a frame ships only once `local_seq <=
        # wal.durable_seq()`, and FIFO means an undurable frame holds
        # later ones back (order on every standby mirrors the primary log)
        self._queue: deque = deque()
        self._enq_seq = 0  # gseq of the newest tapped frame
        self._pruned_gseq = 0  # highest gseq dropped (durable fleet-wide)
        self._links: list[_Link] = []
        # bootstrap cuts not yet consumed by an attach: abspath(dir) →
        # cut gseq, plus FIFO order for transports that can't name a dir
        self._cuts: dict[str, int] = {}
        self._pending_cuts: list[str] = []
        self._stopped = False
        self._broken: Exception | None = None
        self._promoted = None  # the standby promote picked (rejoin target)
        self.router = ReplicaRouter(self)

    # ------------------------------------------------------- primary wiring

    def bootstrap(self, standby_dir: str) -> None:
        """Seed a standby data dir with a consistent snapshot of the
        primary (subscribe-after-checkpoint: the standby boots from
        snapshot + shipped log tail) and record the ship cut AT THE SAME
        BARRIER — under the primary's kv lock no mutation is mid-flight,
        so every frame after the cut ships to this standby and nothing
        before it does. Call once per standby dir; the first call also
        installs the tap."""
        store = self.store
        if store.wal is None:
            raise TiDBError("WAL shipping requires a durable primary (data_dir)")
        from . import wal as w

        os.makedirs(standby_dir, exist_ok=True)
        key = os.path.abspath(standby_dir)
        with store.kv.lock:
            # the standby starts its own epoch numbering at 0
            payload = store._snapshot_payload_locked(0)
            w.snap_write(os.path.join(standby_dir, "snapshot.bin"), payload)
            w.fsync_dir(standby_dir)
            self.install(store.wal)
            with self._cond:
                self._cuts[key] = self._enq_seq
                if key not in self._pending_cuts:
                    self._pending_cuts.append(key)
        store._shipper = self

    def install(self, wal) -> None:
        """(Re)target the tap — called at bootstrap and by the Storage
        whenever the log rotates (checkpoint epoch bump, spare-dir
        failover): the ship stream is epoch-agnostic, a rotated-away log
        simply drains as fully durable."""
        wal.tap = self._tap
        wal.on_durable = self._on_durable

    def _tap(self, wal, seq: int, payload: bytes) -> None:
        # called under the wal append lock: enqueue only, never block
        with self._cond:
            self._enq_seq += 1
            self._queue.append((wal, seq, payload, self._enq_seq, time.time()))
            self._cond.notify_all()

    def _on_durable(self, wal, covered: int) -> None:
        # called when the primary's fsync high-water advances: wake the
        # link threads (frames just became shippable)
        with self._cond:
            self._cond.notify_all()

    def _take_cut(self, standby_dir: str | None) -> tuple[str, int]:
        """Consume a bootstrap cut for a new link: by dir when known,
        else the oldest unconsumed bootstrap (FIFO pairs bootstrap →
        attach for transports that can't name the far dir)."""
        with self._cond:
            if standby_dir is not None:
                key = os.path.abspath(standby_dir)
                if key not in self._cuts:
                    raise TiDBError(
                        f"standby dir {standby_dir!r} was not bootstrap()ed "
                        f"by this shipper"
                    )
            elif self._pending_cuts:
                key = self._pending_cuts[0]
            else:
                raise TiDBError("bootstrap() the standby dir before attaching")
            if key in self._pending_cuts:
                self._pending_cuts.remove(key)
            return key, self._cuts.pop(key)

    # ---------------------------------------------------------- transports

    def attach(self, standby) -> None:
        """In-process transport: frames land straight in the standby
        Storage's receive path; the link's ship thread starts here."""
        if self.store._shipper is not self:
            raise TiDBError("bootstrap() the standby dir before attaching")
        key, cut = self._take_cut(getattr(standby, "data_dir", None))
        link = _Link(os.path.basename(key) or key, cut, standby=standby)
        self._add_link(link)

    def attach_socket(self, host: str, port: int, connect_timeout: float = 5.0,
                      standby_dir: str | None = None, standby=None) -> None:
        """Socket transport to a StandbyServer: WAL-shaped frames out,
        cumulative (count, applied_ts) ack back after each batch fsync.
        The HELLO handshake learns the standby's instance token and
        already-acked frame count, which seeds the resync point.
        `standby` optionally names the far side's Storage when it lives
        in THIS process (embedded socket fleet): the follower-read
        router may then serve from it directly while the WAL stream
        still exercises the real wire — it is never a promote target."""
        _key, cut = self._take_cut(standby_dir)
        sender = _SocketSender(host, port, connect_timeout)
        sender.io_timeout = self._hb_conf()[1]
        count, applied = sender.connect()
        link = _Link(f"{host}:{port}", cut, sender=sender)
        link.sent_gseq = link.durable_gseq = cut + count
        link.applied_ts = applied
        link.route_standby = standby
        self._add_link(link)

    def _add_link(self, link: _Link) -> None:
        with self._cond:
            if self._stopped:
                raise TiDBError("shipper is stopped")
            self._links.append(link)
            self._cond.notify_all()
        link.thread = threading.Thread(
            target=self._link_run, args=(link,),
            name=f"wal-ship:{link.name}", daemon=True,
        )
        link.thread.start()
        self._start_monitor()

    def _start_monitor(self) -> None:
        with self._mon_lock:
            if self._mon_thread is not None:
                return
            self._mon_thread = threading.Thread(
                target=self._monitor_run, name="fleet-lag-monitor", daemon=True,
            )
            self._mon_thread.start()

    def _monitor_run(self) -> None:
        while True:
            self._mon_wake.wait(self.MONITOR_INTERVAL_S)
            with self._cond:
                if self._stopped:
                    return
            self.monitor_tick()

    def monitor_tick(self) -> None:
        """One lag-monitor sample: each live link's apply staleness
        (wall clock minus its applied watermark, the same measure the
        follower router gates on) lands in the tidb_replica_lag_seconds
        histogram — the SLO signal the lagging-replica inspection rule
        reads. Public so tests can force a tick instead of sleeping."""
        from ..utils import metrics as M

        for s in self.link_states():
            if not s["broken"]:
                M.REPLICA_LAG_SECONDS.observe(s["lag_ms"] / 1e3, replica=s["name"])
        with self._mon_lock:
            self._mon_last = time.time()

    def _hb_conf(self) -> tuple[float, float]:
        """(heartbeat interval, heartbeat deadline) in seconds, read live
        from the store's globals so tests/ops can retune a running fleet.
        The deadline doubles as the socket IO timeout: a black-holed
        link — open, accepting, never answering — surfaces as a typed
        `timeout` break within it instead of a 30s stall."""
        gv = self.store.global_vars
        try:
            hb = int(gv.get("tidb_replica_heartbeat_ms", self.HEARTBEAT_MS))
            tmo = int(gv.get("tidb_replica_heartbeat_timeout_ms",
                             self.HEARTBEAT_TIMEOUT_MS))
        except (TypeError, ValueError):
            hb, tmo = self.HEARTBEAT_MS, self.HEARTBEAT_TIMEOUT_MS
        return max(hb, 10) / 1e3, max(tmo, 10) / 1e3

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
            threads = [l.thread for l in self._links]
        self._stop_event.set()
        self._mon_wake.set()
        with self._mon_lock:
            mon = self._mon_thread
        me = threading.current_thread()
        for t in [*threads, mon]:
            if t is not None and t is not me:
                t.join(timeout=5.0)

    @property
    def broken(self) -> Exception | None:
        """First link error once EVERY link is broken (the single-standby
        shape callers test), else any shipper-level failure."""
        with self._cond:
            errs = [l.error for l in self._links]
            if errs and all(e is not None for e in errs):
                return next(e for e in errs if e is not None)
            return self._broken

    def link_states(self) -> list[dict]:
        """Ops/test introspection: one dict per link, including the
        CLUSTER_REPLICATION fields — transport kind, apply staleness and
        the broken reason. Lag is the router's own eligibility measure:
        wall clock minus the link's applied watermark (ts = physical ms
        << 18), NOT `mvcc.high_water_ts()` — the high-water read scans
        both CFs under the kv lock, which a periodic monitor tick must
        never do to a serving primary."""
        now_ms = time.time() * 1000
        with self._cond:
            return [
                {
                    "name": l.name, "base_gseq": l.base_gseq,
                    "durable_gseq": l.durable_gseq, "applied_ts": l.applied_ts,
                    "broken": l.error is not None, "reconnects": l.reconnects,
                    "transport": "inproc" if l.standby is not None else "socket",
                    # applied_ts == 0 means nothing shipped since the
                    # bootstrap snapshot (which is complete by the cut):
                    # not lag, just an idle link
                    "lag_ms": (round(max(0.0, now_ms - (l.applied_ts >> 18)), 3)
                               if l.applied_ts else 0.0),
                    # typed taxonomy first (peer_closed | io_error |
                    # timeout | partitioned | refused), detail after —
                    # CLUSTER_REPLICATION's BROKEN_REASON and the
                    # broken-link inspection rule render this verbatim
                    "reason": (f"{l.reason or 'error'}: "
                               f"{type(l.error).__name__}: {l.error}"
                               if l.error is not None else ""),
                    "ack_wall": l.ack_wall,
                }
                for l in self._links
            ]

    def fleet_statuses(self, timeout_s: float | None = None,
                       detail: bool = True) -> list[dict]:
        """Fleet-wide status fan-out for the CLUSTER_* memtables and
        /debug/fleet: the primary answers directly, in-process members
        are read directly, socket members go over the status RPC — each
        on its own thread with a bounded per-member timeout, so a dead
        or hung node contributes one `{"name", "error"}` entry (partial
        rows) instead of hanging the query. `detail=False` strips the
        bulky metrics/statements payloads (the /debug/fleet shape)."""
        timeout_s = self.STATUS_TIMEOUT_S if timeout_s is None else timeout_s
        with self._cond:
            members = [
                (l.name,
                 l.standby if l.standby is not None else l.route_standby,
                 l.sender)
                for l in self._links
            ]
        out = [node_status(self.store, name="primary")]
        results: list = [None] * len(members)

        def fetch(i: int, name: str, standby, sender) -> None:
            try:
                if standby is not None:
                    st = node_status(standby, name=name)
                else:
                    st = fetch_status(sender.host, sender.port, timeout_s)
                    st["name"] = name
            except Exception as e:  # noqa: BLE001 — partial rows, never a hang
                st = {"name": name, "error": f"{type(e).__name__}: {e}"}
            with self._status_lock:
                results[i] = st

        threads = []
        for i, (name, standby, sender) in enumerate(members):
            t = threading.Thread(
                target=fetch, args=(i, name, standby, sender),
                name=f"fleet-status:{name}", daemon=True,
            )
            threads.append(t)
            t.start()
        deadline = time.time() + timeout_s + 0.5
        for t in threads:
            t.join(max(0.0, deadline - time.time()))
        with self._status_lock:
            snap = list(results)
        for i, st in enumerate(snap):
            if st is None:  # the fetch thread outlived the deadline
                st = {"name": members[i][0],
                      "error": f"status timeout after {timeout_s}s"}
            out.append(st)
        if not detail:
            out = [{k: v for k, v in st.items()
                    if k not in ("metrics", "statements")} for st in out]
        return out

    # ----------------------------------------------------------- ship loop

    def _link_run(self, link: _Link) -> None:
        while True:
            hb_s, tmo_s = self._hb_conf()
            if link.sender is not None:
                link.sender.set_timeout(tmo_s)
            with self._cond:
                while (not self._stopped and link.error is None
                       and not (self._queue and self._queue[-1][3] > link.sent_gseq)
                       and not (link.sender is not None
                                and time.time() - link.hb_wall >= hb_s)):
                    self._cond.wait(min(self.POLL_S * 4, hb_s / 2))
                if self._stopped or link.error is not None:
                    return
                pending = [f for f in self._queue if f[3] > link.sent_gseq]
            # durability horizon OUTSIDE our lock: durable_seq takes the
            # wal's own locks, which rank below the ship condition
            horizon: dict[int, int] = {}
            batch = []
            for wal, seq, payload, gseq, t_enq in pending:
                d = horizon.get(id(wal))
                if d is None:
                    d = horizon[id(wal)] = wal.durable_seq()
                if seq > d:
                    break  # FIFO: order on the standby mirrors the log
                batch.append((gseq, payload, t_enq))
            if not batch:
                if (link.sender is not None
                        and time.time() - link.hb_wall >= hb_s
                        and not self._heartbeat(link)):
                    return
                with self._cond:
                    if self._stopped:
                        return
                    self._cond.wait(self.POLL_S)
                self._update_lag()
                continue
            try:
                count, applied = self._deliver(link, batch)
                if link.base_gseq + count < batch[-1][0]:
                    raise ConnectionError(
                        f"standby acked {count} frames < shipped through "
                        f"gseq {batch[-1][0]} (base {link.base_gseq})"
                    )
            except TimeoutError as e:
                # socket.timeout ⊂ OSError, so this arm must come FIRST.
                # A peer that accepted the frames but never answers (a
                # black-holed link) is not worth reconnecting to: break
                # typed within the heartbeat deadline so the link stops
                # pinning quorum waits — the reconnect ladder is for
                # peers that FAIL, not peers that stall
                self._break_link(link, e, reason="timeout")
                return
            except (ConnectionError, OSError) as e:
                if link.sender is not None:
                    r = self._reconnect(link, e)
                    if r is True:
                        continue  # resynced: re-walk the queue from the ack point
                    self._break_link(link, e, reason=r)
                    return
                self._break_link(link, e)
                return
            except Exception as e:  # noqa: BLE001 — standby verdict (refusal)
                self._break_link(link, e, reason="refused")
                return
            from ..utils import metrics as M

            acked_wall = time.time()
            with self._cond:
                link.reconnects = 0
                link.sent_gseq = max(link.sent_gseq, batch[-1][0])
                link.durable_gseq = link.base_gseq + count
                link.applied_ts = max(link.applied_ts, applied)
                link.ack_wall = acked_wall
                link.hb_wall = acked_wall
                self._prune_locked()
                self._cond.notify_all()
            M.REPLICA_DURABLE_FRAMES.set(float(count), replica=link.name)
            M.REPLICA_APPLIED_TS.set(float(link.applied_ts), replica=link.name)
            # enqueue→durable-ack latency of the batch's newest frame:
            # the per-link half of the quorum-wait decomposition
            M.REPLICA_ACK_SECONDS.observe(
                max(0.0, acked_wall - batch[-1][2]), replica=link.name
            )
            self._update_lag()

    def _deliver(self, link: _Link,
                 batch: list[tuple[int, bytes, float]]) -> tuple[int, int]:
        # link-relative frame seqs (gseq − base) ride with the payloads
        # so the standby's receive is idempotent: a duplicated or
        # re-shipped frame can neither double-apply nor double-count the
        # durable horizon (PR 19)
        payloads = [p for _, p, _ in batch]
        seqs = [g - link.base_gseq for g, _, _ in batch]
        if link.standby is not None:
            total = link.standby.receive_frames(payloads, seqs=seqs)
            return total, link.standby.applied_ts
        return link.sender.send_batch(payloads, seqs=seqs)

    def _heartbeat(self, link: _Link) -> bool:
        """Idle-link liveness probe (PR 19): an empty batch is a bare
        SYNC marker the standby acks like any other — no protocol change
        — so a silently dead link breaks typed within the heartbeat
        deadline instead of lurking until the next real frame stalls a
        quorum wait. The ack also refreshes the link's applied watermark
        (follower-read staleness stays honest on an idle fleet). Returns
        False when the link broke (the ship thread exits)."""
        try:
            count, applied = link.sender.send_batch([])
        except TimeoutError as e:
            self._break_link(link, e, reason="timeout")
            return False
        except (ConnectionError, OSError) as e:
            r = self._reconnect(link, e)
            if r is True:
                return True
            self._break_link(link, e, reason=r)
            return False
        except Exception as e:  # noqa: BLE001 — standby verdict (refusal)
            self._break_link(link, e, reason="refused")
            return False
        from ..utils import metrics as M

        now = time.time()
        with self._cond:
            link.reconnects = 0
            link.hb_wall = now
            new = link.base_gseq + count
            if new > link.durable_gseq:
                link.durable_gseq = new
                link.ack_wall = now
            link.applied_ts = max(link.applied_ts, applied)
            self._prune_locked()
            self._cond.notify_all()
        M.REPLICA_APPLIED_TS.set(float(link.applied_ts), replica=link.name)
        return True

    def _reconnect(self, link: _Link, cause: Exception):
        """Bounded reconnect-with-resync for a socket link: a transient
        wire fault (bit-flip → standby CRC refusal → dropped connection,
        or a plain broken pipe) must not silently degrade semi-sync to
        local-only. Resync restarts from the standby's acked count — the
        frames it never acked simply re-ship. Returns True on a resync;
        otherwise the typed break reason the caller hands _break_link —
        "partitioned" once the budget is exhausted without ever reaching
        the peer, "refused" on a token mismatch (a DIFFERENT standby
        instance answered), or the cause's own class."""
        from ..utils import metrics as M

        reason = "peer_closed" if isinstance(cause, ConnectionError) else "io_error"
        while True:
            with self._cond:
                if self._stopped or link.error is not None:
                    return reason
                link.reconnects += 1
                attempt = link.reconnects
            if attempt > self.RECONNECT_MAX:
                return "partitioned"
            M.SHIP_RECONNECTS.inc(reason=reason)
            # stop-event-aware backoff (PR 19): fleet shutdown must not
            # wait out the ladder
            if self._stop_event.wait(
                    min(1.0, self.RECONNECT_BACKOFF_S * (2 ** (attempt - 1)))):
                return reason
            try:
                link.sender.close()
                count, applied = link.sender.connect()
            except (ConnectionError, OSError):
                continue  # counted; try again until the budget runs out
            except TiDBError:
                return "refused"  # token mismatch: a DIFFERENT standby instance
            with self._cond:
                # resync point: everything past the standby's acked count
                # re-ships (it journals/acks strictly in order, so the
                # count IS the durable prefix length)
                link.sent_gseq = link.durable_gseq = link.base_gseq + count
                link.applied_ts = max(link.applied_ts, applied)
                self._cond.notify_all()
            log.warning(
                "ship link %s reconnected (attempt %d, reason=%s): resyncing "
                "from %d acked frames", link.name, attempt, reason, count,
            )
            return True

    def _break_link(self, link: _Link, e: Exception,
                    reason: str | None = None) -> None:
        from ..utils import metrics as M

        if reason is None:
            reason = ("timeout" if isinstance(e, TimeoutError)
                      else "peer_closed" if isinstance(e, ConnectionError)
                      else "io_error" if isinstance(e, OSError)
                      else "refused")
        with self._cond:
            link.error = e
            link.reason = reason
            self._prune_locked()  # a broken link no longer pins the queue
            self._cond.notify_all()
            all_broken = all(l.error is not None for l in self._links)
        if link.sender is not None and reason in ("timeout", "partitioned"):
            # terminal typed breaks share the reconnect counter's reason
            # dimension so dashboards see the new failure classes
            M.SHIP_RECONNECTS.inc(reason=reason)
        log.warning("WAL shipping to %s stopped (%s): %s", link.name, reason, e)
        if all_broken:
            log.warning("ALL replica links are broken: semi-sync acks will "
                        "fail until a standby is re-attached")

    def _prune_locked(self) -> None:
        """Drop queue frames durable on EVERY live link (broken links
        don't pin memory; not-yet-attached bootstrap cuts do, so a
        standby attached after a write burst still gets its tail)."""
        floors = [self._cuts[k] for k in self._pending_cuts]
        floors += [l.durable_gseq for l in self._links if l.error is None]
        if not floors:
            return
        floor = min(floors)
        while self._queue and self._queue[0][3] <= floor:
            f = self._queue.popleft()
            self._pruned_gseq = f[3]

    def _update_lag(self) -> None:
        from ..utils import metrics as M

        with self._cond:
            lag = (time.time() - self._queue[0][4]) if self._queue else 0.0
        M.WAL_SHIP_LAG.set(round(lag, 3))

    # --------------------------------------------------- semi-sync / quorum

    @property
    def can_promote(self) -> bool:
        """Does this shipper hold a promotion target? True only when an
        in-process standby is attached — a socket link cannot promote
        the far side, so primary-degrade handling must fall through to
        the spare re-probe instead of fencing for a promotion that will
        never happen."""
        with self._cond:
            return any(l.standby is not None for l in self._links)

    def _durable_target(self) -> int:
        """Highest gseq durable on the PRIMARY right now: everything
        already pruned (durable fleet-wide) plus the queue's durable
        FIFO prefix. The committer's own frames are covered (its local
        fsync just returned) — another session's appended-yet-unfsynced
        journal frames (pessimistic locks, rollbacks) are deliberately
        NOT: waiting on those would block this ack on durability nobody
        promised, potentially forever."""
        with self._cond:
            pending = list(self._queue)
            target = self._pruned_gseq
        # durability horizon OUTSIDE the ship condition (lock order:
        # durable_seq takes the wal's own locks, ranked below ours)
        horizon: dict[int, int] = {}
        for wal, seq, _p, gseq, _t in pending:
            d = horizon.get(id(wal))
            if d is None:
                d = horizon[id(wal)] = wal.durable_seq()
            if seq > d:
                break  # FIFO: nothing past an unfsynced frame is durable
            target = gseq
        return target

    def wait_durable(self, session=None, deadline=None, mode: str = "ON") -> None:
        """Block until the commit's frames are durable on enough
        standbys. `ON`: one ack suffices (the PR 14 contract). `QUORUM`:
        the MEDIAN per-link durable horizon must cover the commit —
        equivalently a majority ceil(N/2) of the N registered links
        acked it, so any minority of standby losses loses no acked
        commit. KILL / max_execution_time release the wait through the
        shared interrupt gate — the commit is then indeterminate, never
        falsely acked. A stopped shipper, or more broken links than the
        quorum can spare, raises the typed indeterminate shape instead
        of blocking forever. With NO links registered yet (mid-wiring:
        bootstrap done, attach pending) the wait blocks until one
        appears — exactly the single-standby behavior."""
        from ..utils import metrics as M

        tracer = getattr(session, "_tracer", None) if session is not None else None
        t0_wall = time.time()
        t0_perf = time.perf_counter()
        # bounded wait (PR 19): a stalled-but-open majority — every link
        # live, none acking — must convert into the typed indeterminate
        # shape instead of pinning the committer until the links break.
        # 0 disables the bound (the pre-PR-19 wait-forever behavior).
        try:
            quorum_timeout_ms = int(self.store.global_vars.get(
                "tidb_replica_quorum_timeout_ms", self.QUORUM_TIMEOUT_MS))
        except (TypeError, ValueError):
            quorum_timeout_ms = self.QUORUM_TIMEOUT_MS
        target = self._durable_target()
        with self._cond:
            while True:
                links = self._links
                need = 1
                if mode == "QUORUM" and links:
                    need = (len(links) + 1) // 2
                acked = sum(1 for l in links if l.durable_gseq >= target)
                if mode == "QUORUM" and 0 < acked < need:
                    # crash-harness window: a MINORITY of the fleet has
                    # the commit durable, the client has NOT been acked —
                    # dying here must never surface the commit as acked
                    from ..utils.failpoint import inject as _fp

                    _fp("ship/quorum-partial-ack")
                if links and acked >= need:
                    if mode == "QUORUM":
                        M.REPLICA_QUORUM.inc(outcome="acked")
                    self._note_quorum_wait(
                        tracer, t0_wall, t0_perf, mode, target, links
                    )
                    return
                if self._stopped or self._broken is not None:
                    raise CommitIndeterminateError(
                        "semi-sync: the replica fleet is unavailable "
                        f"({self._broken or 'shipper stopped'}); the commit "
                        "is durable locally but UNCONFIRMED on any standby"
                    )
                # a broken link can still COUNT for acks it sent before
                # breaking (those frames ARE durable there), but it can
                # never contribute new ones — if the remaining live links
                # plus already-acked dead ones can't reach the quorum,
                # no amount of waiting helps
                potential = sum(
                    1 for l in links
                    if l.error is None or l.durable_gseq >= target
                )
                if links and potential < need:
                    if mode == "QUORUM":
                        M.REPLICA_QUORUM.inc(outcome="unreachable")
                    raise CommitIndeterminateError(
                        f"semi-sync {mode}: quorum unreachable — {need} "
                        f"ack(s) required, only {potential} link(s) can "
                        f"still provide one; the commit is durable locally "
                        f"but UNCONFIRMED on the fleet"
                    )
                if (quorum_timeout_ms > 0
                        and (time.time() - t0_wall) * 1e3 >= quorum_timeout_ms):
                    if mode == "QUORUM":
                        M.REPLICA_QUORUM.inc(outcome="timeout")
                    raise CommitIndeterminateError(
                        f"semi-sync {mode}: no quorum within "
                        f"tidb_replica_quorum_timeout_ms={quorum_timeout_ms} "
                        f"({acked} of {need} ack(s)); the commit is durable "
                        f"locally but UNCONFIRMED on the fleet"
                    )
                self._cond.wait(self.POLL_S)
                if session is not None or deadline is not None:
                    from ..sched.scheduler import raise_if_interrupted

                    raise_if_interrupted(session, deadline)

    def _note_quorum_wait(self, tracer, t0_wall: float, t0_perf: float,
                          mode: str, target: int, links) -> None:
        """Decompose the commit's replication wait into the statement
        trace: a closed `quorum.wait` span whose tags carry the per-link
        ack timeline (`name:+12.3ms` relative to the wait's start, `pre`
        when the link had already acked before the wait began), plus the
        quorum_wait_ms counter that feeds the slow log /
        STATEMENTS_SUMMARY columns. Called under `_cond` (link fields)
        on the acked path only — the trace lock ranks above wal.ship."""
        if tracer is None:
            return
        dur_s = time.perf_counter() - t0_perf
        tracer.add("quorum_wait_ms", dur_s * 1e3)
        acks = []
        for l in links:
            if l.durable_gseq >= target:
                if l.ack_wall >= t0_wall:
                    acks.append(f"{l.name}:+{(l.ack_wall - t0_wall) * 1e3:.1f}ms")
                else:
                    acks.append(f"{l.name}:pre")
        tracer.closed_span(
            "quorum.wait", dur_s, mode=mode, acks=",".join(acks) or "-"
        )

    def wait_caught_up(self, timeout: float = 10.0) -> bool:
        """Test/ops helper: True once every currently-durable frame is
        durable on every live link (no links: once the queue is empty or
        holds only not-yet-fsynced frames)."""
        end = time.time() + timeout
        while time.time() < end:
            target = self._durable_target()
            with self._cond:
                if self._stopped:
                    return not self._queue
                live = [l for l in self._links if l.error is None]
                if live:
                    if all(l.durable_gseq >= target for l in live):
                        return True
                else:
                    head = self._queue[0] if self._queue else None
            if not live:
                if head is None:
                    return True
                if head[1] > head[0].durable_seq():
                    return True
            time.sleep(self.POLL_S / 2)
        return False

    # ----------------------------------------------------- failover wiring

    def on_primary_degraded(self) -> None:
        """The primary degraded and could NOT rotate onto a spare: drain
        what is durable, then promote the in-process standby with the
        HIGHEST durable horizon (auto_promote only) — with N>1
        candidates that pick loses the least acked history. Frames past
        the primary's last fsync are gone with its page cache — dropping
        them is exactly the never-ahead invariant."""
        with self._cond:
            cands = [l for l in self._links if l.standby is not None and l.error is None]
        if not self.auto_promote or not cands:
            return
        end = time.time() + self.DRAIN_DEADLINE_S
        while time.time() < end:
            target = self._durable_target()
            with self._cond:
                if self._stopped:
                    break
                if any(l.durable_gseq >= target for l in cands):
                    break  # the best candidate holds every durable frame
            time.sleep(self.POLL_S)
        self.stop()
        with self._cond:
            best = max(cands, key=lambda l: l.durable_gseq)
        try:
            best.standby.promote()
        except TiDBError:
            pass  # already promoted by an operator — same outcome
        self._promoted = best.standby
        log.warning(
            "auto-promote: standby %s is the new primary (durable horizon "
            "%d, %d candidate(s))",
            getattr(best.standby, "data_dir", "?"), best.durable_gseq, len(cands),
        )

    # ------------------------------------------------------ rejoin (heal)

    def rejoin(self, old_store) -> None:
        """Rebuild a fenced old primary as a standby of THIS shipper's
        store (the new primary) — the fleet heals instead of shrinking.
        The old store's divergent unacked tail (anything it journaled
        past what the new primary's history contains) is discarded
        wholesale: a fresh snapshot of the new primary is cut (under the
        new primary's kv lock, same barrier as bootstrap), written into
        the old dir under a BUMPED epoch, the old epoch's logs are
        unlinked (the truncate), and the in-memory state is rebuilt from
        the snapshot. Then the dir re-enters the fleet as a normal link
        and shipping resumes. Safe against a crash mid-way: the new
        snapshot names epoch old+1, so recovery from the dir ignores (and
        deletes) the stale old-epoch logs whether or not the unlink
        landed — the same ordering contract as checkpoint()."""
        from ..utils import metrics as M
        from . import wal as w

        store = self.store
        if old_store is store:
            raise TiDBError("ADMIN REJOIN: a store cannot rejoin itself")
        if store.wal is None:
            raise TiDBError("rejoin requires a durable new primary (data_dir)")
        try:
            with old_store._standby_lock:
                if old_store.standby:
                    raise TiDBError(
                        "ADMIN REJOIN: store is already a standby"
                    )
                if not (old_store._failover_disabled or old_store._io_degraded
                        or old_store.wal is None):
                    raise TiDBError(
                        "ADMIN REJOIN: store is a healthy primary — rejoin "
                        "is for a FENCED old primary after failover (fencing "
                        "guards split brain; a healthy primary has nothing "
                        "to rejoin)"
                    )
                data_dir = old_store.data_dir
                new_epoch = old_store._wal_epoch + 1
                with store.kv.lock:
                    # the snapshot payload names the epoch whose log the
                    # rebuilt standby will journal shipped frames into
                    payload = store._snapshot_payload_locked(new_epoch)
                    w.snap_write(os.path.join(data_dir, "snapshot.bin"), payload)
                    w.fsync_dir(data_dir)
                    with self._cond:
                        # the cut pins the queue (like a bootstrap cut)
                        # until the link attaches below — other links'
                        # fast acks must not prune the rejoiner's tail
                        key = os.path.abspath(data_dir)
                        self._cuts[key] = self._enq_seq
                        if key not in self._pending_cuts:
                            self._pending_cuts.append(key)
                    self.install(store.wal)
                # crashpoint: new-primary snapshot durable in the old dir,
                # the old (divergent) logs not yet unlinked, memory not yet
                # rebuilt — recovery must boot from the NEW snapshot and
                # discard the stale epoch's logs
                from ..utils.failpoint import inject as _fp

                _fp("standby/rejoin-mid-truncate")
                old_wal = old_store.wal
                if old_wal is not None:
                    old_wal.tap = None
                    old_wal.on_durable = None
                    old_wal.close()
                for f in os.listdir(data_dir):
                    if f.startswith("wal.") and f.endswith(".log"):
                        os.unlink(os.path.join(data_dir, f))
                w.fsync_dir(data_dir)
                old_store._rebuild_as_standby(payload, new_epoch)
            key, cut = self._take_cut(data_dir)
            link = _Link(os.path.basename(key) or key, cut, standby=old_store)
            self._add_link(link)
        except Exception:
            M.REPLICA_REJOINS.inc(outcome="failed")
            raise
        M.REPLICA_REJOINS.inc(outcome="ok")
        log.warning(
            "REJOIN: fenced old primary %s rebuilt as a standby of %s "
            "(epoch %d, cut gseq %d)", data_dir, store.data_dir, new_epoch, cut,
        )


# the PR 14 name: one shipper, one standby. The fleet generalization
# keeps the class (an N=1 ReplicaSet IS the old shipper, API included).
WalShipper = ReplicaSet


class ReplicaRouter:
    """Lag-bounded follower-read routing (the read half of the fleet).

    Mirrors the PR 6 placement shape (TPUEngine.place): score every
    eligible replica, choose-and-bump atomically under one lock so
    concurrent statements spread instead of dog-piling the same replica
    — but the weight is applied-ts LAG (staleness), blended with
    in-flight statement count, instead of lane occupancy. `None` means
    no replica is eligible (every one too stale / broken / promoted
    away): the caller falls back to the primary."""

    def __init__(self, replica_set: ReplicaSet):
        self._rs = replica_set
        self._lock = threading.Lock()
        self._inflight: dict[int, int] = {}  # id(store) → live statements

    def route(self, as_of_ts: int | None = None, max_lag_ms: int = 5000,
              decision: dict | None = None):
        """Pick a replica for one read-only statement. For `AS OF
        TIMESTAMP t` reads a replica is eligible iff its applied
        watermark has REACHED t (it then serves the exact same snapshot
        the primary would — never a commit above t, never missing one at
        or below it). For plain follower reads eligibility is bounded
        staleness: applied-ts lag within `max_lag_ms`. Returns the
        chosen standby Storage (inflight-bumped: pair with `release`),
        or None for primary fallback. `decision`, when given, is filled
        with the outcome/reason/replica/lag_ms quad so the caller can
        stamp the routing decision onto the statement trace."""
        from ..utils import metrics as M

        with self._rs._cond:
            links = [
                l for l in self._rs._links
                if (l.standby is not None or l.route_standby is not None)
                and l.error is None
            ]
        now_ms = int(time.time() * 1000)
        cands = []
        skip_over_lag = skip_watermark = 0
        for l in links:
            st = l.standby if l.standby is not None else l.route_standby
            if not st.standby:
                continue  # promoted away: it is a primary now
            ats = st.applied_ts
            if as_of_ts is not None:
                if ats < as_of_ts:
                    # hasn't caught up to t: would miss commits <= t
                    skip_watermark += 1
                    continue
                lag_ms = 0.0
            else:
                lag_ms = max(0.0, now_ms - (ats >> 18))
                if lag_ms > max_lag_ms:
                    skip_over_lag += 1
                    continue
            cands.append((st, lag_ms, l.name))
        if not cands:
            outcome = "fallback_stale" if links else "fallback_none"
            reason = ("over_lag" if skip_over_lag
                      else "beyond_watermark" if skip_watermark
                      else "no_replica")
            M.REPLICA_READS.inc(outcome=outcome, reason=reason)
            if decision is not None:
                decision.update(outcome=outcome, reason=reason,
                                replica="", lag_ms=0.0)
            return None
        with self._lock:
            best = min(
                cands,
                key=lambda c: self._inflight.get(id(c[0]), 0)
                + c[1] / max(1.0, float(max_lag_ms)),
            )
            self._inflight[id(best[0])] = self._inflight.get(id(best[0]), 0) + 1
        M.REPLICA_READS.inc(outcome="follower", reason="-")
        if decision is not None:
            decision.update(outcome="follower", reason="-",
                            replica=best[2], lag_ms=round(best[1], 3))
        return best[0]

    def release(self, store) -> None:
        with self._lock:
            n = self._inflight.get(id(store), 0)
            if n <= 1:
                self._inflight.pop(id(store), None)
            else:
                self._inflight[id(store)] = n - 1


# ------------------------------------------------------------------ socket

_FRAME_HDR = struct.Struct("<BII")  # tag, len, crc32
_TAG_FRAME = 0x46  # 'F'
# 'f' (PR 19): seq-tagged frame — payload is an 8-byte link-relative
# frame seq (gseq − base, 1-based) followed by the WAL record, CRC over
# the whole payload. The seq makes the standby's receive idempotent:
# chaos-duplicated frames and resync re-ship overlap apply exactly once
# and never double-count the durable ack. Legacy _TAG_FRAME still works.
_TAG_FRAME_SEQ = 0x66
_TAG_SYNC = 0x53  # 'S'
_TAG_HELLO = 0x48  # 'H' — sender-initiated handshake/resync probe
_TAG_STATUS = 0x51  # 'Q' — fleet status RPC (CLUSTER_* memtable fan-out)
_ACK = struct.Struct("<QQ")  # cumulative durable frame count, applied_ts
_HELLO = struct.Struct("<16sQQ")  # instance token, acked count, applied_ts
_SEQ = struct.Struct("<Q")


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        got = sock.recv(n - len(buf))
        if not got:
            raise ConnectionError("ship peer closed")
        buf += got
    return buf


def node_status(store, name: str = "") -> dict:
    """The status-RPC payload for one store: identity/role, replication
    watermarks, the full metrics registry, and a statements-summary
    snapshot — exactly what the federated CLUSTER_METRICS /
    CLUSTER_STATEMENTS_SUMMARY memtables read, whether the store is
    queried in-process or over the wire."""
    from ..utils.metrics import REGISTRY

    ss = store.stmt_stats
    with ss._lock:
        stmts = [
            {
                "digest": st["digest"], "exec_count": st["exec_count"],
                "sum_latency_s": st["sum_latency_s"], "errors": st["errors"],
                "sample_sql": st["sample_sql"][:256],
            }
            for st in ss.summary.values()
        ]
    return {
        "name": name or os.path.basename(store.data_dir or "") or "memory",
        "role": "standby" if store.standby else "primary",
        "applied_ts": int(store.applied_ts),
        "applied_frames": int(getattr(store, "_applied_frames", 0)),
        "metrics": [[n, lbl, v] for n, lbl, v in REGISTRY.rows()],
        "statements": stmts,
    }


def fetch_status(host: str, port: int, timeout_s: float = 1.0) -> dict:
    """One bounded status-RPC round trip on a FRESH connection — the
    ship link's socket stays dedicated to frames/acks, and a dead or
    hung member costs exactly `timeout_s`, never a blocked query."""
    import json

    sock = socket.create_connection((host, port), timeout=timeout_s)
    try:
        sock.settimeout(timeout_s)
        sock.sendall(_FRAME_HDR.pack(_TAG_STATUS, 0, 0))
        tag, ln, crc = _FRAME_HDR.unpack(_recv_exact(sock, _FRAME_HDR.size))
        if tag != _TAG_STATUS:
            raise ConnectionError(f"unexpected status reply tag {tag:#x}")
        body = _recv_exact(sock, ln)
        if zlib.crc32(body) != crc:
            raise ConnectionError("status reply failed CRC check")
        return json.loads(body)
    finally:
        sock.close()


class _SocketSender:
    """Primary-side socket transport: HELLO handshake on (re)connect,
    WAL-shaped frames + a sync marker per batch, then the standby's
    cumulative (durable count, applied_ts) ack."""

    def __init__(self, host: str, port: int, connect_timeout: float = 5.0):
        self.host = host
        self.port = port
        self.connect_timeout = connect_timeout
        # per-IO deadline, retuned live from tidb_replica_heartbeat_timeout_ms
        # by the ship loop: a peer that accepts but never answers (a
        # black-holed link) surfaces as socket.timeout (TimeoutError)
        # within it — typed `reason=timeout` — instead of a 30s stall
        self.io_timeout = ReplicaSet.HEARTBEAT_TIMEOUT_MS / 1e3
        self.token: bytes | None = None
        self.sock: socket.socket | None = None

    def set_timeout(self, seconds: float) -> None:
        if seconds == self.io_timeout and self.sock is not None:
            return
        self.io_timeout = seconds
        if self.sock is not None:
            try:
                self.sock.settimeout(seconds)
            except OSError:
                pass

    def connect(self) -> tuple[int, int]:
        """(Re)establish the connection and handshake. Returns the
        standby's (acked frame count, applied_ts) — the resync point.
        Raises TiDBError if the far side is a DIFFERENT standby instance
        than the one this link bootstrapped (its frame count restarted
        with it, so count-based resync would corrupt: re-bootstrap)."""
        self.sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout
        )
        self.sock.settimeout(self.io_timeout)
        self.sock.sendall(_FRAME_HDR.pack(_TAG_HELLO, 0, 0))
        token, count, applied = _HELLO.unpack(_recv_exact(self.sock, _HELLO.size))
        if self.token is None:
            self.token = token
        elif token != self.token:
            raise TiDBError(
                "ship resync refused: the standby instance changed (token "
                "mismatch) — its acked-frame count restarted with it, so "
                "resuming by count would corrupt; re-bootstrap the standby"
            )
        return int(count), int(applied)

    def send_batch(self, payloads: list[bytes],
                   seqs: list[int] | None = None) -> tuple[int, int]:
        out = bytearray()
        if seqs is not None:
            # seq'd frames: the standby can discard duplicates (resync
            # overlap, chaos-duplicated frames) instead of re-applying
            for sq, p in zip(seqs, payloads):
                body = _SEQ.pack(sq) + p
                out += _FRAME_HDR.pack(_TAG_FRAME_SEQ, len(body),
                                       zlib.crc32(body))
                out += body
        else:
            for p in payloads:
                out += _FRAME_HDR.pack(_TAG_FRAME, len(p), zlib.crc32(p))
                out += p
        out += _FRAME_HDR.pack(_TAG_SYNC, 0, 0)
        self.sock.sendall(bytes(out))
        count, applied = _ACK.unpack(_recv_exact(self.sock, _ACK.size))
        return int(count), int(applied)

    def close(self) -> None:
        if self.sock is None:
            return
        try:
            self.sock.close()
        except OSError:
            pass
        self.sock = None


class StandbyServer:
    """Standby-side socket transport: validates each frame's CRC (the
    wire reuses the WAL frame shape, so a flipped bit on the wire is
    caught exactly like one on disk — the connection drops and the
    sender resyncs from the acked count), answers HELLO with this
    instance's token + acked count, feeds whole batches to the standby's
    receive path at each sync marker, and acks the cumulative durable
    frame count plus the applied watermark."""

    def __init__(self, standby, host: str = "127.0.0.1", port: int = 0):
        self.standby = standby
        # identifies THIS standby instance across sender reconnects: a
        # restarted standby re-counts applied frames from its recovered
        # state, so a sender must not resume into it by stale count
        self.token = os.urandom(16)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(4)
        self._closing = False
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="standby-server", daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        # one thread per connection: the ship link's connection lives for
        # the fleet's lifetime, so a serial accept loop would starve the
        # short status-RPC connections behind it forever
        while not self._closing:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="standby-server-conn", daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            self._serve(conn)
        except (ConnectionError, OSError, TiDBError) as e:
            log.warning("standby server connection ended: %s", e)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve(self, conn: socket.socket) -> None:
        batch: list[bytes] = []
        seqs: list[int] = []
        total = self.standby._applied_frames
        while not self._closing:
            tag, ln, crc = _FRAME_HDR.unpack(_recv_exact(conn, _FRAME_HDR.size))
            if tag == _TAG_FRAME:
                payload = _recv_exact(conn, ln)
                if zlib.crc32(payload) != crc:
                    # never apply a frame the wire damaged; dropping the
                    # connection makes the sender reconnect and resync
                    # from the last acked count (bounded retries)
                    raise ConnectionError("shipped frame failed CRC check")
                batch.append(payload)
            elif tag == _TAG_FRAME_SEQ:
                payload = _recv_exact(conn, ln)
                if zlib.crc32(payload) != crc:
                    raise ConnectionError("shipped frame failed CRC check")
                seqs.append(_SEQ.unpack_from(payload)[0])
                batch.append(payload[_SEQ.size:])
            elif tag == _TAG_SYNC:
                if batch:
                    total = self.standby.receive_frames(
                        batch, seqs=seqs if seqs else None
                    )
                    batch = []
                    seqs = []
                conn.sendall(_ACK.pack(total, self.standby.applied_ts))
            elif tag == _TAG_HELLO:
                conn.sendall(_HELLO.pack(
                    self.token, self.standby._applied_frames,
                    self.standby.applied_ts,
                ))
            elif tag == _TAG_STATUS:
                import json

                body = json.dumps(node_status(self.standby)).encode()
                conn.sendall(
                    _FRAME_HDR.pack(_TAG_STATUS, len(body), zlib.crc32(body))
                    + body
                )
            else:
                raise ConnectionError(f"unknown ship tag {tag:#x}")

    def close(self) -> None:
        self._closing = True
        try:
            self._sock.close()
        except OSError:
            pass

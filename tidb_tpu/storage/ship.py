"""Warm-standby WAL shipping (PR 14) — the log IS the database, so
durability-by-replication is just streaming it (ref: "Near Data
Processing in Taurus Database", arXiv:2506.20010 — Log Stores replicate
the log, Page Stores replay it; MySQL semi-sync replication is the
commit-protocol analog).

`WalShipper` taps the primary's `Wal` (every accepted append enqueues;
see Wal.tap) and streams frames to a standby data dir — but ONLY frames
the primary has fsynced (`Wal.durable_seq`): the standby must never be
ahead of the primary's durable state, or a primary crash+recovery would
leave the standby holding history the primary lost. The standby journals
each shipped frame into its OWN wal (fresh CRC chain — a reopened
standby replay-verifies the shipped bytes for free), fsyncs once per
batch, applies, and advances `tidb_standby_applied_ts`.

Transports: in-process (`attach` — the crashpoint harness's shape: one
process, two data dirs, SIGKILL kills both, the standby DIR survives)
and a socket (`StandbyServer` / `attach_socket`) whose wire format
reuses the WAL frame shape (u32 len, u32 crc32, payload) with a sync
marker per batch and a cumulative u64 ack back.

Semi-sync (`tidb_wal_semi_sync=ON`): Storage.wal_sync calls
`wait_durable` after local durability — the ack then additionally means
durable-on-standby. The wait polls the shared interrupt gate (KILL /
max_execution_time release it; the commit is then indeterminate, never
falsely acked), and a stopped/broken shipper raises the typed
indeterminate shape instead of blocking forever.

Failover coupling: when the primary degrades and cannot rotate onto a
spare (storage/txn.py online WAL failover), a shipper constructed with
`auto_promote=True` drains the remaining DURABLE frames and promotes the
standby; the degraded primary is then permanently fenced
(`_failover_disabled`) so a later media heal cannot create split brain.
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import threading
import time
import zlib
from collections import deque

from ..errors import CommitIndeterminateError, TiDBError

log = logging.getLogger(__name__)


def frame_table_prefix(payload: bytes) -> bytes | None:
    """9-byte table prefix (b't' + table_id) a WAL record touches, for
    the standby's data-version bump: replayed frames must invalidate the
    same tile/cop-result caches a primary commit would (Storage.
    bump_version), or standby reads keep serving pre-apply results."""
    if not payload:
        return None
    tag = payload[:1]
    if tag in (b"P", b"D") and len(payload) >= 5:
        (klen,) = struct.unpack_from("<I", payload, 1)
        key = payload[5 : 5 + klen]
        # kv-layer keys carry a CF prefix byte (d/w/l) before the user key
        if len(key) >= 10 and key[:1] in (b"d", b"w", b"l"):
            return key[1:10]
        return key[:9] if len(key) >= 9 else None
    if tag in (b"X", b"K") and len(payload) >= 5:
        (slen,) = struct.unpack_from("<I", payload, 1)
        start = payload[5 : 5 + slen]
        if len(start) >= 10 and start[:1] in (b"d", b"w", b"l"):
            return start[1:10]
        return start[:9] if len(start) >= 9 else None
    if tag == b"R" and len(payload) >= 21:
        w, n, _cts = struct.unpack_from("<IQQ", payload, 1)
        if n and w >= 9:
            return payload[21 : 21 + 9]  # first row of the key matrix
    if tag == b"C" and len(payload) >= 29:
        from ..codec import tablecodec

        table_id = struct.unpack_from("<QQq", payload, 1)[2]
        return tablecodec.record_prefix(table_id)[:9]
    if tag == b"N" and len(payload) >= 35:
        from ..codec import tablecodec

        table_id = struct.unpack_from("<QQq", payload, 1)[2]
        return tablecodec.record_prefix(table_id)[:9]
    if tag == b"I" and len(payload) >= 13:
        # one logical bulk ingest: every nested run targets one table —
        # the first sub-record's prefix stands for the frame
        (slen,) = struct.unpack_from("<Q", payload, 5)
        return frame_table_prefix(payload[13 : 13 + slen])
    if tag == b"Z" and len(payload) >= 17:
        from ..codec import tablecodec

        (table_id,) = struct.unpack_from("<q", payload, 1)
        return tablecodec.record_prefix(table_id)[:9]
    return None


def frame_commit_ts(payload: bytes) -> int:
    """Best-effort commit_ts carried by one WAL record: R (ingest run)
    records name it outright; P records landing in the write CF encode
    it in the key suffix. Everything else (locks, defaults, deletes)
    reports 0 — the applied watermark only ever advances on commits."""
    if not payload:
        return 0
    tag = payload[:1]
    if tag == b"R" and len(payload) >= 21:
        return struct.unpack_from("<IQQ", payload, 1)[2]
    if tag in (b"C", b"N") and len(payload) >= 17:
        return struct.unpack_from("<QQ", payload, 1)[1]
    if tag == b"I" and len(payload) >= 13:
        (slen,) = struct.unpack_from("<Q", payload, 5)
        return frame_commit_ts(payload[13 : 13 + slen])
    if tag == b"Z" and len(payload) >= 17:
        # a compaction frame's fold timestamp: every version it folds is
        # at/below it, so the applied watermark never regresses
        return struct.unpack_from("<Q", payload, 9)[0]
    if tag == b"P" and len(payload) >= 5:
        (klen,) = struct.unpack_from("<I", payload, 1)
        if len(payload) >= 5 + klen and klen >= 9:
            key = payload[5 : 5 + klen]
            if key[:1] == b"w":
                from .mvcc import unrev_ts

                return unrev_ts(key[-8:])
    return 0


class WalShipper:
    """Primary-side half of warm-standby replication: observes appends
    via the Wal tap, ships durable frames in order, releases semi-sync
    waiters once the standby confirms its fsync."""

    POLL_S = 0.05  # cond-wait slice (interrupt-gate cadence, like sync_group)
    DRAIN_DEADLINE_S = 5.0  # auto-promote: max wait for durable frames to drain

    def __init__(self, store, auto_promote: bool = False):
        self.store = store
        self.auto_promote = auto_promote
        self._cond = threading.Condition()
        # FIFO of (wal, local_seq, payload, global_seq, enqueue_wall):
        # append order IS ship order; a frame ships only once `local_seq
        # <= wal.durable_seq()`, and FIFO means an undurable frame holds
        # later ones back (order on the standby mirrors the primary log)
        self._queue: deque = deque()
        self._enq_seq = 0
        self._shipped_seq = 0  # highest global seq durable on the standby
        self._receiver = None  # callable(list[payload]) — transport seam
        self._standby = None  # in-process standby Storage (auto-promote target)
        self._stopped = False
        self._broken: Exception | None = None
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------- primary wiring

    def bootstrap(self, standby_dir: str) -> None:
        """Seed a standby data dir with a consistent snapshot of the
        primary (subscribe-after-checkpoint: the standby boots from
        snapshot + shipped log tail) and install the tap AT THE SAME
        BARRIER — under the primary's kv lock no mutation is mid-flight,
        so every frame after the cut ships and nothing before it does."""
        store = self.store
        if store.wal is None:
            raise TiDBError("WAL shipping requires a durable primary (data_dir)")
        from . import wal as w

        os.makedirs(standby_dir, exist_ok=True)
        with store.kv.lock:
            # the standby starts its own epoch numbering at 0
            payload = store._snapshot_payload_locked(0)
            w.snap_write(os.path.join(standby_dir, "snapshot.bin"), payload)
            w.fsync_dir(standby_dir)
            self.install(store.wal)
        store._shipper = self

    def install(self, wal) -> None:
        """(Re)target the tap — called at bootstrap and by the Storage
        whenever the log rotates (checkpoint epoch bump, spare-dir
        failover): the ship stream is epoch-agnostic, a rotated-away log
        simply drains as fully durable."""
        wal.tap = self._tap
        wal.on_durable = self._on_durable

    def _tap(self, wal, seq: int, payload: bytes) -> None:
        # called under the wal append lock: enqueue only, never block
        with self._cond:
            self._enq_seq += 1
            self._queue.append((wal, seq, payload, self._enq_seq, time.time()))
            self._cond.notify_all()

    def _on_durable(self, wal, covered: int) -> None:
        # called when the primary's fsync high-water advances: wake the
        # ship thread (frames just became shippable)
        with self._cond:
            self._cond.notify_all()

    # ---------------------------------------------------------- transports

    def attach(self, standby) -> None:
        """In-process transport: frames land straight in the standby
        Storage's receive path; the ship thread starts here."""
        if self.store._shipper is not self:
            raise TiDBError("bootstrap() the standby dir before attaching")
        self._standby = standby
        self._receiver = standby.receive_frames
        self._start()

    def attach_socket(self, host: str, port: int, connect_timeout: float = 5.0) -> None:
        """Socket transport to a StandbyServer: WAL-shaped frames out,
        cumulative ack back after each batch fsync."""
        sender = _SocketSender(host, port, connect_timeout)
        self._receiver = sender.send_batch
        self._start()

    def _start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="wal-shipper", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)

    @property
    def broken(self) -> Exception | None:
        with self._cond:
            return self._broken

    # ----------------------------------------------------------- ship loop

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stopped:
                    self._cond.wait(self.POLL_S * 4)
                if self._stopped:
                    return
                pending = list(self._queue)
            # durability horizon OUTSIDE our lock: durable_seq takes the
            # wal's own locks, which rank below the ship condition
            horizon: dict[int, int] = {}
            batch = []
            for wal, seq, payload, gseq, t_enq in pending:
                d = horizon.get(id(wal))
                if d is None:
                    d = horizon[id(wal)] = wal.durable_seq()
                if seq > d:
                    break  # FIFO: order on the standby mirrors the log
                batch.append((gseq, payload))
            if not batch:
                with self._cond:
                    if self._stopped:
                        return
                    self._cond.wait(self.POLL_S)
                self._update_lag()
                continue
            try:
                self._receiver([p for _, p in batch])
            except Exception as e:  # noqa: BLE001 — transport/standby verdict
                with self._cond:
                    self._broken = e
                    self._stopped = True
                    self._cond.notify_all()
                log.warning("WAL shipping stopped: %s", e)
                return
            with self._cond:
                for _ in batch:
                    self._queue.popleft()
                self._shipped_seq = batch[-1][0]
                self._cond.notify_all()
            self._update_lag()

    def _update_lag(self) -> None:
        from ..utils import metrics as M

        with self._cond:
            lag = (time.time() - self._queue[0][4]) if self._queue else 0.0
        M.WAL_SHIP_LAG.set(round(lag, 3))

    # ----------------------------------------------------------- semi-sync

    @property
    def can_promote(self) -> bool:
        """Does this shipper hold a promotion target? True only for the
        in-process transport — a socket shipper cannot promote the far
        side, so primary-degrade handling must fall through to the
        spare re-probe instead of fencing for a promotion that will
        never happen."""
        return self._standby is not None

    def wait_durable(self, session=None, deadline=None) -> None:
        """Block until every frame DURABLE on the primary right now is
        durable on the standby. The committer's own frames are covered
        (its local fsync just returned, and they were tapped during its
        appends) — but another session's appended-yet-unfsynced journal
        frames (pessimistic lock acquisitions, rollbacks — neither runs
        a sync) are deliberately NOT: waiting on those would block this
        ack on durability nobody promised, potentially forever. KILL /
        max_execution_time release the wait through the shared interrupt
        gate — the commit is then indeterminate-on-standby, never
        falsely acked."""
        with self._cond:
            pending = list(self._queue)
            target = self._shipped_seq  # frames already gone are covered
        # durability horizon OUTSIDE the ship condition (lock order:
        # durable_seq takes the wal's own locks, ranked below ours)
        horizon: dict[int, int] = {}
        for wal, seq, _p, gseq, _t in pending:
            d = horizon.get(id(wal))
            if d is None:
                d = horizon[id(wal)] = wal.durable_seq()
            if seq > d:
                break  # FIFO: nothing past an unfsynced frame is durable
            target = gseq
        with self._cond:
            while True:
                if self._shipped_seq >= target:
                    return
                if self._stopped or self._broken is not None:
                    raise CommitIndeterminateError(
                        "semi-sync: the standby is unavailable "
                        f"({self._broken or 'shipper stopped'}); the commit "
                        "is durable locally but UNCONFIRMED on the standby"
                    )
                self._cond.wait(self.POLL_S)
                if session is not None or deadline is not None:
                    from ..sched.scheduler import raise_if_interrupted

                    raise_if_interrupted(session, deadline)

    def wait_caught_up(self, timeout: float = 10.0) -> bool:
        """Test/ops helper: True once every currently-durable frame has
        shipped (the queue is empty or holds only not-yet-fsynced
        frames)."""
        end = time.time() + timeout
        while time.time() < end:
            with self._cond:
                head = self._queue[0] if self._queue else None
                if self._stopped:
                    return not self._queue
            if head is None:
                return True
            if head[1] > head[0].durable_seq():
                return True
            time.sleep(self.POLL_S / 2)
        return False

    # ----------------------------------------------------- failover wiring

    def on_primary_degraded(self) -> None:
        """The primary degraded and could NOT rotate onto a spare: drain
        what is durable, then promote the standby (auto_promote only).
        Frames past the primary's last fsync are gone with its page
        cache — dropping them is exactly the never-ahead invariant."""
        if not self.auto_promote or self._standby is None:
            return
        end = time.time() + self.DRAIN_DEADLINE_S
        while time.time() < end:
            with self._cond:
                if self._stopped:
                    break
                head = self._queue[0] if self._queue else None
            if head is None:
                break
            if head[1] > head[0].durable_seq():
                break  # the rest can never become durable
            time.sleep(self.POLL_S)
        self.stop()
        try:
            self._standby.promote()
        except TiDBError:
            pass  # already promoted by an operator — same outcome
        log.warning("auto-promote: standby %s is the new primary",
                    getattr(self._standby, "data_dir", "?"))


# ------------------------------------------------------------------ socket

_FRAME_HDR = struct.Struct("<BII")  # tag, len, crc32
_TAG_FRAME = 0x46  # 'F'
_TAG_SYNC = 0x53  # 'S'
_ACK = struct.Struct("<Q")


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        got = sock.recv(n - len(buf))
        if not got:
            raise ConnectionError("ship peer closed")
        buf += got
    return buf


class _SocketSender:
    """Primary-side socket transport: WAL-shaped frames + a sync marker
    per batch, then wait for the standby's cumulative durable ack."""

    def __init__(self, host: str, port: int, connect_timeout: float = 5.0):
        self.sock = socket.create_connection((host, port), timeout=connect_timeout)
        self.sock.settimeout(30.0)
        self._sent = 0

    def send_batch(self, payloads: list[bytes]) -> None:
        out = bytearray()
        for p in payloads:
            out += _FRAME_HDR.pack(_TAG_FRAME, len(p), zlib.crc32(p))
            out += p
        out += _FRAME_HDR.pack(_TAG_SYNC, 0, 0)
        self.sock.sendall(bytes(out))
        self._sent += len(payloads)
        (acked,) = _ACK.unpack(_recv_exact(self.sock, _ACK.size))
        if acked < self._sent:
            raise ConnectionError(
                f"standby acked {acked} < shipped {self._sent} frames"
            )

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class StandbyServer:
    """Standby-side socket transport: validates each frame's CRC (the
    wire reuses the WAL frame shape, so a flipped bit on the wire is
    caught exactly like one on disk), feeds whole batches to the
    standby's receive path at each sync marker, and acks the cumulative
    durable frame count."""

    def __init__(self, standby, host: str = "127.0.0.1", port: int = 0):
        self.standby = standby
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(4)
        self._closing = False
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="standby-server", daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            try:
                self._serve(conn)
            except (ConnectionError, OSError, TiDBError) as e:
                log.warning("standby server connection ended: %s", e)
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def _serve(self, conn: socket.socket) -> None:
        batch: list[bytes] = []
        total = 0
        while not self._closing:
            tag, ln, crc = _FRAME_HDR.unpack(_recv_exact(conn, _FRAME_HDR.size))
            if tag == _TAG_FRAME:
                payload = _recv_exact(conn, ln)
                if zlib.crc32(payload) != crc:
                    # never apply a frame the wire damaged; dropping the
                    # connection makes the shipper surface it loudly
                    raise ConnectionError("shipped frame failed CRC check")
                batch.append(payload)
            elif tag == _TAG_SYNC:
                if batch:
                    total = self.standby.receive_frames(batch)
                    batch = []
                conn.sendall(_ACK.pack(total))
            else:
                raise ConnectionError(f"unknown ship tag {tag:#x}")

    def close(self) -> None:
        self._closing = True
        try:
            self._sock.close()
        except OSError:
            pass

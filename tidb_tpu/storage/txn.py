"""Storage facade + transaction client (ref: kv/kv.go Storage/Transaction
interfaces; the 2PC flow re-implements what tikv client-go provides —
SURVEY §2.12 says the repo only wraps it, so this is new work).

A `Storage` owns the MVCC store, TSO, and region map, and hands out
`Snapshot`s and `Txn`s. `Txn` buffers writes in a membuffer and commits
via percolator 2PC: prewrite all keys (primary first in the mutation
order), fetch commit_ts, commit primary, then secondaries — with
lock-resolution retries (ref: unistore tikv/server.go:331,353 semantics).
"""

from __future__ import annotations

import logging
import os
import time
from threading import Lock

log = logging.getLogger(__name__)

from ..errors import (
    CommitIndeterminateError,
    DeadlockError,
    LockedError,
    RetryableError,
    StandbyReadOnly,
    StorageIOError,
    TiDBError,
    TxnAborted,
    WalCorruptionError,
    WriteConflict,
)
from ..utils.failpoint import inject as _fp
from .memkv import MemKV
from .mvcc import MVCCStore, Mutation, OP_DEL, OP_LOCK, OP_PUT
from .regions import RegionMap
from .tso import TSO

TOMBSTONE = b"\x00__del__"


class Snapshot:
    def __init__(self, store: "Storage", read_ts: int):
        self.store = store
        self.read_ts = read_ts

    def get(self, key: bytes) -> bytes | None:
        return self._with_resolve(lambda: self.store.mvcc.get(key, self.read_ts))

    def batch_get(self, keys: list[bytes]) -> dict[bytes, bytes]:
        return self._with_resolve(lambda: self.store.mvcc.batch_get(keys, self.read_ts))

    def scan(self, start: bytes, end: bytes, limit: int | None = None):
        return self._with_resolve(lambda: self.store.mvcc.scan(start, end, self.read_ts, limit))

    def scan_segments(self, start: bytes, end: bytes):
        """Zero-materialization scan → (segments, loose pairs); the columnar
        decode path (copr/tilecache.py) gathers straight from run buffers."""
        return self._with_resolve(lambda: self.store.mvcc.scan_segments(start, end, self.read_ts))

    RESOLVE_DEADLINE_S = 8.0  # > lock TTL: orphan locks must expire within this

    def _with_resolve(self, fn):
        """Reads resolve blocking locks via the primary (client-go
        behavior). Deadline-based: an orphaned prewrite lock only becomes
        resolvable once its TTL expires, so the wait must outlive the TTL
        (ref: Backoffer maxSleep in store/copr)."""
        backoff = 0.002
        deadline = time.time() + self.RESOLVE_DEADLINE_S
        while True:
            try:
                return fn()
            except LockedError as e:
                # deadline bounds BOTH outcomes: a stream of resolvable
                # locks must not spin a reader forever either
                if time.time() > deadline:
                    raise RetryableError("could not resolve locks for read") from e
                if self.store.standby:
                    # a warm standby must never WRITE: resolving would
                    # commit/rollback on the replica and diverge it from
                    # the primary. A shipped prewrite lock clears when its
                    # commit (or rollback) frame arrives — wait for it.
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 0.25)
                    continue
                now_ms = int(time.time() * 1000)
                if not self.store.mvcc.resolve_lock(e.key, e.lock, now_ms):
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 0.25)


class Txn:
    """Buffered transaction: optimistic by default; with pessimistic=True,
    DML acquires pessimistic locks at statement time via lock_keys_for_update
    (ref: client-go pessimistic txns + unistore KvPessimisticLock)."""

    LOCK_WAIT_S = 3.0  # innodb_lock_wait_timeout analog (shortened)

    def __init__(self, store: "Storage", start_ts: int, pessimistic: bool = False):
        self.store = store
        self.start_ts = start_ts
        self.membuf: dict[bytes, bytes] = {}  # TOMBSTONE value = delete
        self.snapshot = Snapshot(store, start_ts)
        self.committed = False
        self.commit_ts = 0
        self._locked_keys: set[bytes] = set()
        self.pessimistic = pessimistic
        self.for_update_ts = start_ts
        self._pess_keys: set[bytes] = set()
        self._pess_primary: bytes | None = None
        store._txn_started(start_ts)

    def lock_keys_for_update(self, keys) -> None:
        """Pessimistic DML lock acquisition with deadlock detection and a
        lock-wait timeout; optimistic txns record the keys for commit-time
        locking (SELECT FOR UPDATE semantics)."""
        keys = sorted(set(keys) - self._pess_keys)
        if not keys:
            return
        if not self.pessimistic:
            self._locked_keys.update(keys)
            return
        # pessimistic locks are journaled writes: refuse before touching
        # the store when a WAL IO failure has degraded it read-only
        self.store.check_writable()
        mvcc = self.store.mvcc
        # the primary is only PINNED once an acquisition succeeds — a
        # never-locked primary would read as rolled_back to waiters, who
        # would then steal our live locks
        primary = self._pess_primary if self._pess_primary is not None else keys[0]
        deadline = time.time() + self.LOCK_WAIT_S
        backoff = 0.002
        while True:
            self.for_update_ts = self.store.tso.next()
            try:
                mvcc.acquire_pessimistic_lock(keys, primary, self.start_ts, self.for_update_ts)
                self.store.detector.done(self.start_ts)
                if self._pess_primary is None:
                    self._pess_primary = primary
                self._pess_keys.update(keys)
                self._locked_keys.update(keys)
                return
            except LockedError as e:
                try:
                    # raises DeadlockError when this edge closes a cycle
                    self.store.detector.register(self.start_ts, e.lock.start_ts)
                except DeadlockError:
                    self.store.detector.done(self.start_ts)
                    raise
                now_ms = int(time.time() * 1000)
                if not mvcc.resolve_lock(e.key, e.lock, now_ms):
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 0.05)
                if time.time() > deadline:
                    self.store.detector.done(self.start_ts)
                    raise RetryableError("pessimistic lock wait timeout")
            except WriteConflict:
                # a commit landed after our for_update_ts: take a fresh one
                # (bounded by the same lock-wait deadline)
                if time.time() > deadline:
                    self.store.detector.done(self.start_ts)
                    raise RetryableError("pessimistic lock kept conflicting")
                time.sleep(backoff)
                backoff = min(backoff * 2, 0.05)

    # --- reads see own writes ---------------------------------------------

    def get(self, key: bytes) -> bytes | None:
        if key in self.membuf:
            v = self.membuf[key]
            return None if v == TOMBSTONE else v
        return self.snapshot.get(key)

    def batch_get(self, keys: list[bytes]) -> dict[bytes, bytes]:
        out = {}
        missing = []
        for k in keys:
            if k in self.membuf:
                if self.membuf[k] != TOMBSTONE:
                    out[k] = self.membuf[k]
            else:
                missing.append(k)
        out.update(self.snapshot.batch_get(missing))
        return out

    def scan(self, start: bytes, end: bytes, limit: int | None = None):
        """Merge membuffer over snapshot (the UnionScan semantic,
        ref: executor/union_scan.go)."""
        return self._scan_with(self.snapshot, start, end, limit)

    def scan_current(self, start: bytes, end: bytes, limit: int | None = None):
        """Pessimistic current read: scan at a FRESH for_update_ts so
        commits after start_ts are visible (ref: client-go for_update_ts
        statement reads), still merged under the membuffer."""
        self.for_update_ts = self.store.tso.next()
        return self._scan_with(Snapshot(self.store, self.for_update_ts), start, end, limit)

    def _scan_with(self, snapshot: Snapshot, start: bytes, end: bytes, limit: int | None):
        dirty = sorted(
            (k, v) for k, v in self.membuf.items() if start <= k and (not end or k < end)
        )
        # deletes can shrink the snapshot below the limit: fetch unlimited
        # when dirty keys overlap, then clip after the merge
        snap = snapshot.scan(start, end, None if dirty else limit)
        if not dirty:
            return snap
        merged: dict[bytes, bytes] = dict(snap)
        for k, v in dirty:
            if v == TOMBSTONE:
                merged.pop(k, None)
            else:
                merged[k] = v
        out = sorted(merged.items())
        return out[:limit] if limit is not None else out

    # --- writes ------------------------------------------------------------

    def put(self, key: bytes, value: bytes) -> None:
        self.membuf[key] = value

    def delete(self, key: bytes) -> None:
        self.membuf[key] = TOMBSTONE

    def lock_key(self, key: bytes) -> None:
        """SELECT ... FOR UPDATE: lock without writing."""
        self._locked_keys.add(key)

    @property
    def size(self) -> int:
        return sum(len(k) + len(v) for k, v in self.membuf.items())

    # --- 2PC ---------------------------------------------------------------

    def commit(self) -> int:
        if self.committed:
            raise TxnAborted("transaction already committed")
        if not self.membuf and not self._locked_keys and not self._pess_keys:
            self.committed = True
            self.store._txn_done(self.start_ts)
            return self.start_ts
        # degrade gate (fsyncgate discipline): after ONE WAL IO failure no
        # commit may ever ack again. Failing HERE — before prewrite touches
        # anything — keeps the in-memory state consistent with the durable
        # log, so reads keep serving. Empty commits above are read acks and
        # pass through.
        self.store.check_writable()
        muts = []
        for k, v in self.membuf.items():
            if v == TOMBSTONE:
                muts.append(Mutation(OP_DEL, k))
            else:
                muts.append(Mutation(OP_PUT, k, v))
        locked = self._locked_keys | self._pess_keys
        # _pess_keys beyond _locked_keys = locks taken by statements that
        # later failed (the statement savepoint restores _locked_keys
        # only); committing them as lock-only mutations both releases the
        # physical lock and leaves a commit record for resolvers
        for k in locked:
            if k not in self.membuf:
                muts.append(Mutation(OP_LOCK, k))
        muts.sort(key=lambda m: m.key)
        primary = muts[0].key
        mvcc = self.store.mvcc

        if self.pessimistic and self._pess_primary is not None:
            # keys were locked under this primary; keep resolve paths valid
            primary = self._pess_primary

        # phase 1: prewrite with lock-resolution retry
        _fp("txn/before-prewrite")
        backoff = 0.002
        fut = self.for_update_ts if self.pessimistic else 0
        for attempt in range(12):
            try:
                mvcc.prewrite(
                    muts, primary, self.start_ts, ttl_ms=3000, for_update_ts=fut,
                    pess_keys=frozenset(self._pess_keys),
                )
                break
            except LockedError as e:
                now_ms = int(time.time() * 1000)
                if not mvcc.resolve_lock(e.key, e.lock, now_ms):
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 0.1)
            except (WriteConflict, TxnAborted):
                # partially-prewritten locks must not linger for their TTL;
                # the txn is dead — release its start_ts or it pins the GC
                # safepoint for the whole leak horizon
                mvcc.rollback([m.key for m in muts], self.start_ts)
                self.store._txn_done(self.start_ts)
                raise
        else:
            mvcc.rollback([m.key for m in muts], self.start_ts)
            self.store._txn_done(self.start_ts)
            raise RetryableError("prewrite kept hitting live locks")

        # phase 2
        _fp("txn/commit-after-prewrite")
        # crashpoint: prewrite locks appended (possibly flushed), primary
        # commit record not — recovery must leave resolvable orphan locks
        _fp("txn/between-prewrite-and-commit")
        self.commit_ts = self.store.tso.next()
        try:
            mvcc.commit([primary], self.start_ts, self.commit_ts)
        except TxnAborted:
            mvcc.rollback([m.key for m in muts], self.start_ts)
            self.store._txn_done(self.start_ts)
            raise
        _fp("txn/commit-after-primary")
        secondaries = [m.key for m in muts if m.key != primary]
        if secondaries:
            mvcc.commit(secondaries, self.start_ts, self.commit_ts)
        self.committed = True
        self.store._txn_done(self.start_ts)
        self.store.bump_version([m.key for m in muts])
        try:
            self.store.wal_sync()  # group-commit durability point
        except CommitIndeterminateError:
            raise
        except StorageIOError as e:
            # the failure landed AT the durability point: phase 2 is done
            # in memory, the fsync outcome is unknown — surface the typed
            # indeterminate shape (8150), distinct from the determinate
            # "commit refused before touching anything" StorageIOError
            # that check_writable raises at the top of this method
            raise CommitIndeterminateError(
                f"commit (start_ts={self.start_ts}) was in flight at a WAL "
                f"failure: outcome indeterminate — the ack is withheld, but "
                f"the write may or may not be durable ({e.msg})"
            ) from e
        # change feed: the txn is durable (primary committed + WAL synced);
        # a post-commit hook must never turn a durable commit into an
        # error (ref: binlog.go commit hook)
        cdc = getattr(self.store, "cdc", None)
        if cdc is not None and cdc.active:
            try:
                cdc.publish(self.start_ts, self.commit_ts, muts)
            except Exception:  # noqa: BLE001
                log.exception("change-feed sink failed post-commit (dropped)")
        return self.commit_ts

    def rollback(self) -> None:
        if self._pess_keys:
            try:
                self.store.mvcc.pessimistic_rollback(sorted(self._pess_keys), self.start_ts)
            except StorageIOError:
                # the WAL died mid-txn: the physical lock release cannot be
                # journaled. Leave the locks — the store is read-only
                # degraded anyway, and a reopened store resolves them via
                # the primary's TTL like any other orphan.
                log.warning("pessimistic rollback skipped: WAL degraded (txn %d)", self.start_ts)
            self._pess_keys.clear()
        self.store.detector.done(self.start_ts)
        self.membuf.clear()
        self._locked_keys.clear()
        self.committed = True
        self.store._txn_done(self.start_ts)


class Storage:
    """The kv.Storage of the framework: MVCC + TSO + regions + versions.

    With `data_dir`, the store is durable: a native WAL (native/wal.cpp)
    journals every mutation, commits group-flush + fsync, a fresh Storage
    over the same dir recovers snapshot + intact log prefix, and
    checkpoint() compacts log into snapshot (the reference's storage node
    persists the same way through badger/RocksDB WALs + SSTs).

    `wal_recovery_mode` governs what recovery does with a damaged log
    (sysvar `tidb_wal_recovery_mode`; persisted in the RECOVERY_MODE
    sidecar so SET GLOBAL survives a crash+restart):
      - 'tolerate-torn-tail' (default): a torn tail (crash cut the last
        frames, nothing valid after) is truncated; MID-LOG corruption
        (valid CRC frames follow a bad one) refuses with
        WalCorruptionError — truncating there drops committed data.
      - 'absolute': any bad frame refuses.
      - 'drop-corrupt': explicit opt-in to skip corrupt log frames and
        salvage the intact records after them (dropped bytes counted in
        tidb_wal_recovery_dropped_bytes_total). Never applies to a
        corrupt snapshot — that is refused in every mode."""

    RECOVERY_MODES = ("tolerate-torn-tail", "absolute", "drop-corrupt")

    def __init__(self, data_dir: str | None = None, wal_recovery_mode: str | None = None,
                 standby: bool = False, spare_dirs: list[str] | None = None):
        if wal_recovery_mode is not None and wal_recovery_mode not in self.RECOVERY_MODES:
            raise ValueError(f"unknown wal_recovery_mode {wal_recovery_mode!r}")
        if standby and data_dir is None:
            raise ValueError("a standby store requires a data_dir (it journals shipped frames)")
        self.wal_recovery_mode = wal_recovery_mode
        self._io_degraded = False
        # --- warm standby + online WAL failover (PR 14) --------------------
        # standby: this store replays a primary's shipped WAL frames and
        # serves stale reads at its applied watermark; every write entry
        # point refuses until promote() flips it read-write.
        self.standby = standby
        self.applied_ts = 0  # newest commit_ts replayed from shipped frames
        self._applied_frames = 0
        from threading import RLock as _RLock

        self._standby_lock = _RLock()  # serializes receive_frames vs promote
        self._shipper = None  # ReplicaSet (storage/ship.py) when attached
        self._ship_asm = None  # GroupAssembler for shipped frame groups
        # spare WAL media (tidb_wal_spare_dirs): on an IO failure the
        # store checkpoints onto a spare and resumes writes instead of
        # degrading read-only for the rest of its life
        self.wal_spare_dirs: list[str] = list(spare_dirs or [])
        self._failover_lock = Lock()
        self._failover_disabled = False  # set once a standby took over (split-brain guard)
        self._no_spare_counted = False
        self._media_state: dict[str, dict] = {}  # spare path → probe bookkeeping
        self._reprobe_thread = None
        self.kv = MemKV()
        self.mvcc = MVCCStore(self.kv)
        self.mvcc.txn_live = self.txn_is_active
        self.tso = TSO()
        # SET GLOBAL overrides: seed new sessions, serve @@global.x reads
        self.global_vars: dict[str, str] = {}
        # commit-time change feed (ref: cdclog/binlog hooks) — inert
        # until a sink subscribes
        from ..cdc import ChangeFeed

        self.cdc = ChangeFeed()
        # distinguishes stores in process-wide caches (table ids restart
        # per store, so (table_id, version) alone is ambiguous)
        import uuid as _uuid

        self.store_uid = _uuid.uuid4().hex[:16]
        self.data_dir = data_dir
        self.start_time = time.time()  # cluster_info uptime
        self.wal = None
        self._wal_epoch = 0
        self.regions = RegionMap()
        # auto-split: regions split when a bulk ingest lands more than
        # this many keys (PD's size-based split policy analog; ref:
        # unistore cluster.go region management + executor/split.go).
        # Sized like the reference's 96MB regions (~2M short rows): on a
        # single chip each cop task pays a device launch + fetch round
        # trip, so undersized regions tax warm queries for no parallelism
        self.region_split_size = 1 << 21
        self.mvcc.split_hook = self._auto_split_run
        # bulk-ingest windows (PR 15): table_id → active window count.
        # The DDL worker parks job steps for tables with a live window
        # (the ingest/DDL exclusion contract — see br/ingest.BulkIngest);
        # the lock guards ONLY this dict and is never held across other
        # acquisitions (rank "ingest.registry" in lock_order.toml).
        # RLock: a GC-triggered BulkIngest.__del__ finalizer may fire
        # while the owning thread is INSIDE the registry — a plain Lock
        # would self-deadlock
        from threading import RLock as _IngestRLock

        self._ingest_lock = _IngestRLock()
        self._ingesting: dict[int, int] = {}
        # pessimistic-lock wait-for graph (ref: unistore tikv/detector.go)
        from .detector import DeadlockDetector

        self.detector = DeadlockDetector()
        self._gc_worker = None
        self._compactor = None  # delta-main compactor (durable primaries only)
        # active-txn registry: GC clamps its safepoint to the oldest live
        # start_ts so long transactions keep their snapshot readable
        # (ref: store/gcworker/gc_worker.go:397 min-start-ts calculation)
        self._active_starts: dict[int, float] = {}
        self._active_lock = Lock()
        import threading as _threading

        self._processes: dict = {}
        self._proc_lock = _threading.Lock()
        # eager: racing lazy-inits would defeat the worker's owner lock
        from ..ddl.worker import DDLWorker

        self._ddl = DDLWorker(self)
        # table-prefix data-version counters: the tile cache (TiFlash-
        # columnar-replica analog) invalidates on these.
        self._versions: dict[bytes, int] = {}
        self._stats = None
        # durable mode opens LAST: replay re-runs ingest hooks (region
        # splits) against fully-initialized state
        if data_dir is not None:
            self._open_durable(data_dir)
        elif self.wal_recovery_mode is None:
            self.wal_recovery_mode = self.RECOVERY_MODES[0]
        if standby:
            # shipped frames are journaled into OUR wal explicitly by
            # receive_frames and then replayed with the journal DETACHED
            # — kv/mvcc must not re-journal every applied record. The
            # journals re-attach at promote().
            self.kv.journal = None
            self.mvcc.journal = None

    # --- IO-failure degrade (fsyncgate discipline) -------------------------

    def _wal_io_error(self, op: str) -> None:
        """Installed as the Wal's on_io_error hook: the first failed
        append/fsync lands here (before the writer sees StorageIOError)
        and flips the store read-only. Without spare media that is the
        end of the story (the PR 10 fsyncgate discipline: reopen on
        healthy media in a fresh process); with `tidb_wal_spare_dirs`
        configured the follow-up thread attempts an online rotation onto
        a spare (writes resume, zero acks lost — every acked commit was
        fsynced before this failure and the rotation snapshot captures
        the full in-memory state). The hook itself only flags and
        spawns: it runs under the failing Wal's append lock (and often
        the kv lock), both of which the rotation needs free."""
        if self._io_degraded:
            return
        self._io_degraded = True
        from ..utils import metrics as M

        M.WAL_DEGRADED.set(1)
        log.error(
            "WAL %s failed on %s: storage degraded read-only — commits "
            "fail loud from here on, reads keep serving; attempting "
            "spare-dir failover (tidb_wal_spare_dirs=%r)",
            op, self.data_dir, self.wal_spare_dirs,
        )
        import threading as _threading

        _threading.Thread(
            target=self._degrade_followup, name="wal-failover", daemon=True
        ).start()

    def _degrade_followup(self) -> None:
        """Async half of the degrade hook: try the spare rotation; if the
        store stays degraded, hand the baton to the attached shipper
        (auto-promote standby) or the background re-probe loop."""
        try:
            if self._attempt_wal_failover():
                return
            sh = self._shipper
            if sh is not None and getattr(sh, "auto_promote", False) \
                    and getattr(sh, "can_promote", False):
                # the standby takes over: this store must NEVER heal
                # afterwards — two writable stores over one history is
                # split brain. Decide under the failover lock: a
                # concurrent check_writable rotation that healed us in
                # the window wins (no promote), and once the fence is
                # set no queued rotation can slip through (the attempt
                # re-checks the flag under the same lock).
                with self._failover_lock:
                    if not self._io_degraded:
                        return
                    self._failover_disabled = True
                sh.on_primary_degraded()
                return
            if self.wal_spare_dirs:
                self._start_reprobe()
        except Exception:  # noqa: BLE001 — a follow-up thread must not die loud
            log.exception("WAL failover follow-up failed")

    def check_writable(self) -> None:
        """Raise when the store must not accept writes. Every write
        entry point (commit, pessimistic locking, checkpoint) gates here
        so nothing can ack after the log went bad — but a degraded store
        with spare media first gets one (serialized) chance to rotate
        and heal, so the next write after an IO failure resumes instead
        of failing for the rest of the process."""
        if self.standby:
            raise StandbyReadOnly(
                "store is a warm standby (replaying shipped WAL): writes "
                "are rejected until ADMIN PROMOTE"
            )
        if self._io_degraded:
            self._attempt_wal_failover()
        if self._io_degraded:
            raise StorageIOError(
                "storage is read-only: a WAL IO failure poisoned the log "
                "(no commit can ack durably); reads keep serving — reopen "
                "the store on healthy media to restore writes"
            )

    # --- online WAL media failover (PR 14) ---------------------------------

    PROBE_COOLDOWN_S = 2.0  # min spacing between probes of failed media
    PROBE_OK_STREAK = 2  # consecutive good probes before re-eligibility

    def set_wal_spare_dirs(self, csv: str) -> None:
        """SET GLOBAL tidb_wal_spare_dirs seam: comma-separated spare
        paths tried in order on a WAL IO failure."""
        self.wal_spare_dirs = [p.strip() for p in (csv or "").split(",") if p.strip()]
        self._no_spare_counted = False

    def _attempt_wal_failover(self) -> bool:
        """Try to rotate the store onto a spare dir. Returns True when
        the store is (already or now) healthy. Serialized: concurrent
        committers queue on the failover lock for the few ms a rotation
        takes, then find the store healed and proceed."""
        if not self._io_degraded:
            return True
        if self._failover_disabled or self.wal is None or self.standby:
            return False
        from ..utils import metrics as M

        spares = [
            d for d in self.wal_spare_dirs
            if os.path.abspath(d) != os.path.abspath(self.data_dir or "")
        ]
        if not spares:
            if not self._no_spare_counted:
                self._no_spare_counted = True
                M.WAL_ROTATIONS.inc(outcome="no_spare")
            return False
        with self._failover_lock:
            if not self._io_degraded:
                return True
            if self._failover_disabled:
                # re-checked under the lock: a queued rotation must not
                # slip past the split-brain fence set while it waited
                return False
            for cand in spares:
                if not self._media_eligible(cand):
                    continue
                try:
                    self._rotate_onto(cand)
                except (OSError, StorageIOError) as e:
                    # StorageIOError too: the fresh spare log's own
                    # first sync can fail through the fsyncgate hook —
                    # that spare is bad media like any other, and the
                    # next candidate deserves its try
                    self._media_state[cand] = {"last_fail": time.time(), "ok_streak": 0}
                    M.WAL_ROTATIONS.inc(outcome="failed")
                    log.warning("WAL failover onto %s failed: %s", cand, e)
                    continue
                M.WAL_ROTATIONS.inc(outcome="ok")
                return True
            return False

    def _media_eligible(self, cand: str) -> bool:
        """Hysteresis gate for failed media: after a failure the path
        must sit out PROBE_COOLDOWN_S, then pass PROBE_OK_STREAK
        consecutive write+fsync probes (spaced by the same cooldown)
        before a rotation trusts it again — one lucky write on a
        flapping disk is not a heal. Never-failed paths pass through."""
        st = self._media_state.get(cand)
        if st is None:
            return True
        now = time.time()
        if now - st["last_fail"] < self.PROBE_COOLDOWN_S:
            return False
        if now - st.get("last_probe", 0.0) < self.PROBE_COOLDOWN_S:
            return st["ok_streak"] >= self.PROBE_OK_STREAK
        st["last_probe"] = now
        if self._probe_media(cand):
            st["ok_streak"] += 1
        else:
            st["last_fail"] = now
            st["ok_streak"] = 0
        return st["ok_streak"] >= self.PROBE_OK_STREAK

    @staticmethod
    def _probe_media(cand: str) -> bool:
        try:
            os.makedirs(cand, exist_ok=True)
            p = os.path.join(cand, ".wal-probe")
            with open(p, "wb") as f:
                f.write(b"probe")
                f.flush()
                os.fsync(f.fileno())
            os.unlink(p)
            return True
        except OSError:
            return False

    def _start_reprobe(self) -> None:
        """Background re-probe: while degraded, periodically retry the
        failover (which probes failed media under the hysteresis gate)."""
        with self._proc_lock:
            if self._reprobe_thread is not None and self._reprobe_thread.is_alive():
                return
            import threading as _threading

            t = _threading.Thread(target=self._reprobe_loop, name="wal-reprobe", daemon=True)
            self._reprobe_thread = t
        t.start()

    def _reprobe_loop(self) -> None:
        while self._io_degraded and not self._failover_disabled:
            time.sleep(self.PROBE_COOLDOWN_S / 2)
            try:
                if self._attempt_wal_failover():
                    return
            except Exception:  # noqa: BLE001 — the probe loop must survive
                log.exception("WAL re-probe attempt failed")

    def _rotate_onto(self, cand: str) -> None:
        """Checkpoint-to-spare: under the kv lock (the same barrier a
        checkpoint takes — journal-first writers hold it across
        append+apply, so memory is exactly the durable state plus
        fully-appended unacked residue), snapshot the full state into
        the spare dir, open a fresh log there, swap the store over and
        clear the degrade. Every acked commit was fsynced BEFORE the
        failure and memory is a superset of fsynced state, so the
        snapshot loses zero acks; unacked in-flight residue (prewrite
        locks) recovers like any crash leftovers."""
        from ..utils import metrics as M
        from . import wal as w

        os.makedirs(cand, exist_ok=True)
        old_dir = self.data_dir
        with self.kv.lock:
            new_epoch = self._wal_epoch + 1
            payload = self._snapshot_payload_locked(new_epoch)
            w.snap_write(os.path.join(cand, "snapshot.bin"), payload)
            if self.wal_recovery_mode:
                # the RECOVERY_MODE sidecar follows the store to its new home
                tmp = os.path.join(cand, "RECOVERY_MODE.tmp")
                with open(tmp, "w") as f:
                    f.write(self.wal_recovery_mode + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, os.path.join(cand, "RECOVERY_MODE"))
            w.fsync_dir(cand)
            # crashpoint: snapshot durable on the spare, store not yet
            # swapped — the OLD dir (all acked commits fsynced there
            # before the failure) and the spare snapshot must BOTH
            # recover every ack
            _fp("wal/rotate-after-checkpoint")
            old = self.wal
            self.data_dir = cand
            self._wal_epoch = new_epoch
            nw = w.Wal(self._wal_path(new_epoch), on_io_error=self._wal_io_error)
            self.wal = nw
            self.kv.journal = nw
            self.mvcc.journal = nw
            # supersede+close the old log BEFORE the new log's first
            # sync: the durability carrier is the already-fsynced spare
            # SNAPSHOT, not the fresh log — so the shipper may treat the
            # old log's queued frames as durable (see Wal.durable_seq)
            # even if this spare turns out bad below, and a failed
            # nw.sync() leaves no leaked half-open wal for the next
            # candidate's attempt
            old._superseded = True
            old.close()
            nw.sync()
            w.fsync_dir(cand)
            self._io_degraded = False
            self._no_spare_counted = False
            M.WAL_DEGRADED.set(0)
            sh = self._shipper
            if sh is not None:
                sh.install(nw)
        # best-effort breadcrumb for operators (often on dead media)
        try:
            with open(os.path.join(old_dir, "FAILED_OVER_TO"), "w") as f:
                f.write(cand + "\n")
        except OSError:
            pass
        log.warning("WAL failover: %s -> %s (epoch %d); writes resumed",
                    old_dir, cand, new_epoch)

    @property
    def io_degraded(self) -> bool:
        return self._io_degraded

    def set_wal_recovery_mode(self, mode: str) -> None:
        """SET GLOBAL tidb_wal_recovery_mode seam: validate, persist in
        the RECOVERY_MODE sidecar (so the setting survives the very crash
        it exists for) and only then apply in memory — a sidecar write
        failure must not leave @@global reporting a mode the next
        recovery won't actually run under."""
        if mode not in self.RECOVERY_MODES:
            raise ValueError(f"unknown wal_recovery_mode {mode!r}")
        if self.data_dir is not None:
            try:
                self._write_recovery_mode_sidecar(mode)
            except OSError as e:
                raise StorageIOError(
                    f"cannot persist tidb_wal_recovery_mode={mode!r} to the "
                    f"RECOVERY_MODE sidecar ({e}); the setting was NOT applied"
                ) from e
        self.wal_recovery_mode = mode

    def _write_recovery_mode_sidecar(self, mode: str) -> None:
        import os

        from . import wal as w

        path = os.path.join(self.data_dir, "RECOVERY_MODE")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(mode + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        w.fsync_dir(self.data_dir)

    # --- bulk-ingest windows (PR 15) ----------------------------------------

    def begin_table_ingest(self, table_id: int) -> None:
        with self._ingest_lock:
            self._ingesting[table_id] = self._ingesting.get(table_id, 0) + 1

    def end_table_ingest(self, table_id: int) -> None:
        with self._ingest_lock:
            c = self._ingesting.get(table_id, 0) - 1
            if c <= 0:
                self._ingesting.pop(table_id, None)
            else:
                self._ingesting[table_id] = c

    def table_ingesting(self, table_id: int) -> bool:
        with self._ingest_lock:
            return table_id in self._ingesting

    @property
    def ddl(self):
        """Shared online-DDL worker (the owner seam: one per store)."""
        return self._ddl

    @property
    def stats(self):
        """Shared stats handle (ref: statistics/handle — hangs off Storage
        so all sessions over this store see one stats view)."""
        if self._stats is None:
            from ..statistics.handle import StatsHandle

            self._stats = StatsHandle(self)
        return self._stats

    @property
    def mem(self):
        """Shared server memory tracker/arbiter (utils/memory
        ServerMemTracker): the root every session's statement trackers
        attach under — tidb_server_memory_limit enforcement, soft-limit
        degradation and top-consumer OOM kills happen here, store-wide."""
        if getattr(self, "_mem", None) is None:
            with self._proc_lock:
                if getattr(self, "_mem", None) is None:
                    from ..utils.memory import ServerMemTracker

                    self._mem = ServerMemTracker()
        return self._mem

    @property
    def sched(self):
        """Shared resource controller (ref: resource control's store-scoped
        resource manager): admission, resource groups and the cross-session
        device-launch batcher — one per store so every session's cop tasks
        meet in the same queues."""
        if getattr(self, "_sched", None) is None:
            with self._proc_lock:
                if getattr(self, "_sched", None) is None:
                    from ..sched import ResourceController

                    self._sched = ResourceController(self)
        return self._sched

    def begin(self, pessimistic: bool = False) -> Txn:
        return Txn(self, self.tso.next(), pessimistic=pessimistic)

    def snapshot(self, read_ts: int | None = None) -> Snapshot:
        return Snapshot(self, read_ts if read_ts is not None else self.tso.next())

    def current_version(self) -> int:
        return self.tso.current()

    # --- data-version tracking (for tile-cache invalidation) --------------

    def bump_version(self, keys: list[bytes]) -> None:
        prefixes = {k[:9] for k in keys if len(k) >= 9}  # b't' + table_id
        ts = self.tso.current()
        for p in prefixes:
            ver, _ = self._versions.get(p, (0, 0))
            self._versions[p] = (ver + 1, ts)
        # workload-history plane (PR 20): measured walls for a table whose
        # data version moved are stale — drop its routing entries. Guarded
        # on the lazy singleton so pure-OLTP commit paths that never armed
        # a profile pay one attribute read
        wl = getattr(self, "_workload", None)
        if wl is not None and len(wl):
            wl.invalidate_prefixes(prefixes)

    def data_version(self, table_prefix: bytes) -> tuple[int, int]:
        """→ (version counter, last-commit ts) for the table key space."""
        return self._versions.get(table_prefix[:9], (0, 0))

    def gc(self, safe_point: int | None = None) -> int:
        sp = safe_point if safe_point is not None else self.tso.current()
        return self.mvcc.gc(sp)

    def mvcc_versions(self, key: bytes) -> list[tuple[int, int, int]]:
        """MVCC introspection for the HTTP /mvcc endpoint (ref:
        http_status.go mvccTxnHandler): [(start_ts, commit_ts, value_len)]
        newest first, across the write CF and ingest runs."""
        from .mvcc import WriteRecord, _dk, unrev_ts

        out = []
        for k, v in self.mvcc.kv.iter_from(b"w" + key):
            if not k.startswith(b"w" + key) or len(k) != 1 + len(key) + 8:
                break
            rec = WriteRecord.decode(v)
            cts = unrev_ts(k[-8:])
            val = self.mvcc.kv.get(_dk(key, rec.start_ts))
            out.append((rec.start_ts, cts, len(val) if val else 0))
        for run in reversed(self.mvcc.runs):
            i = run.find(key)
            if i >= 0:
                out.append((run.commit_ts, run.commit_ts, len(run.value(i))))
        return out

    # --- durability (native WAL + snapshot) --------------------------------

    def _wal_path(self, epoch: int) -> str:
        import os

        return os.path.join(self.data_dir, f"wal.{epoch:06d}.log")

    def _open_durable(self, data_dir: str) -> None:
        import os
        import struct

        from ..utils import metrics as M
        from . import wal as w

        os.makedirs(data_dir, exist_ok=True)
        # 0) recovery mode: an explicit ctor arg governs THIS open only
        # (one-shot salvage must not permanently opt the store into
        # dropping corruption); else the RECOVERY_MODE sidecar (a prior
        # SET GLOBAL — persisted so it survives the crash it exists for);
        # else the default
        mode_path = os.path.join(data_dir, "RECOVERY_MODE")
        if self.wal_recovery_mode is None:
            if os.path.exists(mode_path):
                with open(mode_path) as f:
                    saved = f.read().strip()
                if saved in self.RECOVERY_MODES:
                    self.wal_recovery_mode = saved
                else:
                    log.warning("ignoring unknown RECOVERY_MODE sidecar value %r", saved)
            if self.wal_recovery_mode is None:
                self.wal_recovery_mode = self.RECOVERY_MODES[0]
        # @@global.tidb_wal_recovery_mode reflects the mode THIS open
        # actually ran under (sidecar or one-shot ctor arg included)
        self.global_vars.setdefault("tidb_wal_recovery_mode", self.wal_recovery_mode)
        snap_path = os.path.join(data_dir, "snapshot.bin")
        # 1) snapshot (if any); its header names the WAL epoch it subsumes.
        # snap_read returns None for absent AND corrupt; a PRESENT-but-
        # unreadable snapshot is refused in EVERY mode — booting without it
        # would replay the wrong epoch's (or no) log over an empty store,
        # silently losing everything the snapshot held. (snap_probe gives
        # the same classification for tooling; one read suffices here.)
        payload = w.snap_read(snap_path)
        if payload is None and os.path.exists(snap_path):
            raise WalCorruptionError(
                f"snapshot {snap_path!r} is present but corrupt (short file, "
                f"bad magic, or CRC mismatch); refusing to recover — restore "
                f"the snapshot from a replica/backup (refused in every "
                f"tidb_wal_recovery_mode, including drop-corrupt)"
            )
        if payload:
            try:
                self._wal_epoch = self._load_snapshot_payload(payload)
            except (struct.error, ValueError) as e:
                # CRC checked out but the payload misparses: a writer bug,
                # not media damage — same refuse-don't-guess treatment
                raise WalCorruptionError(
                    f"snapshot {snap_path!r} payload does not parse ({e}); "
                    f"refusing to recover from a half-understood snapshot"
                ) from e
        # 2) replay THIS epoch's log only — a crash between snapshot rename
        # and log rotation must not re-apply runs the snapshot already
        # contains. The scan distinguishes a torn tail (nothing valid after
        # the first bad frame — the expected crash shape, truncated) from
        # MID-LOG corruption (valid CRC frames follow — bit rot inside
        # committed history), which only `drop-corrupt` may skip.
        wal_path = self._wal_path(self._wal_epoch)
        salvage: list[bytes] = []
        if os.path.exists(wal_path):
            scan = w.Wal.scan_log(wal_path)
            if scan.corrupt:
                bad = scan.file_size - scan.valid_prefix
                if self.wal_recovery_mode == "absolute":
                    raise WalCorruptionError(
                        f"WAL {wal_path!r} has a bad frame at byte "
                        f"{scan.valid_prefix} ({bad} bytes unreadable) and "
                        f"tidb_wal_recovery_mode=absolute refuses ANY damage"
                    )
                if scan.mid_log and self.wal_recovery_mode != "drop-corrupt":
                    raise WalCorruptionError(
                        f"WAL {wal_path!r} is corrupt MID-LOG: {len(scan.salvage)} "
                        f"intact record(s) follow the bad frame at byte "
                        f"{scan.valid_prefix} — this is bit rot inside committed "
                        f"history, not a torn tail, and truncating would silently "
                        f"drop committed data. Restore from a replica, or opt in "
                        f"with tidb_wal_recovery_mode=drop-corrupt to skip the "
                        f"corrupt region and salvage the records after it"
                    )

            def _replay(rec: bytes, what: str) -> None:
                # CRC passed but the payload misparses: a writer bug on the
                # intact prefix, or a pseudo-frame chain on the salvage path
                # — either way refuse typed, never crash untyped out of the
                # constructor in the one mode meant to survive corruption
                try:
                    w.apply_record(rec, self.kv, self.mvcc)
                except ValueError as e:
                    raise WalCorruptionError(
                        f"WAL {wal_path!r}: {what} record does not parse "
                        f"({e}); refusing to recover from a half-understood "
                        f"log — restore from a replica/backup"
                    ) from e

            def _feed(asm, rec: bytes, what: str) -> list:
                try:
                    return asm.feed(rec)
                except ValueError as e:
                    raise WalCorruptionError(
                        f"WAL {wal_path!r}: {what} frame-group sequence is "
                        f"malformed ({e}); refusing to recover from a "
                        f"half-understood log — restore from a replica/backup"
                    ) from e

            # frame groups (G/g chunk/F) join back into their logical
            # record before applying; the group's byte offset is tracked
            # so a torn trailing group truncates at its BEGIN frame (the
            # whole group replays atomically or not at all)
            asm = w.GroupAssembler()
            group_off = off = 0
            for rec in scan.records:
                if rec[:1] == b"G" and not asm.open:
                    group_off = off
                off += 8 + len(rec)
                for full in _feed(asm, rec, "intact-prefix"):
                    _replay(full, "intact-prefix")
            trunc_to = scan.valid_prefix if scan.corrupt else None
            if asm.open:
                # the group's closing frame never became durable: its
                # chunks stayed buffered (nothing half-applied) and the
                # whole group is cut like any torn tail
                trunc_to = group_off
                M.WAL_RECOVERY_DROPPED.inc(
                    scan.valid_prefix - group_off, kind="torn-group"
                )
            if scan.corrupt and scan.mid_log:
                # drop-corrupt: skip the bad region, keep the rest. The
                # salvage runs through its OWN assembler (a group cannot
                # span the corrupt gap); a trailing open group in the
                # salvage is dropped, complete ones re-append whole.
                salv_asm = w.GroupAssembler()
                kept: list[bytes] = []
                group_frames: list[bytes] = []
                for rec in scan.salvage:
                    in_group = salv_asm.open or rec[:1] == b"G"
                    (group_frames if in_group else kept).append(rec)
                    done = _feed(salv_asm, rec, "salvaged")
                    if done:
                        kept.extend(group_frames)
                        group_frames = []
                    for full in done:
                        _replay(full, "salvaged")
                salvage = kept
                dropped = (scan.file_size - scan.valid_prefix) - sum(
                    8 + len(r) for r in salvage
                )
                M.WAL_RECOVERY_DROPPED.inc(dropped, kind="corrupt")
                log.warning(
                    "drop-corrupt recovery on %s: skipped %d corrupt byte(s), "
                    "salvaged %d record(s) past them", wal_path, dropped, len(salvage),
                )
            elif scan.corrupt:
                M.WAL_RECOVERY_DROPPED.inc(scan.file_size - scan.valid_prefix, kind="torn")
            if trunc_to is not None:
                # truncate before appending (salvaged records are
                # re-appended below, through the fresh Wal)
                os.truncate(wal_path, trunc_to)
        # stale epochs (pre-checkpoint logs) are garbage
        for f in os.listdir(data_dir):
            if f.startswith("wal.") and f.endswith(".log") and f != os.path.basename(wal_path):
                os.unlink(os.path.join(data_dir, f))
        # 3) attach journals (AFTER replay so replay doesn't self-append)
        self.wal = w.Wal(wal_path, on_io_error=self._wal_io_error)
        self.kv.journal = self.wal
        self.mvcc.journal = self.wal
        if salvage:
            # make the salvaged suffix durable again in its compacted place
            for rec in salvage:
                self.wal.append(rec)
            self.wal.sync()
        # 4) seed the TSO past every timestamp the recovered state holds.
        # TSO physical time is wall-clock ms: reopened in the SAME
        # millisecond the predecessor last committed in, a fresh oracle
        # would hand out read timestamps BELOW that commit_ts and the
        # newest committed writes vanish until the clock ticks over.
        self.tso.advance_to(self.mvcc.high_water_ts())

    def wal_sync(self) -> None:
        """Commit durability point. Default: group commit — concurrent
        committers batch into one leader fsync (`Wal.sync_group`), with
        the follower wait released through the shared interrupt gate.
        `SET GLOBAL tidb_wal_group_commit = OFF` recovers the exact
        per-commit-fsync behavior live (incident fallback).

        Semi-sync (`tidb_wal_semi_sync`, PR 14/17): with a shipper
        attached the ack additionally means durable-on-REPLICA — after
        local durability the committer waits (through the same interrupt
        gate) for the fleet to confirm. `ON` waits for any ONE standby's
        fsync (the PR 14 pair contract); `QUORUM` waits until the median
        per-standby durable horizon covers the commit — a majority
        ceil(N/2) of the N attached links. The wait piggybacks the
        group-commit cadence: the shipper ships per flushed group, so
        one standby fsync covers the whole group."""
        wal = self.wal
        if wal is None:
            return
        sh = self._shipper
        semi_mode = self.global_vars.get("tidb_wal_semi_sync", "OFF")
        semi = sh is not None and semi_mode in ("ON", "QUORUM")
        # the committing statement's session/deadline (if any) let a KILL
        # or max_execution_time release the follower/semi-sync wait; the
        # commit is then INDETERMINATE (the leader's fsync may still land
        # it) — the PR 10 contract for an error at the durability point,
        # never a false ack
        session = deadline = None
        if semi or self.global_vars.get("tidb_wal_group_commit", "ON") == "ON":
            from ..executor.executors import _ACTIVE_SESSION

            session = _ACTIVE_SESSION.get()
            deadline = getattr(session, "_deadline", None) if session is not None else None
        # decompose the durability point for the statement trace: the
        # local fsync (wal.fsync) vs the replication wait (quorum.wait,
        # emitted inside wait_durable with per-link ack offsets)
        tracer = getattr(session, "_tracer", None) if session is not None else None
        t0 = time.perf_counter()
        if self.global_vars.get("tidb_wal_group_commit", "ON") != "ON":
            from ..utils import metrics as M

            wal.sync()
            M.WAL_GROUP_COMMIT.inc(outcome="off")
        else:
            wal.sync_group(session=session, deadline=deadline)
        if tracer is not None:
            tracer.closed_span("wal.fsync", time.perf_counter() - t0)
        if semi:
            sh.wait_durable(session=session, deadline=deadline, mode=semi_mode)

    def _snapshot_payload_locked(self, epoch: int) -> bytes:
        """Serialize the full in-memory state as a snapshot payload that
        names `epoch` as the WAL epoch it subsumes. Caller MUST hold the
        kv lock (the consistency barrier). Shared by checkpoint(), the
        spare-dir failover rotation and the standby bootstrap."""
        import struct

        parts = [struct.pack("<Q", epoch), struct.pack("<Q", len(self.kv._keys))]
        for k in self.kv._keys:
            v = self.kv._map[k]
            parts.append(struct.pack("<II", len(k), len(v)))
            parts.append(k)
            parts.append(v)
        runs = list(self.mvcc.runs)
        parts.append(struct.pack("<I", len(runs)))
        for run in runs:
            # self-describing per-run record (columnar runs serialize
            # their columns directly — no row-major plane materialized);
            # killed rows compact out at snapshot time
            rec = run.to_wal_record()
            parts.append(struct.pack("<Q", len(rec)))
            parts.append(rec)
        return b"".join(parts)

    def _load_snapshot_payload(self, payload: bytes) -> int:
        """Parse a `_snapshot_payload_locked` payload into the in-memory
        store (kv pairs + ingest runs) and return the WAL epoch it names.
        Raises struct.error/ValueError on a malformed payload — callers
        wrap those in the typed refusal."""
        import struct

        from . import wal as w

        pos = 0
        (epoch,) = struct.unpack_from("<Q", payload, pos)
        pos += 8
        (n_entries,) = struct.unpack_from("<Q", payload, pos)
        pos += 8
        pairs = []
        for _ in range(n_entries):
            klen, vlen = struct.unpack_from("<II", payload, pos)
            pos += 8
            if pos + klen + vlen > len(payload):
                raise ValueError("snapshot entry overruns payload")
            k = payload[pos : pos + klen]
            pos += klen
            v = payload[pos : pos + vlen]
            pos += vlen
            pairs.append((k, v))
        self.kv.bulk_load(pairs)
        (n_runs,) = struct.unpack_from("<I", payload, pos)
        pos += 4
        for _ in range(n_runs):
            rec_len = struct.unpack_from("<Q", payload, pos)[0]
            pos += 8
            if pos + rec_len > len(payload):
                raise ValueError("snapshot run record overruns payload")
            w.apply_record(payload[pos : pos + rec_len], self.kv, self.mvcc)
            pos += rec_len
        return int(epoch)

    def checkpoint(self) -> None:
        """Compact the WAL into an atomic snapshot file (the storage
        node's flush/compaction analog)."""
        if self.wal is None:
            raise TiDBError("checkpoint requires a durable Storage (data_dir)")
        # degraded log: the snapshot would capture in-memory state the WAL
        # can no longer guarantee matches disk — refuse like any write
        self.check_writable()
        import os

        from . import wal as w

        with self.kv.lock:
            new_epoch = self._wal_epoch + 1
            payload = self._snapshot_payload_locked(new_epoch)
            # snapshot names epoch E+1 and atomically renames BEFORE the
            # new log exists: a crash in between recovers from the
            # snapshot alone (the old epoch's log is simply ignored)
            w.snap_write(os.path.join(self.data_dir, "snapshot.bin"), payload)
            # crashpoint: snapshot (epoch E+1) renamed into place, the new
            # log not yet created and the old one not yet unlinked — recovery
            # must come up from the snapshot alone, ignoring the stale log
            _fp("checkpoint/after-snap-rename")
            old = self.wal
            self._wal_epoch = new_epoch
            self.wal = w.Wal(self._wal_path(new_epoch), on_io_error=self._wal_io_error)
            self.kv.journal = self.wal
            self.mvcc.journal = self.wal
            sh = self._shipper
            if sh is not None:
                # the ship tap follows the log across epoch rotations;
                # the closed predecessor is fully durable, so its queued
                # frames drain in order before the new epoch's
                sh.install(self.wal)
            old.close()
            # the new log must be durably present in the dir BEFORE the
            # old one disappears (power-loss ordering)
            self.wal.sync()
            w.fsync_dir(self.data_dir)
            old_path = self._wal_path(new_epoch - 1)
            if os.path.exists(old_path):
                # crashpoint: both epochs' logs exist; recovery must pick the
                # snapshot's epoch and discard the stale predecessor
                _fp("checkpoint/before-old-unlink")
                os.unlink(old_path)
                w.fsync_dir(self.data_dir)

    # --- warm standby: shipped-frame ingest + promotion (PR 14) ------------

    def receive_frames(self, payloads: list[bytes],
                       seqs: list[int] | None = None) -> int:
        """Standby ingest path (called by the WalShipper / StandbyServer):
        journal every shipped frame into OUR wal (re-framed by the native
        appender — fresh CRC chain, so a reopened standby replay-verifies
        the shipped bytes for free), fsync ONCE per batch (the standby's
        group commit), then replay into memory and advance the applied
        watermark. Returns the total frames applied so far.

        `seqs` carries each frame's 1-based link-relative sequence number
        (gseq − link base). With it the receive is IDEMPOTENT: frames at
        or below the applied count (a resync re-ship after reconnect) and
        adjacent duplicates (a chaos-duplicated wire frame) are discarded
        before journaling — they can neither double-apply nor advance the
        durable-ack count twice. A GAP (a dropped seq'd frame) raises, so
        the connection drops and the sender resyncs from the acked count.

        Order matters for the never-ahead invariant: the shipper only
        hands us frames DURABLE on the primary, and we only ack (return)
        after our own fsync — so `semi-sync acked ⇒ durable on standby`
        and `standby state ⊆ primary durable state` both hold across a
        SIGKILL at any point in this method."""
        from ..utils import metrics as M
        from . import wal as w
        from .ship import frame_commit_ts, frame_table_prefix

        with self._standby_lock:
            if not self.standby:
                raise TiDBError(
                    "shipped frames refused: store is not (or no longer) a standby"
                )
            if seqs is not None:
                fresh: list[bytes] = []
                last = self._applied_frames
                for sq, p in zip(seqs, payloads):
                    if sq <= last:
                        continue  # resync overlap or duplicated frame
                    if sq != last + 1:
                        raise TiDBError(
                            f"shipped frame gap: expected seq {last + 1}, "
                            f"got {sq} — dropping the connection to resync"
                        )
                    last = sq
                    fresh.append(p)
                if not fresh:
                    return self._applied_frames
                payloads = fresh
            wal = self.wal
            for p in payloads:
                wal.append(p)
                # crash/EIO site: frame journaled on the standby (maybe
                # only buffered), batch not yet fsynced or applied — a
                # death here may tear the standby log's tail, which
                # recovery truncates; nothing was acked to semi-sync
                _fp("wal/ship-mid-frame")
            wal.sync()
            applied = self.applied_ts
            prefixes: set[bytes] = set()
            # frame groups (G/g chunk/F) re-join into the logical record
            # before applying; a group split across ship batches stays
            # buffered in the assembler — its journaled chunks are acked
            # (durable), its effects land when the closing frame arrives
            asm = self._ship_asm
            if asm is None:
                asm = self._ship_asm = w.GroupAssembler()
            for p in payloads:
                for rec in asm.feed(p):
                    w.apply_record(rec, self.kv, self.mvcc)
                    ts = frame_commit_ts(rec)
                    if ts > applied:
                        applied = ts
                    pref = frame_table_prefix(rec)
                    if pref is not None:
                        prefixes.add(pref)
            # the version bump below stamps tso.current() as the table's
            # last-commit ts, and the tile/cop-result caches key snapshot
            # validity off that stamp — without advancing first a standby
            # TSO still reads 0, every historic AS OF read satisfies
            # `read_ts >= 0`, and the FIRST follower read's tile (built at
            # its own, possibly historic, snapshot) serves every later one
            self.tso.advance_to(applied)
            if prefixes:
                # replayed frames must invalidate tile/cop-result caches
                # exactly like a local commit would
                self.bump_version(sorted(prefixes))
            self.applied_ts = applied
            self._applied_frames += len(payloads)
            M.STANDBY_APPLIED_TS.set(float(applied))
            return self._applied_frames

    def promote(self) -> None:
        """ADMIN PROMOTE: flip a warm standby read-write. Serialized
        against receive_frames on the standby lock, so a promote issued
        while a ship batch is mid-frame waits for the batch to land and
        every later batch is refused — the shipper observes the flip and
        stops. Double promote (or promoting a store that never was a
        standby) is rejected."""
        with self._standby_lock:
            if not self.standby:
                raise TiDBError(
                    "ADMIN PROMOTE: store is not a standby (already primary; "
                    "double promote rejected)"
                )
            self.standby = False
            # re-attach the journals: from here every mutation journals
            # through the normal primary path
            self.kv.journal = self.wal
            self.mvcc.journal = self.wal
            self.wal.sync()
            # the shipped frames carry the OLD primary's timestamps — a
            # promoted standby must never allocate below them (same seed
            # discipline as recovery)
            self.tso.advance_to(max(self.applied_ts, self.mvcc.high_water_ts()))
        log.warning(
            "standby PROMOTED to primary (data_dir=%s, applied_ts=%d, "
            "%d shipped frames applied)",
            self.data_dir, self.applied_ts, self._applied_frames,
        )

    def _rebuild_as_standby(self, payload: bytes, new_epoch: int) -> None:
        """Rejoin's in-place rebuild (called by ReplicaSet.rejoin under
        OUR standby lock, after it wrote the new primary's snapshot into
        our dir and unlinked the divergent old logs): discard the whole
        in-memory state, reload from the snapshot payload, open a fresh
        log under the bumped epoch, and come up as a standby — journals
        detached, writes refused until promote, applied watermark at the
        snapshot's high water. The store_uid changes: every process-wide
        cache entry keyed to the old (divergent) history must miss."""
        import os
        import uuid as _uuid

        from ..utils import metrics as M
        from . import wal as w

        self.kv = MemKV()
        self.mvcc = MVCCStore(self.kv)
        self.mvcc.txn_live = self.txn_is_active
        self.mvcc.split_hook = self._auto_split_run
        self.regions = RegionMap()
        self._versions = {}
        self.store_uid = _uuid.uuid4().hex[:16]
        self._ship_asm = None
        self._load_snapshot_payload(payload)
        self._wal_epoch = new_epoch
        self.wal = w.Wal(self._wal_path(new_epoch), on_io_error=self._wal_io_error)
        self.wal.sync()
        w.fsync_dir(self.data_dir)
        # standby discipline: shipped frames journal explicitly in
        # receive_frames; kv/mvcc must not re-journal applied records
        self.kv.journal = None
        self.mvcc.journal = None
        self.standby = True
        self._shipper = None  # the OLD primary's shipper died with its role
        self._applied_frames = 0
        self.applied_ts = self.mvcc.high_water_ts()
        self.tso.advance_to(self.applied_ts)
        # the fence existed to keep the DIVERGENT history from serving;
        # that history is gone — this store is a consistent follower now
        # and may degrade/promote again like any standby
        self._io_degraded = False
        self._failover_disabled = False
        self._no_spare_counted = False
        M.WAL_DEGRADED.set(0)
        M.STANDBY_APPLIED_TS.set(float(self.applied_ts))

    def rejoin(self, new_primary: "Storage | None" = None) -> None:
        """ADMIN REJOIN: rebuild this fenced old primary as a standby of
        the promoted new primary, healing the fleet after a failover.
        With no explicit target, the new primary is discovered from this
        store's old shipper: the standby auto-promote picked, or any
        attached in-process standby that has since been promoted."""
        target = new_primary
        sh = self._shipper
        if target is None and sh is not None:
            target = getattr(sh, "_promoted", None)
            if target is None:
                with sh._cond:
                    for l in sh._links:
                        st = l.standby
                        if st is not None and not st.standby:
                            target = st
                            break
        if target is None:
            raise TiDBError(
                "ADMIN REJOIN: no promoted new primary found — this store's "
                "shipper never promoted a standby (pass the new primary "
                "explicitly via Storage.rejoin(new_primary))"
            )
        if sh is not None:
            sh.stop()
        nsh = target._shipper
        if nsh is None:
            from .ship import ReplicaSet

            nsh = ReplicaSet(target)
            target._shipper = nsh
        nsh.rejoin(self)

    @property
    def plugins(self):
        if getattr(self, "_plugins", None) is None:
            from ..plugin import PluginRegistry

            self._plugins = PluginRegistry()
        return self._plugins

    # --- live statement registry (ref: PROCESSLIST + server conn registry)

    def register_process(self, conn_id: int, info: dict) -> None:
        with self._proc_lock:
            self._processes[conn_id] = info

    def clear_process(self, conn_id: int) -> None:
        with self._proc_lock:
            self._processes.pop(conn_id, None)

    def get_process(self, conn_id: int) -> dict | None:
        with self._proc_lock:
            return self._processes.get(conn_id)

    def process_snapshot(self) -> list:
        with self._proc_lock:
            return sorted(self._processes.items())

    @property
    def stmt_stats(self):
        if getattr(self, "_stmt_stats", None) is None:
            from ..utils.stmtstats import StmtStats

            self._stmt_stats = StmtStats()
        return self._stmt_stats

    @property
    def trace_ring(self):
        """Last-N statement traces (utils/tracing.TraceRing) — the
        TIDB_TRACE memtable / `/debug/trace` backing store."""
        if getattr(self, "_trace_ring", None) is None:
            from ..utils.tracing import TraceRing

            self._trace_ring = TraceRing()
        return self._trace_ring

    _timeline_init_lock = Lock()

    @property
    def timeline(self):
        """Per-store device timeline ring (utils/timeline.TimelineRing) —
        the TIDB_TIMELINE memtable / `/debug/timeline` backing store;
        `SET GLOBAL tidb_enable_timeline` flips its recording flag.
        Double-checked init: unlike trace_ring, first access can come
        from PARALLEL cop worker threads (the TL.bind seam), and a racing
        second ring would silently swallow the loser's events."""
        if getattr(self, "_timeline", None) is None:
            from ..utils.timeline import TimelineRing

            with Storage._timeline_init_lock:
                if getattr(self, "_timeline", None) is None:
                    self._timeline = TimelineRing()
        return self._timeline

    @property
    def build_cache(self):
        """Store-wide device-resident MPP build-side cache
        (copr/tilecache.BuildSideCache): one pool per store so every
        session's fused dispatch reuses the same uploaded join
        structures; registered with the memory arbiter so the soft-limit
        degrade sweep reclaims it with the tile caches. Double-checked
        init like the timeline ring — first touch comes from whichever
        session dispatches MPP first."""
        if getattr(self, "_build_cache", None) is None:
            from ..copr.tilecache import BuildSideCache

            with Storage._timeline_init_lock:
                if getattr(self, "_build_cache", None) is None:
                    bc = BuildSideCache()
                    self.mem.register_cache(bc)
                    self._build_cache = bc
        return self._build_cache

    @property
    def workload(self):
        """Per-store workload-history plane (utils/workload.WorkloadProfile):
        observed per-(digest, row bucket) execution profiles fed at
        statement completion and consulted by the cop client's `auto`
        routing (SET GLOBAL tidb_tpu_feedback_route). Double-checked init
        like the timeline ring — first touch can come from parallel cop
        workers consulting the router mid-statement."""
        if getattr(self, "_workload", None) is None:
            from ..utils.workload import WorkloadProfile

            with Storage._timeline_init_lock:
                if getattr(self, "_workload", None) is None:
                    self._workload = WorkloadProfile()
        return self._workload

    # --- active-txn registry (GC safepoint clamp) --------------------------

    MAX_TXN_PIN_S = 3600.0  # leaked/abandoned txns stop blocking GC after this

    def _txn_started(self, start_ts: int) -> None:
        with self._active_lock:
            self._active_starts[start_ts] = time.time()

    def _txn_done(self, start_ts: int) -> None:
        with self._active_lock:
            self._active_starts.pop(start_ts, None)

    def txn_is_active(self, start_ts: int) -> bool:
        """Is `start_ts` a LIVE transaction of this process? The MVCC
        layer's `txn_live` hook: lock resolution must not TTL-expire a
        slow-but-alive owner's locks (the in-process stand-in for the
        reference's txn heartbeat). Entries past MAX_TXN_PIN_S read as
        dead, like the GC clamp — a leaked Txn object stops shielding
        its locks at the same horizon it stops pinning the safepoint."""
        horizon = time.time() - self.MAX_TXN_PIN_S
        with self._active_lock:
            t0 = self._active_starts.get(start_ts)
        return t0 is not None and t0 >= horizon

    def min_active_start_ts(self) -> int | None:
        """Oldest live transaction start-ts, or None. Entries pinned longer
        than MAX_TXN_PIN_S are dropped as leaks (the reference bounds this
        via txn max TTL + the session manager's process list)."""
        horizon = time.time() - self.MAX_TXN_PIN_S
        with self._active_lock:
            for ts, t0 in list(self._active_starts.items()):
                if t0 < horizon:
                    del self._active_starts[ts]
            return min(self._active_starts) if self._active_starts else None

    @property
    def gc_worker(self):
        if self._gc_worker is None:
            from .gcworker import GCWorker

            self._gc_worker = GCWorker(self)
        return self._gc_worker

    @property
    def compactor(self):
        """The delta-main compactor (storage/compact.py) — durable
        primaries only. In-memory stores have no segments worth folding
        into and a standby must never produce WAL records, so both read
        None here (and gcworker.tick falls back to the per-key mvcc.gc
        sweep). A promoted standby grows one on the next access."""
        if self.wal is None or self.standby:
            return None
        if self._compactor is None:
            from .compact import Compactor

            self._compactor = Compactor(self)
            self._compactor.start()
        return self._compactor

    def _auto_split_run(self, run) -> None:
        """Split regions at every region_split_size-th key of a freshly
        ingested (sorted) run so large tables scan region-parallel."""
        step = self.region_split_size
        if run.n < 2 * step:
            return
        # key_at, not key_mat[i]: columnar runs synthesize the handful of
        # split keys without materializing the whole key matrix
        keys = [run.key_at(i) for i in range(step, run.n - step // 2, step)]
        self.regions.split_many(keys)
